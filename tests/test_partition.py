"""Partitioner tests — reference remainder semantics (server.c:185-216)."""

import numpy as np
import pytest

from dsort_tpu.data.partition import (
    equal_partition,
    pad_kv_to_shards,
    pad_to_shards,
    partition,
)
from dsort_tpu.ops.local_sort import sentinel_for


def test_equal_partition_reference_semantics():
    # server.c:185-196: base = total/num, first total%num workers get one extra.
    assert equal_partition(10_000, 4) == [2500, 2500, 2500, 2500]
    assert equal_partition(10, 4) == [3, 3, 2, 2]
    assert equal_partition(3, 4) == [1, 1, 1, 0]
    assert equal_partition(0, 4) == [0, 0, 0, 0]
    assert equal_partition(16_384, 4) == [4096] * 4  # reference max job


def test_equal_partition_uncapped():
    # The reference aborts above 4096/chunk (server.c:193-196); we must not.
    sizes = equal_partition(1_000_000, 8)
    assert sum(sizes) == 1_000_000
    assert max(sizes) - min(sizes) <= 1


def test_partition_concat_roundtrip():
    data = np.arange(103, dtype=np.int32)
    chunks = partition(data, 7)
    assert len(chunks) == 7
    np.testing.assert_array_equal(np.concatenate(chunks), data)


def test_pad_to_shards_layout():
    data = np.arange(10, dtype=np.int32)
    shards, counts = pad_to_shards(data, 4, multiple=8)
    assert shards.shape == (4, 8)
    np.testing.assert_array_equal(counts, [3, 3, 2, 2])
    sent = sentinel_for(np.int32)
    recovered = np.concatenate([shards[i, : counts[i]] for i in range(4)])
    np.testing.assert_array_equal(recovered, data)
    assert (shards[0, 3:] == sent).all()


def test_pad_kv_to_shards():
    keys = np.arange(10, dtype=np.int64)
    vals = np.stack([np.arange(10), np.arange(10) * 2], axis=1).astype(np.uint8)
    sk, sv, counts = pad_kv_to_shards(keys, vals, 3)
    rec_k = np.concatenate([sk[i, : counts[i]] for i in range(3)])
    rec_v = np.concatenate([sv[i, : counts[i]] for i in range(3)])
    np.testing.assert_array_equal(rec_k, keys)
    np.testing.assert_array_equal(rec_v, vals)


@pytest.mark.parametrize("n,w", [(0, 4), (1, 8), (7, 8), (64, 8)])
def test_pad_to_shards_edge_sizes(n, w):
    data = np.random.default_rng(0).integers(-100, 100, n).astype(np.int32)
    shards, counts = pad_to_shards(data, w)
    assert counts.sum() == n
    rec = np.concatenate([shards[i, : counts[i]] for i in range(w)]) if n else []
    np.testing.assert_array_equal(rec, data)


def test_make_mesh_rejects_zero_worker_axis():
    # Regression: dp > device count must raise, not build a (dp, 0) mesh.
    import jax

    from dsort_tpu.config import ConfigError, MeshConfig
    from dsort_tpu.parallel.mesh import make_mesh

    with pytest.raises(ConfigError):
        make_mesh(MeshConfig(dp=16), jax.devices()[:8])
