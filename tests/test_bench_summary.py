"""The driver-artifact contract: the bench's FINAL line must fit the
driver's bounded (2,000-byte) tail capture (VERDICT r5 missing #1 — the r5
full summary grew past it and `parsed` came back null).
"""

import importlib.util
import json
import os

_BENCH = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "bench.py"
)
_spec = importlib.util.spec_from_file_location("dsort_bench", _BENCH)
bench = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(bench)


def _fake_emitted(n_metrics: int) -> list:
    """A suite-shaped _EMITTED with realistic long names and extras."""
    names = [
        "sort_throughput_int32_16777216_keys_single_chip_tpu",
        "sort_throughput_int32_16777216_keys_single_chip_tpu_lax_kernel",
        "sort_throughput_int32_67108864_keys_single_chip_tpu",
        "sort_throughput_int64_8388608_keys_single_chip_tpu",
        "sort_throughput_int64_8388608_keys_single_chip_tpu_lax_kernel",
        "int64_block_vs_lax_ratio_8388608",
        "terasort_local_phase_4194304_records_kv",
        "merge_phase_8x131072_sorted_runs",
        "transfer_probe_link",
        "config1_reference_workload_16384_int32",
        "config2_uniform_1M_int32_spmd",
        "config3_uniform_1M_int64_spmd",
        "config4_terasort_65536_records_kv",
        "config5_zipf_1M_with_injected_failure",
        "config5_zipf_1M_injected_failure_8dev_cpu_mesh",
        "spmd_sort_1M_end_to_end_phase_split",
        "spmd_sort_2p26_end_to_end_phase_split",
        "spmd_sort_1M_phase_split_8dev_cpu_mesh",
        "tunnel_drift_sensor_lax_int32",
        "sort_throughput_int32_4194304_keys_single_chip_cpu_fallback",
    ]
    while len(names) < n_metrics:
        names.append(f"extra_capability_line_number_{len(names)}_keys")
    out = []
    for i, name in enumerate(names[:n_metrics]):
        line = {
            "metric": name,
            "value": round(1.234e9 / (i + 1), 1),
            "unit": "keys/sec",
            "method": "chain_slope(8,48)",
            "chained_value": round(1.1e9 / (i + 1), 1),
            "fixed_overhead_ms_per_dispatch": 101.23,
            "phases_seconds": {"partition": 0.1234, "assemble": 0.5678,
                               "spmd_sort": 0.9} if "phase" in name else {},
            "host_fraction": 0.594,
        }
        if i % 2 == 0:
            line["vs_baseline"] = round(28_000.0 / (i + 1), 2)
        out.append(line)
    return out


def test_compact_summary_fits_driver_tail():
    """>= 20 metrics, compact line < 1,800 bytes (driver capture is 2,000)."""
    emitted = _fake_emitted(20)
    compact = bench._compact_summary(emitted)
    encoded = json.dumps(compact)
    assert len(encoded) < 1800, f"{len(encoded)} bytes: {encoded[:200]}..."
    # one entry per metric — dedupe never drops a line
    assert len(compact["l"]) == 20
    # headline value + vs_baseline survive on the top level
    assert compact["value"] == emitted[0]["value"]
    assert compact["vs_baseline"] == emitted[0]["vs_baseline"]


def test_compact_summary_keys_unique_and_stable():
    emitted = _fake_emitted(25)
    a = bench._compact_summary(emitted)
    b = bench._compact_summary(emitted)
    assert a == b  # deterministic
    assert len(set(a["l"])) == 25


def test_abbrev_distinguishes_dtypes_and_sizes():
    a = bench._abbrev("sort_throughput_int32_16777216_keys_single_chip_tpu")
    b = bench._abbrev("sort_throughput_int64_16777216_keys_single_chip_tpu")
    c = bench._abbrev("sort_throughput_int32_67108864_keys_single_chip_tpu")
    assert len({a, b, c}) == 3
    assert "2p24" in a and "2p26" in c
    assert "i64" in b


def test_emit_summary_prints_compact_last(capsys):
    """The LAST stdout line is the compact summary — the driver's `parsed`
    lands exactly there."""
    bench._EMITTED.clear()
    try:
        for line in _fake_emitted(20):
            bench._EMITTED.append(line)
        bench._emit_summary()
    finally:
        bench._EMITTED.clear()
    out = capsys.readouterr().out.strip().splitlines()
    assert len(out) == 2  # full summary, then compact
    full, compact = json.loads(out[0]), json.loads(out[1])
    assert full["metric"] == "summary"
    assert compact["metric"] == "compact_summary"
    assert len(out[1]) < 1800


def test_full_summary_keeps_metric_collisions(capsys):
    """ADVICE r5 low: two emitted lines sharing a metric label (e.g. ladder
    variants distinguished only by `kernel`) must BOTH survive into the full
    summary's `lines` — keyed apart, never silently overwritten."""
    bench._EMITTED.clear()
    try:
        bench._EMITTED.append(
            {"metric": "config9_variant", "value": 1.0, "unit": "keys/sec",
             "kernel": "block"}
        )
        bench._EMITTED.append(
            {"metric": "config9_variant", "value": 2.0, "unit": "keys/sec",
             "kernel": "lax"}
        )
        bench._EMITTED.append(  # no kernel extra at all: index-suffixed
            {"metric": "config9_variant", "value": 3.0, "unit": "keys/sec"}
        )
        bench._emit_summary()
    finally:
        bench._EMITTED.clear()
    out = capsys.readouterr().out.strip().splitlines()
    full, compact = json.loads(out[0]), json.loads(out[1])
    assert len(full["lines"]) == 3
    values = sorted(e["value"] for e in full["lines"].values())
    assert values == [1.0, 2.0, 3.0]
    assert "config9_variant" in full["lines"]
    assert "config9_variant#lax" in full["lines"]
    # the compact line keeps all three too (suffix dedupe)
    assert len(compact["l"]) == 3


def test_compact_summary_size_holds_under_collisions():
    """The size bound holds even when the suite contains duplicate metric
    labels (the collision case the full summary now disambiguates)."""
    emitted = _fake_emitted(16)
    for i, kern in enumerate(("block", "lax", "bitonic", "radix")):
        ln = dict(emitted[0])
        ln["kernel"] = kern
        ln["value"] = float(i)
        emitted.append(ln)
    compact = bench._compact_summary(emitted)
    encoded = json.dumps(compact)
    assert len(encoded) < 1800, f"{len(encoded)} bytes"
    assert len(compact["l"]) == 20  # nothing dropped


# ---- artifact self-parsing: schema header + bench.py --check --------------


def test_schema_header_shape():
    hdr = bench._schema_header()
    assert hdr["bench_schema"] == bench.BENCH_SCHEMA_VERSION
    assert hdr["required"] == {"metric": "str", "value": "num", "unit": "str"}
    # The header is the artifact's FIRST line (never the driver-parsed
    # tail — that bound binds `_compact_summary` above); this bound only
    # keeps it one sanely-sized JSON line as the field vocabulary grows
    # with each bench family (~9 typed fields per PR).
    assert len(json.dumps(hdr)) < 4000


def test_check_artifact_accepts_valid_lines(tmp_path):
    p = tmp_path / "art.jsonl"
    with open(p, "w") as f:
        f.write(json.dumps(bench._schema_header()) + "\n")
        f.write(json.dumps({"metric": "m1", "value": 1.5, "unit": "keys/sec",
                            "vs_baseline": 2.0}) + "\n")
        f.write("\n")  # blank lines tolerated
        f.write(json.dumps({"metric": "m2", "value": 3, "unit": "rec/sec",
                            "custom_extra": [1, 2]}) + "\n")
    assert bench.check_artifact(str(p)) == []


def test_check_artifact_flags_violations(tmp_path):
    p = tmp_path / "bad.jsonl"
    with open(p, "w") as f:
        f.write(json.dumps({"metric": "ok", "value": 1.0, "unit": "u"}) + "\n")
        f.write("not json at all\n")
        f.write(json.dumps({"metric": "no_value", "unit": "u"}) + "\n")
        f.write(json.dumps({"metric": 7, "value": 1.0, "unit": "u"}) + "\n")
        f.write(json.dumps({"metric": "bad_extra", "value": 1.0, "unit": "u",
                            "vs_baseline": "high"}) + "\n")
        f.write(json.dumps(["a", "list"]) + "\n")
        # bool must not satisfy "num" (bool is an int subclass in Python).
        f.write(json.dumps({"metric": "boolval", "value": True, "unit": "u"})
                + "\n")
    errs = bench.check_artifact(str(p))
    assert len(errs) == 6, errs
    assert any("not JSON" in e for e in errs)
    assert any("missing required 'value'" in e for e in errs)
    assert any("'metric' is not of type 'str'" in e for e in errs)
    assert any("'vs_baseline' is not of type 'num'" in e for e in errs)
    assert any("not a JSON object" in e for e in errs)
    assert any("'value' is not of type 'num'" in e for e in errs)


def test_check_artifact_header_after_metrics_flagged(tmp_path):
    p = tmp_path / "late.jsonl"
    with open(p, "w") as f:
        f.write(json.dumps({"metric": "m", "value": 1.0, "unit": "u"}) + "\n")
        f.write(json.dumps(bench._schema_header()) + "\n")
    errs = bench.check_artifact(str(p))
    assert any("schema header after metric lines" in e for e in errs)


def test_check_artifact_missing_file():
    errs = bench.check_artifact("/nonexistent/artifact.jsonl")
    assert len(errs) == 1 and "unreadable" in errs[0]


def test_check_main_exit_codes(tmp_path, capsys):
    good = tmp_path / "good.jsonl"
    good.write_text(json.dumps({"metric": "m", "value": 1.0, "unit": "u"})
                    + "\n")
    bad = tmp_path / "bad.jsonl"
    bad.write_text("nope\n")
    assert bench._check_main([str(good)]) == 0
    assert bench._check_main([str(good), str(bad)]) == 1
    assert bench._check_main([]) == 2
    out = capsys.readouterr().out
    assert "OK" in out and "schema violations" in out


def test_in_tree_artifacts_pass_schema_check():
    """Tier-1 gate: every committed BENCH_*.jsonl artifact round-trips
    against the schema (pre-header artifacts validate under the v0
    default) — the driver-artifact contract, now machine-checkable."""
    import glob

    root = os.path.dirname(_BENCH)
    artifacts = sorted(glob.glob(os.path.join(root, "BENCH_*.jsonl")))
    assert artifacts, "no in-tree BENCH_*.jsonl artifacts found"
    for art in artifacts:
        assert bench.check_artifact(art) == [], art
