"""North-star-scale CPU evidence (VERDICT r5 weak #6 / next #6).

The in-process suite simulates 8 devices (conftest pins the XLA device
count at backend init), so P=16/P=32 behavior — splitter quality, the
32->31 mesh re-form, capacity quantization at wide meshes — ran nowhere.
These tests spawn subprocesses with their OWN simulated device counts and
drive the public APIs at those widths; the capacity-policy quantization
checks are pure host math and run in-process.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from dsort_tpu.parallel.sample_sort import (
    cap_from_observed,
    cap_pair_policy,
    next_cap_pair,
)


def _run_ndev(n_devices: int, body: str, timeout: float = 540.0) -> str:
    """Run ``body`` in a fresh interpreter simulating ``n_devices`` CPUs."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    # REPLACE the parent's flag (conftest pinned 8): the child must
    # initialize its backend at the requested width.
    env["XLA_FLAGS"] = (
        f"--xla_force_host_platform_device_count={n_devices}"
    )
    env.pop("PALLAS_AXON_POOL_IPS", None)
    r = subprocess.run(
        [sys.executable, "-c", body], env=env, capture_output=True,
        text=True, timeout=timeout,
    )
    assert r.returncode == 0, (
        f"{n_devices}-device subprocess failed:\n{r.stdout}\n{r.stderr}"
    )
    return r.stdout


_BODY_16 = r"""
import json
import jax, numpy as np
jax.config.update("jax_enable_x64", True)
from dsort_tpu.config import JobConfig
from dsort_tpu.data.ingest import gen_zipf
from dsort_tpu.models.validate import _multiset
from dsort_tpu.parallel.mesh import local_device_mesh
from dsort_tpu.parallel.sample_sort import SampleSort
from dsort_tpu.utils.metrics import Metrics

assert len(jax.devices()) == 16, jax.devices()
mesh = local_device_mesh(16)
# Splitter quality at P=16 on Zipf skew: correct output, bounded retries.
data = gen_zipf(1 << 17, a=1.2, seed=41)
m = Metrics()
ss = SampleSort(mesh, JobConfig(key_dtype=np.int64))
out = ss.sort(data, metrics=m)
np.testing.assert_array_equal(out, np.sort(data))
# Device-resident handle + on-device validation at P=16.
h = ss.sort(data, keep_on_device=True)
rep = h.validate_on_device()
assert rep.sorted_ok and rep.records == len(data)
assert rep.checksum == _multiset(data, len(data), data.dtype.itemsize)
assert h.num_shards == 16
print(json.dumps({
    "ok": True,
    "capacity_retries": m.counters.get("capacity_retries", 0),
}))
"""


_BODY_32 = r"""
import json
import jax, numpy as np
jax.config.update("jax_enable_x64", True)
from dsort_tpu.config import JobConfig, MeshConfig
from dsort_tpu.data.ingest import gen_uniform, gen_zipf
from dsort_tpu.parallel.mesh import make_mesh
from dsort_tpu.parallel.sample_sort import SampleSort
from dsort_tpu.scheduler import FaultInjector, SpmdScheduler
from dsort_tpu.utils.metrics import Metrics

assert len(jax.devices()) == 32, jax.devices()
mesh = make_mesh(MeshConfig(num_workers=32), jax.devices())
# 1) P=32 splitter quality: uniform AND Zipf at 2^18, exact vs np.sort.
#    32 splitters from 32*oversample samples must hold buckets near the
#    ideal N/32 — assert no more than one measured-capacity retry fired.
for seed, gen in ((43, gen_uniform), (44, lambda n, seed: gen_zipf(n, a=1.2, seed=seed))):
    data = gen(1 << 18, seed=seed)
    m = Metrics()
    job = JobConfig() if data.dtype.itemsize == 4 else JobConfig(key_dtype=data.dtype)
    out = SampleSort(mesh, job).sort(data, metrics=m)
    np.testing.assert_array_equal(out, np.sort(data))
    assert m.counters.get("capacity_retries", 0) <= 1, m.counters
# 2) The 32->31 mesh re-form: lose device 17 mid-shuffle, re-form over 31
#    survivors (a non-power-of-two mesh), still exact.
inj = FaultInjector()
sched = SpmdScheduler(job=JobConfig(settle_delay_s=0.01), injector=inj)
data = gen_uniform(1 << 18, seed=45)
inj.fail_once(17, "spmd")
m = Metrics()
out = sched.sort(data, metrics=m)
np.testing.assert_array_equal(out, np.sort(data))
assert m.counters.get("mesh_reforms") == 1
assert not sched.table.is_alive(17)
assert len(sched.table.live_workers()) == 31
print(json.dumps({"ok": True, "mesh_reforms": m.counters["mesh_reforms"]}))
"""


def test_scale_16_devices_dryrun():
    """P=16: Zipf splitter quality + device-resident validation, subprocess
    with a 16-device simulated mesh."""
    out = json.loads(_run_ndev(16, _BODY_16).strip().splitlines()[-1])
    assert out["ok"] is True
    # Zipf at capacity_factor 1.3 with measured retries: at most one resize.
    assert out["capacity_retries"] <= 1


@pytest.mark.slow  # two 32-wide meshes compile (32 and the re-formed 31)
def test_scale_32_devices_splitters_and_reform():
    """P=32 splitter quality and the 32->31 injected-loss mesh re-form."""
    out = json.loads(_run_ndev(32, _BODY_32).strip().splitlines()[-1])
    assert out["ok"] is True and out["mesh_reforms"] == 1


def test_capacity_policy_quantization_at_scale():
    """The capacity policy at P=16/32 (host math — no devices needed):
    quantization keeps distinct compiled programs bounded while the cap
    never exceeds the shard size and never drops below alignment."""
    for p in (16, 32):
        n_local = 1 << 18
        cap = cap_pair_policy(n_local, 1.3, p)
        assert cap % 8 == 0 and 8 <= cap <= n_local
        # measured resize quantizes to 1/8 of the ideal bucket: <= ~9
        # distinct steps between the ideal and the n_local clamp
        step = max(n_local // (8 * p), 8)
        caps = {
            cap_from_observed(obs, n_local, p)
            for obs in range(n_local // p, n_local + 1, step)
        }
        assert all(c % step == 0 or c == n_local for c in caps)
        assert len(caps) <= 8 * p  # bounded compile count
        # growth invariant: a retry is always strictly larger
        c0 = cap_pair_policy(n_local, 1.0, p)
        assert next_cap_pair(c0 + 1, c0, n_local, p) > c0
