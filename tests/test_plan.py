"""Closed-loop planner plane tests (ISSUE 16, ARCHITECTURE §15).

The acceptance bar: every automatic knob choice is a journaled, typed,
REPLAYABLE ``plan_decision`` — policy, chosen value, the measured inputs
it saw, the rejected alternatives — emitted BEFORE dispatch; explicit
flags always win (journaled ``plan_override``); ``--no-autotune`` makes
the planner vanish bit-identically; and `obs.analyze`'s ``plan`` verdict
replays every decision from the journal alone with zero mismatches.
"""

import json
import os

import numpy as np
import pytest

from dsort_tpu.config import ConfigError, JobConfig, ServeConfig, SortConfig
from dsort_tpu.data.ingest import gen_uniform, gen_zipf
from dsort_tpu.obs.analyze import analyze_records, format_analysis
from dsort_tpu.obs.plan import (
    PLAN_DECISION_FIELDS,
    PLAN_OVERRIDE_FIELDS,
    PLAN_POLICIES,
    PREWARM_HISTORY,
    SKEW_RING_THRESHOLD,
    WAVE_MAX_ELEMS,
    WAVE_MIN_ELEMS,
    Planner,
    plan_ladder,
    plan_rung,
    plan_table,
    planned_exchange,
    planned_wave_elems,
    probe_skew,
    replay_decision,
    variant_key_label,
)
from dsort_tpu.utils.events import COUNTERS, EVENT_TYPES, EventLog
from dsort_tpu.utils.metrics import Metrics

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _metered():
    return Metrics(journal=EventLog())


def _records(journal):
    return [e.to_dict() for e in journal.events()]


# ---- registries + pure-twin math -------------------------------------------


def test_plan_events_and_counters_registered():
    for etype in ("plan_decision", "plan_override"):
        assert etype in EVENT_TYPES
    for counter in ("plan_decisions", "plan_overrides"):
        assert counter in COUNTERS
    assert PLAN_POLICIES == (
        "exchange", "wave_elems", "redundancy", "redundancy_mode",
        "prewarm", "dispatch_timeout_s", "slice_devices",
    )
    assert PLAN_DECISION_FIELDS == ("policy", "chosen", "inputs", "rejected")
    assert PLAN_OVERRIDE_FIELDS == ("policy", "explicit", "planned", "inputs")


def test_plan_rung_and_ladder_pinned_to_serving_twins():
    """The planner quantizes admissions with the SAME rung math the
    serving cache keys variants on — pinned against the jax-backed
    originals so the two can never drift."""
    from dsort_tpu.models.pipelines import pad_rung
    from dsort_tpu.parallel.exchange import ladder_rungs

    for n in (1, 7, 8, 9, 100, 3000, 3050, 9000, 16384, 16385,
              (1 << 20) - 1, 1 << 20, (1 << 22) + 17):
        assert plan_rung(n) == pad_rung(n), n
    for hi, lo in ((1 << 16, 8), (1 << 16, 1 << 14), (20000, 12000), (64, 8)):
        assert plan_ladder(hi, lo) == ladder_rungs(hi, lo=lo), (hi, lo)


def test_probe_skew_deterministic_and_separates_workloads():
    zipf = gen_zipf(1 << 17, a=1.3, seed=4)
    uni = gen_uniform(1 << 17, seed=0)
    a = probe_skew(zipf, 8)
    b = probe_skew(zipf, 8)
    assert a == b  # deterministic stride sample: same data, same inputs
    assert a["max_mean_ratio"] >= SKEW_RING_THRESHOLD
    assert a["num_workers"] == 8 and a["n_keys"] == len(zipf)
    u = probe_skew(uni, 8)
    assert u["max_mean_ratio"] < SKEW_RING_THRESHOLD
    # degenerate shapes answer neutrally rather than raising
    assert probe_skew(np.array([], dtype=np.int32), 8)["max_mean_ratio"] == 1.0
    assert probe_skew(uni, 1)["max_mean_ratio"] == 1.0


# ---- pure policies (decision == f(inputs)) ---------------------------------


def test_exchange_policy_decisions():
    # skewed + no TPU -> ring; skewed + TPU -> fused
    chosen, rejected = replay_decision(
        "exchange", {"max_mean_ratio": 3.2, "num_workers": 8}
    )
    assert chosen == "ring"
    assert {r["value"] for r in rejected} == {"alltoall", "fused"}
    chosen, _ = replay_decision(
        "exchange",
        {"max_mean_ratio": 3.2, "num_workers": 8, "fused_ok": True},
    )
    assert chosen == "fused"
    # uniform -> alltoall; replica plane -> ring regardless of skew
    chosen, _ = replay_decision(
        "exchange", {"max_mean_ratio": 1.1, "num_workers": 8}
    )
    assert chosen == "alltoall"
    chosen, _ = replay_decision(
        "exchange",
        {"max_mean_ratio": 1.1, "num_workers": 8, "redundancy": 2},
    )
    assert chosen == "ring"
    # one worker: every schedule is the same program
    assert replay_decision(
        "exchange", {"max_mean_ratio": 9.9, "num_workers": 1}
    )[0] == "alltoall"


def test_wave_policy_decisions():
    # no device stats (cpu backend): keep the hand-set size, say why
    chosen, rejected = replay_decision(
        "wave_elems", {"current": 1 << 20, "itemsize": 4}
    )
    assert chosen == 1 << 20
    assert rejected and "keeping wave_elems" in rejected[0]["reason"]
    # measured watermark: budget / per-elem bytes, floored to a pow2
    chosen, _ = replay_decision("wave_elems", {
        "current": 1 << 20, "itemsize": 4,
        "max_device_bytes": 1 << 30, "peak_bytes": 32 << 20,
    })
    assert chosen & (chosen - 1) == 0  # a power of two
    assert WAVE_MIN_ELEMS <= chosen <= WAVE_MAX_ELEMS
    per_elem = (32 << 20) / (1 << 20)
    assert chosen * per_elem <= (1 << 30) * 0.6  # inside the budget
    assert chosen * 2 * per_elem > (1 << 30) * 0.6  # maximal pow2
    # clamps hold at the extremes
    assert replay_decision("wave_elems", {
        "current": 1 << 20, "itemsize": 8,
        "max_device_bytes": 1 << 16, "peak_bytes": 1 << 15,
    })[0] == WAVE_MIN_ELEMS
    assert replay_decision("wave_elems", {
        "current": 1 << 20, "itemsize": 4,
        "max_device_bytes": 1 << 45, "peak_bytes": 0,
    })[0] == WAVE_MAX_ELEMS


def test_redundancy_policy_decisions():
    # no signal at all: keep the current posture
    assert replay_decision("redundancy", {"current": 1})[0] == 1
    assert replay_decision("redundancy", {"current": 2})[0] == 2
    # any observed loss buys a replica
    chosen, rejected = replay_decision(
        "redundancy", {"loss_events": 1, "agents": 2, "degraded": 0}
    )
    assert chosen == 2
    assert {r["value"] for r in rejected} == {1, 3}
    # a quarter of the fleet degraded buys one too
    assert replay_decision(
        "redundancy", {"agents": 4, "degraded": 1, "loss_events": 0}
    )[0] == 2
    # healthy fleet: r=1, with the premium named in the rejection
    chosen, rejected = replay_decision(
        "redundancy", {"agents": 4, "degraded": 0, "loss_events": 0}
    )
    assert chosen == 1
    assert rejected[0]["value"] == 2


def test_prewarm_policy_decisions():
    ladder = [12288, 14336, 16384]
    # cold start: the exhaustive ladder is the only honest warm set
    chosen, rejected = replay_decision(
        "prewarm", {"history": [], "ladder": ladder, "dtype": "int32"}
    )
    assert chosen == [variant_key_label(r, "int32") for r in ladder]
    assert rejected == []
    # history: the admission mix ranks the set, the rest is rejected
    hist = ["14336:int32"] * 5 + ["16384:int64"] * 2
    chosen, rejected = replay_decision("prewarm", {
        "history": hist, "ladder": ladder, "dtype": "int32", "limit": 2,
    })
    assert chosen == sorted(["14336:int32", "16384:int64"])
    assert {r["value"] for r in rejected} == {"12288:int32", "16384:int32"}


def test_replay_decision_unknown_policy_raises():
    with pytest.raises(ValueError, match="unknown plan policy"):
        replay_decision("mystery", {})


# ---- decision emission + schema --------------------------------------------


def test_decide_journals_schema_and_bumps_counter():
    m = _metered()
    p = Planner(job=JobConfig(autotune=True))
    chosen = p.decide(
        "exchange", {"max_mean_ratio": 3.0, "num_workers": 8}, m
    )
    assert chosen == "ring"
    (ev,) = [e for e in m.journal.events() if e.type == "plan_decision"]
    # every Metrics event also stamps the `job` ordinal; the typed schema
    # is exactly PLAN_DECISION_FIELDS on top of that
    assert set(ev.fields) - {"job"} == set(PLAN_DECISION_FIELDS)
    assert ev.fields["chosen"] == "ring"
    assert ev.fields["inputs"]["max_mean_ratio"] == 3.0
    assert all({"value", "reason"} <= set(r) for r in ev.fields["rejected"])
    assert m.counters["plan_decisions"] == 1
    # the journaled inputs alone reproduce the choice (the replay seam)
    assert replay_decision("exchange", ev.fields["inputs"])[0] == "ring"


def test_note_override_journals_planned_value():
    m = _metered()
    p = Planner(job=JobConfig(autotune=True, exchange="alltoall",
                              explicit=("exchange",)))
    got = p.resolve(
        "exchange", {"max_mean_ratio": 3.0, "num_workers": 8}, m
    )
    assert got == "alltoall"  # the explicit flag won
    (ev,) = [e for e in m.journal.events() if e.type == "plan_override"]
    assert set(ev.fields) - {"job"} == set(PLAN_OVERRIDE_FIELDS)
    assert ev.fields["explicit"] == "alltoall"
    assert ev.fields["planned"] == "ring"  # what the planner would have done
    assert m.counters["plan_overrides"] == 1
    assert not [e for e in m.journal.events() if e.type == "plan_decision"]


def test_resolve_precedence_call_beats_config_beats_planner():
    m = _metered()
    p = Planner(job=JobConfig(autotune=True, exchange="alltoall",
                              explicit=("exchange",)))
    inputs = {"max_mean_ratio": 3.0, "num_workers": 8}
    assert p.resolve("exchange", inputs, m, call_value="fused") == "fused"
    assert p.resolve("exchange", inputs, m) == "alltoall"
    off = Planner(job=JobConfig())  # autotune off: planner is inert
    assert off.resolve("exchange", inputs, m) is None
    assert off.resolve("exchange", inputs, m, call_value="ring") == "ring"
    # inert means inert: the off-planner journaled nothing
    types = [e.type for e in m.journal.events()]
    assert types.count("plan_override") == 2  # both from the ON planner


# ---- rolling state: live == journal replay ---------------------------------


def test_planner_live_state_equals_journal_replay():
    m = _metered()
    p = Planner(job=JobConfig(autotune=True))
    p.attach(m)
    m.event("job_admitted", tenant="a", queue_depth=1, n_keys=3050,
            dtype="int32")
    m.event("job_admitted", tenant="a", queue_depth=1, n_keys=14000,
            dtype="int64")
    m.event("hbm_watermark", phase="exchange", bytes_in_use=123456,
            max_device_bytes=1 << 30, device=0)
    m.event("worker_dead", worker=3)
    m.event("job_rerouted", job_id="j1", frm="a0", to="a1",
            reason="agent_lost")
    m.event("job_rerouted", job_id="j2", frm="a1", to="a0",
            reason="dispatch_failed")  # NOT a loss signal
    m.event("health_verdict", agent="a0", score=2.5, degraded=True)
    st = p.state_dict()
    assert st["admissions"] == [
        variant_key_label(plan_rung(3050), "int32"),
        variant_key_label(plan_rung(14000), "int64"),
    ]
    assert st["hbm_peak"] == 123456
    assert st["max_device_bytes"] == 1 << 30
    assert st["loss_events"] == 2  # worker_dead + agent_lost reroute only
    assert st["degraded"] == {"a0": True}
    # THE pin: a fold over the journal records rebuilds the live state
    assert Planner.replay(_records(m.journal)).state_dict() == st


def test_prewarm_history_is_bounded():
    p = Planner()
    for i in range(PREWARM_HISTORY + 40):
        p.observe("job_admitted", {"n_keys": 3050 + i, "dtype": "int32"})
    assert len(p.state_dict()["admissions"]) == PREWARM_HISTORY


# ---- the sample_sort seam (mesh) -------------------------------------------


def test_autotune_picks_ring_on_zipf_alltoall_on_uniform(mesh8):
    """The exchange policy end to end: the planner's measured probe picks
    ring for the skewed workload and alltoall for the uniform one, each
    dispatch journals ONE plan_decision, and the sorted output is
    bit-identical to the unplanned path."""
    from dsort_tpu.parallel.sample_sort import SampleSort

    zipf = gen_zipf(1 << 17, a=1.3, seed=4)
    uni = gen_uniform(1 << 17, seed=0)
    m = _metered()
    auto64 = SampleSort(mesh8, JobConfig(autotune=True, key_dtype=np.int64))
    out_z = auto64.sort(zipf, metrics=m)
    auto32 = SampleSort(mesh8, JobConfig(autotune=True))
    out_u = auto32.sort(uni, metrics=m)
    plans = [e for e in m.journal.events() if e.type == "plan_decision"]
    assert [p.fields["policy"] for p in plans] == ["exchange", "exchange"]
    assert plans[0].fields["chosen"] == "ring"
    assert plans[1].fields["chosen"] == "alltoall"
    # the decision's measured input is the probe of THIS job's keys
    assert plans[0].fields["inputs"]["max_mean_ratio"] >= SKEW_RING_THRESHOLD
    assert plans[1].fields["inputs"]["max_mean_ratio"] < SKEW_RING_THRESHOLD
    np.testing.assert_array_equal(out_z, np.sort(zipf))
    np.testing.assert_array_equal(out_u, np.sort(uni))
    # bit-identical to the unplanned path (--no-autotune A/B)
    plain = SampleSort(mesh8, JobConfig(key_dtype=np.int64))
    np.testing.assert_array_equal(out_z, plain.sort(zipf))


def test_autotune_per_call_exchange_journals_override(mesh8):
    from dsort_tpu.parallel.sample_sort import SampleSort

    zipf = gen_zipf(1 << 16, a=1.3, seed=4)
    m = _metered()
    ss = SampleSort(mesh8, JobConfig(autotune=True, key_dtype=np.int64))
    out = ss.sort(zipf, metrics=m, exchange="alltoall")
    np.testing.assert_array_equal(out, np.sort(zipf))
    (ov,) = [e for e in m.journal.events() if e.type == "plan_override"]
    assert ov.fields["policy"] == "exchange"
    assert ov.fields["explicit"] == "alltoall"
    assert ov.fields["planned"] == "ring"  # skewed: the planner disagreed
    assert not [e for e in m.journal.events() if e.type == "plan_decision"]


def test_autotune_off_journals_nothing(mesh8):
    from dsort_tpu.parallel.sample_sort import SampleSort

    m = _metered()
    SampleSort(mesh8, JobConfig()).sort(gen_uniform(1 << 14, seed=1),
                                        metrics=m)
    types = [e.type for e in m.journal.events()]
    assert "plan_decision" not in types and "plan_override" not in types


def test_planned_exchange_respects_redundancy(mesh8):
    """A resolved redundancy > 1 reaches the policy as a measured input:
    the planner picks ring BECAUSE of the replica plane, and the journal
    says so."""
    from dsort_tpu.parallel.sample_sort import SampleSort

    uni = gen_uniform(1 << 14, seed=2)
    m = _metered()
    ss = SampleSort(mesh8, JobConfig(autotune=True, redundancy=2))
    out = ss.sort(uni, metrics=m)
    np.testing.assert_array_equal(out, np.sort(uni))
    (plan,) = [e for e in m.journal.events() if e.type == "plan_decision"]
    assert plan.fields["chosen"] == "ring"
    assert plan.fields["inputs"]["redundancy"] == 2


# ---- the wave seam ----------------------------------------------------------


def test_planned_wave_elems_reads_hbm_ledger():
    job = JobConfig(autotune=True)
    records = [
        {"type": "hbm_watermark", "seq": 0, "t": 0.0, "mono": 0.0,
         "phase": "exchange", "bytes_in_use": 32 << 20,
         "max_device_bytes": 1 << 30, "device": 0},
    ]
    m = _metered()
    chosen = planned_wave_elems(job, 1 << 20, 4, records, m)
    (ev,) = [e for e in m.journal.events() if e.type == "plan_decision"]
    assert ev.fields["policy"] == "wave_elems"
    assert ev.fields["chosen"] == chosen
    # the decision's inputs carry the ledger's ground truth verbatim
    assert ev.fields["inputs"]["peak_bytes"] == 32 << 20
    assert ev.fields["inputs"]["max_device_bytes"] == 1 << 30
    assert replay_decision("wave_elems", ev.fields["inputs"])[0] == chosen
    # autotune off: the seam is a pass-through, nothing journaled
    m2 = _metered()
    assert planned_wave_elems(JobConfig(), 1 << 20, 4, records, m2) == 1 << 20
    assert len(m2.journal) == 0
    # explicit wave_elems: the hand-set size wins, override journaled
    m3 = _metered()
    exp = JobConfig(autotune=True, explicit=("wave_elems",))
    assert planned_wave_elems(exp, 1 << 20, 4, records, m3) == 1 << 20
    (ov,) = [e for e in m3.journal.events() if e.type == "plan_override"]
    assert ov.fields["policy"] == "wave_elems"


# ---- the fleet redundancy seam ----------------------------------------------


def test_fleet_controller_plans_redundancy_from_loss_signal():
    from dsort_tpu.fleet.controller import FleetController, FleetTicket, _Job

    journal = EventLog()
    # one unreachable agent: the connect fails fast and is survived; with
    # start=False no dispatch/heartbeat threads ever run
    ctl = FleetController(agents=[("127.0.0.1", 1)], start=False,
                          journal=journal, autotune=True)
    job = _Job("j1", "acme", 100, "int32", None,
               FleetTicket("j1", "acme", 100, Metrics(journal=journal)))
    # healthy, no history: keep r=1 (no stamp semantics live in the value)
    assert ctl._plan_redundancy(job) == 1
    # an agent lost with work on it: the controller's own journal signal
    ctl._svc_metrics.event("job_rerouted", job_id="x", frm="a0", to="a1",
                           reason="agent_lost")
    assert ctl._plan_redundancy(job) == 2
    decisions = [e for e in journal.events() if e.type == "plan_decision"]
    assert [d.fields["chosen"] for d in decisions] == [1, 2]
    assert decisions[1].fields["inputs"]["loss_events"] == 1
    # every decision replays from its own journaled inputs
    for d in decisions:
        assert replay_decision("redundancy", d.fields["inputs"])[0] == \
            d.fields["chosen"]
    ctl.shutdown()


def test_fleet_controller_explicit_redundancy_overrides():
    from dsort_tpu.fleet.controller import FleetController, FleetTicket, _Job

    journal = EventLog()
    ctl = FleetController(agents=[("127.0.0.1", 1)], start=False,
                          journal=journal, autotune=True, redundancy=2)
    job = _Job("j1", "acme", 100, "int32", None,
               FleetTicket("j1", "acme", 100, Metrics(journal=journal)))
    assert ctl._plan_redundancy(job) == 2
    (ov,) = [e for e in journal.events() if e.type == "plan_override"]
    assert ov.fields["policy"] == "redundancy"
    assert ov.fields["explicit"] == 2
    assert ov.fields["planned"] == 2  # current posture, no signal: keep
    # autotune OFF forwards the explicit value silently (no planner plane)
    ctl2 = FleetController(agents=[("127.0.0.1", 1)], start=False,
                           autotune=False, redundancy=3)
    assert ctl2._plan_redundancy(job) == 3
    ctl.shutdown()
    ctl2.shutdown()


def test_service_submit_redundancy_reaches_exchange(devices):
    """The dispatch-header plumb: a per-job ``redundancy`` override rides
    submit -> ticket -> scheduler, and the coded replica plane runs."""
    from dsort_tpu.serve import SortService

    journal = EventLog()
    svc = SortService(
        job=JobConfig(settle_delay_s=0.01),
        serve=ServeConfig(small_job_max=1, max_queue_depth=16,
                          max_tenant_inflight=16),
        journal=journal,
    )
    d = gen_uniform(1 << 14, seed=3)
    _, t = svc.submit(d, redundancy=2)
    np.testing.assert_array_equal(t.result(120), np.sort(d))
    svc.shutdown(drain=True)
    assert "coded_replica_ship" in [e.type for e in journal.events()]


# ---- the prewarm seam --------------------------------------------------------


def _prewarm_svc(journal, policy="auto"):
    from dsort_tpu.serve import SortService

    return SortService(
        job=JobConfig(settle_delay_s=0.01),
        serve=ServeConfig(max_queue_depth=32, max_tenant_inflight=32,
                          prewarm_policy=policy,
                          prewarm_min_keys=12000, prewarm_max_keys=20000),
        journal=journal,
    )


def test_prewarm_auto_predicts_from_admission_mix(devices):
    journal = EventLog()
    svc = _prewarm_svc(journal)
    rng = np.random.default_rng(6)
    for _ in range(3):
        d = rng.integers(0, 1000, 14000, dtype=np.int32)
        svc.submit(d)[1].result(120)
    # the admission mix is all 14336:int32 -> predict exactly that rung,
    # which the traffic itself already compiled: ZERO fresh compiles,
    # where `--prewarm all` would still build the 2 cold rungs
    assert svc.prewarm() == 0
    (plan,) = [e for e in journal.events() if e.type == "plan_decision"
               and e.fields["policy"] == "prewarm"]
    assert plan.fields["chosen"] == [variant_key_label(plan_rung(14000),
                                                       "int32")]
    # the decision's history input IS the journal's admission stream
    admitted = [variant_key_label(plan_rung(e.fields["n_keys"]),
                                  e.fields["dtype"])
                for e in journal.events() if e.type == "job_admitted"]
    assert plan.fields["inputs"]["history"] == admitted
    assert replay_decision("prewarm", plan.fields["inputs"])[0] == \
        plan.fields["chosen"]
    svc.shutdown(drain=True)


def test_prewarm_auto_cold_start_compiles_full_ladder(devices):
    from dsort_tpu.parallel.exchange import ladder_rungs

    journal = EventLog()
    svc = _prewarm_svc(journal)
    ladder = ladder_rungs(20000, lo=12000)
    assert svc.prewarm() == len(ladder)  # no history: the honest warm set
    (plan,) = [e for e in journal.events() if e.type == "plan_decision"]
    assert plan.fields["chosen"] == [variant_key_label(r, "int32")
                                     for r in ladder]
    svc.shutdown(drain=True)


def test_prewarm_all_keeps_exhaustive_ladder(devices):
    from dsort_tpu.parallel.exchange import ladder_rungs

    journal = EventLog()
    svc = _prewarm_svc(journal, policy="all")
    rng = np.random.default_rng(7)
    svc.submit(rng.integers(0, 1000, 14000, dtype=np.int32))[1].result(120)
    # exhaustive: every rung the traffic did NOT already warm gets built
    assert svc.prewarm() == len(ladder_rungs(20000, lo=12000)) - 1
    # the old exhaustive behavior journals NO plan_decision: nothing was
    # predicted, the operator asked for everything
    assert not [e for e in journal.events() if e.type == "plan_decision"]
    svc.shutdown(drain=True)


# ---- tri-state config / CLI precedence --------------------------------------


def test_jobconfig_explicit_tristate():
    assert JobConfig().explicit == ()
    assert not JobConfig().autotune  # library default: OFF
    job = JobConfig(explicit=("exchange", "redundancy"))
    assert job.is_explicit("exchange") and not job.is_explicit("prewarm")
    # lists normalize; non-string knob names are a config error
    assert JobConfig(explicit=["exchange"]).explicit == ("exchange",)
    with pytest.raises(ConfigError, match="explicit"):
        JobConfig(explicit=(42,))


def test_conf_file_values_are_explicit():
    cfg = SortConfig.from_mapping({"EXCHANGE": "ring", "AUTOTUNE": "1"})
    assert cfg.job.autotune
    assert cfg.job.is_explicit("exchange")
    assert not cfg.job.is_explicit("redundancy")
    cfg2 = SortConfig.from_mapping({"SERVE_PREWARM": "all"})
    assert cfg2.serve.prewarm and cfg2.serve.prewarm_policy == "all"
    assert cfg2.job.is_explicit("prewarm")
    assert not SortConfig.from_mapping({"AUTOTUNE": "0"}).job.autotune


def test_cli_load_config_autotune_precedence(tmp_path):
    import argparse

    from dsort_tpu.cli import _load_config

    def ns(**kw):
        base = dict(conf=None, workers=None, dtype=None, kernel=None,
                    merge_kernel=None, exchange=None, redundancy=None,
                    checkpoint_dir=None, tenant=None, flight_dir=None,
                    no_autotune=False, prewarm=None)
        base.update(kw)
        return argparse.Namespace(**base)

    # CLI default: the closed loop is ON
    assert _load_config(ns()).job.autotune
    # --no-autotune wins over everything
    assert not _load_config(ns(no_autotune=True)).job.autotune
    # an explicit conf AUTOTUNE= is respected (no CLI re-default)
    conf = tmp_path / "dsort.conf"
    conf.write_text("AUTOTUNE=0\n")
    assert not _load_config(ns(conf=str(conf))).job.autotune
    # a knob flag joins the explicit set so the planner yields to it
    cfg = _load_config(ns(exchange="ring", redundancy=2))
    assert cfg.job.autotune
    assert cfg.job.is_explicit("exchange")
    assert cfg.job.is_explicit("redundancy")
    assert _load_config(ns(prewarm="all")).job.is_explicit("prewarm")


# ---- the audit drill: journal -> plan verdict -> replay ---------------------


def test_analyze_plan_verdict_replays_decisions(mesh8):
    """The §15 audit drill: a zipf job and a uniform job with autotune
    on; the ``plan`` verdict replays every decision from its journaled
    inputs with ZERO mismatches, the zipf decision is ring, and the
    decision's measured skew agrees with the ring plan's own
    ``skew_report`` ground truth from the SAME journal."""
    from dsort_tpu.parallel.sample_sort import SampleSort

    zipf = gen_zipf(1 << 17, a=1.3, seed=4)
    uni = gen_uniform(1 << 17, seed=0)
    m = _metered()
    SampleSort(mesh8, JobConfig(autotune=True, key_dtype=np.int64)).sort(
        zipf, metrics=m
    )
    SampleSort(mesh8, JobConfig(autotune=True)).sort(uni, metrics=m)
    recs = _records(m.journal)
    v = analyze_records(recs)["plan"]
    assert v["decisions"] == 2 and v["mismatches"] == 0
    assert v["overrides"] == 0 and v["by_policy"] == {"exchange": 2}
    ring_dec = next(d for d in v["replayed"] if d["chosen"] == "ring")
    assert ring_dec["match"] is True
    # ground truth: the chosen ring plan journaled its EXACT histogram
    # skew; the probe's sampled estimate must sit on the same side of the
    # threshold and in the same ballpark
    (skew_ev,) = [r for r in recs if r["type"] == "skew_report"]
    exact = skew_ev["max_mean_ratio"]
    probed = ring_dec["inputs"]["max_mean_ratio"]
    assert exact >= SKEW_RING_THRESHOLD and probed >= SKEW_RING_THRESHOLD
    assert 0.5 <= probed / exact <= 2.0
    # the human table renders the audit trail
    txt = format_analysis(analyze_records(recs))
    assert "planner decisions (replayed from journaled inputs):" in txt
    assert "2 decision(s)" in txt and "0 replay mismatch(es)" in txt


def test_analyze_plan_verdict_flags_tampered_inputs():
    """A decision whose journaled inputs do NOT reproduce its chosen
    value is an audit failure — mismatches counts it."""
    m = _metered()
    Planner(job=JobConfig(autotune=True)).decide(
        "exchange", {"max_mean_ratio": 3.0, "num_workers": 8}, m
    )
    recs = _records(m.journal)
    for r in recs:
        if r["type"] == "plan_decision":
            r["inputs"] = {"max_mean_ratio": 1.0, "num_workers": 8}
    v = analyze_records(recs)["plan"]
    assert v["mismatches"] == 1
    assert v["replayed"][0]["match"] is False


def test_planner_counters_reach_metrics_and_top():
    from dsort_tpu.obs import Telemetry
    from dsort_tpu.obs.telemetry import parse_prometheus_text
    from dsort_tpu.obs.top import render_top

    tel = Telemetry()
    m = _metered()
    tel.attach(m)
    p = Planner(job=JobConfig(autotune=True))
    p.decide("exchange", {"max_mean_ratio": 3.0, "num_workers": 8}, m)
    p.decide("exchange", {"max_mean_ratio": 1.0, "num_workers": 8}, m)
    p.note_override("redundancy", 2, {"current": 1}, m)
    scrape = parse_prometheus_text(tel.render_prometheus())
    assert scrape[("dsort_plan_decisions",
                   (("policy", "exchange"),))] == 2
    assert scrape[("dsort_plan_overrides",
                   (("policy", "redundancy"),))] == 1
    assert scrape[("dsort_plan_info", tuple(sorted({
        "policy": "exchange", "chosen": "alltoall",
    }.items())))] == 1
    out = render_top(scrape)
    assert "planner:" in out
    assert "exchange" in out and "alltoall" in out
    # the pane and the report renderer share plan_table (no-drift)
    assert plan_table([("exchange", 2, 0, "alltoall")]).splitlines()[1] \
        in out


def test_plan_table_renders_lists_and_empty():
    assert "(no planner decisions)" in plan_table([])
    txt = plan_table([("prewarm", 1, 0, ["a", "b", "c"])])
    assert "[3 key(s)]" in txt


# ---- CLI A/B + bench gates --------------------------------------------------


def test_cli_no_autotune_ab_bit_identical(tmp_path):
    """The escape hatch: the same input through ``dsort run`` with the
    planner on (the CLI default) and with ``--no-autotune`` produces
    byte-identical output files; only the planned run journals plan
    events."""
    from dsort_tpu import cli

    zipf = gen_zipf(20_000, a=1.3, seed=9, dtype=np.int32)
    inp = tmp_path / "in.txt"
    np.savetxt(inp, zipf, fmt="%d")
    out_a, out_b = tmp_path / "a.txt", tmp_path / "b.txt"
    j_a = tmp_path / "a.jsonl"
    # force the exchange plane (redundancy=2 skips the fused small-job
    # shortcut) so the planned run actually plans
    assert cli.main(["run", str(inp), "--redundancy", "2",
                     "--journal", str(j_a), "-o", str(out_a)]) == 0
    assert cli.main(["run", str(inp), "--redundancy", "2", "--no-autotune",
                     "-o", str(out_b)]) == 0
    assert out_a.read_bytes() == out_b.read_bytes()
    recs = [json.loads(ln) for ln in open(j_a)]
    plans = [r for r in recs if r["type"] == "plan_decision"]
    (exc,) = [p for p in plans if p["policy"] == "exchange"]
    assert exc["chosen"] == "ring"  # redundancy=2: the replica plane
    assert exc["inputs"]["redundancy"] == 2
    # --redundancy was explicit -> it cannot have been planner-chosen,
    # and the analyze verdict replays clean
    v = analyze_records(recs)["plan"]
    assert v["mismatches"] == 0


def test_cli_bench_autotune_ab_gate(capsys):
    """Tier-1 gate for `make autotune-smoke`: the A/B harness runs end to
    end — planner picks ring on zipf / alltoall on uniform, outputs
    bit-identical to both hand-set arms, one plan_decision per rep."""
    from dsort_tpu import cli

    rc = cli.main(["bench", "--autotune-ab", "--n", "65536", "--reps", "1"])
    out = capsys.readouterr().out
    rows = [json.loads(ln) for ln in out.splitlines() if ln.startswith("{")]
    assert rc == 0
    assert len(rows) == 2
    zipf = next(r for r in rows if "zipf" in r["metric"])
    uni = next(r for r in rows if "uniform" in r["metric"])
    assert zipf["chosen_exchange"] == "ring"
    assert uni["chosen_exchange"] == "alltoall"
    for r in rows:
        assert r["bit_identical"] is True
        assert r["plan_decisions"] == 1
        assert r["autotune_vs_best"] > 0
        assert r["alltoall_keys_per_sec"] > 0
        assert r["ring_keys_per_sec"] > 0


def test_cli_bench_autotune_ab_is_exclusive():
    from dsort_tpu import cli

    with pytest.raises(SystemExit, match="its own benchmark"):
        cli.main(["bench", "--autotune-ab", "--suite"])


def test_bench_r16_artifact_checks_and_compares():
    """BENCH_r16.jsonl: --check clean, the autotune rows join the
    trajectory as 'added' vs r15, and the headline holds: the planner
    picked the right schedule per workload, bit-identically, at >= 0.95x
    the best hand-set arm."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "bench", os.path.join(REPO, "bench.py")
    )
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    r16 = os.path.join(REPO, "BENCH_r16.jsonl")
    assert bench.check_artifact(r16) == []
    rows = bench.compare_artifacts(os.path.join(REPO, "BENCH_r15.jsonl"), r16)
    added = {r["metric"] for r in rows if r["class"] == "added"}
    assert any(m.startswith("autotune_ab_zipf") for m in added)
    assert any(m.startswith("autotune_ab_uniform") for m in added)
    with open(r16) as f:
        lines = [json.loads(ln) for ln in f if ln.strip()]
    zipf = next(l for l in lines
                if l.get("metric", "").startswith("autotune_ab_zipf"))
    uni = next(l for l in lines
               if l.get("metric", "").startswith("autotune_ab_uniform"))
    assert zipf["chosen_exchange"] == "ring"
    assert uni["chosen_exchange"] == "alltoall"
    for l in (zipf, uni):
        assert l["bit_identical"] is True
        assert l["autotune_vs_best"] >= 0.95  # the planner paid for itself
        assert l["plan_decisions"] >= 1


# ---- docs are part of the contract ------------------------------------------


def test_architecture_documents_planner_plane():
    """§15's contract is test-enforced like §7–§14: the policy catalog,
    both event schemas verbatim, the precedence order, the replay
    contract and the escape hatch."""
    arch = open(os.path.join(REPO, "ARCHITECTURE.md"),
                encoding="utf-8").read()
    assert "## 15. Planner plane" in arch
    for policy in PLAN_POLICIES:
        assert f"`{policy}`" in arch, f"policy {policy} undocumented"
    for field in PLAN_DECISION_FIELDS + PLAN_OVERRIDE_FIELDS:
        assert f"`{field}`" in arch, f"schema field {field} undocumented"
    for etype in ("plan_decision", "plan_override"):
        assert f"`{etype}`" in arch
    for term in ("SKEW_RING_THRESHOLD", "WAVE_HBM_BUDGET_FRAC",
                 "REDUNDANCY_DEGRADED_FRAC", "PREWARM_HISTORY",
                 "replay_decision", "--no-autotune", "AUTOTUNE",
                 "explicit flag > conf file > planner",
                 "--prewarm all", "autotune-smoke"):
        assert term in arch, f"§15 must explain {term}"
