"""Oracle tests for the block-bitonic Pallas kernel (``ops.block_sort``).

Runs under the Pallas interpreter on the CPU mesh (conftest), with small
``tile_rows=8`` / ``block_rows=64`` so the full multi-kernel pass structure
(K1 tile sort, K1b combiner passes 8->32->64 rows, K2 cross stages, K3 merge
tails) runs on test-sized inputs — the same code paths the real chip
executes at 256/1024-row blocks.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from dsort_tpu.ops.block_sort import block_sort
from dsort_tpu.ops.local_sort import sort_with_kernel


def _deep_interpret_ok() -> bool:
    """Can this jax's pallas interpreter lower the deep cross/orbit kernels?

    Older jaxlib interpreters hit an MLIR operand-type mismatch (an i64
    weak scalar in the span while-loop under x64) for any input that
    engages the multi-block cross stages (> one 64x128 block here).  Probe
    once at collection: on such an environment the affected oracle tests
    skip with this reason instead of burning minutes failing one by one —
    the single-block / combiner paths still run everywhere.
    """
    try:
        # Smallest shape that engages the multi-block cross stages (> one
        # 64x128 block): keeps the collection-time probe cheap either way.
        x = np.arange(9_000, dtype=np.int32)[::-1].copy()
        out = np.asarray(
            block_sort(jnp.asarray(x), block_rows=64, tile_rows=8,
                       interpret=True)
        )
        return bool((np.diff(out) >= 0).all())
    except Exception:
        return False


deep_interpret = pytest.mark.skipif(
    not _deep_interpret_ok(),
    reason="pallas interpreter on this jax cannot lower the deep "
           "cross/orbit kernels (MLIR i64 operand mismatch)",
)


@pytest.mark.parametrize(
    "n",
    [1, 2, 129, 1000, 1024,
     pytest.param(4096, marks=pytest.mark.slow),
     pytest.param(65_536, marks=deep_interpret),
     pytest.param(100_000, marks=deep_interpret),
     pytest.param((1 << 17) + 77, marks=deep_interpret)],
)
def test_block_sort_matches_numpy(n):
    rng = np.random.default_rng(n)
    x = rng.integers(-(2**31), 2**31 - 1, n, dtype=np.int64).astype(np.int32)
    out = np.asarray(block_sort(jnp.asarray(x), block_rows=64, tile_rows=8, interpret=True))
    np.testing.assert_array_equal(out, np.sort(x))


@pytest.mark.parametrize("dtype", [np.int32, np.uint32, np.float32])
@deep_interpret
def test_block_sort_dtypes(dtype):
    rng = np.random.default_rng(7)
    if dtype == np.float32:
        x = (rng.standard_normal(20_000) * 1e6).astype(dtype)
    else:
        x = rng.integers(0, 2**31, 20_000).astype(dtype)
    out = np.asarray(block_sort(jnp.asarray(x), block_rows=64, tile_rows=8, interpret=True))
    np.testing.assert_array_equal(out, np.sort(x))


@deep_interpret
def test_block_sort_extremes_and_duplicates():
    """Sentinel-valued real keys survive padding; heavy duplicates sort."""
    rng = np.random.default_rng(3)
    x = np.concatenate(
        [
            np.full(100, np.iinfo(np.int32).max, np.int32),
            np.full(100, np.iinfo(np.int32).min, np.int32),
            rng.integers(-5, 5, 10_000).astype(np.int32),
        ]
    )
    rng.shuffle(x)
    out = np.asarray(block_sort(jnp.asarray(x), block_rows=64, tile_rows=8, interpret=True))
    np.testing.assert_array_equal(out, np.sort(x))


def test_block_sort_single_block_path():
    """n small enough for one block: no cross/tail kernels involved."""
    rng = np.random.default_rng(11)
    x = rng.integers(-(2**31), 2**31 - 1, 8 * 128, dtype=np.int64).astype(
        np.int32
    )
    out = np.asarray(block_sort(jnp.asarray(x), interpret=True))
    np.testing.assert_array_equal(out, np.sort(x))


@deep_interpret
def test_block_sort_sorted_and_reverse_inputs():
    """Comparator networks are data-oblivious, but exercise the edges."""
    n = 30_000
    asc = np.arange(n, dtype=np.int32)
    for x in (asc, asc[::-1].copy(), np.zeros(n, np.int32)):
        out = np.asarray(block_sort(jnp.asarray(x), block_rows=64, tile_rows=8, interpret=True))
        np.testing.assert_array_equal(out, np.sort(x))


@pytest.mark.slow
def test_sort_with_kernel_block():
    rng = np.random.default_rng(5)
    x = rng.integers(-(2**31), 2**31 - 1, 50_000, dtype=np.int64).astype(
        np.int32
    )
    out = np.asarray(sort_with_kernel(jnp.asarray(x), kernel="block"))
    np.testing.assert_array_equal(out, np.sort(x))


def test_block_sort_rejects_bad_block_rows():
    x = jnp.arange(10, dtype=jnp.int32)
    with pytest.raises(ValueError):
        block_sort(x, block_rows=300, interpret=True)
    with pytest.raises(ValueError):
        block_sort(x, tile_rows=4, interpret=True)


@pytest.mark.parametrize("dtype", [np.int64, np.uint64])
@deep_interpret
def test_block_sort_64bit_planes(dtype):
    """64-bit keys ride as lexicographic (hi, lo) uint32 planes."""
    rng = np.random.default_rng(9)
    lo = 0 if dtype == np.uint64 else -(2**62)
    x = rng.integers(lo, 2**62, 30_000).astype(dtype)
    out = np.asarray(block_sort(jnp.asarray(x), block_rows=64, tile_rows=8, interpret=True))
    np.testing.assert_array_equal(out, np.sort(x))


@deep_interpret
def test_block_sort_64bit_hi_plane_collisions():
    """Keys equal in the hi plane order by the lo plane."""
    rng = np.random.default_rng(10)
    x = ((rng.integers(0, 3, 20_000).astype(np.uint64)) << 32) | rng.integers(
        0, 2**32, 20_000
    ).astype(np.uint64)
    out = np.asarray(block_sort(jnp.asarray(x), block_rows=64, tile_rows=8, interpret=True))
    np.testing.assert_array_equal(out, np.sort(x))


@deep_interpret
def test_block_sort_64bit_deep_cross_levels():
    """Enough blocks (t=64 at block_rows=8) that the multi-plane K2 path
    (single cross stages at m > MULTI_M_HI) executes, not just K2b/K3."""
    rng = np.random.default_rng(11)
    x = rng.integers(-(2**62), 2**62, 40_000).astype(np.int64)
    out = np.asarray(block_sort(jnp.asarray(x), block_rows=8, tile_rows=8, interpret=True))
    np.testing.assert_array_equal(out, np.sort(x))


def test_block_sort_rejects_2d():
    with pytest.raises(ValueError, match="1-D"):
        block_sort(jnp.zeros((64, 128), jnp.int32), interpret=True)


@deep_interpret
def test_orbit_pass_multi_level():
    """128 blocks at block_rows=8: levels kb=64 and kb=128 each run their
    m>span cross stages as ONE K2c orbit pass (mid 4 and 8) — the r4 pass
    that replaced per-stage K2 crosses.  Exactness over the full array pins
    both the strided view's block mapping and the grid-scalar directions."""
    rng = np.random.default_rng(12)
    x = rng.integers(-(2**31), 2**31, 1 << 17, dtype=np.int64).astype(np.int32)
    out = np.asarray(
        block_sort(jnp.asarray(x), block_rows=8, tile_rows=8, interpret=True)
    )
    np.testing.assert_array_equal(out, np.sort(x))


@deep_interpret
def test_orbit_pass_uint32_sign_flip_path():
    """uint32 keys ride the signed fast path (sign-bit flip) and are
    single-plane, so they take the orbit pass too — pinned at a depth
    (128 blocks at block_rows=8) where multi-stage orbits really run."""
    rng = np.random.default_rng(14)
    x = rng.integers(0, 2**32, 1 << 17, dtype=np.uint64).astype(np.uint32)
    out = np.asarray(
        block_sort(jnp.asarray(x), block_rows=8, tile_rows=8, interpret=True)
    )
    np.testing.assert_array_equal(out, np.sort(x))


@deep_interpret
def test_orbit_cap_peels_k2_singles(monkeypatch):
    """With ORBIT_MID_MAX forced to 2, wide levels peel their top cross
    stages as K2 singles before the capped orbit — the >=2^27-int32 fallback
    exercised at test scale.  kb_shift > 0 directions are what this pins."""
    import dsort_tpu.ops.block_sort as B

    monkeypatch.setattr(B, "ORBIT_MID_MAX", 2)
    rng = np.random.default_rng(13)
    x = rng.integers(-(2**31), 2**31, 1 << 17, dtype=np.int64).astype(np.int32)
    out = np.asarray(
        block_sort(jnp.asarray(x), block_rows=8, tile_rows=8, interpret=True)
    )
    np.testing.assert_array_equal(out, np.sort(x))


def test_auto_kernel_keeps_floats_on_lax(monkeypatch):
    """auto must never hand raw floats (possible NaNs) to the min/max network."""
    import dsort_tpu.ops.pallas_sort as ps

    monkeypatch.setattr(ps, "_on_tpu", lambda: True)
    called = {}
    import dsort_tpu.ops.block_sort as bs

    def no_block(*a, **k):
        called["block"] = True
        raise AssertionError("block kernel must not see floats via auto")

    monkeypatch.setattr(bs, "block_sort", no_block)
    x = np.full(1 << 16, np.nan, np.float32)
    x[:100] = np.arange(100, dtype=np.float32)
    out = np.asarray(sort_with_kernel(jnp.asarray(x), "auto"))
    assert "block" not in called
    assert (out[:100] == np.arange(100, dtype=np.float32)).all()
    assert np.isnan(out[100:]).all()


@pytest.mark.parametrize("dtype", [np.int32, np.uint32, np.int64, np.uint64])
@deep_interpret
def test_block_sort_pairs_matches_lexsort(dtype):
    """(key, rank) lexicographic pairs sort: the shuffle-combine building
    block — rank breaks ties deterministically and returns the permutation."""
    from dsort_tpu.ops.block_sort import block_sort_pairs

    rng = np.random.default_rng(17)
    n = 9_000
    lo, hi = (0, 50) if np.issubdtype(dtype, np.unsignedinteger) else (-25, 25)
    k = rng.integers(lo, hi, n).astype(dtype)  # heavy duplicates: ranks matter
    r = rng.permutation(n).astype(np.int32)
    ok, orr = block_sort_pairs(
        jnp.asarray(k), jnp.asarray(r), block_rows=64, tile_rows=8,
        interpret=True,
    )
    order = np.lexsort((r, k))
    np.testing.assert_array_equal(np.asarray(ok), k[order])
    np.testing.assert_array_equal(np.asarray(orr), r[order])


@pytest.mark.slow
def test_block_sort_pairs_sentinel_keys_with_rank():
    """Real keys equal to the padding sentinel stay ordered by rank ahead of
    the int32-max pad ranks."""
    from dsort_tpu.ops.block_sort import block_sort_pairs

    n = 1500  # non-power-of-two: padding engages
    k = np.full(n, np.iinfo(np.int32).max, np.int32)
    r = np.arange(n, dtype=np.int32)[::-1].copy()
    ok, orr = block_sort_pairs(
        jnp.asarray(k), jnp.asarray(r), block_rows=64, tile_rows=8,
        interpret=True,
    )
    np.testing.assert_array_equal(np.asarray(ok), k)
    np.testing.assert_array_equal(np.asarray(orr), np.arange(n, dtype=np.int32))


def _sorted_runs(rng, r, l, dtype=np.int32, pad_tail=0):
    """r rows of l keys each, row-sorted, optionally sentinel-padded tails."""
    lo, hi = (0, 2**32) if dtype == np.uint32 else (-(2**31), 2**31 - 1)
    runs = np.sort(rng.integers(lo, hi, (r, l)).astype(dtype), axis=1)
    if pad_tail:
        sent = np.iinfo(dtype).max
        for i in range(r):
            k = int(rng.integers(0, pad_tail + 1))
            if k:
                runs[i, -k:] = sent
                runs[i] = np.sort(runs[i])
    return runs


@pytest.mark.parametrize("r,l", [
    pytest.param(2, 64, marks=pytest.mark.slow),
    (4, 1000),
    pytest.param(8, 4096, marks=deep_interpret),
    (3, 700),
    pytest.param(16, 256, marks=pytest.mark.slow),
    pytest.param(7, 128, marks=pytest.mark.slow),
])
def test_block_merge_runs_matches_sort(r, l):
    from dsort_tpu.ops.block_sort import block_merge_runs

    rng = np.random.default_rng(r * 1000 + l)
    runs = _sorted_runs(rng, r, l, pad_tail=l // 4)
    out = np.asarray(
        block_merge_runs(jnp.asarray(runs), block_rows=64, interpret=True)
    )
    np.testing.assert_array_equal(out, np.sort(runs.reshape(-1)))


@deep_interpret
def test_block_merge_runs_through_orbit_levels():
    """64 one-block runs at block_rows=8: the merge driver's upper levels
    run their above-span cross stages as K2c orbit passes (mid 4 and 8) —
    the merge-entry counterpart of test_orbit_pass_multi_level."""
    from dsort_tpu.ops.block_sort import block_merge_runs

    rng = np.random.default_rng(77)
    runs = _sorted_runs(rng, 64, 1024)
    out = np.asarray(
        block_merge_runs(jnp.asarray(runs), block_rows=8, interpret=True)
    )
    np.testing.assert_array_equal(out, np.sort(runs.reshape(-1)))


@pytest.mark.parametrize(
    "dtype",
    [pytest.param(np.uint32, marks=pytest.mark.slow), np.int64, np.uint64],
)
def test_block_merge_runs_dtypes(dtype):
    from dsort_tpu.ops.block_sort import block_merge_runs

    rng = np.random.default_rng(17)
    if np.dtype(dtype).itemsize == 8:
        lo, hi = (
            (0, 2**64) if dtype == np.uint64 else (-(2**63), 2**63 - 1)
        )
        runs = np.sort(
            rng.integers(lo, hi, (8, 512), dtype=dtype), axis=1
        )
    else:
        runs = _sorted_runs(rng, 8, 512, dtype=dtype)
    out = np.asarray(
        block_merge_runs(jnp.asarray(runs), block_rows=64, interpret=True)
    )
    np.testing.assert_array_equal(out, np.sort(runs.reshape(-1)))


@deep_interpret
def test_block_merge_runs_spmd_shape_runs_exceed_block():
    """Runs longer than a merge block take the cross/span-tail entry path
    (the real SPMD shape: each received row spans >= 1 block)."""
    from dsort_tpu.ops.block_sort import block_merge_runs

    rng = np.random.default_rng(23)
    runs = _sorted_runs(rng, 8, 64 * 128 * 2)  # 2 blocks per run at rows=64
    out = np.asarray(
        block_merge_runs(jnp.asarray(runs), block_rows=64, interpret=True)
    )
    np.testing.assert_array_equal(out, np.sort(runs.reshape(-1)))


def test_block_merge_runs_kv_matches_lexsort():
    from dsort_tpu.ops.block_sort import block_merge_runs_kv

    rng = np.random.default_rng(29)
    r, l = 8, 1024
    total = r * l
    # few distinct keys -> heavy ties; rank = is_pad*total + position per the
    # shuffle's tiebreak, rows sorted by (key, rank)
    keys = rng.integers(0, 50, (r, l)).astype(np.int32)
    rank = np.arange(total, dtype=np.int32).reshape(r, l)
    order = np.lexsort((rank, keys), axis=1)
    keys = np.take_along_axis(keys, order, axis=1)
    rank = np.take_along_axis(rank, order, axis=1)
    out_k, out_r = block_merge_runs_kv(
        jnp.asarray(keys), jnp.asarray(rank), block_rows=64, interpret=True
    )
    flat = np.lexsort((rank.reshape(-1), keys.reshape(-1)))
    np.testing.assert_array_equal(np.asarray(out_k), keys.reshape(-1)[flat])
    np.testing.assert_array_equal(np.asarray(out_r), rank.reshape(-1)[flat])


def test_block_merge_runs_single_run():
    from dsort_tpu.ops.block_sort import block_merge_runs

    x = np.sort(np.random.default_rng(1).integers(0, 100, (1, 777)).astype(np.int32))
    np.testing.assert_array_equal(
        np.asarray(block_merge_runs(jnp.asarray(x), interpret=True)),
        x.reshape(-1),
    )
