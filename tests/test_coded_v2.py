"""Coded exchange v2 (`parallel.coded` parity plane, ARCHITECTURE §18).

The acceptance bar (ISSUE 19): parity slots cut the wire premium below
0.75x of r=2 replication at the same single-loss survivability; kv
payloads ride the replica/parity plane (no silent uncoded downgrade);
a live-but-slow owner's range is served straggler-first under an
exactly-once journaled claim; and every mode x fault-shape cell stays
bit-identical, including the GF(256) byte-plane round trip on floats,
NaNs and sentinels.
"""

import os
import time

import numpy as np
import pytest

from dsort_tpu.analysis.spec import assert_conformant
from dsort_tpu.config import ConfigError, JobConfig, SortConfig
from dsort_tpu.data.ingest import gen_terasort, gen_uniform, gen_zipf
from dsort_tpu.parallel.coded import (
    CodedBudgetExceeded,
    _byte_row,
    _gf_scale,
    _parity_solve,
)
from dsort_tpu.parallel.exchange import (
    parity_slots,
    parity_wire_bytes,
    replica_wire_bytes,
    resolve_redundancy_mode,
)
from dsort_tpu.parallel.sample_sort import SampleSort
from dsort_tpu.scheduler.fault import FaultInjector, WorkerFailure
from dsort_tpu.utils.events import COUNTERS, EVENT_TYPES, EventLog
from dsort_tpu.utils.metrics import Metrics

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _metered():
    return Metrics(journal=EventLog())


def _sweep_hook(injector, p, stage="ring"):
    def hook():
        failed = []
        for i in range(p):
            try:
                injector.check(i, stage)
            except WorkerFailure as f:
                failed.append(f.worker)
        if failed:
            e = WorkerFailure(failed[0], stage)
            e.workers = failed
            raise e

    return hook


# ---- knob resolution + config ---------------------------------------------


def test_resolve_redundancy_mode_vocabulary():
    assert resolve_redundancy_mode(None, "replicate") == "replicate"
    assert resolve_redundancy_mode(None, "parity") == "parity"
    assert resolve_redundancy_mode("parity", "replicate") == "parity"
    with pytest.raises(ValueError):
        resolve_redundancy_mode("raid", "replicate")


def test_parity_slots_budget():
    assert parity_slots(1) == 0      # uncoded: no parity plane
    assert parity_slots(2) == 1      # XOR covers any single loss
    assert parity_slots(3) == 2      # P+Q covers any double
    assert parity_slots(8) == 2      # two erasures is the RAID-6 ceiling


def test_job_config_redundancy_mode_validated(tmp_path):
    assert JobConfig().redundancy_mode == "replicate"
    assert JobConfig(redundancy_mode="parity").redundancy_mode == "parity"
    with pytest.raises(ConfigError):
        JobConfig(redundancy_mode="raid")
    conf = tmp_path / "job.conf"
    conf.write_text("REDUNDANCY=2\nREDUNDANCY_MODE=parity\nEXCHANGE=ring\n")
    cfg = SortConfig.from_conf_file(str(conf))
    assert cfg.job.redundancy_mode == "parity"
    assert cfg.job.is_explicit("redundancy_mode")

    from dsort_tpu import cli

    class A:
        conf = None
        redundancy = 2
        redundancy_mode = "parity"

    assert cli._load_config(A()).job.redundancy_mode == "parity"


def test_parity_wire_bytes_model():
    caps = (16, 8, 8, 24)
    p, bps = 4, 4
    # One XOR slot sized at the max-cap bucket per device.
    assert parity_wire_bytes(caps, bps, p, 2) == 24 * bps * p
    # P+Q doubles it; uncoded ships nothing.
    assert parity_wire_bytes(caps, bps, p, 3) == 2 * 24 * bps * p
    assert parity_wire_bytes(caps, bps, p, 1) == 0
    # THE premium claim, on the model: parity r=2 < 0.75x replicate r=2
    # whenever the mesh has more than a few buckets per device.
    caps8 = (16, 8, 8, 24, 16, 8, 8, 12)
    ratio = parity_wire_bytes(caps8, 8, 8, 2) / replica_wire_bytes(
        caps8, 8, 8, 2
    )
    assert ratio < 0.75


# ---- GF(256) math: the byte-plane round trip ------------------------------


def test_gf256_parity_solve_round_trip_bit_identical():
    """Kill any 1 or 2 rows of a byte group; P (XOR) alone recovers one,
    P+Q recovers two — bit-identical, including NaN payload bytes."""
    rng = np.random.default_rng(7)
    p, cap = 8, 64
    rows = {}
    for k in range(p):
        f = rng.standard_normal(cap // 2).astype(np.float32)
        f[:4] = [np.nan, -0.0, np.inf, -np.inf]
        rows[k] = np.ascontiguousarray(f).view(np.uint8).reshape(-1)
    xor = np.zeros(rows[0].shape, np.uint8)
    q = np.zeros(rows[0].shape, np.uint8)
    from dsort_tpu.parallel.coded import _GF_EXP

    for k, r in rows.items():
        xor ^= r
        q ^= _gf_scale(r, int(_GF_EXP[k % 255]))
    # single erasure: XOR peel
    known = {k: r for k, r in rows.items() if k != 3}
    out = _parity_solve(known, [xor], [3])
    np.testing.assert_array_equal(out[3], rows[3])
    # double erasure: the P+Q closed form, every pair
    for i, j in ((0, 1), (2, 5), (6, 7)):
        known = {k: r for k, r in rows.items() if k not in (i, j)}
        out = _parity_solve(known, [xor, q], [i, j])
        np.testing.assert_array_equal(out[i], rows[i])
        np.testing.assert_array_equal(out[j], rows[j])


def test_byte_row_pads_with_sentinel():
    run = np.array([3, 1 << 40], np.int64)
    row = _byte_row(run, 4, np.array(np.iinfo(np.int64).max, np.int64))
    back = row.view(np.int64)
    assert list(back[:2]) == [3, 1 << 40]
    assert (back[2:] == np.iinfo(np.int64).max).all()


# ---- exchange-level: healthy parity bit-identical + premium ---------------


@pytest.mark.parametrize("red", [2, 3])
def test_parity_healthy_bit_identical(mesh8, red):
    ss = SampleSort(
        mesh8,
        JobConfig(exchange="ring", redundancy=red, redundancy_mode="parity"),
    )
    data = gen_uniform(100_003, seed=1)
    m = _metered()
    np.testing.assert_array_equal(ss.sort(data, metrics=m), np.sort(data))
    assert m.counters["coded_replica_bytes"] > 0
    ship = next(
        e for e in m.journal.events() if e.type == "coded_replica_ship"
    )
    assert ship.fields["mode"] == "parity"
    assert ship.fields["slots"] == parity_slots(red) * 8


def test_parity_premium_below_three_quarters_of_replicate(mesh8):
    """THE wire-premium acceptance gate at equal single-loss
    survivability: parity r=2 ships < 0.75x replicate r=2's
    `coded_replica_bytes` on the same measured plan."""
    data = gen_zipf(1 << 17, a=1.2, seed=3)
    bytes_by_mode = {}
    for mode in ("replicate", "parity"):
        ss = SampleSort(
            mesh8,
            JobConfig(
                exchange="ring", redundancy=2, redundancy_mode=mode,
                key_dtype=np.int64,
            ),
        )
        m = _metered()
        np.testing.assert_array_equal(
            ss.sort(data, metrics=m), np.sort(data)
        )
        bytes_by_mode[mode] = m.counters["coded_replica_bytes"]
    assert bytes_by_mode["parity"] > 0
    assert bytes_by_mode["parity"] < 0.75 * bytes_by_mode["replicate"]


def test_parity_float_keys_ride_mapped(mesh8):
    ss = SampleSort(
        mesh8,
        JobConfig(exchange="ring", redundancy=2, redundancy_mode="parity"),
    )
    rng = np.random.default_rng(3)
    f = rng.standard_normal(20_000).astype(np.float32)
    f[:7] = [np.nan, -np.nan, 0.0, -0.0, np.inf, -np.inf, 1.5]
    np.testing.assert_array_equal(ss.sort(f), np.sort(f))


# ---- fault matrix: snapshot-level reconstruction --------------------------


def test_parity_snapshot_fault_matrix(mesh8):
    """r=2 (XOR): any single loss solves, any double exceeds.  r=3
    (P+Q): non-adjacent doubles solve; an adjacent pair kills a parity
    holder and degrades cleanly."""
    data = gen_uniform(80_000, seed=5)
    expect = np.sort(data)
    ss = SampleSort(
        mesh8,
        JobConfig(exchange="ring", redundancy=2, redundancy_mode="parity"),
    )
    ss.fault_hook = lambda: (_ for _ in ()).throw(WorkerFailure(3, "ring"))
    with pytest.raises(WorkerFailure) as ei:
        ss.sort(data)
    st = ei.value.coded_state
    assert st.mode == "parity" and st.num_workers == 8
    for d in range(8):
        out, info = st.assemble([d])
        np.testing.assert_array_equal(out, expect)
        assert info["recovered_keys"] == len(st.ranges[d])
        assert info["replica_bytes"] > 0
    with pytest.raises(CodedBudgetExceeded):
        st.assemble([2, 5])  # one XOR slot cannot solve two erasures

    ss3 = SampleSort(
        mesh8,
        JobConfig(exchange="ring", redundancy=3, redundancy_mode="parity"),
    )
    e3 = WorkerFailure(2, "ring")
    e3.workers = [2, 5]
    ss3.fault_hook = lambda: (_ for _ in ()).throw(e3)
    with pytest.raises(WorkerFailure) as ei3:
        ss3.sort(data)
    st3 = ei3.value.coded_state
    out3, info3 = st3.assemble([2, 5])  # P+Q, non-adjacent pair
    np.testing.assert_array_equal(out3, expect)
    assert info3["holders"] == {2: [3, 4], 5: [6, 7]}
    with pytest.raises(CodedBudgetExceeded):
        st3.assemble([3, 4])  # 4 holds 3's P slot: holder dead
    with pytest.raises(CodedBudgetExceeded):
        st3.assemble([1, 2, 5])  # three erasures beat the RAID-6 ceiling


def test_parity_kv_snapshot_reconstructs_payload(mesh8):
    tk, tv = gen_terasort(6144, seed=9)
    ss = SampleSort(
        mesh8,
        JobConfig(
            exchange="ring", redundancy=2, redundancy_mode="parity",
            key_dtype=np.uint64, payload_bytes=tv.shape[1],
        ),
    )
    ss.fault_hook = lambda: (_ for _ in ()).throw(WorkerFailure(4, "ring"))
    with pytest.raises(WorkerFailure) as ei:
        ss.sort_kv(tk, tv)
    st = ei.value.coded_state
    assert st.kv and st.mode == "parity"
    (out_k, out_v), info = st.assemble([4])
    order = np.argsort(tk, kind="stable")
    np.testing.assert_array_equal(out_k, tk[order])
    np.testing.assert_array_equal(out_v, tv[order])
    assert info["replica_bytes"] > 0


# ---- scheduler drills: both modes through the full fault contract ---------


def test_scheduler_parity_recovery_zero_rerun(tmp_path):
    """The §14 acceptance drill in parity mode: one loss at r=2 recovers
    with zero re-dispatch, journals `parity_recover`, dumps a
    `parity_reconstruct` bundle."""
    from dsort_tpu.obs.flight import FlightRecorder
    from dsort_tpu.scheduler import SpmdScheduler

    inj = FaultInjector()
    sched = SpmdScheduler(
        job=JobConfig(
            settle_delay_s=0.01, exchange="ring", redundancy=2,
            redundancy_mode="parity", flight_recorder_dir=str(tmp_path),
        ),
        injector=inj,
    )
    z = gen_zipf(1 << 16, a=1.3, seed=5)
    np.testing.assert_array_equal(sched.sort(z), np.sort(z))  # warm
    inj.fail_once(3, "ring")
    m = _metered()
    np.testing.assert_array_equal(sched.sort(z, metrics=m), np.sort(z))
    assert m.counters["coded_recoveries"] == 1
    assert m.counters.get("device_handle_reruns", 0) == 0
    assert m.counters.get("shuffle_resort_keys", 0) == 0
    types = m.journal.types()
    assert types.count("attempt_start") == 1
    assert "parity_recover" in types and "coded_recover" not in types
    assert (
        types.index("worker_dead")
        < types.index("mesh_reform")
        < types.index("parity_recover")
    )
    rec = next(e for e in m.journal.events() if e.type == "parity_recover")
    assert rec.fields["dead"] == [3] and rec.fields["mode"] == "parity"
    assert rec.fields["recovered_keys"] > 0
    bundles = [
        b["recovery_path"]
        for b in FlightRecorder.read_bundles(str(tmp_path))
    ]
    assert bundles.count("parity_reconstruct") == 1
    assert_conformant(m.journal)  # parity_recovery grammar holds


def test_scheduler_parity_over_budget_degrades():
    from dsort_tpu.scheduler import SpmdScheduler

    inj = FaultInjector()
    sched = SpmdScheduler(
        job=JobConfig(
            settle_delay_s=0.01, exchange="ring", redundancy=2,
            redundancy_mode="parity",
        ),
        injector=inj,
    )
    z = gen_zipf(1 << 16, a=1.3, seed=5)
    np.testing.assert_array_equal(sched.sort(z), np.sort(z))
    inj.fail_sequence([(2, "ring"), (5, "ring")])  # 2 erasures > 1 XOR slot
    m = _metered()
    np.testing.assert_array_equal(sched.sort(z, metrics=m), np.sort(z))
    types = m.journal.types()
    assert "coded_budget_exceeded" in types
    assert "parity_recover" not in types
    assert types.count("attempt_start") == 2  # the re-run happened


def test_kv_parity_end_to_end_and_cheaper_than_replicate(mesh8):
    """kv + parity end-to-end: payloads follow their keys bit-exactly,
    and the kv premium (keys AND payload planes) still undercuts kv
    replication."""
    tk, tv = gen_terasort(8192, seed=21)
    order = np.argsort(tk, kind="stable")
    bytes_by_mode = {}
    for mode in ("replicate", "parity"):
        ss = SampleSort(
            mesh8,
            JobConfig(
                exchange="ring", redundancy=2, redundancy_mode=mode,
                key_dtype=np.uint64, payload_bytes=tv.shape[1],
            ),
        )
        m = _metered()
        ok, ov = ss.sort_kv(tk, tv, metrics=m)
        np.testing.assert_array_equal(ok, tk[order])
        np.testing.assert_array_equal(ov, tv[order])
        bytes_by_mode[mode] = m.counters["coded_replica_bytes"]
    assert 0 < bytes_by_mode["parity"] < 0.75 * bytes_by_mode["replicate"]


# ---- straggler-first range serving ----------------------------------------


@pytest.mark.parametrize("mode", ["replicate", "parity"])
def test_straggler_serve_exactly_once(mesh8, mode):
    """A live-but-slow owner's range is served from the coded plane:
    exactly one `coded_straggler_serve`, the losing owner fetch journals
    `won=False` after the drain, output bit-identical, no failure, no
    mesh re-form."""
    ss = SampleSort(
        mesh8,
        JobConfig(exchange="ring", redundancy=2, redundancy_mode=mode),
    )
    ss.straggler_fn = lambda: 3
    ss.fetch_delay_fn = lambda s: 0.75  # the holder leg always wins
    data = gen_uniform(60_000, seed=11)
    m = _metered()
    t0 = time.perf_counter()
    np.testing.assert_array_equal(ss.sort(data, metrics=m), np.sort(data))
    wall = time.perf_counter() - t0
    assert m.counters["coded_straggler_serves"] == 1
    serve = next(
        e for e in m.journal.events() if e.type == "coded_straggler_serve"
    )
    assert serve.fields["range"] == 3 and serve.fields["mode"] == mode
    assert serve.fields["recovered_keys"] > 0
    # the sort returned WITHOUT paying the owner's injected delay
    assert serve.fields["wall_s"] < 0.75
    types = m.journal.types()
    assert "worker_dead" not in types and "mesh_reform" not in types
    ss.join_stragglers()
    fetch = next(
        e for e in m.journal.events() if e.type == "coded_owner_fetch"
    )
    assert fetch.fields["won"] is False and fetch.fields["range"] == 3
    report = assert_conformant(m.journal)
    assert report["contracts"]["straggler_serve"]["checked"] >= 1
    del wall


def test_straggler_serve_uncoded_ignored(mesh8):
    """No replica plane, no race: redundancy=1 keeps the plain wait-on-
    owner path even with a named straggler."""
    ss = SampleSort(mesh8, JobConfig(exchange="ring"))
    ss.straggler_fn = lambda: 3
    ss.fetch_delay_fn = lambda s: 0.0
    data = gen_uniform(30_000, seed=13)
    m = _metered()
    np.testing.assert_array_equal(ss.sort(data, metrics=m), np.sort(data))
    assert m.counters.get("coded_straggler_serves", 0) == 0
    assert "coded_straggler_serve" not in m.journal.types()


def test_scheduler_straggler_binding_via_injector():
    """`FaultInjector.slow` names a WORKER; the scheduler translates to
    the attempt's mesh POSITION and the serve happens with no fault."""
    from dsort_tpu.scheduler import SpmdScheduler

    inj = FaultInjector()
    sched = SpmdScheduler(
        job=JobConfig(settle_delay_s=0.01, exchange="ring", redundancy=2),
        injector=inj,
    )
    z = gen_zipf(1 << 16, a=1.3, seed=7)
    np.testing.assert_array_equal(sched.sort(z), np.sort(z))  # warm
    inj.slow(5, 0.75)
    m = _metered()
    np.testing.assert_array_equal(sched.sort(z, metrics=m), np.sort(z))
    assert m.counters["coded_straggler_serves"] == 1
    serve = next(
        e for e in m.journal.events() if e.type == "coded_straggler_serve"
    )
    assert serve.fields["range"] == 5
    types = m.journal.types()
    assert types.count("attempt_start") == 1
    assert "worker_dead" not in types  # no failure was injected
    for ss in sched._sorters.values():
        ss.join_stragglers()
    assert_conformant(m.journal)
    inj.slow(5, 0)  # clear
    # all 8 workers still live: serving never evicts the slow owner
    assert sorted(sched.table.live_workers()) == list(range(8))


def test_health_verdict_names_straggler_position():
    """`obs.health.straggler_position` is the production binding: a
    verdict that is BOTH straggler and degraded maps to its mesh
    position; healthy or merely-degraded agents don't."""
    from dsort_tpu.obs.health import straggler_position

    class FakeAnalyzer:
        def __init__(self, verdicts):
            self._v = verdicts

        def verdicts(self):
            return self._v

    v = {
        "a0": {"straggler": False, "degraded": False},
        "a1": {"straggler": True, "degraded": False},   # fast blip only
        "a2": {"straggler": True, "degraded": True},    # the real one
    }
    assert straggler_position(FakeAnalyzer(v), ["a0", "a1", "a2"]) == 2
    assert straggler_position(FakeAnalyzer(v), ["a0", "a1"]) is None
    assert straggler_position(FakeAnalyzer({}), ["a0"]) is None


# ---- wave pipeline: parity + retention ------------------------------------


def test_wave_parity_repair_and_restart_resume(tmp_path):
    """A parity-coded wave repairs a mid-ring loss from the parity plane
    (no host re-sort) and its runs stay ordinary durable entries for
    restart-resume."""
    from dsort_tpu.models.wave_sort import ExternalWaveSort

    data = gen_uniform(1 << 17, seed=17)
    kw = dict(
        wave_elems=1 << 16, spill_dir=str(tmp_path), job_id="parwave",
        job=JobConfig(exchange="ring"), redundancy=2,
        redundancy_mode="parity",
    )
    ws = ExternalWaveSort(**kw)
    assert ws.redundancy_mode == "parity"
    inj = FaultInjector()
    inj.fail_once(3, "ring")
    ws.fault_hook = _sweep_hook(inj, ws.num_workers)
    m = _metered()
    np.testing.assert_array_equal(ws.sort(data, metrics=m), np.sort(data))
    assert m.counters["coded_recoveries"] == 1
    assert m.counters.get("wave_runs_resorted", 0) == 0
    types = m.journal.types()
    assert "parity_recover" in types and "wave_resume" not in types
    assert_conformant(m.journal)
    # restart: coded runs restore for free
    ws2 = ExternalWaveSort(**kw)
    m2 = _metered()
    np.testing.assert_array_equal(ws2.sort(data, metrics=m2), np.sort(data))
    assert m2.counters["runs_resumed"] == 2 * ws2.num_workers
    assert m2.counters.get("waves_sorted", 0) == 0


def test_wave_terasort_coded_retention_repair(tmp_path, devices):
    """Record waves keep the retention doctrine: a coded TeraSort wave
    repairs from the retained D2H shards — `coded_recover` with
    mode="retain", replica_bytes=0, zero runs re-sorted — and the
    output still matches the oracle byte-for-byte."""
    from dsort_tpu.data.ingest import _pack_be64, gen_terasort_file, terasort_secondary
    from dsort_tpu.models.wave_sort import ExternalWaveTeraSort
    from dsort_tpu.parallel.mesh import local_device_mesh

    in_path = str(tmp_path / "in.bin")
    out_path = str(tmp_path / "out.bin")
    gen_terasort_file(in_path, 16000, seed=23)
    t = ExternalWaveTeraSort(
        local_device_mesh(8), wave_recs=4096,
        spill_dir=str(tmp_path / "spill"), job_id="twc", redundancy=2,
        resume=False,
    )
    inj = FaultInjector()
    inj.fail_once(3, "ring")
    t.fault_hook = _sweep_hook(inj, t.num_workers)
    m = _metered()
    t.sort_file(in_path, out_path, metrics=m)
    raw = np.fromfile(in_path, np.uint8).reshape(-1, 100)
    order = np.lexsort(
        (terasort_secondary(raw[:, 8:10]), _pack_be64(raw[:, :8]))
    )
    got = np.fromfile(out_path, np.uint8).reshape(-1, 100)
    np.testing.assert_array_equal(got, raw[order])
    rec = next(e for e in m.journal.events() if e.type == "coded_recover")
    assert rec.fields["mode"] == "retain"
    assert rec.fields["replica_bytes"] == 0
    assert m.counters.get("wave_runs_resorted", 0) == 0
    assert_conformant(m.journal)


# ---- planner: the mode and slice policies ---------------------------------


def test_plan_redundancy_mode_policy_replay():
    from dsort_tpu.obs.plan import replay_decision

    # observed losses: full copies
    chosen, rejected = replay_decision(
        "redundancy_mode", {"agents": 4, "degraded": 0, "loss_events": 2}
    )
    assert chosen == "replicate"
    assert rejected[0]["value"] == "parity"
    # degraded-but-alive fleet: parity
    chosen, rejected = replay_decision(
        "redundancy_mode", {"agents": 4, "degraded": 2, "loss_events": 0}
    )
    assert chosen == "parity"
    assert rejected[0]["value"] == "replicate"
    # healthy fleet: replicate (the no-signal default)
    chosen, _ = replay_decision(
        "redundancy_mode", {"agents": 4, "degraded": 0, "loss_events": 0}
    )
    assert chosen == "replicate"


def test_plan_slice_devices_policy_replay():
    from dsort_tpu.obs.plan import SLICE_KEYS_PER_DEVICE, replay_decision

    # small admitted rungs: 1-device slices (max packing)
    chosen, _ = replay_decision(
        "slice_devices",
        {"num_devices": 8, "current": 4, "rungs": [1 << 12] * 10},
    )
    assert chosen == 1
    # heavy mix: widen until p90/w fits the per-device budget
    heavy = [4 * SLICE_KEYS_PER_DEVICE] * 10
    chosen, _ = replay_decision(
        "slice_devices",
        {"num_devices": 8, "current": 1, "rungs": heavy},
    )
    assert chosen == 4
    # no admissions: keep the current width, named rejection
    chosen, rejected = replay_decision(
        "slice_devices", {"num_devices": 8, "current": 2, "rungs": []}
    )
    assert chosen == 2 and rejected[0]["value"] == "resize"


def test_planned_slice_devices_seam_replay_equals_live():
    from dsort_tpu.obs.plan import planned_slice_devices

    job = JobConfig(autotune=True)
    records = [
        {"type": "job_admitted", "n_keys": 1 << 12, "dtype": "int32"}
        for _ in range(6)
    ]
    m = _metered()
    live = planned_slice_devices(job, None, 4, 8, records, m)
    assert live == 1
    dec = next(
        e for e in m.journal.events() if e.type == "plan_decision"
    )
    assert dec.fields["policy"] == "slice_devices"
    assert dec.fields["chosen"] == 1
    # replay the journaled decision from its own inputs
    from dsort_tpu.obs.plan import replay_decision

    assert replay_decision("slice_devices", dec.fields["inputs"])[0] == live
    # a second replay from the same records is bit-identical
    assert planned_slice_devices(job, None, 4, 8, records, _metered()) == 1
    # autotune off: the knob rides untouched, nothing journaled
    m2 = _metered()
    assert planned_slice_devices(JobConfig(), None, 4, 8, records, m2) == 4
    assert m2.journal.types() == []


def test_planned_slice_devices_explicit_wins():
    from dsort_tpu.obs.plan import planned_slice_devices

    job = JobConfig(autotune=True, explicit=("slice_devices",))
    records = [
        {"type": "job_admitted", "n_keys": 1 << 12, "dtype": "int32"}
        for _ in range(6)
    ]
    m = _metered()
    assert planned_slice_devices(job, None, 4, 8, records, m) == 4
    ov = next(e for e in m.journal.events() if e.type == "plan_override")
    assert ov.fields["policy"] == "slice_devices"
    assert ov.fields["explicit"] == 4 and ov.fields["planned"] == 1


def test_serve_replans_slice_width_from_journal():
    """`SortService.__init__` replays the attached journal through the
    slice policy: a small-rung admission history narrows the slices
    before any worker starts."""
    from dsort_tpu.config import ServeConfig
    from dsort_tpu.serve.service import SortService

    journal = EventLog()
    for _ in range(6):
        journal.emit("job_admitted", n_keys=1 << 12, dtype="int32")
    svc = SortService(
        job=JobConfig(autotune=True),
        serve=ServeConfig(slice_devices=4),
        journal=journal, start=False,
    )
    try:
        assert all(len(g) == 1 for g in svc._slices.values())
        assert len(svc._slices) == len(svc._devices)
    finally:
        svc.shutdown()


# ---- analyzer: the v2 recovery verdict ------------------------------------


def test_analyze_recovery_verdict_parity_and_straggler():
    from dsort_tpu.obs.analyze import analyze_records
    from dsort_tpu.scheduler import SpmdScheduler

    z = gen_zipf(1 << 16, a=1.3, seed=5)
    inj = FaultInjector()
    sched = SpmdScheduler(
        job=JobConfig(
            settle_delay_s=0.01, exchange="ring", redundancy=2,
            redundancy_mode="parity",
        ),
        injector=inj,
    )
    sched.sort(z)  # warm
    inj.fail_once(3, "ring")
    m = _metered()
    np.testing.assert_array_equal(sched.sort(z, metrics=m), np.sort(z))
    v = analyze_records([e.to_dict() for e in m.journal.events()])["recovery"]
    assert v["path"] == "parity_reconstruct"
    assert v["coded"]["parity_recoveries"] == 1
    assert v["coded"]["recoveries"] == 0
    assert v["straggler"]["serves"] == 0
    # straggler-only journal: serves counted, no failure posture
    inj.slow(5, 0.4)
    m2 = _metered()
    np.testing.assert_array_equal(sched.sort(z, metrics=m2), np.sort(z))
    for ss in sched._sorters.values():
        ss.join_stragglers()
    v2 = analyze_records(
        [e.to_dict() for e in m2.journal.events()]
    )["recovery"]
    assert v2["path"] == "straggler_serve"
    assert v2["straggler"]["serves"] == 1
    assert v2["straggler"]["served_keys"] > 0


# ---- registries + docs ----------------------------------------------------


def test_v2_events_and_counters_registered():
    for ev in ("parity_recover", "coded_straggler_serve",
               "coded_owner_fetch"):
        assert ev in EVENT_TYPES
    assert "coded_straggler_serves" in COUNTERS
    from dsort_tpu.analysis.spec.contracts import TRACE_CONTRACTS

    assert "parity_recovery" in TRACE_CONTRACTS
    assert "straggler_serve" in TRACE_CONTRACTS


def test_architecture_documents_coded_v2():
    """§18's schema is test-enforced like §7–§17: the section must name
    the knob, the parity math, the events, and the bench artifact."""
    text = open(os.path.join(REPO, "ARCHITECTURE.md")).read()
    assert "## 18. Coded exchange v2" in text
    s18 = text.split("## 18. Coded exchange v2", 1)[1]
    for term in (
        "`redundancy_mode`", "REDUNDANCY_MODE", "parity_slots", "GF(256)",
        "0x11D", "`parity_recover`", "`coded_straggler_serve`",
        "`coded_owner_fetch`", "`coded_straggler_serves`",
        "`StragglerClaim`", "`coded_replica_bytes`", "straggler_serve",
        "parity_recovery", "BENCH_r19.jsonl", "coded-v2-smoke",
        "join_stragglers",
    ):
        assert term in s18, f"§18 must document {term}"


def test_cli_bench_coded_v2_ab_gate(capsys):
    """Tier-1 gate for `make coded-v2-smoke`: the v2 A/B harness runs end
    to end — parity premium under 0.75x replicate, both loss arms recover
    locally, and the straggler row's serve beats its measured
    wait-on-owner baseline with exactly one claim."""
    import json

    from dsort_tpu import cli

    rc = cli.main(["bench", "--coded-v2-ab", "--n", "65536", "--reps", "1"])
    out = capsys.readouterr().out
    rows = [json.loads(ln) for ln in out.splitlines() if ln.startswith("{")]
    assert rc == 0
    premium = next(r for r in rows if "premium" in r["metric"])
    failure = next(r for r in rows if "failure" in r["metric"])
    straggler = next(r for r in rows if "straggler" in r["metric"])
    assert premium["bit_identical"] is True
    assert premium["redundancy_mode"] == "parity"
    assert 0 < premium["coded_replica_bytes"] < (
        0.75 * premium["replicate_replica_bytes"]
    )
    assert premium["premium_ratio"] < 0.75
    assert failure["bit_identical"] is True
    assert failure["coded_recoveries"] == 1
    assert failure["recovered_keys"] > 0
    assert failure["throughput_under_failure_ratio"] > 0
    assert straggler["bit_identical"] is True
    assert straggler["straggler_serves"] == 1
    assert straggler["mesh_reforms"] == 0
    assert straggler["p99_serve_s"] < straggler["p99_owner_s"]
    assert straggler["speedup_vs_wait"] > 1
