"""Test harness: force an 8-device simulated CPU mesh (SURVEY.md §4).

The reference has no tests at all; its de-facto strategy is a golden
input/output pair plus manual multi-process runs (SURVEY.md §4).  Here the
"cluster" for tests is JAX's CPU multi-device simulation, so distributed
behavior (shard_map, all_to_all, fault reassignment) runs in-process.

Note: this environment may pre-import jax via a site hook with a TPU platform
pinned in ``JAX_PLATFORMS``; env vars alone are then too late, so we also use
``jax.config.update`` before any backend is initialized.
"""

import os

if os.environ.get("DSORT_TPU_TESTS") == "1":
    # Hardware-gate mode: leave the real backend in charge so
    # tests/test_tpu_smoke.py runs on the chip —
    #   DSORT_TPU_TESTS=1 python -m pytest tests/test_tpu_smoke.py -q
    import jax
else:
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
    ).strip()
    os.environ["JAX_PLATFORMS"] = "cpu"

    import jax

    jax.config.update("jax_platforms", "cpu")

jax.config.update("jax_enable_x64", True)  # 64-bit key dtypes (BASELINE config #3)

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def devices():
    devs = jax.devices()
    assert len(devs) >= 8, f"expected >=8 simulated CPU devices, got {devs}"
    return devs


@pytest.fixture(scope="session")
def mesh8(devices):
    from dsort_tpu.parallel.mesh import local_device_mesh

    return local_device_mesh(8)
