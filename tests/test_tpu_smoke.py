"""On-chip smoke tests for the Pallas kernel family (VERDICT r1 item 3).

The regular suite pins ``JAX_PLATFORMS=cpu`` (conftest) and exercises these
kernels under the Pallas interpreter; this module is the *hardware* gate —
it runs the same kernels with ``interpret=False`` and is skipped off-TPU.
Run on a chip-attached host with::

    DSORT_TPU_TESTS=1 JAX_COMPILATION_CACHE_DIR=/root/repo/.jax_cache \
        python -m pytest tests/test_tpu_smoke.py --no-header -q

(``DSORT_TPU_TESTS=1`` tells conftest.py to leave the real backend in
charge instead of pinning the simulated CPU mesh).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

on_tpu = pytest.mark.skipif(
    jax.devices()[0].platform != "tpu",
    reason="needs a real TPU (suite pins CPU); see module docstring",
)


@on_tpu
def test_block_sort_on_chip():
    from dsort_tpu.ops.block_sort import block_sort

    rng = np.random.default_rng(0)
    x = rng.integers(-(2**31), 2**31 - 1, (1 << 20) + 17, dtype=np.int64)
    x = x.astype(np.int32)
    out = np.asarray(block_sort(jnp.asarray(x), interpret=False))
    np.testing.assert_array_equal(out, np.sort(x))


@on_tpu
def test_block_sort_orbit_levels_on_chip():
    """Hardware gate for the deep cross levels (r4 final): the K2c orbit
    pass — strided 5-D view + grid-scalar directions — must legalize under
    Mosaic at sizes where it actually runs (>= 64 merge blocks; every
    smaller smoke size never reaches it).  Element-exact against np.sort:
    int32 takes the orbit path, int64 pins the multi-plane per-stage K2
    path at the same depth (the A/B kept wide keys off the orbit); +5
    keeps the pad/trim path honest at these sizes too."""
    from dsort_tpu.ops.block_sort import block_sort

    rng = np.random.default_rng(40)
    x32 = rng.integers(-(2**31), 2**31 - 1, (1 << 23) + 5, dtype=np.int64)
    x32 = x32.astype(np.int32)
    out = np.asarray(block_sort(jnp.asarray(x32), interpret=False))
    np.testing.assert_array_equal(out, np.sort(x32))

    x64 = rng.integers(-(2**62), 2**62, 1 << 23, dtype=np.int64)
    out64 = np.asarray(block_sort(jnp.asarray(x64), interpret=False))
    np.testing.assert_array_equal(out64, np.sort(x64))


@on_tpu
def test_pallas_tile_sort_on_chip():
    from dsort_tpu.ops.pallas_sort import pallas_sort

    rng = np.random.default_rng(1)
    x = rng.integers(-(2**31), 2**31 - 1, 200_000, dtype=np.int64)
    x = x.astype(np.int32)
    out = np.asarray(pallas_sort(jnp.asarray(x), interpret=False))
    np.testing.assert_array_equal(out, np.sort(x))


@on_tpu
def test_pallas_sort_kv_on_chip():
    from dsort_tpu.ops.pallas_sort import pallas_sort_kv

    rng = np.random.default_rng(2)
    k = rng.integers(0, 1000, 50_000).astype(np.int32)
    v = rng.integers(0, 255, (50_000, 8)).astype(np.uint8)
    ok, ov = pallas_sort_kv(jnp.asarray(k), jnp.asarray(v), interpret=False)
    ok, ov = np.asarray(ok), np.asarray(ov)
    order = np.argsort(k, kind="stable")
    np.testing.assert_array_equal(ok, k[order])
    np.testing.assert_array_equal(ov, v[order])


@on_tpu
def test_radix_histogram_on_chip():
    from dsort_tpu.ops.pallas_sort import radix_histogram

    rng = np.random.default_rng(3)
    x = rng.integers(0, 2**31, 300_000).astype(np.int32)
    hist = np.asarray(radix_histogram(jnp.asarray(x), 16, 8, interpret=False))
    expect = np.bincount((x >> 16) & 0xFF, minlength=256)
    np.testing.assert_array_equal(hist, expect)


@on_tpu
def test_block_sort_uint32_float32_on_chip():
    """uint32 exposed a real Mosaic gap (arith.minui does not legalize) that
    interpreter runs cannot catch — keep both non-int32 planes gated here."""
    from dsort_tpu.ops.block_sort import block_sort

    rng = np.random.default_rng(4)
    u = rng.integers(0, 2**32, 200_000, dtype=np.uint64).astype(np.uint32)
    np.testing.assert_array_equal(
        np.asarray(block_sort(jnp.asarray(u), interpret=False)), np.sort(u)
    )
    f = rng.standard_normal(200_000).astype(np.float32)
    np.testing.assert_array_equal(
        np.asarray(block_sort(jnp.asarray(f), interpret=False)), np.sort(f)
    )


@on_tpu
def test_block_sort_int64_on_chip():
    from dsort_tpu.ops.block_sort import block_sort

    rng = np.random.default_rng(5)
    x = rng.integers(-(2**62), 2**62, 300_000, dtype=np.int64)
    np.testing.assert_array_equal(
        np.asarray(block_sort(jnp.asarray(x), interpret=False)), np.sort(x)
    )


@on_tpu
def test_block_sort_pairs_on_chip():
    """The kv-merge plane path (key + rank), incl. the 3-plane int64 config —
    new Mosaic leg combinations only hardware can validate (r2: two real
    legalization gaps were invisible to the interpreter)."""
    from dsort_tpu.ops.block_sort import block_sort_pairs

    rng = np.random.default_rng(6)
    n = 300_000
    for dtype, lo, hi in ((np.int32, -50, 50), (np.uint64, 0, 100)):
        k = rng.integers(lo, hi, n).astype(dtype)  # duplicates: ranks decide
        r = rng.permutation(n).astype(np.int32)
        ok, orr = block_sort_pairs(jnp.asarray(k), jnp.asarray(r), interpret=False)
        order = np.lexsort((r, k))
        np.testing.assert_array_equal(np.asarray(ok), k[order])
        np.testing.assert_array_equal(np.asarray(orr), r[order])


@on_tpu
def test_spmd_sample_sort_end_to_end_on_chip():
    """VERDICT r2 item 4: the flagship SPMD path (shard_map + collectives +
    auto kernel dispatch + merge) on the real device, ~1M int32 — a kernel
    or dispatch regression here must fail a test before it reaches bench."""
    from dsort_tpu.parallel.mesh import local_device_mesh
    from dsort_tpu.parallel.sample_sort import SampleSort

    rng = np.random.default_rng(7)
    data = rng.integers(-(2**31), 2**31 - 1, (1 << 20) + 3, dtype=np.int64)
    data = data.astype(np.int32)
    out = SampleSort(local_device_mesh()).sort(data)
    np.testing.assert_array_equal(out, np.sort(data))


@on_tpu
def test_spmd_sample_sort_float_nan_on_chip():
    """Float keys WITH NaNs through the on-chip SPMD path: the float_order
    bijection must bring every NaN back, sorted last like np.sort."""
    from dsort_tpu.parallel.mesh import local_device_mesh
    from dsort_tpu.parallel.sample_sort import SampleSort
    from dsort_tpu.config import JobConfig

    rng = np.random.default_rng(8)
    data = rng.standard_normal(200_000).astype(np.float32)
    data[rng.integers(0, len(data), 500)] = np.nan
    data[:4] = [np.inf, -np.inf, 0.0, -0.0]
    out = SampleSort(local_device_mesh(), JobConfig(key_dtype=np.float32)).sort(data)
    n_nan = int(np.isnan(data).sum())
    assert np.isnan(out[-n_nan:]).all()
    np.testing.assert_array_equal(out[:-n_nan], np.sort(data)[:-n_nan])


@on_tpu
def test_taskpool_block_kernel_on_chip():
    """VERDICT r2 item 2 follow-through: task-pool mode's executor reaches
    the block kernel on TPU via the auto dispatch (>= 2^16 keys/shard)."""
    from dsort_tpu.scheduler import DeviceExecutor, Scheduler

    rng = np.random.default_rng(9)
    data = rng.integers(-(2**31), 2**31 - 1, 1 << 18, dtype=np.int64)
    data = data.astype(np.int32)
    sched = Scheduler(DeviceExecutor())
    out = sched.run_job(data)
    np.testing.assert_array_equal(out, np.sort(data))


@on_tpu
def test_block_merge_runs_on_chip():
    """Hardware gate for the merge-entry kernels (r4): the span_low kb_start
    parametrization and the odd-row flip must legalize under Mosaic, not
    just under the interpreter."""
    from dsort_tpu.ops.block_sort import block_merge_runs

    rng = np.random.default_rng(41)
    # The SPMD post-shuffle shape: 8 runs of one merge block each.
    runs = np.sort(
        rng.integers(-(2**31), 2**31 - 1, (8, 1 << 17), dtype=np.int64)
        .astype(np.int32),
        axis=1,
    )
    out = np.asarray(block_merge_runs(jnp.asarray(runs), interpret=False))
    np.testing.assert_array_equal(out, np.sort(runs.reshape(-1)))

    # Runs smaller than a block: the _sort_levels(k_start) entry.
    small = np.sort(
        rng.integers(-(2**31), 2**31 - 1, (16, 1 << 13), dtype=np.int64)
        .astype(np.int32),
        axis=1,
    )
    out2 = np.asarray(block_merge_runs(jnp.asarray(small), interpret=False))
    np.testing.assert_array_equal(out2, np.sort(small.reshape(-1)))


@on_tpu
def test_block_merge_runs_kv_on_chip():
    from dsort_tpu.ops.block_sort import block_merge_runs_kv

    rng = np.random.default_rng(43)
    r, l = 8, 1 << 14
    total = r * l
    keys = rng.integers(0, 1000, (r, l)).astype(np.int32)  # heavy ties
    rank = np.arange(total, dtype=np.int32).reshape(r, l)
    order = np.lexsort((rank, keys), axis=1)
    keys = np.take_along_axis(keys, order, axis=1)
    rank = np.take_along_axis(rank, order, axis=1)
    out_k, out_r = block_merge_runs_kv(
        jnp.asarray(keys), jnp.asarray(rank), interpret=False
    )
    flat = np.lexsort((rank.reshape(-1), keys.reshape(-1)))
    np.testing.assert_array_equal(np.asarray(out_k), keys.reshape(-1)[flat])
    np.testing.assert_array_equal(np.asarray(out_r), rank.reshape(-1)[flat])
