"""On-chip smoke tests for the Pallas kernel family (VERDICT r1 item 3).

The regular suite pins ``JAX_PLATFORMS=cpu`` (conftest) and exercises these
kernels under the Pallas interpreter; this module is the *hardware* gate —
it runs the same kernels with ``interpret=False`` and is skipped off-TPU.
Run directly on a chip-attached host with::

    JAX_PLATFORMS='' python -m pytest tests/test_tpu_smoke.py --no-header -q

(an empty JAX_PLATFORMS lets the real backend win over the conftest pin;
drive it via ``python -m pytest`` from an env whose default platform is the
TPU, e.g. the axon tunnel in this dev container).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

on_tpu = pytest.mark.skipif(
    jax.devices()[0].platform != "tpu",
    reason="needs a real TPU (suite pins CPU); see module docstring",
)


@on_tpu
def test_block_sort_on_chip():
    from dsort_tpu.ops.block_sort import block_sort

    rng = np.random.default_rng(0)
    x = rng.integers(-(2**31), 2**31 - 1, (1 << 20) + 17, dtype=np.int64)
    x = x.astype(np.int32)
    out = np.asarray(block_sort(jnp.asarray(x), interpret=False))
    np.testing.assert_array_equal(out, np.sort(x))


@on_tpu
def test_pallas_tile_sort_on_chip():
    from dsort_tpu.ops.pallas_sort import pallas_sort

    rng = np.random.default_rng(1)
    x = rng.integers(-(2**31), 2**31 - 1, 200_000, dtype=np.int64)
    x = x.astype(np.int32)
    out = np.asarray(pallas_sort(jnp.asarray(x), interpret=False))
    np.testing.assert_array_equal(out, np.sort(x))


@on_tpu
def test_pallas_sort_kv_on_chip():
    from dsort_tpu.ops.pallas_sort import pallas_sort_kv

    rng = np.random.default_rng(2)
    k = rng.integers(0, 1000, 50_000).astype(np.int32)
    v = rng.integers(0, 255, (50_000, 8)).astype(np.uint8)
    ok, ov = pallas_sort_kv(jnp.asarray(k), jnp.asarray(v), interpret=False)
    ok, ov = np.asarray(ok), np.asarray(ov)
    order = np.argsort(k, kind="stable")
    np.testing.assert_array_equal(ok, k[order])
    np.testing.assert_array_equal(ov, v[order])


@on_tpu
def test_radix_histogram_on_chip():
    from dsort_tpu.ops.pallas_sort import radix_histogram

    rng = np.random.default_rng(3)
    x = rng.integers(0, 2**31, 300_000).astype(np.int32)
    hist = np.asarray(radix_histogram(jnp.asarray(x), 16, 8, interpret=False))
    expect = np.bincount((x >> 16) & 0xFF, minlength=256)
    np.testing.assert_array_equal(hist, expect)


@on_tpu
def test_block_sort_uint32_float32_on_chip():
    """uint32 exposed a real Mosaic gap (arith.minui does not legalize) that
    interpreter runs cannot catch — keep both non-int32 planes gated here."""
    from dsort_tpu.ops.block_sort import block_sort

    rng = np.random.default_rng(4)
    u = rng.integers(0, 2**32, 200_000, dtype=np.uint64).astype(np.uint32)
    np.testing.assert_array_equal(
        np.asarray(block_sort(jnp.asarray(u), interpret=False)), np.sort(u)
    )
    f = rng.standard_normal(200_000).astype(np.float32)
    np.testing.assert_array_equal(
        np.asarray(block_sort(jnp.asarray(f), interpret=False)), np.sort(f)
    )


@on_tpu
def test_block_sort_int64_on_chip():
    from dsort_tpu.ops.block_sort import block_sort

    rng = np.random.default_rng(5)
    x = rng.integers(-(2**62), 2**62, 300_000, dtype=np.int64)
    np.testing.assert_array_equal(
        np.asarray(block_sort(jnp.asarray(x), interpret=False)), np.sort(x)
    )
