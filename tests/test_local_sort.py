"""L0 kernel tests — oracle is np.sort (SURVEY.md §4's property-test plan)."""

import jax.numpy as jnp
import numpy as np
import pytest

from dsort_tpu.ops.local_sort import (
    sentinel_for,
    sort_keys,
    sort_kv,
    sort_kv_padded,
    sort_padded,
)


@pytest.mark.parametrize("dtype", [np.int32, np.int64, np.uint32, np.float32])
def test_sort_keys_matches_numpy(dtype):
    rng = np.random.default_rng(1)
    if np.issubdtype(dtype, np.floating):
        x = rng.standard_normal(1000).astype(dtype)
    else:
        info = np.iinfo(dtype)
        x = rng.integers(info.min, info.max, 1000, dtype=dtype)
    np.testing.assert_array_equal(np.asarray(sort_keys(jnp.asarray(x))), np.sort(x))


def test_sort_keys_negative_and_minus_one():
    # The reference reserves -1 on its wire (server.c:405-406); we must sort it.
    x = np.array([5, -1, 3, -1, -7], dtype=np.int32)
    np.testing.assert_array_equal(np.asarray(sort_keys(jnp.asarray(x))), np.sort(x))


def test_sort_kv_permutes_payload():
    keys = np.array([3, 1, 2], dtype=np.int32)
    vals = np.array([30, 10, 20], dtype=np.int32)
    k, v = sort_kv(jnp.asarray(keys), jnp.asarray(vals))
    np.testing.assert_array_equal(np.asarray(k), [1, 2, 3])
    np.testing.assert_array_equal(np.asarray(v), [10, 20, 30])


def test_sort_kv_2d_payload():
    keys = np.array([3, 1, 2], dtype=np.int64)
    vals = np.arange(12, dtype=np.uint8).reshape(3, 4)
    k, v = sort_kv(jnp.asarray(keys), jnp.asarray(vals))
    np.testing.assert_array_equal(np.asarray(k), [1, 2, 3])
    np.testing.assert_array_equal(np.asarray(v), vals[[1, 2, 0]])


def test_sort_padded_trims_correctly():
    buf = np.array([5, 2, 9, 777, 888], dtype=np.int32)  # last 2 are garbage
    out, count = sort_padded(jnp.asarray(buf), 3)
    out = np.asarray(out)
    np.testing.assert_array_equal(out[:3], [2, 5, 9])
    assert (out[3:] == sentinel_for(np.int32)).all()
    assert int(count) == 3


def test_sort_padded_keys_equal_to_sentinel():
    # Key-only: real INT32_MAX keys may interleave with pads; count-trim is
    # still an exact multiset (equal keys are indistinguishable).
    m = np.iinfo(np.int32).max
    buf = np.array([m, 1, m, 0, 12345], dtype=np.int32)
    out, count = sort_padded(jnp.asarray(buf), 4)
    np.testing.assert_array_equal(np.asarray(out)[:4], np.sort(buf[:4]))


def test_sort_kv_padded_no_reserved_key():
    # KV: even keys equal to the sentinel keep their payloads ahead of pads —
    # strictly better than the reference's reserved -1 (client.c:113).
    m = np.iinfo(np.int32).max
    keys = np.array([m, 1, 7, 999], dtype=np.int32)  # last is garbage
    vals = np.array([111, 222, 333, 0], dtype=np.int32)
    k, v, count = sort_kv_padded(jnp.asarray(keys), jnp.asarray(vals), 3)
    k, v = np.asarray(k), np.asarray(v)
    np.testing.assert_array_equal(k[:3], [1, 7, m])
    np.testing.assert_array_equal(v[:3], [222, 333, 111])
    assert int(count) == 3


def test_sort_padded_batched():
    rng = np.random.default_rng(2)
    buf = rng.integers(-1000, 1000, (4, 16)).astype(np.int32)
    counts = np.array([16, 0, 5, 10], dtype=np.int32)
    import jax

    out, _ = jax.vmap(sort_padded)(jnp.asarray(buf), jnp.asarray(counts))
    out = np.asarray(out)
    for i, c in enumerate(counts):
        np.testing.assert_array_equal(out[i, :c], np.sort(buf[i, :c]))
        assert (out[i, c:] == sentinel_for(np.int32)).all()


def test_sort_kv_batched_payload():
    # Regression: batched keys + trailing-dim payload must permute per-row
    # (take_along_axis semantics), not fan out globally.
    rng = np.random.default_rng(8)
    keys = rng.integers(0, 100, (2, 5)).astype(np.int32)
    vals = rng.integers(0, 255, (2, 5, 3)).astype(np.uint8)
    k, v = sort_kv(jnp.asarray(keys), jnp.asarray(vals))
    assert np.asarray(v).shape == (2, 5, 3)
    for b in range(2):
        order = np.argsort(keys[b], kind="stable")
        np.testing.assert_array_equal(np.asarray(k)[b], keys[b][order])
        np.testing.assert_array_equal(np.asarray(v)[b], vals[b][order])


def test_sort_kv2_padded_orders_by_secondary():
    from dsort_tpu.ops.local_sort import sort_kv2_padded

    # Primary collides everywhere: secondary must decide; pads trim exactly.
    keys = np.array([5, 5, 5, 5, 999], dtype=np.int32)
    sec = np.array([30, 10, 20, 10, 0], dtype=np.int32)
    vals = np.array([[3], [1], [2], [9], [0]], dtype=np.uint8)
    k, s, v, count = sort_kv2_padded(
        jnp.asarray(keys), jnp.asarray(sec), jnp.asarray(vals), 4
    )
    np.testing.assert_array_equal(np.asarray(k)[:4], [5, 5, 5, 5])
    np.testing.assert_array_equal(np.asarray(s)[:4], [10, 10, 20, 30])
    assert sorted(np.asarray(v)[:4, 0].tolist()) == [1, 2, 3, 9]
    assert set(np.asarray(v)[:2, 0].tolist()) == {1, 9}  # the two sec=10 rows
    assert int(count) == 4


def test_sort_kv2_padded_sentinel_key_real_record_survives():
    from dsort_tpu.ops.local_sort import sort_kv2_padded

    m = np.iinfo(np.int32).max
    keys = np.array([m, m, 1, 777], dtype=np.int32)  # last entry is garbage pad
    sec = np.array([2, 1, 0, 0], dtype=np.int32)
    vals = np.array([20, 10, 5, 0], dtype=np.int32)
    k, s, v, _ = sort_kv2_padded(jnp.asarray(keys), jnp.asarray(sec), jnp.asarray(vals), 3)
    np.testing.assert_array_equal(np.asarray(k)[:3], [1, m, m])
    np.testing.assert_array_equal(np.asarray(s)[:3], [0, 1, 2])
    np.testing.assert_array_equal(np.asarray(v)[:3], [5, 10, 20])


def test_terasort_pack_and_secondary_roundtrip():
    from dsort_tpu.data.ingest import _pack_be64, terasort_secondary

    rows = np.array(
        [[0, 0, 0, 0, 0, 0, 0, 1], [255] * 8, [1, 2, 3, 4, 5, 6, 7, 8]],
        dtype=np.uint8,
    )
    packed = _pack_be64(rows)
    assert packed.dtype == np.uint64 and packed[0] == 1
    assert packed[1] == np.uint64(0xFFFFFFFFFFFFFFFF)
    assert packed[2] == np.uint64(0x0102030405060708)
    payload = np.array([[0xAB, 0xCD, 7], [0, 1, 9]], dtype=np.uint8)
    np.testing.assert_array_equal(terasort_secondary(payload), [0xABCD, 0x0001])
