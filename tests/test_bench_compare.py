"""`bench.py --compare OLD NEW`: the bench-artifact regression differ.

The in-tree BENCH_*.jsonl artifacts are a trajectory; this tool reads it.
Covers the tolerance-ladder classification, added/removed coverage
signals, error-line handling, and the CLI exit-code contract (severe
fails; regression fails only under --strict — session noise must not turn
CI red).
"""

import importlib.util
import json
import os

_BENCH = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "bench.py"
)
_spec = importlib.util.spec_from_file_location("dsort_bench_cmp", _BENCH)
bench = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(bench)


def _write(path, lines):
    with open(path, "w", encoding="utf-8") as f:
        f.write(json.dumps(bench._schema_header()) + "\n")
        for ln in lines:
            f.write(json.dumps(ln) + "\n")
    return str(path)


def _line(metric, value, unit="keys/sec", **extra):
    return {"metric": metric, "value": value, "unit": unit, **extra}


def test_ladder_classification():
    assert bench.classify_ratio(1.2) == "ok"
    assert bench.classify_ratio(0.95) == "ok"
    assert bench.classify_ratio(0.90) == "noise"
    assert bench.classify_ratio(0.60) == "regression"
    assert bench.classify_ratio(0.10) == "severe"


def test_compare_rows(tmp_path):
    old = _write(tmp_path / "old.jsonl", [
        _line("a", 100.0),
        _line("b", 100.0),
        _line("c", 100.0),
        _line("gone", 5.0),
        _line("ratio_line", 1.1, unit="ratio"),
        _line("errored", 0.0, error="boom"),
        {"metric": "summary", "value": 1, "unit": "keys/sec", "lines": {}},
    ])
    new = _write(tmp_path / "new.jsonl", [
        _line("a", 99.0),       # ok
        _line("b", 82.0),       # noise
        _line("c", 30.0),       # severe
        _line("fresh", 1.0),    # added
        _line("ratio_line", 1.2, unit="ratio"),  # info (not a rate)
        _line("errored", 50.0),                  # error side -> class error
        {"metric": "summary", "value": 1, "unit": "keys/sec", "lines": {}},
    ])
    rows = {r["metric"]: r for r in bench.compare_artifacts(old, new)}
    assert "summary" not in rows  # summary/header lines never diff
    assert rows["a"]["class"] == "ok" and rows["a"]["ratio"] == 0.99
    assert rows["b"]["class"] == "noise"
    assert rows["c"]["class"] == "severe" and rows["c"]["ratio"] == 0.3
    assert rows["gone"]["class"] == "removed"
    assert rows["fresh"]["class"] == "added"
    assert rows["ratio_line"]["class"] == "info"
    assert rows["errored"]["class"] == "error"


def test_compare_cli_exit_codes(tmp_path, capsys):
    old = _write(tmp_path / "o.jsonl", [_line("a", 100.0), _line("b", 100.0)])
    ok_new = _write(tmp_path / "n1.jsonl", [_line("a", 96.0), _line("b", 101.0)])
    assert bench._compare_main([old, ok_new]) == 0
    reg_new = _write(tmp_path / "n2.jsonl", [_line("a", 60.0), _line("b", 101.0)])
    # regression: reported, not fatal — unless --strict
    assert bench._compare_main([old, reg_new]) == 0
    assert bench._compare_main([old, reg_new, "--strict"]) == 1
    sev_new = _write(tmp_path / "n3.jsonl", [_line("a", 10.0), _line("b", 101.0)])
    assert bench._compare_main([old, sev_new]) == 1
    out = capsys.readouterr().out
    # the summary line closes each run with the ladder + class counts
    summaries = [
        json.loads(ln) for ln in out.splitlines()
        if '"compare_summary"' in ln
    ]
    assert summaries and summaries[-1]["classes"].get("severe") == 1


def test_compare_cli_usage_errors(tmp_path):
    assert bench._compare_main([]) == 2
    empty = tmp_path / "empty.jsonl"
    empty.write_text("")
    assert bench._compare_main([str(empty), str(empty)]) == 2


def test_in_tree_trajectory_compares(tmp_path):
    """The recorded artifacts really feed the differ: comparing the in-tree
    trajectory yields rows (classes are machine-dependent; the tool must
    parse them, not judge them here)."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    old = os.path.join(repo, "BENCH_r05_preview.jsonl")
    new = os.path.join(repo, "BENCH_r06.jsonl")
    rows = bench.compare_artifacts(old, new)
    assert rows, "in-tree artifacts must produce comparison rows"
    assert any("ratio" in r for r in rows) or any(
        r["class"] in ("added", "removed") for r in rows
    )
    # r06 -> r07 (ISSUE 7): the serving-layer row joins the trajectory as
    # an 'added' metric and the comparison parses end to end.
    r07 = os.path.join(repo, "BENCH_r07.jsonl")
    rows = bench.compare_artifacts(new, r07)
    added = {r["metric"] for r in rows if r["class"] == "added"}
    assert "service_mixed_workload_8dev_cpu_mesh" in added
