"""Event-journal tests: ordering, thread-safety, the two consumers
(Chrome-trace export, `dsort report`), the counter registry, and the
`dsort run --journal` -> `dsort report` round trip on a healthy job.
"""

import json
import os
import re
import threading

import numpy as np
import pytest

from dsort_tpu.utils.events import (
    COUNTERS,
    EVENT_TYPES,
    EventLog,
    format_report,
    to_chrome_trace,
)


def test_emit_orders_and_stamps():
    log = EventLog()
    log.emit("job_start", mode="spmd", n_keys=10)
    log.emit("worker_dead", worker=3)
    log.emit("job_done", n_keys=10)
    evs = log.events()
    assert [e.type for e in evs] == ["job_start", "worker_dead", "job_done"]
    assert [e.seq for e in evs] == [0, 1, 2]
    # monotonic stamps never go backwards; fields ride verbatim
    assert evs[0].mono <= evs[1].mono <= evs[2].mono
    assert evs[1].fields == {"worker": 3}


def test_emit_rejects_unregistered_type():
    with pytest.raises(ValueError, match="unregistered"):
        EventLog().emit("made_up_event")


def test_thread_safety_unique_seqs():
    log = EventLog()
    n_threads, per = 8, 200

    def emitter(w):
        for _ in range(per):
            log.emit("probe", worker=w, ok=True)

    ts = [threading.Thread(target=emitter, args=(w,)) for w in range(n_threads)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    evs = log.events()
    assert len(evs) == n_threads * per
    assert sorted(e.seq for e in evs) == list(range(n_threads * per))
    # every thread's events all landed
    for w in range(n_threads):
        assert sum(e.fields["worker"] == w for e in evs) == per


def test_jsonl_round_trip(tmp_path):
    log = EventLog()
    log.emit("job_start", mode="taskpool", n_keys=4)
    log.emit("reassign", shard=1, frm=0, to=2)
    log.emit("job_done", n_keys=4, counters={"reassignments": 1})
    path = str(tmp_path / "j.jsonl")
    log.write_jsonl(path)
    records = EventLog.read_jsonl(path)
    assert [r["type"] for r in records] == ["job_start", "reassign", "job_done"]
    assert records[1]["frm"] == 0 and records[1]["to"] == 2
    assert records[2]["counters"] == {"reassignments": 1}


def test_flush_jsonl_appends_only_new_events(tmp_path):
    """The per-job REPL persist: each flush writes only the delta, the
    first flush truncates, and the file always equals the full journal."""
    path = str(tmp_path / "session.jsonl")
    log = EventLog()
    log.emit("job_start", mode="spmd", n_keys=1)
    log.flush_jsonl(path)
    log.emit("job_done", n_keys=1)
    log.flush_jsonl(path)
    log.flush_jsonl(path)  # nothing new: no-op, no duplicates
    records = EventLog.read_jsonl(path)
    assert [r["type"] for r in records] == ["job_start", "job_done"]
    assert [r["seq"] for r in records] == [0, 1]
    # a fresh log's first flush truncates a stale session file
    log2 = EventLog()
    log2.emit("job_start", mode="spmd", n_keys=2)
    log2.flush_jsonl(path)
    assert [r["type"] for r in EventLog.read_jsonl(path)] == ["job_start"]


def test_chrome_trace_export():
    log = EventLog()
    log.emit("phase_start", phase="partition")
    log.emit("worker_dead", worker=5)
    log.emit("phase_end", phase="partition", seconds=0.25)
    trace = to_chrome_trace([e.to_dict() for e in log.events()])
    evs = trace["traceEvents"]
    assert [e["ph"] for e in evs] == ["B", "i", "E"]
    assert evs[0]["name"] == "dsort:partition"
    assert evs[1]["args"] == {"worker": 5}
    assert evs[0]["ts"] <= evs[1]["ts"] <= evs[2]["ts"]
    json.dumps(trace)  # must serialize


def test_format_report_tables():
    log = EventLog()
    log.emit("job_start", mode="spmd", n_keys=100)
    log.emit("phase_start", phase="partition")
    log.emit("phase_end", phase="partition", seconds=0.5)
    log.emit("mesh_reform", survivors=7)
    log.emit("job_done", n_keys=100, counters={"mesh_reforms": 1})
    text = format_report([e.to_dict() for e in log.events()])
    assert "job_start" in text and "mesh_reform" in text
    assert "partition" in text and "500.000 ms" in text
    assert "mesh_reforms" in text  # counter table with registry description
    assert COUNTERS["mesh_reforms"] in text


def test_counter_registry_is_exhaustive():
    """Every `Metrics.bump` name in the package is a documented counter —
    the registry (shared by journal, bench, README) cannot drift."""
    root = os.path.join(os.path.dirname(os.path.dirname(__file__)), "dsort_tpu")
    bumped = set()
    for dirpath, _, names in os.walk(root):
        for name in names:
            if not name.endswith(".py"):
                continue
            with open(os.path.join(dirpath, name), encoding="utf-8") as f:
                bumped |= set(re.findall(r"\.bump\(\s*\"([a-z0-9_]+)\"", f.read()))
    assert bumped, "no counters found — did the scan break?"
    unregistered = bumped - set(COUNTERS)
    assert not unregistered, (
        f"counters bumped but not in utils.events.COUNTERS: {unregistered}"
    )


def test_event_registry_covers_issue_schema():
    """The minimum schema the observability spec names must stay registered."""
    required = {
        "attempt_start", "heartbeat_lapse", "probe", "worker_dead",
        "reassign", "mesh_reform", "capacity_retry", "checkpoint_persist",
        "checkpoint_restore", "phase_start", "phase_end", "job_done",
        "job_failed",
    }
    assert required <= set(EVENT_TYPES)


def test_phase_timer_emits_phase_events():
    from dsort_tpu.utils.metrics import Metrics, PhaseTimer

    log = EventLog()
    m = Metrics(journal=log)
    with PhaseTimer(m).phase("merge"):
        pass
    assert log.types() == ["phase_start", "phase_end"]
    end = log.events()[1]
    assert end.fields["phase"] == "merge"
    assert end.fields["seconds"] >= 0


def test_capacity_retry_journaled(mesh8):
    """The capacity-retry fault path lands on the journal: all-equal keys
    overflow one bucket at capacity_factor=1, the retry resizes, and the
    journal shows capacity_retry between attempt phases."""
    from dsort_tpu.config import JobConfig
    from dsort_tpu.parallel.sample_sort import SampleSort
    from dsort_tpu.utils.metrics import Metrics

    data = np.full(40_000, 7, np.int32)
    log = EventLog()
    m = Metrics(journal=log)
    out = SampleSort(mesh8, JobConfig(capacity_factor=1.0)).sort(data, metrics=m)
    np.testing.assert_array_equal(out, data)
    assert m.counters.get("capacity_retries", 0) >= 1
    types = log.types()
    assert "capacity_retry" in types
    ev = [e for e in log.events() if e.type == "capacity_retry"][0]
    assert ev.fields["cap_pair"] > 0 and ev.fields["observed"] > 0


def test_cli_run_journal_report_round_trip(tmp_path, capsys):
    """The acceptance path: `dsort run --journal out.jsonl` on a healthy job,
    then `dsort report out.jsonl` renders the timeline + tables, and
    `--chrome-trace` exports a loadable trace_event file."""
    from dsort_tpu import cli

    inp = tmp_path / "in.txt"
    rng = np.random.default_rng(3)
    inp.write_text("\n".join(str(x) for x in rng.integers(0, 10**6, 3000)))
    out = tmp_path / "out.txt"
    journal = tmp_path / "run.jsonl"
    trace = tmp_path / "trace.json"
    assert cli.main(["run", str(inp), "-o", str(out), "--journal",
                     str(journal)]) == 0
    assert journal.exists()
    records = EventLog.read_jsonl(str(journal))
    types = [r["type"] for r in records]
    assert types[0] == "job_start"
    assert "job_done" in types
    assert "phase_start" in types and "phase_end" in types
    # the sorted output really is sorted (the journal describes a real job)
    got = np.array([int(x) for x in out.read_text().split()])
    assert (np.diff(got) >= 0).all()
    assert cli.main(["report", str(journal), "--chrome-trace",
                     str(trace)]) == 0
    text = capsys.readouterr().out
    assert "timeline:" in text and "job_done" in text and "phases:" in text
    loaded = json.loads(trace.read_text())
    assert loaded["traceEvents"], "chrome trace must carry events"


def test_native_coord_event_line_parser():
    """runtime/native.py parses the C++ coordinator's compact event lines
    into journal-shaped records, skipping malformed lines."""
    from dsort_tpu.runtime.native import parse_coord_events

    text = (
        "t=12.500000 ev=worker_join w=0\n"
        "t=12.600000 ev=attempt_start w=0 task=3\n"
        "garbage line without fields\n"
        "t=12.700000 ev=worker_dead w=0\n"
        "t=12.800000 ev=reassign w=0 task=3\n"
        "t=12.900000 ev=unknown_kind w=1\n"
    )
    recs = parse_coord_events(text)
    assert [r["type"] for r in recs] == [
        "worker_join", "attempt_start", "worker_dead", "reassign",
    ]
    assert recs[1]["task"] == 3 and recs[1]["worker"] == 0
    # parsed records ingest into a journal under registered types
    log = EventLog()
    for r in recs:
        fields = {k: v for k, v in r.items() if k not in ("type", "t", "mono")}
        log.ingest(r["t"], r["mono"], r["type"], **fields)
    assert log.types() == [r["type"] for r in recs]
