"""Out-of-core external sort: correctness, resume, file-to-file path."""

import numpy as np
import pytest

from dsort_tpu.models.external_sort import ExternalSort
from dsort_tpu.utils.metrics import Metrics


@pytest.mark.parametrize("n,run", [(0, 64), (1, 64), (100, 64), (1000, 128), (4096, 512)])
def test_external_matches_oracle(tmp_path, n, run):
    rng = np.random.default_rng(n)
    data = rng.integers(-(2**31), 2**31 - 1, n, dtype=np.int64).astype(np.int32)
    s = ExternalSort(run_elems=run, spill_dir=str(tmp_path), job_id=f"t{n}")
    np.testing.assert_array_equal(s.sort(data), np.sort(data))


def test_external_partial_run_with_sentinel_keys(tmp_path):
    # Final partial run trim must not drop real max-valued keys.
    sent = np.iinfo(np.int32).max
    data = np.array([5, sent, 1, sent, 3, 2, 7, sent, 0], dtype=np.int32)
    s = ExternalSort(run_elems=4, spill_dir=str(tmp_path), job_id="sent")
    np.testing.assert_array_equal(s.sort(data), np.sort(data))


def test_external_resume_skips_finished_runs(tmp_path):
    rng = np.random.default_rng(7)
    data = rng.integers(-1000, 1000, 1000).astype(np.int32)
    s1 = ExternalSort(run_elems=100, spill_dir=str(tmp_path), job_id="resume")
    m1 = Metrics()
    np.testing.assert_array_equal(s1.sort(data, metrics=m1), np.sort(data))
    assert m1.counters["runs_sorted"] == 10
    # Second pass over the same job id re-sorts nothing.
    s2 = ExternalSort(run_elems=100, spill_dir=str(tmp_path), job_id="resume")
    m2 = Metrics()
    np.testing.assert_array_equal(s2.sort(data, metrics=m2), np.sort(data))
    assert m2.counters.get("runs_sorted", 0) == 0
    assert m2.counters["runs_resumed"] == 10
    # resume=False clears and redoes the work.
    s3 = ExternalSort(
        run_elems=100, spill_dir=str(tmp_path), job_id="resume", resume=False
    )
    m3 = Metrics()
    np.testing.assert_array_equal(s3.sort(data, metrics=m3), np.sort(data))
    assert m3.counters["runs_sorted"] == 10


def test_external_partial_resume_after_simulated_crash(tmp_path):
    # Kill the job after 3 runs; the retry sorts only the remaining 7
    # (SURVEY.md §5.4: strictly better than the reference's restart-the-chunk).
    rng = np.random.default_rng(8)
    data = rng.integers(-1000, 1000, 700).astype(np.int32)
    s = ExternalSort(run_elems=100, spill_dir=str(tmp_path), job_id="crash")

    calls = {"n": 0}
    orig = s._sort_run

    def dying(chunk):
        if calls["n"] == 3:
            raise RuntimeError("injected crash")
        calls["n"] += 1
        return orig(chunk)

    s._sort_run = dying
    with pytest.raises(RuntimeError, match="injected crash"):
        s.sort(data)
    s._sort_run = orig
    m = Metrics()
    np.testing.assert_array_equal(s.sort(data, metrics=m), np.sort(data))
    assert m.counters["runs_resumed"] == 3
    assert m.counters["runs_sorted"] == 4


def test_external_binary_file_roundtrip(tmp_path):
    rng = np.random.default_rng(9)
    data = rng.integers(-(2**31), 2**31 - 1, 5000, dtype=np.int64).astype(np.int32)
    in_path = str(tmp_path / "in.bin")
    out_path = str(tmp_path / "out.bin")
    data.tofile(in_path)
    s = ExternalSort(run_elems=1024, spill_dir=str(tmp_path / "spill"), job_id="file")
    m = Metrics()
    s.sort_binary_file(in_path, out_path, dtype=np.int32, metrics=m)
    out = np.fromfile(out_path, dtype=np.int32)
    np.testing.assert_array_equal(out, np.sort(data))


def test_external_output_into_memmap(tmp_path):
    rng = np.random.default_rng(10)
    data = rng.integers(0, 10**6, 2000).astype(np.uint32)
    out_path = str(tmp_path / "out.raw")
    out = np.memmap(out_path, dtype=np.uint32, mode="w+", shape=(2000,))
    s = ExternalSort(run_elems=256, spill_dir=str(tmp_path / "spill"), job_id="mm")
    res = s.sort(data, out=out)
    assert res is out
    out.flush()
    np.testing.assert_array_equal(
        np.fromfile(out_path, dtype=np.uint32), np.sort(data)
    )


def test_cli_external_subcommand(tmp_path):
    from dsort_tpu.cli import main as cli_main

    rng = np.random.default_rng(11)
    data = rng.integers(-(2**31), 2**31 - 1, 3000, dtype=np.int64).astype(np.int32)
    in_path, out_path = str(tmp_path / "in.bin"), str(tmp_path / "out.bin")
    data.tofile(in_path)
    rc = cli_main([
        "external", in_path, "-o", out_path,
        "--run-elems", "512", "--spill-dir", str(tmp_path / "spill"),
    ])
    assert rc == 0
    np.testing.assert_array_equal(np.fromfile(out_path, dtype=np.int32), np.sort(data))


def test_external_reused_job_id_detects_different_data(tmp_path):
    # A reused job_id with different data must NOT return the old output
    # (the manifest fingerprint invalidates stale runs).
    rng = np.random.default_rng(12)
    a = rng.integers(-1000, 1000, 500).astype(np.int32)
    b = rng.integers(-1000, 1000, 500).astype(np.int32)
    s = ExternalSort(run_elems=100, spill_dir=str(tmp_path), job_id="same")
    np.testing.assert_array_equal(s.sort(a), np.sort(a))
    np.testing.assert_array_equal(s.sort(b), np.sort(b))
    # Different run_elems over the same data is also detected.
    s2 = ExternalSort(run_elems=250, spill_dir=str(tmp_path), job_id="same")
    np.testing.assert_array_equal(s2.sort(b), np.sort(b))


def test_external_single_run_result_is_owned(tmp_path):
    data = np.array([3, 1, 2], dtype=np.int32)
    s = ExternalSort(run_elems=100, spill_dir=str(tmp_path), job_id="own")
    out = s.sort(data)
    assert out.flags.writeable
    out[0] = 7  # must not raise or corrupt checkpoint state


def test_external_empty_binary_file(tmp_path):
    in_path, out_path = str(tmp_path / "e.bin"), str(tmp_path / "e.out")
    open(in_path, "wb").close()
    s = ExternalSort(run_elems=64, spill_dir=str(tmp_path / "spill"), job_id="e")
    s.sort_binary_file(in_path, out_path, dtype=np.int32)
    assert np.fromfile(out_path, dtype=np.int32).size == 0


def test_native_merge_rejects_readonly_out(tmp_path):
    from dsort_tpu.runtime import native

    if not native.available():
        pytest.skip("native library unavailable")
    runs = [np.array([1, 3], dtype=np.int32), np.array([2, 4], dtype=np.int32)]
    ro = np.zeros(4, dtype=np.int32)
    ro.setflags(write=False)
    with pytest.raises(ValueError, match="writable"):
        native.kway_merge(runs, out=ro)
