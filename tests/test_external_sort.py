"""Out-of-core external sort: correctness, resume, file-to-file path."""

import os

import numpy as np
import pytest

from dsort_tpu.models.external_sort import ExternalSort
from dsort_tpu.utils.metrics import Metrics


@pytest.mark.parametrize("n,run", [(0, 64), (1, 64), (100, 64), (1000, 128), (4096, 512)])
def test_external_matches_oracle(tmp_path, n, run):
    rng = np.random.default_rng(n)
    data = rng.integers(-(2**31), 2**31 - 1, n, dtype=np.int64).astype(np.int32)
    s = ExternalSort(run_elems=run, spill_dir=str(tmp_path), job_id=f"t{n}")
    np.testing.assert_array_equal(s.sort(data), np.sort(data))


def test_external_partial_run_with_sentinel_keys(tmp_path):
    # Final partial run trim must not drop real max-valued keys.
    sent = np.iinfo(np.int32).max
    data = np.array([5, sent, 1, sent, 3, 2, 7, sent, 0], dtype=np.int32)
    s = ExternalSort(run_elems=4, spill_dir=str(tmp_path), job_id="sent")
    np.testing.assert_array_equal(s.sort(data), np.sort(data))


def test_external_resume_skips_finished_runs(tmp_path):
    rng = np.random.default_rng(7)
    data = rng.integers(-1000, 1000, 1000).astype(np.int32)
    s1 = ExternalSort(run_elems=100, spill_dir=str(tmp_path), job_id="resume")
    m1 = Metrics()
    np.testing.assert_array_equal(s1.sort(data, metrics=m1), np.sort(data))
    assert m1.counters["runs_sorted"] == 10
    # Second pass over the same job id re-sorts nothing.
    s2 = ExternalSort(run_elems=100, spill_dir=str(tmp_path), job_id="resume")
    m2 = Metrics()
    np.testing.assert_array_equal(s2.sort(data, metrics=m2), np.sort(data))
    assert m2.counters.get("runs_sorted", 0) == 0
    assert m2.counters["runs_resumed"] == 10
    # resume=False clears and redoes the work.
    s3 = ExternalSort(
        run_elems=100, spill_dir=str(tmp_path), job_id="resume", resume=False
    )
    m3 = Metrics()
    np.testing.assert_array_equal(s3.sort(data, metrics=m3), np.sort(data))
    assert m3.counters["runs_sorted"] == 10


def test_external_partial_resume_after_simulated_crash(tmp_path):
    # Kill the job at the 4th device submit; the retry sorts only what was
    # lost (SURVEY.md §5.4: strictly better than the reference's
    # restart-the-chunk).  The pipeline keeps ONE run in flight (its D2H
    # overlaps the next run's device work), so a crash at submit k loses
    # the in-flight run k-1 too: submits 0..2 completed => runs 0..1 are
    # safely on disk, runs 2..6 re-sort on resume.
    rng = np.random.default_rng(8)
    data = rng.integers(-1000, 1000, 700).astype(np.int32)
    s = ExternalSort(run_elems=100, spill_dir=str(tmp_path), job_id="crash")

    calls = {"n": 0}
    orig = s._submit_run

    def dying(chunk):
        if calls["n"] == 3:
            raise RuntimeError("injected crash")
        calls["n"] += 1
        return orig(chunk)

    s._submit_run = dying
    with pytest.raises(RuntimeError, match="injected crash"):
        s.sort(data)
    s._submit_run = orig
    m = Metrics()
    np.testing.assert_array_equal(s.sort(data, metrics=m), np.sort(data))
    assert m.counters["runs_resumed"] == 2
    assert m.counters["runs_sorted"] == 5


def test_external_binary_file_roundtrip(tmp_path):
    rng = np.random.default_rng(9)
    data = rng.integers(-(2**31), 2**31 - 1, 5000, dtype=np.int64).astype(np.int32)
    in_path = str(tmp_path / "in.bin")
    out_path = str(tmp_path / "out.bin")
    data.tofile(in_path)
    s = ExternalSort(run_elems=1024, spill_dir=str(tmp_path / "spill"), job_id="file")
    m = Metrics()
    s.sort_binary_file(in_path, out_path, dtype=np.int32, metrics=m)
    out = np.fromfile(out_path, dtype=np.int32)
    np.testing.assert_array_equal(out, np.sort(data))


def test_external_output_into_memmap(tmp_path):
    rng = np.random.default_rng(10)
    data = rng.integers(0, 10**6, 2000).astype(np.uint32)
    out_path = str(tmp_path / "out.raw")
    out = np.memmap(out_path, dtype=np.uint32, mode="w+", shape=(2000,))
    s = ExternalSort(run_elems=256, spill_dir=str(tmp_path / "spill"), job_id="mm")
    res = s.sort(data, out=out)
    assert res is out
    out.flush()
    np.testing.assert_array_equal(
        np.fromfile(out_path, dtype=np.uint32), np.sort(data)
    )


def test_cli_external_subcommand(tmp_path):
    from dsort_tpu.cli import main as cli_main

    rng = np.random.default_rng(11)
    data = rng.integers(-(2**31), 2**31 - 1, 3000, dtype=np.int64).astype(np.int32)
    in_path, out_path = str(tmp_path / "in.bin"), str(tmp_path / "out.bin")
    data.tofile(in_path)
    rc = cli_main([
        "external", in_path, "-o", out_path,
        "--run-elems", "512", "--spill-dir", str(tmp_path / "spill"),
    ])
    assert rc == 0
    np.testing.assert_array_equal(np.fromfile(out_path, dtype=np.int32), np.sort(data))


def test_external_reused_job_id_detects_different_data(tmp_path):
    # A reused job_id with different data must NOT return the old output
    # (the manifest fingerprint invalidates stale runs).
    rng = np.random.default_rng(12)
    a = rng.integers(-1000, 1000, 500).astype(np.int32)
    b = rng.integers(-1000, 1000, 500).astype(np.int32)
    s = ExternalSort(run_elems=100, spill_dir=str(tmp_path), job_id="same")
    np.testing.assert_array_equal(s.sort(a), np.sort(a))
    np.testing.assert_array_equal(s.sort(b), np.sort(b))
    # Different run_elems over the same data is also detected.
    s2 = ExternalSort(run_elems=250, spill_dir=str(tmp_path), job_id="same")
    np.testing.assert_array_equal(s2.sort(b), np.sort(b))


def test_external_single_run_result_is_owned(tmp_path):
    data = np.array([3, 1, 2], dtype=np.int32)
    s = ExternalSort(run_elems=100, spill_dir=str(tmp_path), job_id="own")
    out = s.sort(data)
    assert out.flags.writeable
    out[0] = 7  # must not raise or corrupt checkpoint state


def test_external_empty_binary_file(tmp_path):
    in_path, out_path = str(tmp_path / "e.bin"), str(tmp_path / "e.out")
    open(in_path, "wb").close()
    s = ExternalSort(run_elems=64, spill_dir=str(tmp_path / "spill"), job_id="e")
    s.sort_binary_file(in_path, out_path, dtype=np.int32)
    assert np.fromfile(out_path, dtype=np.int32).size == 0


def test_native_merge_rejects_readonly_out(tmp_path):
    from dsort_tpu.runtime import native

    if not native.available():
        pytest.skip("native library unavailable")
    runs = [np.array([1, 3], dtype=np.int32), np.array([2, 4], dtype=np.int32)]
    ro = np.zeros(4, dtype=np.int32)
    ro.setflags(write=False)
    with pytest.raises(ValueError, match="writable"):
        native.kway_merge(runs, out=ro)


def _tera_oracle(path):
    """Full 10-byte-key record order via np.lexsort (the external oracle)."""
    from dsort_tpu.data.ingest import _pack_be64

    raw = np.fromfile(path, dtype=np.uint8).reshape(-1, 100)
    k1 = _pack_be64(raw[:, :8])
    k2 = (raw[:, 8].astype(np.uint16) << np.uint16(8)) | raw[:, 9]
    return raw[np.lexsort((k2, k1))]


def test_external_terasort_multirun(tmp_path):
    from dsort_tpu.data.ingest import gen_terasort_file
    from dsort_tpu.models.external_sort import ExternalTeraSort

    in_path, out_path = str(tmp_path / "t.bin"), str(tmp_path / "t_sorted.bin")
    gen_terasort_file(in_path, 3000, seed=11)
    s = ExternalTeraSort(run_recs=512, spill_dir=str(tmp_path / "spill"), job_id="t1")
    m = Metrics()
    s.sort_file(in_path, out_path, metrics=m)
    got = np.fromfile(out_path, dtype=np.uint8).reshape(-1, 100)
    np.testing.assert_array_equal(got, _tera_oracle(in_path))
    assert m.counters["runs_sorted"] == 6


def test_external_terasort_prefix_collisions(tmp_path):
    """Records with equal 8-byte prefixes must order by key bytes 8-9."""
    from dsort_tpu.models.external_sort import ExternalTeraSort

    rng = np.random.default_rng(4)
    raw = rng.integers(0, 256, (1000, 100)).astype(np.uint8)
    raw[:, :8] = 7  # every primary collides
    in_path, out_path = str(tmp_path / "c.bin"), str(tmp_path / "c_sorted.bin")
    raw.tofile(in_path)
    s = ExternalTeraSort(run_recs=256, spill_dir=str(tmp_path / "spill"), job_id="t2")
    s.sort_file(in_path, out_path)
    got = np.fromfile(out_path, dtype=np.uint8).reshape(-1, 100)
    np.testing.assert_array_equal(got[:, :10], _tera_oracle(in_path)[:, :10])


def test_external_terasort_resume(tmp_path):
    from dsort_tpu.data.ingest import gen_terasort_file
    from dsort_tpu.models.external_sort import ExternalTeraSort

    in_path, out_path = str(tmp_path / "r.bin"), str(tmp_path / "r_sorted.bin")
    gen_terasort_file(in_path, 2000, seed=5)
    kw = dict(run_recs=512, spill_dir=str(tmp_path / "spill"), job_id="t3")
    ExternalTeraSort(**kw).sort_file(in_path, out_path)
    m = Metrics()
    ExternalTeraSort(**kw).sort_file(in_path, out_path, metrics=m)
    assert m.counters.get("runs_resumed") == 4 and "runs_sorted" not in m.counters
    got = np.fromfile(out_path, dtype=np.uint8).reshape(-1, 100)
    np.testing.assert_array_equal(got, _tera_oracle(in_path))


def test_external_terasort_python_fallback_merge(tmp_path, monkeypatch):
    from dsort_tpu.data.ingest import gen_terasort_file
    from dsort_tpu.models.external_sort import ExternalTeraSort
    from dsort_tpu.runtime import native

    monkeypatch.setattr(native, "available", lambda: False)
    in_path, out_path = str(tmp_path / "f.bin"), str(tmp_path / "f_sorted.bin")
    gen_terasort_file(in_path, 1500, seed=6)
    s = ExternalTeraSort(run_recs=400, spill_dir=str(tmp_path / "spill"), job_id="t4")
    s.sort_file(in_path, out_path)
    got = np.fromfile(out_path, dtype=np.uint8).reshape(-1, 100)
    np.testing.assert_array_equal(got, _tera_oracle(in_path))


def test_external_terasort_empty_and_partial(tmp_path):
    from dsort_tpu.data.ingest import gen_terasort_file
    from dsort_tpu.models.external_sort import ExternalTeraSort

    empty, out_e = str(tmp_path / "e.bin"), str(tmp_path / "e_sorted.bin")
    open(empty, "wb").close()
    s = ExternalTeraSort(run_recs=64, spill_dir=str(tmp_path / "spill"), job_id="t5")
    s.sort_file(empty, out_e)
    assert os.path.getsize(out_e) == 0
    # single partial run (n < run_recs)
    one, out_o = str(tmp_path / "o.bin"), str(tmp_path / "o_sorted.bin")
    gen_terasort_file(one, 33, seed=7)
    s2 = ExternalTeraSort(run_recs=64, spill_dir=str(tmp_path / "spill2"), job_id="t6")
    s2.sort_file(one, out_o)
    got = np.fromfile(out_o, dtype=np.uint8).reshape(-1, 100)
    np.testing.assert_array_equal(got, _tera_oracle(one))


def test_cli_terasort_external_validates(tmp_path):
    import subprocess
    import sys

    in_path = str(tmp_path / "cli.bin")
    out_path = str(tmp_path / "cli_sorted.bin")
    env = {**os.environ, "JAX_PLATFORMS": "cpu", "PALLAS_AXON_POOL_IPS": ""}
    run = lambda *a: subprocess.run(
        [sys.executable, "-m", "dsort_tpu.cli", *a],
        env=env, capture_output=True, text=True, timeout=240,
    )
    assert run("gen", "600", "-o", in_path, "--dist", "terasort").returncode == 0
    r = run("terasort", in_path, "-o", out_path, "--external", "--run-recs", "256",
            "--spill-dir", str(tmp_path / "spill"))
    assert r.returncode == 0, r.stderr
    v = run("validate", out_path, "--against", in_path, "--terasort")
    assert v.returncode == 0, v.stdout + v.stderr
