"""Device-resident result path (the no-relay pipeline contract).

`DeviceSortResult` keeps the sorted global array sharded on the mesh:
``.to_host()`` is the only D2H, ``.consume(fn)`` chains a jitted next stage
with buffer donation, and ``.validate_on_device()`` runs `dsort validate`
semantics (order + FNV-1a multiset checksum, matching `models.validate`'s
host results bit-for-bit) as jitted shard_map reductions.  The scheduler
drill pins the fault contract: a mesh re-form invalidates outstanding
handles and they transparently re-run on the surviving mesh.
"""

import json

import numpy as np
import pytest

from dsort_tpu.config import JobConfig
from dsort_tpu.data.ingest import gen_uniform, gen_zipf
from dsort_tpu.models.validate import _multiset
from dsort_tpu.parallel.device_result import DeviceSortResult
from dsort_tpu.parallel.sample_sort import SampleSort
from dsort_tpu.utils.events import EventLog
from dsort_tpu.utils.metrics import Metrics


def _host_sum(a: np.ndarray) -> int:
    return _multiset(a, len(a), a.dtype.itemsize)


def test_device_result_round_trip(mesh8):
    """The acceptance round trip: validate ok on device, checksum equals the
    host `_multiset` of the same data, and to_host equals np.sort."""
    data = gen_uniform(120_000, seed=3)
    m = Metrics(journal=EventLog())
    h = SampleSort(mesh8).sort(data, metrics=m, keep_on_device=True)
    assert h.valid and len(h) == len(data) and h.num_shards == 8
    rep = h.validate_on_device()
    assert rep.sorted_ok and rep.records == len(data)
    assert rep.checksum == _host_sum(data)  # permutation proof, no relay
    np.testing.assert_array_equal(h.to_host(), np.sort(data))
    assert m.counters["device_handles"] == 1
    assert m.counters["device_validates"] == 1
    types = m.journal.types()
    assert "device_handle" in types and "device_validate" in types
    # offsets metadata recovers the exact global layout
    assert h.offsets[-1] == len(data)
    assert (np.diff(h.offsets) == h.shard_lengths).all()


@pytest.mark.parametrize("dtype", [np.int64, np.uint32])
def test_device_result_dtypes(mesh8, dtype):
    rng = np.random.default_rng(11)
    info = np.iinfo(dtype)
    data = rng.integers(info.min, info.max, 30_000).astype(dtype)
    h = SampleSort(mesh8, JobConfig(key_dtype=dtype)).sort(
        data, keep_on_device=True
    )
    rep = h.validate_on_device()
    assert rep.sorted_ok and rep.checksum == _host_sum(data)
    np.testing.assert_array_equal(h.to_host(), np.sort(data))


def test_device_result_sentinel_keys_and_duplicates(mesh8):
    """Real sentinel-valued keys (dtype max) and heavy duplicates must pass
    on-device validation — pads are excluded by count, not by value."""
    sent = np.iinfo(np.int32).max
    rng = np.random.default_rng(13)
    data = rng.integers(-50, 50, 40_000).astype(np.int32)
    data[::91] = sent
    h = SampleSort(mesh8).sort(data, keep_on_device=True)
    rep = h.validate_on_device()
    assert rep.sorted_ok and rep.records == len(data)
    assert rep.checksum == _host_sum(data)


def test_device_result_skew_capacity_retry(mesh8):
    """A capacity retry mid-dispatch still yields a valid handle."""
    data = np.concatenate(
        [np.full(30_000, 9, np.int32), gen_uniform(8_000, seed=5)]
    )
    m = Metrics()
    h = SampleSort(mesh8, JobConfig(capacity_factor=1.0)).sort(
        data, metrics=m, keep_on_device=True
    )
    assert m.counters.get("capacity_retries", 0) >= 1
    assert h.validate_on_device().sorted_ok
    np.testing.assert_array_equal(h.to_host(), np.sort(data))


def test_device_validate_detects_unsorted_rows():
    """An in-row order break is caught by the plain-jit validator."""
    import jax.numpy as jnp

    rows = np.array([[3, 1, 2, 7], [8, 9, 10, 11]], np.int32)
    h = DeviceSortResult(
        jnp.asarray(rows.reshape(-1)),
        shard_lengths=np.array([4, 4]), n=8,
    )
    rep = h.validate_on_device()
    assert not rep.sorted_ok
    assert rep.records == 8


def test_device_validate_detects_boundary_violation(mesh8):
    """A cross-shard boundary break is caught by the shard_map validator:
    shard 0's keys exceed shard 1's (each shard locally sorted)."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    rows = np.stack(
        [np.arange(100, 116, dtype=np.int32) + 16 * ((7 - i) % 8)
         for i in range(8)]
    )  # every row sorted, rows in DESCENDING key ranges
    arr = jax.device_put(
        rows.reshape(-1), NamedSharding(mesh8, P("w"))
    )
    h = DeviceSortResult(
        arr, shard_lengths=np.full(8, 16), n=128, mesh=mesh8, axis="w",
    )
    rep = h.validate_on_device()
    assert not rep.sorted_ok
    # the multiset checksum is order-independent and still exact
    assert rep.checksum == _host_sum(rows.reshape(-1))


def test_device_validate_corruption_changes_checksum(mesh8):
    """Flipping one key's value flips the checksum — the permutation proof
    has teeth."""
    data = gen_uniform(20_000, seed=7)
    h = SampleSort(mesh8).sort(data, keep_on_device=True)
    rep = h.validate_on_device()
    corrupted = data.copy()
    corrupted[123] ^= 1
    assert rep.checksum == _host_sum(data)
    assert rep.checksum != _host_sum(corrupted)


def test_device_result_consume_chains_jitted_stage(mesh8):
    """consume() runs a jitted next stage over the device buffer (donated)
    and marks the handle consumed — later reads refuse loudly."""
    data = gen_uniform(50_000, seed=17)
    m = Metrics(journal=EventLog())
    h = SampleSort(mesh8).sort(data, metrics=m, keep_on_device=True)
    lengths = h.shard_lengths.copy()
    out = h.consume(lambda x: x ^ 1)
    # the stage saw the sorted padded layout: valid prefix of each shard is
    # np.sort(data)'s interval, xor'd
    got = np.asarray(out)
    cap = got.size // 8
    expect = np.sort(data) ^ 1
    off = 0
    for i in range(8):
        ci = int(lengths[i])
        np.testing.assert_array_equal(
            got[i * cap : i * cap + ci], expect[off : off + ci]
        )
        off += ci
    assert m.counters["device_consumes"] == 1
    assert not h.valid
    with pytest.raises(RuntimeError, match="consumed"):
        h.to_host()
    with pytest.raises(RuntimeError, match="consumed"):
        h.validate_on_device()


def test_device_result_consume_without_donation_keeps_handle(mesh8):
    data = gen_uniform(9_000, seed=19)
    h = SampleSort(mesh8).sort(data, keep_on_device=True)
    h.consume(lambda x: x + 0, donate=False)
    assert h.valid
    np.testing.assert_array_equal(h.to_host(), np.sort(data))


def test_device_result_empty_and_float_refusal(mesh8):
    h = SampleSort(mesh8).sort(np.empty(0, np.int32), keep_on_device=True)
    assert len(h) == 0
    rep = h.validate_on_device()
    assert rep.sorted_ok and rep.records == 0 and rep.checksum == 0
    assert h.to_host().size == 0
    with pytest.raises(TypeError, match="integer keys"):
        SampleSort(mesh8).sort(
            np.zeros(10, np.float32), keep_on_device=True
        )


def test_fused_sort_small_keep_on_device():
    """The single-chip fused path: one H2D + async execute, no fetch; the
    handle validates and assembles lazily."""
    from dsort_tpu.models.pipelines import fused_sort_small

    rng = np.random.default_rng(23)
    data = rng.integers(-(2**31), 2**31 - 1, 10_000).astype(np.int32)
    m = Metrics()
    h = fused_sort_small(data, metrics=m, keep_on_device=True)
    assert h.num_shards == 1 and len(h) == len(data)
    rep = h.validate_on_device()
    assert rep.sorted_ok and rep.checksum == _host_sum(data)
    np.testing.assert_array_equal(h.to_host(), np.sort(data))
    assert m.counters["device_handles"] == 1
    with pytest.raises(TypeError, match="integer keys"):
        fused_sort_small(np.zeros(4, np.float64), keep_on_device=True)


def test_batch_sample_sort_keep_on_device(devices):
    from dsort_tpu.config import MeshConfig
    from dsort_tpu.parallel.mesh import make_mesh
    from dsort_tpu.parallel.sample_sort import BatchSampleSort

    mesh = make_mesh(MeshConfig(num_workers=4, dp=2), devices[:8])
    rng = np.random.default_rng(29)
    jobs = [
        rng.integers(-(10**6), 10**6, n).astype(np.int32)
        for n in (5000, 1, 0, 4096, 777)
    ]
    m = Metrics()
    handles = BatchSampleSort(mesh).sort(jobs, metrics=m, keep_on_device=True)
    assert len(handles) == len(jobs)
    for j, h in zip(jobs, handles):
        np.testing.assert_array_equal(h.to_host(), np.sort(j))
        rep = h.validate_on_device()
        assert rep.sorted_ok and rep.records == len(j)
        if len(j):
            assert rep.checksum == _host_sum(j)
    assert m.counters["device_handles"] == len(jobs)


def test_spmd_scheduler_device_resident_fault_drill(mesh8):
    """The acceptance fault drill: a mesh re-form invalidates an issued
    handle, and the handle transparently re-runs on the surviving mesh."""
    from dsort_tpu.scheduler import FaultInjector, SpmdScheduler

    inj = FaultInjector()
    sched = SpmdScheduler(
        job=JobConfig(settle_delay_s=0.01), injector=inj
    )
    data = gen_uniform(60_000, seed=31)
    m = Metrics(journal=EventLog())
    h = sched.sort(data, metrics=m, keep_on_device=True)
    assert h.valid
    # a later job loses a device -> the mesh re-forms -> the handle's
    # buffers (partly on the reaped device) are invalidated
    inj.fail_once(2, "spmd")
    sched.sort(gen_uniform(8_000, seed=32), metrics=m)
    assert m.counters["mesh_reforms"] == 1
    assert not h.valid
    # next use re-runs on the 7-survivor mesh and heals the handle
    np.testing.assert_array_equal(h.to_host(), np.sort(data))
    assert h.valid
    assert m.counters["device_handle_reruns"] == 1
    rep = h.validate_on_device()
    assert rep.sorted_ok and rep.checksum == _host_sum(data)
    types = m.journal.types()
    assert "device_handle_invalidated" in types
    assert types.index("mesh_reform") < types.index(
        "device_handle_invalidated"
    )


def test_spmd_scheduler_device_resident_survives_injected_failure(mesh8):
    """A device lost DURING the device-resident sort itself: the scheduler
    re-forms and the returned handle is already the re-run's."""
    from dsort_tpu.scheduler import FaultInjector, SpmdScheduler

    inj = FaultInjector()
    sched = SpmdScheduler(job=JobConfig(settle_delay_s=0.01), injector=inj)
    data = gen_zipf(50_000, a=1.2, seed=33)
    inj.fail_once(3, "spmd")
    m = Metrics()
    h = sched.sort(data, metrics=m, keep_on_device=True)
    assert m.counters["mesh_reforms"] == 1
    assert h.validate_on_device().sorted_ok
    np.testing.assert_array_equal(h.to_host(), np.sort(data))


def test_spmd_scheduler_device_resident_skips_checkpoint(tmp_path):
    """keep_on_device + checkpoint config: the job runs (no range persist)
    and warns instead of mixing handles with persisted ranges."""
    from dsort_tpu.scheduler import SpmdScheduler

    sched = SpmdScheduler(
        job=JobConfig(settle_delay_s=0.01, checkpoint_dir=str(tmp_path))
    )
    data = gen_uniform(9_000, seed=35)
    h = sched.sort(data, metrics=Metrics(), job_id="dev", keep_on_device=True)
    np.testing.assert_array_equal(h.to_host(), np.sort(data))
    assert not list(tmp_path.iterdir())  # nothing persisted


def test_spmd_scheduler_device_resident_float_refusal():
    from dsort_tpu.scheduler import SpmdScheduler

    with pytest.raises(TypeError, match="integer keys"):
        SpmdScheduler(job=JobConfig()).sort(
            np.zeros(8, np.float32), keep_on_device=True
        )


# ---- the `make bench-smoke` tier-1 gate -----------------------------------


def test_cli_bench_smoke_device_resident(tmp_path, capsys):
    """The bench-smoke path (`dsort bench --device-resident --journal`):
    emits the sort_e2e_device_resident_* and validate lines, exits 0, and
    journals the device-handle/validate events."""
    from dsort_tpu import cli

    journal = tmp_path / "smoke.jsonl"
    rc = cli.main([
        "bench", "--device-resident", "--n", "50000", "--reps", "1",
        "--journal", str(journal),
    ])
    assert rc == 0
    out_lines = [
        json.loads(ln) for ln in capsys.readouterr().out.splitlines() if ln
    ]
    metrics = {ln["metric"]: ln for ln in out_lines}
    e2e = [m for m in metrics if m.startswith("sort_e2e_device_resident_")]
    val = [m for m in metrics if m.startswith("device_validate_")]
    assert e2e and val
    assert metrics[e2e[0]]["value"] > 0
    assert metrics[val[0]]["validated_ok"] is True
    types = [r["type"] for r in EventLog.read_jsonl(str(journal))]
    assert "device_handle" in types and "device_validate" in types


def test_cli_run_device_resident(tmp_path):
    """`dsort run --device-resident` writes the sorted file and validates on
    device (exit 0)."""
    from dsort_tpu import cli

    rng = np.random.default_rng(37)
    inp = tmp_path / "in.txt"
    inp.write_text("\n".join(str(x) for x in rng.integers(0, 10**6, 4000)))
    out = tmp_path / "out.txt"
    journal = tmp_path / "run.jsonl"
    rc = cli.main([
        "run", str(inp), "-o", str(out), "--device-resident",
        "--journal", str(journal),
    ])
    assert rc == 0
    got = np.array([int(x) for x in out.read_text().split()])
    assert (np.diff(got) >= 0).all() and len(got) == 4000
    types = [r["type"] for r in EventLog.read_jsonl(str(journal))]
    assert "device_handle" in types and "device_validate" in types
