"""Coded redundancy plane (`parallel.coded`, ARCHITECTURE §14).

The acceptance bar (ISSUE 15): one injected device loss at redundancy=2
recovers with ZERO re-sorted keys and ZERO re-dispatches — counter-
asserted across the SPMD scheduler, the wave pipeline and serve's
eviction path — with bit-identical output; losses over the budget
degrade cleanly to the re-run path (journaled `coded_budget_exceeded`,
still bit-identical).
"""

import json
import os

import numpy as np
import pytest

from dsort_tpu.analysis.spec import assert_conformant
from dsort_tpu.config import ConfigError, JobConfig, SortConfig
from dsort_tpu.data.ingest import gen_uniform, gen_zipf
from dsort_tpu.parallel.coded import (
    CodedBudgetExceeded,
    dead_positions,
)
from dsort_tpu.parallel.exchange import (
    replica_wire_bytes,
    resolve_redundancy,
    ring_wire_bytes,
)
from dsort_tpu.parallel.sample_sort import SampleSort
from dsort_tpu.scheduler.fault import FaultInjector, WorkerFailure
from dsort_tpu.utils.events import COUNTERS, EVENT_TYPES, EventLog
from dsort_tpu.utils.metrics import Metrics

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _metered():
    return Metrics(journal=EventLog())


def _sweep_hook(injector, p, stage="ring"):
    """The scheduler's aggregating ring-hook shape for bare SampleSort /
    wave drills: sweep every position, raise ONE failure carrying all."""

    def hook():
        failed = []
        for i in range(p):
            try:
                injector.check(i, stage)
            except WorkerFailure as f:
                failed.append(f.worker)
        if failed:
            e = WorkerFailure(failed[0], stage)
            e.workers = failed
            raise e

    return hook


# ---- knob resolution + config ---------------------------------------------


def test_resolve_redundancy_vocabulary():
    assert resolve_redundancy(None, 1, 8) == 1
    assert resolve_redundancy(None, 3, 8) == 3
    assert resolve_redundancy(2, 1, 8) == 2      # override > config
    assert resolve_redundancy(16, 1, 8) == 8     # clamped to the mesh
    assert resolve_redundancy(4, 1, 1) == 1      # no replica holder on P=1
    with pytest.raises(ValueError):
        resolve_redundancy(0, 1, 8)
    with pytest.raises(ValueError):
        resolve_redundancy(None, -1, 8)


def test_job_config_redundancy_validated():
    assert JobConfig(redundancy=2).redundancy == 2
    with pytest.raises(ConfigError):
        JobConfig(redundancy=0)


def test_conf_key_and_cli_flag_thread_redundancy(tmp_path):
    conf = tmp_path / "job.conf"
    conf.write_text("REDUNDANCY=2\nEXCHANGE=ring\n")
    cfg = SortConfig.from_conf_file(str(conf))
    assert cfg.job.redundancy == 2 and cfg.job.exchange == "ring"

    from dsort_tpu import cli

    class A:
        conf = None
        redundancy = 3

    assert cli._load_config(A()).job.redundancy == 3


def test_replica_wire_bytes_model():
    caps = (16, 8, 8, 24)
    p, bps = 4, 4
    # r=2: each device re-ships caps[k] at shift k+1; k=3 lands on itself.
    assert replica_wire_bytes(caps, bps, p, 2) == (16 + 8 + 8) * bps * p
    # r=1 is uncoded: no replica traffic.
    assert replica_wire_bytes(caps, bps, p, 1) == 0
    # r=p: every off-self slot of every shift ships.
    full = sum(
        sum(caps[k] for k in range(p) if (k + j) % p != 0)
        for j in range(1, p)
    ) * bps * p
    assert replica_wire_bytes(caps, bps, p, p) == full
    # uniform caps: the r=2 premium is exactly one extra ring's worth
    u = (32, 32, 32, 32)
    assert replica_wire_bytes(u, bps, p, 2) == ring_wire_bytes(u, bps, p)


def test_dead_positions_mapping():
    e = WorkerFailure(5, "ring")
    assert dead_positions(e) == [5]
    assert dead_positions(e, live=[0, 2, 5, 7]) == [2]
    e.workers = [5, 7]
    assert dead_positions(e, live=[0, 2, 5, 7]) == [2, 3]


# ---- exchange-level: healthy bit-identical + reconstruction ---------------


@pytest.mark.parametrize("red", [2, 3])
def test_coded_healthy_bit_identical(mesh8, red):
    ss = SampleSort(mesh8, JobConfig(exchange="ring", redundancy=red))
    data = gen_uniform(100_003, seed=1)
    m = _metered()
    np.testing.assert_array_equal(ss.sort(data, metrics=m), np.sort(data))
    assert m.counters["coded_replica_bytes"] > 0
    types = m.journal.types()
    assert "coded_replica_ship" in types and "skew_report" in types
    ship = next(
        e for e in m.journal.events() if e.type == "coded_replica_ship"
    )
    assert ship.fields["redundancy"] == red
    assert ship.fields["bytes"] == m.counters["coded_replica_bytes"]


def test_coded_zipf_per_call_override(mesh8):
    """Per-call redundancy= override on an uncoded JobConfig, skewed keys."""
    ss = SampleSort(mesh8, JobConfig(exchange="ring", key_dtype=np.int64))
    z = gen_zipf(1 << 16, a=1.3, seed=4)
    np.testing.assert_array_equal(
        ss.sort(z, redundancy=2), np.sort(z)
    )


def test_coded_float_keys_ride_mapped(mesh8):
    ss = SampleSort(mesh8, JobConfig(exchange="ring", redundancy=2))
    rng = np.random.default_rng(3)
    f = rng.standard_normal(20_000).astype(np.float32)
    f[:7] = [np.nan, -np.nan, 0.0, -0.0, np.inf, -np.inf, 1.5]
    np.testing.assert_array_equal(ss.sort(f), np.sort(f))


def test_coded_forces_ring_from_alltoall_and_fused(mesh8, caplog):
    data = gen_uniform(50_000, seed=2)
    for exch in ("alltoall", "fused"):
        ss = SampleSort(mesh8, JobConfig(exchange=exch, redundancy=2))
        m = _metered()
        np.testing.assert_array_equal(ss.sort(data, metrics=m), np.sort(data))
        # The coded run took the lax ring: replica plane journaled, and no
        # fused launch happened.
        assert m.counters["coded_replica_bytes"] > 0
        assert m.counters.get("fused_exchange_launches", 0) == 0


def test_coded_kv_runs_coded(mesh8):
    """v2 (§18) retired the kv warn-and-run-uncoded downgrade: payload
    rows ride the replica plane and the premium is priced."""
    from dsort_tpu.data.ingest import gen_terasort

    tk, tv = gen_terasort(4096, seed=3)
    ss = SampleSort(
        mesh8,
        JobConfig(
            exchange="ring", redundancy=2, key_dtype=np.uint64,
            payload_bytes=tv.shape[1],
        ),
    )
    m = _metered()
    out_k, out_v = ss.sort_kv(tk, tv, metrics=m)
    order = np.argsort(tk, kind="stable")
    np.testing.assert_array_equal(out_k, tk[order])
    np.testing.assert_array_equal(out_v, tv[order])
    assert m.counters["coded_replica_bytes"] > 0  # kv premium is priced
    assert "coded_replica_ship" in m.journal.types()


def test_fault_snapshot_reconstructs_every_loss_shape(mesh8):
    """The `CodedExchangeState` contract: single loss, non-adjacent double
    loss at r=2, budget exceeded on an adjacent pair at r=2, adjacent
    pair covered at r=3."""
    data = gen_uniform(80_000, seed=5)
    ss = SampleSort(mesh8, JobConfig(exchange="ring", redundancy=2))
    ss.fault_hook = lambda: (_ for _ in ()).throw(WorkerFailure(3, "ring"))
    with pytest.raises(WorkerFailure) as ei:
        ss.sort(data)
    st = ei.value.coded_state
    assert st.num_workers == 8 and st.redundancy == 2
    expect = np.sort(data)
    out, info = st.assemble([3])
    np.testing.assert_array_equal(out, expect)
    assert info["holders"] == {3: 4}
    assert info["recovered_keys"] == len(st.ranges[3])
    assert info["replica_bytes"] > 0
    # non-adjacent double loss is covered at r=2
    out2, info2 = st.assemble([2, 5])
    np.testing.assert_array_equal(out2, expect)
    assert info2["holders"] == {2: 3, 5: 6}
    # an adjacent pair exceeds the r=2 budget
    with pytest.raises(CodedBudgetExceeded):
        st.assemble([3, 4])
    # ... and r=3 covers it (both ranges rebuilt from the j=2 holder)
    ss3 = SampleSort(mesh8, JobConfig(exchange="ring", redundancy=3))
    e3 = WorkerFailure(3, "ring")
    e3.workers = [3, 4]
    ss3.fault_hook = lambda: (_ for _ in ()).throw(e3)
    with pytest.raises(WorkerFailure) as ei3:
        ss3.sort(data)
    out3, info3 = ei3.value.coded_state.assemble([3, 4])
    np.testing.assert_array_equal(out3, expect)
    assert info3["holders"] == {3: 5, 4: 5}


# ---- FaultInjector multi-trip sequences -----------------------------------


def test_fail_sequence_trips_in_order():
    inj = FaultInjector()
    inj.fail_sequence([(3, "ring"), (4, "ring"), (2, "spmd")])
    # out-of-order checks don't trip until the head matches
    inj.check(4, "ring")
    inj.check(2, "spmd")
    with pytest.raises(WorkerFailure):
        inj.check(3, "ring")
    # the next entry armed immediately: one sweep can trip both
    with pytest.raises(WorkerFailure):
        inj.check(4, "ring")
    # a later attempt's sweep continues the remainder
    inj.check(4, "ring")
    with pytest.raises(WorkerFailure):
        inj.check(2, "spmd")
    inj.check(2, "spmd")  # consumed: the sequence is exhausted
    assert inj.trips == 3


# ---- the SPMD scheduler drill (acceptance) --------------------------------


def test_scheduler_coded_recovery_zero_rerun(tmp_path):
    """THE acceptance drill: one injected mid-ring loss at redundancy=2
    recovers with zero re-sorted keys and zero re-dispatches —
    counter-asserted (`coded_recoveries`=1, `device_handle_reruns`=0,
    exactly ONE attempt_start), output bit-identical, one
    `coded_reconstruct` flight bundle."""
    from dsort_tpu.obs.flight import FlightRecorder
    from dsort_tpu.scheduler import SpmdScheduler

    inj = FaultInjector()
    sched = SpmdScheduler(
        job=JobConfig(
            settle_delay_s=0.01, exchange="ring", redundancy=2,
            flight_recorder_dir=str(tmp_path),
        ),
        injector=inj,
    )
    z = gen_zipf(1 << 17, a=1.3, seed=5)
    np.testing.assert_array_equal(sched.sort(z), np.sort(z))  # warm
    inj.fail_once(3, "ring")
    m = _metered()
    out = sched.sort(z, metrics=m)
    np.testing.assert_array_equal(out, np.sort(z))
    assert m.counters["coded_recoveries"] == 1
    assert m.counters["coded_recovered_keys"] > 0
    assert m.counters.get("device_handle_reruns", 0) == 0
    assert m.counters.get("wave_runs_resorted", 0) == 0
    assert m.counters.get("shuffle_resort_keys", 0) == 0
    assert m.counters["mesh_reforms"] == 1
    types = m.journal.types()
    assert types.count("attempt_start") == 1  # zero re-dispatch
    # full fault contract order: death -> re-form -> coded reconstruct.
    assert (
        types.index("worker_dead")
        < types.index("mesh_reform")
        < types.index("coded_recover")
    )
    assert types[-1] == "job_done"
    rec = next(e for e in m.journal.events() if e.type == "coded_recover")
    assert rec.fields["dead"] == [3] and rec.fields["holders"] == {3: 4}
    assert rec.fields["recovered_keys"] == m.counters["coded_recovered_keys"]
    assert rec.fields["wall_s"] >= 0
    bundles = [
        b for b in FlightRecorder.read_bundles(str(tmp_path))
        if b["recovery_path"] == "coded_reconstruct"
    ]
    assert len(bundles) == 1
    assert bundles[0]["detail"]["dead"] == [3]
    # the scheduler still re-formed: the dead device left the mesh
    assert sorted(sched.table.live_workers()) == [0, 1, 2, 4, 5, 6, 7]


def test_scheduler_over_budget_degrades_to_rerun():
    """Two losses at redundancy=2 (a range's owner AND its replica
    holder, via the multi-trip injector) exceed the budget: journaled
    `coded_budget_exceeded`, clean degrade to the re-run path,
    bit-identical output."""
    from dsort_tpu.scheduler import SpmdScheduler

    inj = FaultInjector()
    sched = SpmdScheduler(
        job=JobConfig(settle_delay_s=0.01, exchange="ring", redundancy=2),
        injector=inj,
    )
    z = gen_zipf(1 << 17, a=1.3, seed=5)
    np.testing.assert_array_equal(sched.sort(z), np.sort(z))  # warm
    inj.fail_sequence([(3, "ring"), (4, "ring")])
    m = _metered()
    out = sched.sort(z, metrics=m)
    np.testing.assert_array_equal(out, np.sort(z))
    types = m.journal.types()
    assert "coded_budget_exceeded" in types
    assert m.counters.get("coded_recoveries", 0) == 0
    assert types.count("attempt_start") == 2  # the re-run happened
    assert m.counters["mesh_reforms"] == 1
    b = next(
        e for e in m.journal.events() if e.type == "coded_budget_exceeded"
    )
    assert b.fields["dead"] == [3, 4] and b.fields["redundancy"] == 2
    # both devices actually left the mesh in ONE re-form
    assert sorted(sched.table.live_workers()) == [0, 1, 2, 5, 6, 7]


def test_scheduler_uncoded_rerun_contract_unchanged():
    """redundancy=1 keeps today's re-run path byte-for-byte: the mid-ring
    drill's contract (PR 4) still holds with the new hook plumbing."""
    from dsort_tpu.scheduler import SpmdScheduler

    inj = FaultInjector()
    sched = SpmdScheduler(
        job=JobConfig(settle_delay_s=0.01, exchange="ring"), injector=inj
    )
    z = gen_zipf(1 << 16, a=1.3, seed=5)
    np.testing.assert_array_equal(sched.sort(z), np.sort(z))
    inj.fail_once(3, "ring")
    m = _metered()
    np.testing.assert_array_equal(sched.sort(z, metrics=m), np.sort(z))
    types = m.journal.types()
    assert types.count("attempt_start") == 2
    assert "coded_recover" not in types and "coded_replica_ship" not in types


def test_scheduler_coded_loss_in_resume_subset_keeps_restored_ranges(
    tmp_path,
):
    """A coded loss inside a checkpoint-resume's SUBSET re-sort must not
    complete from the subset-only snapshot (it covers only the lost
    interval — assembling it as the job output would drop every restored
    range): the partial snapshot degrades to the re-run loop, whose next
    attempt resumes correctly.  Output bit-identical, restored ranges
    intact."""
    from dsort_tpu.scheduler import SpmdScheduler

    inj = FaultInjector()
    job = JobConfig(
        settle_delay_s=0.01, checkpoint_dir=str(tmp_path),
        heartbeat_timeout_s=5.0, exchange="ring", redundancy=2,
    )
    sched = SpmdScheduler(job=job, injector=inj)
    data = gen_uniform(40_000, seed=60)
    # Loss 1 (uncoded stage): range 7 dies while read back — ranges 0..6
    # persist, the retry resumes by re-sorting only the lost interval.
    # Loss 2 (coded stage): the SUBSET re-sort's ring trips — its coded
    # snapshot covers only the subset and must NOT short-circuit the job.
    # Ordered via fail_sequence so the ring trip cannot fire before the
    # assemble-stage loss has produced a resume.
    inj.fail_sequence([(7, "assemble"), (6, "ring")])
    m = _metered()
    out = sched.sort(data, metrics=m, job_id="codedresume")
    np.testing.assert_array_equal(out, np.sort(data))
    assert m.counters["shuffle_ranges_restored"] >= 7
    assert 0 < m.counters["shuffle_resort_keys"] < len(data)
    # the partial snapshot was refused: no coded completion happened
    assert m.counters.get("coded_recoveries", 0) == 0


# ---- the wave pipeline drill ----------------------------------------------


def test_wave_coded_repair_no_host_resort(tmp_path):
    """A coded wave repairs a mid-ring loss from replica slots: zero
    `wave_runs_resorted`, zero `wave_resort_keys`, no `wave_resume`,
    bit-identical output, and the pipeline continues on the mesh."""
    from dsort_tpu.models.wave_sort import ExternalWaveSort

    data = gen_uniform(1 << 18, seed=7)
    ws = ExternalWaveSort(
        wave_elems=1 << 16, spill_dir=str(tmp_path), job_id="codedwave",
        job=JobConfig(exchange="ring"), redundancy=2, resume=False,
    )
    inj = FaultInjector()
    inj.fail_once(3, "ring")
    sweep = _sweep_hook(inj, ws.num_workers)
    calls = {"n": 0}

    def hook():
        calls["n"] += 1
        if calls["n"] == 2:  # the second wave's exchange
            sweep()

    ws.fault_hook = hook
    m = _metered()
    out = ws.sort(data, metrics=m)
    np.testing.assert_array_equal(out, np.sort(data))
    assert m.counters["coded_recoveries"] == 1
    assert m.counters.get("wave_runs_resorted", 0) == 0
    assert m.counters.get("wave_resort_keys", 0) == 0
    assert m.counters["waves_sorted"] == 4
    types = m.journal.types()
    assert "coded_recover" in types and "wave_resume" not in types
    rec = next(e for e in m.journal.events() if e.type == "coded_recover")
    assert rec.fields["wave"] == 1 and rec.fields["dead"] == [3]


def test_wave_coded_over_budget_degrades_to_host_resort(tmp_path):
    from dsort_tpu.models.wave_sort import ExternalWaveSort

    data = gen_uniform(1 << 17, seed=9)
    ws = ExternalWaveSort(
        wave_elems=1 << 16, spill_dir=str(tmp_path), job_id="codedwave2",
        job=JobConfig(exchange="ring"), redundancy=2, resume=False,
    )
    inj = FaultInjector()
    inj.fail_sequence([(3, "ring"), (4, "ring")])
    ws.fault_hook = _sweep_hook(inj, ws.num_workers)
    m = _metered()
    out = ws.sort(data, metrics=m)
    np.testing.assert_array_equal(out, np.sort(data))
    types = m.journal.types()
    assert "coded_budget_exceeded" in types and "wave_resume" in types
    assert m.counters.get("coded_recoveries", 0) == 0
    assert m.counters["wave_runs_resorted"] == ws.num_workers


def test_wave_coded_composes_with_restart_resume(tmp_path):
    """Coded runs are ordinary durable (wave, run) entries: a second run
    of the same job restores them for free (`runs_resumed`)."""
    from dsort_tpu.models.wave_sort import ExternalWaveSort

    data = gen_uniform(1 << 17, seed=11)
    kw = dict(
        wave_elems=1 << 16, spill_dir=str(tmp_path), job_id="codedresume",
        job=JobConfig(exchange="ring"), redundancy=2,
    )
    ws = ExternalWaveSort(**kw)
    inj = FaultInjector()
    inj.fail_once(3, "ring")
    ws.fault_hook = _sweep_hook(inj, ws.num_workers)
    m = _metered()
    np.testing.assert_array_equal(ws.sort(data, metrics=m), np.sort(data))
    assert m.counters["coded_recoveries"] == 1
    ws2 = ExternalWaveSort(**kw)
    m2 = _metered()
    np.testing.assert_array_equal(ws2.sort(data, metrics=m2), np.sort(data))
    assert m2.counters["runs_resumed"] == 2 * ws2.num_workers
    assert m2.counters.get("waves_sorted", 0) == 0


def test_wave_fused_overrides_to_ring_when_coded(tmp_path):
    from dsort_tpu.models.wave_sort import ExternalWaveSort

    ws = ExternalWaveSort(
        wave_elems=1 << 15, spill_dir=str(tmp_path), job_id="codedfused",
        job=JobConfig(exchange="fused"), redundancy=2, resume=False,
    )
    assert ws.exchange == "ring" and ws.redundancy == 2
    data = gen_uniform(1 << 16, seed=13)
    np.testing.assert_array_equal(ws.sort(data), np.sort(data))


# ---- serve: eviction completes from replicas ------------------------------


def _coded_runner_service(tmp_path, journal):
    """A runner-mode service whose sorter is a coded SampleSort with an
    injected mid-ring loss on its FIRST run — the eviction drill rig."""
    from dsort_tpu.parallel.mesh import local_device_mesh
    from dsort_tpu.serve.service import SortService

    mesh = local_device_mesh()
    job = JobConfig(
        exchange="ring", redundancy=2, settle_delay_s=0.01,
        flight_recorder_dir=str(tmp_path),
    )
    ss = SampleSort(mesh, job)
    inj = FaultInjector()
    ss.fault_hook = _sweep_hook(inj, mesh.shape["w"])
    calls = []

    def runner(data, metrics, job_id=None):
        calls.append(1)
        return ss.sort(data, metrics)

    svc = SortService(job=job, journal=journal, runner=runner, start=False)
    return svc, ss, inj, calls


def test_serve_evicted_coded_job_completes_from_replicas(tmp_path):
    """`job_evicted` on a coded job re-admits and completes from replicas
    instead of re-running: the runner executes ONCE, the re-dispatch is
    a local merge (`coded_recover`), output bit-identical, one eviction
    bundle + one `coded_reconstruct` bundle."""
    from dsort_tpu.obs.flight import FlightRecorder

    journal = EventLog()
    svc, ss, inj, calls = _coded_runner_service(tmp_path, journal)
    data = gen_uniform(60_000, seed=1)
    ss.sort(data)  # warm OUTSIDE the service (not a runner call)
    inj.fail_once(3, "ring")
    v, t = svc.submit(data, tenant="acme")
    assert v.admitted
    svc.start()
    np.testing.assert_array_equal(t.result(timeout=300), np.sort(data))
    svc.shutdown(drain=True)
    assert len(calls) == 1  # the sort ran once; completion came from replicas
    types = journal.types()
    # Sequencing rides the declared contracts (ISSUE 17): the job's
    # evict->readmit->terminal cycle is the `job_lifecycle` grammar.
    report = assert_conformant(journal)
    assert report["contracts"]["job_lifecycle"]["checked"] == 1
    # Behavioral facts the grammar cannot pin: the completion came from
    # replicas — a reconstruct between readmission and the terminal, and
    # a second dequeue for the local merge.
    assert types.index("job_readmitted") < types.index("coded_recover")
    assert types.index("coded_recover") < types.index("job_done")
    assert types.count("job_dequeued") == 2
    paths = [
        b["recovery_path"]
        for b in FlightRecorder.read_bundles(str(tmp_path))
    ]
    assert paths.count("job_evicted") == 1
    assert paths.count("coded_reconstruct") == 1


def test_serve_over_budget_coded_job_reruns(tmp_path):
    """An over-budget snapshot on the ticket degrades to the ordinary
    re-dispatch: the runner executes twice, `coded_budget_exceeded`
    journaled, output still bit-identical."""
    journal = EventLog()
    svc, ss, inj, calls = _coded_runner_service(tmp_path, journal)
    data = gen_uniform(60_000, seed=2)
    ss.sort(data)  # warm
    inj.fail_sequence([(3, "ring"), (4, "ring")])
    _, t = svc.submit(data, tenant="acme")
    svc.start()
    np.testing.assert_array_equal(t.result(timeout=300), np.sort(data))
    svc.shutdown(drain=True)
    assert len(calls) == 2  # evicted, then genuinely re-run
    types = journal.types()
    assert "coded_budget_exceeded" in types
    assert "coded_recover" not in types


# ---- analyzer: the recovery verdict ---------------------------------------


def test_analyze_recovery_verdict_coded_vs_rerun():
    """`dsort report --analyze`'s `recovery` key splits re-run vs
    coded-local recovery, asserted against journal ground truth on an
    injected coded drill AND the existing re-run drill."""
    from dsort_tpu.obs.analyze import VERDICT_KEYS, analyze_records
    from dsort_tpu.scheduler import SpmdScheduler

    assert "recovery" in VERDICT_KEYS
    z = gen_zipf(1 << 16, a=1.3, seed=5)

    def drill(red, seq):
        inj = FaultInjector()
        sched = SpmdScheduler(
            job=JobConfig(
                settle_delay_s=0.01, exchange="ring", redundancy=red
            ),
            injector=inj,
        )
        sched.sort(z)
        inj.fail_sequence(seq)
        m = _metered()
        np.testing.assert_array_equal(sched.sort(z, metrics=m), np.sort(z))
        return m, [e.to_dict() for e in m.journal.events()]

    # coded drill: path = coded_reconstruct, figures == journal ground truth
    m, recs = drill(2, [(3, "ring")])
    v = analyze_records(recs)["recovery"]
    rec_ev = next(r for r in recs if r["type"] == "coded_recover")
    assert v["path"] == "coded_reconstruct"
    assert v["coded"]["recoveries"] == 1
    assert v["coded"]["recovered_keys"] == rec_ev["recovered_keys"]
    assert v["coded"]["replica_bytes"] == rec_ev["replica_bytes"]
    assert v["coded"]["wall_s"] == pytest.approx(rec_ev["wall_s"])
    assert v["rerun"]["mesh_reforms"] == 1
    assert v["rerun"]["resorted_keys"] == 0
    # re-run drill: path = rerun, no coded side
    m2, recs2 = drill(1, [(3, "ring")])
    v2 = analyze_records(recs2)["recovery"]
    assert v2["path"] == "rerun"
    assert v2["coded"]["recoveries"] == 0
    assert v2["rerun"]["mesh_reforms"] == 1
    # healthy journal: no recovery section at all
    from dsort_tpu.parallel.mesh import local_device_mesh

    m3 = _metered()
    SampleSort(local_device_mesh(), JobConfig(exchange="ring")).sort(
        z, metrics=m3
    )
    v3 = analyze_records([e.to_dict() for e in m3.journal.events()])
    assert v3["recovery"] is None
    # the human table renders the split
    from dsort_tpu.obs.analyze import format_analysis

    txt = format_analysis(analyze_records(recs))
    assert "recovery" in txt and "coded_reconstruct" in txt


# ---- CLI / bench gates ----------------------------------------------------


def test_cli_bench_coded_ab_gate(capsys):
    """Tier-1 gate for `make coded-smoke`: the coded A/B harness runs end
    to end — all four arms bit-identical, exactly one coded recovery per
    faulted coded sort, both ratio fields present."""
    from dsort_tpu import cli

    rc = cli.main(["bench", "--coded-ab", "--n", "65536", "--reps", "1"])
    out = capsys.readouterr().out
    rows = [json.loads(ln) for ln in out.splitlines() if ln.startswith("{")]
    assert rc == 0
    healthy = next(r for r in rows if "healthy" in r["metric"])
    failure = next(r for r in rows if "failure" in r["metric"])
    assert healthy["bit_identical"] is True
    assert healthy["redundancy"] == 2
    assert healthy["coded_replica_bytes"] > 0
    assert healthy["replica_overhead_frac"] >= 0
    assert failure["bit_identical"] is True
    assert failure["coded_recoveries"] == 1
    assert failure["recovered_keys"] > 0
    assert failure["throughput_under_failure_ratio"] > 0
    assert failure["rerun_failure_ratio"] > 0


def test_cli_run_small_coded_job_reaches_exchange(tmp_path):
    """`dsort run --redundancy 2` must reach the exchange plane even for
    a small input: the fused single-device shortcut has no replica plane,
    so an explicit availability posture skips it (the checkpointing
    rule)."""
    from dsort_tpu import cli

    rng = np.random.default_rng(17)
    inp = tmp_path / "in.txt"
    np.savetxt(
        inp, rng.integers(0, 1 << 30, 20_000, dtype=np.int32), fmt="%d"
    )
    out = tmp_path / "out.txt"
    jpath = tmp_path / "run.jsonl"
    rc = cli.main([
        "run", str(inp), "--exchange", "ring", "--redundancy", "2",
        "--journal", str(jpath), "-o", str(out),
    ])
    assert rc == 0
    got = np.loadtxt(out, dtype=np.int64)
    want = np.sort(np.loadtxt(inp, dtype=np.int64))
    np.testing.assert_array_equal(got, want)
    types = [json.loads(ln)["type"] for ln in open(jpath)]
    assert "coded_replica_ship" in types  # not the fused shortcut


def test_bench_r15_artifact_checks_and_compares():
    """BENCH_r15.jsonl: --check clean, the coded rows join the trajectory
    as 'added' vs r14, and the headline holds: zipf-1M throughput under
    one injected failure at redundancy=2 beats the re-run baseline's
    ~0.41x ratio, with the healthy-path replica overhead alongside."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "bench", os.path.join(REPO, "bench.py")
    )
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    r15 = os.path.join(REPO, "BENCH_r15.jsonl")
    assert bench.check_artifact(r15) == []
    rows = bench.compare_artifacts(os.path.join(REPO, "BENCH_r14.jsonl"), r15)
    added = {r["metric"] for r in rows if r["class"] == "added"}
    assert any(m.startswith("coded_redundancy_failure") for m in added)
    assert any(m.startswith("coded_redundancy_healthy") for m in added)
    with open(r15) as f:
        lines = [json.loads(ln) for ln in f if ln.strip()]
    failure = next(
        l for l in lines
        if l.get("metric", "").startswith("coded_redundancy_failure")
    )
    healthy = next(
        l for l in lines
        if l.get("metric", "").startswith("coded_redundancy_healthy")
    )
    assert failure["bit_identical"] is True
    assert failure["coded_recoveries"] == 1
    # THE headline: coded survives a loss better than re-running does.
    assert (
        failure["throughput_under_failure_ratio"]
        > failure["rerun_failure_ratio"]
    )
    assert failure["throughput_under_failure_ratio"] > 0.41
    assert healthy["bit_identical"] is True
    assert healthy["replica_overhead_frac"] >= 0


# ---- registries + docs schema ---------------------------------------------


def test_coded_registries():
    for etype in (
        "coded_replica_ship", "coded_recover", "coded_budget_exceeded"
    ):
        assert etype in EVENT_TYPES
    for counter in (
        "coded_recoveries", "coded_replica_bytes", "coded_recovered_keys"
    ):
        assert counter in COUNTERS
    from dsort_tpu.obs.flight import RECOVERY_EVENTS, recovery_path_name

    assert "coded_recover" in RECOVERY_EVENTS
    assert recovery_path_name("coded_recover", {}) == "coded_reconstruct"


def test_architecture_documents_coded_plane():
    """§14's contract is test-enforced like §7–§13: replica placement,
    the reconstruction contract, the budget/fallback state machine, and
    every event/counter name appear verbatim."""
    arch = open(
        os.path.join(REPO, "ARCHITECTURE.md"), encoding="utf-8"
    ).read()
    assert "## 14. Coded redundancy plane" in arch
    for etype in (
        "coded_replica_ship", "coded_recover", "coded_budget_exceeded"
    ):
        assert f"`{etype}`" in arch, f"event {etype} undocumented"
        assert etype in EVENT_TYPES
    for counter in (
        "coded_recoveries", "coded_replica_bytes", "coded_recovered_keys"
    ):
        assert f"`{counter}`" in arch, f"counter {counter} undocumented"
        assert counter in COUNTERS
    for term in (
        "--redundancy", "REDUNDANCY", "resolve_redundancy",
        "coded_reconstruct", "CodedBudgetExceeded", "fail_sequence",
        "replica_overhead_frac", "throughput_under_failure_ratio",
        "BENCH_r15.jsonl", "`recovery`", "arXiv:1702.04850",
    ):
        assert term in arch, f"{term} missing from §14"
