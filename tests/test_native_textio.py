"""Native ASCII int ingest/egress (runtime/native/textio.cpp).

Parity target: the reference's C file IO (two-pass fscanf ingest
``server.c:171-182``; fprintf-per-int egress ``server.c:517-519``), as a
memory-bandwidth buffer parser/formatter behind `data.ingest`.
"""

import io

import numpy as np
import pytest

from dsort_tpu.data.ingest import read_ints_file, write_ints_file
from dsort_tpu.runtime import native

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native library unavailable"
)


@pytest.mark.parametrize("dtype", [np.int32, np.int64, np.uint32, np.uint64])
def test_roundtrip_extremes_and_random(dtype):
    info = np.iinfo(dtype)
    rng = np.random.default_rng(3)
    vals = np.concatenate(
        [
            np.array([info.min, info.max, 0], dtype=dtype),
            rng.integers(info.min, info.max, 500, dtype=dtype, endpoint=True),
        ]
    )
    txt = native.format_ints_text(vals)
    np.testing.assert_array_equal(native.parse_ints_text(txt, dtype), vals)
    # numpy reads our output identically (byte-level format compatibility)
    np.testing.assert_array_equal(
        np.loadtxt(io.BytesIO(txt), dtype=dtype, ndmin=1), vals
    )


def test_format_matches_savetxt_bytes():
    vals = np.array([-5, 0, 7, 2**31 - 1, -(2**31)], dtype=np.int32)
    buf = io.BytesIO()
    np.savetxt(buf, vals, fmt="%d")
    assert native.format_ints_text(vals) == buf.getvalue()


def test_whitespace_variants_and_empty():
    assert native.parse_ints_text(b"  1\t2\r\n3\n\n 4 ", np.int32).tolist() == [
        1, 2, 3, 4,
    ]
    assert len(native.parse_ints_text(b"", np.int32)) == 0
    assert len(native.parse_ints_text(b"  \n\t ", np.int32)) == 0


def test_space_separated_denser_than_lines_hits_retry_path():
    # Newline-count capacity (0+1) underestimates; the parser must fall back
    # to the exact token-count pass and still succeed.
    n = 1000
    txt = b" ".join(str(i).encode() for i in range(n))
    np.testing.assert_array_equal(
        native.parse_ints_text(txt, np.int32), np.arange(n, dtype=np.int32)
    )


@pytest.mark.parametrize("bad", [b"12 abc", b"1.5", b"0x10"])
def test_malformed_tokens_raise(bad):
    with pytest.raises(ValueError):
        native.parse_ints_text(bad, np.int32)


def test_range_is_per_dtype():
    # Out-of-range raises OverflowError specifically — callers must not
    # recover into a lossy fallback that silently wraps keys.
    with pytest.raises(OverflowError):
        native.parse_ints_text(b"3000000000", np.int32)
    with pytest.raises(OverflowError):
        native.parse_ints_text(b"99999999999999999999999999 1", np.int32)
    assert native.parse_ints_text(b"3000000000", np.uint32)[0] == 3_000_000_000
    big = str(2**64 - 1).encode()
    assert native.parse_ints_text(big, np.uint64)[0] == np.uint64(2**64 - 1)
    # '-' into unsigned is a grammar reject (from_chars), not a range error
    with pytest.raises(ValueError):
        native.parse_ints_text(b"-1", np.uint32)


def test_read_ints_file_overflow_is_loud_not_wrapped(tmp_path):
    # Regression: an int64-sized key read with the default int32 dtype used
    # to fall back to np.loadtxt and silently wrap to INT32_MIN, corrupting
    # the sort. It must raise instead.
    p = tmp_path / "big.txt"
    p.write_text("1\n2000734708531680000\n2\n")
    with pytest.raises(OverflowError):
        read_ints_file(p, dtype=np.int32)
    out = read_ints_file(p, dtype=np.int64)
    assert out.tolist() == [1, 2000734708531680000, 2]


def test_read_write_ints_file_native_path(tmp_path):
    p = tmp_path / "keys.txt"
    vals = np.array([-1, -(2**31), 2**31 - 1, 0, 42], dtype=np.int32)
    write_ints_file(p, vals)
    assert p.read_bytes() == b"-1\n-2147483648\n2147483647\n0\n42\n"
    np.testing.assert_array_equal(read_ints_file(p), vals)


def test_read_ints_file_falls_back_on_comments(tmp_path):
    # '#' comments are np.loadtxt grammar, not the native parser's; the
    # ingest wrapper must transparently fall back.
    p = tmp_path / "c.txt"
    p.write_text("# header\n1\n2\n# mid\n3\n")
    np.testing.assert_array_equal(read_ints_file(p), [1, 2, 3])


def test_sort_n_oracle_compatibility(tmp_path):
    # End-to-end: our writer's output must be what `sort -n` would produce
    # for the sorted array (the reference's golden-pair property).
    import subprocess

    rng = np.random.default_rng(11)
    vals = rng.integers(-1000, 1000, 5000).astype(np.int32)
    src = tmp_path / "in.txt"
    write_ints_file(src, vals)
    golden = subprocess.run(
        ["sort", "-n", str(src)], capture_output=True, text=True, check=True
    ).stdout
    out = tmp_path / "out.txt"
    write_ints_file(out, np.sort(vals))
    assert out.read_text() == golden


def test_parallel_parse_matches_serial():
    # This container may expose 1 CPU, where the wrapper picks 1 thread; force
    # the multi-threaded ranges directly so the split/offset logic is tested.
    import ctypes

    lib = native._load()
    rng = np.random.default_rng(7)
    vals = rng.integers(-(2**31), 2**31 - 1, 300_000).astype(np.int32)
    txt = native.format_ints_text(vals)
    assert len(txt) > (1 << 20)  # above the MT engage threshold
    out = np.empty(len(vals), dtype=np.int32)
    n = lib.dsort_parse_mt_i32(
        txt, len(txt), out.ctypes.data_as(ctypes.c_void_p), len(vals), 4, None
    )
    assert n == len(vals)
    np.testing.assert_array_equal(out, vals)


def test_parallel_format_matches_serial():
    import ctypes

    lib = native._load()
    rng = np.random.default_rng(9)
    vals = rng.integers(-(2**31), 2**31 - 1, 400_000).astype(np.int32)  # > 2^18
    width = native._TEXT_WIDTH["i32"]
    cap = len(vals) * width + 1
    buf = ctypes.create_string_buffer(cap)
    written = lib.dsort_format_mt_i32(
        vals.ctypes.data_as(ctypes.c_void_p), len(vals), buf, cap, width, 4
    )
    assert written > 0
    expect = b"".join(b"%d\n" % v for v in vals.tolist())
    assert buf.raw[:written] == expect


def test_parallel_parse_error_codes():
    import ctypes

    lib = native._load()
    bad = (b"1\n" * 700_000) + b"oops\n"  # error in the last range
    out = np.empty(700_001, dtype=np.int32)
    n = lib.dsort_parse_mt_i32(
        bad, len(bad), out.ctypes.data_as(ctypes.c_void_p), 700_001, 4, None
    )
    assert n == -1  # PARSE_BAD_CHAR surfaces from the count pass
