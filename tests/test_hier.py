"""Hierarchical exchange plane (`parallel.exchange` hier schedule,
ARCHITECTURE §17).

The acceptance bar (ISSUE 18): `exchange="hier"` runs the two-level pod
schedule — intra-host aggregation, exactly ONE transfer per (src-host,
dst-host) pair over the DCN leg, local scatter + merge — bit-identically
to the flat schedules with a MEASURED DCN-byte reduction; device loss
re-forms within the host grouping and a whole-host loss re-plans the
(H, H) schedule on the survivors (journaled `hier_reform`,
trace-contract-pinned); the planner arms the schedule only from a real
topology signal; capacity rungs and splitter quality hold out to
P=128–512 simulated devices (pure host math — no 512-device backend).
"""

import json
import logging
import os

import numpy as np
import pytest

from dsort_tpu.analysis.spec import assert_conformant
from dsort_tpu.config import JobConfig, SortConfig
from dsort_tpu.data.ingest import gen_uniform, gen_zipf
from dsort_tpu.parallel.exchange import (
    HierPlan,
    hier_plan,
    hier_wire_bytes,
    host_matrix,
    ladder_rungs,
    note_hier_plan,
    resolve_exchange,
    resolve_hier_hosts,
    ring_caps,
    ring_dcn_bytes,
)
from dsort_tpu.parallel.sample_sort import SampleSort
from dsort_tpu.scheduler.fault import FaultInjector
from dsort_tpu.utils.events import EventLog
from dsort_tpu.utils.metrics import Metrics

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _metered():
    return Metrics(journal=EventLog())


@pytest.fixture
def dsort_warnings(caplog):
    """caplog that actually sees dsort warnings: the package root logger
    ships its own stderr handler with propagate=False, so pytest's
    root-attached capture handler needs propagation restored."""
    root = logging.getLogger("dsort_tpu")
    old = root.propagate
    root.propagate = True
    try:
        with caplog.at_level(logging.WARNING, logger="dsort_tpu"):
            yield caplog
    finally:
        root.propagate = old


# ---- knob resolution -------------------------------------------------------


def test_resolve_exchange_accepts_hier():
    assert resolve_exchange("hier", "alltoall", 8) == "hier"
    assert resolve_exchange(None, "hier", 8) == "hier"
    # A 1-worker mesh short-circuits every schedule.
    assert resolve_exchange("hier", "alltoall", 1) == "alltoall"
    with pytest.raises(ValueError, match="hier"):
        resolve_exchange("hierarchical", "alltoall", 8)


def test_resolve_hier_hosts_cases():
    # Below 4 workers no >=2x2 grouping exists.
    assert resolve_hier_hosts(2, 2) == 0
    assert resolve_hier_hosts(0, 3) == 0
    # Explicit divisor wins as-is.
    assert resolve_hier_hosts(2, 8) == 2
    assert resolve_hier_hosts(4, 8) == 4
    # Non-divisor falls back to the largest divisor <= want with >= 2
    # devices per host — the fault contract's re-plan rule.
    assert resolve_hier_hosts(3, 8) == 2
    assert resolve_hier_hosts(4, 6) == 3
    # An explicit H == P passes through (1 device/host degenerates to a
    # pure host ring — nothing to aggregate, but still correct; auto and
    # the planner arm never pick it).
    assert resolve_hier_hosts(8, 8) == 8
    # Auto in a single process simulates 2 hosts.
    assert resolve_hier_hosts(0, 8) == 2
    # A prime mesh has no valid grouping at all.
    assert resolve_hier_hosts(2, 7) == 0


def test_job_config_validates_hier_hosts():
    from dsort_tpu.config import ConfigError

    assert JobConfig(exchange="hier", hier_hosts=2).hier_hosts == 2
    with pytest.raises(ConfigError, match="hier_hosts"):
        JobConfig(hier_hosts=-1)
    with pytest.raises(ConfigError, match="exchange"):
        JobConfig(exchange="two-level")


# ---- host-side plan math ---------------------------------------------------


def _synthetic_hist(p: int, n_local: int, seed: int = 0) -> np.ndarray:
    """A skewed (P, P) bucket histogram with every row summing n_local —
    what the plan phase all_gathers for a P-device mesh."""
    rng = np.random.default_rng(seed)
    w = rng.zipf(1.4, size=(p, p)).astype(np.float64)
    hist = np.floor(w / w.sum(axis=1, keepdims=True) * n_local).astype(np.int64)
    hist[:, 0] += n_local - hist.sum(axis=1)  # exact row sums
    return hist


def test_host_matrix_reduces_device_blocks():
    p, h = 8, 2
    hist = _synthetic_hist(p, 4096, seed=1)
    mat = host_matrix(hist, h)
    assert mat.shape == (h, h)
    d = p // h
    for g in range(h):
        for dst in range(h):
            blk = hist[g * d:(g + 1) * d, dst * d:(dst + 1) * d]
            assert mat[g, dst] == blk.sum()
    # A batched histogram reduces element-wise max over jobs first.
    batched = np.stack([hist, 2 * hist])
    assert np.array_equal(host_matrix(batched, h), host_matrix(2 * hist, h))


@pytest.mark.parametrize("p,hosts", [(8, 2), (8, 4), (16, 4)])
def test_hier_plan_caps_cover_measured_maxima(p, hosts):
    n_local = 4096
    hist = _synthetic_hist(p, n_local, seed=p + hosts)
    plan = hier_plan(hist, n_local, p, hosts)
    d = p // hosts
    assert plan == HierPlan(hosts, d, -(-hosts // d), plan.agg_cap,
                            plan.leg_caps, plan.scatter_cap)
    # Phase 1: the cap covers every (src device, dst host) aggregate.
    dev_host = hist.reshape(p, hosts, d).sum(axis=2)
    assert plan.agg_cap >= dev_host.max()
    # Phase 2: leg 0 is the self leg (never crosses the DCN); each shift's
    # cap covers its (src-host, dst-host) diagonal max.
    mat = host_matrix(hist, hosts)
    assert plan.leg_caps[0] == 0 and len(plan.leg_caps) == hosts
    for shift in range(1, hosts):
        mx = max(mat[g, (g + shift) % hosts] for g in range(hosts))
        assert plan.leg_caps[shift] >= mx
    # Phase 3: the scatter cap is bounded by the whole-HOST receiving
    # population — a skewed sub-slice of one host's aggregate can exceed
    # a single device's n_local.
    host_dev = hist.reshape(hosts, d, p).sum(axis=1)
    assert plan.scatter_cap >= host_dev.max()


def test_hier_plan_caps_sit_on_the_quantization_ladder():
    """Recompile-bound doctrine: every hier cap is a `ladder_rungs` value
    (or an exact clamp bound), so skew can demand only a bounded number
    of distinct compiled hier programs."""
    p, hosts, n_local = 8, 2, 4096
    d = p // hosts
    hist = _synthetic_hist(p, n_local, seed=7)
    plan = hier_plan(hist, n_local, p, hosts)
    assert plan.agg_cap % 8 == 0
    assert plan.scatter_cap % 8 == 0
    assert all(c % 8 == 0 for c in plan.leg_caps[1:])
    rungs = set(ladder_rungs(n_local * d * 2))
    clamp_bounds = {-(-n_local // 8) * 8, -(-(n_local * d) // 8) * 8,
                    d * plan.agg_cap}
    for cap in (plan.agg_cap, plan.scatter_cap, *plan.leg_caps[1:]):
        assert cap in rungs or cap in clamp_bounds or cap % 8 == 0


def test_hier_wire_bytes_and_flat_ring_baseline():
    p, hosts, n_local, bps = 8, 2, 1024, 8
    hist = np.full((p, p), n_local // p, dtype=np.int64)
    plan = hier_plan(hist, n_local, p, hosts)
    dcn, intra = hier_wire_bytes(plan, bps)
    # DCN: each non-self shift ships H aggregated transfers of its cap.
    assert dcn == sum(plan.leg_caps[1:]) * hosts * bps
    # Intra: slots x (agg + scatter) per device per step, both local rings.
    per_step = plan.slots * (plan.agg_cap + plan.scatter_cap)
    assert intra == (plan.dev_per_host - 1) * per_step * p * bps
    # The flat ring pushes its caps across the host boundary whenever src
    # and dst land on different hosts — strictly more DCN traffic than
    # one aggregated transfer per host pair under uniform load.
    caps = ring_caps(hist, n_local, p)
    flat_dcn = ring_dcn_bytes(caps, bps, p, hosts)
    d = p // hosts
    expect = sum(
        int(caps[k]) * sum(
            1 for i in range(p) if i // d != ((i + k) % p) // d
        ) for k in range(1, p)
    ) * bps
    assert flat_dcn == expect
    # Uniform load is the no-win case: the same keys cross hosts either
    # way, so aggregation can at best tie the flat baseline ...
    assert dcn <= flat_dcn
    # ... while skew is where the flat ring pays: every step pads to its
    # diagonal MAX bucket, and aggregation averages that padding away.
    skewed = _synthetic_hist(p, n_local, seed=17)
    s_plan = hier_plan(skewed, n_local, p, hosts)
    s_dcn, _ = hier_wire_bytes(s_plan, bps)
    s_caps = ring_caps(skewed, n_local, p)
    assert s_dcn < ring_dcn_bytes(s_caps, bps, p, hosts)


def test_note_hier_plan_counters_and_events():
    p, hosts, n_local, bps = 8, 4, 4096, 8
    hist = _synthetic_hist(p, n_local, seed=3)
    plan = hier_plan(hist, n_local, p, hosts)
    caps = ring_caps(hist, n_local, p)
    m = _metered()
    note_hier_plan(m, plan, caps, hist, n_local, p, bps, 1.25)
    dcn, intra = hier_wire_bytes(plan, bps)
    flat_dcn = ring_dcn_bytes(caps, bps, p, hosts)
    assert m.counters["hier_exchanges"] == 1
    assert m.counters["dcn_bytes_on_wire"] == dcn
    assert m.counters["intra_host_bytes_on_wire"] == intra
    assert m.counters["exchange_bytes_on_wire"] == dcn + intra
    # The headline identity: saved == what the flat ring would have
    # pushed over the inter-host fabric minus what hier actually ships.
    assert m.counters["dcn_bytes_saved"] == max(flat_dcn - dcn, 0)
    types = m.journal.types()
    assert types.count("hier_exchange_plan") == 1
    assert types.count("hier_exchange_leg") == hosts - 1
    assert "skew_report" in types
    ev = next(e for e in m.journal.events() if e.type == "hier_exchange_plan")
    assert ev.fields["hosts"] == hosts
    assert ev.fields["flat_ring_dcn_bytes"] == flat_dcn


# ---- end-to-end correctness on the mesh ------------------------------------


@pytest.mark.parametrize("hosts", [2, 4])
def test_hier_bit_identical_vs_ring(mesh8, hosts):
    for data in (gen_zipf(60_000, a=1.3, seed=11),
                 gen_uniform(60_000, seed=12)):
        expect = np.sort(data)
        ring = SampleSort(mesh8, JobConfig(exchange="ring")).sort(data)
        hier = SampleSort(
            mesh8, JobConfig(exchange="hier", hier_hosts=hosts)
        ).sort(data)
        np.testing.assert_array_equal(ring, expect)
        np.testing.assert_array_equal(hier, expect)


def test_hier_journals_the_dcn_split(mesh8):
    data = gen_zipf(100_000, a=1.3, seed=13)
    m = _metered()
    ss = SampleSort(mesh8, JobConfig(exchange="hier", hier_hosts=2))
    np.testing.assert_array_equal(ss.sort(data, metrics=m), np.sort(data))
    assert m.counters["hier_exchanges"] == 1
    assert m.counters["dcn_bytes_on_wire"] > 0
    assert m.counters["intra_host_bytes_on_wire"] > 0
    assert m.counters["exchange_bytes_on_wire"] == (
        m.counters["dcn_bytes_on_wire"]
        + m.counters["intra_host_bytes_on_wire"]
    )
    # The two-level schedule crossed the host boundary with LESS than the
    # flat ring's measured baseline for the same histogram.
    assert m.counters["dcn_bytes_saved"] > 0
    assert "hier_exchange_plan" in m.journal.types()


def test_hier_kv_downgrades_to_ring_with_warning(mesh8, dsort_warnings):
    from dsort_tpu.data.ingest import gen_terasort

    tk, tv = gen_terasort(4096, seed=5)
    ss = SampleSort(
        mesh8,
        JobConfig(exchange="hier", hier_hosts=2, key_dtype=np.uint64,
                  payload_bytes=tv.shape[1]),
    )
    m = _metered()
    out_k, out_v = ss.sort_kv(tk, tv, metrics=m)
    np.testing.assert_array_equal(out_k, np.sort(tk))
    assert any("keys-only" in r.getMessage()
               for r in dsort_warnings.records)
    assert m.counters.get("hier_exchanges", 0) == 0


def test_hier_small_mesh_downgrades_with_warning(dsort_warnings):
    from dsort_tpu.parallel.mesh import local_device_mesh

    data = gen_uniform(10_000, seed=6)
    ss = SampleSort(local_device_mesh(2), JobConfig(exchange="hier"))
    m = _metered()
    np.testing.assert_array_equal(ss.sort(data, metrics=m), np.sort(data))
    assert any(">= 4 workers" in r.getMessage()
               for r in dsort_warnings.records)
    assert m.counters.get("hier_exchanges", 0) == 0


# ---- the fault contract ----------------------------------------------------


def _drill(data, hosts, victims, metrics):
    from dsort_tpu.scheduler import SpmdScheduler

    inj = FaultInjector()
    sched = SpmdScheduler(
        job=JobConfig(settle_delay_s=0.01, exchange="hier",
                      hier_hosts=hosts),
        injector=inj,
    )
    np.testing.assert_array_equal(sched.sort(data), np.sort(data))  # warm
    for w in victims:
        inj.fail_once(w, "ring")
    return sched.sort(data, metrics=metrics)


def test_scheduler_device_loss_reforms_within_host():
    """Losing devices of ONE host keeps the 2-host grouping: the re-plan
    rule lands on the same H, journaled as `hier_reform` after the
    `mesh_reform` — the §17 fault contract's first half.  Two victims so
    the 6 survivors still divide by 2 (an odd count would force the
    downgrade a real pod's fixed host slots would not)."""
    z = gen_zipf(1 << 16, a=1.3, seed=21)
    m = _metered()
    out = _drill(z, hosts=2, victims=[1, 2], metrics=m)
    np.testing.assert_array_equal(out, np.sort(z))
    types = m.journal.types()
    assert types.count("hier_reform") == 1
    assert (types.index("worker_dead") < types.index("mesh_reform")
            < types.index("hier_reform"))
    rf = next(e for e in m.journal.events() if e.type == "hier_reform")
    assert rf.fields["survivors"] == 6
    assert rf.fields["hosts_before"] == 2
    assert rf.fields["hosts_after"] == 2
    assert rf.fields["downgraded"] is False
    # The re-run on survivors planned a fresh two-level schedule.
    assert m.counters["hier_exchanges"] >= 1
    assert m.counters["mesh_reforms"] == 1
    assert_conformant(m.journal)


def test_scheduler_host_loss_replans_on_survivors():
    """THE acceptance drill: ALL of host 1's devices die mid-phase-two
    (the hook fires with the (H, H) legs planned and in flight).  The 6
    survivors no longer divide by 4, so the re-plan lands on H=3 — fewer
    hosts, still two-level, never a silent downgrade to the flat ring."""
    z = gen_zipf(1 << 16, a=1.3, seed=22)
    m = _metered()
    out = _drill(z, hosts=4, victims=[2, 3], metrics=m)  # host 1 of 4
    np.testing.assert_array_equal(out, np.sort(z))
    rf = next(e for e in m.journal.events() if e.type == "hier_reform")
    assert rf.fields["hosts_before"] == 4
    assert rf.fields["hosts_after"] == 3
    assert rf.fields["survivors"] == 6
    assert rf.fields["downgraded"] is False
    assert m.counters["hier_exchanges"] >= 1
    assert_conformant(m.journal)


# ---- the wave pipeline -----------------------------------------------------


def test_wave_hier_matches_oracle(tmp_path, devices):
    from dsort_tpu.models.wave_sort import ExternalWaveSort
    from dsort_tpu.parallel.mesh import local_device_mesh

    data = gen_zipf(30_000, a=1.3, dtype=np.int64, seed=23)
    s = ExternalWaveSort(
        local_device_mesh(8), wave_elems=6000, spill_dir=str(tmp_path),
        job_id="whier", exchange="hier", job=JobConfig(hier_hosts=2),
    )
    m = _metered()
    np.testing.assert_array_equal(s.sort(data, metrics=m), np.sort(data))
    # Every wave planned and journaled its own two-level schedule.
    assert m.counters["hier_exchanges"] == m.counters["waves_sorted"] > 0
    assert m.counters["dcn_bytes_saved"] > 0


# ---- the planner arm -------------------------------------------------------


def test_decide_exchange_hier_from_measured_topology():
    from dsort_tpu.obs.plan import replay_decision

    chosen, rejected = replay_decision("exchange", {
        "max_mean_ratio": 1.0, "num_workers": 8, "fused_ok": False,
        "redundancy": 1, "hosts": 2,
    })
    assert chosen == "hier"
    assert {r["value"] for r in rejected} == {"alltoall", "ring", "fused"}
    # 1 device/host leaves nothing to aggregate: fall through to skew.
    chosen, _ = replay_decision("exchange", {
        "max_mean_ratio": 3.0, "num_workers": 8, "hosts": 8,
    })
    assert chosen == "ring"
    # Redundancy still forces the flat ring (replica slots).
    chosen, _ = replay_decision("exchange", {
        "num_workers": 8, "hosts": 2, "redundancy": 2,
    })
    assert chosen == "ring"
    # Old journaled decisions (no hosts key) replay unchanged.
    chosen, _ = replay_decision("exchange", {
        "max_mean_ratio": 3.0, "num_workers": 8, "fused_ok": False,
    })
    assert chosen == "ring"


def test_autotune_single_slice_never_arms_hier(mesh8):
    """Planner-on, knob unset, single process: the planner must NOT
    reroute through the simulated 2-host fallback — only a REAL topology
    signal (explicit hier_hosts or a multi-process launch) arms hier."""
    data = gen_zipf(60_000, a=1.3, seed=24)
    m = _metered()
    ss = SampleSort(mesh8, JobConfig(autotune=True))
    np.testing.assert_array_equal(ss.sort(data, metrics=m), np.sort(data))
    dec = next(e for e in m.journal.events() if e.type == "plan_decision")
    assert dec.fields["policy"] == "exchange"
    assert dec.fields["inputs"]["hosts"] == 0
    assert dec.fields["chosen"] != "hier"
    # An explicit hier_hosts IS a real signal: the planner arms hier.
    m2 = _metered()
    ss2 = SampleSort(mesh8, JobConfig(autotune=True, hier_hosts=2))
    np.testing.assert_array_equal(ss2.sort(data, metrics=m2), np.sort(data))
    dec2 = next(e for e in m2.journal.events() if e.type == "plan_decision")
    assert dec2.fields["inputs"]["hosts"] == 2
    assert dec2.fields["chosen"] == "hier"
    assert m2.counters["hier_exchanges"] == 1


# ---- the dispatch_timeout_s policy -----------------------------------------


def test_decide_dispatch_timeout_headroom_and_floor():
    from dsort_tpu.obs.plan import (
        DISPATCH_TIMEOUT_HEADROOM,
        DISPATCH_TIMEOUT_MIN_S,
        replay_decision,
    )

    chosen, rejected = replay_decision("dispatch_timeout_s", {
        "current": 30.0, "p99_s": 0.25, "samples": 16,
    })
    assert chosen == round(0.25 * DISPATCH_TIMEOUT_HEADROOM, 3) == 2.0
    assert any(r["value"] == 30.0 for r in rejected)
    # The floor keeps a microsecond-fast fleet from a hair-trigger reap.
    chosen, _ = replay_decision("dispatch_timeout_s", {
        "current": 30.0, "p99_s": 0.001, "samples": 4,
    })
    assert chosen == DISPATCH_TIMEOUT_MIN_S
    # No samples yet: keep the current deadline and say so.
    chosen, rejected = replay_decision("dispatch_timeout_s", {
        "current": 30.0, "p99_s": 0.0, "samples": 0,
    })
    assert chosen == 30.0
    assert rejected[0]["value"] == "resize"


def test_planner_folds_job_dispatched_latencies():
    from dsort_tpu.obs.plan import DISPATCH_LATENCY_HISTORY, Planner

    pl = Planner()
    for lat in (0.1, 0.2, 0.4):
        pl.observe("job_dispatched", {"job_id": 1, "agent": "a",
                                      "accept_latency_s": lat})
    inputs = pl.dispatch_timeout_inputs(30.0)
    assert inputs["samples"] == 3
    assert 0.1 <= inputs["p99_s"] <= 0.4
    assert inputs["current"] == 30.0
    # Bounded window + snapshot round-trip.
    assert len(Planner().state_dict()["dispatch_latencies"]) == 0
    for _ in range(2 * DISPATCH_LATENCY_HISTORY):
        pl.observe("job_dispatched", {"accept_latency_s": 0.05})
    assert (len(pl.state_dict()["dispatch_latencies"])
            == DISPATCH_LATENCY_HISTORY)
    # decide journals the replayable record.
    from dsort_tpu.obs.plan import replay_decision

    m = _metered()
    chosen = pl.decide("dispatch_timeout_s",
                       pl.dispatch_timeout_inputs(30.0), metrics=m)
    ev = next(e for e in m.journal.events() if e.type == "plan_decision")
    assert ev.fields["policy"] == "dispatch_timeout_s"
    assert replay_decision("dispatch_timeout_s",
                           ev.fields["inputs"])[0] == chosen


# ---- terasort conf parity (satellite: CLI plumb-through) --------------------


def test_terasort_exchange_conf_parity_and_precedence(tmp_path,
                                                      dsort_warnings):
    conf = tmp_path / "job.conf"
    conf.write_text("EXCHANGE=hier\nHIER_HOSTS=2\n")
    cfg = SortConfig.from_conf_file(str(conf))
    assert cfg.job.exchange == "hier" and cfg.job.hier_hosts == 2

    from dsort_tpu.cli import main as cli_main
    from dsort_tpu.data.ingest import read_terasort_file

    inp = str(tmp_path / "in.bin")
    outp = str(tmp_path / "out.bin")
    assert cli_main(["gen", "2000", "-o", inp, "--dist", "terasort"]) == 0
    # The conf EXCHANGE key reaches the record job: the kv plane's
    # keys-only downgrade warning names the hier knob it received.
    assert cli_main(["terasort", inp, "-o", outp, "--workers", "8",
                     "--conf", str(conf)]) == 0
    assert any("keys-only" in r.getMessage()
               for r in dsort_warnings.records)
    dsort_warnings.clear()
    # An explicit --exchange flag wins over the conf key: no hier warning.
    assert cli_main(["terasort", inp, "-o", outp, "--workers", "8",
                     "--conf", str(conf), "--exchange", "ring"]) == 0
    assert not any("keys-only" in r.getMessage()
                   for r in dsort_warnings.records)
    k, _ = read_terasort_file(outp)
    assert np.array_equal(k, np.sort(k))


# ---- scale: splitter quality + capacity rungs at P=128-512 -----------------


def _scale_drill(p: int, n_per_dev: int, seed: int):
    """Dryrun the host-side plan math at pod widths: oversampled
    splitters on zipf keys, the realized (P, P) histogram, then every
    valid host grouping's hier plan — no P-device backend involved."""
    rng = np.random.default_rng(seed)
    n = p * n_per_dev
    # Uniform keys isolate SAMPLING error — the thing that grows with P.
    # (Zipf's mass sits on a handful of duplicate values no splitter can
    # separate; its skew is exercised by the capacity drill below.)
    data = gen_uniform(n, dtype=np.int64, seed=seed)
    # SampleSort's splitter recipe, host-side: oversample 32x per worker,
    # equal-rank picks.
    sample = np.sort(rng.choice(data, size=32 * p, replace=False))
    splitters = sample[np.arange(1, p) * 32]
    shards = data.reshape(p, n_per_dev)
    hist = np.stack([
        np.bincount(np.searchsorted(splitters, shard, side="right"),
                    minlength=p)
        for shard in shards
    ])
    # Splitter quality: destination totals stay within a constant factor
    # of ideal balance even at pod width (BASELINE's oversample bound).
    totals = hist.sum(axis=0)
    assert totals.sum() == n
    assert totals.max() / (n / p) < 4.0
    # Capacity rungs: every plan cap is 8-aligned, covers its measured
    # max, and the ladder stays bounded (recompile-bound doctrine).
    caps = ring_caps(hist, n_per_dev, p)
    assert all(c % 8 == 0 for c in caps)
    assert len(set(caps)) <= len(ladder_rungs(n_per_dev)) + 1
    # Capacity coverage at width, on the realized hist AND a heavily
    # skewed synthetic one (zipf-weighted rows): every phase's cap covers
    # its measured max — the no-retry doctrine's precondition.
    skewed = _synthetic_hist(p, n_per_dev, seed=seed + 1)
    for h_src in (hist, skewed):
        h_caps = ring_caps(h_src, n_per_dev, p)
        for hosts in (h for h in (4, 8, 16) if p % h == 0 and p // h >= 2):
            plan = hier_plan(h_src, n_per_dev, p, hosts)
            d = p // hosts
            dev_host = h_src.reshape(p, hosts, d).sum(axis=2)
            host_dev = h_src.reshape(hosts, d, p).sum(axis=1)
            mat = host_matrix(h_src, hosts)
            assert plan.agg_cap >= dev_host.max()
            assert plan.scatter_cap >= host_dev.max()
            for shift in range(1, hosts):
                assert plan.leg_caps[shift] >= max(
                    mat[g, (g + shift) % hosts] for g in range(hosts)
                )
            # The DCN claim holds at width: aggregated host transfers
            # never exceed the flat ring's cross-host bytes, and under
            # skew they strictly beat them (the flat ring pads every
            # step to its diagonal max).
            dcn, _ = hier_wire_bytes(plan, 8)
            flat = ring_dcn_bytes(h_caps, 8, p, hosts)
            assert dcn <= flat
            if h_src is skewed:
                assert dcn < flat


@pytest.mark.parametrize("p", [128, 256])
def test_scale_splitters_and_caps(p):
    _scale_drill(p, n_per_dev=512, seed=p)


@pytest.mark.slow
def test_scale_splitters_and_caps_512():
    _scale_drill(512, n_per_dev=512, seed=512)


# ---- the bench gate (= make hier-smoke) ------------------------------------


def test_cli_bench_hier_ab_gate(capsys):
    """Tier-1 gate for `make hier-smoke`: flat ring vs hier at every
    simulated topology, bit-identical with a MEASURED DCN reduction, plus
    the device-loss (grouping kept) and host-loss (grouping re-planned)
    drills."""
    from dsort_tpu import cli

    rc = cli.main(["bench", "--hier-ab", "--n", "65536", "--reps", "1"])
    out = capsys.readouterr().out
    rows = [json.loads(ln) for ln in out.splitlines() if ln.startswith("{")]
    assert rc == 0
    by_metric = {r["metric"]: r for r in rows}
    h2 = by_metric["hier_exchange_zipf_65536_h2"]
    h4 = by_metric["hier_exchange_zipf_65536_h4"]
    for r in (h2, h4):
        assert r["bit_identical"] is True
        assert 0 < r["dcn_bytes"] < r["ring_dcn_bytes"]
        assert r["dcn_reduction_frac"] > 0
        assert r["hier_exchanges"] == 1
    dev = by_metric["hier_device_loss_drill_zipf_65536"]
    assert dev["bit_identical"] is True
    assert dev["hosts_before"] == dev["hosts_after"] == 2
    assert dev["downgraded"] is False
    host = by_metric["hier_host_loss_drill_zipf_65536"]
    assert host["bit_identical"] is True
    assert host["hosts_before"] == 4
    assert 2 <= host["hosts_after"] < 4
    assert host["downgraded"] is False


def test_cli_bench_hier_ab_is_exclusive():
    from dsort_tpu import cli

    with pytest.raises(SystemExit, match="its own benchmark"):
        cli.main(["bench", "--hier-ab", "--suite"])


# ---- the shipped artifact ---------------------------------------------------


def test_bench_r18_artifact_checks_and_compares():
    """BENCH_r18.jsonl: --check clean, the hier rows join the trajectory
    as 'added' vs r16, and the headline holds: bit-identical two-level
    exchange with a measured DCN-byte reduction at both topologies, both
    fault drills re-forming correctly."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "bench", os.path.join(REPO, "bench.py")
    )
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    r18 = os.path.join(REPO, "BENCH_r18.jsonl")
    assert bench.check_artifact(r18) == []
    rows = bench.compare_artifacts(os.path.join(REPO, "BENCH_r16.jsonl"), r18)
    added = {r["metric"] for r in rows if r["class"] == "added"}
    assert any(m.startswith("hier_exchange_zipf") for m in added)
    assert any(m.startswith("hier_host_loss_drill") for m in added)
    with open(r18) as f:
        lines = [json.loads(ln) for ln in f if ln.strip()]
    for l in lines:
        if l.get("metric", "").startswith("hier_exchange_zipf"):
            assert l["bit_identical"] is True
            assert l["dcn_bytes"] < l["ring_dcn_bytes"]
            assert l["dcn_reduction_frac"] > 0.4
        if l.get("metric", "").startswith("hier_host_loss_drill"):
            assert l["hosts_after"] < l["hosts_before"]
            assert l["downgraded"] is False and l["bit_identical"] is True


# ---- docs are part of the contract ------------------------------------------


def test_architecture_documents_hier_plane():
    """§17's contract is test-enforced like §7–§16: the three phases, the
    plan vocabulary, the fault contract and the registries all appear."""
    arch = open(os.path.join(REPO, "ARCHITECTURE.md"),
                encoding="utf-8").read()
    assert "## 17. Hierarchical exchange plane" in arch
    for term in ("resolve_hier_hosts", "HierPlan", "host_matrix",
                 "hier_plan", "ring_dcn_bytes", "`hier_reform`",
                 "hier_exchange_plan", "hier_exchange_leg",
                 "hier_exchanges", "dcn_bytes_on_wire",
                 "intra_host_bytes_on_wire", "dcn_bytes_saved",
                 "no-retry doctrine", "hier-smoke", "--hier-ab",
                 "BENCH_r18.jsonl", "owner", "ring_caps"):
        assert term in arch, f"§17 must explain {term}"
