"""Validation subsystem tests (models/validate.py — the valsort role).

The reference's validation story is one golden pair checked by eye (SURVEY.md
§4); here order + permutation proof must hold for arbitrary jobs, streamed.
"""

import numpy as np
import pytest

from dsort_tpu.data.ingest import (
    gen_terasort_file,
    write_ints_file,
)
from dsort_tpu.models.validate import (
    checksum_ints_file,
    validate_ints_file,
    validate_terasort_file,
)
from tests.test_cli_checkpoint import cli_main  # shared CLI harness import


def test_ints_sorted_and_permutation(tmp_path):
    rng = np.random.default_rng(1)
    data = rng.integers(-(2**31), 2**31 - 1, 10_000).astype(np.int32)
    inp, outp = tmp_path / "in.txt", tmp_path / "out.txt"
    write_ints_file(inp, data)
    write_ints_file(outp, np.sort(data))
    rep = validate_ints_file(outp)
    assert rep.sorted_ok and rep.records == 10_000
    n_in, sum_in = checksum_ints_file(inp)
    assert (n_in, sum_in) == (rep.records, rep.checksum)


def test_ints_detects_unsorted_and_tamper(tmp_path):
    data = np.arange(1000, dtype=np.int32)
    bad = data.copy()
    bad[500], bad[501] = bad[501], bad[500]
    p = tmp_path / "bad.txt"
    write_ints_file(p, bad)
    rep = validate_ints_file(p)
    assert not rep.sorted_ok and rep.first_violation == 501
    # tampering one value changes the multiset checksum
    q = tmp_path / "tampered.txt"
    t = np.sort(data)
    t[7] += 1
    write_ints_file(q, t)
    assert validate_ints_file(q).checksum != checksum_ints_file(p)[1]


def test_terasort_validate_roundtrip(tmp_path):
    inp, outp = tmp_path / "t.bin", tmp_path / "t_out.bin"
    gen_terasort_file(inp, 3_000, seed=2)
    assert cli_main(["terasort", str(inp), "-o", str(outp), "--workers", "8"]) == 0
    rep = validate_terasort_file(outp)
    assert rep.sorted_ok and rep.records == 3_000
    assert not validate_terasort_file(inp).sorted_ok  # random input isn't sorted
    # permutation proof input <-> output
    from dsort_tpu.models.validate import checksum_terasort_file

    assert checksum_terasort_file(inp) == (rep.records, rep.checksum)


def test_terasort_boundary_violation_detected(tmp_path, monkeypatch):
    # Order break exactly at a streamed chunk boundary must be caught.
    import dsort_tpu.models.validate as v

    monkeypatch.setattr(v, "_CHUNK_RECORDS", 4)
    recs = np.zeros((8, 100), dtype=np.uint8)
    for i in range(8):
        recs[i, 0] = i
    recs[[3, 4]] = recs[[4, 3]]  # records 3/4 swap: violation at index 4
    p = tmp_path / "b.bin"
    recs.tofile(p)
    rep = v.validate_terasort_file(p)
    assert not rep.sorted_ok
    assert rep.first_violation == 4


def test_empty_and_single(tmp_path):
    p = tmp_path / "e.txt"
    p.write_text("")
    rep = validate_ints_file(p)
    assert rep.ok and rep.records == 0
    p.write_text("42\n")
    rep = validate_ints_file(p)
    assert rep.ok and rep.records == 1


def test_cli_validate_exit_codes(tmp_path):
    data = np.arange(100, dtype=np.int32)
    good, bad, orig = tmp_path / "g.txt", tmp_path / "b.txt", tmp_path / "o.txt"
    write_ints_file(orig, data[::-1].copy())
    write_ints_file(good, data)
    write_ints_file(bad, data[::-1].copy())
    assert cli_main(["validate", str(good), "--against", str(orig)]) == 0
    assert cli_main(["validate", str(bad)]) == 1
    # dropped record -> permutation check fails even though sorted
    write_ints_file(good, data[:-1])
    assert cli_main(["validate", str(good), "--against", str(orig)]) == 1


def test_python_fnv_fallback_matches_native():
    from dsort_tpu.models.validate import _fnv_multiset_py
    from dsort_tpu.runtime import native

    if not native.available():
        pytest.skip("native library unavailable")
    rng = np.random.default_rng(31)
    buf = rng.integers(0, 256, (500, 100), dtype=np.uint8)
    assert _fnv_multiset_py(buf, 500, 100) == native.fnv_multiset(buf, 500, 100)
    ints = rng.integers(-(2**31), 2**31 - 1, 777).astype(np.int32)
    assert _fnv_multiset_py(ints, 777, 4) == native.fnv_multiset(ints, 777, 4)


def test_binary_key_file_validate_roundtrip(tmp_path):
    """gen --format bin -> sort -> validate --binary: the 1B-key artifact
    flow at test scale, incl. the chunk-boundary order check."""
    import dsort_tpu.models.validate as V
    from dsort_tpu.data.ingest import gen_uniform_bin_file

    src = tmp_path / "in.bin"
    out = tmp_path / "out.bin"
    gen_uniform_bin_file(src, 100_000, dtype=np.int32, seed=5, chunk=8192)
    data = np.fromfile(src, dtype=np.int32)
    assert len(data) == 100_000
    np.sort(data).tofile(out)
    # stream in small chunks so boundary comparisons actually engage
    old = V._CHUNK_ELEMS
    V._CHUNK_ELEMS = 4096
    try:
        rep = V.validate_bin_file(out, dtype=np.int32)
        assert rep.ok and rep.records == 100_000
        n_in, sum_in = V.checksum_bin_file(src, dtype=np.int32)
        assert (n_in, sum_in) == (rep.records, rep.checksum)
        # an out-of-order boundary is caught
        bad = np.sort(data)
        bad[4096], bad[4095] = bad[4095], bad[4096]
        if bad[4096] == bad[4095]:
            bad[4096] = bad[4095] - 1
        bad.tofile(out)
        rep2 = V.validate_bin_file(out, dtype=np.int32)
        assert not rep2.ok and rep2.first_violation == 4096
        # a dropped key fails the permutation proof
        np.sort(data)[:-1].tofile(out)
        rep3 = V.validate_bin_file(out, dtype=np.int32)
        assert rep3.ok and rep3.checksum != sum_in
    finally:
        V._CHUNK_ELEMS = old


def test_cli_gen_bin_external_validate(tmp_path):
    """CLI surface: dsort gen --format bin -> external -> validate --binary."""
    from dsort_tpu import cli

    src, out = str(tmp_path / "a.bin"), str(tmp_path / "b.bin")
    assert cli.main(["gen", "50000", "-o", src, "--format", "bin"]) == 0
    assert cli.main([
        "external", src, "-o", out, "--run-elems", "8192",
        "--spill-dir", str(tmp_path / "spill"), "--job-id", "binjob",
    ]) == 0
    assert cli.main(["validate", out, "--binary", "--against", src]) == 0
