"""SPMD semantics verifier tests (`dsort_tpu.analysis.spmd`, DS12xx/DS13xx).

Four layers of gates:

1. Fixture pairs: ``bad_spmd.py``/``bad_caps.py`` must produce exactly the
   pinned per-code counts; the ``good_*`` near-miss twins produce none.
2. Seeded-mutation gates (the cross-check contract): re-introducing an
   inverted ring shift, deleting the hier DCN re-pack hop, or knocking
   ``ring_step_quantum`` off the 8 grid in a COPY of the real tree must
   each be caught statically — and the unmutated copy stays clean, as does
   a copy whose ``SPMD_CONTRACT`` is deleted (no-vacuous-pass: the
   registry minima make the missing declaration itself a finding).
3. Differential: the restricted evaluator must agree with the imported
   functions on the bounded grids (the proofs are about THIS arithmetic).
4. Engine satellites: the cache key tracks the spmd registry's required
   sources, SARIF output round-trips, ``--stats`` accounts per checker,
   and a warm cached whole-tree lint stays interactive.
"""

import ast
import json
import os
import shutil
import time
from collections import Counter

from dsort_tpu.analysis import (
    LintConfig,
    LintStats,
    format_sarif,
    lint_paths,
    load_config,
)
from dsort_tpu.analysis.checkers import all_checkers
from dsort_tpu.analysis.checkers.caps import CapsChecker
from dsort_tpu.analysis.checkers.spmd import SpmdChecker
from dsort_tpu.analysis.engine import ResultCache
from dsort_tpu.analysis.spmd import Evaluator, extract_functions

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(REPO, "tests", "data", "lint")


def fixture(name: str) -> str:
    return os.path.join(FIXTURES, name)


def run_fixture(name: str):
    # Fixtures live outside the checkers' default dsort_tpu/ scope (the
    # shipped-tree gate must not see them), so tests rescope.
    cfg = LintConfig(root=REPO)
    return lint_paths(
        [fixture(name)],
        cfg,
        checkers=[SpmdChecker(scope=("*",)), CapsChecker(scope=("*",))],
    )


# -- fixture pairs -----------------------------------------------------------


def test_bad_spmd_fixture_counts():
    counts = Counter(d.code for d in run_fixture("bad_spmd.py"))
    assert counts == {
        "DS1200": 1, "DS1201": 3, "DS1202": 2, "DS1203": 1, "DS1204": 1,
    }


def test_good_spmd_fixture_clean():
    assert run_fixture("good_spmd.py") == []


def test_bad_caps_fixture_counts():
    counts = Counter(d.code for d in run_fixture("bad_caps.py"))
    assert counts == {"DS1300": 2, "DS1301": 1, "DS1302": 1, "DS1303": 3}


def test_good_caps_fixture_clean():
    assert run_fixture("good_caps.py") == []


def test_host_plane_collective_flagged(tmp_path):
    src = tmp_path / "mod.py"
    src.write_text(
        'SPMD_CONTRACT = {"plane": "host"}\n'
        "import jax\n\n\n"
        "def f(x, axis):\n"
        "    return jax.lax.psum(x, axis)\n"
    )
    diags = lint_paths(
        [str(src)],
        LintConfig(root=REPO),
        checkers=[SpmdChecker(scope=("*",))],
    )
    assert [d.code for d in diags] == ["DS1202"]


# -- seeded-mutation gates on a copy of the real tree ------------------------

#: Files the copied verification tree needs: the registry, the mesh-axis
#: vocabulary source, and the module under mutation.
_TREE_FILES = (
    "dsort_tpu/analysis/spmd/registry.py",
    "dsort_tpu/config.py",
    "dsort_tpu/parallel/exchange.py",
)


def _copy_tree(tmp_path, old=None, new=None):
    for rel in _TREE_FILES:
        dst = tmp_path / rel
        dst.parent.mkdir(parents=True, exist_ok=True)
        shutil.copyfile(os.path.join(REPO, rel), dst)
    ex = tmp_path / "dsort_tpu" / "parallel" / "exchange.py"
    if old is not None:
        text = ex.read_text()
        assert old in text, f"mutation anchor drifted: {old!r}"
        ex.write_text(text.replace(old, new, 1))
    return str(ex)


def _lint_copy(tmp_path):
    return lint_paths(
        [str(tmp_path / "dsort_tpu" / "parallel" / "exchange.py")],
        LintConfig(root=str(tmp_path)),
        checkers=[SpmdChecker(), CapsChecker()],
    )


def test_clean_copy_has_no_findings(tmp_path):
    _copy_tree(tmp_path)
    assert _lint_copy(tmp_path) == []


def test_mutation_inverted_ring_shift_is_caught(tmp_path):
    _copy_tree(
        tmp_path,
        "(i, (i + k) % num_workers)",
        "(i, (i - k) % num_workers)",
    )
    diags = _lint_copy(tmp_path)
    assert "DS1201" in {d.code for d in diags}
    assert any("_ring_perm" in d.message for d in diags)


def test_mutation_deleted_repack_hop_is_caught(tmp_path):
    _copy_tree(tmp_path, "_pad_run(rbuf, agg_total, sent)", "rbuf")
    diags = _lint_copy(tmp_path)
    assert "DS1302" in {d.code for d in diags}
    assert any("_hier_exchange_shard" in d.message for d in diags)


def test_mutation_offgrid_quantum_is_caught(tmp_path):
    _copy_tree(
        tmp_path,
        "return max(-(-max(n_local // (8 * num_workers), 8) // 8) * 8, 8)",
        "return max(n_local // (8 * num_workers), 12)",
    )
    diags = _lint_copy(tmp_path)
    codes = {d.code for d in diags}
    assert "DS1303" in codes
    assert any("ring_step_quantum" in d.message for d in diags)


def test_deleted_contract_is_itself_a_finding(tmp_path):
    # No-vacuous-pass: silencing the proofs by removing the declaration
    # they check against is a DS1200 (the registry minima pin the file).
    _copy_tree(tmp_path, "SPMD_CONTRACT = {", "SPMD_CONTRACT_DISABLED = {")
    diags = _lint_copy(tmp_path)
    assert "DS1200" in {d.code for d in diags}


def test_shipped_tree_has_no_spmd_findings():
    # The no-findings gate: the real tree PASSES its own proofs (and the
    # lint-clean CI gate in test_lint.py keeps every other checker green).
    diags = lint_paths(
        [os.path.join(REPO, "dsort_tpu")],
        load_config(REPO),
        checkers=[SpmdChecker(), CapsChecker()],
    )
    assert diags == []


# -- differential: restricted evaluator vs the imported functions ------------


def test_symeval_matches_real_functions():
    from dsort_tpu.parallel import exchange as real

    with open(
        os.path.join(REPO, "dsort_tpu", "parallel", "exchange.py"),
        encoding="utf-8",
    ) as f:
        ev = Evaluator(extract_functions(ast.parse(f.read())))
    for p in (1, 2, 3, 4, 6, 8):
        for n in (8, 100, 4096):
            assert ev.call("ring_step_quantum", [n, p]) == (
                real.ring_step_quantum(n, p)
            )
            for m in (0, 1, n // 2, n):
                assert ev.call("_quantize_cap", [m, n, p]) == (
                    real._quantize_cap(m, n, p)
                )
        for k in range(p):
            assert ev.call("_ring_perm", [p, k]) == real._ring_perm(p, k)
    assert ev.call("ladder_rungs", [4096]) == real.ladder_rungs(4096)
    assert ev.call("parity_slots", [3]) == real.parity_slots(3)


# -- engine satellites -------------------------------------------------------


def test_cache_key_tracks_spmd_required_sources(tmp_path):
    _copy_tree(tmp_path)
    cfg = load_config(str(tmp_path))
    checkers = all_checkers()
    k1 = ResultCache._config_key(cfg, checkers)
    ex = tmp_path / "dsort_tpu" / "parallel" / "exchange.py"
    ex.write_text(ex.read_text() + "\n# cap-ladder tweak\n")
    k2 = ResultCache._config_key(cfg, checkers)
    assert k1 != k2, "editing a required SPMD source must invalidate cache"
    # A file the registry does NOT require never participates in the key.
    (tmp_path / "dsort_tpu" / "other.py").write_text("X = 1\n")
    assert ResultCache._config_key(cfg, checkers) == k2


def test_sarif_round_trip():
    diags = run_fixture("bad_spmd.py")
    assert diags  # a round-trip over nothing proves nothing
    doc = json.loads(format_sarif(diags))
    assert doc["version"] == "2.1.0"
    run = doc["runs"][0]
    rules = {r["id"] for r in run["tool"]["driver"]["rules"]}
    # The full catalog ships as driver rules, findings or not.
    assert {
        "DS1200", "DS1201", "DS1202", "DS1203", "DS1204",
        "DS1300", "DS1301", "DS1302", "DS1303",
    } <= rules
    got = {
        (
            r["ruleId"],
            r["locations"][0]["physicalLocation"]["artifactLocation"]["uri"],
            r["locations"][0]["physicalLocation"]["region"]["startLine"],
            r["locations"][0]["physicalLocation"]["region"]["startColumn"] - 1,
            r["message"]["text"],
            r["level"],
        )
        for r in run["results"]
    }
    want = {
        (d.code, d.path, d.line, d.col, d.message, d.severity) for d in diags
    }
    assert got == want


def test_stats_accounting():
    stats = LintStats()
    lint_paths(
        [fixture("bad_spmd.py")],
        LintConfig(root=REPO),
        checkers=[SpmdChecker(scope=("*",)), CapsChecker(scope=("*",))],
        stats=stats,
    )
    assert stats.files == 1 and stats.cached == 0
    row = stats.checkers["spmd"]
    assert row["findings"] == 8
    assert row["files"] == 1
    assert row["seconds"] >= 0.0
    assert not row["project"]
    table = stats.format()
    assert "spmd" in table and "caps" in table and "checker" in table


def test_warm_cached_whole_tree_lint_is_fast(tmp_path):
    # The interactivity pin: a warm cached `make lint` must stay in
    # interactive territory (cold measured ~6s, warm ~1.5s in-process; the
    # bound leaves CI headroom without letting the cache silently rot).
    cfg = load_config(REPO)
    cache = str(tmp_path / "lint-cache.json")
    paths = [os.path.join(REPO, "dsort_tpu")]
    lint_paths(paths, cfg, cache_path=cache)  # cold: populate
    stats = LintStats()
    t0 = time.perf_counter()
    diags = lint_paths(paths, cfg, cache_path=cache, stats=stats)
    warm = time.perf_counter() - t0
    assert diags == []
    assert stats.files > 0 and stats.cached == stats.files
    assert warm < 4.0, f"warm cached lint took {warm:.2f}s"
