"""Introspection-plane tests (ISSUE 9, ARCHITECTURE §9).

Covers the three instruments and the satellites: the compile/cost/HBM
ledger (journal == live ledger == /metrics scrape, VariantCache entries
carried), skew & straggler attribution (skew_report fields, memwatch
watermarks), the journal-native analyzer (merged 2-process ground truth
with an injected-latency straggler; a REAL in-suite serve session), SLO
admission shedding with recovery, journal rotation + report stitching,
the analyze-smoke gate, and the §9 schema enforcement.
"""

import json
import os
import time

import numpy as np
import pytest

from dsort_tpu.obs import (
    LEDGER,
    LEDGER_EVENT_FIELDS,
    MemWatch,
    Telemetry,
    VERDICT_KEYS,
    analyze_records,
    format_analysis,
    ledger_from_journal,
    parse_prometheus_text,
    variant_label,
)
from dsort_tpu.obs.merge import (
    group_rotated,
    merge_records,
    read_journal,
    read_journal_set,
    rotated_set,
)
from dsort_tpu.serve.admission import ADMISSION_REASONS
from dsort_tpu.utils.events import EVENT_TYPES, EventLog
from dsort_tpu.utils.metrics import Metrics

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# -- compile/cost/HBM ledger -------------------------------------------------


def test_variant_label_flattens_and_sanitizes():
    assert variant_label(("fused", 81920, "int32", "auto")) == (
        "fused|81920|int32|auto"
    )
    # Nested tuples (ring caps) flatten with '-'; characters the minimal
    # Prometheus parser would choke on (commas, spaces) become '_'.
    label = variant_label(("spmd_ring", 8, (16, 24), "a b,c"))
    assert label == "spmd_ring|8|16-24|a_b_c"
    assert "," not in label and " " not in label


def test_ledger_aggregates_and_journal_replay_matches():
    from dsort_tpu.obs.prof import CompileLedger

    led = CompileLedger()
    led.record(("fused", 64, "int32", "lax"), 0.25,
               cost=[{"flops": 100.0, "bytes accessed": 640.0}],
               mem=None)
    led.record(("fused", 64, "int32", "lax"), 0.15,
               cost={"flops": 100.0, "bytes accessed": 640.0}, mem=None)
    snap = led.snapshot()
    e = snap["fused|64|int32|lax"]
    assert e["compiles"] == 2 and e["compile_s"] == pytest.approx(0.40)
    assert e["flops"] == 100.0
    jl = EventLog()
    m = Metrics(journal=jl)
    assert led.drain_to(m) == 2
    assert led.pending() == 0
    # A metrics with no journal AND no taps must not swallow the queue.
    led.record(("x",), 0.1)
    assert led.drain_to(Metrics()) == 0 and led.pending() == 1
    replay = ledger_from_journal([ev.to_dict() for ev in jl.events()])
    assert replay == snap
    for field in LEDGER_EVENT_FIELDS:
        assert all(field in ev.fields for ev in jl.events()
                   if ev.type == "variant_compiled")


def test_instrumented_jit_times_real_compile(devices):
    import jax
    import jax.numpy as jnp

    from dsort_tpu.obs.prof import CompileLedger, LedgeredJit

    led = CompileLedger()
    fn = LedgeredJit(
        jax.jit(lambda x: jnp.sort(x)), lambda *a: ("t", a[0].shape[0]),
        ledger=led,
    )
    x = np.arange(4096, dtype=np.int32)[::-1].copy()
    out = np.asarray(fn(x))
    assert np.array_equal(out, np.sort(x))
    np.asarray(fn(x))  # repeat call: no second compile
    snap = led.snapshot()
    e = snap["t|4096"]
    assert e["compiles"] == 1
    assert e["compile_s"] > 0
    assert e["peak_hbm_bytes"] > 0
    assert e["output_hbm_bytes"] >= x.nbytes


def test_variant_cache_entries_carry_ledger_scrape_equals_journal(devices):
    """Acceptance: every VariantCache entry carries compile_s / flops /
    peak_hbm_bytes in BOTH the journal and a /metrics scrape, and the
    scrape equals the journal replay."""
    from dsort_tpu.models.pipelines import _fused_small_fn, pad_rung
    from dsort_tpu.serve.variants import VariantCache, fused_variant_key

    LEDGER.reset()
    _fused_small_fn.cache_clear()  # force fresh compiles into the ledger
    cache = VariantCache()
    jl = EventLog()
    m = Metrics(journal=jl)
    keys = set()
    for n in (1000, 5000, 1000):  # repeat size: cache hit, ONE compile
        key = fused_variant_key(n, "int32", "lax")
        keys.add(key)
        fn = cache.get_or_build(
            key,
            lambda n=n: _fused_small_fn(pad_rung(n), "int32", "lax"),
            metrics=m,
        )
        buf = np.zeros(pad_rung(n), np.int32)
        np.asarray(fn(buf, np.int32(n)))
    LEDGER.drain_to(m)
    records = [ev.to_dict() for ev in jl.events()]
    truth = ledger_from_journal(records)
    assert truth == LEDGER.snapshot()
    for key in keys:
        e = truth[variant_label(key)]
        assert e["compile_s"] > 0
        assert "flops" in e and e["flops"] >= 0
        assert e["peak_hbm_bytes"] > 0
    parsed = parse_prometheus_text(Telemetry().render_prometheus())
    for label, e in truth.items():
        lab = (("variant", label),)
        assert parsed[("dsort_variant_compile_seconds", lab)] == (
            pytest.approx(e["compile_s"], rel=1e-4)
        )
        assert parsed[("dsort_variant_compiles", lab)] == e["compiles"]
        assert parsed[("dsort_variant_flops", lab)] == (
            pytest.approx(e["flops"], rel=1e-4)
        )
        assert parsed[("dsort_variant_peak_hbm_bytes", lab)] == (
            pytest.approx(e["peak_hbm_bytes"], rel=1e-4)
        )


# -- skew & memwatch ---------------------------------------------------------


def test_skew_stats_fields_and_imbalance():
    from dsort_tpu.parallel.exchange import skew_stats

    hist = np.full((4, 4), 10, np.int32)
    # two sources both ship hot buckets to device 2: the RECEIVE side is
    # the concentrated one — device 2 is the predicted merge gate
    hist[1, 2] = 40
    hist[3, 2] = 40
    s = skew_stats(hist, 4)
    assert s["max_bucket"] == 40
    assert s["max_mean_ratio"] == pytest.approx(40 / hist.mean(), rel=1e-3)
    assert s["recv_argmax"] == 2
    assert s["recv_load"][2] == 100 and sum(s["send_load"]) == int(hist.sum())
    assert s["recv_imbalance"] > s["send_imbalance"] >= 1.0
    uniform = skew_stats(np.full((4, 4), 10, np.int32), 4)
    assert uniform["max_mean_ratio"] == 1.0


def test_memwatch_tap_emits_watermarks_at_phase_boundaries():
    snaps = iter(range(100))

    def fake_snapshot():
        return {"bytes_in_use": 1000 + next(snaps), "max_device_bytes": 500,
                "peak_bytes": 0, "devices": 2, "source": "fake"}

    jl = EventLog()
    m = Metrics(journal=jl)
    MemWatch(snapshot_fn=fake_snapshot).attach(m)
    from dsort_tpu.utils.metrics import PhaseTimer

    with PhaseTimer(m).phase("partition"):
        pass
    marks = [e for e in jl.events() if e.type == "hbm_watermark"]
    assert [e.fields["edge"] for e in marks] == ["start", "end"]
    assert all(e.fields["phase"] == "partition" for e in marks)
    assert m.counters["hbm_watermarks"] == 2
    # the tap never recurses into itself: exactly 2 watermarks, no more
    assert len(jl.events()) == 4  # phase_start/end + 2 watermarks


# -- journal-native analyzer -------------------------------------------------


def _proc_journal(wall_base, phases, jobs=(), tenant="default"):
    """Synthetic one-process journal mirroring the multihost emitters:
    clock_sync + phase spans + job boundaries on a private mono base."""
    mono = wall_base % 1000.0  # distinct mono base per process
    recs = [{"seq": 0, "t": wall_base, "mono": mono, "type": "clock_sync",
             "process": int(wall_base) % 7}]
    t = 0.01
    for job, n_keys in jobs:
        recs.append({"seq": len(recs), "t": wall_base + t, "mono": mono + t,
                     "type": "job_start", "job": job, "n_keys": n_keys,
                     "tenant": tenant})
    for phase, sec in phases:
        recs.append({"seq": len(recs), "t": wall_base + t, "mono": mono + t,
                     "type": "phase_start", "phase": phase})
        t += sec
        recs.append({"seq": len(recs), "t": wall_base + t, "mono": mono + t,
                     "type": "phase_end", "phase": phase,
                     "seconds": round(sec, 6)})
    for job, n_keys in jobs:
        recs.append({"seq": len(recs), "t": wall_base + t, "mono": mono + t,
                     "type": "job_done", "job": job, "n_keys": n_keys,
                     "counters": {"exchange_bytes_on_wire": 1 << 20}})
    return recs


def test_analyze_merged_multihost_names_straggler_and_critical_path():
    """Acceptance: a merged 2-process journal with an injected-latency
    straggler — the verdict names the straggler process and the
    critical-path phase, and the JSON matches journal ground truth."""
    fast = _proc_journal(
        1000.0, [("partition", 0.01), ("spmd_sort", 0.05), ("assemble", 0.01)],
        jobs=[(1, 1 << 20)],
    )
    slow = _proc_journal(  # the injected latency: 6x the spmd_sort phase
        1000.0, [("partition", 0.01), ("spmd_sort", 0.30), ("assemble", 0.01)],
        jobs=[(1, 1 << 20)],
    )
    merged = merge_records([fast, slow])
    v = analyze_records(merged)
    assert v["straggler"]["name"] == "p1"
    assert v["critical_src"] == "p1"
    assert v["critical_phase"] == "spmd_sort"
    assert v["dominant_phase"] == "spmd_sort"
    assert "spmd_sort" in v["straggler"]["phase_excess_s"]
    assert v["straggler"]["phase_excess_s"]["spmd_sort"] == (
        pytest.approx(0.25, abs=1e-6)
    )
    # JSON verdict == journal ground truth, independently derived.
    truth_phase = {}
    for r in merged:
        if r["type"] == "phase_end":
            truth_phase[(r["src"], r["phase"])] = (
                truth_phase.get((r["src"], r["phase"]), 0.0) + r["seconds"]
            )
    for (src, phase), sec in truth_phase.items():
        assert v["phases"][f"p{src}"][phase] == pytest.approx(sec)
    assert v["wire"]["bytes_on_wire"] == 2 * (1 << 20)
    assert v["splits"]["phase_wall_s"] == pytest.approx(
        sum(truth_phase.values())
    )
    # the verdict is JSON-able end to end (the --analyze-json contract)
    assert json.loads(json.dumps(v))["straggler"]["name"] == "p1"
    # and the human table names the same verdict
    table = format_analysis(v)
    assert "p1" in table and "spmd_sort" in table


def test_analyze_empty_and_wire_pricing():
    assert analyze_records([])["span_s"] is None
    recs = _proc_journal(5.0, [("spmd_sort", 0.1)], jobs=[(1, 10)])
    v = analyze_records(recs, link_bytes_per_s=1 << 20)
    assert v["wire"]["expected_transfer_s"] == pytest.approx(1.0)
    assert v["straggler"] is None  # one process: nothing to attribute


def test_analyze_real_serve_session_with_injected_latency(
    tmp_path, monkeypatch
):
    """Satellite: a REAL in-suite serve session with an injected-latency
    drill — --analyze names the injected dominant phase and the slowest
    job against journal ground truth (scrape==replay discipline)."""
    from dsort_tpu import cli
    from dsort_tpu.models import pipelines

    rng = np.random.default_rng(3)
    files, sizes = [], (1500, 4000, 1500)
    for i, n in enumerate(sizes):
        p = tmp_path / f"in{i}.txt"
        p.write_text("\n".join(str(x) for x in rng.integers(0, 10**6, n)))
        files.append(str(p))
    slow_rung = pipelines.pad_rung(4000)
    real = pipelines._fused_small_fn

    def injected(n_pad, dtype_str, kernel):
        fn = real(n_pad, dtype_str, kernel)
        if n_pad != slow_rung:
            return fn

        def slow(x, count):  # the latency lands INSIDE the local_sort phase
            time.sleep(0.25)
            return fn(x, count)

        return slow

    monkeypatch.setattr(pipelines, "_fused_small_fn", injected)
    feed = iter(files)
    monkeypatch.setattr(
        "builtins.input", lambda prompt="": next(feed, "exit")
    )
    journal = tmp_path / "serve.jsonl"
    rc = cli.main([
        "serve", "-o", str(tmp_path / "out.txt"), "--mode", "local",
        "--journal", str(journal), "--tenant", "acme",
    ])
    assert rc == 0
    records, skipped = read_journal(str(journal))
    assert skipped == 0
    v = analyze_records(records)
    # ground truth: the injected 0.25 s sleep dominates every other phase
    assert v["dominant_phase"] == "local_sort"
    assert v["critical_phase"] == "local_sort"
    assert v["critical_src"] == "p0"
    sj = v["slowest_job"]
    assert sj["n_keys"] == 4000 and sj["tenant"] == "acme"
    # verdict == journal ground truth for the phase waterfall
    truth = {}
    for r in records:
        if r["type"] == "phase_end":
            truth[r["phase"]] = truth.get(r["phase"], 0.0) + r["seconds"]
    for phase, sec in truth.items():
        assert v["phases"]["p0"][phase] == pytest.approx(sec)
    assert truth["local_sort"] > 0.25


def test_report_analyze_cli_writes_json(tmp_path, capsys):
    from dsort_tpu import cli

    path = tmp_path / "j.jsonl"
    with open(path, "w") as f:
        for r in _proc_journal(8.0, [("spmd_sort", 0.2)], jobs=[(1, 64)]):
            f.write(json.dumps(r) + "\n")
    vpath = tmp_path / "v.json"
    rc = cli.main([
        "report", str(path), "--analyze", "--analyze-json", str(vpath),
    ])
    out = capsys.readouterr().out
    assert rc == 0
    assert "why-slow verdict" in out and "spmd_sort" in out
    v = json.loads(vpath.read_text())
    assert v["dominant_phase"] == "spmd_sort"
    assert set(VERDICT_KEYS) <= set(v)


# -- journal rotation (satellite) -------------------------------------------


def test_rotation_stitches_and_merge_keeps_sources(tmp_path):
    base = str(tmp_path / "a.jsonl")
    log = EventLog(rotate_bytes=300)
    for i in range(18):
        log.emit("probe", worker=i, ok=True)
        log.flush_jsonl(base)
    pieces = rotated_set(base)
    assert len(pieces) > 1, "the threshold must have rotated the journal"
    recs, skipped = read_journal_set(pieces)
    assert skipped == 0
    assert [r["seq"] for r in recs] == list(range(18))
    # pieces of ONE journal never masquerade as extra processes, even when
    # listed explicitly next to a second journal
    other = str(tmp_path / "b.jsonl")
    blog = EventLog()
    blog.emit("probe", worker=99, ok=True)
    blog.write_jsonl(other)
    groups = group_rotated([pieces[0], base, other])
    assert len(groups) == 2
    assert groups[0] == pieces and groups[1] == [other]
    journals = [read_journal_set(g)[0] for g in groups]
    merged = merge_records(journals)
    assert {r["src"] for r in merged} == {0, 1}
    assert sum(r["src"] == 0 for r in merged) == 18


def test_new_session_clears_stale_rotated_pieces(tmp_path):
    """A second session on the same journal path must not leave the first
    session's path.N pieces behind: the first flush's truncate-on-fresh
    guard covers the WHOLE rotated set, or `dsort report` would stitch a
    cross-session trace."""
    base = str(tmp_path / "s.jsonl")
    first = EventLog(rotate_bytes=250)
    for i in range(12):
        first.emit("probe", worker=i, ok=True)
        first.flush_jsonl(base)
    assert len(rotated_set(base)) > 2  # session 1 really rotated
    second = EventLog(rotate_bytes=250)
    second.emit("probe", worker=99, ok=True)
    second.flush_jsonl(base)
    recs, skipped = read_journal_set(rotated_set(base))
    assert skipped == 0
    assert [r["worker"] for r in recs] == [99]  # session 1 fully gone


def test_group_rotated_keeps_independent_dot_n_journals(tmp_path):
    """Per-rank journals named trace.0/trace.1 (no base file) are NOT a
    rotation set: each keeps its own merge group, so the multi-process
    clock alignment is never silently collapsed."""
    for i in range(2):
        log = EventLog()
        log.emit("probe", worker=i, ok=True)
        log.write_jsonl(str(tmp_path / f"trace.{i}"))
    groups = group_rotated([str(tmp_path / "trace.0"),
                            str(tmp_path / "trace.1")])
    assert groups == [[str(tmp_path / "trace.0")],
                      [str(tmp_path / "trace.1")]]
    # ... and a single .N arg does not vacuum its digit-suffixed siblings
    assert group_rotated([str(tmp_path / "trace.0")]) == [
        [str(tmp_path / "trace.0")]
    ]


def test_report_cli_stitches_rotated_set(tmp_path, capsys):
    from dsort_tpu import cli

    base = str(tmp_path / "s.jsonl")
    log = EventLog(rotate_bytes=250)
    for i in range(10):
        log.emit("probe", worker=i, ok=True)
        log.flush_jsonl(base)
    assert len(rotated_set(base)) > 1
    rc = cli.main(["report", base])
    out = capsys.readouterr().out
    assert rc == 0
    assert out.count("probe") == 10  # every rotated piece rendered, once


# -- SLO-driven admission shedding (satellite) ------------------------------


def _slow_runner(delay):
    def run(data, metrics, job_id=None):
        metrics.event("job_start", mode="runner", n_keys=len(data),
                      tenant="t")
        time.sleep(delay)
        metrics.event("job_done", n_keys=len(data),
                      counters=dict(metrics.counters))
        return np.sort(data)

    return run


def test_slo_shed_rejects_over_target_and_recovers_after_drain():
    from dsort_tpu.config import ServeConfig
    from dsort_tpu.serve import SortService

    tel = Telemetry()
    jl = EventLog()
    svc = SortService(
        runner=_slow_runner(0.06),
        serve=ServeConfig(slo_shed_ms=5.0),
        telemetry=tel, journal=jl,
    )
    data = np.arange(64, dtype=np.int32)
    tickets = []
    for _ in range(4):
        verdict, t = svc.submit(data, tenant="t")
        assert verdict.admitted
        tickets.append(t)
    shed = None
    for _ in range(300):
        verdict, t = svc.submit(data, tenant="t")
        if not verdict.admitted:
            shed = verdict
            break
        tickets.append(t)
        time.sleep(0.02)
    assert shed is not None and shed.reason == "slo_shed"
    for t in tickets:
        t.result(timeout=60)
    time.sleep(0.05)  # queue drained: the next submit must be ADMITTED
    verdict, t = svc.submit(data, tenant="t")
    assert verdict.admitted, verdict
    t.result(timeout=60)
    svc.shutdown()
    # verdict journaled + counted into the per-tenant admission series
    assert any(
        e.type == "job_rejected" and e.fields.get("reason") == "slo_shed"
        for e in jl.events()
    )
    assert tel.snapshot()["admissions"].get("t/slo_shed", 0) >= 1
    parsed = parse_prometheus_text(tel.render_prometheus())
    assert parsed[(
        "dsort_admissions_total",
        (("reason", "slo_shed"), ("tenant", "t")),
    )] >= 1


def test_slo_shed_config_validation_and_conf_key():
    from dsort_tpu.config import ConfigError, ServeConfig, SortConfig

    with pytest.raises(ConfigError, match="slo_shed_ms"):
        ServeConfig(slo_shed_ms=0)
    cfg = SortConfig.from_mapping({"SERVE_SLO_SHED_MS": "250"})
    assert cfg.serve.slo_shed_ms == 250.0
    assert SortConfig.from_mapping({}).serve.slo_shed_ms is None
    assert "slo_shed" in ADMISSION_REASONS


# -- the analyze-smoke gate (satellite: make profile-smoke) ------------------


def test_bench_analyze_smoke_gate(capsys, devices):
    """Tier-1 gate for `make profile-smoke`: the introspection-plane cost
    harness runs end to end on the in-suite mesh — skew margin real,
    analyzer verdict coherent.  (The < 5% overhead contract binds at the
    1M row recorded in BENCH_r09.jsonl; at this gate's small n the
    timing is noise-dominated and only sanity-bounded.)"""
    from dsort_tpu import cli

    rc = cli.main(["bench", "--analyze-smoke", "--n", "200000", "--reps", "2"])
    out = capsys.readouterr().out
    row = json.loads(
        [ln for ln in out.splitlines() if ln.startswith("{")][-1]
    )
    assert rc == 0
    assert row["unit"] == "frac"
    assert row["introspection_ok"] is True
    assert row["skew_ratio_zipf"] > row["skew_ratio_uniform"] >= 1.0
    assert row["dominant_phase"] == "spmd_sort"
    assert row["bare_keys_per_sec"] > 0 and row["journaled_keys_per_sec"] > 0
    assert row["hbm_watermark_bytes"] > 0


def test_bench_r09_artifact_checks_and_compares():
    """BENCH_r09.jsonl: --check clean, and the introspection row joins the
    trajectory as an 'added' metric vs r07."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "bench", os.path.join(REPO, "bench.py")
    )
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    r09 = os.path.join(REPO, "BENCH_r09.jsonl")
    assert bench.check_artifact(r09) == []
    rows = bench.compare_artifacts(os.path.join(REPO, "BENCH_r07.jsonl"), r09)
    added = {r["metric"] for r in rows if r["class"] == "added"}
    assert "analyze_overhead_1M_8dev_cpu_mesh" in added
    with open(r09) as f:
        lines = [json.loads(ln) for ln in f if ln.strip()]
    row = [l for l in lines if l.get("metric", "").startswith("analyze_")][0]
    assert row["overhead_frac"] < 0.05 and row["introspection_ok"] is True
    assert row["skew_ratio_zipf"] > 1.5 * row["skew_ratio_uniform"]


# -- ARCHITECTURE §9 schema enforcement --------------------------------------


def test_architecture_documents_introspection_plane():
    """§9's contract is test-enforced like §7's bundle schema and §8's
    admission vocabulary: ledger fields, verdict keys, and the new event
    types all appear verbatim."""
    arch = open(
        os.path.join(REPO, "ARCHITECTURE.md"), encoding="utf-8"
    ).read()
    assert "## 9. Introspection plane" in arch
    for field in LEDGER_EVENT_FIELDS:
        assert f"`{field}`" in arch, f"ledger field {field} undocumented"
    for key in VERDICT_KEYS:
        assert f"`{key}`" in arch, f"verdict key {key} undocumented"
    for etype in ("variant_compiled", "skew_report", "hbm_watermark"):
        assert f"`{etype}`" in arch, f"event {etype} undocumented"
        assert etype in EVENT_TYPES
    for term in ("critical path", "straggler", "--analyze", "--memwatch",
                 "--journal-rotate-mb", "ladder-rung"):
        assert term in arch
