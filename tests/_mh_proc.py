"""One process of the multi-host sort test cluster (not a test module).

Spawned by tests/test_multihost.py: joins a 2-process JAX CPU cluster
(collectives over the Gloo/DCN path — the CPU stand-in for a real pod),
contributes host-local data to `parallel.distributed.sort_local_shards`,
and writes its slice of the global output for the parent to verify.
"""

import json
import os
import sys


def main() -> None:
    pid, port, outdir, dtype = (
        int(sys.argv[1]),
        sys.argv[2],
        sys.argv[3],
        sys.argv[4],
    )
    nprocs = int(sys.argv[5]) if len(sys.argv) > 5 else 2
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_enable_x64", True)
    # Cross-process CPU collectives need an explicit implementation on jax
    # builds where the CPU backend defaults to none ("Multiprocess
    # computations aren't implemented on the CPU backend") — Gloo is the
    # DCN stand-in this cluster exists to exercise.
    try:
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except Exception:
        pass  # newer jax enables CPU collectives by default
    # Explicit, generous init timeout (VERDICT r5 weak #2): on a loaded CI
    # box the peer processes can take a long time to reach the coordination
    # barrier; the default is fine interactively but the drill must never
    # flake on machine load.  Collective slowness past init surfaces as a
    # Gloo SIGABRT, which the parent (tests/test_multihost.py) retries once
    # with a logged note.
    jax.distributed.initialize(
        f"127.0.0.1:{port}", num_processes=nprocs, process_id=pid,
        initialization_timeout=600,
    )

    import numpy as np

    rng = np.random.default_rng(100 + pid)
    n = 4000 + 1000 * pid  # deliberately unequal host loads

    if dtype == "terasort":
        from dsort_tpu.config import JobConfig
        from dsort_tpu.data.ingest import gen_terasort, terasort_secondary
        from dsort_tpu.parallel.distributed import sort_local_records

        keys, payload = gen_terasort(n, seed=100 + pid)
        job = JobConfig(key_dtype=np.uint64, payload_bytes=payload.shape[1])
        out_k, out_v, off = sort_local_records(
            keys, payload, secondary=terasort_secondary(payload), job=job
        )
        np.save(os.path.join(outdir, f"in_{pid}.npy"), keys)
        np.save(os.path.join(outdir, f"inv_{pid}.npy"), payload)
        np.save(os.path.join(outdir, f"out_{pid}.npy"), out_k)
        np.save(os.path.join(outdir, f"outv_{pid}.npy"), out_v)
        with open(os.path.join(outdir, f"meta_{pid}.json"), "w") as f:
            json.dump({"offset": off}, f)
        return

    from dsort_tpu.parallel.distributed import sort_local_shards

    if dtype == "ckpt":
        # Recoverable-job mode: ONE deterministic global dataset split
        # evenly over however many processes this run has (the
        # partition-independent fingerprint must accept a 2-process job
        # restarting as 1 process), persisted ranges under the shared
        # checkpoint dir from DSORT_MH_CKPT_DIR.
        from dsort_tpu.config import JobConfig
        from dsort_tpu.data.partition import equal_partition
        from dsort_tpu.utils.metrics import Metrics

        from dsort_tpu.utils.events import EventLog

        all_data = (
            np.random.default_rng(777)
            .integers(-(10**6), 10**6, 9000)
            .astype(np.int32)
        )
        if os.environ.get("DSORT_MH_FLIP_KEY"):
            all_data[0] ^= 1  # staleness drill: same job_id, changed data
        sizes = equal_partition(len(all_data), nprocs)
        start = int(np.sum(sizes[:pid]))
        data = all_data[start : start + sizes[pid]]
        job = JobConfig(
            checkpoint_dir=os.environ["DSORT_MH_CKPT_DIR"],
            # Telemetry-plane drill knobs: a flight-recorder dir so the
            # crash-RESUME run dumps a postmortem bundle naming the
            # multihost_partial path, and a tenant label on the journal.
            flight_recorder_dir=os.environ.get("DSORT_MH_FLIGHT_DIR") or None,
            tenant=os.environ.get("DSORT_MH_TENANT", "default"),
        )
        journal = EventLog()
        m = Metrics(journal=journal)
        out, off = sort_local_shards(data, job=job, metrics=m, job_id="mhjob")
        # Per-process journal JSONL: the parent's merged-trace assertions
        # (obs.merge) read these back as a 2-journal fleet trace.
        journal.write_jsonl(os.path.join(outdir, f"journal_{pid}.jsonl"))
        np.save(os.path.join(outdir, f"out_{pid}.npy"), out)
        with open(os.path.join(outdir, f"meta_{pid}.json"), "w") as f:
            # The event-type sequence rides along so the parent test can
            # assert the fault timeline (restore vs fresh sort) per process.
            json.dump(
                {"offset": off, "counters": dict(m.counters),
                 "events": journal.types()},
                f,
            )
        return

    if dtype == "ckpt_kv":
        # Recoverable record-job mode: deterministic global TeraSort
        # records split over the current process count.
        from dsort_tpu.config import JobConfig
        from dsort_tpu.data.ingest import gen_terasort, terasort_secondary
        from dsort_tpu.data.partition import equal_partition
        from dsort_tpu.parallel.distributed import sort_local_records
        from dsort_tpu.utils.metrics import Metrics

        from dsort_tpu.utils.events import EventLog

        all_k, all_v = gen_terasort(3000, seed=777)
        sizes = equal_partition(len(all_k), nprocs)
        start = int(np.sum(sizes[:pid]))
        k = all_k[start : start + sizes[pid]]
        v = all_v[start : start + sizes[pid]]
        job = JobConfig(
            key_dtype=np.uint64, payload_bytes=v.shape[1],
            checkpoint_dir=os.environ["DSORT_MH_CKPT_DIR"],
        )
        journal = EventLog()
        m = Metrics(journal=journal)
        out_k, out_v, off = sort_local_records(
            k, v, secondary=terasort_secondary(v), job=job, metrics=m,
            job_id="mhkv",
        )
        np.save(os.path.join(outdir, f"out_{pid}.npy"), out_k)
        np.save(os.path.join(outdir, f"outv_{pid}.npy"), out_v)
        with open(os.path.join(outdir, f"meta_{pid}.json"), "w") as f:
            json.dump(
                {"offset": off, "counters": dict(m.counters),
                 "events": journal.types()},
                f,
            )
        return

    if dtype == "float32nan":
        data = rng.normal(size=n).astype(np.float32)
        data[::97] = np.nan
    else:
        data = rng.integers(-(10**6), 10**6, n).astype(dtype)
    out, off = sort_local_shards(data)
    np.save(os.path.join(outdir, f"in_{pid}.npy"), data)
    np.save(os.path.join(outdir, f"out_{pid}.npy"), out)
    with open(os.path.join(outdir, f"meta_{pid}.json"), "w") as f:
        json.dump({"offset": off}, f)


if __name__ == "__main__":
    main()
