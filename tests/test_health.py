"""Live fleet health plane tests (ISSUE 14, ARCHITECTURE §13): the
bounded telemetry delta stream over the fleet protocol, the streaming
why-slow analyzer (`obs.health`) and its live==replay contract against
`obs.analyze`, health-aware big-job routing (drilled A/B vs locality),
the degraded->flight-bundle contract, per-agent health gauges + the
`dsort top` health pane, protocol-level clock sync for `dsort report
--merge`, and the `bench.py --history` trajectory satellite."""

import json
import os
import time

import numpy as np
import pytest

from dsort_tpu.fleet import proto
from dsort_tpu.fleet.agent import FleetAgent
from dsort_tpu.fleet.controller import FleetController
from dsort_tpu.obs.analyze import VERDICT_KEYS, analyze_records
from dsort_tpu.obs.health import (
    HEALTH_VERDICT_KEYS,
    SHARED_VERDICT_KEYS,
    HealthAnalyzer,
    HealthDeltaCollector,
    format_health,
)
from dsort_tpu.obs.merge import merge_records
from dsort_tpu.utils.events import COUNTERS, EVENT_TYPES, EventLog
from dsort_tpu.utils.metrics import Metrics, PhaseTimer

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _slow_runner(data, metrics, job_id=None):
    timer = PhaseTimer(metrics)
    with timer.phase("local_sort"):
        time.sleep(0.25)
    metrics.event("job_done", n_keys=len(data), counters=dict(metrics.counters))
    return np.sort(data)


def _fast_runner(data, metrics, job_id=None):
    timer = PhaseTimer(metrics)
    with timer.phase("local_sort"):
        time.sleep(0.01)
    metrics.event("job_done", n_keys=len(data), counters=dict(metrics.counters))
    return np.sort(data)


def _close_all(ctl, agents):
    try:
        ctl.shutdown(drain=True, timeout=30)
    finally:
        for a in agents:
            a.close()


# -- the delta collector (agent side) ----------------------------------------


def test_collector_accumulates_and_drains():
    """The collector is a Metrics tap accumulating exactly the analyzer's
    inputs; drain() returns the bounded delta and resets, with the
    running sums exact regardless of the sample-window bound."""
    c = HealthDeltaCollector()
    m = Metrics()
    c.attach(m)
    c.attach(m)  # idempotent
    assert m.taps.count(c) == 1
    timer = PhaseTimer(m)
    with timer.phase("local_sort"):
        pass
    with timer.phase("exchange"):
        pass
    for i in range(200):  # overflow the wait window; the sum stays exact
        m.event("job_dequeued", tenant="t", wait_s=0.001)
    m.event("variant_compiled", variant="fused|8|int32", compile_s=0.5)
    m.event("skew_report", max_mean_ratio=2.0, recv_argmax=3)
    m.event("skew_report", max_mean_ratio=1.2, recv_argmax=1)  # not worst
    m.event("hbm_watermark", phase="exchange", edge="end", bytes_in_use=123)
    m.event("job_done", n_keys=10)
    delta = c.drain()
    assert set(delta["phases"]) == {"local_sort", "exchange"}
    assert delta["wait_count"] == 200
    assert delta["wait_s_sum"] == pytest.approx(0.2)
    assert len(delta["waits"]) <= 64  # bounded window, sums exact above
    assert delta["compile_s_sum"] == pytest.approx(0.5)
    assert delta["compiles"][0]["variant"] == "fused|8|int32"
    assert delta["skew"]["max_mean_ratio"] == 2.0
    assert delta["hbm"]["bytes_in_use"] == 123
    assert delta["jobs_done"] == 1
    empty = c.drain()  # reset
    assert empty["phases"] == {} and empty["wait_count"] == 0
    assert empty["seq"] == delta["seq"] + 1


# -- the bounded frame (satellite: heartbeat-plane growth) -------------------


def test_bounded_frame_evicts_oldest_first():
    """A long-running agent cannot inflate the heartbeat plane: an
    oversized telemetry frame is evicted oldest-first down to the byte
    budget, keeping the NEWEST wait/compile samples and preserving the
    per-phase seconds TOTAL (smallest phases fold into 'other')."""
    delta = {
        "seq": 9,
        "phases": {f"phase_{i:03d}": float(i + 1) for i in range(40)},
        "wait_s_sum": 1.0, "wait_count": 500,
        "waits": [float(i) for i in range(500)],
        "compile_s_sum": 2.0, "compile_count": 200,
        "compiles": [
            {"variant": f"fused|{8 * (i + 1)}|int32|auto", "compile_s": 0.1}
            for i in range(200)
        ],
        "skew": None, "hbm": None, "jobs_done": 3, "jobs_failed": 0,
    }
    header = {
        "type": "telemetry", "agent_id": "A", "wall": 1.0, "mono": 2.0,
        "variants": [f"fused|{8 * (i + 1)}|int64|auto" for i in range(300)],
        "delta": delta,
    }
    assert proto.frame_bytes(header) > proto.TELEMETRY_BYTE_BUDGET
    out = proto.bounded_frame(header)
    assert proto.frame_bytes(out) <= proto.TELEMETRY_BYTE_BUDGET
    # The original is never mutated.
    assert len(header["delta"]["waits"]) == 500
    # Eviction is oldest-first: whatever survives is the list TAIL.
    waits = out["delta"].get("waits", [])
    assert waits == [float(i) for i in range(500 - len(waits), 500)]
    # The exact running sums always survive.
    assert out["delta"]["wait_s_sum"] == 1.0
    assert out["delta"]["compile_s_sum"] == 2.0
    # Per-phase TOTAL is preserved even if attribution coarsened.
    assert sum(out["delta"]["phases"].values()) == pytest.approx(
        sum(delta["phases"].values())
    )
    # The dominant phase survives any folding.
    assert max(out["delta"]["phases"], key=out["delta"]["phases"].get) in (
        "phase_039", "other",
    )
    if "other" in out["delta"]["phases"]:
        assert out["delta"]["phases"].get("phase_039") == 40.0
    # A small frame passes through untouched.
    small = {"type": "heartbeat", "variants": ["a"], "queued": 0}
    assert proto.bounded_frame(small) == small


def test_agent_advertises_bounded_recent_variants():
    """The heartbeat's variant advertisement is bounded with eviction
    oldest-first (LRU order): the newest rungs survive."""
    agent = FleetAgent(runner=_fast_runner, agent_id="bnd")
    try:
        vc = agent.service.variants
        for i in range(60):
            vc._insert(("fused", 8 * (i + 1), "int32", "auto"), vc.TOKEN, None)
        labels = agent.variant_labels()
        assert len(labels) <= proto.MAX_ADVERTISED_VARIANTS
        assert f"fused|{8 * 60}|int32|auto" in labels  # newest kept
        assert f"fused|{8 * 1}|int32|auto" not in labels  # oldest evicted
    finally:
        agent.close(drain=False)


# -- the incremental analyzer ------------------------------------------------


def test_health_verdict_schema_shares_analyze_vocabulary():
    """Live and replay verdicts are comparable by construction: the
    shared keys are spelled identically (subset pinned)."""
    assert set(SHARED_VERDICT_KEYS) <= set(VERDICT_KEYS)
    for k in ("straggler", "dominant_phase", "splits", "skew", "hbm"):
        assert k in SHARED_VERDICT_KEYS and k in HEALTH_VERDICT_KEYS


def test_analyzer_scores_straggler_and_degrades():
    h = HealthAnalyzer(degraded_score=1.5, min_busy_s=0.05, slo_ms=100.0)
    h.ingest("A", {"seq": 1, "phases": {"local_sort": 0.9, "merge": 0.1},
                   "wait_s_sum": 0.01, "wait_count": 1, "waits": [0.01],
                   "compile_s_sum": 0.2, "compile_count": 1})
    h.ingest("B", {"seq": 1, "phases": {"local_sort": 0.2},
                   "wait_s_sum": 0.3, "wait_count": 2, "waits": [0.1, 0.2]})
    vs = h.verdicts()
    assert set(vs) == {"A", "B"}
    a, b = vs["A"], vs["B"]
    assert set(a) == set(HEALTH_VERDICT_KEYS)
    assert a["straggler"] and not b["straggler"]
    assert a["score"] == pytest.approx(1.0 / 0.6, abs=1e-3)
    assert a["dominant_phase"] == "local_sort"
    assert a["splits"]["phase_wall_s"] == pytest.approx(1.0)
    assert a["splits"]["compile_s"] == pytest.approx(0.2)
    assert a["splits"]["execute_s"] == pytest.approx(0.8)
    assert a["degraded"]  # straggler at 1.67x >= 1.5 with real busy time
    # B breaches the 100 ms SLO target (p95 wait 200 ms) -> degraded too.
    assert b["slo_risk"]["ratio"] >= 1.0 and b["degraded"]
    assert h.scores()["A"] == (True, a["score"])
    assert h.frames == 2
    # Deltas FOLD: a second ingest doubles A's busy time.
    h.ingest("A", {"seq": 2, "phases": {"local_sort": 1.0}})
    assert h.verdicts()["A"]["splits"]["phase_wall_s"] == pytest.approx(2.0)
    assert "A" in format_health(h.verdicts())
    h.forget("A")
    assert h.agents() == ["B"]


def test_collector_restore_survives_failed_send():
    """A drained-but-undelivered delta folds BACK (the agent's send
    failed): work completed while the controller was detached must not
    vanish from the health history — the exact sums merge."""
    c = HealthDeltaCollector()
    m = Metrics()
    c.attach(m)
    m.event("phase_end", phase="local_sort", seconds=0.4)
    m.event("job_dequeued", tenant="t", wait_s=0.1)
    m.event("skew_report", max_mean_ratio=2.5)
    lost = c.drain()  # shipped into a dead link...
    m.event("phase_end", phase="local_sort", seconds=0.6)
    m.event("job_dequeued", tenant="t", wait_s=0.2)
    c.restore(lost)  # ...and folded back on the send failure
    merged = c.drain()
    assert merged["phases"]["local_sort"] == pytest.approx(1.0)
    assert merged["wait_s_sum"] == pytest.approx(0.3)
    assert merged["wait_count"] == 2
    assert merged["skew"]["max_mean_ratio"] == 2.5
    # The agent path: telemetry enabled, NO controller attached — the
    # sums survive the failed send and ship on the next success.
    agent = FleetAgent(runner=_fast_runner, agent_id="det")
    try:
        agent._enable_telemetry()
        agent._collector.attach(m2 := Metrics())
        m2.event("phase_end", phase="merge", seconds=0.7)
        agent._send_telemetry()  # no conn: drain + restore
        kept = agent._collector.drain()
        assert kept["phases"]["merge"] == pytest.approx(0.7)
    finally:
        agent.close(drain=False)


def test_dead_agent_leaves_fleet_mean_and_straggler_slot():
    """A permanently-down agent's frozen busy time must not make the one
    remaining healthy agent score as the fleet straggler."""
    h = HealthAnalyzer(degraded_score=1.5, min_busy_s=0.05)
    h.ingest("A", {"seq": 1, "phases": {"local_sort": 10.0}})
    h.ingest("B", {"seq": 1, "phases": {"local_sort": 40.0}})
    assert h.verdicts()["B"]["straggler"]
    h.set_active("A", False)  # A died for good; B keeps working alone
    vs = h.verdicts()
    # B is the only live agent: no straggler, no degrade, score 1.0.
    assert not vs["B"]["straggler"] and not vs["B"]["degraded"]
    assert vs["B"]["score"] == pytest.approx(1.0)
    # A's last verdict still renders, but never degraded while down.
    assert not vs["A"]["straggler"] and not vs["A"]["degraded"]
    # A comes back and streams again: it rejoins the computation
    # (busy 210 vs 40 -> score 1.68x >= the 1.5x degrade bar).
    h.ingest("A", {"seq": 2, "phases": {"local_sort": 200.0}})
    vs = h.verdicts()
    assert vs["A"]["straggler"] and vs["A"]["degraded"]


def test_single_agent_is_never_a_straggler():
    h = HealthAnalyzer()
    h.ingest("A", {"seq": 1, "phases": {"local_sort": 5.0}})
    v = h.verdict("A")
    assert not v["straggler"] and not v["degraded"]
    assert v["score"] == pytest.approx(1.0)


# -- live == replay (the scrape==journal discipline, streamed) ---------------


def test_live_verdicts_match_replay_on_drilled_fleet():
    """THE plane's ground-truth drill: on a live fleet with an
    injected-latency agent, the controller's final journaled
    `health_verdict` for each agent matches `obs.analyze` replay of that
    agent's OWN journal (dominant phase, split) and the merged replay
    names the same straggler."""
    ja, jb, jc = EventLog(), EventLog(), EventLog()
    a = FleetAgent(runner=_slow_runner, agent_id="A", journal=ja)
    b = FleetAgent(runner=_fast_runner, agent_id="B", journal=jb)
    ctl = FleetController(
        [a.addr, b.addr], heartbeat_s=0.2, journal=jc,
    )
    try:
        rng = np.random.default_rng(0)
        for _ in range(2):
            d1 = rng.integers(0, 10**6, 900, dtype=np.int32)
            d2 = rng.integers(0, 10**6, 900, dtype=np.int32)
            # Submit BOTH before awaiting: capacity 1 each, so the pair
            # lands one per agent deterministically.
            v1, t1 = ctl.submit(d1, tenant="t")
            v2, t2 = ctl.submit(d2, tenant="t")
            np.testing.assert_array_equal(t1.result(timeout=60), np.sort(d1))
            np.testing.assert_array_equal(t2.result(timeout=60), np.sort(d2))
        replay = {
            aid: analyze_records([e.to_dict() for e in log.events()])
            for aid, log in (("A", ja), ("B", jb))
        }
        for aid in ("A", "B"):
            assert replay[aid]["splits"]["phase_wall_s"] > 0, aid
        # Quiesce: the live verdicts converge onto the replay totals once
        # the agents' final deltas arrive (result-attached, so fast).
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline:
            live = ctl.health_verdicts()
            if len(live) == 2 and all(
                live[aid]["splits"]["phase_wall_s"] == pytest.approx(
                    replay[aid]["splits"]["phase_wall_s"], abs=1e-5
                )
                for aid in ("A", "B")
            ):
                break
            time.sleep(0.05)
        # The FINAL journaled verdict per agent is the live state.
        journaled = {}
        for e in jc.events():
            if e.type == "health_verdict":
                journaled[e.fields["agent"]] = e.fields
        assert set(journaled) == {"A", "B"}
        for aid in ("A", "B"):
            got, want = journaled[aid], replay[aid]
            assert got["dominant_phase"] == want["dominant_phase"], aid
            for key in ("phase_wall_s", "queue_wait_s", "compile_s",
                        "execute_s"):
                assert got["splits"][key] == pytest.approx(
                    want["splits"][key], abs=1e-5
                ), (aid, key)
        # Straggler naming: live says A; the merged replay's straggler is
        # the same agent (src 0 = A's journal).
        assert journaled["A"]["straggler"] is True
        assert journaled["A"]["degraded"] is True
        assert journaled["B"]["straggler"] is False
        merged = merge_records([
            [e.to_dict() for e in log.events()] for log in (ja, jb)
        ])
        straggler = analyze_records(merged)["straggler"]
        assert straggler is not None and straggler["src"] == 0
        assert journaled["A"]["score"] == pytest.approx(
            straggler["score"], abs=1e-2
        )
        # The degraded flip was journaled as the typed event.
        degr = [e for e in jc.events() if e.type == "agent_degraded"]
        assert degr and degr[0].fields["agent"] == "A"
    finally:
        _close_all(ctl, [a, b])


# -- health-aware routing (the drilled A/B of the acceptance criteria) -------


def _prime_and_submit_big(routing: str, journal, flight_dir=None,
                          telemetry=None):
    """One arm of the A/B: slow agent A + fast agent B, one small prime
    job (ties route it to A), wait for verdicts, then one BIG job."""
    a = FleetAgent(runner=_slow_runner, agent_id="A")
    b = FleetAgent(runner=_fast_runner, agent_id="B")
    ctl = FleetController(
        [a.addr, b.addr], heartbeat_s=0.2, journal=journal, routing=routing,
        flight_dir=flight_dir, telemetry=telemetry,
    )
    try:
        d = np.arange(1000, dtype=np.int32)[::-1].copy()
        v, t = ctl.submit(d, tenant="t")
        assert v.admitted
        np.testing.assert_array_equal(t.result(timeout=60), np.sort(d))
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline:
            vs = ctl.health_verdicts()
            if vs.get("A", {}).get("degraded") and "B" in vs:
                break
            time.sleep(0.05)
        assert ctl.health_verdicts()["A"]["degraded"]
        big = np.arange(proto.FLEET_SMALL_JOB_MAX, dtype=np.int32)[::-1].copy()
        v, t = ctl.submit(big, tenant="t")
        assert v.admitted
        np.testing.assert_array_equal(t.result(timeout=120), np.sort(big))
        routed = [
            e.fields for e in journal.events() if e.type == "job_routed"
        ]
        big_routes = [
            r for r in routed if r["n_keys"] >= proto.FLEET_SMALL_JOB_MAX
        ]
        assert len(big_routes) == 1
        return big_routes[0], ctl.stats()
    finally:
        _close_all(ctl, [a, b])


def test_health_routing_routes_big_jobs_around_straggler(tmp_path):
    """The ISSUE 14 acceptance drill: with agent A given an injected
    slowdown, routing="health" places the big job on the CLEAN mesh (B)
    while locality/size routing does not (A wins the load tie) — and the
    degraded flip dumped a flight bundle."""
    from dsort_tpu.obs.flight import FlightRecorder

    flight_dir = str(tmp_path / "flight")
    j_health = EventLog()
    route, stats = _prime_and_submit_big(
        "health", j_health, flight_dir=flight_dir
    )
    assert route["agent"] == "B" and route["reason"] == "health"
    assert stats["agents_degraded"] == 1
    # The degraded->flight-bundle contract: one bundle, typed path.
    bundles = FlightRecorder.read_bundles(flight_dir)
    assert bundles and bundles[0]["recovery_path"] == "agent_degraded"
    assert bundles[0]["detail"]["agent"] == "A"
    # The bundle's state is the fleet view at dump time.
    assert {s["agent"] for s in bundles[0]["state"]} == {"A", "B"}
    # The locality baseline does NOT route around the measured straggler:
    # both agents idle, the load tie breaks on the label and A takes it.
    j_loc = EventLog()
    route, _ = _prime_and_submit_big("locality", j_loc)
    assert route["agent"] == "A" and route["reason"] == "size"


def test_heartbeats_only_controller_streams_no_telemetry():
    """health_telemetry=False (conf FLEET_TELEMETRY=0) is the overhead
    A/B baseline: agents are never opted in, no frames flow, no verdicts
    form — and the opt-in follows the CURRENT controller, so a
    heartbeats-only controller attaching to an agent a previous
    controller enabled stays frame-free too."""
    a = FleetAgent(runner=_fast_runner, agent_id="A")
    ctl = FleetController(
        [a.addr], heartbeat_s=0.2, health_telemetry=False,
    )
    try:
        d = np.arange(500, dtype=np.int32)[::-1].copy()
        v, t = ctl.submit(d, tenant="t")
        np.testing.assert_array_equal(t.result(timeout=60), np.sort(d))
        time.sleep(0.6)  # a few heartbeat rounds
        assert ctl.health_verdicts() == {}
        assert ctl.health.frames == 0
        assert a._collector is None
    finally:
        ctl.shutdown(drain=True, timeout=30)
    # An opted-in controller enables the stream...
    on = FleetController([a.addr], heartbeat_s=0.2)
    try:
        v, t = on.submit(d, tenant="t")
        np.testing.assert_array_equal(t.result(timeout=60), np.sort(d))
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and on.health.frames == 0:
            time.sleep(0.05)
        assert on.health.frames > 0 and a._collector is not None
    finally:
        on.shutdown(drain=True, timeout=30)
    # ...and a LATER heartbeats-only controller turns it back off.
    off = FleetController(
        [a.addr], heartbeat_s=0.2, health_telemetry=False,
    )
    try:
        v, t = off.submit(d, tenant="t")
        np.testing.assert_array_equal(t.result(timeout=60), np.sort(d))
        time.sleep(0.6)
        assert off.health.frames == 0
        assert not a._telemetry_on
    finally:
        _close_all(off, [a])


# -- gauges + the dsort top health pane --------------------------------------


def test_health_gauges_scrape_and_top_pane():
    from dsort_tpu.obs import Telemetry
    from dsort_tpu.obs.telemetry import parse_prometheus_text
    from dsort_tpu.obs.top import render_fleet, render_top

    tel = Telemetry()
    journal = EventLog()
    route, _ = _prime_and_submit_big("health", journal, telemetry=tel)
    assert route["agent"] == "B"
    parsed = parse_prometheus_text(tel.render_prometheus())
    score = parsed[("dsort_agent_health_score", (("agent", "A"),))]
    assert score >= 1.5
    assert parsed[("dsort_agent_health_degraded", (("agent", "A"),))] == 1.0
    assert parsed[("dsort_agent_health_degraded", (("agent", "B"),))] == 0.0
    assert parsed[("dsort_fleet_agents_degraded", ())] == 1.0
    info = [
        (dict(labels), v) for (name, labels), v in parsed.items()
        if name == "dsort_agent_health_info" and dict(labels)["agent"] == "A"
    ]
    # Info-style series REPLACE on refresh: exactly one row per agent.
    assert len(info) == 1
    assert info[0][0]["dominant_phase"] == "local_sort"
    assert info[0][0]["straggler"] == "1"
    top = render_top(parsed)
    assert "health:" in top and "A*" in top and "local_sort" in top
    fleet = render_fleet([("http://ctl/metrics", parsed)])
    assert "health:" in fleet
    # The JSON snapshot carries the labeled series too.
    snap = tel.snapshot()
    assert any(k.startswith("agent_health_score{agent=A}")
               for k in snap["series"])


# -- protocol-level clock sync (satellite 1) ---------------------------------


def test_peer_clock_blessing_aligns_skewed_wall_clocks():
    """`dsort report --merge` aligns controller+agent journals on
    MONOTONIC clocks via the peer (wall, mono) pairs the fleet frames
    carry — an agent with a skewed WALL clock still merges correctly."""
    ctl = [
        {"seq": 0, "t": 1000.0, "mono": 50.0, "type": "clock_sync",
         "source": "ctl"},
        # The blessing: the agent's pair journaled next to OUR stamps.
        {"seq": 1, "t": 1000.1, "mono": 50.1, "type": "clock_sync",
         "source": "ctl", "peer": "A", "peer_t": 5000.0, "peer_mono": 7.0},
        {"seq": 2, "t": 1002.0, "mono": 52.0, "type": "job_routed",
         "job_id": "f1", "agent": "A", "reason": "health", "n_keys": 10,
         "tenant": "t"},
    ]
    # The agent's wall clock is ~1.1 h ahead: wall-based alignment would
    # misplace its records by ~4000 s.
    agent = [
        {"seq": 0, "t": 5000.0, "mono": 7.0, "type": "clock_sync",
         "source": "A"},
        {"seq": 1, "t": 5001.0, "mono": 8.0, "type": "job_start",
         "mode": "fleet", "n_keys": 10, "job_id": "f1"},
    ]
    merged = merge_records([ctl, agent])
    start = next(r for r in merged if r["type"] == "job_start")
    # Monotonic blessing places it ~1 s after the hello (mono 50.1 + 1).
    assert start["mono"] == pytest.approx(51.1, abs=1e-6)
    # The trace is ordered: hello blessing < job_start < job_routed.
    types = [r["type"] for r in merged]
    assert types.index("job_start") < types.index("job_routed")
    # WITHOUT the blessing the same journals misalign by the wall skew —
    # the property the protocol pairs exist to remove.
    no_bless = [r for r in ctl if "peer" not in r]
    misaligned = merge_records([no_bless, agent])
    start = next(r for r in misaligned if r["type"] == "job_start")
    assert start["mono"] > 1000  # wall-skew artifact


def test_mutual_blessings_resolve_without_creep():
    """Symmetric controller<->agent blessings form a CYCLE; with a
    non-fleet journal at index 0 the component anchors at its lowest
    member and each shift is applied exactly once — the redundant edge
    (one network round-trip of disagreement) is ignored, never
    accumulated across resolution passes."""
    driver = [
        {"seq": 0, "t": 1000.0, "mono": 0.0, "type": "clock_sync",
         "source": "drv"},
    ]
    ctl = [
        {"seq": 0, "t": 1000.0, "mono": 50.0, "type": "clock_sync",
         "source": "ctl"},
        {"seq": 1, "t": 1000.1, "mono": 50.1, "type": "clock_sync",
         "source": "ctl", "peer": "A", "peer_t": 5000.0, "peer_mono": 7.0},
    ]
    agent = [
        {"seq": 0, "t": 5000.0, "mono": 7.0, "type": "clock_sync",
         "source": "A"},
        # The mutual half: the agent blesses the controller back.
        {"seq": 1, "t": 5000.05, "mono": 7.05, "type": "clock_sync",
         "source": "A", "peer": "ctl", "peer_t": 1000.0, "peer_mono": 50.0},
        {"seq": 2, "t": 5001.0, "mono": 8.0, "type": "job_start",
         "mode": "fleet", "n_keys": 10, "job_id": "f1"},
    ]
    merged = merge_records([driver, ctl, agent])
    start = next(r for r in merged if r["type"] == "job_start")
    # shift_ctl stays wall-anchored (-50); the agent resolves in ONE hop:
    # shift_A = shift_ctl + (50.1 - 7.0) -> job_start at mono 8 - 6.9.
    assert start["mono"] == pytest.approx(1.1, abs=1e-6)


def test_fleet_journals_carry_peer_blessings_live():
    """A real controller+agent pair journals the blessing on BOTH sides
    (welcome -> controller journal, hello -> agent journal)."""
    ja, jc = EventLog(), EventLog()
    a = FleetAgent(runner=_fast_runner, agent_id="A", journal=ja)
    ctl = FleetController([a.addr], heartbeat_s=0.3, journal=jc)
    try:
        d = np.arange(100, dtype=np.int32)[::-1].copy()
        v, t = ctl.submit(d, tenant="t")
        np.testing.assert_array_equal(t.result(timeout=60), np.sort(d))
        ctl_bless = [
            e.fields for e in jc.events()
            if e.type == "clock_sync" and e.fields.get("peer")
        ]
        assert ctl_bless and ctl_bless[0]["peer"] == "A"
        assert isinstance(ctl_bless[0]["peer_mono"], float)
        agent_bless = [
            e.fields for e in ja.events()
            if e.type == "clock_sync" and e.fields.get("peer")
        ]
        assert agent_bless
        assert agent_bless[0]["peer"] == ctl.controller_id
        # The merged trace orders sanely with journal 0 = controller.
        merged = merge_records([
            [e.to_dict() for e in jc.events()],
            [e.to_dict() for e in ja.events()],
        ])
        types = [r["type"] for r in merged]
        assert types.index("job_routed") < types.index("job_done")
    finally:
        _close_all(ctl, [a])


# -- registries + docs -------------------------------------------------------


def test_health_events_counters_and_frames_registered():
    for etype in ("health_verdict", "agent_degraded"):
        assert etype in EVENT_TYPES
    for counter in ("fleet_telemetry_frames", "health_verdicts",
                    "agent_degradations"):
        assert counter in COUNTERS
    assert "telemetry" in proto.FRAME_TYPES
    assert proto.ROUTING_POLICIES == ("locality", "random", "health")


def test_architecture_documents_health_plane():
    """§13's contract is test-enforced like §7-§12: the telemetry frame,
    the verdict schema (every HEALTH_VERDICT_KEYS name verbatim), the
    routing inputs and the degraded->flight-bundle contract."""
    arch = open(os.path.join(REPO, "ARCHITECTURE.md"), encoding="utf-8").read()
    assert "## 13. Health plane" in arch
    assert "`telemetry`" in arch
    for key in HEALTH_VERDICT_KEYS:
        assert f"`{key}`" in arch, f"verdict key {key} undocumented"
    for etype in ("health_verdict", "agent_degraded"):
        assert f"`{etype}`" in arch, f"health event {etype} undocumented"
    for term in ("TELEMETRY_BYTE_BUDGET", "MAX_ADVERTISED_VARIANTS",
                 "oldest-first", "heartbeats-only", "peer_mono",
                 "degraded", "flight bundle", 'routing="health"',
                 "HealthAnalyzer", "HealthDeltaCollector"):
        assert term in arch, f"§13 must explain {term}"


def test_fleet_cli_accepts_health_routing():
    from dsort_tpu import cli
    from dsort_tpu.config import ConfigError, FleetConfig

    # The parser refuses unknown policies; the config accepts "health".
    with pytest.raises(SystemExit):
        cli.main(["fleet", "--routing", "mystery", "--agents", "h:1"])
    assert FleetConfig(routing="health").routing == "health"
    with pytest.raises(ConfigError, match="routing"):
        FleetConfig(routing="mystery")


# -- bench.py --history (satellite) ------------------------------------------


def _load_bench():
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "bench", os.path.join(REPO, "bench.py")
    )
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    return bench


def test_bench_history_consolidates_real_artifacts():
    """Tier-1 gate on the REAL in-tree artifacts: the trajectory table
    covers every BENCH_r*.jsonl, steps classify on the --compare ladder,
    and the fleet rows appear where their PRs recorded them."""
    bench = _load_bench()
    hist = bench.history_rows(REPO)
    names = hist["artifacts"]
    assert "BENCH_r12.jsonl" in names and "BENCH_r14.jsonl" in names
    assert names == sorted(
        names, key=lambda n: int(n.split("_r")[1].split("_")[0].split(".")[0])
    )
    fleet_metric = "fleet_mixed_workload_2agents_8dev_cpu_mesh"
    fleet = hist["metrics"][fleet_metric]
    assert "BENCH_r12.jsonl" in fleet and "BENCH_r14.jsonl" in fleet
    health = hist["metrics"]["fleet_mixed_health_routing_2agents_8dev_cpu_mesh"]
    assert set(health) == {"BENCH_r14.jsonl"}
    valid = {"ok", "noise", "regression", "severe", "info"}
    for metric, steps in hist["steps"].items():
        for s in steps:
            assert s["class"] in valid, (metric, s)
    # The r12 -> r14 fleet step joined the trajectory (jobs/sec is not a
    # rate unit on the ladder, so it reports info, never a false alarm).
    fleet_steps = [
        s for s in hist["steps"][fleet_metric]
        if s["to"] == "BENCH_r14.jsonl"
    ]
    assert fleet_steps and fleet_steps[0]["class"] == "info"
    # Rate metrics DO classify on the ladder with a ratio per step.
    rated = [
        s for metric, steps in hist["steps"].items()
        for s in steps
        if hist["metrics"][metric][s["to"]].get("unit") == "keys/sec"
    ]
    assert rated and all("ratio" in s for s in rated)


def test_bench_history_cli(tmp_path):
    import subprocess
    import sys

    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"), "--history", REPO],
        capture_output=True, text=True, timeout=120,
    )
    assert r.returncode == 0, r.stderr
    assert "fleet_mixed_workload_2agents" in r.stdout
    assert '"metric": "history_summary"' in r.stdout
    empty = tmp_path / "none"
    empty.mkdir()
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"), "--history",
         str(empty)],
        capture_output=True, text=True, timeout=120,
    )
    assert r.returncode == 2


# -- BENCH_r14 artifact (acceptance) -----------------------------------------


def test_bench_r14_artifact_checks_and_compares():
    """BENCH_r14.jsonl: --check clean, the health row joins the
    trajectory as 'added' vs r12, the fleet row still carries the
    locality>random contract, and the live-telemetry overhead on the
    fleet-mixed bench is < 5% vs heartbeats-only."""
    bench = _load_bench()
    r14 = os.path.join(REPO, "BENCH_r14.jsonl")
    assert bench.check_artifact(r14) == []
    rows = bench.compare_artifacts(os.path.join(REPO, "BENCH_r12.jsonl"), r14)
    added = {r["metric"] for r in rows if r["class"] == "added"}
    assert any(
        m.startswith("fleet_mixed_health_routing") for m in added
    )
    with open(r14) as f:
        lines = [json.loads(ln) for ln in f if ln.strip()]
    fleet = next(
        l for l in lines
        if l.get("metric", "").startswith("fleet_mixed_workload")
    )
    assert fleet["bit_identical"] is True
    assert fleet["cache_hit_rate"] > fleet["cache_hit_rate_random"]
    assert fleet["fairness_p95_ratio"] <= 3.0
    assert fleet["telemetry_overhead_frac"] < 0.05
    health = next(
        l for l in lines
        if l.get("metric", "").startswith("fleet_mixed_health_routing")
    )
    assert health["bit_identical"] is True
    assert health["health_verdicts"] > 0
    assert health["value"] > 0
