"""Fused Pallas ring kernel (`ops.ring_kernel`, ISSUE 11): bit-identical
output across the full dtype/distribution/kv/fault matrix, the single-launch
dispatch model, the kv single-gather wire-byte contract, and the fused wave
pipeline composing with (wave, run) resume.

The acceptance bar mirrors the lax ring's (tests/test_exchange.py), tightened
where the kernel is structurally different: ``exchange="fused"`` must be
bit-identical to ``exchange="ring"`` AND ``np.sort`` everywhere (same plan,
same measured caps, same tag plane — the merged permutation is identical, so
even kv payload buffers compare with ``array_equal``), the whole exchange
must be ONE kernel launch (`DISPATCHES_PER_FUSED_EXCHANGE`), and payload
bytes must be counted (and moved) exactly once per step.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

import jax
from jax.sharding import Mesh

from dsort_tpu.config import ConfigError, JobConfig
from dsort_tpu.data.ingest import gen_terasort, gen_uniform, gen_zipf
from dsort_tpu.parallel.exchange import (
    dispatches_per_exchange,
    ring_wire_bytes,
)
from dsort_tpu.parallel.sample_sort import BatchSampleSort, SampleSort
from dsort_tpu.utils.events import EventLog
from dsort_tpu.utils.metrics import Metrics

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _metered():
    return Metrics(journal=EventLog())


# ---- the dispatch model ----------------------------------------------------


def test_dispatches_per_exchange_model():
    # The structural headline: P-1 ppermute dispatches become ONE launch.
    from dsort_tpu.ops.ring_kernel import DISPATCHES_PER_FUSED_EXCHANGE

    assert DISPATCHES_PER_FUSED_EXCHANGE == 1
    assert dispatches_per_exchange("ring", 8) == 7
    assert dispatches_per_exchange("fused", 8) == 1
    assert dispatches_per_exchange("alltoall", 8) == 1
    assert dispatches_per_exchange("ring", 7) == 6


def test_fused_mesh_folds_unit_batch_axis(mesh8):
    from dsort_tpu.ops.ring_kernel import fused_mesh

    fm = fused_mesh(mesh8, "w")
    assert fm.axis_names == ("w",)
    assert int(fm.shape["w"]) == 8
    # A REAL batch axis has no 1-axis view — the batched driver falls back.
    mesh2d = Mesh(np.array(jax.devices()[:8]).reshape(2, 4), ("dp", "w"))
    with pytest.raises(ValueError, match="fused"):
        fused_mesh(mesh2d, "w")


# ---- bit-identical: dtype / distribution matrix ----------------------------


@pytest.mark.parametrize("n", [64, 5000, 100_003])
def test_fused_uniform_bit_identical(mesh8, n):
    ss = SampleSort(mesh8)
    rng = np.random.default_rng(11)
    data = rng.integers(-(10**6), 10**6, n).astype(np.int32)
    r = ss.sort(data, exchange="ring")
    m = _metered()
    f = ss.sort(data, metrics=m, exchange="fused")
    np.testing.assert_array_equal(r, f)
    np.testing.assert_array_equal(f, np.sort(data))
    assert m.counters["fused_exchange_launches"] == 1
    assert m.counters["fused_exchange_steps"] == 7
    assert m.counters.get("capacity_retries", 0) == 0


def test_fused_zipf_bit_identical_int64(mesh8):
    z = gen_zipf(1 << 17, a=1.3, seed=4)
    ss = SampleSort(mesh8, JobConfig(key_dtype=np.int64))
    np.testing.assert_array_equal(
        ss.sort(z, exchange="ring"), ss.sort(z, exchange="fused")
    )


def test_fused_all_equal_keys(mesh8):
    # Degenerate skew: one destination owns everything; every step's cap is
    # the whole shard and most received slots are pure sentinel.
    ss = SampleSort(mesh8)
    data = np.full(20_000, 7, np.int32)
    np.testing.assert_array_equal(ss.sort(data, exchange="fused"), data)


def test_fused_sentinel_valued_keys(mesh8):
    ss = SampleSort(mesh8)
    rng = np.random.default_rng(3)
    data = rng.integers(-100, 100, 9000).astype(np.int32)
    data[:200] = np.iinfo(np.int32).max
    np.testing.assert_array_equal(
        ss.sort(data, exchange="fused"), np.sort(data)
    )


def test_fused_float_keys_nan(mesh8):
    ss = SampleSort(mesh8)
    rng = np.random.default_rng(6)
    data = rng.normal(size=20_000).astype(np.float32)
    data[::97] = np.nan
    got = ss.sort(data, exchange="fused")
    expect = np.sort(data)  # numpy: NaNs last
    k = len(data) - np.isnan(data).sum()
    np.testing.assert_array_equal(got[:k], expect[:k])
    assert np.isnan(got[k:]).all()


def test_fused_on_7_device_mesh():
    # Non-power-of-two P (the post-re-form mesh shape): the step offsets,
    # the merge tower's final fold and the semaphore arrays must not
    # assume pow2 P.
    mesh7 = Mesh(np.array(jax.devices()[:7]), ("w",))
    ss = SampleSort(mesh7)
    rng = np.random.default_rng(5)
    data = rng.integers(-(10**6), 10**6, 70_001).astype(np.int32)
    m = _metered()
    f = ss.sort(data, metrics=m, exchange="fused")
    np.testing.assert_array_equal(f, ss.sort(data, exchange="ring"))
    assert m.counters["fused_exchange_steps"] == 6


def test_fused_empty_bucket_and_tiny_input(mesh8):
    # Few distinct keys over 8 devices: several destinations own EMPTY
    # ranges, so whole receive slots are sentinel-only.
    ss = SampleSort(mesh8)
    rng = np.random.default_rng(9)
    data = rng.integers(0, 3, 4000).astype(np.int32)
    np.testing.assert_array_equal(
        ss.sort(data, exchange="fused"), np.sort(data)
    )
    # Tiny input: caps bottom out at the 8-element rung.
    small = rng.integers(0, 100, 40).astype(np.int32)
    np.testing.assert_array_equal(
        ss.sort(small, exchange="fused"), np.sort(small)
    )


def test_fused_single_worker_and_empty():
    ss1 = SampleSort(Mesh(np.array(jax.devices()[:1]), ("w",)))
    data = np.random.default_rng(1).integers(0, 100, 999).astype(np.int32)
    # P=1 resolves to the all_to_all short-circuit — no kernel exists.
    np.testing.assert_array_equal(
        ss1.sort(data, exchange="fused"), np.sort(data)
    )
    ss = SampleSort(Mesh(np.array(jax.devices()[:2]), ("w",)))
    assert len(ss.sort(np.empty(0, np.int32), exchange="fused")) == 0


# ---- the eager in-kernel merge tower ---------------------------------------
#
# On the CPU mesh `merge_kernel="auto"` resolves to the flat re-sort, which
# the fused kernel defers to one in-kernel combine (the lax ring's doctrine).
# Forcing a run-merge kernel exercises the in-kernel bitonic merge network:
# per-step folds between DMA start and wait, the unequal-length final fold,
# and the kv (key, tag) pair network.


def test_fused_eager_tower_bitonic(mesh8):
    ss = SampleSort(mesh8, JobConfig(merge_kernel="bitonic"))
    data = gen_uniform(30_000, seed=61)
    np.testing.assert_array_equal(
        ss.sort(data, exchange="fused"), np.sort(data)
    )


def test_fused_eager_tower_bitonic_7_devices():
    from dsort_tpu.parallel.mesh import local_device_mesh

    ss = SampleSort(local_device_mesh(7), JobConfig(merge_kernel="bitonic"))
    data = gen_uniform(10_000, seed=62)
    np.testing.assert_array_equal(
        ss.sort(data, exchange="fused"), np.sort(data)
    )


def test_fused_eager_tower_kv_duplicate_and_sentinel_keys(mesh8):
    # The in-kernel (key, tag) pair network: duplicates keep every payload,
    # and real keys equal to the padding sentinel keep theirs (the global
    # tag plane orders them ahead of pads).
    sent = np.iinfo(np.int32).max
    rng = np.random.default_rng(12)
    keys = rng.integers(0, 50, 5000).astype(np.int32)
    keys[:300] = sent
    vals = np.arange(5000, dtype=np.int32).reshape(-1, 1)
    ss = SampleSort(mesh8, JobConfig(payload_bytes=4, merge_kernel="bitonic"))
    ks, vs = ss.sort_kv(keys, vals, exchange="fused")
    np.testing.assert_array_equal(ks, np.sort(keys))
    np.testing.assert_array_equal(np.sort(vs[:, 0]), np.arange(5000))
    np.testing.assert_array_equal(keys[vs[:, 0]], ks)


# ---- kv records: payload moves (and is counted) once -----------------------


def test_fused_kv_records_payload_identical(mesh8):
    # The fused tag plane is the lax ring's verbatim, so not just the record
    # multiset — the exact payload permutation matches.
    tk, tv = gen_terasort(30_000, seed=3)
    ss = SampleSort(
        mesh8, JobConfig(key_dtype=np.uint64, payload_bytes=tv.shape[1])
    )
    kr, vr = ss.sort_kv(tk, tv, exchange="ring")
    m = _metered()
    kf, vf = ss.sort_kv(tk, tv, metrics=m, exchange="fused")
    np.testing.assert_array_equal(kr, kf)
    np.testing.assert_array_equal(vr, vf)
    np.testing.assert_array_equal(kf, np.sort(tk))
    assert m.counters["fused_exchange_launches"] == 1


def test_fused_kv_wire_bytes_count_payload_once(mesh8):
    """ISSUE 11 satellite: the kv wire-byte model charges each payload row
    ONCE per step — `ring_wire_bytes` at (key + payload-row) slot bytes over
    the planned caps, exactly what the single per-step DMA ships.  The PR 4
    double-gather is gone on the fused path (the kernel applies the merged
    tag permutation itself), so there is no second shipment or second
    gather to account for."""
    tk, tv = gen_terasort(20_000, seed=7)
    ss = SampleSort(
        mesh8, JobConfig(key_dtype=np.uint64, payload_bytes=tv.shape[1])
    )
    m = _metered()
    ss.sort_kv(tk, tv, metrics=m, exchange="fused")
    slot_bytes = tk.dtype.itemsize + tv.shape[1] * tv.dtype.itemsize
    steps = [
        e for e in m.journal.events() if e.type == "fused_exchange_step"
    ]
    assert len(steps) == 7
    caps = [0] + [e.fields["cap"] for e in steps]  # step 0 never ships
    expect = ring_wire_bytes(caps, slot_bytes, 8)
    assert m.counters["exchange_bytes_on_wire"] == expect
    # Each step's journaled bytes price key+payload once, and they sum to
    # the counter — no payload double-charge anywhere.
    assert sum(e.fields["bytes"] for e in steps) == expect


def test_fused_kv_secondary_falls_back(mesh8, caplog):
    from dsort_tpu.data.ingest import terasort_secondary

    tk, tv = gen_terasort(8000, seed=7)
    sec = terasort_secondary(tv)
    ss = SampleSort(
        mesh8, JobConfig(key_dtype=np.uint64, payload_bytes=tv.shape[1])
    )
    ka, va = ss.sort_kv(tk, tv, secondary=sec)
    with caplog.at_level("WARNING", logger="dsort.sample_sort"):
        kf, vf = ss.sort_kv(tk, tv, secondary=sec, exchange="fused")
    np.testing.assert_array_equal(ka, kf)
    np.testing.assert_array_equal(va, vf)


def test_fused_batch_falls_back_to_ring(devices, caplog):
    # The batched 2-D (dp, w) mesh has no 1-axis view for the kernel's
    # logical device ids; the batch keeps the lax ring, outputs unchanged.
    mesh = Mesh(np.array(devices[:8]).reshape(2, 4), ("dp", "w"))
    bs = BatchSampleSort(mesh, JobConfig())
    rng = np.random.default_rng(7)
    jobs = [rng.integers(0, 10**6, n).astype(np.int32) for n in (5000, 801)]
    m = _metered()
    with caplog.at_level("WARNING", logger="dsort.sample_sort"):
        outs = bs.sort(jobs, metrics=m, exchange="fused")
    for j, o in zip(jobs, outs):
        np.testing.assert_array_equal(o, np.sort(j))
    assert m.counters["exchange_ring_steps"] > 0
    assert "fused_exchange_launches" not in m.counters


def test_fused_config_and_cli_vocabulary():
    from dsort_tpu.config import SortConfig
    from dsort_tpu.parallel.exchange import resolve_exchange

    assert JobConfig(exchange="fused").exchange == "fused"
    with pytest.raises(ConfigError, match="exchange"):
        JobConfig(exchange="bogus")
    cfg = SortConfig.from_mapping({"EXCHANGE": "fused"})
    assert cfg.job.exchange == "fused"
    assert resolve_exchange(None, "fused", 8) == "fused"
    assert resolve_exchange("fused", "alltoall", 8) == "fused"
    assert resolve_exchange(None, "fused", 1) == "alltoall"
    with pytest.raises(ValueError, match="fused"):
        resolve_exchange("mesh", "fused", 8)


# ---- observability contract ------------------------------------------------


def test_fused_plan_keeps_ring_observability(mesh8):
    """The fused run rides the SAME accounting as the lax ring —
    skew_report, exchange_step, wire/saved byte counters — plus the fused
    plane: one fused_exchange_launch (dispatches_replaced = P-1) and one
    fused_exchange_step per transfer step, byte-for-byte equal."""
    z = gen_zipf(1 << 17, a=1.3, seed=4)
    ss = SampleSort(mesh8, JobConfig(key_dtype=np.int64))
    m = _metered()
    ss.sort(z, metrics=m, exchange="fused")
    types = m.journal.types()
    assert "skew_report" in types
    assert types.count("exchange_step") == 7
    assert types.count("fused_exchange_step") == 7
    assert m.counters["exchange_bytes_on_wire"] > 0
    assert m.counters["exchange_bytes_saved"] > 0
    launch = next(
        e for e in m.journal.events() if e.type == "fused_exchange_launch"
    )
    assert launch.fields["dispatches"] == 1
    assert launch.fields["dispatches_replaced"] == 7
    ring_steps = {
        e.fields["step"]: e.fields["bytes"]
        for e in m.journal.events() if e.type == "exchange_step"
    }
    fused_steps = {
        e.fields["step"]: e.fields["bytes"]
        for e in m.journal.events() if e.type == "fused_exchange_step"
    }
    assert ring_steps == fused_steps


# ---- fault matrix ----------------------------------------------------------


def test_fused_mid_ring_device_loss_reforms_and_matches():
    """A device lost between the fused plan and exchange dispatches (the
    same `fault_hook` seam as the lax ring) invalidates the exchange; the
    mesh re-forms over the survivors and the job re-runs there with a
    FRESH plan — verified down to a sorted, checksum-matching output and
    a 7-device second launch."""
    from dsort_tpu.models.validate import _multiset
    from dsort_tpu.scheduler import FaultInjector, SpmdScheduler

    inj = FaultInjector()
    sched = SpmdScheduler(
        job=JobConfig(settle_delay_s=0.01, exchange="fused"), injector=inj
    )
    z = gen_zipf(1 << 17, a=1.3, seed=5)
    np.testing.assert_array_equal(sched.sort(z), np.sort(z))  # warm

    inj.fail_once(3, "ring")
    m = _metered()
    out = sched.sort(z, metrics=m)
    assert (np.diff(out) >= 0).all() and len(out) == len(z)
    assert _multiset(out, len(out), out.dtype.itemsize) == _multiset(
        z, len(z), z.dtype.itemsize
    )
    assert m.counters["mesh_reforms"] == 1
    types = m.journal.types()
    assert types.index("worker_dead") < types.index("mesh_reform")
    assert "fused_exchange_launch" in types[types.index("mesh_reform"):]
    assert types[-1] == "job_done"
    # 8-device first attempt + 7-device re-run: 2 launches, 7+6 steps.
    assert m.counters["fused_exchange_launches"] == 2
    assert m.counters["fused_exchange_steps"] == 13


def test_fused_keep_on_device_validates(mesh8):
    from dsort_tpu.scheduler import SpmdScheduler

    sched = SpmdScheduler(job=JobConfig(exchange="fused"))
    data = gen_uniform(1 << 16, seed=9)
    h = sched.sort(data, keep_on_device=True)
    rep = h.validate_on_device()
    assert rep.sorted_ok and rep.records == len(data)
    np.testing.assert_array_equal(h.to_host(), np.sort(data))


# ---- the fused wave pipeline -----------------------------------------------


def _mesh(n):
    from dsort_tpu.parallel.mesh import local_device_mesh

    return local_device_mesh(n)


def test_fused_wave_matches_oracle(tmp_path, devices):
    from dsort_tpu.models.wave_sort import ExternalWaveSort

    rng = np.random.default_rng(21)
    data = rng.integers(-(10**6), 10**6, 24000).astype(np.int32)
    s = ExternalWaveSort(
        _mesh(8), wave_elems=4000, spill_dir=str(tmp_path),
        job_id="wfused", exchange="fused",
    )
    m = _metered()
    np.testing.assert_array_equal(s.sort(data, metrics=m), np.sort(data))
    # One kernel launch per wave: the wave never leaves the device between
    # partition and spill.
    assert m.counters["fused_exchange_launches"] == 6
    assert m.counters["waves_sorted"] == 6


def test_fused_wave_exchange_from_job_config(tmp_path, devices):
    # JobConfig.exchange="fused" reaches the wave plane through the one
    # resolver seam — no per-call override needed.
    from dsort_tpu.models.wave_sort import ExternalWaveSort

    s = ExternalWaveSort(
        _mesh(8), wave_elems=4000, spill_dir=str(tmp_path),
        job_id="wconf", job=JobConfig(exchange="fused"),
    )
    assert s.exchange == "fused"
    data = np.random.default_rng(3).integers(0, 10**6, 9000).astype(np.int32)
    np.testing.assert_array_equal(s.sort(data), np.sort(data))


def test_fused_wave_mid_ring_loss_repairs_in_flight(tmp_path, devices):
    """Mid-ring device loss inside a FUSED wave repairs at run granularity
    in flight (host re-sort of that wave only), later waves keep launching
    the kernel on the mesh, output bit-identical — the fused path composes
    with the wave plane's fault contract unchanged."""
    from dsort_tpu.models.wave_sort import ExternalWaveSort
    from dsort_tpu.scheduler.fault import WorkerFailure

    rng = np.random.default_rng(12)
    data = rng.integers(-(10**6), 10**6, 24000).astype(np.int32)
    s = ExternalWaveSort(
        _mesh(8), wave_elems=4000, spill_dir=str(tmp_path),
        job_id="wfault_fused", exchange="fused",
    )
    calls = {"n": 0}

    def hook():
        calls["n"] += 1
        if calls["n"] == 3:
            raise WorkerFailure("injected mid-ring device loss")

    s.fault_hook = hook
    m = _metered()
    np.testing.assert_array_equal(s.sort(data, metrics=m), np.sort(data))
    assert m.counters["wave_runs_resorted"] == 8  # one wave's runs
    assert m.counters["waves_sorted"] == 5  # the rest stayed on the mesh
    assert "wave_resume" in m.journal.types()


def test_fused_wave_process_kill_resumes_at_run_granularity(tmp_path, devices):
    """The restart-resume drill THROUGH the fused path: a process killed
    after wave 1's runs are durable restores waves 0-1 for free and sorts
    only the rest — the fused exchange composes with (wave, run) resume."""
    from dsort_tpu.models.wave_sort import DIE_AFTER_WAVE_ENV, ExternalWaveSort

    rng = np.random.default_rng(13)
    data = rng.integers(-(10**6), 10**6, 24000).astype(np.int32)
    in_path = str(tmp_path / "in.bin")
    data.tofile(in_path)
    script = (
        "import numpy as np, jax\n"
        "jax.config.update('jax_enable_x64', True)\n"
        "from dsort_tpu.parallel.mesh import local_device_mesh\n"
        "from dsort_tpu.models.wave_sort import ExternalWaveSort\n"
        "s = ExternalWaveSort(local_device_mesh(8), wave_elems=4000,\n"
        f"    spill_dir={str(tmp_path)!r}, job_id='wkill_fused',\n"
        "    exchange='fused')\n"
        f"s.sort_binary_file({in_path!r}, {str(tmp_path / 'out.bin')!r},\n"
        "    dtype=np.int32)\n"
    )
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        XLA_FLAGS="--xla_force_host_platform_device_count=8",
        **{DIE_AFTER_WAVE_ENV: "1"},
    )
    r = subprocess.run(
        [sys.executable, "-c", script], env=env, capture_output=True,
        text=True, timeout=560,
    )
    assert r.returncode == 17, r.stderr[-2000:]
    done = {
        name for name in os.listdir(tmp_path / "wkill_fused")
        if name.startswith("aux_w")
    }
    assert len(done) == 16, sorted(done)  # waves 0-1 durable, rest never ran
    s2 = ExternalWaveSort(
        _mesh(8), wave_elems=4000, spill_dir=str(tmp_path),
        job_id="wkill_fused", exchange="fused",
    )
    m = _metered()
    np.testing.assert_array_equal(s2.sort(data, metrics=m), np.sort(data))
    assert m.counters["runs_resumed"] == 16
    assert m.counters["runs_sorted"] == 4 * 8  # only the unfinished waves
    assert m.counters["fused_exchange_launches"] == 4  # one per fresh wave


# ---- slow full-scale case --------------------------------------------------


@pytest.mark.slow  # 1M interpret-mode kernel launches: keep tier-1 fast
def test_fused_1m_zipf_bit_identical(mesh8):
    z = gen_zipf(1 << 20, a=1.3, seed=4)
    ss = SampleSort(mesh8, JobConfig(key_dtype=np.int64))
    m = _metered()
    f = ss.sort(z, metrics=m, exchange="fused")
    np.testing.assert_array_equal(f, ss.sort(z, exchange="ring"))
    assert m.counters.get("capacity_retries", 0) == 0
    assert m.counters["fused_exchange_launches"] == 1


# ---- the `make bench-fused-smoke` tier-1 gate ------------------------------


def test_cli_bench_exchange_ab_fused_arm(tmp_path, capsys):
    """Tier-1 gate for `make bench-fused-smoke` (= bench-exchange-smoke):
    the three-way A/B emits one fused-vs-ring row per workload next to the
    unchanged ring-vs-alltoall rows, with the structural dispatch counts
    (P-1 -> 1), fused launch accounting, and bit_identical everywhere."""
    from dsort_tpu import cli

    journal = tmp_path / "fused_ab.jsonl"
    rc = cli.main([
        "bench", "--exchange-ab", "--n", "100000", "--reps", "1",
        "--journal", str(journal),
    ])
    assert rc == 0
    rows = [
        json.loads(ln) for ln in capsys.readouterr().out.splitlines()
        if ln.startswith("{")
    ]
    by_metric = {r["metric"]: r for r in rows}
    # The old contract rows are untouched by the new arm.
    assert "exchange_ring_vs_alltoall_uniform_int32_100000" in by_metric
    for label in ("uniform_int32_100000", "zipf_int64_100000",
                  "kv_65536_records"):
        row = by_metric[f"exchange_fused_vs_ring_{label}"]
        assert row["bit_identical"] is True
        assert row["dispatches_per_exchange"] == 1
        assert row["dispatches_per_exchange_ring"] == 7
        assert row["fused_launches_per_sort"] == 1
        assert row["value"] > 0 and row["ring_keys_per_sec"] > 0
        assert row["bytes_on_wire"] > 0
    types = [r["type"] for r in EventLog.read_jsonl(str(journal))]
    assert "fused_exchange_launch" in types
    assert "fused_exchange_step" in types


def test_cli_run_with_fused_exchange(tmp_path):
    """`dsort run --exchange fused` sorts a file through the fused kernel
    (checkpointing routes around the small-job single-device path, so the
    exchange actually runs at this size)."""
    from dsort_tpu import cli

    rng = np.random.default_rng(23)
    inp = tmp_path / "in.txt"
    inp.write_text("\n".join(str(x) for x in rng.integers(0, 10**6, 4000)))
    out = tmp_path / "out.txt"
    journal = tmp_path / "run.jsonl"
    rc = cli.main([
        "run", str(inp), "-o", str(out), "--exchange", "fused",
        "--checkpoint-dir", str(tmp_path / "ckpt"),
        "--journal", str(journal),
    ])
    assert rc == 0
    got = np.loadtxt(out, dtype=np.int64)
    np.testing.assert_array_equal(got, np.sort(np.loadtxt(inp, dtype=np.int64)))
    types = [r["type"] for r in EventLog.read_jsonl(str(journal))]
    assert "fused_exchange_launch" in types


def test_cli_external_mesh_fused_wave(tmp_path, devices):
    """`dsort external --mesh 8 --exchange fused` drives the fused wave
    pipeline end to end from the CLI."""
    from dsort_tpu import cli

    rng = np.random.default_rng(29)
    data = rng.integers(-(10**6), 10**6, 20_000).astype(np.int32)
    inp = tmp_path / "in.bin"
    data.tofile(inp)
    outp = tmp_path / "out.bin"
    journal = tmp_path / "wave.jsonl"
    rc = cli.main([
        "external", str(inp), "-o", str(outp), "--mesh", "8",
        "--wave-elems", "5000", "--exchange", "fused",
        "--spill-dir", str(tmp_path / "spill"), "--journal", str(journal),
    ])
    assert rc == 0
    got = np.fromfile(outp, dtype=np.int32)
    np.testing.assert_array_equal(got, np.sort(data))
    types = [r["type"] for r in EventLog.read_jsonl(str(journal))]
    assert "fused_exchange_launch" in types
    assert "wave_done" in types


# -- ARCHITECTURE §11 schema enforcement -------------------------------------


def test_architecture_documents_fused_ring():
    """§11's contract is test-enforced like §7-§10: the fused plane's event
    and counter names, the exchange vocabulary, the dispatch-count model,
    the interpreter seam and the CI surface all appear verbatim."""
    from dsort_tpu.utils.events import COUNTERS, EVENT_TYPES

    arch = open(
        os.path.join(REPO, "ARCHITECTURE.md"), encoding="utf-8"
    ).read()
    assert "## 11. Fused ring kernel" in arch
    for etype in ("fused_exchange_launch", "fused_exchange_step"):
        assert f"`{etype}`" in arch, f"event {etype} undocumented"
        assert etype in EVENT_TYPES
    for counter in ("fused_exchange_launches", "fused_exchange_steps"):
        assert f"`{counter}`" in arch, f"counter {counter} undocumented"
        assert counter in COUNTERS
    for term in (
        'exchange="fused"', "--exchange fused", "make_async_remote_copy",
        "dispatches_per_exchange", "ring_caps", "fused_mesh",
        "note_fused_plan", "bench-fused-smoke", "BENCH_r11.jsonl",
        "fault_hook", "interpreter", "ICI-only",
        "exchange_fused_vs_ring_",
    ):
        assert term in arch, f"{term} missing from §11"


# ---- BENCH_r11 artifact ----------------------------------------------------


def test_bench_r11_artifact_checks_and_compares():
    """BENCH_r11.jsonl: --check clean, the fused A/B rows join the
    trajectory as 'added' metrics vs r10, and the recorded rows carry the
    acceptance contract: dispatches_per_exchange 1 vs the lax ring's P-1,
    bit_identical everywhere, and the canonical uniform-int32 row no worse
    than 0.95x the lax ring end-to-end on the cpu mesh (the byte-heavy
    zipf row documents the interpreter's remote-DMA emulation tax — see
    ARCHITECTURE §11; the overlap win itself is ICI-only)."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "bench", os.path.join(REPO, "bench.py")
    )
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    r11 = os.path.join(REPO, "BENCH_r11.jsonl")
    assert bench.check_artifact(r11) == []
    rows = bench.compare_artifacts(os.path.join(REPO, "BENCH_r10.jsonl"), r11)
    added = {r["metric"] for r in rows if r["class"] == "added"}
    assert any(
        m.startswith("exchange_fused_vs_ring_uniform") for m in added
    )
    assert any(m.startswith("exchange_fused_vs_ring_zipf") for m in added)
    with open(r11) as f:
        lines = [json.loads(ln) for ln in f if ln.strip()]
    fused_rows = [
        l for l in lines
        if l.get("metric", "").startswith("exchange_fused_vs_ring_")
    ]
    assert len(fused_rows) >= 2
    for row in fused_rows:
        assert row["bit_identical"] is True
        assert row["dispatches_per_exchange"] == 1
        assert row["dispatches_per_exchange_ring"] == 7
    uni = next(l for l in fused_rows if "uniform_int32" in l["metric"])
    assert uni["speedup_vs_ring"] >= 0.95
