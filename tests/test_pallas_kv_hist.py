"""Pallas key+payload tile sort and VMEM radix histogram (interpret mode).

These run the real kernels under the Pallas interpreter on the CPU test
mesh; on TPU the identical code lowers to Mosaic (SURVEY.md §4 strategy:
distributed/TPU behavior exercised without the hardware).
"""

import jax.numpy as jnp
import numpy as np
import pytest

from dsort_tpu.ops.pallas_sort import pallas_sort_kv, radix_histogram

# Tiny tiles so multi-tile paths (merge tree, grid accumulation) are hit.
TR = 2  # tile_rows -> tile of 256 elements


# The >=257-key params each cost ~30-45 s under the CPU interpreter:
# slow-marked so tier-1 keeps the small-shape oracle and full runs keep
# the multi-tile coverage.
@pytest.mark.parametrize(
    "n",
    [1,
     pytest.param(5, marks=pytest.mark.slow),
     255, 256,
     pytest.param(257, marks=pytest.mark.slow),
     pytest.param(1000, marks=pytest.mark.slow),
     pytest.param(2048, marks=pytest.mark.slow)],
)
def test_pallas_kv_matches_stable_oracle(n):
    rng = np.random.default_rng(n)
    keys = rng.integers(-50, 50, n).astype(np.int32)  # many duplicates
    payload = np.arange(n, dtype=np.int32)
    out_k, out_v = pallas_sort_kv(
        jnp.asarray(keys), jnp.asarray(payload), tile_rows=TR
    )
    perm = np.argsort(keys, kind="stable")
    np.testing.assert_array_equal(np.asarray(out_k), keys[perm])
    np.testing.assert_array_equal(np.asarray(out_v), perm)


def test_pallas_kv_wide_payload():
    rng = np.random.default_rng(0)
    n = 700
    keys = rng.integers(-(2**31), 2**31 - 1, n, dtype=np.int64).astype(np.int32)
    payload = rng.integers(0, 256, (n, 9)).astype(np.uint8)  # TeraSort-like rows
    out_k, out_v = pallas_sort_kv(
        jnp.asarray(keys), jnp.asarray(payload), tile_rows=TR
    )
    perm = np.argsort(keys, kind="stable")
    np.testing.assert_array_equal(np.asarray(out_k), keys[perm])
    np.testing.assert_array_equal(np.asarray(out_v), payload[perm])


def test_pallas_kv_sentinel_keys_not_reserved():
    # Real keys equal to the padding sentinel must survive (the reference
    # reserves -1 on its wire, server.c:405-406; we reserve nothing).
    sent = np.iinfo(np.int32).max
    keys = np.array([5, sent, 1, sent, 3], dtype=np.int32)
    payload = np.array([50, 51, 52, 53, 54], dtype=np.int32)
    out_k, out_v = pallas_sort_kv(jnp.asarray(keys), jnp.asarray(payload), tile_rows=TR)
    np.testing.assert_array_equal(np.asarray(out_k), [1, 3, 5, sent, sent])
    np.testing.assert_array_equal(np.asarray(out_v), [52, 54, 50, 51, 53])


@pytest.mark.parametrize("shift,bits", [(0, 8), (8, 8), (24, 8), (0, 4)])
def test_radix_histogram_exact(shift, bits):
    rng = np.random.default_rng(shift + bits)
    x = rng.integers(0, 2**31 - 1, 3000, dtype=np.int64).astype(np.int32)
    hist = np.asarray(radix_histogram(jnp.asarray(x), shift, bits, tile_rows=TR))
    digits = (x >> shift) & ((1 << bits) - 1)
    expected = np.bincount(digits, minlength=1 << bits)
    np.testing.assert_array_equal(hist, expected)
    assert hist.sum() == len(x)


def test_radix_histogram_pad_correction():
    # n not a tile multiple and lots of real zeros: pad subtraction is exact.
    x = np.zeros(77, dtype=np.int32)
    hist = np.asarray(radix_histogram(jnp.asarray(x), 0, 8, tile_rows=TR))
    assert hist[0] == 77 and hist[1:].sum() == 0
