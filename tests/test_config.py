"""Config layer tests (reference read_conf_file parity: server.c:61-90)."""

import jax.numpy as jnp
import pytest

from dsort_tpu.config import (
    ConfigError,
    JobConfig,
    MeshConfig,
    SortConfig,
    load_conf_file,
)


def test_load_conf_file_reference_format(tmp_path):
    # server.conf:1 / client.conf:1-2 exact format
    p = tmp_path / "server.conf"
    p.write_text("SERVER_PORT=9008\n")
    assert load_conf_file(p) == {"SERVER_PORT": "9008"}
    p2 = tmp_path / "client.conf"
    p2.write_text("SERVER_IP=128.226.114.205\nSERVER_PORT=9008\n")
    assert load_conf_file(p2) == {
        "SERVER_IP": "128.226.114.205",
        "SERVER_PORT": "9008",
    }


def test_load_conf_file_comments_and_blank(tmp_path):
    p = tmp_path / "c.conf"
    p.write_text("# comment\n\nKEY = spaced value \n")
    assert load_conf_file(p) == {"KEY": "spaced value"}


def test_load_conf_file_missing_raises():
    with pytest.raises(ConfigError, match="not found"):
        load_conf_file("/nonexistent/x.conf")


def test_load_conf_file_malformed_raises(tmp_path):
    p = tmp_path / "bad.conf"
    p.write_text("NOEQUALS\n")
    with pytest.raises(ConfigError, match="KEY=value"):
        load_conf_file(p)


def test_sort_config_from_mapping():
    cfg = SortConfig.from_mapping(
        {
            "SERVER_IP": "10.0.0.1",
            "SERVER_PORT": "9999",
            "NUM_WORKERS": "8",
            "KEY_DTYPE": "int64",
            "CAPACITY_FACTOR": "3.5",
        }
    )
    assert cfg.server_ip == "10.0.0.1"
    assert cfg.server_port == 9999
    assert cfg.mesh.num_workers == 8
    assert cfg.job.key_dtype == jnp.int64
    assert cfg.job.capacity_factor == 3.5


def test_sort_config_defaults_match_reference():
    cfg = SortConfig()
    assert cfg.server_port == 9008  # server.conf:1
    assert cfg.output_path == "output.txt"  # server.c:517


def test_validation_errors():
    with pytest.raises(ConfigError):
        MeshConfig(num_workers=0)
    with pytest.raises(ConfigError):
        JobConfig(capacity_factor=0.5)
    with pytest.raises(ConfigError):
        JobConfig(oversample=0)


def test_from_mapping_rejects_zero_values():
    # Regression: explicit 0 must hit validation, not be silently defaulted.
    with pytest.raises(ConfigError):
        SortConfig.from_mapping({"OVERSAMPLE": "0"})
    with pytest.raises(ConfigError):
        SortConfig.from_mapping({"DP": "0"})
