"""End-to-end pipeline tests on the 8-device simulated mesh (SURVEY.md §4).

The minimum end-to-end slice from SURVEY.md §7 step 3: random int32 on an
8-device mesh, shard_map'd local sort + host gather-merge, oracle np.sort.
"""

import numpy as np
import pytest

from dsort_tpu.data.ingest import gen_uniform, gen_zipf, read_ints_file, write_ints_file
from dsort_tpu.models.pipelines import GatherMergeSort, local_pipeline_step
from dsort_tpu.data.partition import pad_to_shards


def test_local_pipeline_step():
    import jax.numpy as jnp

    data = gen_uniform(10_000, seed=7)
    shards, counts = pad_to_shards(data, 8)
    flat, total = local_pipeline_step(jnp.asarray(shards), jnp.asarray(counts))
    assert int(total) == len(data)
    np.testing.assert_array_equal(np.asarray(flat)[: len(data)], np.sort(data))


@pytest.mark.parametrize("n", [0, 1, 7, 1000, 100_000])
def test_gather_merge_sort_uniform(mesh8, n):
    data = gen_uniform(n, seed=n)
    out = GatherMergeSort(mesh8).sort(data)
    np.testing.assert_array_equal(out, np.sort(data))


def test_gather_merge_sort_zipf(mesh8):
    data = gen_zipf(50_000, seed=5)
    out = GatherMergeSort(mesh8).sort(data)
    np.testing.assert_array_equal(out, np.sort(data))


def test_gather_merge_reference_golden_workload(mesh8, tmp_path):
    # The reference's shipped job: 10,000 ints in 1..100; its golden output is
    # `sort -n input.txt` (SURVEY.md §4).  Reproduce format + semantics.
    rng = np.random.default_rng(42)
    data = rng.integers(1, 101, 10_000).astype(np.int32)
    inp = tmp_path / "input.txt"
    write_ints_file(inp, data)
    loaded = read_ints_file(inp)
    np.testing.assert_array_equal(loaded, data)
    out = GatherMergeSort(mesh8).sort(loaded)
    outp = tmp_path / "output.txt"
    write_ints_file(outp, out)
    np.testing.assert_array_equal(read_ints_file(outp), np.sort(data))


def test_metrics_populated(mesh8):
    from dsort_tpu.utils.metrics import Metrics

    m = Metrics()
    GatherMergeSort(mesh8).sort(gen_uniform(1000), metrics=m)
    assert {"partition", "local_sort", "gather", "merge"} <= set(m.phase_s)
    assert m.total_s() > 0
    assert m.keys_per_sec(1000) > 0


# ---- fused small-job path (VERDICT r2 item 3) ----


def test_fused_sort_small_matches_numpy():
    from dsort_tpu.models.pipelines import fused_sort_small

    rng = np.random.default_rng(5)
    for n in (0, 1, 7, 1000, 16_384, 50_001):
        data = rng.integers(-(2**31), 2**31 - 1, n, dtype=np.int64).astype(np.int32)
        out = fused_sort_small(data)
        np.testing.assert_array_equal(out, np.sort(data))


def test_fused_sort_small_sentinel_and_floats():
    from dsort_tpu.models.pipelines import fused_sort_small

    # sentinel-valued real keys survive the pad/trim exactly
    data = np.array([5, np.iinfo(np.int32).max, -1, np.iinfo(np.int32).max],
                    np.int32)
    np.testing.assert_array_equal(fused_sort_small(data), np.sort(data))
    # float keys with NaNs ride the ops.float_order bijection: NaNs come
    # back (last), never trimmed as pads
    f = np.array([3.5, np.nan, -np.inf, 0.0, -0.0, np.inf, np.nan], np.float32)
    out = fused_sort_small(f)
    assert np.isnan(out[-2:]).all()
    np.testing.assert_array_equal(out[:-2], np.sort(f)[:-2])


def test_cli_spmd_mode_routes_small_jobs_fused():
    """`dsort run --mode spmd` on a small job must take the fused path."""
    from dsort_tpu import cli
    from dsort_tpu.config import SortConfig
    from dsort_tpu.utils.metrics import Metrics

    sorter = cli._make_sorter(SortConfig(), "spmd")
    rng = np.random.default_rng(8)
    small = rng.integers(0, 10**6, 16_384).astype(np.int32)
    m = Metrics()
    out = sorter(small, m)
    np.testing.assert_array_equal(out, np.sort(small))
    assert m.counters.get("fused_small_jobs") == 1
    # a big job still goes through the SPMD scheduler
    big = rng.integers(0, 10**6, 1 << 20).astype(np.int32)
    m2 = Metrics()
    out2 = sorter(big, m2)
    np.testing.assert_array_equal(out2, np.sort(big))
    assert "fused_small_jobs" not in m2.counters


def test_cli_spmd_fused_falls_back_to_scheduler_on_device_error(monkeypatch):
    """A device-runtime failure on the fused path must retry on the SPMD
    scheduler (fault tolerance preserved), not crash the CLI."""
    from dsort_tpu import cli
    from dsort_tpu.config import SortConfig
    from dsort_tpu.utils.metrics import Metrics

    import dsort_tpu.models.pipelines as pl

    def dying(data, kernel="auto", metrics=None):
        from tests.test_fault_tolerance import _xla_error

        raise _xla_error("UNAVAILABLE: device tunnel dropped")

    monkeypatch.setattr(pl, "fused_sort_small", dying)
    sorter = cli._make_sorter(SortConfig(), "spmd")
    rng = np.random.default_rng(11)
    small = rng.integers(0, 10**6, 10_000).astype(np.int32)
    m = Metrics()
    out = sorter(small, m)
    np.testing.assert_array_equal(out, np.sort(small))
    assert m.counters.get("fused_fallbacks") == 1
    assert "fused_small_jobs" not in m.counters

    def broken(data, kernel="auto", metrics=None):
        raise ValueError("INVALID_ARGUMENT: a genuine program bug")

    monkeypatch.setattr(pl, "fused_sort_small", broken)
    sorter2 = cli._make_sorter(SortConfig(), "spmd")  # closure binds at build
    with pytest.raises(ValueError):  # program errors must NOT be eaten
        sorter2(small, Metrics())
