"""End-to-end pipeline tests on the 8-device simulated mesh (SURVEY.md §4).

The minimum end-to-end slice from SURVEY.md §7 step 3: random int32 on an
8-device mesh, shard_map'd local sort + host gather-merge, oracle np.sort.
"""

import numpy as np
import pytest

from dsort_tpu.data.ingest import gen_uniform, gen_zipf, read_ints_file, write_ints_file
from dsort_tpu.models.pipelines import GatherMergeSort, local_pipeline_step
from dsort_tpu.data.partition import pad_to_shards


def test_local_pipeline_step():
    import jax.numpy as jnp

    data = gen_uniform(10_000, seed=7)
    shards, counts = pad_to_shards(data, 8)
    flat, total = local_pipeline_step(jnp.asarray(shards), jnp.asarray(counts))
    assert int(total) == len(data)
    np.testing.assert_array_equal(np.asarray(flat)[: len(data)], np.sort(data))


@pytest.mark.parametrize("n", [0, 1, 7, 1000, 100_000])
def test_gather_merge_sort_uniform(mesh8, n):
    data = gen_uniform(n, seed=n)
    out = GatherMergeSort(mesh8).sort(data)
    np.testing.assert_array_equal(out, np.sort(data))


def test_gather_merge_sort_zipf(mesh8):
    data = gen_zipf(50_000, seed=5)
    out = GatherMergeSort(mesh8).sort(data)
    np.testing.assert_array_equal(out, np.sort(data))


def test_gather_merge_reference_golden_workload(mesh8, tmp_path):
    # The reference's shipped job: 10,000 ints in 1..100; its golden output is
    # `sort -n input.txt` (SURVEY.md §4).  Reproduce format + semantics.
    rng = np.random.default_rng(42)
    data = rng.integers(1, 101, 10_000).astype(np.int32)
    inp = tmp_path / "input.txt"
    write_ints_file(inp, data)
    loaded = read_ints_file(inp)
    np.testing.assert_array_equal(loaded, data)
    out = GatherMergeSort(mesh8).sort(loaded)
    outp = tmp_path / "output.txt"
    write_ints_file(outp, out)
    np.testing.assert_array_equal(read_ints_file(outp), np.sort(data))


def test_metrics_populated(mesh8):
    from dsort_tpu.utils.metrics import Metrics

    m = Metrics()
    GatherMergeSort(mesh8).sort(gen_uniform(1000), metrics=m)
    assert {"partition", "local_sort", "gather", "merge"} <= set(m.phase_s)
    assert m.total_s() > 0
    assert m.keys_per_sec(1000) > 0
