"""Multi-host (multi-process) distributed sort over the DCN path.

The reference's "multi-node" test strategy is several processes on one
machine talking TCP (SURVEY.md §4).  The TPU-native equivalent: a REAL
2-process JAX cluster (``jax.distributed.initialize`` on the CPU backend,
cross-process collectives over Gloo — the same code path that rides DCN on
a pod), each process feeding host-local data into
`parallel.distributed.sort_local_shards` and getting back its own devices'
slice of the globally sorted, range-partitioned output.
"""

import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_PROC = os.path.join(REPO, "tests", "_mh_proc.py")

# Subprocess-cluster tests are SERIAL (VERDICT r5 weak #2): each spawns a
# whole jax.distributed CPU cluster, and two clusters contending for cores
# on a loaded box is exactly the condition that produced the flaky Gloo
# SIGABRT.  The marker is registered in pyproject.toml; distributed runners
# (xdist and friends) can key off it, and the in-tree tier-1 command already
# runs single-process.
pytestmark = pytest.mark.serial


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _spawn_cluster_once(
    tmp_path, dtype: str, nprocs: int, env: dict,
) -> list[tuple[int, bytes]]:
    """One cluster run; returns per-process (returncode, stderr)."""
    port = _free_port()
    procs = [
        subprocess.Popen(
            [sys.executable, _PROC, str(pid), str(port), str(tmp_path), dtype,
             str(nprocs)],
            env=env,
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
        )
        for pid in range(nprocs)
    ]
    results = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=300)
            results.append((p.returncode, err))
    finally:  # a hung cluster must not leak live jax processes into CI
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait(timeout=10)
    return results


def _snapshot_dirs(paths):
    """Copy each existing dir aside so a retry can replay from clean state."""
    import shutil
    import tempfile

    backup_root = tempfile.mkdtemp(prefix="dsort-mh-retry-")
    saved = {}
    for i, p in enumerate(paths):
        p = str(p)
        if os.path.isdir(p):
            dst = os.path.join(backup_root, str(i))
            shutil.copytree(p, dst)
            saved[p] = dst
        else:
            saved[p] = None  # did not exist: a restore just deletes it
    return backup_root, saved


def _restore_dirs(saved) -> None:
    import shutil

    for p, backup in saved.items():
        shutil.rmtree(p, ignore_errors=True)
        if backup is not None:
            shutil.copytree(backup, p)


def _run_cluster(
    tmp_path, dtype: str, nprocs: int = 2, env_extra: dict | None = None,
    expect_rc: dict | None = None, require_files: list | None = None,
) -> None:
    import shutil

    env = dict(os.environ)
    env["PYTHONPATH"] = REPO
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env.update(env_extra or {})
    # Snapshot the run's mutable state (outputs + the shared checkpoint dir)
    # so a retry REPLAYS the attempt instead of resuming whatever the
    # aborted attempt left behind — a crash drill retried against its own
    # half-written checkpoints would change the very semantics under test.
    state_dirs = [str(tmp_path)]
    if env.get("DSORT_MH_CKPT_DIR"):
        state_dirs.append(env["DSORT_MH_CKPT_DIR"])
    backup_root, saved = _snapshot_dirs(state_dirs)
    try:
        _run_cluster_attempts(
            tmp_path, dtype, nprocs, env, expect_rc, saved, require_files
        )
    finally:
        shutil.rmtree(backup_root, ignore_errors=True)


def _run_cluster_attempts(
    tmp_path, dtype, nprocs, env, expect_rc, saved, require_files=None
) -> None:
    for attempt in (0, 1):
        if attempt > 0:
            _restore_dirs(saved)
        results = _spawn_cluster_once(tmp_path, dtype, nprocs, env)
        bad = []
        for pid, (rc, err) in enumerate(results):
            want = (expect_rc or {}).get(pid, 0)
            if want == "any":  # crash drills: survivors also fail at the
                continue  # collective/shutdown barrier once a host is gone
            if rc != want:
                bad.append((pid, rc, err))
        # Crash drills tolerate "any" rc for the survivor (it legitimately
        # collapses at the shutdown barrier) — but a Gloo SIGABRT can also
        # kill it BEFORE it persisted the state the drill asserts on, which
        # used to surface as a flaky downstream assert
        # (test_multihost_kv_partial_checkpoint_resorts, VERDICT r5 weak
        # #2).  `require_files` makes the drill's state contract explicit:
        # missing state + an infra-signal abort anywhere in the cluster is
        # the same retry-once case as an rc mismatch.
        missing = [
            str(f) for f in (require_files or []) if not os.path.exists(f)
        ]
        if not bad and not missing:
            return
        any_sigabrt = any(rc == -6 for rc, _ in results)
        # SIGABRT is Gloo's infra signal (a collective timing out under
        # machine load, not a product failure): retry ONCE with a logged
        # note so the drill tests what they exist to test (VERDICT r5 weak
        # #2).  Any other mismatch — or a second SIGABRT — fails loudly.
        if attempt == 0 and (
            any(rc == -6 for _, rc, _ in bad) or (missing and any_sigabrt)
        ):
            print(
                f"NOTE: multihost cluster ({dtype}, nprocs={nprocs}) hit a "
                f"Gloo SIGABRT (procs {[(p, rc) for p, rc, _ in bad]}, "
                f"missing state {missing}); retrying once (infra signal "
                "under load, see tests/_mh_proc.py)",
                file=sys.stderr,
            )
            continue
        if bad:
            pid, rc, err = bad[0]
            want = (expect_rc or {}).get(pid, 0)
            raise AssertionError(
                f"proc {pid}: rc {rc} != {want}\n" + err.decode()[-2000:]
            )
        raise AssertionError(
            f"cluster ({dtype}, nprocs={nprocs}) exited clean but required "
            f"drill state is missing: {missing}"
        )


def _check(tmp_path, sort_like_numpy, nprocs: int = 2) -> None:
    ins = [np.load(tmp_path / f"in_{i}.npy") for i in range(nprocs)]
    outs = [np.load(tmp_path / f"out_{i}.npy") for i in range(nprocs)]
    offs = [
        json.load(open(tmp_path / f"meta_{i}.json"))["offset"]
        for i in range(nprocs)
    ]
    got = np.concatenate(outs)
    allin = np.concatenate(ins)
    assert len(got) == len(allin)
    # Offsets stitch the slices back contiguously in global order.
    expect_off = 0
    for i in range(nprocs):
        assert offs[i] == expect_off
        expect_off += len(outs[i])
    sort_like_numpy(got, allin)


@pytest.mark.slow
def test_two_process_cluster_int32(tmp_path):
    _run_cluster(tmp_path, "int32")
    _check(
        tmp_path,
        lambda got, allin: np.testing.assert_array_equal(got, np.sort(allin)),
    )


@pytest.mark.slow
def test_three_process_cluster_int32(tmp_path):
    """3 processes x 2 devices: odd process counts exercise the process-major
    device-order/offset math beyond the 2-way split."""
    _run_cluster(tmp_path, "int32", nprocs=3)
    _check(
        tmp_path,
        lambda got, allin: np.testing.assert_array_equal(got, np.sort(allin)),
        nprocs=3,
    )


@pytest.mark.slow
def test_two_process_cluster_terasort_records(tmp_path):
    """TeraSort records (two-level key + 92 B payload) across the 2-process
    cluster: each host feeds local records, gets back its key-range slice."""
    from dsort_tpu.data.ingest import terasort_secondary

    _run_cluster(tmp_path, "terasort")
    kin = [np.load(tmp_path / f"in_{i}.npy") for i in range(2)]
    vin = [np.load(tmp_path / f"inv_{i}.npy") for i in range(2)]
    kout = [np.load(tmp_path / f"out_{i}.npy") for i in range(2)]
    vout = [np.load(tmp_path / f"outv_{i}.npy") for i in range(2)]
    offs = [
        json.load(open(tmp_path / f"meta_{i}.json"))["offset"] for i in range(2)
    ]
    all_k, all_v = np.concatenate(kin), np.concatenate(vin)
    got_k, got_v = np.concatenate(kout), np.concatenate(vout)
    assert offs[0] == 0 and offs[1] == len(kout[0])
    order = np.lexsort((terasort_secondary(all_v), all_k))
    np.testing.assert_array_equal(got_k, all_k[order])
    np.testing.assert_array_equal(got_v, all_v[order])


@pytest.mark.slow
def test_two_process_cluster_float32_nan(tmp_path):
    """NaN float keys survive the multi-host path too (boundary bijection)."""
    _run_cluster(tmp_path, "float32nan")

    def check(got, allin):
        expect = np.sort(allin)  # numpy: NaNs last
        k = len(allin) - np.isnan(allin).sum()
        np.testing.assert_array_equal(got[:k], expect[:k])
        assert np.isnan(got[k:]).all()

    _check(tmp_path, check)


def _mh_global_data() -> np.ndarray:
    """The deterministic global dataset of _mh_proc's 'ckpt' mode."""
    return (
        np.random.default_rng(777)
        .integers(-(10**6), 10**6, 9000)
        .astype(np.int32)
    )


def _ckpt_outputs(rundir, nprocs):
    outs = [np.load(rundir / f"out_{i}.npy") for i in range(nprocs)]
    metas = [
        json.load(open(rundir / f"meta_{i}.json")) for i in range(nprocs)
    ]
    got = np.concatenate(outs)
    off = 0
    for o, meta in zip(outs, metas):
        assert meta["offset"] == off
        off += len(o)
    return got, metas


def test_multihost_checkpoint_crash_resume(tmp_path):
    """The pod-scale recovery story (VERDICT r4 missing #1): a 2-process job
    loses a host mid-persist; re-running the SAME job_id — even with a
    DIFFERENT process count — restores the surviving host's range and
    re-sorts only the missing key interval, then a further run restores
    fully.  jax.distributed cannot re-form live, so the model is
    restart-and-resume (ARCHITECTURE 'multi-host')."""
    ck = tmp_path / "ck"
    expect = np.sort(_mh_global_data())
    env = {"DSORT_MH_CKPT_DIR": str(ck)}

    # Run 1: process 1 dies between the collective and its range persist —
    # exactly the mid-job host loss state (range_0 persisted, range_1 not).
    # The survivor persists its range but then fails at the cluster's
    # shutdown barrier (jax.distributed cannot outlive a dead host) — that
    # collapse IS the failure mode the recovery model exists for.
    r1 = tmp_path / "run1"
    r1.mkdir()
    _run_cluster(
        r1, "ckpt", nprocs=2,
        env_extra={**env, "DSORT_MH_DIE_BEFORE_RANGE": "1"},
        expect_rc={0: "any", 1: 17},
        # The survivor's persisted range is the drill's contract: a Gloo
        # SIGABRT that kills proc 0 before the persist retries the run
        # instead of flaking the assert below (VERDICT r5 weak #2).
        require_files=[ck / "mhjob" / "range_00000.npy"],
    )
    assert (ck / "mhjob" / "range_00000.npy").exists()
    assert not (ck / "mhjob" / "range_00001.npy").exists()

    # Run 2: restart with ONE process over the same global data: restores
    # range 0, re-sorts only the missing interval, output exact.
    r2 = tmp_path / "run2"
    r2.mkdir()
    _run_cluster(r2, "ckpt", nprocs=1, env_extra=env)
    got, metas = _ckpt_outputs(r2, 1)
    np.testing.assert_array_equal(got, expect)
    c = metas[0]["counters"]
    assert c.get("multihost_ranges_restored") == 1
    assert 0 < c.get("multihost_resort_keys", 0) < len(expect)
    # Fault timeline: the resume run's journal records the partial restore
    # before the job completes (job_start → checkpoint_restore → job_done).
    ev = metas[0]["events"]
    assert ev[0] == "job_start" and ev[-1] == "job_done"
    assert "checkpoint_restore" in ev
    assert ev.index("checkpoint_restore") < ev.index("job_done")

    # Run 3: back to 2 processes — the rewritten checkpoint fully restores
    # (no re-sort at all), slices stitch to the same exact output.
    r3 = tmp_path / "run3"
    r3.mkdir()
    _run_cluster(r3, "ckpt", nprocs=2, env_extra=env)
    got3, metas3 = _ckpt_outputs(r3, 2)
    np.testing.assert_array_equal(got3, expect)
    for meta in metas3:
        assert meta["counters"].get("multihost_ranges_restored", 0) >= 1
        assert "multihost_resort_keys" not in meta["counters"]


def test_multihost_crash_drill_merged_trace_and_postmortem(tmp_path):
    """PR 6 acceptance: a 2-process crash drill produces ONE merged trace
    with monotonic aligned timestamps (obs.merge over the per-process
    journals) plus a postmortem bundle naming the resume path — the
    multi-host crash-retry — and its cost (resort_keys)."""
    from dsort_tpu.obs import FlightRecorder, merge_journals, slo_from_journal

    ck = tmp_path / "ck"
    flights = tmp_path / "flights"
    expect = np.sort(_mh_global_data())
    env = {
        "DSORT_MH_CKPT_DIR": str(ck),
        "DSORT_MH_FLIGHT_DIR": str(flights),
        "DSORT_MH_TENANT": "acme",
    }

    # Run 1: the crash — process 1 dies between the collective and its
    # range persist (same drill state as the canonical crash_resume test).
    r1 = tmp_path / "run1"
    r1.mkdir()
    _run_cluster(
        r1, "ckpt", nprocs=2,
        env_extra={**env, "DSORT_MH_DIE_BEFORE_RANGE": "1"},
        expect_rc={0: "any", 1: 17},
        require_files=[ck / "mhjob" / "range_00000.npy"],
    )

    # Run 2: the crash-RETRY — both processes resume, restore range 0 and
    # re-sort only the missing interval.
    r2 = tmp_path / "run2"
    r2.mkdir()
    _run_cluster(r2, "ckpt", nprocs=2, env_extra=env)
    got, metas = _ckpt_outputs(r2, 2)
    np.testing.assert_array_equal(got, expect)

    # ONE merged fleet trace from the two per-process journals: records
    # from BOTH processes, monotonically aligned, globally re-sequenced,
    # with the clock_sync handshake pairs present per source.
    journals = [str(r2 / f"journal_{i}.jsonl") for i in range(2)]
    merged, skipped = merge_journals(journals)
    assert skipped == 0
    assert {r["src"] for r in merged} == {0, 1}
    monos = [r["mono"] for r in merged]
    assert monos == sorted(monos)
    assert [r["seq"] for r in merged] == list(range(len(merged)))
    for src in (0, 1):
        src_types = [r["type"] for r in merged if r["src"] == src]
        assert "clock_sync" in src_types
        assert src_types[0] in ("job_start", "clock_sync")
        assert "checkpoint_restore" in src_types and "job_done" in src_types
    # the merged trace carries the per-tenant SLO signal end to end
    truth = slo_from_journal(merged)
    assert ("acme", "admit_to_sorted") in truth
    assert truth[("acme", "admit_to_sorted")].count == 2  # one per process

    # ISSUE 9: the analyzer replays the SAME real merged 2-process trace
    # into a coherent why-slow verdict — the critical path names one of
    # the two processes and a phase that actually ran there, and the
    # per-source waterfall matches the journal's phase_end ground truth.
    from dsort_tpu.obs import analyze_records

    v = analyze_records(merged)
    assert set(v["sources"]) == {"p0", "p1"}
    assert v["critical_src"] in ("p0", "p1")
    assert v["critical_phase"] in v["phases"][v["critical_src"]]
    assert v["straggler"] is not None and v["straggler"]["name"] in ("p0", "p1")
    phase_truth: dict = {}
    for r in merged:
        if r["type"] == "phase_end" and isinstance(r.get("seconds"), float):
            key = (r["src"], r["phase"])
            phase_truth[key] = phase_truth.get(key, 0.0) + r["seconds"]
    for (src, phase), sec in phase_truth.items():
        assert v["phases"][f"p{src}"][phase] == pytest.approx(sec)

    # The postmortem bundle names the resume path and its cost.
    bundles = FlightRecorder.read_bundles(str(flights))
    partial = [
        b for b in bundles
        if b["recovery_path"] == "checkpoint_restore:multihost_partial"
    ]
    assert partial, f"no multihost_partial bundle in {[b['recovery_path'] for b in bundles]}"
    b = partial[0]
    assert b["detail"]["n"] == 1  # one surviving range restored
    assert 0 < b["detail"]["resort_keys"] < len(expect)  # the re-run cost
    assert b["state"]["mode"] == "multihost"
    assert b["config"]["tenant"] == "acme"
    assert any(r["type"] == "job_start" for r in b["ring"])


@pytest.mark.slow
def test_multihost_checkpoint_stale_data_clears(tmp_path):
    """A job_id resumed against DIFFERENT global data must not serve stale
    ranges: the partition-independent fingerprint mismatches and the job
    re-sorts from scratch (the single-host staleness guard, pod-scale)."""
    ck = tmp_path / "ck"
    env = {"DSORT_MH_CKPT_DIR": str(ck)}
    r1 = tmp_path / "run1"
    r1.mkdir()
    _run_cluster(r1, "ckpt", nprocs=2, env_extra=env)
    got, _ = _ckpt_outputs(r1, 2)
    np.testing.assert_array_equal(got, np.sort(_mh_global_data()))
    # Same job_id, different data (the drill flips one element via env) —
    # must NOT restore.
    r2 = tmp_path / "run2"
    r2.mkdir()
    _run_cluster(
        r2, "ckpt", nprocs=2, env_extra={**env, "DSORT_MH_FLIP_KEY": "1"},
    )
    flipped = _mh_global_data()
    flipped[0] ^= 1
    got2, metas2 = _ckpt_outputs(r2, 2)
    np.testing.assert_array_equal(got2, np.sort(flipped))
    for meta in metas2:
        assert "multihost_ranges_restored" not in meta["counters"]


@pytest.mark.slow
def test_multihost_kv_checkpoint_restore(tmp_path):
    """Record (TeraSort) jobs persist per-host (keys range, payload block)
    pairs; a restart — here with a different process count — restores the
    complete checkpoint instead of re-shuffling 92 B payloads."""
    from dsort_tpu.data.ingest import gen_terasort, terasort_secondary

    ck = tmp_path / "ck"
    env = {"DSORT_MH_CKPT_DIR": str(ck)}
    all_k, all_v = gen_terasort(3000, seed=777)
    order = np.lexsort((terasort_secondary(all_v), all_k))

    r1 = tmp_path / "run1"
    r1.mkdir()
    _run_cluster(r1, "ckpt_kv", nprocs=2, env_extra=env)
    got_k = np.concatenate(
        [np.load(r1 / f"out_{i}.npy") for i in range(2)]
    )
    np.testing.assert_array_equal(got_k, all_k[order])

    r2 = tmp_path / "run2"
    r2.mkdir()
    _run_cluster(r2, "ckpt_kv", nprocs=1, env_extra=env)
    got_k2 = np.load(r2 / "out_0.npy")
    got_v2 = np.load(r2 / "outv_0.npy")
    meta = json.load(open(r2 / "meta_0.json"))
    np.testing.assert_array_equal(got_k2, all_k[order])
    np.testing.assert_array_equal(got_v2, all_v[order])
    assert meta["counters"].get("multihost_ranges_restored") == 2
    assert meta["offset"] == 0


def test_multihost_kv_partial_checkpoint_resorts(tmp_path):
    """A kv job losing a host mid-persist leaves a PARTIAL set; the re-run
    restores the surviving (keys, payload, secondary) host set and
    re-sorts ONLY the missing RECORDS — the record-level value
    reconstruction of VERDICT r5 #2 (the (key, payload-row) multiset
    difference), with ``multihost_resort_keys`` well below the total —
    still producing the exact output."""
    from dsort_tpu.data.ingest import gen_terasort, terasort_secondary

    ck = tmp_path / "ck"
    env = {"DSORT_MH_CKPT_DIR": str(ck)}
    all_k, all_v = gen_terasort(3000, seed=777)
    order = np.lexsort((terasort_secondary(all_v), all_k))

    r1 = tmp_path / "run1"
    r1.mkdir()
    _run_cluster(
        r1, "ckpt_kv", nprocs=2,
        env_extra={**env, "DSORT_MH_DIE_BEFORE_RANGE": "1"},
        expect_rc={0: "any", 1: 17},
        # VERDICT r5 weak #2: this drill's flake mode was a Gloo SIGABRT
        # killing the survivor before its persist — tolerated by the "any"
        # rc, then failing the assert below.  Requiring the persisted
        # range routes that case into the logged one-retry treatment the
        # other multihost drills already have (the module is also
        # serial-marked, pytestmark above).
        require_files=[ck / "mhkv" / "range_00000.npy"],
    )
    assert (ck / "mhkv" / "range_00000.npy").exists()
    assert not (ck / "mhkv" / "range_00001.npy").exists()

    r2 = tmp_path / "run2"
    r2.mkdir()
    _run_cluster(r2, "ckpt_kv", nprocs=2, env_extra=env)
    got_k = np.concatenate([np.load(r2 / f"out_{i}.npy") for i in range(2)])
    got_v = np.concatenate([np.load(r2 / f"outv_{i}.npy") for i in range(2)])
    np.testing.assert_array_equal(got_k, all_k[order])
    np.testing.assert_array_equal(got_v, all_v[order])
    metas = [json.load(open(r2 / f"meta_{i}.json")) for i in range(2)]
    for meta in metas:
        # The surviving host set restores; only the dead host's records
        # (plus boundary-key copies) re-sort — NOT the whole job.
        c = meta["counters"]
        assert c.get("multihost_ranges_restored") == 1
        assert 0 < c.get("multihost_resort_keys", 0) <= 0.75 * len(all_k)
        assert "checkpoint_restore" in meta["events"]

    # And the re-persisted state from run 2 restores fully on a third run.
    r3 = tmp_path / "run3"
    r3.mkdir()
    _run_cluster(r3, "ckpt_kv", nprocs=2, env_extra=env)
    metas3 = [json.load(open(r3 / f"meta_{i}.json")) for i in range(2)]
    for meta in metas3:
        assert meta["counters"].get("multihost_ranges_restored") == 2


# ---- single-process regressions (ADVICE r5) -------------------------------
#
# These force internal branches directly (no subprocess cluster needed: the
# multihost drivers run single-process against the simulated mesh, with the
# cross-host decisions monkeypatched to the raced outcome).


def test_mh_stale_clear_resets_valid_keys_path(tmp_path, monkeypatch):
    """ADVICE r5 medium: `_mh_stale_clear` returning True on a process that
    computed valid=True (the raced directory listing its allgather exists to
    cover) must fall through to the fresh sort — before the fix it crashed
    on `int(None["n_ranges"])` and diverged peers at the next barrier."""
    from dsort_tpu.config import JobConfig
    from dsort_tpu.parallel import distributed as dist
    from dsort_tpu.utils.metrics import Metrics

    rng = np.random.default_rng(51)
    data = rng.integers(0, 10**6, 20_000).astype(np.int32)
    job = JobConfig(checkpoint_dir=str(tmp_path))
    out, off = dist.sort_local_shards(
        data, job=job, metrics=Metrics(), job_id="stale"
    )
    np.testing.assert_array_equal(out, np.sort(data))
    # Second run WOULD full-restore (manifest + range valid) — force the
    # raced-clear vote instead: some peer saw stale state and everyone
    # agreed to clear.
    monkeypatch.setattr(dist, "_mh_stale_clear", lambda *a, **k: True)
    m = Metrics()
    out2, off2 = dist.sort_local_shards(
        data, job=job, metrics=m, job_id="stale"
    )
    np.testing.assert_array_equal(out2, np.sort(data))
    assert off2 == 0
    # the restore path never ran: the job re-sorted fresh
    assert "multihost_ranges_restored" not in m.counters


def test_mh_stale_clear_resets_valid_kv_path(tmp_path, monkeypatch):
    """The same raced-clear regression on `_sort_local_records_ckpt`
    (ADVICE r5 names both call sites)."""
    from dsort_tpu.config import JobConfig
    from dsort_tpu.data.ingest import gen_terasort, terasort_secondary
    from dsort_tpu.parallel import distributed as dist
    from dsort_tpu.utils.metrics import Metrics

    keys, payload = gen_terasort(2000, seed=53)
    sec = terasort_secondary(payload)
    order = np.lexsort((sec, keys))
    job = JobConfig(checkpoint_dir=str(tmp_path), key_dtype=np.uint64)
    out_k, out_v, _ = dist.sort_local_records(
        keys, payload, secondary=sec, job=job, metrics=Metrics(),
        job_id="stale_kv",
    )
    np.testing.assert_array_equal(out_k, keys[order])
    monkeypatch.setattr(dist, "_mh_stale_clear", lambda *a, **k: True)
    m = Metrics()
    out_k2, out_v2, off2 = dist.sort_local_records(
        keys, payload, secondary=sec, job=job, metrics=m, job_id="stale_kv"
    )
    np.testing.assert_array_equal(out_k2, keys[order])
    np.testing.assert_array_equal(out_v2, payload[order])
    assert off2 == 0
    assert "multihost_ranges_restored" not in m.counters


def test_global_fingerprint_tag_mismatch_raises(monkeypatch):
    """ADVICE r5 low: hosts passing different dtypes/payload shapes must
    fail loudly at the fingerprint allgather, not deadlock at a later
    barrier with divergent `valid` decisions."""
    from dsort_tpu.parallel import distributed as dist

    real = dist._allgather_u64

    def two_hosts_one_differs(vals):
        g = real(vals)
        if g.shape[1] == 3:  # the (h, n, tag_hash) fingerprint gather
            g = np.vstack([g, g])
            g[1, 2] ^= np.uint64(1)  # host 1 computed a different tag
        return g

    monkeypatch.setattr(dist, "_allgather_u64", two_hosts_one_differs)
    data = np.arange(100, dtype=np.int32)
    with pytest.raises(ValueError, match="tag disagrees"):
        dist._global_fingerprint(data)
    # agreeing hosts still fingerprint fine (identical rows)
    monkeypatch.setattr(
        dist, "_allgather_u64",
        lambda vals: np.vstack([real(vals), real(vals)]),
    )
    fp, total = dist._global_fingerprint(data)
    assert total == 200  # two simulated hosts' counts sum
