"""Multi-host (multi-process) distributed sort over the DCN path.

The reference's "multi-node" test strategy is several processes on one
machine talking TCP (SURVEY.md §4).  The TPU-native equivalent: a REAL
2-process JAX cluster (``jax.distributed.initialize`` on the CPU backend,
cross-process collectives over Gloo — the same code path that rides DCN on
a pod), each process feeding host-local data into
`parallel.distributed.sort_local_shards` and getting back its own devices'
slice of the globally sorted, range-partitioned output.
"""

import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_PROC = os.path.join(REPO, "tests", "_mh_proc.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _run_cluster(tmp_path, dtype: str, nprocs: int = 2) -> None:
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    port = _free_port()
    procs = [
        subprocess.Popen(
            [sys.executable, _PROC, str(pid), str(port), str(tmp_path), dtype,
             str(nprocs)],
            env=env,
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
        )
        for pid in range(nprocs)
    ]
    try:
        for p in procs:
            out, err = p.communicate(timeout=300)
            assert p.returncode == 0, err.decode()[-2000:]
    finally:  # a hung cluster must not leak live jax processes into CI
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait(timeout=10)


def _check(tmp_path, sort_like_numpy, nprocs: int = 2) -> None:
    ins = [np.load(tmp_path / f"in_{i}.npy") for i in range(nprocs)]
    outs = [np.load(tmp_path / f"out_{i}.npy") for i in range(nprocs)]
    offs = [
        json.load(open(tmp_path / f"meta_{i}.json"))["offset"]
        for i in range(nprocs)
    ]
    got = np.concatenate(outs)
    allin = np.concatenate(ins)
    assert len(got) == len(allin)
    # Offsets stitch the slices back contiguously in global order.
    expect_off = 0
    for i in range(nprocs):
        assert offs[i] == expect_off
        expect_off += len(outs[i])
    sort_like_numpy(got, allin)


def test_two_process_cluster_int32(tmp_path):
    _run_cluster(tmp_path, "int32")
    _check(
        tmp_path,
        lambda got, allin: np.testing.assert_array_equal(got, np.sort(allin)),
    )


def test_three_process_cluster_int32(tmp_path):
    """3 processes x 2 devices: odd process counts exercise the process-major
    device-order/offset math beyond the 2-way split."""
    _run_cluster(tmp_path, "int32", nprocs=3)
    _check(
        tmp_path,
        lambda got, allin: np.testing.assert_array_equal(got, np.sort(allin)),
        nprocs=3,
    )


def test_two_process_cluster_terasort_records(tmp_path):
    """TeraSort records (two-level key + 92 B payload) across the 2-process
    cluster: each host feeds local records, gets back its key-range slice."""
    from dsort_tpu.data.ingest import terasort_secondary

    _run_cluster(tmp_path, "terasort")
    kin = [np.load(tmp_path / f"in_{i}.npy") for i in range(2)]
    vin = [np.load(tmp_path / f"inv_{i}.npy") for i in range(2)]
    kout = [np.load(tmp_path / f"out_{i}.npy") for i in range(2)]
    vout = [np.load(tmp_path / f"outv_{i}.npy") for i in range(2)]
    offs = [
        json.load(open(tmp_path / f"meta_{i}.json"))["offset"] for i in range(2)
    ]
    all_k, all_v = np.concatenate(kin), np.concatenate(vin)
    got_k, got_v = np.concatenate(kout), np.concatenate(vout)
    assert offs[0] == 0 and offs[1] == len(kout[0])
    order = np.lexsort((terasort_secondary(all_v), all_k))
    np.testing.assert_array_equal(got_k, all_k[order])
    np.testing.assert_array_equal(got_v, all_v[order])


def test_two_process_cluster_float32_nan(tmp_path):
    """NaN float keys survive the multi-host path too (boundary bijection)."""
    _run_cluster(tmp_path, "float32nan")

    def check(got, allin):
        expect = np.sort(allin)  # numpy: NaNs last
        k = len(allin) - np.isnan(allin).sum()
        np.testing.assert_array_equal(got[:k], expect[:k])
        assert np.isnan(got[k:]).all()

    _check(tmp_path, check)
