"""CLI + checkpoint/recovery tests."""

import os

import numpy as np
import pytest

from dsort_tpu.checkpoint import ShardCheckpoint
from dsort_tpu.cli import main as cli_main
from dsort_tpu.config import JobConfig
from dsort_tpu.data.ingest import gen_uniform, read_ints_file, write_ints_file
from dsort_tpu.scheduler import DeviceExecutor, FaultInjector, JobFailedError, Scheduler
from dsort_tpu.utils.metrics import Metrics


def test_cli_run_roundtrip(tmp_path):
    inp, outp = tmp_path / "in.txt", tmp_path / "out.txt"
    data = gen_uniform(5_000, seed=31)
    write_ints_file(inp, data)
    rc = cli_main(["run", str(inp), "-o", str(outp), "--mode", "spmd"])
    assert rc == 0
    np.testing.assert_array_equal(read_ints_file(outp), np.sort(data))


def test_cli_gen_and_run_taskpool(tmp_path):
    inp, outp = tmp_path / "g.txt", tmp_path / "o.txt"
    assert cli_main(["gen", "3000", "-o", str(inp), "--dist", "zipf"]) == 0
    assert cli_main(["run", str(inp), "-o", str(outp), "--mode", "taskpool",
                     "--dtype", "int64"]) == 0
    data = read_ints_file(inp, dtype=np.int64)
    np.testing.assert_array_equal(read_ints_file(outp, dtype=np.int64), np.sort(data))


def test_cli_bench_json(tmp_path, capsys):
    assert cli_main(["bench", "--n", "20000", "--reps", "1", "--mode", "local"]) == 0
    import json

    line = capsys.readouterr().out.strip()
    rec = json.loads(line)
    assert set(rec) == {"metric", "value", "unit", "vs_baseline"}
    assert rec["vs_baseline"] > 1.0


def test_cli_serve_repl(tmp_path, monkeypatch, capsys):
    # The reference REPL workflow: two jobs then 'exit' (server.c:160-167).
    inp1, inp2, outp = tmp_path / "a.txt", tmp_path / "b.txt", tmp_path / "out.txt"
    d1, d2 = gen_uniform(100, seed=1), gen_uniform(200, seed=2)
    write_ints_file(inp1, d1)
    write_ints_file(inp2, d2)
    lines = iter([str(inp1), "not_a_file.txt", str(inp2), "exit"])
    monkeypatch.setattr("builtins.input", lambda *_: next(lines))
    rc = cli_main(["serve", "-o", str(outp), "--mode", "local"])
    assert rc == 0  # the bad job must not kill the server
    np.testing.assert_array_equal(read_ints_file(outp), np.sort(d2))


def test_shard_checkpoint_roundtrip(tmp_path):
    ck = ShardCheckpoint(str(tmp_path), "job1")
    assert not ck.has(0)
    arr = np.arange(10, dtype=np.int64)
    ck.save(0, arr)
    ck.save(3, arr * 2)
    assert ck.has(0) and ck.has(3) and not ck.has(1)
    np.testing.assert_array_equal(ck.load(3), arr * 2)
    assert ck.completed_shards() == [0, 3]
    ck.write_manifest(4, np.int64, 40)
    assert ck.manifest()["num_shards"] == 4
    ck.clear()
    assert ck.completed_shards() == []


def test_shard_checkpoint_namespaces_clear_independently(tmp_path):
    """`clear_shards` drops ONLY the shard namespace and `clear_ranges` only
    the ranges — the multihost recovery paths rely on that separation (the
    kv payload halves and the resume publish channel live in shards; a
    stale-clear must drop both but a range rewrite must not touch a
    concurrent reader's shards)."""
    ck = ShardCheckpoint(str(tmp_path), "jobns")
    ck.save(0, np.arange(4, dtype=np.int32))
    ck.save_range(1, np.arange(6, dtype=np.int32))
    ck.clear_shards()
    assert ck.completed_shards() == []
    assert ck.completed_ranges() == [1]
    ck.save(2, np.arange(3, dtype=np.int32))
    ck.clear_ranges()
    assert ck.completed_ranges() == []
    assert ck.completed_shards() == [2]


def test_shard_checkpoint_mmap_reads(tmp_path):
    """`load_mmap` / `load_range_mmap` return mmap-backed arrays equal to
    their np.load twins — the O(chunk) restore path depends on them."""
    ck = ShardCheckpoint(str(tmp_path), "jobmm")
    a = np.arange(1000, dtype=np.int64)
    ck.save(0, a)
    ck.save_range(2, a[::-1].copy())
    m = ck.load_mmap(0)
    r = ck.load_range_mmap(2)
    assert isinstance(m, np.memmap) and isinstance(r, np.memmap)
    np.testing.assert_array_equal(np.asarray(m), a)
    np.testing.assert_array_equal(np.asarray(r), a[::-1])
    # Slices materialize only the touched region (basic contract check).
    np.testing.assert_array_equal(np.asarray(r[10:20]), a[::-1][10:20])


def test_merge_split_and_slice_parts():
    """The resume path's rank-bisection merge slicing is exact on ragged
    parts with duplicate keys across the split boundary."""
    from dsort_tpu.parallel.distributed import (
        _CatParts,
        _merge_slice,
        _merge_split,
    )

    rng = np.random.default_rng(7)
    a_flat = np.sort(rng.integers(0, 50, 155).astype(np.int32))
    a_parts = []  # split the sorted stream into ragged consecutive parts
    off = 0
    for n in (17, 0, 80, 58):
        a_parts.append(a_flat[off : off + n])
        off += n
    b = np.sort(rng.integers(0, 50, 71).astype(np.int32))
    a = _CatParts(a_parts)
    merged = np.sort(np.concatenate([a_flat, b]))
    total = len(merged)
    for start, stop in [(0, total), (0, 0), (13, 13), (1, total - 1),
                        (total // 3, 2 * total // 3)]:
        got = _merge_slice(a, _CatParts([b]), start, stop)
        np.testing.assert_array_equal(got, merged[start:stop])
    for k in (0, 1, total // 2, total):
        i, j = _merge_split(a, _CatParts([b]), k)
        assert i + j == k


def test_global_fingerprint_empty_host_layout_stable():
    """An EMPTY-ingest host must compute the same dtype tag (and row
    layout) as its peers — widths come from metadata, never inferred from
    the data — or resume control flow diverges across processes and the
    barriers deadlock (r5 review finding)."""
    from dsort_tpu.parallel.distributed import _global_fingerprint

    k = np.arange(5, dtype=np.uint64)
    v = np.zeros((5, 92), np.uint8)
    fp_full, total_full = _global_fingerprint(k, payload=v)
    fp_empty, total_empty = _global_fingerprint(k[:0], payload=v[:0])
    # fp format is "total:dt:checksum" — the dt segment must match.
    assert fp_full.split(":")[1] == fp_empty.split(":")[1]
    assert total_full == 5 and total_empty == 0
    # Keys-only path too.
    fk, _ = _global_fingerprint(k)
    fe, _ = _global_fingerprint(k[:0])
    assert fk.split(":")[1] == fe.split(":")[1]


def test_job_recovery_skips_completed_shards(tmp_path):
    """Fail a job midway, then re-run: only lost shards are re-sorted."""
    data = gen_uniform(8_000, seed=33)
    job = JobConfig(
        settle_delay_s=0.01, checkpoint_dir=str(tmp_path), heartbeat_timeout_s=5.0
    )
    inj = FaultInjector()
    sched = Scheduler(DeviceExecutor(injector=inj), job)
    w = sched.executor.num_workers
    # Run 1: workers 4..7 dead AND worker 0 dies after 3 successful shards —
    # engineered instead: kill everything so some shards fail after others
    # complete.  Simplest deterministic split: fail shards on workers >= 2 by
    # killing 2..7; shards 0,1 complete and checkpoint, rest reassign to 0/1
    # and also complete... so instead kill ALL after first exchange: use
    # one-shot failures on workers 2..7 and permanent kill on 0..1 swapped.
    for i in range(2, w):
        inj.kill(i)
    out1 = sched.run_job(data, job_id="jobA")  # completes via reassignment
    np.testing.assert_array_equal(out1, np.sort(data))
    # Run 2 of the same job: every shard restores from checkpoint; even with
    # ALL workers dead the job succeeds without any compute.
    inj2 = FaultInjector()
    for i in range(w):
        inj2.kill(i)
    sched2 = Scheduler(DeviceExecutor(injector=inj2), job)
    m = Metrics()
    out2 = sched2.run_job(data, metrics=m, job_id="jobA")
    np.testing.assert_array_equal(out2, np.sort(data))
    assert m.counters["shards_restored"] == w
    # Without the checkpoint the same scheduler fails cleanly.
    with pytest.raises(JobFailedError):
        sched2.run_job(data, job_id="jobB")


def test_cli_terasort_binary_roundtrip(tmp_path):
    from dsort_tpu.data.ingest import read_terasort_file

    inp, outp = tmp_path / "t.bin", tmp_path / "t_out.bin"
    assert cli_main(["gen", "2000", "-o", str(inp), "--dist", "terasort"]) == 0
    assert cli_main(["terasort", str(inp), "-o", str(outp), "--workers", "8"]) == 0
    from dsort_tpu.data.ingest import terasort_secondary

    k_in, v_in = read_terasort_file(inp)
    k_out, v_out = read_terasort_file(outp)
    np.testing.assert_array_equal(k_out, np.sort(k_in))
    # output is ordered by the FULL 10-byte key (secondary breaks prefix ties)
    s_out = terasort_secondary(v_out)
    lex_ok = (k_out[1:] > k_out[:-1]) | (
        (k_out[1:] == k_out[:-1]) & (s_out[1:] >= s_out[:-1])
    )
    assert lex_ok.all()
    # full records preserved as a multiset
    assert sorted(zip(k_out.tolist(), map(bytes, v_out))) == sorted(
        zip(k_in.tolist(), map(bytes, v_in))
    )


def test_multihost_initialize_noop_without_env(monkeypatch):
    from dsort_tpu.parallel.distributed import initialize_multihost

    monkeypatch.delenv("JAX_COORDINATOR_ADDRESS", raising=False)
    assert initialize_multihost() is False


def test_global_worker_mesh():
    from dsort_tpu.parallel.distributed import global_worker_mesh

    mesh = global_worker_mesh()
    assert mesh.shape["w"] >= 8


def test_cli_subprocess_enables_x64(tmp_path):
    """64-bit CLI paths must work in a fresh process (no conftest x64).

    Regression: `dsort external --dtype int64` / `dsort terasort` crashed
    outside the test harness because nothing enabled jax_enable_x64 before
    configs were built — the CLI must do it itself.
    """
    import os
    import subprocess
    import sys

    big = tmp_path / "big.bin"
    out = tmp_path / "big_sorted.bin"
    data = np.random.default_rng(7).integers(
        -(2**63), 2**63 - 1, 20_000, dtype=np.int64
    )
    data.tofile(big)
    env = dict(os.environ)
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env.pop("JAX_ENABLE_X64", None)
    env.pop("PALLAS_AXON_POOL_IPS", None)  # site hook hangs with cpu pinned
    env["JAX_PLATFORMS"] = "cpu"
    subprocess.run(
        [sys.executable, "-m", "dsort_tpu.cli", "external", str(big),
         "-o", str(out), "--dtype", "int64"],
        check=True, env=env, timeout=300,
    )
    np.testing.assert_array_equal(
        np.fromfile(out, dtype=np.int64), np.sort(data)
    )


def test_cli_bench_suite_runs_all_configs():
    """The BASELINE config ladder emits one valid JSON line per config."""
    import json as _json
    import subprocess
    import sys

    env = {**os.environ, "JAX_PLATFORMS": "cpu", "PALLAS_AXON_POOL_IPS": ""}
    r = subprocess.run(
        [sys.executable, "-m", "dsort_tpu.cli", "bench", "--suite", "--reps", "1"],
        env=env, capture_output=True, text=True, timeout=480,
    )
    assert r.returncode == 0, r.stderr[-2000:]
    lines = [l for l in r.stdout.splitlines() if l.startswith("{")]
    assert len(lines) == 5
    metrics = [_json.loads(l) for l in lines]
    assert [m["metric"][:7] for m in metrics] == [
        f"config{i}" for i in range(1, 6)
    ]
    # Structural only: throughput thresholds are hardware/load-dependent and
    # belong in the benchmark artifact, not a correctness test (ADVICE r1).
    assert all(m["value"] > 0 for m in metrics)
    assert all(("vs_baseline" in m) == (m["unit"] == "keys/sec") for m in metrics)


def test_cli_run_with_checkpoint_resume(tmp_path):
    """dsort run --checkpoint-dir: first run persists ranges under the
    input-derived job id; a re-run takes the full-restore path."""
    from dsort_tpu import cli
    from dsort_tpu.data.ingest import write_ints_file

    rng = np.random.default_rng(31)
    data = rng.integers(0, 10**6, 50_000).astype(np.int32)
    src = tmp_path / "ck_input.txt"
    out = tmp_path / "out.txt"
    write_ints_file(src, data)
    ck = tmp_path / "ck"
    argv = ["run", str(src), "-o", str(out), "--mode", "spmd",
            "--checkpoint-dir", str(ck)]
    assert cli.main(argv) == 0
    job_dir = ck / "ck_input.txt"
    assert job_dir.is_dir() and any(
        n.startswith("range_") for n in os.listdir(job_dir)
    )
    got1 = np.loadtxt(out, dtype=np.int64).astype(np.int32)
    np.testing.assert_array_equal(got1, np.sort(data))
    # wipe the output; the re-run restores from the checkpoint and rewrites
    out.unlink()
    assert cli.main(argv) == 0
    got2 = np.loadtxt(out, dtype=np.int64).astype(np.int32)
    np.testing.assert_array_equal(got2, np.sort(data))
    # changed data under the same filename: stale state cleared, still exact
    data2 = rng.integers(0, 10**6, 50_000).astype(np.int32)
    write_ints_file(src, data2)
    assert cli.main(argv) == 0
    got3 = np.loadtxt(out, dtype=np.int64).astype(np.int32)
    np.testing.assert_array_equal(got3, np.sort(data2))


def test_cli_taskpool_checkpoint_flag(tmp_path):
    from dsort_tpu import cli
    from dsort_tpu.data.ingest import write_ints_file

    rng = np.random.default_rng(33)
    data = rng.integers(0, 1000, 9_000).astype(np.int32)
    src = tmp_path / "tp_in.txt"
    out = tmp_path / "tp_out.txt"
    write_ints_file(src, data)
    argv = ["run", str(src), "-o", str(out), "--mode", "taskpool",
            "--checkpoint-dir", str(tmp_path / "ck2"), "--job-id", "tpjob"]
    assert cli.main(argv) == 0
    assert any(
        n.startswith("shard_")
        for n in os.listdir(tmp_path / "ck2" / "tpjob")
    )
    got = np.loadtxt(out, dtype=np.int64).astype(np.int32)
    np.testing.assert_array_equal(got, np.sort(data))


def test_cli_job_id_path_escape_rejected(tmp_path):
    """'..' or separator job ids must be refused, not resolved (a '..' id
    plus the stale-state clear() would rmtree the checkpoint PARENT)."""
    from dsort_tpu import cli

    src = tmp_path / "x.txt"
    write_ints_file(src, np.arange(10, dtype=np.int32))
    for bad in ("..", ".", "a/b", "a\\b", "..."):
        with pytest.raises(SystemExit):
            cli.main([
                "run", str(src), "-o", str(tmp_path / "o.txt"),
                "--checkpoint-dir", str(tmp_path / "ck"), "--job-id", bad,
            ])
    with pytest.raises(ValueError):
        ShardCheckpoint(str(tmp_path / "ck"), "..")


def test_cli_conf_plus_flag_keeps_conf_settings(tmp_path):
    """A CLI override must not silently drop unrelated conf-file settings."""
    from dsort_tpu import cli

    conf = tmp_path / "c.conf"
    conf.write_text("OVERSAMPLE=64\nCAPACITY_FACTOR=3.0\nOUTPUT_PATH=zz.txt\n")

    class A:
        pass

    a = A()
    a.conf = str(conf)
    a.workers = None
    a.dtype = None
    a.kernel = None
    a.checkpoint_dir = str(tmp_path / "ck")
    cfg = cli._load_config(a)
    assert cfg.job.oversample == 64
    assert cfg.job.capacity_factor == 3.0
    assert cfg.output_path == "zz.txt"
    assert cfg.job.checkpoint_dir == str(tmp_path / "ck")


def test_cli_batch_sorts_many_files(tmp_path):
    """dsort batch: many files through ONE (dp, w) batched SPMD program."""
    from dsort_tpu import cli

    rng = np.random.default_rng(37)
    paths = []
    datas = []
    for i, n in enumerate((5_000, 12_345, 17)):
        d = rng.integers(-1000, 1000, n).astype(np.int32)
        p = tmp_path / f"job{i}.txt"
        write_ints_file(p, d)
        paths.append(str(p))
        datas.append(d)
    outdir = tmp_path / "sorted"
    assert cli.main(
        ["batch", *paths, "--outdir", str(outdir), "--dp", "2", "--workers", "4"]
    ) == 0
    for p, d in zip(paths, datas):
        got = read_ints_file(outdir / os.path.basename(p))
        np.testing.assert_array_equal(got, np.sort(d))


def test_cli_batch_rejects_duplicate_basenames(tmp_path):
    from dsort_tpu import cli

    (tmp_path / "a").mkdir()
    (tmp_path / "b").mkdir()
    for d in ("a", "b"):
        write_ints_file(tmp_path / d / "same.txt", np.arange(5, dtype=np.int32))
    with pytest.raises(SystemExit, match="duplicate"):
        cli.main([
            "batch", str(tmp_path / "a" / "same.txt"),
            str(tmp_path / "b" / "same.txt"), "--outdir", str(tmp_path / "o"),
        ])


def test_cli_batch_overcommit_clean_error(tmp_path):
    from dsort_tpu import cli

    src = tmp_path / "x.txt"
    write_ints_file(src, np.arange(10, dtype=np.int32))
    with pytest.raises(SystemExit, match="devices"):
        cli.main([
            "batch", str(src), "--outdir", str(tmp_path / "o"),
            "--dp", "2", "--workers", "8",
        ])


def test_cli_batch_checkpoint_resume(tmp_path):
    """`dsort batch --checkpoint-dir`: a re-run restores every completed
    file from its checkpoint instead of re-sorting (VERDICT r3 #7)."""
    import dsort_tpu.parallel.sample_sort as ssmod

    ins = []
    datas = []
    for i, n in enumerate((4_000, 1_000, 7_000)):
        p = tmp_path / f"b{i}.txt"
        d = gen_uniform(n, seed=40 + i)
        write_ints_file(p, d)
        ins.append(str(p))
        datas.append(d)
    outdir, ck = str(tmp_path / "out"), str(tmp_path / "ck")
    rc = cli_main(["batch", *ins, "--outdir", outdir, "--checkpoint-dir", ck])
    assert rc == 0
    for i, d in enumerate(datas):
        np.testing.assert_array_equal(
            read_ints_file(os.path.join(outdir, f"b{i}.txt")), np.sort(d)
        )
    # Second run: every job restores; no bucket program executes.
    calls = []
    orig = ssmod.BatchSampleSort._run_bucket
    ssmod.BatchSampleSort._run_bucket = (
        lambda self, ks, vs, cap, m: calls.append(cap) or orig(self, ks, vs, cap, m)
    )
    try:
        rc = cli_main(
            ["batch", *ins, "--outdir", outdir, "--checkpoint-dir", ck]
        )
    finally:
        ssmod.BatchSampleSort._run_bucket = orig
    assert rc == 0
    assert calls == []
    for i, d in enumerate(datas):
        np.testing.assert_array_equal(
            read_ints_file(os.path.join(outdir, f"b{i}.txt")), np.sort(d)
        )
