"""Profiler hooks actually capture (SURVEY.md §5.1 — the reference has none)."""

import os
import subprocess
import sys

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_profile_trace_writes_capture(tmp_path):
    from dsort_tpu.parallel import SampleSort, local_device_mesh
    from dsort_tpu.utils.tracing import profile_trace

    x = np.random.default_rng(0).integers(0, 10**6, 20_000).astype(np.int32)
    logdir = str(tmp_path / "trace")
    with profile_trace(logdir):
        out = SampleSort(local_device_mesh(8)).sort(x)
    assert (out == np.sort(x)).all()
    # jax.profiler writes plugins/profile/<ts>/*.xplane.pb under the logdir
    captures = [
        os.path.join(root, f)
        for root, _, files in os.walk(logdir)
        for f in files
        if f.endswith(".xplane.pb") or f.endswith(".trace.json.gz")
    ]
    assert captures, f"no profiler capture under {logdir}"


def test_profile_trace_none_is_noop():
    from dsort_tpu.utils.tracing import profile_trace

    with profile_trace(None):
        pass  # must not require jax or create anything


def test_cli_run_profile_dir(tmp_path):
    env = {**os.environ, "JAX_PLATFORMS": "cpu", "PALLAS_AXON_POOL_IPS": ""}
    in_path = str(tmp_path / "in.txt")
    np.savetxt(in_path, np.random.default_rng(1).integers(0, 1000, 5000), fmt="%d")
    prof = str(tmp_path / "prof")
    r = subprocess.run(
        [sys.executable, "-m", "dsort_tpu.cli", "run", in_path,
         "-o", str(tmp_path / "out.txt"), "--profile-dir", prof],
        env=env, capture_output=True, text=True, timeout=240,
    )
    assert r.returncode == 0, r.stderr
    assert os.path.isdir(prof) and any(os.scandir(prof))


def test_annotate_propagates_body_exceptions():
    """Regression: a try/except wrapping the yield swallowed body exceptions,
    breaking JobFailedError propagation across PhaseTimer phases."""
    import pytest

    from dsort_tpu.utils.metrics import Metrics, PhaseTimer
    from dsort_tpu.utils.tracing import annotate

    with pytest.raises(RuntimeError, match="boom"):
        with annotate("x"):
            raise RuntimeError("boom")
    t = PhaseTimer(Metrics())
    with pytest.raises(RuntimeError, match="boom"):
        with t.phase("p"):
            raise RuntimeError("boom")
