"""Serving-layer tests (ISSUE 7, ARCHITECTURE §8): admission control,
deficit-round-robin fairness (asserted from the journal, never from
sleeps), mesh-slice packing bit-identical to serial execution, the
compiled-variant cache, concurrent-job fault drills with per-eviction
flight bundles, graceful shutdown, and the `dsort serve` / `dsort bench
--serve-mixed` CLI gates."""

import json
import os

import numpy as np
import pytest

from dsort_tpu.analysis.spec import assert_conformant
from dsort_tpu.config import ConfigError, JobConfig, ServeConfig, SortConfig
from dsort_tpu.obs import Telemetry
from dsort_tpu.obs.telemetry import parse_prometheus_text
from dsort_tpu.scheduler import FaultInjector
from dsort_tpu.serve import (
    ADMISSION_REASONS,
    Admission,
    AdmissionController,
    DeficitRoundRobin,
    ServiceClosed,
    SortService,
    VariantCache,
    fused_variant_key,
    parse_weights,
)
from dsort_tpu.utils.events import EVENT_TYPES, EventLog

JOB = JobConfig(settle_delay_s=0.01)


def _svc(tmp=None, injector=None, telemetry=None, journal=None, start=True,
         serve=None, job=None):
    job = job or JOB
    if tmp is not None:
        import dataclasses

        job = dataclasses.replace(job, flight_recorder_dir=str(tmp))
    return SortService(
        job=job,
        serve=serve or ServeConfig(small_job_max=1 << 18,
                                   max_tenant_inflight=32,
                                   max_queue_depth=128),
        telemetry=telemetry, journal=journal, injector=injector, start=start,
    )


def _events(journal):
    return [(e.type, e.fields) for e in journal.events()]


# -- admission control -------------------------------------------------------


def test_admission_verdict_vocabulary():
    with pytest.raises(ValueError, match="unknown admission reason"):
        Admission(False, "because", "t", 0, 0)
    ctl = AdmissionController(max_queue_depth=2, max_tenant_inflight=1)
    v1 = ctl.consider("a", shutting_down=False)
    assert v1.admitted and v1.reason == "admitted" and v1.queue_depth == 1
    v2 = ctl.consider("a", shutting_down=False)
    assert not v2.admitted and v2.reason == "tenant_limit"
    ctl.consider("b", shutting_down=False)
    v3 = ctl.consider("c", shutting_down=False)
    assert v3.reason == "queue_full"
    v4 = ctl.consider("a", shutting_down=True)
    assert v4.reason == "shutting_down"
    # release: a finished improves the tenant budget, dequeue the queue
    ctl.dequeued()
    ctl.finished("a")
    v5 = ctl.consider("a", shutting_down=False)
    assert v5.admitted


def test_service_rejects_beyond_queue_depth(devices):
    journal = EventLog()
    tel = Telemetry()
    svc = SortService(
        job=JOB,
        serve=ServeConfig(max_queue_depth=3, max_tenant_inflight=2,
                          small_job_max=1 << 18),
        telemetry=tel, journal=journal, start=False,
    )
    data = np.arange(100, dtype=np.int32)
    verdicts = [svc.submit(data, tenant=f"t{i}")[0] for i in range(5)]
    reasons = [v.reason for v in verdicts]
    assert reasons[:3] == ["admitted"] * 3
    assert set(reasons[3:]) <= {"queue_full", "tenant_limit"}
    # tenant_limit fires independently of global depth
    v_same = svc.submit(data, tenant="t0")[0]
    assert not v_same.admitted
    svc.shutdown(drain=True)
    types = [t for t, _ in _events(journal)]
    assert types.count("job_admitted") == 3
    assert types.count("job_rejected") == 3
    # every verdict reached the per-tenant admission series
    snap = tel.snapshot()
    assert sum(snap["admissions"].values()) == 6
    assert snap["admissions"]["t0/admitted"] == 1


# -- deficit round robin -----------------------------------------------------


def test_parse_weights():
    assert parse_weights("acme=2, blue=1.5") == {"acme": 2.0, "blue": 1.5}
    assert parse_weights(None) == {}
    with pytest.raises(ValueError, match="NAME=WEIGHT"):
        parse_weights("acme")
    with pytest.raises(ValueError, match="> 0"):
        parse_weights("acme=0")


def test_drr_interleaves_tenants():
    drr = DeficitRoundRobin(quantum=10)
    for i in range(6):
        drr.push("heavy", 10, f"h{i}")
    for i in range(2):
        drr.push("light", 10, f"l{i}")
    order = []
    while True:
        nxt = drr.pop()
        if nxt is None:
            break
        order.append(nxt[0])
    # one job per visit at quantum == cost: strict alternation while both
    # queues are non-empty, then the heavy backlog drains
    assert order[:4] == ["heavy", "light", "heavy", "light"]
    assert len(order) == 8 and order.count("light") == 2


def test_drr_weights_give_proportional_share():
    drr = DeficitRoundRobin(quantum=10, weights={"gold": 2.0})
    for i in range(8):
        drr.push("gold", 10, i)
        drr.push("base", 10, i)
    first8 = [drr.pop()[0] for _ in range(8)]
    assert first8.count("gold") >= 5  # ~2/3 share for weight 2


def test_drr_big_job_accumulates_without_starving():
    drr = DeficitRoundRobin(quantum=10)
    drr.push("big", 100, "B")
    for i in range(5):
        drr.push("small", 10, f"s{i}")
    order = [drr.pop()[1] for _ in range(6)]
    assert set(order[:5]) == {"s0", "s1", "s2", "s3", "s4"}
    assert order[5] == "B"  # dispatched once its deficit covers the cost


def test_drr_idle_tenant_banks_no_credit():
    drr = DeficitRoundRobin(quantum=10)
    drr.push("a", 10, "a0")
    assert drr.pop() == ("a", "a0")
    # 'a' drained; many rounds later it must not burst ahead of 'b'
    for i in range(3):
        drr.push("b", 10, f"b{i}")
    drr.push("a", 10, "a1")
    order = [drr.pop()[0] for _ in range(4)]
    assert order.count("a") == 1


# -- compiled-variant cache --------------------------------------------------


def test_variant_cache_lru_and_counters():
    from dsort_tpu.utils.metrics import Metrics

    m = Metrics()
    cache = VariantCache(max_entries=2)
    built = []

    def builder(tag):
        return lambda: built.append(tag) or tag

    assert cache.get_or_build(("k", 1), builder(1), metrics=m) == 1
    assert cache.get_or_build(("k", 1), builder("dup"), metrics=m) == 1
    assert cache.get_or_build(("k", 2), builder(2), metrics=m) == 2
    assert cache.get_or_build(("k", 3), builder(3), metrics=m) == 3  # evicts 1
    assert cache.stats() == {
        "entries": 2, "hits": 1, "misses": 3, "evictions": 1, "prewarmed": 0,
    }
    assert m.counters["variant_cache_hits"] == 1
    assert m.counters["variant_cache_misses"] == 3
    assert m.counters["variant_cache_evictions"] == 1
    # key 1 was evicted: rebuilding is a miss again
    cache.get_or_build(("k", 1), builder("again"), metrics=m)
    assert built == [1, 2, 3, "again"]


def test_variant_cache_prewarm_counts_separately():
    cache = VariantCache(max_entries=8)
    assert cache.prewarm(("k", 1), lambda: "v") == ("v", True)
    assert cache.prewarm(("k", 1), lambda: "v2") == ("v", False)  # present
    st = cache.stats()
    assert st["prewarmed"] == 1 and st["misses"] == 0
    # a later lookup of the prewarmed key is a HIT
    assert cache.get_or_build(("k", 1), lambda: "v3") == "v"
    assert cache.stats()["hits"] == 1


def test_variant_keys_quantize_to_ladder_rungs():
    from dsort_tpu.models.pipelines import pad_rung
    from dsort_tpu.parallel.exchange import ladder_rungs

    # every enumerated rung is its own pad (the ladder is a fixed point)
    rungs = ladder_rungs(1 << 16, lo=8)
    assert all(pad_rung(r) == r for r in rungs)
    assert rungs == sorted(set(rungs))
    # any size maps to a rung on the enumerated ladder
    for n in (1, 7, 9, 100, 5000, 12345, 65535):
        assert pad_rung(n) in rungs
    # nearby sizes share a rung -> shared compiled variant
    k1 = fused_variant_key(5000, "int32", "auto")
    k2 = fused_variant_key(5100, "int32", "auto")
    assert k1 == k2
    assert fused_variant_key(50000, "int32", "auto") != k1


# -- the serving core --------------------------------------------------------


def test_mixed_workload_bit_identical_and_cached(devices):
    """≥8 small jobs across ≥3 tenants + 1 large job, submitted
    concurrently: every output bit-identical to serial execution
    (np.sort), repeat-size cache hit rate ≥ 50% (acceptance)."""
    journal = EventLog()
    tel = Telemetry()
    svc = _svc(telemetry=tel, journal=journal)
    rng = np.random.default_rng(0)
    jobs = []
    for i in range(9):
        d = rng.integers(0, 1 << 30, 8000 + (i % 2) * 500, dtype=np.int32)
        _, t = svc.submit(d, tenant=f"tenant{i % 3}")
        jobs.append((d, t))
    big = rng.integers(0, 1 << 30, 1 << 18, dtype=np.int32)
    v, tbig = svc.submit(big, tenant="tenant0")
    assert v.admitted
    for d, t in jobs:
        np.testing.assert_array_equal(t.result(timeout=300), np.sort(d))
    np.testing.assert_array_equal(tbig.result(timeout=300), np.sort(big))
    assert svc.variants.hit_rate() >= 0.5
    st = svc.stats()
    assert st["done"] == 10 and st["failed"] == 0
    svc.shutdown(drain=True)
    types = [t for t, _ in _events(journal)]
    assert types.count("job_admitted") == 10
    assert types.count("job_done") == 10
    assert types.count("result_fetch") == 10
    # the big job went to the full mesh, the small ones onto slices
    deq = [f for t, f in _events(journal) if t == "job_dequeued"]
    assert sum(1 for f in deq if f["big"]) == 1
    assert sum(1 for f in deq if not f["big"]) == 9


def test_fairness_from_journal_no_tenant_starved(devices):
    """Journal-derived fairness (acceptance): with equal weights, a heavy
    tenant's backlog cannot starve light tenants — dequeue order from the
    journal, no sleeps."""
    journal = EventLog()
    svc = SortService(
        job=JOB,
        serve=ServeConfig(small_job_max=1 << 18, max_tenant_inflight=64,
                          max_queue_depth=128, slice_devices=8),
        journal=journal, start=False,
    )  # slice_devices=8 -> ONE slice: strictly serial dispatch order
    rng = np.random.default_rng(1)
    for i in range(8):
        svc.submit(rng.integers(0, 1000, 5000, dtype=np.int32), tenant="heavy")
    for i in range(2):
        svc.submit(rng.integers(0, 1000, 5000, dtype=np.int32), tenant="light")
    svc.start()
    svc.shutdown(drain=True)
    deq = [f for t, f in _events(journal) if t == "job_dequeued"]
    order = [f["tenant"] for f in deq]
    assert len(order) == 10
    # both light jobs dispatch inside the first DRR rotations
    assert order.index("light") < 4, f"light starved: {order}"
    # and the journal's measured queue waits hold the 3x p95 bound
    waits = {}
    for f in deq:
        waits.setdefault(f["tenant"], []).append(f["wait_s"])
    p95 = {t: float(np.percentile(w, 95)) for t, w in waits.items()}
    assert max(p95.values()) <= 3 * max(min(p95.values()), 1e-9) + 0.5


def test_weighted_tenant_gets_proportional_share(devices):
    journal = EventLog()
    svc = SortService(
        job=JOB,
        serve=ServeConfig(small_job_max=1 << 18, max_tenant_inflight=64,
                          max_queue_depth=128, slice_devices=8,
                          tenant_weights={"gold": 2.0}),
        journal=journal, start=False,
    )
    rng = np.random.default_rng(2)
    for i in range(6):
        svc.submit(rng.integers(0, 1000, 8000, dtype=np.int32), tenant="gold")
        svc.submit(rng.integers(0, 1000, 8000, dtype=np.int32), tenant="base")
    svc.start()
    svc.shutdown(drain=True)
    order = [f["tenant"] for t, f in _events(journal) if t == "job_dequeued"]
    assert order[:6].count("gold") >= 4  # ~2x share while both queues full


def test_queue_wait_is_the_admit_to_dispatch_slo(devices):
    """The service emits job_start at ADMISSION, so the existing
    admit_to_dispatch histogram IS the queue wait — live scrape and
    journal replay agree per tenant (PR 6 contract extended to the
    serving layer)."""
    from dsort_tpu.obs.slo import slo_from_journal

    journal = EventLog()
    tel = Telemetry()
    svc = _svc(telemetry=tel, journal=journal)
    rng = np.random.default_rng(3)
    tickets = [
        svc.submit(rng.integers(0, 1000, 4000, dtype=np.int32),
                   tenant="acme")[1]
        for _ in range(3)
    ]
    for t in tickets:
        t.result(timeout=120)
    svc.shutdown(drain=True)
    records = [e.to_dict() for e in journal.events()]
    truth = slo_from_journal(records)
    assert ("acme", "admit_to_dispatch") in truth
    scrape = parse_prometheus_text(tel.render_prometheus())
    for q in (0.5, 0.95, 0.99):
        key = ("dsort_job_stage_seconds", tuple(sorted({
            "tenant": "acme", "stage": "admit_to_dispatch",
            "quantile": str(q),
        }.items())))
        assert scrape[key] == pytest.approx(
            truth[("acme", "admit_to_dispatch")].quantile(q), rel=1e-5
        )


def test_cache_stats_reach_metrics_endpoint(devices):
    tel = Telemetry()
    svc = _svc(telemetry=tel)
    svc.prewarm(sizes=[4000])
    rng = np.random.default_rng(4)
    for _ in range(2):
        svc.submit(rng.integers(0, 1000, 4000, dtype=np.int32))[1].result(120)
    svc.shutdown(drain=True)
    scrape = parse_prometheus_text(tel.render_prometheus())
    assert scrape[("dsort_variant_cache_entries", ())] >= 1
    assert scrape[("dsort_variant_cache_prewarmed", ())] == 1
    assert scrape[("dsort_variant_cache_hits", ())] == 2  # both jobs warm
    assert scrape[("dsort_counter_total", (("name", "variant_cache_prewarms"),))] == 1
    # the journal-side counters flowed through job_done absorption too
    assert scrape[("dsort_counter_total", (("name", "variant_cache_hits"),))] == 2
    assert scrape[("dsort_counter_total", (("name", "jobs_admitted"),))] == 2
    assert scrape[("dsort_counter_total", (("name", "slice_dispatches"),))] == 2


def test_top_renders_cache_and_admissions(capsys):
    tel = Telemetry()
    tel.set_gauge("variant_cache_entries", 3)
    tel.set_gauge("variant_cache_hits", 9)
    tel.set_gauge("variant_cache_misses", 3)
    tel.set_gauge("variant_cache_prewarmed", 2)
    tel.admission_verdict("acme", "admitted")
    tel.admission_verdict("acme", "queue_full")
    from dsort_tpu.obs.top import render_top

    out = render_top(parse_prometheus_text(tel.render_prometheus()))
    assert "variant cache: 3 entries" in out
    assert "hit rate 75.0%" in out
    assert "admissions:" in out and "queue_full" in out


def test_prewarm_ladder_rungs(devices):
    tel = Telemetry()
    svc = _svc(telemetry=tel)
    n = svc.prewarm(sizes=[3000, 3050, 9000])  # two distinct rungs
    assert n == 2
    assert svc.prewarm(sizes=[3000]) == 0  # idempotent
    rng = np.random.default_rng(5)
    d = rng.integers(0, 1000, 3050, dtype=np.int32)
    _, t = svc.submit(d)
    np.testing.assert_array_equal(t.result(120), np.sort(d))
    st = svc.variants.stats()
    assert st["prewarmed"] == 2 and st["hits"] >= 1 and st["misses"] == 0
    svc.shutdown(drain=True)


# -- concurrent-job fault drills --------------------------------------------


def test_fault_drill_concurrent_jobs_two_tenants(devices, tmp_path):
    """Satellite: inject a device loss while ≥3 jobs from 2 tenants are
    queued/in-flight; every job either completes bit-identical or is
    re-admitted and completes — exact journal sequences, one
    flight-recorder bundle per affected job."""
    from dsort_tpu.obs.flight import FlightRecorder

    inj = FaultInjector()
    journal = EventLog()
    svc = _svc(tmp=tmp_path, injector=inj, journal=journal, start=False)
    rng = np.random.default_rng(6)
    inj.fail_once(0, "slice")   # first small dispatch on slice 0 dies
    inj.fail_once(2, "spmd")    # the big job loses device 2 mid-mesh
    jobs = []
    for i in range(4):
        d = rng.integers(0, 1 << 30, 9000, dtype=np.int32)
        v, t = svc.submit(d, tenant=["acme", "blue"][i % 2])
        assert v.admitted
        jobs.append((d, t))
    big = rng.integers(0, 1 << 30, 1 << 18, dtype=np.int32)
    _, tbig = svc.submit(big, tenant="acme")
    svc.start()
    for d, t in jobs:
        np.testing.assert_array_equal(t.result(timeout=300), np.sort(d))
    np.testing.assert_array_equal(tbig.result(timeout=300), np.sort(big))
    svc.shutdown(drain=True)
    evs = [(e.type, e.fields) for e in journal.events()]
    evicted_jobs = {f["job"] for t, f in evs if t == "job_evicted"}
    assert len(evicted_jobs) == 1
    job = next(iter(evicted_jobs))
    # The exact per-job recovery sequence is the declared `job_lifecycle`
    # grammar (ISSUE 17): the contract engine replays every job's trace
    # — one admission, dequeue/attempt rounds with the evict->readmit
    # loop, at most one terminal — instead of a hand-rolled literal.
    report = assert_conformant(journal)
    assert report["contracts"]["job_lifecycle"]["checked"] == 5
    # Behavioral facts the grammar alone cannot pin: the evicted job went
    # around the loop exactly once and completed.
    seq = [t for t, f in evs if f.get("job") == job]
    assert seq.index("job_evicted") < seq.index("job_readmitted")
    assert seq.index("job_readmitted") < seq.index("job_done")
    assert seq.count("job_dequeued") == 2 and seq.count("attempt_start") == 2
    # one flight bundle per eviction, naming the path and the tenant
    bundles = [
        b for b in FlightRecorder.read_bundles(str(tmp_path))
        if b["recovery_path"] == "job_evicted"
    ]
    assert len(bundles) == len(evicted_jobs)
    assert bundles[0]["detail"]["tenant"] in ("acme", "blue")
    assert bundles[0]["state"]["mode"] == "serve"
    # the big job recovered via the SPMD mesh re-form (its own bundle)
    assert any(t == "mesh_reform" for t, _ in evs)
    reform_bundles = [
        b for b in FlightRecorder.read_bundles(str(tmp_path))
        if b["recovery_path"].startswith("mesh_reform")
    ]
    assert len(reform_bundles) == 1


def test_slice_retired_after_dead_probe(devices, monkeypatch):
    """A slice whose lead device fails its probe leaves the packing
    rotation; the evicted job completes on another slice."""
    inj = FaultInjector()
    journal = EventLog()
    svc = _svc(injector=inj, journal=journal, start=False)
    inj.fail_once(0, "slice")
    inj.fail_once(0, "probe")  # the post-eviction probe fails too
    rng = np.random.default_rng(7)
    d = rng.integers(0, 1 << 30, 9000, dtype=np.int32)
    _, t = svc.submit(d, tenant="acme")
    svc.start()
    np.testing.assert_array_equal(t.result(timeout=300), np.sort(d))
    svc.shutdown(drain=True)
    types = [e.type for e in journal.events()]
    assert "slice_retired" in types
    assert svc.stats()["slices"] == 7


def test_fullmesh_reform_retires_dead_slice(devices):
    """A device permanently lost under a FULL-mesh job leaves the slice
    rotation too (the scheduler's reform listener), so later small jobs
    never dispatch onto the corpse."""
    inj = FaultInjector()
    journal = EventLog()
    svc = _svc(injector=inj, journal=journal)
    rng = np.random.default_rng(11)
    inj.kill(5)  # permanent: the re-form probe fails too
    big = rng.integers(0, 1 << 30, 1 << 18, dtype=np.int32)
    _, tbig = svc.submit(big, tenant="acme")
    np.testing.assert_array_equal(tbig.result(timeout=300), np.sort(big))
    assert svc.stats()["slices"] == 7
    retired = [f for t, f in _events(journal) if t == "slice_retired"]
    assert retired and retired[0]["reason"] == "mesh_reform"
    # small jobs keep completing on the surviving slices
    d = rng.integers(0, 1 << 30, 7000, dtype=np.int32)
    _, t = svc.submit(d, tenant="blue")
    np.testing.assert_array_equal(t.result(timeout=300), np.sort(d))
    svc.shutdown(drain=True)


# -- graceful shutdown -------------------------------------------------------


def test_shutdown_drains_queued_jobs(devices):
    journal = EventLog()
    svc = _svc(journal=journal, start=False)
    rng = np.random.default_rng(8)
    jobs = [
        (d := rng.integers(0, 1000, 5000, dtype=np.int32),
         svc.submit(d, tenant="acme")[1])
        for _ in range(4)
    ]
    assert svc.shutdown(drain=True, timeout=120)
    for d, t in jobs:
        assert t.done()
        np.testing.assert_array_equal(t.result(), np.sort(d))
    types = [e.type for e in journal.events()]
    assert "serve_drain" in types
    assert types[-1] == "serve_stop"
    assert types.count("job_done") == 4
    v, none = svc.submit(np.arange(3, dtype=np.int32))
    assert none is None and v.reason == "shutting_down"


def test_shutdown_no_drain_fails_queued_with_verdict(devices):
    journal = EventLog()
    svc = _svc(journal=journal, start=False)
    d = np.arange(1000, dtype=np.int32)
    _, t = svc.submit(d, tenant="acme")
    svc.shutdown(drain=False, timeout=120)
    with pytest.raises(ServiceClosed):
        t.result(timeout=10)
    types = [e.type for e in journal.events()]
    assert "job_failed" in types and types[-1] == "serve_stop"


def test_shutdown_racing_submit_strands_no_ticket(devices):
    """An admitted submission racing shutdown(drain=True) must still
    complete: the dispatcher's drain-exit consults the admission count
    (incremented before the queue push), so an admitted-but-not-yet-
    pushed ticket can never be stranded (code-review fix)."""
    import threading

    svc = _svc(journal=EventLog())
    results = []
    gate = threading.Barrier(5)

    def submitter(i):
        d = np.arange(2000 + i, dtype=np.int32)
        gate.wait()
        v, t = svc.submit(d, tenant="racer")
        if v.admitted:
            results.append((d, t))

    ths = [threading.Thread(target=submitter, args=(i,)) for i in range(4)]
    for th in ths:
        th.start()
    gate.wait()  # release the submitters and shut down immediately
    assert svc.shutdown(drain=True, timeout=120)
    for th in ths:
        th.join()
    for d, t in results:  # every ADMITTED job completed — none stranded
        np.testing.assert_array_equal(t.result(timeout=60), np.sort(d))


def test_cli_serve_unwritable_output_does_not_kill_server(tmp_path, monkeypatch):
    """A failing result write (bad -o path) logs and serves on — the old
    loop's 'a bad job must not kill the server' contract, kept through
    the async core (code-review fix)."""
    from dsort_tpu import cli

    inp = tmp_path / "in.txt"
    _write_keys(inp, np.arange(50, dtype=np.int64))
    lines = iter([str(inp), "exit"])
    monkeypatch.setattr("builtins.input", lambda *_: next(lines))
    # -o points at a DIRECTORY: every write raises OSError
    rc = cli.main(["serve", "-o", str(tmp_path), "--mode", "local"])
    assert rc == 0


def test_serve_events_registered():
    for etype in (
        "job_admitted", "job_rejected", "job_dequeued", "job_evicted",
        "job_readmitted", "slice_retired", "variant_prewarm",
        "serve_drain", "serve_stop",
    ):
        assert etype in EVENT_TYPES


# -- CLI: dsort serve on the async core --------------------------------------


def _write_keys(path, data):
    path.write_text("\n".join(str(int(x)) for x in data))


def test_cli_serve_sigint_graceful_shutdown(tmp_path, monkeypatch):
    """Ctrl-C (and SIGTERM via `_sigterm_to_interrupt`) drains in-flight
    jobs, flushes the journal with a serve_stop close event, and exits 0
    — today's satellite over the old mid-job teardown."""
    from dsort_tpu import cli

    rng = np.random.default_rng(9)
    d = rng.integers(0, 10**6, 2000, dtype=np.int64)
    inp = tmp_path / "in.txt"
    _write_keys(inp, d)
    journal = tmp_path / "serve.jsonl"
    lines = iter([str(inp)])

    def fake_input(prompt=""):
        try:
            return next(lines)
        except StopIteration:
            raise KeyboardInterrupt  # the SIGINT path

    monkeypatch.setattr("builtins.input", fake_input)
    rc = cli.main([
        "serve", "-o", str(tmp_path / "out.txt"), "--mode", "local",
        "--journal", str(journal), "--tenant", "acme",
    ])
    assert rc == 0
    records = EventLog.read_jsonl(str(journal))
    types = [r["type"] for r in records]
    assert types.count("job_done") == 1
    assert "serve_drain" in types and types[-1] == "serve_stop"
    out = np.loadtxt(tmp_path / "out.txt", dtype=np.int64)
    np.testing.assert_array_equal(out, np.sort(d))


def test_sigterm_handler_routes_to_interrupt():
    from dsort_tpu import cli

    with pytest.raises(KeyboardInterrupt):
        cli._sigterm_to_interrupt(15, None)


def test_cli_serve_async_two_tenants(tmp_path, monkeypatch):
    """README's two-tenant quick-start shape: async REPL (--max-in-flight)
    with per-line tenant labels; both tenants' jobs complete and the
    journal carries their admission records."""
    from dsort_tpu import cli

    rng = np.random.default_rng(10)
    files, datas = [], []
    for i in range(4):
        d = rng.integers(0, 10**6, 1500 + 100 * i, dtype=np.int64)
        p = tmp_path / f"in{i}.txt"
        _write_keys(p, d)
        files.append(p)
        datas.append(d)
    journal = tmp_path / "serve.jsonl"
    lines = iter(
        [f"tenant=acme {files[0]}", f"tenant=blue {files[1]}",
         f"tenant=acme {files[2]}", f"tenant=blue {files[3]}", "exit"]
    )
    monkeypatch.setattr("builtins.input", lambda *_: next(lines))
    rc = cli.main([
        "serve", "-o", str(tmp_path / "out.txt"), "--mode", "spmd",
        "--journal", str(journal), "--max-in-flight", "4",
    ])
    assert rc == 0
    records = EventLog.read_jsonl(str(journal))
    admitted = [r for r in records if r["type"] == "job_admitted"]
    assert {r["tenant"] for r in admitted} == {"acme", "blue"}
    assert len(admitted) == 4
    done = [r for r in records if r["type"] == "job_done"]
    assert len(done) >= 4


def test_parse_serve_line():
    from dsort_tpu.cli import _parse_serve_line

    assert _parse_serve_line("a.txt", "default") == ("default", "a.txt")
    assert _parse_serve_line("tenant=acme  b.txt ", "d") == ("acme", "b.txt")
    assert _parse_serve_line("  exit ", "d") == ("d", "exit")


def test_serve_config_validation_and_conf_keys():
    with pytest.raises(ConfigError):
        ServeConfig(max_queue_depth=0)
    with pytest.raises(ConfigError):
        ServeConfig(slice_devices=0)
    with pytest.raises(ConfigError):
        ServeConfig(tenant_weights={"a": -1})
    cfg = SortConfig.from_mapping({
        "SERVE_QUEUE_DEPTH": "9", "SERVE_TENANT_INFLIGHT": "3",
        "SERVE_SLICE_DEVICES": "2", "SERVE_WEIGHTS": "acme=2",
        "SERVE_PREWARM": "1",
    })
    assert cfg.serve.max_queue_depth == 9
    assert cfg.serve.max_tenant_inflight == 3
    assert cfg.serve.slice_devices == 2
    assert cfg.serve.tenant_weights == {"acme": 2.0}
    assert cfg.serve.prewarm


# -- the tier-1 serve-smoke gate ---------------------------------------------


def test_bench_serve_mixed_gate(capsys):
    """Tier-1 gate for `make serve-smoke`: the mixed small/large
    three-tenant workload through the real queue emits its row with
    bit-identical outputs and a ≥50% repeat-size cache hit rate."""
    from dsort_tpu import cli

    rc = cli.main(["bench", "--serve-mixed", "--n", "20000", "--reps", "1"])
    out = capsys.readouterr().out
    row = json.loads(
        [ln for ln in out.splitlines() if ln.startswith("{")][-1]
    )
    assert rc == 0
    assert row["metric"] == "service_mixed_workload"
    assert row["unit"] == "jobs/sec" and row["value"] > 0
    assert row["bit_identical"] is True
    assert row["cache_hit_rate"] >= 0.5
    assert row["jobs"] >= 9 and row["tenants"] >= 3
    # The 3x fairness bound is asserted on a controlled workload in
    # test_fairness_from_journal_no_tenant_starved; at this gate's tiny
    # job sizes the waits are dispatch noise and the ratio is meaningless.
    assert row["fairness_p95_ratio"] > 0
    assert row["p95_queue_wait_ms"] >= 0


# -- ARCHITECTURE §8 schema enforcement --------------------------------------


def test_architecture_documents_serving_layer():
    """§8's contract is test-enforced like §7's bundle schema: every
    admission verdict and every serving-layer event type appears
    verbatim."""
    arch = open(
        os.path.join(os.path.dirname(os.path.dirname(__file__)),
                     "ARCHITECTURE.md"),
        encoding="utf-8",
    ).read()
    assert "## 8. Serving layer" in arch
    for reason in ADMISSION_REASONS:
        assert f"`{reason}`" in arch, f"admission reason {reason} undocumented"
    for etype in (
        "job_admitted", "job_rejected", "job_dequeued", "job_evicted",
        "job_readmitted", "serve_drain", "serve_stop",
    ):
        assert f"`{etype}`" in arch, f"serve event {etype} undocumented"
    for term in ("deficit", "capacity ladder", "prewarm", "shutdown"):
        assert term in arch
