"""Native C++ runtime tests: k-way merge parity, worker table, and a real
multi-process coordinator cluster with an injected worker kill (the SURVEY.md
§0 experiment, natively)."""

import os
import subprocess
import sys
import time

import numpy as np
import pytest

from dsort_tpu.runtime import native

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native library not built"
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.mark.parametrize(
    "dtype", [np.int32, np.int64, np.uint64, np.uint32, np.uint16]
)
def test_native_kway_merge_parity(dtype):
    rng = np.random.default_rng(1)
    info = np.iinfo(dtype)
    runs = [
        np.sort(rng.integers(info.min, info.max, n, dtype=dtype))
        for n in (0, 17, 1000, 3, 4096)
    ]
    runs = [r.astype(dtype) for r in runs]
    out = native.kway_merge(runs)
    np.testing.assert_array_equal(out, np.sort(np.concatenate(runs)))


def test_native_kway_merge_kv():
    rng = np.random.default_rng(2)
    key_runs, val_runs = [], []
    for n in (50, 0, 200):
        k = np.sort(rng.integers(0, 1000, n).astype(np.uint64))
        v = rng.integers(0, 255, (n, 90)).astype(np.uint8)
        # payloads must follow their keys: make payload derivable from key
        v[:, 0] = (k % 251).astype(np.uint8)
        key_runs.append(k)
        val_runs.append(v)
    ok, ov = native.kway_merge_kv(key_runs, val_runs)
    np.testing.assert_array_equal(ok, np.sort(np.concatenate(key_runs)))
    np.testing.assert_array_equal(ov[:, 0], (ok % 251).astype(np.uint8))


def test_native_worker_table_semantics():
    t = native.NativeWorkerTable(4, heartbeat_timeout_s=0.2)
    assert t.first_live() == 0
    t.mark_dead(0)
    t.mark_dead(2)
    assert t.first_live() == 1
    assert t.first_live(exclude=1) == 3
    assert t.live_workers() == [1, 3]
    assert t.death_count == 2
    # heartbeat lapse
    time.sleep(0.3)
    t.heartbeat(1)
    newly = t.check_heartbeats()
    assert newly == [3]
    assert t.live_workers() == [1]
    t.revive_all()
    assert t.live_workers() == [0, 1, 2, 3]


def _spawn_workers(port, n, dtype="int32"):
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO  # drop the jax-preloading site hook for shims
    env.pop("PALLAS_AXON_POOL_IPS", None)
    procs = [
        subprocess.Popen(
            [
                sys.executable, "-m", "dsort_tpu.runtime.worker",
                "--port", str(port), "--backend", "numpy", "--dtype", dtype,
            ],
            env=env,
            stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL,
        )
        for _ in range(n)
    ]
    return procs


@pytest.fixture
def cluster():
    from dsort_tpu.runtime import NativeCoordinator

    coord = NativeCoordinator(port=0, heartbeat_timeout_s=5.0)
    procs = _spawn_workers(coord.port, 4)
    try:
        coord.wait_workers(4, timeout_s=30.0)
        yield coord, procs
    finally:
        coord.shutdown()
        for p in procs:
            try:
                p.wait(timeout=5)
            except subprocess.TimeoutExpired:
                p.kill()


def test_coordinator_healthy_job(cluster):
    coord, _ = cluster
    data = np.random.default_rng(3).integers(-(2**31), 2**31 - 1, 40_000).astype(np.int32)
    out = coord.run_job(data, num_shards=4)
    np.testing.assert_array_equal(out, np.sort(data))
    assert coord.num_live == 4


def test_coordinator_worker_killed_midjob(cluster):
    # The reference experiment: kill -9 one worker; job completes via
    # reassignment to a live worker.
    from dsort_tpu.utils.events import EventLog
    from dsort_tpu.utils.metrics import Metrics

    coord, procs = cluster
    procs[1].kill()  # actual process kill, like SURVEY.md §0
    time.sleep(0.2)
    data = np.random.default_rng(4).integers(-(2**31), 2**31 - 1, 20_000).astype(np.int32)
    journal = EventLog()
    m = Metrics(journal=journal)
    out = coord.run_job(data, num_shards=4, metrics=m)
    np.testing.assert_array_equal(out, np.sort(data))
    assert coord.num_live == 3
    # The C++ coordinator's state transitions landed on the SAME journal:
    # 4 joins, the killed worker's death (detected pre-dispatch here, so
    # shards route straight to live workers — no reassign line), one
    # attempt per shard, and every result.  (worker_join events were
    # buffered at cluster start and drain with the first job.)
    types = journal.types()
    assert types.count("worker_join") == 4
    assert "worker_dead" in types
    assert types.count("attempt_start") >= 4
    assert types.count("task_done") >= 4
    dead = [e for e in journal.events() if e.type == "worker_dead"]
    assert dead and all("worker" in e.fields for e in dead)


def test_coordinator_socket_kill_fault_injection(cluster):
    coord, _ = cluster
    coord.kill_worker(2)  # injector path: hard-close the socket
    time.sleep(0.2)
    data = np.random.default_rng(5).integers(0, 10**6, 10_000).astype(np.int32)
    out = coord.run_job(data, num_shards=4)
    np.testing.assert_array_equal(out, np.sort(data))
    assert coord.num_live == 3


def test_coordinator_all_workers_dead_fails_cleanly(cluster):
    from dsort_tpu.scheduler.fault import JobFailedError

    coord, procs = cluster
    for p in procs:
        p.kill()
    time.sleep(0.5)
    data = np.arange(100, dtype=np.int32)[::-1].copy()
    with pytest.raises((JobFailedError, TimeoutError)):
        coord.run_job(data, num_shards=4)
    # Coordinator object survives for the next job/cluster (server.c:265-268).
    assert coord.num_live == 0


def test_native_selftest_binary():
    """Build + run the C++ in-process selftest (coordinator protocol,
    reassignment, all-dead, merge, table) — no Python worker shims."""
    native_dir = os.path.join(REPO, "dsort_tpu", "runtime", "native")
    subprocess.run(
        ["make", "-C", native_dir, "selftest"], check=True, capture_output=True
    )
    out = subprocess.run(
        [os.path.join(native_dir, "selftest")],
        capture_output=True, text=True, timeout=60,
    )
    assert out.returncode == 0, out.stderr
    assert "SELFTEST PASS" in out.stdout


@pytest.mark.slow
@pytest.mark.parametrize("san", ["tsan", "asan", "ubsan"])
def test_native_selftest_sanitizers(san):
    """Sanitizer-hardened native runtime: the full selftest (threaded
    coordinator, kills, reassignment, merges, textio) must run clean under
    TSan/ASan/UBSan.  The instrumented binary is REBUILT from the Makefile
    target every run — never a checked-in artifact — so the run always
    reflects the current sources.  Any sanitizer report fails the binary
    (TSan exits nonzero on a race; UBSan builds with
    -fno-sanitize-recover=all)."""
    native_dir = os.path.join(REPO, "dsort_tpu", "runtime", "native")
    binary = os.path.join(native_dir, f"selftest_{san}")
    if os.path.exists(binary):
        os.remove(binary)  # stale instrumented binaries must not mask drift
    build = subprocess.run(
        ["make", "-C", native_dir, f"{san}-selftest"],
        capture_output=True, text=True, timeout=300,
    )
    if build.returncode != 0:
        pytest.skip(f"toolchain cannot build -fsanitize={san}: "
                    f"{build.stderr.splitlines()[-1:]}")
    env = dict(os.environ)
    env.setdefault("TSAN_OPTIONS", "halt_on_error=1")
    env.setdefault("ASAN_OPTIONS", "detect_leaks=0")  # selftest exits hot
    out = subprocess.run(
        [binary], capture_output=True, text=True, timeout=600, env=env,
    )
    assert out.returncode == 0, f"{san} report:\n{out.stdout}\n{out.stderr}"
    assert "SELFTEST PASS" in out.stdout


def test_jax_worker_int64_cluster():
    """int64 keys through real jax-backend worker subprocesses.

    Regression: SortWorker's own entrypoint never passes through cli.main(),
    so without enabling x64 itself a jax-backed int64 worker silently
    downcast keys to int32 and returned half-length, value-truncated result
    frames.
    """
    from dsort_tpu.runtime import NativeCoordinator

    coord = NativeCoordinator(port=0, heartbeat_timeout_s=10.0)
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env.pop("JAX_ENABLE_X64", None)  # the worker must enable x64 itself
    env["JAX_PLATFORMS"] = "cpu"
    procs = [
        subprocess.Popen(
            [sys.executable, "-m", "dsort_tpu.runtime.worker",
             "--port", str(coord.port), "--backend", "jax", "--dtype", "int64"],
            env=env, stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
        )
        for _ in range(2)
    ]
    try:
        coord.wait_workers(2, timeout_s=60.0)
        data = np.random.default_rng(9).integers(
            -(2**63), 2**63 - 1, 10_000, dtype=np.int64
        )
        out = coord.run_job(data, num_shards=2)
        assert out.dtype == np.int64
        np.testing.assert_array_equal(out, np.sort(data))
    finally:
        coord.shutdown()
        for p in procs:
            try:
                p.wait(timeout=5)
            except subprocess.TimeoutExpired:
                p.kill()


def test_native_kway_merge_kv2_two_level_order():
    """Records merge by (u64 primary, u16 secondary) — ties break exactly."""
    rng = np.random.default_rng(9)
    k1s, k2s, vs = [], [], []
    for n in (300, 0, 77):
        k1 = rng.integers(0, 8, n).astype(np.uint64)  # heavy primary ties
        k2 = rng.integers(0, 2**16, n).astype(np.uint16)
        order = np.lexsort((k2, k1))
        k1, k2 = k1[order], k2[order]
        v = rng.integers(0, 255, (n, 10)).astype(np.uint8)
        v[:, 0] = (k2 % 251).astype(np.uint8)
        k1s.append(k1); k2s.append(k2); vs.append(v)
    ok1, ok2, ov = native.kway_merge_kv2(k1s, k2s, vs, want_keys=True)
    a1, a2 = np.concatenate(k1s), np.concatenate(k2s)
    order = np.lexsort((a2, a1))
    np.testing.assert_array_equal(ok1, a1[order])
    np.testing.assert_array_equal(ok2, a2[order])
    np.testing.assert_array_equal(ov[:, 0], (ok2 % 251).astype(np.uint8))


def test_native_kway_merge_kv2_rejects_bad_buffers():
    k1 = [np.array([1, 2], np.uint64)]
    k2 = [np.array([0, 0], np.uint16)]
    v = [np.zeros((2, 8), np.uint8)]
    with pytest.raises(ValueError):  # wrong row width
        native.kway_merge_kv2(k1, k2, v, out_v=np.zeros((2, 4), np.uint8))
    with pytest.raises(ValueError):  # wrong dtype
        native.kway_merge_kv2(k1, k2, v, out_v=np.zeros((2, 8), np.uint16))
    with pytest.raises(ValueError):  # mismatched run lengths
        native.kway_merge_kv2(k1, [np.array([0], np.uint16)], v)


def test_coordinator_float_nan_cluster():
    """Float keys with NaNs through a real worker cluster: no sentinel
    padding on this path, so NaNs must survive and order last (np.sort
    semantics) without the ops.float_order mapping — which would break the
    workers' spawn-time --dtype frame contract."""
    from dsort_tpu.runtime import NativeCoordinator

    coord = NativeCoordinator(port=0, heartbeat_timeout_s=10.0)
    procs = _spawn_workers(coord.port, 2, dtype="float32")
    try:
        coord.wait_workers(2, timeout_s=30.0)
        rng = np.random.default_rng(12)
        data = rng.normal(size=8_000).astype(np.float32)
        data[::101] = np.nan
        out = coord.run_job(data, num_shards=2)
        expect = np.sort(data)  # NaNs last
        k = len(data) - np.isnan(data).sum()
        np.testing.assert_array_equal(out[:k], expect[:k])
        assert np.isnan(out[k:]).all()
    finally:
        coord.shutdown()
        for p in procs:
            try:
                p.wait(timeout=5)
            except subprocess.TimeoutExpired:
                p.kill()


@pytest.mark.parametrize("dtype", [np.int32, np.uint64, np.uint16])
def test_native_parallel_merge_parity(dtype):
    """Range-partitioned threaded merge == serial merge == np.sort, across
    empty runs, unequal lengths, and heavy duplicates (degenerate splitters)."""
    rng = np.random.default_rng(7)
    info = np.iinfo(dtype)
    for sizes, lo, hi in [
        ((1 << 20, 300_000, 0, 7), info.min, info.max),
        ((400_000, 400_001, 399_999, 1), info.min, info.max),
        ((800_000,) * 5, 0, 3),  # heavy dups: splitters all collide
    ]:
        runs = [np.sort(rng.integers(lo, hi, n, dtype=dtype)) for n in sizes]
        expect = np.sort(np.concatenate(runs))
        for th in (1, 4, 7):
            np.testing.assert_array_equal(
                native.kway_merge(runs, threads=th), expect
            )


def test_native_parallel_kv2_merge_parity():
    """Threaded record merge == serial == lexsort across thread counts."""
    rng = np.random.default_rng(3)
    k1s, k2s, vs = [], [], []
    for n in (400_000, 0, 120_001):
        k1 = rng.integers(0, 50, n).astype(np.uint64)  # heavy primary ties
        k2 = rng.integers(0, 2**16, n).astype(np.uint16)
        order = np.lexsort((k2, k1))
        k1, k2 = k1[order], k2[order]
        v = rng.integers(0, 256, (n, 20)).astype(np.uint8)
        v[:, 0] = (k1 % 251).astype(np.uint8)
        v[:, 1] = (k2 % 251).astype(np.uint8)
        k1s.append(k1); k2s.append(k2); vs.append(v)
    a1, a2 = np.concatenate(k1s), np.concatenate(k2s)
    order = np.lexsort((a2, a1))
    for th in (1, 5, 9):
        ok1, ok2, ov = native.kway_merge_kv2(
            k1s, k2s, vs, want_keys=True, threads=th
        )
        np.testing.assert_array_equal(ok1, a1[order])
        np.testing.assert_array_equal(ok2, a2[order])
        np.testing.assert_array_equal(ov[:, 0], (ok1 % 251).astype(np.uint8))
        np.testing.assert_array_equal(ov[:, 1], (ok2 % 251).astype(np.uint8))
