"""Bitonic network + Pallas tile-sort kernel tests (interpret mode on CPU)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dsort_tpu.config import ConfigError, JobConfig
from dsort_tpu.ops.bitonic import bitonic_merge_pair, bitonic_sort, merge_sorted_runs
from dsort_tpu.ops.local_sort import sort_with_kernel
from dsort_tpu.ops.pallas_sort import pallas_sort


@pytest.mark.parametrize("n", [1, 2, 3, 127, 128, 1000, 4096])
def test_bitonic_sort_matches_numpy(n):
    rng = np.random.default_rng(n)
    x = rng.integers(-(2**31), 2**31 - 1, n).astype(np.int32)
    y = np.asarray(jax.jit(bitonic_sort)(jnp.asarray(x)))
    np.testing.assert_array_equal(y, np.sort(x))


def test_bitonic_sort_dtypes():
    rng = np.random.default_rng(0)
    for dtype in (np.int64, np.uint64, np.float32):
        if np.issubdtype(dtype, np.floating):
            x = rng.standard_normal(512).astype(dtype)
        else:
            x = rng.integers(0, 2**60, 512).astype(dtype)
        np.testing.assert_array_equal(np.asarray(bitonic_sort(jnp.asarray(x))), np.sort(x))


def test_bitonic_merge_pair():
    rng = np.random.default_rng(2)
    a = np.sort(rng.integers(0, 10**6, 1024).astype(np.int32))
    b = np.sort(rng.integers(0, 10**6, 1024).astype(np.int32))
    out = np.asarray(bitonic_merge_pair(jnp.asarray(a), jnp.asarray(b)))
    np.testing.assert_array_equal(out, np.sort(np.concatenate([a, b])))


def test_merge_sorted_runs_tree():
    rng = np.random.default_rng(3)
    runs = np.sort(rng.integers(-(10**6), 10**6, (4, 256)).astype(np.int32), axis=1)
    out = np.asarray(merge_sorted_runs(jnp.asarray(runs)))
    np.testing.assert_array_equal(out, np.sort(runs.reshape(-1)))


@pytest.mark.parametrize("n,rows", [(1024, 8), (3 * 1024 + 17, 8)])
def test_pallas_sort_matches_numpy(n, rows):
    rng = np.random.default_rng(n)
    x = rng.integers(-(2**31), 2**31 - 1, n).astype(np.int32)
    y = np.asarray(pallas_sort(jnp.asarray(x), tile_rows=rows))
    np.testing.assert_array_equal(y, np.sort(x))


def test_sort_with_kernel_dispatch():
    x = jnp.asarray(np.array([5, -3, 7, 0], dtype=np.int32))
    for kernel in ("lax", "bitonic"):
        np.testing.assert_array_equal(
            np.asarray(sort_with_kernel(x, kernel)), [-3, 0, 5, 7]
        )
    with pytest.raises(ValueError, match="unknown local kernel"):
        sort_with_kernel(x, "quicksort")


def test_job_config_validates_kernel():
    with pytest.raises(ConfigError, match="local_kernel"):
        JobConfig(local_kernel="bogus")


def test_sample_sort_with_bitonic_kernel(mesh8):
    from dsort_tpu.data.ingest import gen_uniform
    from dsort_tpu.parallel.sample_sort import SampleSort

    data = gen_uniform(20_000, seed=21)
    out = SampleSort(mesh8, JobConfig(local_kernel="bitonic")).sort(data)
    np.testing.assert_array_equal(out, np.sort(data))


@pytest.mark.slow  # ~70 s interpreted; the bitonic-kernel twin keeps
# the kernel-inside-sample-sort path in tier-1
def test_sample_sort_with_pallas_kernel(mesh8):
    from dsort_tpu.data.ingest import gen_uniform
    from dsort_tpu.parallel.sample_sort import SampleSort

    data = gen_uniform(2_048, seed=22)
    out = SampleSort(mesh8, JobConfig(local_kernel="pallas")).sort(data)
    np.testing.assert_array_equal(out, np.sort(data))
