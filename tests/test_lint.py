"""Static-analysis suite tests (`dsort_tpu.analysis` / `dsort lint`).

Per checker: a fixture with deliberate violations must produce exactly the
expected codes (true-positive), and its near-miss clean twin must produce
none (false-positive guard).  Then the engine plumbing — suppressions,
baseline, JSON output, config — and the CI gates: the shipped tree lints
clean with an EMPTY baseline, and vocabulary drift seeded on either side of
the Python/C++ boundary is caught without running any cluster.
"""

import json
import os
import shutil
import subprocess
import sys

import pytest

from dsort_tpu.analysis import (
    LintConfig,
    lint_paths,
    load_config,
    write_baseline,
)
from dsort_tpu.analysis.checkers import all_checkers, checker_catalog
from dsort_tpu.analysis.checkers.exceptions import ExceptionsChecker

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(REPO, "tests", "data", "lint")


def fixture(name: str) -> str:
    return os.path.join(FIXTURES, name)


def run_fixture(name: str, checkers=None):
    cfg = LintConfig(root=REPO)
    return lint_paths([fixture(name)], cfg, checkers=checkers)


def codes_of(diags) -> list[str]:
    return [d.code for d in diags]


# -- per-checker true positives + clean twins -------------------------------


def test_registry_checker_fixture():
    assert codes_of(run_fixture("bad_registry.py")) == [
        "DS102", "DS101", "DS101", "DS101",
    ]
    assert run_fixture("good_registry.py") == []


def test_concurrency_checker_fixture():
    diags = run_fixture("bad_concurrency.py")
    assert sorted(codes_of(diags)) == [
        "DS201", "DS201", "DS202", "DS202", "DS203",
    ]
    # the ABBA report points at the inner acquisition of the reversed order
    abba = [d for d in diags if d.code == "DS203"][0]
    assert "both orders" in abba.message
    assert run_fixture("good_concurrency.py") == []


def test_tracing_checker_fixture():
    diags = run_fixture("bad_tracing.py")
    counts = {c: codes_of(diags).count(c) for c in set(codes_of(diags))}
    assert counts == {"DS301": 4, "DS302": 2}
    assert run_fixture("good_tracing.py") == []


def test_ring_kernel_fixture():
    """ISSUE 11: the fused-ring-kernel failure modes stay pinned — a
    journaling/clock-reading kernel body (DS301) and non-static
    grid/out_shape launch geometry (DS302) must be caught; the real
    module's shape (static caps tuple, host-side note_fused_plan
    journaling) stays clean."""
    diags = run_fixture("bad_ring_kernel.py")
    counts = {c: codes_of(diags).count(c) for c in set(codes_of(diags))}
    assert counts == {"DS301": 3, "DS302": 2}
    assert run_fixture("good_ring_kernel.py") == []


def test_obs_fixture():
    """The telemetry plane's discipline contract: recorder-ring state stays
    lock-guarded with no blocking work under the lock, and nothing scrapes
    or journals from inside a traced function."""
    diags = run_fixture("bad_obs.py")
    counts = {c: codes_of(diags).count(c) for c in set(codes_of(diags))}
    assert counts == {"DS201": 1, "DS202": 2, "DS301": 3}
    assert run_fixture("good_obs.py") == []


def test_prof_fixture():
    """The introspection plane's discipline contract: ledger state stays
    lock-guarded with the compile (seconds!) and the journal emission both
    outside the lock, and nothing records from inside a traced function
    (the 'compile timer' would become a trace-time constant)."""
    diags = run_fixture("bad_prof.py")
    counts = {c: codes_of(diags).count(c) for c in set(codes_of(diags))}
    assert counts == {"DS201": 1, "DS202": 2, "DS301": 3}
    assert run_fixture("good_prof.py") == []


def test_health_fixture():
    """ISSUE 14: the live health plane's discipline contract — rolling
    collector/analyzer state stays lock-guarded with the frame ship (a
    socket write) outside the lock, and no verdict is emitted from inside
    a traced function (the busy timer would become a trace-time
    constant)."""
    diags = run_fixture("bad_health.py")
    counts = {c: codes_of(diags).count(c) for c in set(codes_of(diags))}
    assert counts == {"DS201": 1, "DS202": 2, "DS301": 3}
    assert run_fixture("good_health.py") == []


def test_coded_fixture():
    """ISSUE 15: the coded redundancy plane's discipline contract — the
    replica-state table stays lock-guarded with the k-way reconstruction
    merge outside the lock, and no recovery event or wall clock is
    emitted from inside a traced function (the recovery cost would become
    a trace-time constant)."""
    diags = run_fixture("bad_coded.py")
    counts = {c: codes_of(diags).count(c) for c in set(codes_of(diags))}
    assert counts == {"DS201": 1, "DS202": 2, "DS301": 3}
    assert run_fixture("good_coded.py") == []


def test_coded_v2_fixture():
    """ISSUE 19: the coded-v2 discipline contract — the exactly-once
    straggler claim stays lock-guarded with the owner join and injected
    delay outside the lock, and no serve event or solve clock is emitted
    from inside a traced function (the race outcome would become a
    trace-time constant)."""
    diags = run_fixture("bad_coded_v2.py")
    counts = {c: codes_of(diags).count(c) for c in set(codes_of(diags))}
    assert counts == {"DS201": 1, "DS202": 2, "DS301": 3}
    assert run_fixture("good_coded_v2.py") == []


def test_plan_fixture():
    """ISSUE 16: the planner plane's discipline contract — the rolling
    signal state stays lock-guarded with the skew probe outside the lock,
    and no plan_decision (or probe clock) is emitted from inside a traced
    function (the measured inputs would become trace-time constants and
    the replay audit would replay a decision that never ran)."""
    diags = run_fixture("bad_plan.py")
    counts = {c: codes_of(diags).count(c) for c in set(codes_of(diags))}
    assert counts == {"DS201": 1, "DS202": 2, "DS301": 3}
    assert run_fixture("good_plan.py") == []


def test_hier_fixture():
    """ISSUE 18: the hierarchical exchange plane's discipline contract —
    the host-topology table stays lock-guarded with the (H,H) histogram
    re-plan outside the lock, and no hier_exchange_plan event (or DCN
    wall clock) is emitted from inside a traced shard function (the
    wire-byte split would become a trace-time constant)."""
    diags = run_fixture("bad_hier.py")
    counts = {c: codes_of(diags).count(c) for c in set(codes_of(diags))}
    assert counts == {"DS201": 1, "DS202": 2, "DS301": 3}
    assert run_fixture("good_hier.py") == []


def test_durability_checker_fixture():
    """ISSUE 13: the PR 12 review-fix classes stay pinned — a raw write to
    a persisted-state path, a rename with no fsync, and persist IO under a
    shared lock; the clean twin carries the full tmp+fsync+rename idiom,
    the touch idiom, and the dedicated-flush-lock shape."""
    from dsort_tpu.analysis.checkers.durability import DurabilityChecker

    scoped = [DurabilityChecker(scope=("*.py",))]
    diags = run_fixture("bad_durability.py", checkers=scoped)
    counts = {c: codes_of(diags).count(c) for c in set(codes_of(diags))}
    assert counts == {"DS701": 1, "DS702": 1, "DS703": 3}
    assert run_fixture("good_durability.py", checkers=scoped) == []


def test_protocol_checker_fixture():
    """ISSUE 13: frame vocabulary + dispatch coverage — an unregistered
    send/compare, a no-default dispatch chain, unregistered admission
    reasons; the clean twin has an explicit default and a reply guard."""
    from dsort_tpu.analysis.checkers.protocol import ProtocolChecker

    scoped = [ProtocolChecker(scope=("*.py",))]
    diags = run_fixture("bad_protocol.py", checkers=scoped)
    counts = {c: codes_of(diags).count(c) for c in set(codes_of(diags))}
    assert counts == {"DS801": 2, "DS802": 1, "DS803": 2}
    missing = [d for d in diags if d.code == "DS802"][0]
    # the coverage report names what actually falls through
    assert "'result'" in missing.message and "'submit'" in missing.message
    assert run_fixture("good_protocol.py", checkers=scoped) == []


def test_lifecycle_checker_fixture():
    """ISSUE 13: the fused-ring DMA pairing contract — a started-never-
    waited copy, a half-drained copy — and thread daemon/join discipline;
    the clean twin is the real kernel's start/fold/wait schedule.
    ISSUE 17 widened DS903 to Timer (cancel/join/daemon-attr pairing)
    and concurrent.futures executors (with-block or .shutdown())."""
    diags = run_fixture("bad_lifecycle.py")
    counts = {c: codes_of(diags).count(c) for c in set(codes_of(diags))}
    assert counts == {"DS901": 1, "DS902": 1, "DS903": 4}
    messages = [d.message for d in diags if d.code == "DS903"]
    assert any("timer" in m for m in messages)
    assert any("ThreadPoolExecutor" in m for m in messages)
    assert run_fixture("good_lifecycle.py") == []


def test_layers_checker_fixtures():
    """ISSUE 13 tentpole: the declared-pure module reaching a forbidden
    backend transitively is flagged WITH the import chain (DS601), a
    layer pattern naming a dead module is loud (DS602); the clean twin's
    lazy + TYPE_CHECKING imports pass."""
    bad_root = fixture("layers_bad")
    diags = lint_paths([os.path.join(bad_root, "pkg")], load_config(bad_root))
    assert codes_of(diags) == ["DS601", "DS602"]
    chain = diags[0]
    assert chain.path == "pkg/helper.py" and chain.line == 1
    assert "pkg.pure -> pkg.helper -> fakebackend.core" in chain.message
    assert "pkg.missing_module" in diags[1].message
    good_root = fixture("layers_good")
    assert lint_paths(
        [os.path.join(good_root, "pkg")], load_config(good_root)
    ) == []


def test_exceptions_checker_fixture():
    # Fixtures live outside the checker's recovery-path scope: rescope.
    scoped = [ExceptionsChecker(scope=("*.py",))]
    assert codes_of(run_fixture("bad_excepts.py", checkers=scoped)) == [
        "DS401", "DS402",
    ]
    assert run_fixture("good_excepts.py", checkers=scoped) == []


def test_compat_checker_fixture():
    assert sorted(codes_of(run_fixture("bad_compat.py"))) == [
        "DS501", "DS502",
    ]
    assert run_fixture("good_compat.py") == []


def test_cpp_registry_fixture():
    diags = run_fixture("bad_coordinator.cpp")
    assert codes_of(diags) == ["DS103", "DS104"]
    assert "fake_native_event" in diags[0].message
    assert "probe" in diags[1].message  # registered, but unparseable on drain
    assert run_fixture("good_coordinator.cpp") == []


# -- engine plumbing --------------------------------------------------------


def test_suppression_comments():
    diags = run_fixture("suppressed.py")
    # DS102 suppressed by code; second line suppressed wholesale; the
    # mis-coded ignore[DS999] suppresses nothing.
    assert codes_of(diags) == ["DS101"]


def test_baseline_round_trip(tmp_path):
    cfg = LintConfig(root=REPO)
    diags = lint_paths([fixture("bad_registry.py")], cfg)
    assert diags
    base = tmp_path / "baseline.json"
    write_baseline(str(base), diags)
    cfg2 = LintConfig(root=REPO, baseline=str(base))
    assert lint_paths([fixture("bad_registry.py")], cfg2) == []
    # baseline keys are line-independent: the file documents (path, code,
    # message), never line numbers
    entries = json.loads(base.read_text())["entries"]
    assert entries and all(set(e) == {"path", "code", "message"} for e in entries)


def test_json_output_shape():
    from dsort_tpu.analysis import format_json

    diags = run_fixture("bad_compat.py")
    loaded = json.loads(format_json(diags))
    assert {d["code"] for d in loaded} == {"DS501", "DS502"}
    assert all(
        {"path", "line", "col", "code", "severity", "message"} <= set(d)
        for d in loaded
    )


def test_config_from_pyproject():
    cfg = load_config(REPO)
    assert cfg.baseline == ".lint-baseline.json"
    assert set(cfg.enable) == {c.name for c in all_checkers()}


def test_checker_catalog_is_documented():
    """Every checker publishes codes; every code appears in ARCHITECTURE.md
    (the catalog the suppression syntax points suppressors at)."""
    catalog = checker_catalog()
    assert set(catalog) == {
        "registry", "concurrency", "tracing", "exceptions", "compat",
        "layers", "durability", "protocol", "lifecycle", "spec",
        "spmd", "caps",
    }
    arch = open(os.path.join(REPO, "ARCHITECTURE.md"), encoding="utf-8").read()
    for codes in catalog.values():
        for code in codes:
            assert code in arch, f"{code} missing from ARCHITECTURE.md"


def test_registry_config_error_is_loud(tmp_path):
    cfg = LintConfig(root=str(tmp_path), registry_path="nope/events.py",
                     native_map_path="nope/native.py")
    src = tmp_path / "x.py"
    src.write_text("def f(m):\n    m.bump('anything')\n")
    diags = lint_paths([str(src)], cfg)
    assert "DS105" in codes_of(diags)


# -- the CI gates -----------------------------------------------------------


def test_shipped_tree_lints_clean_with_empty_baseline():
    """THE gate: `dsort lint dsort_tpu/` on the real tree, real pyproject
    config, and the baseline must be shipped EMPTY."""
    from dsort_tpu import cli

    base = json.load(open(os.path.join(REPO, ".lint-baseline.json")))
    assert base["entries"] == [], "ship the tree lint-clean, not baselined"
    assert cli.main(["lint", "--root", REPO]) == 0


def test_seeded_python_counter_drift_is_caught(tmp_path):
    """A counter bumped in Python but absent from COUNTERS fails the lint
    without running anything."""
    pkg = tmp_path / "dsort_tpu"
    shutil.copytree(os.path.join(REPO, "dsort_tpu"), pkg,
                    ignore=shutil.ignore_patterns("*.so", "selftest*",
                                                  "__pycache__"))
    (pkg / "_seeded.py").write_text(
        "def f(metrics):\n    metrics.bump('never_registered_counter')\n"
    )
    shutil.copy(os.path.join(REPO, "pyproject.toml"), tmp_path / "pyproject.toml")
    cfg = load_config(str(tmp_path))
    diags = lint_paths([str(pkg)], cfg)
    assert [d for d in diags if d.code == "DS102"
            and "never_registered_counter" in d.message]


def test_seeded_cpp_event_drift_is_caught(tmp_path):
    """Seeding a fake event name into coordinator.cpp is caught by the
    registry checker (acceptance criterion — no cluster involved)."""
    native = tmp_path / "native"
    native.mkdir()
    src = open(
        os.path.join(REPO, "dsort_tpu", "runtime", "native", "coordinator.cpp"),
        encoding="utf-8",
    ).read()
    assert 'log_event_locked("worker_join"' in src
    seeded = src.replace(
        'log_event_locked("worker_join"', 'log_event_locked("franken_event"'
    )
    (native / "coordinator.cpp").write_text(seeded)
    cfg = LintConfig(root=REPO)
    diags = lint_paths([str(native / "coordinator.cpp")], cfg)
    assert [d for d in diags if d.code == "DS103"
            and "franken_event" in d.message]


def test_cli_lint_nonzero_exit_on_findings(capsys):
    from dsort_tpu import cli

    rc = cli.main(["lint", "--root", REPO, fixture("bad_compat.py")])
    out = capsys.readouterr().out
    assert rc == 1
    assert "DS501" in out and "DS502" in out


def test_cli_lint_runs_without_jax_backend():
    """`dsort lint` must not initialize a JAX backend (it skips the x64
    toggle and never touches devices) — enforced by pinning JAX_PLATFORMS
    to a platform that CANNOT initialize: any backend touch in the lint
    path would crash the subprocess."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "no_such_platform_lint_guard"
    env.pop("PALLAS_AXON_POOL_IPS", None)  # site hook pins a TPU platform
    r = subprocess.run(
        [sys.executable, "-m", "dsort_tpu.cli", "lint", "--root", REPO],
        capture_output=True, text=True, env=env, cwd=REPO, timeout=300,
    )
    assert r.returncode == 0, r.stdout + r.stderr


def test_write_baseline_is_idempotent(tmp_path):
    """Regenerating the baseline must keep already-tolerated findings —
    linting THROUGH the old baseline and writing the leftovers would erase
    them and resurrect the findings on the next run."""
    from dsort_tpu import cli

    target = tmp_path / "bad.py"
    shutil.copy(fixture("bad_registry.py"), target)
    base = tmp_path / "base.json"
    assert cli.main(["lint", "--root", REPO, str(target), "--baseline",
                     str(base), "--write-baseline"]) == 0
    first = json.loads(base.read_text())["entries"]
    assert len(first) == 4
    assert cli.main(["lint", "--root", REPO, str(target), "--baseline",
                     str(base), "--write-baseline"]) == 0
    assert json.loads(base.read_text())["entries"] == first
    assert cli.main(["lint", "--root", REPO, str(target), "--baseline",
                     str(base)]) == 0  # still fully tolerated


def test_compat_checker_bypass_forms(tmp_path):
    """The `from jax import config` and `import jax.experimental.shard_map`
    spellings are the same violations and must not slip through."""
    src = tmp_path / "bypass.py"
    src.write_text(
        "from jax import config\n"
        "import jax.experimental.shard_map as shard_map\n\n\n"
        "def setup():\n"
        "    config.update(\"jax_enable_x64\", True)\n"
        "    return shard_map\n"
    )
    diags = lint_paths([str(src)], LintConfig(root=REPO))
    assert sorted(codes_of(diags)) == ["DS501", "DS502"]


def test_unknown_enable_name_is_loud():
    with pytest.raises(ValueError, match="unknown checkers"):
        lint_paths(
            [fixture("good_registry.py")],
            LintConfig(root=REPO, enable=("registry", "registries")),
        )


def test_cli_lint_missing_path_is_loud():
    """A typo'd path must fail, never pass vacuously as '0 findings'."""
    from dsort_tpu import cli

    with pytest.raises(SystemExit, match="no such path"):
        cli.main(["lint", "--root", REPO, "definitely/not/a/dir"])


def test_traced_lambda_reported_once(tmp_path):
    """The module-wide and per-function seeding walks both see an inline
    lambda; its findings must not double-report."""
    src = tmp_path / "lam.py"
    src.write_text(
        "import jax\n\n\ndef build():\n"
        "    f = jax.jit(lambda x: print(x) or x)\n    return f\n"
    )
    diags = lint_paths([str(src)], LintConfig(root=REPO))
    assert codes_of(diags) == ["DS301"]


def test_abba_not_reported_across_distinct_class_locks(tmp_path):
    """Two classes' same-named instance locks are DIFFERENT locks: opposite
    nesting orders across classes are not an ABBA inversion.  Module-level
    locks shared by both classes still are."""
    src = tmp_path / "locks.py"
    src.write_text(
        "import threading\n\n"
        "GA = threading.Lock()\nGB = threading.Lock()\n\n\n"
        "class A:\n"
        "    def __init__(self):\n"
        "        self._a = threading.Lock()\n"
        "        self._b = threading.Lock()\n\n"
        "    def fwd(self):\n"
        "        with self._a:\n"
        "            with self._b:\n"
        "                pass\n\n\n"
        "class B:\n"
        "    def __init__(self):\n"
        "        self._a = threading.Lock()\n"
        "        self._b = threading.Lock()\n\n"
        "    def rev(self):\n"
        "        with self._b:\n"
        "            with self._a:\n"
        "                pass\n\n"
        "    def g1(self):\n"
        "        with GA:\n"
        "            with GB:\n"
        "                pass\n\n\n"
        "class C:\n"
        "    def g2(self):\n"
        "        with GB:\n"
        "            with GA:\n"
        "                pass\n"
    )
    diags = lint_paths([str(src)], LintConfig(root=REPO))
    assert codes_of(diags) == ["DS203"]  # only the shared-global inversion
    assert "GA" in diags[0].message and "GB" in diags[0].message


# -- ISSUE 13: import-graph / layers ----------------------------------------


def test_import_graph_synthetic_package(tmp_path):
    """Unit-level contract of the cross-file import resolver: relative
    imports, `from pkg import submodule`, parent-__init__ execution, and
    TYPE_CHECKING exclusion."""
    from dsort_tpu.analysis.checkers.layers import ImportGraph

    pkg = tmp_path / "app"
    sub = pkg / "inner"
    sub.mkdir(parents=True)
    (pkg / "__init__.py").write_text("from app import base\n")
    (pkg / "base.py").write_text(
        "from typing import TYPE_CHECKING\n"
        "from . import util\n"
        "if TYPE_CHECKING:\n"
        "    import typing_only_backend\n"
    )
    (pkg / "util.py").write_text("import forbidden_backend.core\n")
    (sub / "__init__.py").write_text("")
    (sub / "leaf.py").write_text("from ..util import thing\n")
    graph = ImportGraph(str(tmp_path))
    assert graph.resolve("app") == ("app/__init__.py", True)
    assert graph.resolve("app.base") == ("app/base.py", False)
    assert graph.resolve("app.nope") is None
    assert graph.expand("app.*") == [
        "app", "app.base", "app.inner", "app.inner.leaf", "app.util",
    ]
    # relative `from . import util` resolves to app + app.util
    deps = {n for n, _ in graph.module_imports("app.base")}
    assert deps == {"typing", "app", "app.util"}  # TYPE_CHECKING excluded
    # two-dot relative from a nested module
    deps = {n for n, _ in graph.module_imports("app.inner.leaf")}
    assert "app.util" in deps
    # the checker end-to-end: one DS601 with the full chain
    from dsort_tpu.analysis.checkers.layers import LayersChecker

    cfg = LintConfig(
        root=str(tmp_path), layers={"app.base": ("forbidden_backend",)}
    )
    diags = lint_paths([str(pkg)], cfg, checkers=[LayersChecker()])
    assert codes_of(diags) == ["DS601"]
    assert "app.base -> app.util -> forbidden_backend.core" in diags[0].message


def test_layer_map_names_existing_modules():
    """ISSUE 13 CI gate (b): every [tool.dsort.lint.layers] pattern in THE
    pyproject resolves to at least one existing module — a renamed module
    cannot silently un-declare its purity contract."""
    from dsort_tpu.analysis.checkers.layers import ImportGraph

    cfg = load_config(REPO)
    assert cfg.layers, "the layers table vanished from pyproject.toml"
    graph = ImportGraph(REPO)
    for pattern in cfg.layers:
        assert graph.expand(pattern), (
            f"layers pattern {pattern!r} matches no module — update "
            "pyproject.toml to follow the rename"
        )
    # The §12 contracts specifically must stay declared.
    assert "dsort_tpu.fleet.proto" in cfg.layers
    assert "dsort_tpu.fleet.controller" in cfg.layers
    assert "dsort_tpu.serve.policy" in cfg.layers


def test_seeded_layer_violation_is_caught(tmp_path):
    """THE static purity gate: seeding a module-level `import jax` into a
    module the fleet controller reaches at import time fails `dsort lint`
    — no subprocess, no backend (the jax-blocked subprocess test in
    test_fleet.py stays as the dynamic backstop)."""
    pkg = tmp_path / "dsort_tpu"
    shutil.copytree(os.path.join(REPO, "dsort_tpu"), pkg,
                    ignore=shutil.ignore_patterns("*.so", "selftest*",
                                                  "__pycache__"))
    shutil.copy(os.path.join(REPO, "pyproject.toml"),
                tmp_path / "pyproject.toml")
    fair = pkg / "serve" / "fair.py"
    fair.write_text("import jax\n" + fair.read_text())
    cfg = load_config(str(tmp_path))
    cfg.baseline = None
    diags = [d for d in lint_paths([str(pkg)], cfg) if d.code == "DS601"]
    assert diags, "seeded jax import escaped the layer checker"
    # Both the directly-declared module and the fleet controller (which
    # reaches serve.fair through serve.policy) report the breach.
    msgs = "\n".join(d.message for d in diags)
    assert "dsort_tpu.fleet.controller" in msgs
    assert all(d.path == "dsort_tpu/serve/fair.py" for d in diags)


# -- ISSUE 13: result cache + --changed -------------------------------------


class _CountingChecker:
    """Minimal checker observing how often the engine really runs it."""

    name = "counting"
    codes = {"DS998": "test probe"}
    scope = ("*.py",)
    project = False

    def __init__(self):
        self.calls = 0

    def matches(self, relpath):
        return relpath.endswith(".py")

    def check(self, ctx):
        self.calls += 1
        if "seeded_violation" in ctx.source:
            from dsort_tpu.analysis import Diagnostic

            return [Diagnostic(ctx.relpath, 1, 0, "DS998", "seeded")]
        return []


def test_lint_cache_hits_and_invalidates(tmp_path):
    """ISSUE 13 satellite: the per-file result cache is keyed by content
    hash — unchanged files never re-lint, an edited file's stale entry is
    dropped, and a changed checker set invalidates the whole cache."""
    src = tmp_path / "mod.py"
    src.write_text("x = 1\n")
    cache = str(tmp_path / "cache.json")
    cfg = LintConfig(root=str(tmp_path))
    probe = _CountingChecker()
    assert lint_paths([str(src)], cfg, checkers=[probe], cache_path=cache) == []
    assert probe.calls == 1
    # warm: the cached entry is served, the checker never runs
    assert lint_paths([str(src)], cfg, checkers=[probe], cache_path=cache) == []
    assert probe.calls == 1
    # edit -> stale entry dropped, finding surfaces
    src.write_text("x = 1  # seeded_violation\n")
    diags = lint_paths([str(src)], cfg, checkers=[probe], cache_path=cache)
    assert probe.calls == 2 and codes_of(diags) == ["DS998"]
    # warm again on the NEW content
    diags = lint_paths([str(src)], cfg, checkers=[probe], cache_path=cache)
    assert probe.calls == 2 and codes_of(diags) == ["DS998"]
    # a different checker set cannot serve the old entries
    other = _CountingChecker()
    other.name = "counting2"
    lint_paths([str(src)], cfg, checkers=[other], cache_path=cache)
    assert other.calls == 1


def test_lint_cache_invalidates_on_registry_edit(tmp_path):
    """Editing a registry SOURCE invalidates cached per-file results —
    otherwise deleting an event type could leave stale 'clean' entries."""
    pkg = tmp_path / "proj"
    pkg.mkdir()
    reg = pkg / "events.py"
    reg.write_text("EVENT_TYPES = {'alpha': 'x'}\nCOUNTERS = {}\n")
    mod = pkg / "mod.py"
    mod.write_text("def f(m):\n    m.emit('alpha')\n")
    cfg = LintConfig(root=str(pkg), registry_path="events.py",
                     native_map_path="events.py")
    cache = str(pkg / "cache.json")
    assert [
        d for d in lint_paths([str(mod)], cfg, cache_path=cache)
        if d.code == "DS101"
    ] == []
    reg.write_text("EVENT_TYPES = {'beta': 'x'}\nCOUNTERS = {}\n")
    diags = lint_paths([str(mod)], cfg, cache_path=cache)
    assert [d for d in diags if d.code == "DS101"], (
        "stale cache served a clean verdict against the edited registry"
    )


def test_cli_lint_changed_scopes_to_git_diff(tmp_path):
    """`dsort lint --changed` lints exactly the files changed vs HEAD
    (plus untracked), and reports cleanly when nothing changed."""
    from dsort_tpu import cli

    def git(*argv):
        subprocess.run(
            ["git", "-C", str(tmp_path), "-c", "user.email=t@t",
             "-c", "user.name=t", *argv],
            check=True, capture_output=True,
        )

    git("init", "-q")
    clean = tmp_path / "clean.py"
    clean.write_text("x = 1\n")
    tracked = tmp_path / "tracked.py"
    tracked.write_text("y = 2\n")
    git("add", "-A")
    git("commit", "-q", "-m", "seed")
    # nothing changed -> loudly scoped to zero files, exit 0
    assert cli.main(["lint", "--root", str(tmp_path), "--changed",
                     "--no-cache"]) == 0
    # change one tracked file (violation) + add an untracked clean one
    tracked.write_text("def f(m):\n    m.bump('never_registered_x')\n")
    (tmp_path / "fresh.py").write_text("z = 3\n")
    rc = cli.main(["lint", "--root", str(tmp_path), "--changed",
                   "--no-cache"])
    assert rc == 1  # the changed file's DS102 fails the run
    # explicit paths and --changed are mutually exclusive
    with pytest.raises(SystemExit, match="exclusive"):
        cli.main(["lint", "--root", str(tmp_path), "--changed",
                  str(clean)])


def test_protocol_registry_config_error_is_loud(tmp_path):
    """DS804 mirrors DS105: a misconfigured proto/admission registry path
    is a finding, never a silently-empty vocabulary."""
    from dsort_tpu.analysis.checkers.protocol import ProtocolChecker

    cfg = LintConfig(root=str(tmp_path), proto_registry_path="nope/proto.py",
                     admission_registry_path="nope/admission.py")
    src = tmp_path / "x.py"
    src.write_text("from dsort_tpu.fleet.proto import send_frame\n")
    diags = lint_paths(
        [str(src)], cfg, checkers=[ProtocolChecker(scope=("*.py",))]
    )
    assert codes_of(diags) == ["DS804", "DS804"]


# -- native event round trip (registry <-> C++ <-> drain parser) ------------


def test_native_event_names_round_trip_registry():
    """Every event name the C++ coordinator can emit (scanned straight out
    of coordinator.cpp) parses through runtime/native.py's drain parser into
    a REGISTERED journal type — asserted statically + on synthetic drain
    lines, no cluster."""
    from dsort_tpu.analysis.cpp_lexer import call_string_args
    from dsort_tpu.runtime.native import _COORD_EVENT_TYPES, parse_coord_events
    from dsort_tpu.utils.events import EVENT_TYPES, EventLog

    src = open(
        os.path.join(REPO, "dsort_tpu", "runtime", "native", "coordinator.cpp"),
        encoding="utf-8",
    ).read()
    names = sorted({t.value for t in call_string_args(src, "log_event_locked")})
    assert names, "no native events found — did the C++ scan break?"
    lines = "".join(
        f"t={10.0 + i:.6f} ev={name} w=0 task={i}\n"
        for i, name in enumerate(names)
    )
    recs = parse_coord_events(lines)
    # nothing dropped: every emitted name is parseable...
    assert [r for r in recs] and len(recs) == len(names)
    assert {r["type"] for r in recs} <= set(EVENT_TYPES)
    # ...and ingests into a journal under registered types
    log = EventLog()
    for r in recs:
        log.ingest(r["t"], r["mono"], r["type"], worker=r["worker"])
    assert len(log) == len(names)
    # the parser map carries no dead entries pointing outside the registry
    assert set(_COORD_EVENT_TYPES.values()) <= set(EVENT_TYPES)
