"""Out-of-core wave pipeline (ISSUE 10, ARCHITECTURE §10): correctness,
(wave, run)-granular resume, the fault matrix (mid-ring device loss,
process kill between waves, stale manifests), the TeraSort record waves,
CLI/conf wiring, the analyzer's wave verdict, and the §10 schema."""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from dsort_tpu.models.wave_sort import (
    DIE_AFTER_WAVE_ENV,
    ExternalWaveSort,
    ExternalWaveTeraSort,
    sample_global_splitters,
)
from dsort_tpu.utils.events import EventLog
from dsort_tpu.utils.metrics import Metrics

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _mesh(n=8):
    from dsort_tpu.parallel.mesh import local_device_mesh

    return local_device_mesh(n)


def _metered():
    return Metrics(journal=EventLog())


# -- correctness -------------------------------------------------------------


@pytest.mark.parametrize(
    "n,wave,p",
    [(0, 64, 8), (1, 64, 8), (1000, 300, 8), (20000, 4096, 8),
     (5000, 777, 4), (4096, 4096, 8)],
)
def test_wave_matches_oracle(tmp_path, devices, n, wave, p):
    rng = np.random.default_rng(n + wave)
    data = rng.integers(-(2**31), 2**31 - 1, n, dtype=np.int64).astype(np.int32)
    s = ExternalWaveSort(
        _mesh(p), wave_elems=wave, spill_dir=str(tmp_path), job_id=f"w{n}_{p}"
    )
    np.testing.assert_array_equal(s.sort(data), np.sort(data))


def test_wave_matches_oracle_zipf_int64(tmp_path, devices):
    from dsort_tpu.data.ingest import gen_zipf

    data = gen_zipf(30000, a=1.3, dtype=np.int64, seed=3)
    s = ExternalWaveSort(
        _mesh(8), wave_elems=5000, spill_dir=str(tmp_path), job_id="wz"
    )
    m = _metered()
    np.testing.assert_array_equal(s.sort(data, metrics=m), np.sort(data))
    # Every wave planned a ring schedule against the measured histogram.
    assert m.counters["waves_sorted"] == 6
    assert m.counters["exchange_ring_steps"] == 6 * 7
    assert "skew_report" in m.journal.types()


def test_wave_float_nan_keys(tmp_path, devices):
    rng = np.random.default_rng(9)
    data = rng.standard_normal(12000).astype(np.float32)
    data[::211] = np.nan
    data[::301] = -0.0
    s = ExternalWaveSort(
        _mesh(8), wave_elems=2500, spill_dir=str(tmp_path), job_id="wf"
    )
    out = s.sort(data)
    expect = np.sort(data)
    # NaNs sort last like np.sort; -0.0/+0.0 keep value equality.
    np.testing.assert_array_equal(
        out.view(np.uint32), expect.view(np.uint32)
    )


def test_wave_sentinel_valued_keys(tmp_path, devices):
    sent = np.iinfo(np.int32).max
    rng = np.random.default_rng(4)
    data = rng.integers(-100, 100, 3000).astype(np.int32)
    data[::17] = sent  # real max-valued keys must survive the pad trims
    s = ExternalWaveSort(
        _mesh(8), wave_elems=512, spill_dir=str(tmp_path), job_id="ws"
    )
    np.testing.assert_array_equal(s.sort(data), np.sort(data))


def test_wave_no_overlap_matches(tmp_path, devices):
    rng = np.random.default_rng(5)
    data = rng.integers(0, 10**6, 16000).astype(np.int32)
    s = ExternalWaveSort(
        _mesh(8), wave_elems=3000, spill_dir=str(tmp_path), job_id="wno",
        overlap=False,
    )
    np.testing.assert_array_equal(s.sort(data), np.sort(data))


def test_wave_binary_file_roundtrip_memmap(tmp_path, devices):
    rng = np.random.default_rng(6)
    data = rng.integers(-(2**31), 2**31 - 1, 20000, dtype=np.int64).astype(
        np.int32
    )
    in_path, out_path = str(tmp_path / "in.bin"), str(tmp_path / "out.bin")
    data.tofile(in_path)
    s = ExternalWaveSort(
        _mesh(8), wave_elems=4096, spill_dir=str(tmp_path / "spill"),
        job_id="wfile",
    )
    s.sort_binary_file(in_path, out_path, dtype=np.int32)
    np.testing.assert_array_equal(
        np.fromfile(out_path, dtype=np.int32), np.sort(data)
    )


def test_splitters_are_deterministic_and_sorted(devices):
    rng = np.random.default_rng(7)
    data = rng.integers(-(10**6), 10**6, 50000).astype(np.int32)
    s1 = sample_global_splitters(data, len(data), 8)
    s2 = sample_global_splitters(data, len(data), 8)
    np.testing.assert_array_equal(s1, s2)
    assert len(s1) == 7 and (np.diff(s1) >= 0).all()


# -- resume contract: (wave, run) granularity --------------------------------


def test_wave_full_resume_restores_every_run(tmp_path, devices):
    rng = np.random.default_rng(8)
    data = rng.integers(-(10**6), 10**6, 24000).astype(np.int32)
    s1 = ExternalWaveSort(
        _mesh(8), wave_elems=4000, spill_dir=str(tmp_path), job_id="wr"
    )
    m1 = _metered()
    np.testing.assert_array_equal(s1.sort(data, metrics=m1), np.sort(data))
    assert m1.counters["runs_sorted"] == 6 * 8
    s2 = ExternalWaveSort(
        _mesh(8), wave_elems=4000, spill_dir=str(tmp_path), job_id="wr"
    )
    m2 = _metered()
    np.testing.assert_array_equal(s2.sort(data, metrics=m2), np.sort(data))
    assert m2.counters["runs_resumed"] == 6 * 8
    assert m2.counters.get("runs_sorted", 0) == 0
    # resume=False clears and redoes the work.
    s3 = ExternalWaveSort(
        _mesh(8), wave_elems=4000, spill_dir=str(tmp_path), job_id="wr",
        resume=False,
    )
    m3 = _metered()
    np.testing.assert_array_equal(s3.sort(data, metrics=m3), np.sort(data))
    assert m3.counters["runs_sorted"] == 6 * 8


def test_wave_partial_resume_redoes_only_missing_runs(tmp_path, devices):
    """Deleting two runs of one wave re-sorts exactly those two runs — the
    (wave, run) granularity the manifest contract promises."""
    rng = np.random.default_rng(10)
    data = rng.integers(-(10**6), 10**6, 24000).astype(np.int32)
    s = ExternalWaveSort(
        _mesh(8), wave_elems=4000, spill_dir=str(tmp_path), job_id="wp"
    )
    s.sort(data)
    os.remove(str(tmp_path / "wp" / "aux_w00002_00003.npy"))
    os.remove(str(tmp_path / "wp" / "aux_w00002_00005.npy"))
    s2 = ExternalWaveSort(
        _mesh(8), wave_elems=4000, spill_dir=str(tmp_path), job_id="wp"
    )
    m = _metered()
    np.testing.assert_array_equal(s2.sort(data, metrics=m), np.sort(data))
    assert m.counters["wave_runs_resorted"] == 2
    assert m.counters["runs_resumed"] == 6 * 8 - 2
    assert m.counters["wave_resort_keys"] < len(data)
    ev = [e for e in m.journal.events() if e.type == "wave_resume"]
    assert len(ev) == 1 and ev[0].fields["wave"] == 2
    assert ev[0].fields["missing"] == 2 and ev[0].fields["present"] == 6


def test_wave_stale_manifest_detection(tmp_path, devices):
    """Same job_id, different data / different wave layout: the store is
    cleared instead of serving another job's runs — at (wave, run)
    granularity nothing survives a layout change."""
    rng = np.random.default_rng(11)
    data = rng.integers(-(10**6), 10**6, 12000).astype(np.int32)
    s = ExternalWaveSort(
        _mesh(8), wave_elems=3000, spill_dir=str(tmp_path), job_id="wstale"
    )
    s.sort(data)
    flipped = data.copy()
    flipped[0] ^= 1
    s2 = ExternalWaveSort(
        _mesh(8), wave_elems=3000, spill_dir=str(tmp_path), job_id="wstale"
    )
    m2 = _metered()
    np.testing.assert_array_equal(s2.sort(flipped, metrics=m2), np.sort(flipped))
    assert "runs_resumed" not in m2.counters  # cleared, not trusted
    # Changed wave layout (same data) is equally stale.
    s3 = ExternalWaveSort(
        _mesh(8), wave_elems=2000, spill_dir=str(tmp_path), job_id="wstale"
    )
    m3 = _metered()
    np.testing.assert_array_equal(s3.sort(flipped, metrics=m3), np.sort(flipped))
    assert "runs_resumed" not in m3.counters


# -- the fault matrix --------------------------------------------------------


def test_wave_mid_ring_device_loss_repairs_in_flight(tmp_path, devices):
    """A device lost inside wave k's ring (the fault_hook seam, same
    injection point as the scheduler's mid-ring drill) repairs at run
    granularity IN FLIGHT: that wave's runs re-sort on the host, later
    waves keep using the mesh, and the output stays bit-identical."""
    from dsort_tpu.scheduler.fault import WorkerFailure

    rng = np.random.default_rng(12)
    data = rng.integers(-(10**6), 10**6, 24000).astype(np.int32)
    s = ExternalWaveSort(
        _mesh(8), wave_elems=4000, spill_dir=str(tmp_path), job_id="wfault"
    )
    calls = {"n": 0}

    def hook():
        calls["n"] += 1
        if calls["n"] == 3:
            raise WorkerFailure("injected mid-ring device loss")

    s.fault_hook = hook
    m = _metered()
    np.testing.assert_array_equal(s.sort(data, metrics=m), np.sort(data))
    assert m.counters["wave_runs_resorted"] == 8  # one wave's runs
    assert m.counters["waves_sorted"] == 5  # the rest stayed on the mesh
    types = m.journal.types()
    assert "wave_resume" in types
    # resume_fraction contract: one wave of 6 => 8/48 runs.
    assert m.counters["wave_runs_resorted"] / (6 * 8) <= 1 / 6 + 1 / 48


def test_wave_process_kill_between_waves_resumes(tmp_path, devices):
    """The restart-resume drill: a process killed after wave 1 persisted
    leaves waves 0-1 durable; the re-run restores them and sorts only the
    remaining waves — resume_fraction ≤ 1/num_waves + one wave's slack
    over the INTERRUPTED portion, and the final output is bit-identical."""
    rng = np.random.default_rng(13)
    data = rng.integers(-(10**6), 10**6, 24000).astype(np.int32)
    in_path = str(tmp_path / "in.bin")
    data.tofile(in_path)
    script = (
        "import numpy as np, jax\n"
        "jax.config.update('jax_enable_x64', True)\n"
        "from dsort_tpu.parallel.mesh import local_device_mesh\n"
        "from dsort_tpu.models.wave_sort import ExternalWaveSort\n"
        "s = ExternalWaveSort(local_device_mesh(8), wave_elems=4000,\n"
        f"    spill_dir={str(tmp_path)!r}, job_id='wkill')\n"
        f"s.sort_binary_file({in_path!r}, {str(tmp_path / 'out.bin')!r},\n"
        "    dtype=np.int32)\n"
    )
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        XLA_FLAGS="--xla_force_host_platform_device_count=8",
        **{DIE_AFTER_WAVE_ENV: "1"},
    )
    r = subprocess.run(
        [sys.executable, "-c", script], env=env, capture_output=True,
        text=True, timeout=560,
    )
    assert r.returncode == 17, r.stderr[-2000:]
    done = {
        name for name in os.listdir(tmp_path / "wkill")
        if name.startswith("aux_w")
    }
    # Waves 0 and 1 persisted all 8 runs each; later waves never ran.
    assert len(done) == 16, sorted(done)
    s2 = ExternalWaveSort(
        _mesh(8), wave_elems=4000, spill_dir=str(tmp_path), job_id="wkill"
    )
    m = _metered()
    np.testing.assert_array_equal(s2.sort(data, metrics=m), np.sort(data))
    assert m.counters["runs_resumed"] == 16
    assert m.counters["runs_sorted"] == 4 * 8  # only the unfinished waves
    # No partial wave here, so the run-granular repair path stayed idle...
    assert "wave_runs_resorted" not in m.counters
    # ...and the resumed fraction of the whole job is exactly 4/6 waves.
    assert m.counters["runs_sorted"] / (6 * 8) == pytest.approx(4 / 6)


# -- TeraSort records through the wave pipeline ------------------------------


def _tera_oracle(raw):
    from dsort_tpu.data.ingest import _pack_be64, terasort_secondary

    order = np.lexsort(
        (terasort_secondary(raw[:, 8:10]), _pack_be64(raw[:, :8]))
    )
    return raw[order]


def test_wave_terasort_matches_oracle(tmp_path, devices):
    from dsort_tpu.data.ingest import gen_terasort_file

    in_path = str(tmp_path / "in.bin")
    out_path = str(tmp_path / "out.bin")
    gen_terasort_file(in_path, 20000, seed=14)
    t = ExternalWaveTeraSort(
        _mesh(8), wave_recs=4096, spill_dir=str(tmp_path / "spill"),
        job_id="tw",
    )
    m = _metered()
    t.sort_file(in_path, out_path, metrics=m)
    raw = np.fromfile(in_path, np.uint8).reshape(-1, 100)
    got = np.fromfile(out_path, np.uint8).reshape(-1, 100)
    np.testing.assert_array_equal(got, _tera_oracle(raw))
    assert m.counters["waves_sorted"] == 5  # mesh-parallel run generation


def test_wave_terasort_partial_resume(tmp_path, devices):
    from dsort_tpu.data.ingest import gen_terasort_file

    in_path = str(tmp_path / "in.bin")
    out_path = str(tmp_path / "out.bin")
    gen_terasort_file(in_path, 12000, seed=15)
    t = ExternalWaveTeraSort(
        _mesh(8), wave_recs=3000, spill_dir=str(tmp_path), job_id="twp"
    )
    t.sort_file(in_path, out_path)
    os.remove(str(tmp_path / "twp" / "aux_w00001_00004.npy"))
    t2 = ExternalWaveTeraSort(
        _mesh(8), wave_recs=3000, spill_dir=str(tmp_path), job_id="twp"
    )
    m = _metered()
    t2.sort_file(in_path, out_path, metrics=m)
    raw = np.fromfile(in_path, np.uint8).reshape(-1, 100)
    got = np.fromfile(out_path, np.uint8).reshape(-1, 100)
    np.testing.assert_array_equal(got, _tera_oracle(raw))
    assert m.counters["wave_runs_resorted"] == 1
    assert m.counters["runs_resumed"] == 4 * 8 - 1


# -- CLI / conf / bench gates ------------------------------------------------


def test_cli_external_mesh_wave_with_journal_and_analyze(tmp_path, devices, capsys):
    from dsort_tpu import cli
    from dsort_tpu.obs.analyze import analyze_records
    from dsort_tpu.utils.events import EventLog as EL

    rng = np.random.default_rng(16)
    data = rng.integers(-(2**31), 2**31 - 1, 16000, dtype=np.int64).astype(
        np.int32
    )
    in_path, out_path = str(tmp_path / "in.bin"), str(tmp_path / "out.bin")
    jpath = str(tmp_path / "journal.jsonl")
    data.tofile(in_path)
    rc = cli.main([
        "external", in_path, "-o", out_path, "--mesh", "8",
        "--wave-elems", "4000", "--spill-dir", str(tmp_path / "spill"),
        "--journal", jpath,
    ])
    assert rc == 0
    np.testing.assert_array_equal(
        np.fromfile(out_path, dtype=np.int32), np.sort(data)
    )
    # --journal parity with `dsort run`: the wave events landed, and the
    # analyzer renders the wave plane from them.
    records = EL.read_jsonl(jpath)
    types = {r["type"] for r in records}
    assert {"wave_start", "wave_done", "skew_report"} <= types
    verdict = analyze_records(records)
    assert verdict["waves"] is not None
    assert verdict["waves"]["count"] == 4
    assert verdict["waves"]["gating"] is not None
    assert verdict["waves"]["slowest"]["seconds"] >= 0
    # The wave phases land in the ordinary waterfall.
    assert "wave_exchange" in (verdict["phases"].get("p0") or {})
    # And `dsort report --analyze` renders it end to end.
    rc = cli.main(["report", jpath, "--analyze"])
    out = capsys.readouterr().out
    assert rc == 0 and "waves" in out


def test_external_conf_keys_and_flag_precedence(tmp_path, devices):
    from dsort_tpu.config import ConfigError, ExternalConfig, SortConfig

    conf = tmp_path / "ext.conf"
    conf.write_text(
        "EXTERNAL_WAVE_ELEMS=5000\nEXTERNAL_RUN_ELEMS=2048\nEXTERNAL_MESH=4\n"
    )
    cfg = SortConfig.from_conf_file(str(conf))
    assert cfg.external.wave_elems == 5000
    assert cfg.external.run_elems == 2048
    assert cfg.external.mesh == 4
    assert SortConfig().external.mesh is None
    with pytest.raises(ConfigError):
        ExternalConfig(wave_elems=1)
    # Flag precedence over conf (same contract as SERVE_*): the CLI runs
    # the wave path with the conf mesh but the flag's wave size.
    from dsort_tpu import cli

    rng = np.random.default_rng(17)
    data = rng.integers(0, 1 << 20, 8000).astype(np.int32)
    in_path, out_path = str(tmp_path / "in.bin"), str(tmp_path / "o.bin")
    data.tofile(in_path)
    jpath = str(tmp_path / "j.jsonl")
    rc = cli.main([
        "external", in_path, "-o", out_path, "--conf", str(conf),
        "--wave-elems", "2000", "--spill-dir", str(tmp_path / "sp"),
        "--journal", jpath,
    ])
    assert rc == 0
    np.testing.assert_array_equal(
        np.fromfile(out_path, dtype=np.int32), np.sort(data)
    )
    from dsort_tpu.utils.events import EventLog as EL

    waves = [
        r for r in EL.read_jsonl(jpath) if r["type"] == "wave_start"
    ]
    assert len(waves) == 4  # 8000 keys / flag's 2000, on the conf's mesh


def test_cli_terasort_external_mesh(tmp_path, devices):
    from dsort_tpu import cli
    from dsort_tpu.data.ingest import gen_terasort_file

    in_path = str(tmp_path / "in.bin")
    out_path = str(tmp_path / "out.bin")
    gen_terasort_file(in_path, 8000, seed=18)
    rc = cli.main([
        "terasort", in_path, "-o", out_path, "--external", "--mesh", "8",
        "--run-recs", "2000", "--spill-dir", str(tmp_path / "spill"),
        "--job-id", "twcli",
    ])
    assert rc == 0
    raw = np.fromfile(in_path, np.uint8).reshape(-1, 100)
    got = np.fromfile(out_path, np.uint8).reshape(-1, 100)
    np.testing.assert_array_equal(got, _tera_oracle(raw))


def test_bench_external_wave_gate(tmp_path, devices, capsys):
    """Tier-1 gate for `make external-smoke`: the wave-pipeline bench
    harness runs end to end — over-budget dataset bit-identical, overlap
    A/B measured, mid-wave fault drill within the resume_fraction bound."""
    from dsort_tpu import cli

    rc = cli.main(["bench", "--external-wave", "--n", "65536", "--reps", "1"])
    out = capsys.readouterr().out
    rows = [json.loads(ln) for ln in out.splitlines() if ln.startswith("{")]
    assert rc == 0
    main_row = next(r for r in rows if "uniform" in r["metric"])
    drill = next(r for r in rows if "fault_drill" in r["metric"])
    assert main_row["bit_identical"] is True
    assert main_row["over_hbm_factor"] == 8
    assert main_row["overlap_speedup"] > 0
    assert drill["bit_identical"] is True
    assert drill["runs_resorted"] > 0
    assert drill["resume_fraction"] <= 1 / drill["num_waves"] + 1 / 64


def test_bench_r10_artifact_checks_and_compares():
    """BENCH_r10.jsonl: --check clean, the wave rows join the trajectory as
    'added' metrics vs r09, and the recorded rows carry the acceptance
    contract: ≥8x-over-budget bit-identical sort, overlap A/B faster, and
    a mid-wave fault drill within the resume_fraction bound."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "bench", os.path.join(REPO, "bench.py")
    )
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    r10 = os.path.join(REPO, "BENCH_r10.jsonl")
    assert bench.check_artifact(r10) == []
    rows = bench.compare_artifacts(os.path.join(REPO, "BENCH_r09.jsonl"), r10)
    added = {r["metric"] for r in rows if r["class"] == "added"}
    assert any(m.startswith("external_wave_sort_uniform") for m in added)
    assert any(m.startswith("external_wave_fault_drill") for m in added)
    with open(r10) as f:
        lines = [json.loads(ln) for ln in f if ln.strip()]
    main_row = next(
        l for l in lines
        if l.get("metric", "").startswith("external_wave_sort_uniform")
    )
    drill = next(
        l for l in lines
        if l.get("metric", "").startswith("external_wave_fault_drill")
    )
    assert main_row["bit_identical"] is True
    assert main_row["over_hbm_factor"] >= 8
    assert main_row["overlap_speedup"] > 1.0  # the wave pipeline is faster
    assert drill["bit_identical"] is True
    assert drill["resume_fraction"] <= 1 / drill["num_waves"] + 1 / 64


# -- ARCHITECTURE §10 schema enforcement -------------------------------------


def test_architecture_documents_wave_plane():
    """§10's contract is test-enforced like §7/§8/§9: the wave state
    machine's event names, the manifest schema fields, the run-file
    pattern, and the resume vocabulary all appear verbatim."""
    from dsort_tpu.utils.events import COUNTERS, EVENT_TYPES

    arch = open(
        os.path.join(REPO, "ARCHITECTURE.md"), encoding="utf-8"
    ).read()
    assert "## 10. Out-of-core wave plane" in arch
    for etype in ("wave_start", "wave_done", "wave_resume"):
        assert f"`{etype}`" in arch, f"event {etype} undocumented"
        assert etype in EVENT_TYPES
    for counter in ("waves_sorted", "wave_runs_resorted", "wave_resort_keys"):
        assert f"`{counter}`" in arch, f"counter {counter} undocumented"
        assert counter in COUNTERS
    for field in ("num_waves", "num_ranges", "wave_elems", "splitters",
                  "fingerprint", "storage_dtype"):
        assert f"`{field}`" in arch, f"manifest field {field} undocumented"
    for term in ("aux_w", "resume_fraction", "--wave-elems", "--mesh",
                 "over_hbm_factor", "DSORT_WAVE_DIE_AFTER_WAVE",
                 "EXTERNAL_WAVE_ELEMS"):
        assert term in arch, f"{term} missing from §10"
    # The analyzer's wave verdict is part of the §9 contract too.
    assert "`waves`" in arch
