"""Distributed sample-sort tests (SURVEY.md §7 step 4) on the simulated mesh."""

import numpy as np
import pytest

from dsort_tpu.config import JobConfig
from dsort_tpu.data.ingest import gen_terasort, gen_uniform, gen_zipf
from dsort_tpu.parallel.sample_sort import SampleSort
from dsort_tpu.utils.metrics import Metrics


@pytest.mark.parametrize("n", [0, 1, 5, 1000, 100_000])
def test_sample_sort_uniform(mesh8, n):
    data = gen_uniform(n, seed=n + 1)
    out = SampleSort(mesh8).sort(data)
    np.testing.assert_array_equal(out, np.sort(data))


def test_sample_sort_zipf_skew(mesh8):
    # Zipf (BASELINE config #5): heavy duplicate skew stresses splitters.
    data = gen_zipf(80_000, a=1.2, seed=9)
    m = Metrics()
    out = SampleSort(mesh8).sort(data, metrics=m)
    np.testing.assert_array_equal(out, np.sort(data))


def test_sample_sort_all_equal_triggers_capacity_retry(mesh8):
    # Worst case: every key identical -> one bucket takes everything; with
    # capacity_factor=1 this must overflow, retry, and still be correct.
    data = np.full(8_000, 123456, dtype=np.int32)
    m = Metrics()
    out = SampleSort(mesh8, JobConfig(capacity_factor=1.0)).sort(data, metrics=m)
    np.testing.assert_array_equal(out, data)
    assert m.counters.get("capacity_retries", 0) >= 1


def test_sample_sort_negative_and_extremes(mesh8):
    data = np.array(
        [-1, 0, 1, -(2**31), 2**31 - 1, 2**31 - 1, -1, 7] * 100, dtype=np.int32
    )
    out = SampleSort(mesh8).sort(data)
    np.testing.assert_array_equal(out, np.sort(data))


def test_sample_sort_int64(mesh8):
    data = gen_uniform(20_000, dtype=np.int64, seed=3)
    out = SampleSort(mesh8, JobConfig(key_dtype=np.int64)).sort(data)
    np.testing.assert_array_equal(out, np.sort(data))


def test_sample_sort_output_is_range_partitioned(mesh8):
    # The distributed contract: device p's keys all <= device p+1's keys —
    # i.e. the output needs NO central merge (unlike server.c:481-524).
    data = gen_uniform(50_000, seed=11)
    out = SampleSort(mesh8).sort(data)
    np.testing.assert_array_equal(out, np.sort(data))  # concat of shards IS sorted


def test_sample_sort_kv_terasort(mesh8):
    keys, payload = gen_terasort(10_000, seed=13)
    sk, sv = SampleSort(mesh8, JobConfig(key_dtype=np.uint64)).sort_kv(keys, payload)
    np.testing.assert_array_equal(sk, np.sort(keys))
    # Payloads must follow their keys: compare as multiset of records.
    def records(k, v):
        return sorted(zip(k.tolist(), map(bytes, v)))

    assert records(sk, sv) == records(keys, payload)


def test_sample_sort_kv_duplicate_keys_keep_payloads(mesh8):
    rng = np.random.default_rng(17)
    keys = rng.integers(0, 50, 5_000).astype(np.int32)  # heavy duplicates
    payload = rng.integers(0, 255, (5_000, 4)).astype(np.uint8)
    sk, sv = SampleSort(mesh8).sort_kv(keys, payload)
    np.testing.assert_array_equal(sk, np.sort(keys))
    assert sorted(zip(sk.tolist(), map(bytes, sv))) == sorted(
        zip(keys.tolist(), map(bytes, payload))
    )


def test_sample_sort_kv_full_10byte_key_order(mesh8):
    # TeraSort's real contract: order by the FULL 10-byte key.  Force heavy
    # 8-byte-prefix collisions so the 2-byte secondary must do the ordering.
    from dsort_tpu.data.ingest import terasort_secondary

    rng = np.random.default_rng(23)
    n = 6_000
    keys = rng.integers(0, 16, n).astype(np.uint64)  # ~375 records per prefix
    payload = rng.integers(0, 256, (n, 92), dtype=np.uint8)
    sec = terasort_secondary(payload)
    sk, sv = SampleSort(mesh8, JobConfig(key_dtype=np.uint64)).sort_kv(
        keys, payload, secondary=sec
    )
    ssec = terasort_secondary(sv)
    # (key, secondary) pairs are globally nondecreasing lexicographically...
    pairs = sk.astype(np.uint64) * (1 << 16) + ssec.astype(np.uint64)
    assert (np.diff(pairs.astype(np.int64)) >= 0).all()
    # ...and the full records are a permutation of the input.
    assert sorted(zip(sk.tolist(), map(bytes, sv))) == sorted(
        zip(keys.tolist(), map(bytes, payload))
    )


def test_sample_sort_kv_secondary_with_capacity_retry(mesh8):
    # All-equal primaries overflow one bucket; the kv2 path must retry and
    # still produce exact (key, secondary) order.
    from dsort_tpu.data.ingest import terasort_secondary

    rng = np.random.default_rng(29)
    n = 4_000
    keys = np.zeros(n, dtype=np.uint64)
    payload = rng.integers(0, 256, (n, 8), dtype=np.uint8)
    sec = terasort_secondary(payload)
    m = Metrics()
    sk, sv = SampleSort(
        mesh8, JobConfig(key_dtype=np.uint64, capacity_factor=1.0)
    ).sort_kv(keys, payload, metrics=m, secondary=sec)
    assert m.counters.get("capacity_retries", 0) >= 1
    assert (np.diff(terasort_secondary(sv).astype(np.int64)) >= 0).all()
    assert sorted(map(bytes, sv)) == sorted(map(bytes, payload))


@pytest.mark.parametrize(
    "dtype",
    [np.uint32, np.float32, np.float64, np.int8, np.uint8, np.int16, np.uint16],
)
def test_sample_sort_more_dtypes(mesh8, dtype):
    rng = np.random.default_rng(41)
    if np.issubdtype(dtype, np.floating):
        data = (rng.standard_normal(10_000) * 1e6).astype(dtype)
    else:
        info = np.iinfo(dtype)
        data = rng.integers(info.min, info.max, 10_000).astype(dtype)
    out = SampleSort(mesh8, JobConfig(key_dtype=dtype)).sort(data)
    assert out.dtype == data.dtype
    np.testing.assert_array_equal(out, np.sort(data))


def test_sample_sort_fuzz_distributions(mesh8):
    # Property sweep: one padded shape (shared compile), many distributions.
    rng = np.random.default_rng(43)
    n = 9_999
    cases = [
        rng.integers(-(2**31), 2**31 - 1, n).astype(np.int32),   # full range
        rng.integers(0, 10, n).astype(np.int32),                 # tiny alphabet
        np.sort(rng.integers(0, 10**6, n)).astype(np.int32),     # presorted
        np.sort(rng.integers(0, 10**6, n))[::-1].astype(np.int32),  # reversed
        np.concatenate([np.zeros(n // 2), rng.integers(0, 100, n - n // 2)]).astype(np.int32),  # half zeros
    ]
    sorter = SampleSort(mesh8)
    for i, data in enumerate(cases):
        out = sorter.sort(data)
        np.testing.assert_array_equal(out, np.sort(data), err_msg=f"case {i}")


def test_sample_sort_bitonic_merge_kernel(mesh8):
    data = gen_uniform(30_000, seed=61)
    out = SampleSort(mesh8, JobConfig(merge_kernel="bitonic")).sort(data)
    np.testing.assert_array_equal(out, np.sort(data))


def test_sample_sort_bitonic_merge_on_7_device_mesh():
    # Non-power-of-two mesh (post-failure shape): merge tree pads rows.
    import jax

    from dsort_tpu.parallel.mesh import local_device_mesh

    mesh7 = local_device_mesh(7)
    data = gen_uniform(10_000, seed=62)
    out = SampleSort(mesh7, JobConfig(merge_kernel="bitonic")).sort(data)
    np.testing.assert_array_equal(out, np.sort(data))


def test_sample_sort_kv_bitonic_merge_kernel(mesh8):
    # merge_kernel applies to the kv path too (bitonic kv merge tree of the
    # received sorted runs) and must keep every record.
    from dsort_tpu.data.ingest import gen_terasort

    keys, payload = gen_terasort(8_000, seed=23)
    job = JobConfig(key_dtype=np.uint64, merge_kernel="bitonic")
    sk, sv = SampleSort(mesh8, job).sort_kv(keys, payload)
    np.testing.assert_array_equal(sk, np.sort(keys))
    assert sorted(zip(sk.tolist(), map(bytes, sv))) == sorted(
        zip(keys.tolist(), map(bytes, payload))
    )


@pytest.mark.slow  # interpret-mode block merge: ~20-35 s on CPU
def test_sample_sort_block_merge_kernel(mesh8):
    # The block-kernel merge entry (VERDICT r3 #2): received sorted runs are
    # merged from level 2*cap up instead of fully re-sorted.
    data = gen_uniform(30_000, seed=63)
    out = SampleSort(mesh8, JobConfig(merge_kernel="block_merge")).sort(data)
    np.testing.assert_array_equal(out, np.sort(data))


@pytest.mark.slow  # interpret-mode block merge: ~20-35 s on CPU
def test_sample_sort_block_merge_on_7_device_mesh():
    # Non-power-of-two mesh (post-failure shape): merge pads sentinel rows.
    from dsort_tpu.parallel.mesh import local_device_mesh

    mesh7 = local_device_mesh(7)
    data = gen_uniform(10_000, seed=64)
    out = SampleSort(mesh7, JobConfig(merge_kernel="block_merge")).sort(data)
    np.testing.assert_array_equal(out, np.sort(data))


@pytest.mark.slow  # interpret-mode block merge: ~20-35 s on CPU
def test_merge_kernel_auto_resolves_to_block_merge(mesh8, monkeypatch):
    """The default ('auto') must route to block_merge wherever the block
    kernel carries the sort — pinned with local_kernel='block', which
    resolves to 'block' even off-TPU (interpret mode), since on CPU the
    plain default silently takes the 'sort' branch."""
    import dsort_tpu.ops.block_sort as bmod

    calls = []
    real = bmod.block_merge_runs

    def spy(runs, *a, **kw):
        calls.append(runs.shape)
        return real(runs, *a, **kw)

    monkeypatch.setattr(bmod, "block_merge_runs", spy)
    data = gen_uniform(30_000, seed=65)
    job = JobConfig(local_kernel="block", merge_kernel="auto")
    out = SampleSort(mesh8, job).sort(data)
    np.testing.assert_array_equal(out, np.sort(data))
    assert calls, "auto never dispatched to block_merge_runs"


def test_sample_sort_kv_block_merge_kernel(mesh8):
    from dsort_tpu.data.ingest import gen_terasort

    keys, payload = gen_terasort(8_000, seed=24)
    job = JobConfig(key_dtype=np.uint64, merge_kernel="block_merge")
    sk, sv = SampleSort(mesh8, job).sort_kv(keys, payload)
    np.testing.assert_array_equal(sk, np.sort(keys))
    assert sorted(zip(sk.tolist(), map(bytes, sv))) == sorted(
        zip(keys.tolist(), map(bytes, payload))
    )


def test_sample_sort_kv_bitonic_sentinel_keys(mesh8):
    # Real sentinel-valued keys must keep their payloads under all combines.
    sent = np.iinfo(np.int32).max
    rng = np.random.default_rng(29)
    keys = rng.integers(-100, 100, 3_000).astype(np.int32)
    keys[::97] = sent
    payload = rng.integers(0, 255, (3_000, 3)).astype(np.uint8)
    for mk in ("sort", "bitonic", "block_merge"):
        sk, sv = SampleSort(mesh8, JobConfig(merge_kernel=mk)).sort_kv(keys, payload)
        np.testing.assert_array_equal(sk, np.sort(keys))
        assert sorted(zip(sk.tolist(), map(bytes, sv))) == sorted(
            zip(keys.tolist(), map(bytes, payload))
        )


def _mesh_dp2(devices):
    from dsort_tpu.config import MeshConfig
    from dsort_tpu.parallel.mesh import make_mesh

    return make_mesh(MeshConfig(num_workers=4, dp=2), devices[:8])


def test_batch_sample_sort_many_jobs(devices):
    """Public MeshConfig.dp path: a batch of unequal jobs, one SPMD program."""
    from dsort_tpu.parallel.sample_sort import BatchSampleSort

    mesh = _mesh_dp2(devices)
    rng = np.random.default_rng(21)
    jobs = [
        rng.integers(-(10**6), 10**6, n).astype(np.int32)
        for n in (5000, 1, 0, 777, 4096, 9999, 12)
    ]
    outs = BatchSampleSort(mesh).sort(jobs)
    assert len(outs) == len(jobs)
    for j, o in zip(jobs, outs):
        np.testing.assert_array_equal(o, np.sort(j))


def test_batch_sample_sort_float_nan(devices):
    from dsort_tpu.parallel.sample_sort import BatchSampleSort

    mesh = _mesh_dp2(devices)
    rng = np.random.default_rng(22)
    jobs = []
    for n in (1000, 3000):
        x = rng.normal(size=n).astype(np.float32)
        x[::53] = np.nan
        jobs.append(x)
    outs = BatchSampleSort(mesh).sort(jobs)
    for j, o in zip(jobs, outs):
        expect = np.sort(j)
        k = len(j) - np.isnan(j).sum()
        np.testing.assert_array_equal(o[:k], expect[:k])
        assert np.isnan(o[k:]).all()


def test_batch_sample_sort_skew_retry(devices):
    from dsort_tpu.config import JobConfig
    from dsort_tpu.parallel.sample_sort import BatchSampleSort

    mesh = _mesh_dp2(devices)
    zipf = (gen_zipf(4000, a=1.2, seed=23) % 100_000).astype(np.int32)
    jobs = [np.full(4000, 7, np.int32), zipf]
    m = Metrics()
    outs = BatchSampleSort(
        mesh, JobConfig(oversample=4, capacity_factor=1.0)
    ).sort(jobs, metrics=m)
    for j, o in zip(jobs, outs):
        np.testing.assert_array_equal(o, np.sort(j))
    # the all-equal job MUST have overflowed one bucket and retried — pins
    # the batch retry loop as actually exercised
    assert m.counters.get("capacity_retries", 0) >= 1
    # mixed dtypes must be refused, not silently value-cast
    import pytest as _pytest

    with _pytest.raises(TypeError):
        BatchSampleSort(mesh).sort([jobs[0], jobs[1].astype(np.int64)])


def test_batch_size_bucketing_padded_volume(devices):
    """One big job must not make every dp slot pay its layout (VERDICT r1):
    the bucketed padded volume is an order of magnitude below the single-
    layout scheme's batch * w * max_cap."""
    from dsort_tpu.parallel.sample_sort import BatchSampleSort
    from dsort_tpu.utils.metrics import Metrics

    rng = np.random.default_rng(12)
    jobs = [rng.integers(-(2**31), 2**31 - 1, 32_768).astype(np.int32)] + [
        rng.integers(-(2**31), 2**31 - 1, 512).astype(np.int32)
        for _ in range(63)
    ]
    m = Metrics()
    outs = BatchSampleSort(_mesh_dp2(devices)).sort(jobs, metrics=m)
    for j, o in zip(jobs, outs):
        np.testing.assert_array_equal(o, np.sort(j))
    naive = 64 * 4 * 8192  # 64-job batch all padded to the 32K job's layout
    assert m.counters["padded_elems"] <= naive // 8


# ---- VERDICT r2 item 1: P=1 short-circuit, measured capacity, kernel merge ----


def test_batch_checkpoint_restores_completed_jobs(devices, tmp_path):
    """VERDICT r3 #7: a re-run of `BatchSampleSort.sort` with job_ids
    restores completed jobs from disk and re-packs the buckets over only
    the missing/stale ones."""
    from dsort_tpu.parallel.sample_sort import BatchSampleSort

    mesh = _mesh_dp2(devices)
    job = JobConfig(checkpoint_dir=str(tmp_path))
    rng = np.random.default_rng(71)
    jobs = [
        rng.integers(-(2**31), 2**31 - 1, n).astype(np.int32)
        for n in (5_000, 12_000, 900, 7_000, 3_000)
    ]
    ids = [f"file{i}" for i in range(len(jobs))]
    bss = BatchSampleSort(mesh, job)
    m1 = Metrics()
    outs1 = bss.sort(jobs, metrics=m1, job_ids=ids)
    for j, o in zip(jobs, outs1):
        np.testing.assert_array_equal(o, np.sort(j))
    assert "batch_jobs_restored" not in m1.counters

    # Re-run (the "killed and restarted" case, all jobs complete): every
    # job restores, no bucket is sorted at all.
    bss2 = BatchSampleSort(mesh, job)
    calls = []
    orig = bss2._run_bucket
    bss2._run_bucket = lambda ks, vs, cap, m: calls.append(cap) or orig(ks, vs, cap, m)
    m2 = Metrics()
    outs2 = bss2.sort(jobs, metrics=m2, job_ids=ids)
    for a, b in zip(outs1, outs2):
        np.testing.assert_array_equal(a, b)
    assert m2.counters["batch_jobs_restored"] == len(jobs)
    assert calls == []

    # One file's data changes: only that job re-sorts (fingerprint guard).
    jobs[2] = rng.integers(-(2**31), 2**31 - 1, 900).astype(np.int32)
    m3 = Metrics()
    outs3 = BatchSampleSort(mesh, job).sort(jobs, metrics=m3, job_ids=ids)
    np.testing.assert_array_equal(outs3[2], np.sort(jobs[2]))
    assert m3.counters["batch_jobs_restored"] == len(jobs) - 1


def test_batch_kv_many_jobs(devices):
    """Batched key+payload sorts: payloads follow their keys per job."""
    from dsort_tpu.parallel.sample_sort import BatchSampleSort

    mesh = _mesh_dp2(devices)
    rng = np.random.default_rng(73)
    pairs = []
    for n in (4_000, 1_500, 9_000, 2_500):
        keys = rng.integers(-1000, 1000, n).astype(np.int32)
        payload = rng.integers(0, 255, (n, 3)).astype(np.uint8)
        pairs.append((keys, payload))
    outs = BatchSampleSort(mesh).sort_kv(pairs)
    for (k, v), (sk, sv) in zip(pairs, outs):
        np.testing.assert_array_equal(sk, np.sort(k))
        assert sorted(zip(sk.tolist(), map(bytes, sv))) == sorted(
            zip(k.tolist(), map(bytes, v))
        )


def test_batch_kv_checkpoint_resume(devices, tmp_path):
    from dsort_tpu.parallel.sample_sort import BatchSampleSort

    mesh = _mesh_dp2(devices)
    job = JobConfig(checkpoint_dir=str(tmp_path))
    rng = np.random.default_rng(75)
    pairs = [
        (
            rng.integers(0, 10_000, n).astype(np.int32),
            rng.integers(0, 255, (n, 4)).astype(np.uint8),
        )
        for n in (3_000, 6_000, 1_200)
    ]
    ids = [f"kv{i}" for i in range(len(pairs))]
    outs1 = BatchSampleSort(mesh, job).sort_kv(pairs, job_ids=ids)
    m2 = Metrics()
    outs2 = BatchSampleSort(mesh, job).sort_kv(pairs, metrics=m2, job_ids=ids)
    assert m2.counters["batch_jobs_restored"] == len(pairs)
    for (k1, v1), (k2, v2) in zip(outs1, outs2):
        np.testing.assert_array_equal(k1, k2)
        np.testing.assert_array_equal(v1, v2)


def test_batch_float_jobs_checkpoint_resume(devices, tmp_path):
    """Float batches checkpoint under the mapped ordered-uint dtype and
    still restore correctly (NaNs included)."""
    from dsort_tpu.parallel.sample_sort import BatchSampleSort

    mesh = _mesh_dp2(devices)
    job = JobConfig(checkpoint_dir=str(tmp_path))
    rng = np.random.default_rng(79)
    jobs = []
    for n in (2_000, 5_000):
        a = (rng.standard_normal(n) * 1e6).astype(np.float32)
        a[:: max(n // 7, 1)] = np.nan
        jobs.append(a)
    ids = ["fa", "fb"]
    outs1 = BatchSampleSort(mesh, job).sort(jobs, job_ids=ids)
    m2 = Metrics()
    outs2 = BatchSampleSort(mesh, job).sort(jobs, metrics=m2, job_ids=ids)
    assert m2.counters["batch_jobs_restored"] == 2
    for j, o1, o2 in zip(jobs, outs1, outs2):
        np.testing.assert_array_equal(o1, o2)
        np.testing.assert_array_equal(o1, np.sort(j))  # NaNs last, np-style


def test_batch_kv_float_nan_payloads(devices):
    """Float-keyed batched records ride the ordered-uint mapping like every
    other driver (VERDICT r4 weak #5): payloads follow their keys, NaN-keyed
    records come back LAST with payloads attached, keys canonicalized."""
    from dsort_tpu.ops.float_order import float_to_ordered_uint
    from dsort_tpu.parallel.sample_sort import BatchSampleSort

    mesh = _mesh_dp2(devices)
    rng = np.random.default_rng(81)
    pairs = []
    for n in (2_000, 700):
        k = rng.normal(size=n).astype(np.float32)
        k[::37] = np.nan
        v = rng.integers(0, 255, (n, 3)).astype(np.uint8)
        pairs.append((k, v))
    outs = BatchSampleSort(mesh).sort_kv(pairs)
    for (k, v), (sk, sv) in zip(pairs, outs):
        valid = len(k) - int(np.isnan(k).sum())
        np.testing.assert_array_equal(sk[:valid], np.sort(k)[:valid])
        assert np.isnan(sk[valid:]).all()
        # Key-payload association, NaN-safe: compare multisets under the
        # order-preserving bijection (canonicalizes every NaN one way).
        ku, sku = float_to_ordered_uint(k), float_to_ordered_uint(sk)
        assert (np.diff(sku.astype(np.int64)) >= 0).all()
        assert sorted(zip(ku.tolist(), map(bytes, v))) == sorted(
            zip(sku.tolist(), map(bytes, sv))
        )


def test_batch_kv_mixed_payload_shapes_bucketed(devices):
    """Jobs with different payload widths land in different buckets but one
    call sorts them all."""
    from dsort_tpu.parallel.sample_sort import BatchSampleSort

    mesh = _mesh_dp2(devices)
    rng = np.random.default_rng(77)
    pairs = [
        (
            rng.integers(0, 100, 2_000).astype(np.int32),
            rng.integers(0, 255, (2_000, w)).astype(np.uint8),
        )
        for w in (2, 5, 2)
    ]
    outs = BatchSampleSort(mesh).sort_kv(pairs)
    for (k, v), (sk, sv) in zip(pairs, outs):
        np.testing.assert_array_equal(sk, np.sort(k))
        assert sv.shape == v.shape
        assert sorted(zip(sk.tolist(), map(bytes, sv))) == sorted(
            zip(k.tolist(), map(bytes, v))
        )


def test_p1_sorts_exactly_once():
    """On a single-device mesh the SPMD path must invoke exactly ONE local
    sort — no splitters, no all_to_all, no second (merge) sort."""
    import jax
    from jax.sharding import Mesh

    import dsort_tpu.parallel.sample_sort as ssm

    mesh1 = Mesh(np.array(jax.devices()[:1]), ("w",))
    calls = {"sort_padded": 0}
    real_sp = ssm.sort_padded
    real_sk = ssm.sort_keys

    def counting_sp(*a, **kw):
        calls["sort_padded"] += 1
        return real_sp(*a, **kw)

    def counting_sk(*a, **kw):
        raise AssertionError("merge-phase sort ran on a P=1 mesh")

    ssm.sort_padded = counting_sp
    ssm.sort_keys = counting_sk
    try:
        data = gen_uniform(30_000, seed=42)
        out = SampleSort(mesh1).sort(data)
    finally:
        ssm.sort_padded = real_sp
        ssm.sort_keys = real_sk
    np.testing.assert_array_equal(out, np.sort(data))
    assert calls["sort_padded"] == 1  # traced once: one sort in the program


def test_capacity_retry_sizes_from_measured_bucket(mesh8):
    """A skewed overflow converges in ONE measured-size retry, not a
    doubling ladder."""
    data = np.concatenate([
        np.full(30_000, 7, np.int32),        # 3/4 of keys in one bucket
        gen_uniform(10_000, seed=13),
    ])
    m = Metrics()
    out = SampleSort(mesh8, JobConfig(capacity_factor=1.0)).sort(data, metrics=m)
    np.testing.assert_array_equal(out, np.sort(data))
    assert m.counters.get("capacity_retries") == 1


def test_cap_from_observed_quantizes():
    from dsort_tpu.parallel.sample_sort import cap_from_observed

    n_local, p = 1 << 16, 8
    step = n_local // (8 * p)
    c = cap_from_observed(9_000, n_local, p)
    assert c >= int(9_000 * 1.05) and c % 8 == 0
    assert c % step == 0                      # quantized: bounded recompiles
    assert cap_from_observed(10**9, n_local, p) == n_local  # clamped
    assert cap_from_observed(0, 64, 2) >= 8


def test_merge_kernel_dispatch_is_job_kernel(mesh8, monkeypatch):
    """The post-shuffle 'sort' merge goes through sort_with_kernel with the
    JOB's local kernel — not hardcoded lax (VERDICT r2 item 1).  Patching
    ``ops.local_sort.sort_with_kernel`` observes both call sites: the local
    sort (via `sort_padded`) and `_merge_received`'s in-function import."""
    import dsort_tpu.ops.local_sort as lsm

    seen = []
    real = lsm.sort_with_kernel

    def spy(keys, kernel="auto"):
        seen.append(kernel)
        return real(keys, kernel)

    monkeypatch.setattr(lsm, "sort_with_kernel", spy)
    data = gen_uniform(20_000, seed=14)
    out = SampleSort(mesh8, JobConfig(local_kernel="bitonic")).sort(data)
    np.testing.assert_array_equal(out, np.sort(data))
    # local sorts AND the merge phase both dispatched with the job's kernel
    assert len(seen) >= 2 and all(k == "bitonic" for k in seen)


def test_kv_merge_block_pairs_path(monkeypatch):
    """Force the kv combine down the block_sort_pairs plane path (interpret
    mode on CPU) — payloads must follow their keys exactly."""
    import jax
    from jax.sharding import Mesh

    mesh2 = Mesh(np.array(jax.devices()[:2]), ("w",))
    rng = np.random.default_rng(15)
    n = 2_000
    keys = rng.integers(0, 50, n).astype(np.int32)  # duplicates: perm matters
    payload = rng.integers(0, 256, (n, 4)).astype(np.uint8)
    out_k, out_v = SampleSort(mesh2, JobConfig(local_kernel="block")).sort_kv(
        keys, payload
    )
    np.testing.assert_array_equal(out_k, np.sort(keys))
    # every record's payload still sits next to its key (multiset match per key)
    for v in np.unique(keys):
        got = out_v[out_k == v]
        want = payload[keys == v]
        got_set = {bytes(r) for r in got}
        want_set = {bytes(r) for r in want}
        assert got_set == want_set and len(got) == len(want)
