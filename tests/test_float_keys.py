"""NaN-safe float key sorting through every public driver.

The pad sentinel for float keys is ``inf``, but IEEE total order puts NaN
after inf — without the `ops.float_order` boundary bijection, real NaN keys
sort behind the pads and get trimmed away (silent data loss; reproduced
before the fix: 10 NaNs in -> 0 out, 10 leaked inf pads).  The reference
never hits this (int32 keys only, ``server.c:171-182``); supporting floats
is a capability extension, so these tests pin its contract: NaNs order last
like ``np.sort``, one (canonical) NaN comes out per NaN in, and every other
value round-trips bit-exactly.
"""

import numpy as np
import pytest

from dsort_tpu.ops.float_order import (
    float_to_ordered_uint,
    is_float_key_dtype,
    ordered_uint_dtype,
    ordered_uint_to_float,
)


def _tricky(dtype, n=4000, nan_every=97, seed=3):
    rng = np.random.default_rng(seed)
    x = (rng.normal(size=n) * 10.0 ** rng.integers(-30, 30, n)).astype(dtype)
    x[::nan_every] = np.nan
    x[1::601] = np.inf
    x[2::601] = -np.inf
    x[3::601] = 0.0
    x[4::601] = -0.0
    x[5::601] = np.finfo(dtype).tiny / 4  # subnormal
    return x


def _check_sorted_like_numpy(got, x):
    """Same length, NaNs last and same count, non-NaN part identical."""
    assert got.dtype == x.dtype and len(got) == len(x)
    expect = np.sort(x)  # numpy: NaNs at the end
    n_nan = np.isnan(x).sum()
    k = len(x) - n_nan
    np.testing.assert_array_equal(got[:k], expect[:k])
    assert np.isnan(got[k:]).all()


@pytest.mark.parametrize("dtype", [np.float16, np.float32, np.float64])
def test_bijection_roundtrip_and_order(dtype):
    x = _tricky(dtype)
    m = float_to_ordered_uint(x)
    assert m.dtype == ordered_uint_dtype(dtype)
    back = ordered_uint_to_float(m, dtype)
    nan = np.isnan(x)
    # non-NaN values round-trip bit-exactly (signed zeros keep their sign)
    np.testing.assert_array_equal(
        back[~nan].view(ordered_uint_dtype(dtype)),
        x[~nan].view(ordered_uint_dtype(dtype)),
    )
    assert np.isnan(back[nan]).all()
    # unsigned order of the image == numpy's sort order of the floats
    _check_sorted_like_numpy(ordered_uint_to_float(np.sort(m), dtype), x)


def test_is_float_key_dtype():
    assert is_float_key_dtype(np.float32) and is_float_key_dtype("float64")
    assert not is_float_key_dtype(np.int32)
    with pytest.raises(TypeError):
        float_to_ordered_uint(np.arange(3, dtype=np.int32))


@pytest.mark.parametrize("dtype", [np.float16, np.float32, np.float64])
def test_sample_sort_float_nan(mesh8, dtype):
    x = _tricky(dtype)
    from dsort_tpu.parallel.sample_sort import SampleSort

    _check_sorted_like_numpy(SampleSort(mesh8).sort(x), x)


def test_gather_merge_float_nan(mesh8):
    from dsort_tpu.models.pipelines import GatherMergeSort

    x = _tricky(np.float32)
    _check_sorted_like_numpy(GatherMergeSort(mesh8).sort(x), x)


def test_taskpool_scheduler_float_nan():
    from dsort_tpu.config import JobConfig
    from dsort_tpu.scheduler import DeviceExecutor, FaultInjector, Scheduler

    x = _tricky(np.float32)
    inj = FaultInjector()
    inj.kill(1)  # NaN handling must survive the reassignment path too
    got = Scheduler(DeviceExecutor(injector=inj), JobConfig()).run_job(x)
    _check_sorted_like_numpy(got, x)


def test_spmd_scheduler_float_nan(tmp_path):
    from dsort_tpu.config import JobConfig
    from dsort_tpu.scheduler.scheduler import SpmdScheduler

    x = _tricky(np.float32, n=2000)
    job = JobConfig(checkpoint_dir=str(tmp_path))
    got = SpmdScheduler(job=job).sort(x, job_id="floatjob")
    _check_sorted_like_numpy(got, x)
    # resume path: a second run restores the checkpointed (uint) local phase
    got2 = SpmdScheduler(job=job).sort(x, job_id="floatjob")
    _check_sorted_like_numpy(got2, x)


def test_external_sort_float_nan(tmp_path):
    from dsort_tpu.models.external_sort import ExternalSort

    x = _tricky(np.float32, n=5000)
    es = ExternalSort(run_elems=1024, spill_dir=str(tmp_path), job_id="f1")
    _check_sorted_like_numpy(es.sort(x), x)


def test_external_sort_float_binary_file(tmp_path):
    from dsort_tpu.models.external_sort import ExternalSort

    x = _tricky(np.float32, n=3000)
    in_path, out_path = str(tmp_path / "in.bin"), str(tmp_path / "out.bin")
    x.tofile(in_path)
    es = ExternalSort(run_elems=512, spill_dir=str(tmp_path / "spill"), job_id="f2")
    es.sort_binary_file(in_path, out_path, dtype=np.float32)
    _check_sorted_like_numpy(np.fromfile(out_path, dtype=np.float32), x)


def test_unmap_rejects_unmapped_floats():
    # Value-casting raw floats through the unmap would corrupt keys silently.
    with pytest.raises(TypeError):
        ordered_uint_to_float(np.array([1.0, 2.0], np.float32), np.float32)


def test_external_sort_rejects_premapping_checkpoints(tmp_path):
    """Spilled runs from a build without the uint mapping must not be trusted."""
    from dsort_tpu.checkpoint import ShardCheckpoint
    from dsort_tpu.models.external_sort import ExternalSort

    x = _tricky(np.float32, n=3000)
    es = ExternalSort(run_elems=1024, spill_dir=str(tmp_path), job_id="mig")
    _check_sorted_like_numpy(es.sort(x), x)  # writes a mapped-uint checkpoint

    # Forge the pre-mapping layout: float shards + manifest without
    # storage_dtype, same num_shards/dtype/total/run_elems/fingerprint.
    ckpt = ShardCheckpoint(str(tmp_path), "mig")
    m = ckpt.manifest()
    assert m["storage_dtype"] == "uint32"
    num_runs = m["num_shards"]
    for i in range(num_runs):
        lo = i * 1024
        ckpt.save(i, np.sort(x[lo : lo + 1024]))
    ckpt.write_manifest(
        num_runs,
        np.float32,
        m["total"],
        run_elems=m["run_elems"],
        fingerprint=m["fingerprint"],
    )
    # Resume must detect the foreign storage format, clear, and still be right.
    got = ExternalSort(run_elems=1024, spill_dir=str(tmp_path), job_id="mig").sort(x)
    _check_sorted_like_numpy(got, x)


def test_all_nan_input(mesh8):
    from dsort_tpu.parallel.sample_sort import SampleSort

    x = np.full(100, np.nan, np.float32)
    got = SampleSort(mesh8).sort(x)
    assert len(got) == 100 and np.isnan(got).all()


def test_sort_kv_float_keys_nan(mesh8):
    from dsort_tpu.parallel.sample_sort import SampleSort

    rng = np.random.default_rng(5)
    keys = rng.normal(size=500).astype(np.float32)
    keys[::50] = np.nan
    payload = np.arange(500, dtype=np.int64)
    sk, sv = SampleSort(mesh8).sort_kv(keys, payload)
    _check_sorted_like_numpy(sk, keys)
    # payloads of non-NaN keys follow their keys; NaN-key payloads survive
    order = np.argsort(keys, kind="stable")  # numpy also puts NaNs last
    nan_payloads = set(payload[np.isnan(keys)].tolist())
    k = (~np.isnan(keys)).sum()
    np.testing.assert_array_equal(sk[:k], keys[order][:k])
    assert set(sv[k:].tolist()) == nan_payloads


@pytest.mark.parametrize("dtype,udtype", [(np.float32, np.uint32), (np.float64, np.uint64)])
def test_bijection_fuzz_random_bit_patterns(dtype, udtype):
    """Every bit pattern is legal input: denormals, both NaN signs, all NaN
    payloads, infinities.  Sorting the mapped uints must equal np.sort on
    the non-NaN part with all NaNs (canonicalized) at the tail."""
    rng = np.random.default_rng(99)
    bits = rng.integers(
        0, np.iinfo(udtype).max, 20_000, dtype=udtype, endpoint=True
    )
    x = bits.view(dtype)
    got = ordered_uint_to_float(np.sort(float_to_ordered_uint(x)), dtype)
    _check_sorted_like_numpy(got, x)
