"""Ring exchange (`parallel.exchange`): bit-identical output, adaptive
headroom, merge-as-you-receive wiring, and the mid-ring fault contract.

The acceptance bar for the ring schedule is strict: on the same data and
config it must be *bit-identical* to the all_to_all path (both produce each
destination's sorted key-range multiset, and sorted arrays of equal
multisets are equal), ship measurably fewer bytes under skew (the padded
path pays worst-case headroom plus a full re-dispatch on overflow), and
inherit the SPMD fault contract unchanged (a device lost mid-ring re-forms
the mesh and re-runs on the survivors).
"""

import numpy as np
import pytest

import jax
from jax.sharding import Mesh

from dsort_tpu.config import ConfigError, JobConfig
from dsort_tpu.data.ingest import gen_terasort, gen_uniform, gen_zipf
from dsort_tpu.parallel.exchange import (
    alltoall_wire_bytes,
    ring_caps,
    ring_step_quantum,
    ring_wire_bytes,
)
from dsort_tpu.parallel.sample_sort import BatchSampleSort, SampleSort, cap_pair_policy
from dsort_tpu.utils.events import EventLog
from dsort_tpu.utils.metrics import Metrics


def _metered():
    return Metrics(journal=EventLog())


# ---- bit-identical vs the all_to_all path ---------------------------------


@pytest.mark.parametrize("n", [64, 5000, 100_003])
def test_ring_uniform_bit_identical(mesh8, n):
    ss = SampleSort(mesh8)
    rng = np.random.default_rng(11)
    data = rng.integers(-(10**6), 10**6, n).astype(np.int32)
    a = ss.sort(data)
    m = _metered()
    r = ss.sort(data, metrics=m, exchange="ring")
    np.testing.assert_array_equal(a, r)
    assert m.counters["exchange_ring_steps"] == 7
    assert m.counters.get("capacity_retries", 0) == 0


def test_ring_zipf_bit_identical_int64(mesh8):
    z = gen_zipf(1 << 17, a=1.3, seed=4)
    ss = SampleSort(mesh8, JobConfig(key_dtype=np.int64))
    a = ss.sort(z)
    r = ss.sort(z, exchange="ring")
    np.testing.assert_array_equal(a, r)


def test_ring_all_equal_keys(mesh8):
    # The degenerate skew: every key identical — one destination owns
    # everything, every step's cap is the whole shard.
    ss = SampleSort(mesh8)
    data = np.full(20_000, 7, np.int32)
    r = ss.sort(data, exchange="ring")
    np.testing.assert_array_equal(r, data)


def test_ring_sentinel_valued_keys(mesh8):
    # Real keys equal to the padding sentinel must survive the ring's
    # sentinel-padded runs exactly as they survive the padded buffer.
    ss = SampleSort(mesh8)
    rng = np.random.default_rng(3)
    data = rng.integers(-100, 100, 9000).astype(np.int32)
    data[:200] = np.iinfo(np.int32).max
    np.testing.assert_array_equal(
        ss.sort(data, exchange="ring"), np.sort(data)
    )


def test_ring_on_7_device_mesh():
    # Non-power-of-two rings (the post-re-form mesh shape): the ppermute
    # shifts and the merge tower's final fold must not assume pow2 P.
    mesh7 = Mesh(np.array(jax.devices()[:7]), ("w",))
    ss = SampleSort(mesh7)
    rng = np.random.default_rng(5)
    data = rng.integers(-(10**6), 10**6, 70_001).astype(np.int32)
    a = ss.sort(data)
    m = _metered()
    r = ss.sort(data, metrics=m, exchange="ring")
    np.testing.assert_array_equal(a, r)
    assert m.counters["exchange_ring_steps"] == 6


def test_ring_float_keys_nan(mesh8):
    # Floats (incl. NaN) ride the ring as order-preserving uints like every
    # other driver path.
    ss = SampleSort(mesh8)
    rng = np.random.default_rng(6)
    data = rng.normal(size=20_000).astype(np.float32)
    data[::97] = np.nan
    got = ss.sort(data, exchange="ring")
    expect = np.sort(data)  # numpy: NaNs last
    k = len(data) - np.isnan(data).sum()
    np.testing.assert_array_equal(got[:k], expect[:k])
    assert np.isnan(got[k:]).all()


def test_ring_kv_records(mesh8):
    # Keys bit-identical; records as a whole the same multiset in the same
    # key order (payload order among equal keys is unspecified on BOTH
    # paths — the local sorts are unstable).
    tk, tv = gen_terasort(30_000, seed=3)
    ss = SampleSort(mesh8, JobConfig(key_dtype=np.uint64, payload_bytes=tv.shape[1]))
    ka, va = ss.sort_kv(tk, tv)
    m = _metered()
    kr, vr = ss.sort_kv(tk, tv, metrics=m, exchange="ring")
    np.testing.assert_array_equal(ka, kr)
    assert m.counters["exchange_ring_steps"] == 7

    def records_sig(k, v):
        order = np.lexsort(tuple(v[:, i] for i in range(v.shape[1])) + (k,))
        return k[order].tobytes() + v[order].tobytes()

    assert records_sig(ka, va) == records_sig(kr, vr)


def test_ring_kv_duplicate_keys_keep_payloads(mesh8):
    ss = SampleSort(mesh8, JobConfig(payload_bytes=4))
    rng = np.random.default_rng(8)
    keys = rng.integers(0, 50, 6000).astype(np.int32)  # heavy duplicates
    vals = np.arange(6000, dtype=np.int32).reshape(-1, 1)
    ks, vs = ss.sort_kv(keys, vals, exchange="ring")
    np.testing.assert_array_equal(ks, np.sort(keys))
    # Every payload appears exactly once, attached to its own key.
    np.testing.assert_array_equal(np.sort(vs[:, 0]), np.arange(6000))
    np.testing.assert_array_equal(keys[vs[:, 0]], ks)


def test_ring_kv_secondary_falls_back(mesh8, caplog):
    # Two-level keys keep the one-shot combine: ring requests warn and use
    # the all_to_all exchange, output unchanged.
    from dsort_tpu.data.ingest import terasort_secondary

    tk, tv = gen_terasort(8000, seed=7)
    sec = terasort_secondary(tv)
    ss = SampleSort(mesh8, JobConfig(key_dtype=np.uint64, payload_bytes=tv.shape[1]))
    ka, va = ss.sort_kv(tk, tv, secondary=sec)
    with caplog.at_level("WARNING", logger="dsort.sample_sort"):
        kr, vr = ss.sort_kv(tk, tv, secondary=sec, exchange="ring")
    np.testing.assert_array_equal(ka, kr)
    np.testing.assert_array_equal(va, vr)


def test_ring_empty_and_single_worker():
    ss1 = SampleSort(Mesh(np.array(jax.devices()[:1]), ("w",)))
    data = np.random.default_rng(1).integers(0, 100, 999).astype(np.int32)
    # P=1 resolves to the all_to_all short-circuit — no ring program exists.
    np.testing.assert_array_equal(ss1.sort(data, exchange="ring"), np.sort(data))
    ss = SampleSort(Mesh(np.array(jax.devices()[:2]), ("w",)))
    out = ss.sort(np.empty(0, np.int32), exchange="ring")
    assert len(out) == 0


def test_ring_batch_bit_identical(devices):
    mesh = Mesh(np.array(devices[:8]).reshape(2, 4), ("dp", "w"))
    bs = BatchSampleSort(mesh, JobConfig())
    rng = np.random.default_rng(7)
    jobs = [
        rng.integers(0, 10**6, n).astype(np.int32)
        for n in (5000, 12_000, 801, 64)
    ]
    outs_a = bs.sort(jobs)
    m = _metered()
    outs_r = bs.sort(jobs, metrics=m, exchange="ring")
    for a, r in zip(outs_a, outs_r):
        np.testing.assert_array_equal(a, r)
    assert m.counters["exchange_ring_steps"] > 0


def test_ring_invalid_exchange_rejected(mesh8):
    ss = SampleSort(mesh8)
    with pytest.raises(ValueError, match="exchange"):
        ss.sort(np.arange(100, dtype=np.int32), exchange="mesh")
    with pytest.raises(ConfigError, match="exchange"):
        JobConfig(exchange="bogus")


def test_config_exchange_from_mapping():
    from dsort_tpu.config import SortConfig

    cfg = SortConfig.from_mapping({"EXCHANGE": "ring"})
    assert cfg.job.exchange == "ring"


# ---- adaptive headroom ----------------------------------------------------


def test_ring_caps_quantized_and_covering():
    p, n_local = 8, 1 << 15
    rng = np.random.default_rng(0)
    hist = rng.integers(0, n_local // p, (p, p)).astype(np.int64)
    caps = ring_caps(hist, n_local, p)
    assert len(caps) == p
    q = ring_step_quantum(n_local, p)
    for k in range(p):
        step_max = max(int(hist[src, (src + k) % p]) for src in range(p))
        assert caps[k] >= step_max          # covers the measured buckets
        assert caps[k] % 8 == 0             # vreg/DMA alignment rule
        assert caps[k] % q == 0 or caps[k] == -(-n_local // 8) * 8
        assert caps[k] - step_max < q       # tight to one quantum


def test_ring_caps_skew_isolates_hot_steps():
    # One hot (src, dst) pair inflates ONLY the step that carries it; the
    # other steps stay at the uniform rung — the per-step resize.
    p, n_local = 8, 1 << 15
    hist = np.full((p, p), 100, np.int64)
    hist[2, 5] = 3000  # shift k = 3
    caps = ring_caps(hist, n_local, p)
    assert caps[3] >= 3000
    assert all(c < 3000 for i, c in enumerate(caps) if i != 3)


def test_ring_caps_bounded_rungs():
    # Quantization bounds the distinct programs a drifting workload compiles.
    p, n_local = 8, 1 << 15
    rungs = set()
    for m in range(0, n_local // p, 37):
        rungs.add(ring_caps(np.full((p, p), m, np.int64), n_local, p))
    assert len(rungs) <= 12


def test_wire_bytes_model():
    caps = (16, 24, 8, 8)
    assert ring_wire_bytes(caps, 4, 4) == (24 + 8 + 8) * 4 * 4
    assert alltoall_wire_bytes(32, 4, 4) == 3 * 32 * 4 * 4


def test_ring_bytes_saved_uniform(mesh8):
    # Uniform data: the ring's measured caps undercut the 1.3x policy
    # headroom; the saved counter records the difference.
    ss = SampleSort(mesh8)
    data = gen_uniform(1 << 17, seed=1)
    m = _metered()
    ss.sort(data, metrics=m, exchange="ring")
    assert m.counters["exchange_bytes_saved"] > 0
    policy = cap_pair_policy(-(-(1 << 17) // 8), 1.3, 8)
    assert m.counters["exchange_bytes_on_wire"] < alltoall_wire_bytes(
        policy, 4, 8
    )


# ---- the zipf capacity regression (satellite) -----------------------------


def test_zipf_1m_padded_retries_ring_does_not(mesh8):
    """The drill the adaptive headroom exists for: on a zipf-skewed 1M
    input the padded all_to_all overflows its policy-sized buffer and
    re-dispatches the whole job (``capacity_retry`` in the journal), while
    the ring path completes with ZERO retries — its per-step buffers were
    sized from the measured histogram, surfacing as ``exchange_resize``
    events instead.  Outputs stay bit-identical, and the ring ships
    measurably fewer bytes than the padded path's two shipments."""
    z = gen_zipf(1 << 20, a=1.3, seed=4)
    ss = SampleSort(mesh8, JobConfig(key_dtype=np.int64))

    m_pad = _metered()
    out_pad = ss.sort(z, metrics=m_pad)
    assert m_pad.counters["capacity_retries"] >= 1
    types_pad = m_pad.journal.types()
    assert "capacity_retry" in types_pad
    # The retry is a whole-job re-dispatch: a second spmd_sort phase opens
    # after the capacity_retry event.
    idx = types_pad.index("capacity_retry")
    assert "phase_start" in types_pad[idx:]

    m_ring = _metered()
    out_ring = ss.sort(z, metrics=m_ring, exchange="ring")
    np.testing.assert_array_equal(out_pad, out_ring)
    assert m_ring.counters.get("capacity_retries", 0) == 0
    types_ring = m_ring.journal.types()
    assert "capacity_retry" not in types_ring
    # The skew that forced the padded retry shows up as per-step resizes.
    assert "exchange_resize" in types_ring
    assert types_ring.count("exchange_step") == 7
    # Measurably fewer wire bytes than the padded path actually shipped
    # (policy-sized attempt + resized re-dispatch).
    assert (
        m_ring.counters["exchange_bytes_on_wire"]
        < m_pad.counters["exchange_bytes_on_wire"]
    )
    assert m_ring.counters["exchange_bytes_saved"] > 0

    # ISSUE 9: every ring plan journals its skew signal (reduced from the
    # histogram it already measured).  The zipf-1M run's max/mean bucket
    # ratio must exceed a same-size uniform run's by a real margin — the
    # analyzer's skew verdict rests on exactly this separation.
    def skew_ratio(journal):
        reports = [e for e in journal.events() if e.type == "skew_report"]
        assert reports, "every ring plan must journal a skew_report"
        return reports[-1].fields["max_mean_ratio"]

    m_uni = _metered()
    ss.sort(
        gen_uniform(1 << 20, dtype=np.int64, seed=0),
        metrics=m_uni, exchange="ring",
    )
    zipf_skew, uni_skew = skew_ratio(m_ring.journal), skew_ratio(m_uni.journal)
    assert zipf_skew > 1.5 * uni_skew, (zipf_skew, uni_skew)


# ---- fault contract -------------------------------------------------------


def test_mid_ring_device_loss_reforms_and_matches():
    """A device lost mid-ring (between the plan and exchange dispatches)
    invalidates the exchange; the mesh re-forms over the survivors and the
    job re-runs there — same contract as the all_to_all path, verified
    down to a sorted, checksum-matching output."""
    from dsort_tpu.models.validate import _multiset
    from dsort_tpu.scheduler import FaultInjector, SpmdScheduler

    inj = FaultInjector()
    sched = SpmdScheduler(
        job=JobConfig(settle_delay_s=0.01, exchange="ring"), injector=inj
    )
    z = gen_zipf(1 << 17, a=1.3, seed=5)
    np.testing.assert_array_equal(sched.sort(z), np.sort(z))  # warm

    inj.fail_once(3, "ring")
    m = _metered()
    out = sched.sort(z, metrics=m)
    assert (np.diff(out) >= 0).all() and len(out) == len(z)
    assert _multiset(out, len(out), out.dtype.itemsize) == _multiset(
        z, len(z), z.dtype.itemsize
    )
    assert m.counters["mesh_reforms"] == 1
    types = m.journal.types()
    # Fault timeline: attempt -> death -> re-form -> fresh ring plan.
    assert types.index("worker_dead") < types.index("mesh_reform")
    assert "exchange_step" in types[types.index("mesh_reform"):]
    assert types[-1] == "job_done"
    # The re-formed 7-device ring ran 6 transfer steps after the first
    # attempt's 7.
    assert m.counters["exchange_ring_steps"] == 13


def test_ring_keep_on_device_validates(mesh8):
    from dsort_tpu.scheduler import SpmdScheduler

    sched = SpmdScheduler(job=JobConfig(exchange="ring"))
    data = gen_uniform(1 << 17, seed=9)
    h = sched.sort(data, keep_on_device=True)
    rep = h.validate_on_device()
    assert rep.sorted_ok and rep.records == len(data)
    np.testing.assert_array_equal(h.to_host(), np.sort(data))


def test_ring_via_scheduler_checkpoint_path(tmp_path):
    # The checkpointed shuffle path (sort_ranges) honors the ring override:
    # ranges persist and a re-run fully restores, exchange schedule intact.
    from dsort_tpu.scheduler import SpmdScheduler

    job = JobConfig(checkpoint_dir=str(tmp_path), exchange="ring")
    sched = SpmdScheduler(job=job)
    data = gen_uniform(1 << 16, seed=2)
    m = _metered()
    out = sched.sort(data, metrics=m, job_id="ringckpt")
    np.testing.assert_array_equal(out, np.sort(data))
    assert m.counters["exchange_ring_steps"] == 7
    m2 = Metrics()
    out2 = sched.sort(data, metrics=m2, job_id="ringckpt")
    np.testing.assert_array_equal(out2, np.sort(data))
    assert m2.counters.get("shuffle_phase_restores") == 1
    # Fully restored: no exchange ran at all.
    assert "exchange_ring_steps" not in m2.counters


# ---- the `make bench-exchange-smoke` tier-1 gate --------------------------


def test_cli_bench_exchange_ab(tmp_path, capsys):
    """The bench-exchange-smoke path (`dsort bench --exchange-ab`): one
    ring-vs-alltoall row per workload, bit-identical asserted, wire bytes
    measurably below the padded path on the skewed case, exchange events
    journaled, exit 0."""
    import json

    from dsort_tpu import cli

    journal = tmp_path / "exchange.jsonl"
    rc = cli.main([
        "bench", "--exchange-ab", "--n", "100000", "--reps", "1",
        "--journal", str(journal),
    ])
    assert rc == 0
    rows = [
        json.loads(ln) for ln in capsys.readouterr().out.splitlines()
        if ln.startswith("{")
    ]
    by_metric = {r["metric"]: r for r in rows}
    uni = by_metric["exchange_ring_vs_alltoall_uniform_int32_100000"]
    zipf = by_metric["exchange_ring_vs_alltoall_zipf_int64_100000"]
    kv = by_metric["exchange_ring_vs_alltoall_kv_65536_records"]
    assert kv["unit"] == "rec/sec"
    for row in (uni, zipf, kv):
        assert row["bit_identical"] is True
        assert row["value"] > 0 and row["alltoall_keys_per_sec"] > 0
        assert row["bytes_on_wire"] > 0
        assert row["capacity_retries_ring"] == 0
    # The skewed workload is where the adaptive headroom pays: fewer wire
    # bytes than the padded path actually shipped.
    assert zipf["bytes_on_wire"] < zipf["bytes_on_wire_alltoall"]
    types = [r["type"] for r in EventLog.read_jsonl(str(journal))]
    assert "exchange_step" in types


def test_cli_run_with_ring_exchange(tmp_path):
    """`dsort run --exchange ring` sorts a file through the ring schedule."""
    from dsort_tpu import cli

    rng = np.random.default_rng(23)
    inp = tmp_path / "in.txt"
    inp.write_text("\n".join(str(x) for x in rng.integers(0, 10**6, 4000)))
    out = tmp_path / "out.txt"
    journal = tmp_path / "run.jsonl"
    rc = cli.main([
        "run", str(inp), "-o", str(out), "--exchange", "ring",
        "--journal", str(journal),
    ])
    assert rc == 0
    got = np.loadtxt(out, dtype=np.int64)
    expect = np.sort(np.loadtxt(inp, dtype=np.int64))
    np.testing.assert_array_equal(got, expect)


# ---- the eager merge tower (the TPU-side merge-as-you-receive path) -------
#
# On the CPU mesh `merge_kernel="auto"` resolves to the flat re-sort, which
# the ring defers to one end-of-ring combine (folding eagerly under a flat
# re-sort would multiply merge work by log P — see `parallel.exchange`).
# Forcing the run-merge kernels exercises the eager tower itself: per-step
# folds, the unequal-length final fold, and the kv (key, tag) folds.


def test_ring_eager_tower_bitonic(mesh8):
    ss = SampleSort(mesh8, JobConfig(merge_kernel="bitonic"))
    data = gen_uniform(30_000, seed=61)
    a = ss.sort(data)
    r = ss.sort(data, exchange="ring")
    np.testing.assert_array_equal(a, r)


def test_ring_eager_tower_bitonic_7_devices():
    # Non-pow2 P: the tower's final fold merges leftover unequal ranks.
    from dsort_tpu.parallel.mesh import local_device_mesh

    ss = SampleSort(local_device_mesh(7), JobConfig(merge_kernel="bitonic"))
    data = gen_uniform(10_000, seed=62)
    np.testing.assert_array_equal(
        ss.sort(data, exchange="ring"), np.sort(data)
    )


@pytest.mark.slow  # interpret-mode block merges: one per tower fold on CPU
def test_ring_eager_tower_block_merge(mesh8):
    ss = SampleSort(mesh8, JobConfig(merge_kernel="block_merge"))
    data = gen_uniform(10_000, seed=63)
    np.testing.assert_array_equal(
        ss.sort(data, exchange="ring"), np.sort(data)
    )


@pytest.mark.slow  # interpret-mode block kv merges per fold on CPU
def test_ring_eager_tower_block_merge_kv(mesh8):
    job = JobConfig(key_dtype=np.uint64, merge_kernel="block_merge",
                    payload_bytes=92)
    ss = SampleSort(mesh8, job)
    keys, payload = gen_terasort(4_000, seed=24)
    sk, sv = ss.sort_kv(keys, payload, exchange="ring")
    np.testing.assert_array_equal(sk, np.sort(keys))
    assert sorted(zip(sk.tolist(), map(bytes, sv))) == sorted(
        zip(keys.tolist(), map(bytes, payload))
    )


def test_ring_kv_sentinel_keys(mesh8):
    # Real keys equal to the padding sentinel keep their payloads through
    # the ring's tagged runs (the `_merge_received_kv` tiebreak invariant).
    sent = np.iinfo(np.int32).max
    rng = np.random.default_rng(12)
    keys = rng.integers(0, 1000, 5000).astype(np.int32)
    keys[:300] = sent
    vals = np.arange(5000, dtype=np.int32).reshape(-1, 1)
    ss = SampleSort(mesh8, JobConfig(payload_bytes=4))
    ks, vs = ss.sort_kv(keys, vals, exchange="ring")
    np.testing.assert_array_equal(ks, np.sort(keys))
    np.testing.assert_array_equal(np.sort(vs[:, 0]), np.arange(5000))
    np.testing.assert_array_equal(keys[vs[:, 0]], ks)


@pytest.mark.slow  # interpret-mode block kv merges per fold on CPU
def test_ring_eager_tower_block_merge_kv_sentinel_keys(mesh8):
    # The block-path tower fold must not let block_merge_runs_kv's internal
    # (local-scale) pad ranks displace real sentinel-keyed records whose
    # GLOBAL tags are larger — the pre-pad in `_merge2_kv` exists for this.
    sent = np.iinfo(np.int32).max
    rng = np.random.default_rng(13)
    keys = rng.integers(0, 1000, 4000).astype(np.int32)
    keys[:250] = sent
    vals = np.arange(4000, dtype=np.int32).reshape(-1, 1)
    ss = SampleSort(mesh8, JobConfig(merge_kernel="block_merge", payload_bytes=4))
    ks, vs = ss.sort_kv(keys, vals, exchange="ring")
    np.testing.assert_array_equal(ks, np.sort(keys))
    np.testing.assert_array_equal(np.sort(vs[:, 0]), np.arange(4000))
    np.testing.assert_array_equal(keys[vs[:, 0]], ks)


def test_exchange_resize_not_faked_by_quantization():
    # Rounding a step cap up to the quantization rung must NOT fire
    # exchange_resize: the event means "the padded path would have
    # overflowed here", so it keys on the MEASURED max, not the cap.
    from dsort_tpu.parallel.exchange import note_ring_plan, ring_caps

    p, n_local = 8, 1024  # policy cap 168, quantum 16
    hist = np.full((p, p), 161, np.int64)  # <=168 measured, quantizes to 176
    caps = ring_caps(hist, n_local, p)
    assert max(caps) > 168  # quantization DID round past the policy cap
    m = _metered()
    note_ring_plan(m, caps, hist, n_local, p, 4, 1.3)
    assert "exchange_resize" not in m.journal.types()
    hist[2, 5] = 500  # a genuinely overflowing bucket (shift k=3)
    m2 = _metered()
    note_ring_plan(m2, ring_caps(hist, n_local, p), hist, n_local, p, 4, 1.3)
    resizes = [e for e in m2.journal.events() if e.type == "exchange_resize"]
    assert [e.fields["step"] for e in resizes] == [3]
    assert resizes[0].fields["observed"] == 500
