"""LSD radix kernel tests: oracle equivalence, stability, dtypes, kv.

Oracle strategy per SURVEY.md §4: the reference ships only a golden
input/output pair; here every sort is checked against the numpy oracle.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from dsort_tpu.ops.radix import radix_sort, radix_sort_kv

SIZES = [0, 1, 2, 3, 7, 128, 1000, 8192, 8193, 20000]


@pytest.mark.parametrize("n", SIZES)
def test_radix_int32_matches_oracle(n):
    rng = np.random.default_rng(n)
    x = rng.integers(-(2**31), 2**31 - 1, n, dtype=np.int64).astype(np.int32)
    out = np.asarray(radix_sort(jnp.asarray(x)))
    np.testing.assert_array_equal(out, np.sort(x))


@pytest.mark.parametrize(
    "dtype", [np.int32, np.uint32, np.int64, np.uint64, np.int16, np.uint8]
)
def test_radix_integer_dtypes(dtype):
    rng = np.random.default_rng(0)
    info = np.iinfo(dtype)
    x = rng.integers(info.min, info.max, 4097, dtype=dtype, endpoint=True)
    out = np.asarray(radix_sort(jnp.asarray(x)))
    np.testing.assert_array_equal(out, np.sort(x))


def test_radix_float32():
    rng = np.random.default_rng(1)
    x = rng.standard_normal(5000).astype(np.float32) * 1e6
    x[:10] = [0.0, -0.0, np.inf, -np.inf, 1.5, -1.5, 3e38, -3e38, 1e-38, -1e-38]
    out = np.asarray(radix_sort(jnp.asarray(x)))
    np.testing.assert_array_equal(out, np.sort(x))


def test_radix_extremes_and_duplicates():
    x = np.array(
        [0, -1, 1, 2**31 - 1, -(2**31), 5, 5, 5, -1, 0], dtype=np.int32
    )
    out = np.asarray(radix_sort(jnp.asarray(x)))
    np.testing.assert_array_equal(out, np.sort(x))
    allsame = np.full(1000, 42, dtype=np.int32)
    np.testing.assert_array_equal(np.asarray(radix_sort(jnp.asarray(allsame))), allsame)


@pytest.mark.parametrize("bits", [1, 4, 8, 11])
def test_radix_bits_per_pass(bits):
    rng = np.random.default_rng(2)
    x = rng.integers(-(2**31), 2**31 - 1, 3000, dtype=np.int64).astype(np.int32)
    out = np.asarray(radix_sort(jnp.asarray(x), bits_per_pass=bits))
    np.testing.assert_array_equal(out, np.sort(x))


def test_radix_kv_follows_keys():
    rng = np.random.default_rng(3)
    n = 4099
    keys = rng.integers(-1000, 1000, n).astype(np.int32)
    payload = rng.integers(0, 256, (n, 10)).astype(np.uint8)
    out_k, out_v = radix_sort_kv(jnp.asarray(keys), jnp.asarray(payload))
    out_k, out_v = np.asarray(out_k), np.asarray(out_v)
    perm = np.argsort(keys, kind="stable")
    np.testing.assert_array_equal(out_k, keys[perm])
    np.testing.assert_array_equal(out_v, payload[perm])


def test_radix_kv_is_stable():
    # Equal keys keep input order — the property that makes sentinel-padded
    # buffers trim exactly (no reserved key values, unlike server.c:405-406).
    keys = np.array([7, 7, 7, 3, 3, 7], dtype=np.int32)
    payload = np.arange(6, dtype=np.int32)[:, None]
    out_k, out_v = radix_sort_kv(jnp.asarray(keys), jnp.asarray(payload))
    np.testing.assert_array_equal(np.asarray(out_k), [3, 3, 7, 7, 7, 7])
    np.testing.assert_array_equal(np.asarray(out_v)[:, 0], [3, 4, 0, 1, 2, 5])


def test_radix_as_local_kernel():
    from dsort_tpu.ops.local_sort import sort_padded, sort_with_kernel

    rng = np.random.default_rng(4)
    x = rng.integers(-(2**31), 2**31 - 1, 2048, dtype=np.int64).astype(np.int32)
    out = np.asarray(sort_with_kernel(jnp.asarray(x), "radix"))
    np.testing.assert_array_equal(out, np.sort(x))
    # Padded-buffer form used inside the SPMD program.
    buf = np.full(4096, 123, dtype=np.int32)
    buf[:2048] = x
    sorted_buf, _ = sort_padded(jnp.asarray(buf), 2048, "radix")
    np.testing.assert_array_equal(np.asarray(sorted_buf)[:2048], np.sort(x))


def test_radix_in_sample_sort(mesh8):
    from dsort_tpu.config import JobConfig
    from dsort_tpu.parallel.sample_sort import SampleSort

    rng = np.random.default_rng(5)
    data = rng.integers(-(2**31), 2**31 - 1, 40_000, dtype=np.int64).astype(np.int32)
    s = SampleSort(mesh8, JobConfig(local_kernel="radix"))
    np.testing.assert_array_equal(s.sort(data), np.sort(data))
