"""Spec plane (ISSUE 17, ARCHITECTURE §16): trace contracts, the
explicit-state model checker, frame-decoder fuzz, and the DS10xx/DS11xx
cross-checks.

The load-bearing properties pinned here:
  - the contract engine's grammar compiles with postfix operators bound
    to whole names (the `job_start?` regression), scopes traces, and
    names the violated contract on a tampered journal;
  - the model checker explores >= 10,000 distinct states at the smoke
    bound with ZERO violations on the real protocol, and BOTH seeded
    PR-12 mutations are caught, with committed fixtures that replay
    deterministically (the checker is not green-by-construction);
  - every seeded byte mutation of every FRAME_TYPES frame fails TYPED
    (`ProtocolError`) — never a hang, never an allocation past the
    header bound; failing seeds persist as fixtures next to the
    minimized schedules;
  - a seeded spec<->handler drift (one deleted handler arm) is caught
    statically, and the lint cache key tracks the spec sources.
"""

import json
import os
import random
import shutil
import struct

import numpy as np
import pytest

from dsort_tpu.analysis.checkers import all_checkers
from dsort_tpu.analysis.core import LintConfig, load_config
from dsort_tpu.analysis.engine import ResultCache, lint_paths
from dsort_tpu.analysis.spec import (
    CONTRACT_EXEMPT,
    PROTOCOL_SPEC,
    TRACE_CONTRACTS,
    assert_conformant,
    conformance_report,
    format_conformance,
)
from dsort_tpu.analysis.spec.contracts import (
    ContractError,
    compile_contract,
    contract_names,
)
from dsort_tpu.analysis.spec.model import (
    SEAMS,
    ModelConfig,
    check_model,
    load_fixture,
    replay_schedule,
)
from dsort_tpu.fleet import proto
from dsort_tpu.utils.events import EVENT_TYPES, EventLog

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "data", "spec")


# -- trace contracts: the engine ---------------------------------------------


def test_contract_registry_resolves_against_event_types():
    """Every name a contract mentions (steps, when, exempt) is a
    registered event type, and no name is both covered and exempt —
    the same both-ways discipline DS1102 enforces statically."""
    covered = set()
    for name, contract in TRACE_CONTRACTS.items():
        alphabet = contract_names(contract)
        covered |= alphabet
        for n in alphabet | set(contract.get("when", ())):
            assert n in EVENT_TYPES, f"{name} mentions unregistered {n!r}"
    for n in CONTRACT_EXEMPT:
        assert n in EVENT_TYPES, f"exempt name {n!r} unregistered"
    assert not covered & set(CONTRACT_EXEMPT)


def test_compile_postfix_binds_to_whole_name():
    """`b?` must make the NAME optional, not the separator — the
    regression behind the first real-journal violation this PR hit."""
    pat = compile_contract({"steps": ("alpha beta?",)})
    assert pat.fullmatch("alpha,")
    assert pat.fullmatch("alpha,beta,")
    assert not pat.fullmatch("beta,")
    pat = compile_contract({"steps": ("( alpha | beta )+ gamma*",)})
    assert pat.fullmatch("beta,alpha,gamma,gamma,")
    assert not pat.fullmatch("gamma,")


def test_compile_rejects_garbage():
    with pytest.raises(ContractError):
        compile_contract({"steps": ("alpha [beta]",)})
    with pytest.raises(ContractError):
        compile_contract({"steps": ("( alpha",)})  # unbalanced


def _lifecycle(log, job, evict=False, fail=False):
    log.emit("job_admitted", job=job, tenant="t")
    log.emit("job_dequeued", job=job, tenant="t")
    log.emit("attempt_start", job=job, attempt=1)
    if evict:
        log.emit("job_evicted", job=job)
        log.emit("job_readmitted", job=job)
        log.emit("job_dequeued", job=job, tenant="t")
        log.emit("attempt_start", job=job, attempt=2)
    if fail:
        log.emit("job_failed", job=job)
    else:
        log.emit("job_done", job=job)
        log.emit("result_fetch", job=job)


def test_conformance_scopes_interleaved_jobs():
    """Two jobs interleaved in one journal are split into per-job traces;
    each conforms on its own even though the merged order would not."""
    log = EventLog()
    log.emit("job_admitted", job=1, tenant="a")
    log.emit("job_admitted", job=2, tenant="b")
    log.emit("job_dequeued", job=2, tenant="b")
    log.emit("job_dequeued", job=1, tenant="a")
    log.emit("job_done", job=2)
    log.emit("job_done", job=1)
    report = assert_conformant(log)
    assert report["contracts"]["job_lifecycle"]["checked"] == 2


def test_conformance_when_gates_agent_side_journals():
    """A journal that never admits (an agent-side trace) is not held to
    the admission prefix: zero traces checked, still ok."""
    log = EventLog()
    log.emit("job_done", job=1)
    report = conformance_report([e.to_dict() for e in log.events()])
    assert report["ok"]
    assert report["contracts"]["job_lifecycle"]["checked"] == 0


def test_violation_names_contract_and_shows_trace():
    log = EventLog()
    _lifecycle(log, job=1)
    log.emit("job_done", job=1)  # double finish: illegal second terminal
    report = conformance_report(log)
    assert not report["ok"]
    v = report["violations"][0]
    assert v["contract"] == "job_lifecycle"
    assert v["scope"]["job"] == 1
    text = format_conformance(report)
    assert "VIOLATED job_lifecycle" in text
    with pytest.raises(AssertionError, match="job_lifecycle"):
        assert_conformant(log)


def test_tampered_real_drill_journal_names_contract(devices, tmp_path):
    """Satellite: a REAL eviction-drill journal replays conformant; the
    same journal with one `job_dequeued` deleted is flagged, naming the
    violated contract."""
    from dsort_tpu.config import JobConfig
    from dsort_tpu.scheduler import FaultInjector
    from dsort_tpu.serve import SortService

    inj = FaultInjector()
    journal = EventLog()
    svc = SortService(job=JobConfig(settle_delay_s=0.01,
                                    flight_recorder_dir=str(tmp_path)),
                      injector=inj, journal=journal, start=False)
    inj.fail_once(0, "slice")
    rng = np.random.default_rng(17)
    d = rng.integers(0, 1 << 30, 5000, dtype=np.int32)
    v, t = svc.submit(d, tenant="acme")
    assert v.admitted
    svc.start()
    np.testing.assert_array_equal(t.result(timeout=300), np.sort(d))
    svc.shutdown(drain=True)
    records = [e.to_dict() for e in journal.events()]
    assert_conformant(records)  # the real artifact is conformant
    assert any(r["type"] == "job_evicted" for r in records)
    # Tamper: drop the FIRST dequeue — the trace now shows an attempt
    # that was never dequeued.
    cut = next(i for i, r in enumerate(records)
               if r["type"] == "job_dequeued")
    tampered = records[:cut] + records[cut + 1:]
    report = conformance_report(tampered)
    assert not report["ok"]
    assert report["violations"][0]["contract"] == "job_lifecycle"


def test_analyzer_conformance_verdict_key():
    """`obs.analyze` carries the conformance report as a first-class
    verdict key, None on an empty journal."""
    from dsort_tpu.obs.analyze import VERDICT_KEYS, analyze_records

    assert "conformance" in VERDICT_KEYS
    log = EventLog()
    _lifecycle(log, job=1)
    verdict = analyze_records([e.to_dict() for e in log.events()])
    assert verdict["conformance"]["ok"] is True
    assert analyze_records([])["conformance"] is None


def test_cli_report_conform_exit_codes(tmp_path, capsys):
    """`dsort report --conform` exits 0 on a conformant journal, 1 on a
    tampered one, and names the violated contract."""
    from dsort_tpu import cli

    log = EventLog()
    _lifecycle(log, job=1, evict=True)
    good = tmp_path / "good.jsonl"
    log.write_jsonl(str(good))
    assert cli.main(["report", str(good), "--conform"]) == 0
    assert "OK" in capsys.readouterr().out
    bad = tmp_path / "bad.jsonl"
    records = [json.loads(x) for x in good.read_text().splitlines()]
    bad.write_text("\n".join(
        json.dumps(r) for r in records if r["type"] != "job_dequeued"
    ) + "\n")
    assert cli.main(["report", str(bad), "--conform"]) == 1
    assert "job_lifecycle" in capsys.readouterr().out


# -- the model checker -------------------------------------------------------


def test_model_smoke_bound_is_clean():
    """THE acceptance gate: >= 10,000 distinct states at the smoke bound,
    zero invariant violations on the real (unseamed) protocol."""
    res = check_model(ModelConfig(), seams=(), max_states=12_000)
    assert res.ok, res.violation
    assert res.states >= 10_000


def test_model_small_bound_exhausts_clean():
    """A tiny configuration (1 agent, 1 job, no failures) exhausts its
    whole state space — truncation-free — with no violation."""
    cfg = ModelConfig(n_agents=1, n_jobs=1, max_duplications=0,
                      max_deaths=0, max_reattaches=0, max_crashes=0,
                      max_requeues=1)
    res = check_model(cfg, seams=(), max_states=100_000)
    assert res.ok and not res.truncated
    assert res.states > 50


@pytest.mark.parametrize("seam", SEAMS)
def test_seeded_mutation_is_caught(seam):
    """Mutation self-test: each re-introduced PR-12 bug (ack-before-
    persist ordering, non-atomic duplicate-jid reservation) must yield a
    violating schedule, and the minimized schedule must replay to the
    SAME invariant deterministically."""
    res = check_model(ModelConfig(), seams=(seam,), max_states=20_000)
    assert not res.ok, f"seam {seam} not caught"
    v = res.violation
    assert v.schedule, "violation must carry a replayable schedule"
    replayed = replay_schedule(v.schedule, ModelConfig(), (seam,))
    assert replayed is not None and replayed.invariant == v.invariant
    # Deterministic: a second replay reproduces bit-for-bit.
    again = replay_schedule(v.schedule, ModelConfig(), (seam,))
    assert again.invariant == replayed.invariant
    assert again.detail == replayed.detail


@pytest.mark.parametrize("seam", SEAMS)
def test_committed_fixture_replays(seam):
    """The committed minimized fixtures reproduce their recorded
    invariant — the schedule-fixture replay contract of §16."""
    path = os.path.join(FIXTURES, f"{seam}.json")
    with open(path, encoding="utf-8") as f:
        recorded = json.load(f)
    schedule, cfg, seams = load_fixture(path)
    assert seams == (seam,)
    v = replay_schedule(schedule, cfg, seams)
    assert v is not None
    assert v.invariant == recorded["invariant"]


def test_unseamed_replay_of_fixture_schedules_is_clean():
    """The SAME schedules on the REAL protocol (no seam) violate
    nothing: the fixtures isolate the seeded bug, not model noise."""
    for seam in SEAMS:
        schedule, cfg, _ = load_fixture(
            os.path.join(FIXTURES, f"{seam}.json")
        )
        try:
            v = replay_schedule(schedule, cfg, ())
        except ValueError:
            continue  # a seam-only action (e.g. reserve) is not enabled
        assert v is None


def test_cli_spec_check_and_replay(tmp_path, capsys):
    from dsort_tpu import cli

    assert cli.main(["spec", "check", "--max-states", "500"]) == 0
    assert "OK" in capsys.readouterr().out
    fix = tmp_path / "v.json"
    rc = cli.main(["spec", "check", "--seam", "ack_before_persist",
                   "--max-states", "5000", "--dump-fixture", str(fix)])
    assert rc == 1 and fix.exists()
    capsys.readouterr()
    assert cli.main(["spec", "replay", "--fixture", str(fix)]) == 0
    assert "reproduces" in capsys.readouterr().out


# -- frame-decoder fuzz ------------------------------------------------------


class _CaptureSock:
    def __init__(self):
        self.data = bytearray()

    def sendall(self, b):
        self.data.extend(b)


class _ByteSock:
    """A byte-buffer socket that fails the test if the decoder stops
    making progress (the never-hang half of the contract)."""

    def __init__(self, data: bytes):
        self._data = bytes(data)
        self._pos = 0
        self.calls = 0

    def recv(self, n):
        self.calls += 1
        assert self.calls < 10_000, "decoder looped without progress"
        chunk = self._data[self._pos:self._pos + n]
        self._pos += len(chunk)
        return chunk


def _valid_frames() -> dict[str, bytes]:
    """One well-formed wire frame per registered type."""
    out = {}
    for ftype in proto.FRAME_TYPES:
        sock = _CaptureSock()
        header = {"type": ftype, "job_id": "j1", "tenant": "t"}
        payload = b""
        if ftype in ("submit", "result"):
            meta, payload = proto.encode_array(
                np.arange(16, dtype=np.int32)
            )
            header.update(meta)
            header["ok"] = True
        proto.send_frame(sock, header, payload)
        out[ftype] = bytes(sock.data)
    return out


def _decode_all(data: bytes):
    """Drive recv_frame (and the array decoder, where meta rides the
    header) over a byte stream until EOF; ProtocolError is the typed,
    expected outcome for corrupt input."""
    sock = _ByteSock(data)
    while True:
        frame = proto.recv_frame(sock)
        if frame is None:
            return
        header, payload = frame
        if "dtype" in header and "shape" in header:
            try:
                proto.decode_array(header, payload)
            except proto.ProtocolError:
                pass


def _mutate(data: bytes, rng: random.Random) -> bytes:
    buf = bytearray(data)
    op = rng.randrange(4)
    if op == 0:  # flip 1-4 bytes anywhere (length prefix included)
        for _ in range(rng.randint(1, 4)):
            i = rng.randrange(len(buf))
            buf[i] ^= 1 << rng.randrange(8)
    elif op == 1:  # truncate mid-frame
        del buf[rng.randrange(1, len(buf)):]
    elif op == 2:  # duplicate a slice (reordered/garbled tail)
        i = rng.randrange(len(buf))
        buf.extend(buf[i:i + rng.randint(1, 32)])
    else:  # prepend a random prefix (stray client)
        buf[:0] = bytes(rng.randrange(256) for _ in range(rng.randint(1, 8)))
    return bytes(buf)


def _persist_fuzz_fixture(seed, ftype, data, exc):
    path = os.path.join(FIXTURES, f"fuzz_{seed}.json")
    with open(path, "w", encoding="utf-8") as f:
        json.dump({"seed": seed, "frame_type": ftype,
                   "error": repr(exc), "data_hex": data.hex()}, f, indent=1)
        f.write("\n")
    return path


def test_frame_decoder_fuzz_typed_errors_only():
    """Seeded byte mutations of every registered frame either parse or
    raise `ProtocolError` — no hangs, no foreign exceptions.  A failing
    seed persists as a regression fixture next to the minimized
    schedules before the test fails."""
    frames = _valid_frames()
    assert set(frames) == set(proto.FRAME_TYPES)
    for ftype, data in frames.items():  # the unmutated baseline parses
        _decode_all(data)
    types = sorted(frames)
    for seed in range(300):
        rng = random.Random(seed)
        ftype = types[seed % len(types)]
        mutated = _mutate(frames[ftype], rng)
        try:
            _decode_all(mutated)
        except proto.ProtocolError:
            pass
        except Exception as e:  # noqa: BLE001 - the property under test
            path = _persist_fuzz_fixture(seed, ftype, mutated, e)
            raise AssertionError(
                f"seed {seed} ({ftype}): {e!r} is not a ProtocolError; "
                f"fixture persisted at {path}"
            ) from e


def test_frame_fuzz_regression_fixtures_replay():
    """Any persisted failing seed stays fixed: replay every committed
    fuzz fixture and require typed behavior."""
    import glob

    for path in sorted(glob.glob(os.path.join(FIXTURES, "fuzz_*.json"))):
        with open(path, encoding="utf-8") as f:
            fix = json.load(f)
        try:
            _decode_all(bytes.fromhex(fix["data_hex"]))
        except proto.ProtocolError:
            pass


def test_frame_decoder_never_buffers_past_header_bound():
    """A corrupt length prefix claiming a >1 MB header raises BEFORE any
    header bytes are consumed — the no-over-allocation bound."""
    sock = _ByteSock(struct.pack(">I", proto.MAX_HEADER_BYTES + 1) + b"x" * 64)
    with pytest.raises(proto.ProtocolError, match="implausible"):
        proto.recv_frame(sock)
    assert sock._pos == 4  # only the prefix was read
    # A valid header claiming an over-bound payload is equally typed.
    head = json.dumps({"type": "ping",
                       "payload_len": proto.MAX_FRAME_BYTES + 1}).encode()
    sock = _ByteSock(struct.pack(">I", len(head)) + head)
    with pytest.raises(proto.ProtocolError, match="implausible"):
        proto.recv_frame(sock)


def test_decode_array_rejects_malformed_meta():
    _, payload = proto.encode_array(np.arange(8, dtype=np.int64))
    for meta in (
        {"dtype": "not-a-dtype", "shape": [8]},
        {"dtype": "int64", "shape": ["x"]},
        {"dtype": "int64"},
        {"dtype": "int64", "shape": [-1]},
        {"dtype": "int64", "shape": [4]},
    ):
        with pytest.raises(proto.ProtocolError):
            proto.decode_array(meta, payload)


# -- DS10xx: seeded spec<->handler drift -------------------------------------


def _copy_tree(tmp_path):
    root = tmp_path / "repo"
    shutil.copytree(
        os.path.join(REPO, "dsort_tpu"), root / "dsort_tpu",
        ignore=shutil.ignore_patterns("__pycache__", "*.so", "*.o"),
    )
    shutil.copy(os.path.join(REPO, "pyproject.toml"), root / "pyproject.toml")
    return root


def test_seeded_handler_drift_is_caught(tmp_path):
    """Acceptance: delete one handler arm (the agent's `drain`) in a
    copied tree — DS1003 names the frame whose declared transition lost
    its code path."""
    root = _copy_tree(tmp_path)
    agent = root / "dsort_tpu" / "fleet" / "agent.py"
    src = agent.read_text()
    assert 'elif ftype == "drain":' in src
    agent.write_text(src.replace('elif ftype == "drain":',
                                 'elif ftype == "bye":', 1))
    diags = lint_paths([str(root / "dsort_tpu" / "fleet")],
                       load_config(str(root)))
    hits = [d for d in diags if d.code == "DS1003"]
    assert hits, [d.format() for d in diags]
    assert any("drain" in d.message for d in hits)


def test_real_tree_is_spec_clean():
    """The shipped tree has zero DS10xx/DS11xx findings — the checker
    gates drift, it does not start life with a baseline."""
    diags = lint_paths([os.path.join(REPO, "dsort_tpu")], load_config(REPO))
    spec_codes = [d for d in diags if d.code.startswith("DS1")
                  and len(d.code) == 6]
    assert spec_codes == [], [d.format() for d in spec_codes]


def test_no_hand_rolled_sequence_literals_in_tests():
    """Acceptance: the contract engine SERVES the sequence asserts — the
    test tree itself carries no duplicated in-alphabet sequence literals
    (DS1103 over tests/)."""
    diags = lint_paths([os.path.join(REPO, "tests")], load_config(REPO))
    hits = [d for d in diags if d.code == "DS1103"]
    assert hits == [], [d.format() for d in hits]


def test_lint_cache_key_tracks_spec_sources(tmp_path):
    """Satellite: editing a spec source invalidates the lint cache —
    the registry paths participate in the config key."""
    (tmp_path / "machines.py").write_text("PROTOCOL_SPEC = {}\n")
    (tmp_path / "contracts.py").write_text("TRACE_CONTRACTS = {}\n")
    cfg = LintConfig(root=str(tmp_path), spec_registry_path="machines.py",
                     contracts_registry_path="contracts.py")
    checkers = all_checkers()
    k1 = ResultCache._config_key(cfg, checkers)
    (tmp_path / "contracts.py").write_text("TRACE_CONTRACTS = {'x': {}}\n")
    k2 = ResultCache._config_key(cfg, checkers)
    assert k1 != k2


# -- ARCHITECTURE §16 schema enforcement -------------------------------------


def test_architecture_documents_spec_plane():
    """§16's contract is test-enforced like §7-§15: the invariant
    catalog appears VERBATIM, every contract and machine is named, and
    the fixture-replay contract is documented."""
    from dsort_tpu.analysis.spec.machines import SPEC_INVARIANTS

    arch = open(os.path.join(REPO, "ARCHITECTURE.md"),
                encoding="utf-8").read()
    assert "## 16. Spec plane" in arch
    for name, text in SPEC_INVARIANTS.items():
        assert f"`{name}`" in arch, f"invariant {name} undocumented"
        assert text in arch, f"invariant {name} text not verbatim"
    for machine in PROTOCOL_SPEC:
        assert f"`{machine}`" in arch, f"machine {machine} undocumented"
    for contract in TRACE_CONTRACTS:
        assert f"`{contract}`" in arch, f"contract {contract} undocumented"
    for phrase in ("spec-smoke", "replay", "minimized", "--conform"):
        assert phrase in arch
    for code in ("DS1001", "DS1002", "DS1003", "DS1004", "DS1005",
                 "DS1101", "DS1102", "DS1103"):
        assert code in arch, f"{code} missing from the checker catalog"
