"""Merge tests — replaces the reference's O(N*k) central merge (server.c:481-524)."""

import jax.numpy as jnp
import numpy as np

from dsort_tpu.ops.local_sort import sentinel_for, sort_padded
from dsort_tpu.ops.merge import (
    merge_shards_device,
    merge_sorted_host,
    merge_sorted_host_streaming,
)


def test_merge_sorted_host_matches_numpy():
    rng = np.random.default_rng(3)
    chunks = [np.sort(rng.integers(-1000, 1000, n).astype(np.int32)) for n in (10, 0, 57, 3, 1000)]
    out = merge_sorted_host(chunks)
    np.testing.assert_array_equal(out, np.sort(np.concatenate(chunks)))


def test_merge_sorted_host_single_and_empty():
    assert len(merge_sorted_host([])) == 0
    one = np.array([1, 2, 3], dtype=np.int32)
    np.testing.assert_array_equal(merge_sorted_host([one]), one)


def test_merge_streaming():
    chunks = [np.array([1, 4, 7]), np.array([2, 5]), np.array([0, 9])]
    assert list(merge_sorted_host_streaming(chunks)) == [0, 1, 2, 4, 5, 7, 9]


def test_merge_shards_device():
    import jax

    rng = np.random.default_rng(4)
    buf = rng.integers(-50, 50, (4, 8)).astype(np.int32)
    counts = np.array([8, 3, 0, 5], dtype=np.int32)
    sorted_shards, counts_j = jax.vmap(sort_padded)(jnp.asarray(buf), jnp.asarray(counts))
    flat, total = merge_shards_device(sorted_shards, counts_j)
    flat = np.asarray(flat)
    valid = np.concatenate([buf[i, :c] for i, c in enumerate(counts)])
    assert int(total) == len(valid)
    np.testing.assert_array_equal(flat[: len(valid)], np.sort(valid))
    assert (flat[len(valid):] == sentinel_for(np.int32)).all()


def test_merge_sorted_host_preserves_dtype_when_all_empty():
    out = merge_sorted_host([np.empty(0, np.int64), np.empty(0, np.int64)])
    assert out.dtype == np.int64 and len(out) == 0
