"""Clean twin of ``bad_spmd.py`` — same shapes, zero findings.

The builders match their declared closed forms, every ppermute table
traces to a declared builder, branches on device-varying state issue no
collectives, the literal axis name is a constructed mesh axis, and the
kernel's remote-DMA slots are the disjoint partial-sum layout.
"""

import jax

SPMD_CONTRACT = {
    "plane": "device",
    "axis_param": "axis",
    "perms": {
        "shift_perm": {
            "args": ("p", "k"),
            "domain": {"p": "MESH", "k": "range(p)"},
            "kind": "full",
            "axis_size": "p",
            "dst": "(i + k) % p",
        },
        "pair_perm": {
            "args": ("p", "k"),
            "domain": {"p": "MESH", "k": "range(p)"},
            "kind": "full",
            "axis_size": "p",
            "pairs": "[(i, (i + k) % p) for i in range(p)]",
        },
    },
    "layouts": {"good_kernel": {}},
}


def shift_perm(p, k):
    return [(i, (i + k) % p) for i in range(p)]


def pair_perm(p, k):
    return [(i, (i + k) % p) for i in range(p)]


def exchange(x, lens, axis, p, eager):
    me = jax.lax.axis_index(axis)
    out = jax.lax.ppermute(x, axis, shift_perm(p, 1))
    table = pair_perm(p, 2)
    out = jax.lax.ppermute(out, axis, table)
    if eager:  # config flag, not device-varying: branching is uniform
        out = jax.lax.psum(out, axis)
    keep = jax.lax.cond(me > 0, lambda: x, lambda: out)
    y = jax.lax.all_gather(lens, "w")
    return keep, y


def _off(caps):
    offs = [0]
    for c in caps:
        offs.append(offs[-1] + int(c))
    return offs


def good_kernel(*refs, num_workers, caps, axis):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    p = num_workers
    out_ref = refs[p]
    offs = _off(caps)
    me = jax.lax.axis_index(axis)

    def copy(k):
        return pltpu.make_async_remote_copy(
            src_ref=refs[k],
            dst_ref=out_ref.at[pl.ds(offs[k], caps[k])],
            device_id=me,
        )

    for k in range(1, p):
        copy(k).start()
