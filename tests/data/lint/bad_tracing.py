"""Fixture: tracing-hygiene violations (DS301/DS302)."""

import functools
import time

import jax


@jax.jit
def leaky(x, metrics):
    metrics.event("job_start", n_keys=1)  # DS301: journals at trace time
    t0 = time.time()  # DS301: clock read baked in at trace time
    print("tracing", t0)  # DS301
    return x


def make_counter_bumper(counter):
    @jax.jit
    def bump(x):
        nonlocal counter  # DS301: nonlocal mutation under trace
        counter += 1
        return x

    return bump


def _kernel(x_ref, o_ref):
    o_ref[:] = x_ref[:]


@functools.partial(jax.jit, static_argnames=("interpret",))
def bad_geometry(x, n, interpret):
    from jax.experimental import pallas as pl

    return pl.pallas_call(
        _kernel,
        grid=(n,),  # DS302: n is a traced value, not static_argnames
        out_shape=jax.ShapeDtypeStruct((n, 128), x.dtype),  # DS302
        interpret=interpret,
    )(x)
