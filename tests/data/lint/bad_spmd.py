"""Deliberate DS12xx violations (SPMD collective-schedule verifier).

Expected findings (test-pinned):
- DS1200 x1: ``perms['missing_builder']`` declared but no such function.
- DS1201 x3: ``shift_perm`` computes the INVERTED shift (valid bijection,
  wrong declared form); ``collide_perm`` maps two sources to one
  destination at P >= 3; one ``ppermute`` call site whose table traces to
  an undeclared builder.
- DS1202 x2: a ``psum`` under an ``if`` on ``axis_index``-derived state,
  and a collective inside a ``lax.cond`` branch on such a predicate.
- DS1203 x1: an ``all_gather`` naming an axis no mesh constructs.
- DS1204 x1: a kernel whose remote-DMA write regions overlap.
"""

import jax

SPMD_CONTRACT = {
    "plane": "device",
    "axis_param": "axis",
    "perms": {
        "shift_perm": {
            "args": ("p", "k"),
            "domain": {"p": "MESH", "k": "range(p)"},
            "kind": "full",
            "axis_size": "p",
            "dst": "(i + k) % p",
        },
        "collide_perm": {
            "args": ("p",),
            "domain": {"p": "MESH"},
            "kind": "full",
            "axis_size": "p",
        },
        "missing_builder": {
            "args": ("p",),
            "domain": {"p": "MESH"},
            "kind": "full",
            "axis_size": "p",
        },
    },
    "layouts": {"bad_kernel": {}},
}


def shift_perm(p, k):
    # Declared dst is (i + k) % p; this is the inverted ring.
    return [(i, (i - k) % p) for i in range(p)]


def collide_perm(p):
    return [(i, min(i, 1)) for i in range(p)]


def exchange(x, lens, axis, p):
    me = jax.lax.axis_index(axis)
    table = build_table(p)  # noqa: F821 - undeclared builder, AST-only
    out = jax.lax.ppermute(x, axis, table)
    if me > 0:
        out = jax.lax.psum(out, axis)
    out = jax.lax.cond(
        me > 0, lambda: jax.lax.psum(x, axis), lambda: x
    )
    y = jax.lax.all_gather(lens, "q")
    return out, y


def _off(caps):
    offs = [0]
    for c in caps:
        offs.append(offs[-1] + int(c))
    return offs


def bad_kernel(*refs, num_workers, caps, axis):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    p = num_workers
    out_ref = refs[p]
    offs = _off(caps)
    me = jax.lax.axis_index(axis)

    def copy(k):
        # Halved offsets: step k's slot overlaps step k-1's tail.
        return pltpu.make_async_remote_copy(
            src_ref=refs[k],
            dst_ref=out_ref.at[pl.ds(offs[k] // 2, caps[k])],
            device_id=me,
        )

    for k in range(1, p):
        copy(k).start()
