"""Fixture: near-miss clean twin of bad_health — all discipline kept.

The shapes `obs.health` actually ships: lock held only for dict/deque
state, the frame ship and the verdict emission both OUTSIDE the lock, and
the verdict computed AROUND the jitted callable, never inside it.
"""

import threading
import time

import jax


class HealthState:
    def __init__(self):
        self._lock = threading.Lock()
        self._phase_s = {}
        self._waits = []

    def fold(self, delta):
        with self._lock:
            self._waits.append(delta)
            self._phase_s[delta["phase"]] = delta["seconds"]

    def drain(self):
        with self._lock:  # swap the window out under the lock ...
            waits, self._waits = self._waits, []
        return {"waits": waits}  # ... the caller ships after it released

    def ship_outside_lock(self, sock, frame):
        delta = self.drain()  # lock released inside drain
        sock.send(frame, delta)  # the socket write never holds the lock


@jax.jit
def pure_stage(x):
    return x + 1


def verdict_around_trace(x, metrics):
    t0 = time.perf_counter()  # host-side busy timer AROUND the traced call
    y = pure_stage(x)
    metrics.event("health_verdict", agent="a0",
                  score=time.perf_counter() - t0)
    return y
