"""Fixture: near-miss twin of bad_tracing — host effects stay on the host."""

import functools
import time

import jax


@jax.jit
def pure(x):
    return x * 2


def _kernel(x_ref, o_ref):
    o_ref[:] = x_ref[:]


def _shapes(x):
    return jax.ShapeDtypeStruct(x.shape, x.dtype)


@functools.partial(jax.jit, static_argnames=("rows", "interpret"))
def good_geometry(x, rows, interpret):
    from jax.experimental import pallas as pl

    total = x.shape[0] // rows  # shapes are static under jit
    return pl.pallas_call(
        _kernel,
        grid=(total,),  # static: shape arithmetic + static_argnames
        out_shape=_shapes(x),  # helper call: shape-only plumbing
        interpret=interpret,
    )(x)


def host_driver(data, metrics):
    # NOT traced: journaling and timing on the host path are the point.
    t0 = time.time()
    metrics.event("job_start", n_keys=len(data))
    out = pure(data)
    metrics.event("job_done", n_keys=len(data))
    return out, time.time() - t0
