"""Fixture: coded-recovery discipline violations (DS201/DS202 + DS301).

Models the coded redundancy plane's two riskiest shapes: a replica-state
table whose slots must stay lock-guarded with no blocking work under the
lock (the reconstruction is a k-way MERGE of host runs — holding the
table lock across it would serialize every concurrently-failing job's
recovery behind one slow merge), and an exchange shard function that must
never journal its recovery from inside a traced program (the recovery
wall time would become a trace-time constant and the event would fire
once per compile, not per recovery).
"""

import threading
import time

import jax


class ReplicaTable:
    def __init__(self):
        self._lock = threading.Lock()
        self._slots = {}
        self._recoveries = []

    def park(self, dead, state):
        with self._lock:
            self._slots[dead] = state

    def park_racy(self, dead, state):
        self._slots[dead] = state  # DS201: guarded attribute, no lock held

    def reconstruct_under_lock(self, merge, dead):
        with self._lock:
            time.sleep(0.01)  # DS202: the settle delay, lock held
            return merge.wait()  # DS202: blocking k-way merge under the lock


@jax.jit
def recover_inside_trace(x, metrics):
    metrics.event("coded_recover", dead=[3], recovered_keys=7)  # DS301
    t0 = time.perf_counter()  # DS301: recovery wall clock baked at trace
    print("reconstructed at", t0)  # DS301
    return x + 1
