"""Near-miss clean twin of bad_durability.py: tmp+fsync+rename, the touch
idiom, snapshot-under-lock + write-outside, and a dedicated flush lock."""

import json
import os
import threading

import numpy as np


class GoodPersist:
    def __init__(self):
        self._lock = threading.Lock()
        self._flush_lock = threading.Lock()
        self.state = {}
        self._pending = None

    def save_state(self, path):
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(self.state, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)

    def save_shard(self, path, arr):
        tmp = path + ".tmp.npy"
        np.save(tmp, arr)
        fd = os.open(tmp, os.O_RDONLY)
        try:
            os.fsync(fd)
        finally:
            os.close(fd)
        os.replace(tmp, path)

    def preallocate(self, path):
        open(path, "wb").close()  # create/truncate writes no payload

    def bump(self):
        with self._lock:
            self.state["seq"] = self.state.get("seq", 0) + 1

    def snapshot(self):
        with self._lock:
            self._pending = dict(self.state)

    def flush(self, path):
        with self._lock:  # cheap dict work only under the shared lock
            pending = self._pending
        # The dedicated single-function flush lock is the sanctioned shape.
        with self._flush_lock:
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(pending, f)
                os.fsync(f.fileno())
            os.replace(tmp, path)
