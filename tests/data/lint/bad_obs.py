"""Fixture: obs-module discipline violations (DS201/DS202 + DS301).

Models the telemetry plane's two riskiest shapes: a flight-recorder-like
ring class whose state must stay lock-guarded with no blocking work under
the lock (a dump writing to a full disk must never stall the emit path),
and a scrape helper that must never run under trace (a jitted stage
calling into telemetry would journal at compile time, once, forever).
"""

import threading
import time

import jax


class Ring:
    def __init__(self):
        self._lock = threading.Lock()
        self._ring = []
        self._seq = 0

    def observe(self, ev):
        with self._lock:
            self._ring.append(ev)
            self._seq += 1

    def observe_racy(self, ev):
        self._ring.append(ev)  # DS201: guarded attribute, no lock held

    def dump(self, proc):
        with self._lock:
            time.sleep(0.01)  # DS202: blocking while holding the lock
            proc.communicate()  # DS202


@jax.jit
def scrape_inside_trace(x, metrics):
    metrics.event("job_start", n_keys=1)  # DS301: journals at trace time
    t0 = time.monotonic()  # DS301: clock read baked in at trace time
    print("scrape", t0)  # DS301
    return x
