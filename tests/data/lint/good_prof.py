"""Fixture: near-miss clean twin of bad_prof — all discipline kept.

The shapes `obs.prof` actually ships: lock held only for dict/list state,
the compile and the journal emission both OUTSIDE the lock, and the
timing/recording wrapped AROUND the jitted callable, never inside it.
"""

import threading
import time

import jax


class Ledger:
    def __init__(self):
        self._lock = threading.Lock()
        self._entries = {}
        self._pending = []

    def record(self, ev):
        with self._lock:
            self._pending.append(ev)
            self._entries[ev["variant"]] = ev

    def drain_to(self, metrics):
        with self._lock:  # swap the queue out under the lock ...
            pending, self._pending = self._pending, []
        for ev in pending:  # ... emit after it released: fine
            metrics.event("variant_compiled", **ev)
        return len(pending)

    def build_outside_lock(self, fn, x):
        compiled = fn.lower(x).compile()  # seconds — never under the lock
        with self._lock:
            self._entries.setdefault("spec", compiled)
        return compiled


@jax.jit
def pure_stage(x):
    return x + 1


def record_around_trace(x, metrics):
    t0 = time.perf_counter()  # host-side timer AROUND the traced call
    y = pure_stage(x)
    metrics.event("variant_compiled", variant="fused|8|int32",
                  compile_s=time.perf_counter() - t0)
    return y
