// Fixture: near-miss twin of bad_coordinator — clean C++ event usage.
#include <cstdio>
void log_event_locked(const char* type, int w, long task);

void transitions() {
  // log_event_locked("commented_out_event", 1, -1);  <- comments ignored
  const char* s = "worker_dead mentioned in a string is not an emit";
  /* log_event_locked("block_commented_event", 1, -1); */
  log_event_locked("worker_dead", 1, -1);
  log_event_locked("reassign", 1, -1);
  std::printf("%s", s);
}
