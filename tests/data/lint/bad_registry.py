"""Fixture: registry-coverage violations (DS101/DS102)."""


def run(metrics, journal):
    metrics.bump("bogus_counter")  # DS102: not in COUNTERS
    metrics.event("bogus_event", n_keys=1)  # DS101: not in EVENT_TYPES
    journal.emit("another_bogus_event")  # DS101
    journal.ingest(1.0, 2.0, "bogus_ingested_event", worker=0)  # DS101
