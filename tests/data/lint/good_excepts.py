"""Fixture: near-miss twin of bad_excepts — every catch accounts for itself."""


def narrow(ckpt):
    try:
        return ckpt.load(0)
    except OSError:  # specific type: allowed to pass silently
        return None


def reported(ckpt, log):
    try:
        return ckpt.load(1)
    except Exception as e:  # broad, but visibly reported
        log.warning("restore failed: %s", e)
        return None


def reraised(ckpt):
    try:
        return ckpt.load(2)
    except Exception:
        raise


def relayed(ckpt, box):
    try:
        box["r"] = ckpt.load(3)
    except BaseException as e:  # the lane-thread error relay pattern
        box["e"] = e


class Holder:
    def close(self):
        pass

    def __del__(self):
        try:
            self.close()
        except Exception:  # interpreter-teardown idiom: exempt
            pass
