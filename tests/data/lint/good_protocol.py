"""Near-miss clean twin of bad_protocol.py: registered frame types, an
explicit dispatch default, a reply guard, registered admission reasons."""

from dsort_tpu.fleet.proto import send_frame
from dsort_tpu.serve.admission import Admission


def send_submit(sock, payload):
    send_frame(sock, {"type": "submit", "job_id": "j1"}, payload)


def dispatch(header, payload):
    ftype = header["type"]
    if ftype == "hello":
        return "hi"
    elif ftype == "ping":
        return "pong"
    else:  # explicit default: one-directional frames raise loudly
        raise ValueError(ftype)


def reply_guard(frame):
    # A lone equality test is a guard for one expected reply type, not a
    # dispatch surface.
    if frame.get("type") == "welcome":
        return True
    return False


def verdicts(v):
    if v.reason == "queue_full":
        return "backoff"
    return Admission(True, "admitted", "t", 1, 1)
