"""Fixture: coded-v2 straggler/parity discipline violations (DS201/DS202 + DS301).

Models the v2 plane's two riskiest shapes: the exactly-once straggler
claim whose winner slot must stay lock-guarded with no blocking work
under the lock (joining the racing owner thread — or sleeping out its
injected delay — while holding the claim lock would serialize every
range's serve behind one slow fetch), and a parity exchange shard whose
recovery journaling must never run inside the traced program (the solve
wall time would become a trace-time constant and the serve event would
fire once per compile, not per race).
"""

import threading
import time

import jax


class StragglerClaim:
    def __init__(self):
        self._lock = threading.Lock()
        self._winner = None
        self._served = []

    def claim(self, leg):
        with self._lock:
            if self._winner is None:
                self._winner = leg
                return True
            return False

    def claim_racy(self, leg):
        self._winner = leg  # DS201: guarded attribute, no lock held

    def serve_under_lock(self, owner_thread, delay):
        with self._lock:
            time.sleep(delay)  # DS202: the injected straggler delay, lock held
            owner_thread.join()  # DS202: blocking owner-leg join under the lock


@jax.jit
def serve_inside_trace(x, metrics):
    metrics.event("coded_straggler_serve", range=3, mode="parity")  # DS301
    t0 = time.perf_counter()  # DS301: solve wall clock baked at trace
    print("served at", t0)  # DS301
    return x ^ 1
