"""Fixture: near-miss clean twin of bad_coded_v2 — all discipline kept.

The shapes `parallel.coded`'s v2 plane actually ships: the claim lock
held only for the compare-and-set, the owner join and the injected delay
both OUTSIDE it, and the parity solve's wall clock measured AROUND the
host-side reconstruction, never inside a traced function.
"""

import threading
import time

import jax


class StragglerClaim:
    def __init__(self):
        self._lock = threading.Lock()
        self._winner = None
        self._served = []

    def claim(self, leg):
        with self._lock:  # compare-and-set only; nothing blocks in here
            if self._winner is None:
                self._winner = leg
                self._served.append(leg)
                return True
            return False

    def serve_outside_lock(self, owner_thread, delay):
        time.sleep(delay)  # the owner leg sleeps on its own thread's time
        won = self.claim("owner")  # lock released inside claim
        if not won:
            owner_thread.join()  # late-loser drain never holds the lock
        return won


@jax.jit
def pure_parity_step(x):
    return x ^ 1


def serve_around_trace(x, metrics):
    t0 = time.perf_counter()  # host-side wall clock AROUND the traced call
    y = pure_parity_step(x)
    metrics.event("coded_straggler_serve", range=3, mode="parity",
                  wall_s=time.perf_counter() - t0)
    return y
