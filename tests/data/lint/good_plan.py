"""Fixture: near-miss clean twin of bad_plan — all discipline kept.

The shapes `obs.plan` actually ships: lock held only for the rolling
dict/deque state, the skew probe and the decision emission both OUTSIDE
the lock, and the decision journaled AROUND the jitted dispatch, never
inside it (the replay contract needs one ``plan_decision`` per dispatch
with the inputs that dispatch measured).
"""

import threading
import time

import jax


class PlannerState:
    def __init__(self):
        self._lock = threading.Lock()
        self._admissions = []
        self._hbm_peak = 0

    def fold(self, label):
        with self._lock:
            self._admissions.append(label)
            self._hbm_peak = max(self._hbm_peak, len(label))

    def inputs(self):
        with self._lock:  # snapshot the rolling state under the lock ...
            return {"history": list(self._admissions)}

    def decide_outside_lock(self, probe, policy):
        inputs = self.inputs()  # lock released inside inputs
        return probe.run(inputs)  # the probe sort never holds the lock


@jax.jit
def pure_dispatch(x):
    return x + 1


def decide_around_trace(x, metrics):
    t0 = time.perf_counter()  # host-side probe clock AROUND the traced call
    y = pure_dispatch(x)
    metrics.event("plan_decision", policy="exchange", chosen="ring",
                  probe_s=time.perf_counter() - t0)
    return y
