"""Clean twin of ``bad_caps.py`` — same shapes, zero findings.

The quantizer rounds UP on the 8 grid (covering + aligned), the canvas
store keeps its declared re-pack hop, and the clamp window is ordered.
"""

SPMD_CONTRACT = {
    "plane": "host",
    "caps": {
        "grow_cap": {
            "args": ("m",),
            "domain": {"m": "SIZES"},
            "require": (
                ("DS1301", "out >= m"),
                ("DS1303", "out >= 8"),
                ("DS1303", "out % 8 == 0"),
            ),
        },
        "even_quantum": {
            "args": ("n",),
            "domain": {"n": "SIZES"},
            "require": (
                ("DS1303", "out >= 8"),
                ("DS1303", "out % 8 == 0"),
            ),
        },
    },
    "stores": {
        "weave": ({"canvas": "rcv", "repack": "_pad_run", "width": "total"},),
    },
    "consts": {
        "MIN_WINDOW": (("DS1303", "value <= MAX_WINDOW"),),
    },
}

MIN_WINDOW = 1 << 16
MAX_WINDOW = 1 << 20


def grow_cap(m):
    return max(-(-m // 8) * 8, 8)


def even_quantum(n):
    return max(-(-max(n // 96, 8) // 8) * 8, 8)


def _pad_run(buf, width, fill):
    return buf


def weave(rcv, rbuf, total, sent, row):
    rcv = rcv.at[row].set(_pad_run(rbuf, total, sent))
    return rcv
