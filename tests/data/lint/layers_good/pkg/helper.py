from typing import TYPE_CHECKING

if TYPE_CHECKING:
    import fakebackend.core  # annotation-only: never executes


def work(x):
    import fakebackend.core  # lazy: the sanctioned escape hatch

    return fakebackend.core.run(x)
