"""Backend-free: the helper defers its backend import."""

from pkg.helper import work  # noqa: F401
