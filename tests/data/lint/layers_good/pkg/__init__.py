"""Fixture package (clean twin)."""
