"""Fixture: near-miss twin of bad_compat — everything routes via the shim."""

import jax

from dsort_tpu.utils.compat import set_x64, shard_map  # the one true door


def setup():
    set_x64(True)
    jax.config.update("jax_platforms", "cpu")  # different config key: fine
    return shard_map, jax.config.jax_enable_x64  # reading the flag: fine
