"""Fixture: near-miss clean twin of bad_coded — all discipline kept.

The shapes `parallel.coded` actually ships: lock held only for the slot
dict, the k-way merge and the recovery event both OUTSIDE the lock, and
the recovery wall time measured AROUND the device dispatch, never inside
a traced function.
"""

import threading
import time

import jax


class ReplicaTable:
    def __init__(self):
        self._lock = threading.Lock()
        self._slots = {}
        self._recoveries = []

    def park(self, dead, state):
        with self._lock:
            self._slots[dead] = state
            self._recoveries.append(dead)

    def take(self, dead):
        with self._lock:  # swap the snapshot out under the lock ...
            return self._slots.pop(dead, None)

    def reconstruct_outside_lock(self, merge, dead):
        state = self.take(dead)  # lock released inside take
        return merge.run(state)  # the k-way merge never holds the lock


@jax.jit
def pure_exchange_step(x):
    return x + 1


def recover_around_trace(x, metrics):
    t0 = time.perf_counter()  # host-side wall clock AROUND the traced call
    y = pure_exchange_step(x)
    metrics.event("coded_recover", dead=[3],
                  wall_s=time.perf_counter() - t0)
    return y
