"""Deliberate kernel/thread lifecycle violations (DS901/DS902/DS903)."""

import threading

from jax.experimental.pallas import tpu as pltpu


def kernel_forgot_wait(src, dst, sems, p):
    def copy(k):
        return pltpu.make_async_remote_copy(
            src_ref=src, dst_ref=dst, send_sem=sems[0].at[k],
            recv_sem=sems[1].at[k], device_id=k,
        )

    for k in range(1, p):
        copy(k).start()  # DS901: never waited — in flight at kernel end


def kernel_half_drained(src, dst, sems):
    def copy(k):
        return pltpu.make_async_remote_copy(
            src_ref=src, dst_ref=dst, send_sem=sems[0].at[k],
            recv_sem=sems[1].at[k], device_id=k,
        )

    copy(1).start()
    copy(1).wait_recv()  # DS902: the send semaphore is never drained


def spawn_workers(fn):
    threading.Thread(target=fn).start()  # DS903: not daemon, never joined
    t = threading.Thread(target=fn)  # DS903
    t.start()


def arm_watchdog(fn):
    w = threading.Timer(5.0, fn)  # DS903: never cancelled/joined/daemonized
    w.start()


def leak_pool(fn, items):
    from concurrent.futures import ThreadPoolExecutor

    pool = ThreadPoolExecutor(max_workers=4)  # DS903: no with, no shutdown
    for it in items:
        pool.submit(fn, it)
