"""Fixture: near-miss twin of bad_ring_kernel — the real module's shape.

Launch geometry derives from the STATIC caps tuple (a python value closed
over via functools.partial, exactly `ops.ring_kernel`'s pattern) or from
shapes, and every journal emission happens on the host around the dispatch,
never inside the kernel."""

import functools
import time

import jax


def _fused_kernel(send_ref, out_ref, *, caps):
    # Pure kernel body: caps is a static python tuple, no host effects.
    out_ref[...] = send_ref[...]


def _launch(send, caps, interpret):
    from jax.experimental import pallas as pl

    total = int(sum(caps))  # static: caps is a python tuple
    return pl.pallas_call(
        functools.partial(_fused_kernel, caps=caps),
        grid=(len(caps),),
        out_shape=jax.ShapeDtypeStruct((total,), send.dtype),
        interpret=interpret,
    )(send)


def host_driver(send, caps, metrics):
    # NOT traced: the fused plan journals its schedule on the host, then
    # dispatches ONE launch — the `note_fused_plan` shape.
    t0 = time.monotonic()
    for k, cap in enumerate(caps[1:], start=1):
        metrics.event("fused_exchange_step", step=k, cap=cap)
    out = _launch(send, caps, interpret=True)
    metrics.event("fused_exchange_launch", steps=len(caps) - 1)
    return out, time.monotonic() - t0
