"""Fixture: near-miss clean twin of bad_hier — all discipline kept.

The shapes `parallel.exchange`'s hier section actually ships: lock held
only for the grouping dict, the (H,H) histogram reduction and the
`hier_exchange_plan` journal both OUTSIDE the lock (note_hier_plan is
host-side), and the DCN leg wall time measured AROUND the dispatch,
never inside a traced shard function.
"""

import threading
import time

import jax


class HostTable:
    def __init__(self):
        self._lock = threading.Lock()
        self._groupings = {}
        self._replans = []

    def park(self, hosts, plan):
        with self._lock:
            self._groupings[hosts] = plan
            self._replans.append(hosts)

    def take(self, hosts):
        with self._lock:  # swap the plan out under the lock ...
            return self._groupings.pop(hosts, None)

    def replan_outside_lock(self, reduce_hist, survivors):
        stale = self.take(survivors)  # lock released inside take
        return reduce_hist.run(stale)  # the (H,H) reduction never holds the lock


@jax.jit
def pure_hier_shard(xs):
    return xs + 1


def plan_around_trace(xs, metrics):
    t0 = time.perf_counter()  # host-side wall clock AROUND the traced call
    ys = pure_hier_shard(xs)
    metrics.event("hier_exchange_plan", hosts=4,
                  wall_s=time.perf_counter() - t0)
    return ys
