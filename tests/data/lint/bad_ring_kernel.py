"""Fixture: fused-ring-kernel-shaped tracing violations (DS301/DS302).

The failure modes the checkers must pin on `ops.ring_kernel`-style code: a
kernel body that journals or reads clocks (it would fire once at trace
time, claiming DMA steps that never ran), and launch geometry — the
pallas_call ``grid``/``out_shape`` — fed from a traced parameter instead of
the static caps tuple."""

import functools
import time

import jax


def _fused_kernel(send_ref, out_ref, metrics):
    # DS301: journals one "step" at TRACE time, not per launch.
    metrics.event("fused_exchange_step", step=1)
    t0 = time.monotonic()  # DS301: clock read baked into the kernel
    print("dma in flight", t0)  # DS301
    out_ref[...] = send_ref[...]


@functools.partial(jax.jit, static_argnames=("interpret",))
def bad_fused_geometry(send, total, interpret):
    from jax.experimental import pallas as pl

    return pl.pallas_call(
        _fused_kernel,
        grid=(total,),  # DS302: total is traced, not in static_argnames
        out_shape=jax.ShapeDtypeStruct((total,), send.dtype),  # DS302
        interpret=interpret,
    )(send)
