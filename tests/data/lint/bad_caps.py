"""Deliberate DS13xx violations (capacity/layout abstract interpreter).

Expected findings (test-pinned):
- DS1300 x2: ``caps['lost_fn']`` declared but no such function; a declared
  cap function that calls numpy (outside the evaluable subset).
- DS1301 x1: a quantizer that rounds DOWN (cap does not cover demand).
- DS1302 x1: a receive-canvas store without the declared re-pack hop.
- DS1303 x3: a quantum off the 8 grid (two failed properties) and an
  inverted clamp window constant.
"""

import numpy as np

SPMD_CONTRACT = {
    "plane": "host",
    "caps": {
        "shrink_cap": {
            "args": ("m",),
            "domain": {"m": "SIZES"},
            "require": (("DS1301", "out >= m"),),
        },
        "odd_quantum": {
            "args": ("n",),
            "domain": {"n": "SIZES"},
            "require": (
                ("DS1303", "out >= 8"),
                ("DS1303", "out % 8 == 0"),
            ),
        },
        "lost_fn": {
            "args": ("n",),
            "domain": {"n": "SIZES"},
            "require": (("DS1301", "out >= n"),),
        },
        "numpy_cap": {
            "args": ("n",),
            "domain": {"n": "SIZES"},
            "require": (("DS1301", "out >= n"),),
        },
    },
    "stores": {
        "weave": ({"canvas": "rcv", "repack": "_pad_run", "width": "total"},),
    },
    "consts": {
        "MIN_WINDOW": (("DS1303", "value <= MAX_WINDOW"),),
    },
}

MIN_WINDOW = 1 << 20
MAX_WINDOW = 1 << 16  # inverted: clamp(lo=MIN, hi=MAX) collapses to MAX


def shrink_cap(m):
    return m - (m % 16)


def odd_quantum(n):
    return max(n // 12, 3)


def numpy_cap(n):
    return int(np.ceil(n / 8.0)) * 8


def _pad_run(buf, width, fill):
    return buf


def weave(rcv, rbuf, total, sent, row):
    # The re-pack hop is missing: a short leg buffer lands in a
    # total-wide row unpadded.
    rcv = rcv.at[row].set(rbuf)
    return rcv
