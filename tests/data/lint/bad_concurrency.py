"""Fixture: concurrency-discipline violations (DS201/DS202/DS203)."""

import threading
import time

LOCK_A = threading.Lock()
LOCK_B = threading.Lock()
SHARED = {}


class Table:
    def __init__(self):
        self._lock = threading.Lock()
        self._state = [0]

    def guarded(self):
        with self._lock:
            self._state[0] = 1

    def racy(self):
        self._state[0] = 2  # DS201: guarded attribute, no lock held

    def slow(self, worker):
        with self._lock:
            time.sleep(0.1)  # DS202: blocking while holding the lock
            worker.join()  # DS202


def write_shared(key):
    with LOCK_A:
        SHARED[key] = 1


def write_shared_racy(key):
    SHARED[key] = 2  # DS201: guarded module global, no lock held


def order_ab():
    with LOCK_A:
        with LOCK_B:
            pass


def order_ba():
    with LOCK_B:
        with LOCK_A:  # DS203: ABBA with order_ab
            pass
