"""Deliberate protocol-coverage violations (DS801/DS802/DS803)."""

from dsort_tpu.fleet.proto import send_frame
from dsort_tpu.serve.admission import Admission


def send_unregistered(sock):
    send_frame(sock, {"type": "frobnicate", "job_id": "j1"})  # DS801


def dead_branch(header):
    return header.get("type") == "not_a_frame"  # DS801


def dispatch(header, payload):
    # DS802: a dispatch chain with no default — every registered frame
    # type outside the two arms falls through silently.
    ftype = header["type"]
    if ftype == "hello":
        return "hi"
    elif ftype == "ping":
        return "pong"


def verdicts(v):
    if v.reason == "totally_bogus":  # DS803
        return "?"
    return Admission(False, "nope", "t", 0, 0)  # DS803
