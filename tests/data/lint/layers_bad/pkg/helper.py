import fakebackend.core  # the forbidden module-level import


def work(x):
    return fakebackend.core.run(x)
