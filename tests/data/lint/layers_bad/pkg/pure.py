"""Declared backend-free — but helper pulls the backend at import time."""

from pkg.helper import work  # noqa: F401
