"""Fixture package (layer-violation twin)."""
