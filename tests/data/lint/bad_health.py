"""Fixture: health-plane discipline violations (DS201/DS202 + DS301).

Models the live health plane's two riskiest shapes: a delta collector /
analyzer whose rolling state must stay lock-guarded with no blocking work
under the lock (shipping a telemetry frame is a SOCKET write — holding the
analyzer lock across it would serialize every concurrently-ingesting
reader thread behind one slow link), and an instrumented stage that must
never emit a verdict from inside a traced function (the "busy seconds"
would become a trace-time constant).
"""

import threading
import time

import jax


class HealthState:
    def __init__(self):
        self._lock = threading.Lock()
        self._phase_s = {}
        self._waits = []

    def fold(self, delta):
        with self._lock:
            self._waits.append(delta)

    def fold_racy(self, delta):
        self._waits.append(delta)  # DS201: guarded attribute, no lock held

    def ship_under_lock(self, sock, frame):
        with self._lock:
            time.sleep(0.01)  # DS202: the heartbeat pause, lock held
            sock.wait()  # DS202: blocking on the link from under the lock


@jax.jit
def verdict_inside_trace(x, metrics):
    metrics.event("health_verdict", agent="a0", score=2.0)  # DS301
    t0 = time.perf_counter()  # DS301: the busy timer baked in at trace
    print("degraded at", t0)  # DS301
    return x + 1
