"""Fixture: in-line suppressions silence exactly the named codes."""


def run(metrics):
    metrics.bump("bogus_counter")  # dsort: ignore[DS102]
    metrics.bump("second_bogus_counter")  # dsort: ignore
    metrics.event("bogus_event")  # dsort: ignore[DS999] -- wrong code: fires
