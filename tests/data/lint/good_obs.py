"""Fixture: near-miss clean twin of bad_obs — all discipline kept."""

import threading
import time

import jax


class Ring:
    def __init__(self):
        self._lock = threading.Lock()
        self._ring = []
        self._seq = 0

    def observe(self, ev):
        with self._lock:
            self._ring.append(ev)
            self._seq += 1

    def snapshot(self):
        with self._lock:
            return list(self._ring)

    def dump(self, proc):
        with self._lock:  # snapshot under the lock ...
            ring = list(self._ring)
        time.sleep(0.0)  # ... blocking work AFTER it released: fine
        proc.communicate()
        return ring


@jax.jit
def pure_stage(x):
    return x + 1


def scrape_outside_trace(x, metrics):
    y = pure_stage(x)  # device work traced, telemetry on the host side
    metrics.event("job_done", n_keys=1)
    t0 = time.monotonic()
    return y, t0
