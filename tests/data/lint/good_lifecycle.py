"""Near-miss clean twin of bad_lifecycle.py: the ring kernel's
start/fold/wait schedule with BOTH DMA directions drained, a plain-wait
copy, a daemon thread, and joined worker threads."""

import threading

from jax.experimental.pallas import tpu as pltpu


def kernel_paired(src, dst, sems, p):
    def copy(k):
        return pltpu.make_async_remote_copy(
            src_ref=src, dst_ref=dst, send_sem=sems[0].at[k],
            recv_sem=sems[1].at[k], device_id=k,
        )

    copy(1).start()
    for k in range(2, p):
        copy(k).start()
        copy(k - 1).wait_recv()
    copy(p - 1).wait_recv()
    for k in range(1, p):
        copy(k).wait_send()  # every DMA drained before buffer reuse


def kernel_plain_wait(src, dst, sem):
    c = pltpu.make_async_remote_copy(
        src_ref=src, dst_ref=dst, send_sem=sem, recv_sem=sem, device_id=0,
    )
    c.start()
    c.wait()


def spawn_daemon(fn):
    threading.Thread(target=fn, daemon=True).start()


def run_joined(fn, n):
    threads = [threading.Thread(target=fn) for _ in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()


def arm_cancelled_watchdog(fn):
    w = threading.Timer(5.0, fn)
    w.start()
    try:
        fn()
    finally:
        w.cancel()  # timer drained before the owner returns


def arm_daemon_watchdog(fn):
    w = threading.Timer(5.0, fn)
    w.daemon = True  # Timer takes no daemon kwarg; the attribute set pairs
    w.start()


def run_pooled(fn, items):
    from concurrent.futures import ThreadPoolExecutor

    with ThreadPoolExecutor(max_workers=4) as pool:  # scope-bounded drain
        for it in items:
            pool.submit(fn, it)


def run_owned_pool(fn, items):
    from concurrent.futures import ThreadPoolExecutor

    pool = ThreadPoolExecutor(max_workers=4)
    try:
        for it in items:
            pool.submit(fn, it)
    finally:
        pool.shutdown(wait=True)  # module-wide pairing by receiver name
