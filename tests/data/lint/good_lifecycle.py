"""Near-miss clean twin of bad_lifecycle.py: the ring kernel's
start/fold/wait schedule with BOTH DMA directions drained, a plain-wait
copy, a daemon thread, and joined worker threads."""

import threading

from jax.experimental.pallas import tpu as pltpu


def kernel_paired(src, dst, sems, p):
    def copy(k):
        return pltpu.make_async_remote_copy(
            src_ref=src, dst_ref=dst, send_sem=sems[0].at[k],
            recv_sem=sems[1].at[k], device_id=k,
        )

    copy(1).start()
    for k in range(2, p):
        copy(k).start()
        copy(k - 1).wait_recv()
    copy(p - 1).wait_recv()
    for k in range(1, p):
        copy(k).wait_send()  # every DMA drained before buffer reuse


def kernel_plain_wait(src, dst, sem):
    c = pltpu.make_async_remote_copy(
        src_ref=src, dst_ref=dst, send_sem=sem, recv_sem=sem, device_id=0,
    )
    c.start()
    c.wait()


def spawn_daemon(fn):
    threading.Thread(target=fn, daemon=True).start()


def run_joined(fn, n):
    threads = [threading.Thread(target=fn) for _ in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
