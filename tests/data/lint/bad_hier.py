"""Fixture: hierarchical-exchange discipline violations (DS201/DS202 + DS301).

Models the §17 plane's two riskiest shapes: a host-topology table whose
grouping slots must stay lock-guarded with no blocking work under the
lock (the (H,H) re-plan is a device_get + NumPy reduction of the whole
measured histogram — holding the table lock across it would serialize
every concurrently-re-forming job's recovery behind one host sync), and
a shard program that must never journal its DCN accounting from inside a
traced function (the wire-byte split would become a trace-time constant
and `hier_exchange_plan` would fire once per compile, not per exchange).
"""

import threading
import time

import jax


class HostTable:
    def __init__(self):
        self._lock = threading.Lock()
        self._groupings = {}
        self._replans = []

    def park(self, hosts, plan):
        with self._lock:
            self._groupings[hosts] = plan

    def park_racy(self, hosts, plan):
        self._groupings[hosts] = plan  # DS201: guarded attribute, no lock held

    def replan_under_lock(self, reduce_hist, survivors):
        with self._lock:
            time.sleep(0.01)  # DS202: the settle delay, lock held
            return reduce_hist.wait()  # DS202: blocking (H,H) reduction under the lock


@jax.jit
def hier_shard_with_journal(xs, metrics):
    metrics.event("hier_exchange_plan", hosts=4, dcn_bytes=7)  # DS301
    t0 = time.perf_counter()  # DS301: DCN leg wall clock baked at trace
    print("leg dispatched at", t0)  # DS301
    return xs + 1
