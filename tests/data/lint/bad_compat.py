"""Fixture: compat-shim bypasses (DS501/DS502)."""

import jax
from jax.experimental.shard_map import shard_map  # DS502: raw import


def setup():
    jax.config.update("jax_enable_x64", True)  # DS501: bypasses the shim
    return shard_map
