"""Fixture: planner-plane discipline violations (DS201/DS202 + DS301).

Models the closed-loop planner's two riskiest shapes: a rolling-signal
fold (admission mix, watermark peak, loss count) whose state must stay
lock-guarded with no blocking work under the lock (the skew probe is an
O(sample log sample) host sort — holding the planner lock across it
would serialize every concurrently-dispatching job's decision behind one
probe), and a decision that must never be journaled from inside a traced
program (the measured inputs would become trace-time constants and the
``plan_decision`` would fire once per compile, not per dispatch — the
replay contract would audit a decision that never happened).
"""

import threading
import time

import jax


class PlannerState:
    def __init__(self):
        self._lock = threading.Lock()
        self._admissions = []
        self._hbm_peak = 0

    def fold(self, label):
        with self._lock:
            self._admissions.append(label)

    def fold_racy(self, label):
        self._admissions.append(label)  # DS201: guarded attribute, no lock

    def decide_under_lock(self, probe, policy):
        with self._lock:
            time.sleep(0.01)  # DS202: the probe settle, lock held
            return probe.wait()  # DS202: blocking skew probe under the lock


@jax.jit
def decide_inside_trace(x, metrics):
    metrics.event("plan_decision", policy="exchange", chosen="ring")  # DS301
    t0 = time.perf_counter()  # DS301: the probe clock baked in at trace
    print("planned at", t0)  # DS301
    return x + 1
