"""Deliberate durability-discipline violations (DS701/DS702/DS703)."""

import json
import os
import threading

import numpy as np


class BadPersist:
    def __init__(self):
        self._lock = threading.Lock()
        self.state = {}

    def save_state_torn(self, path):
        # DS701: raw write to the final path — a crash mid-write leaves a
        # torn state file where the restart path expects a whole one.
        with open(path, "w") as f:
            json.dump(self.state, f)

    def save_shard_unsynced(self, path, arr):
        tmp = path + ".tmp"
        np.save(tmp, arr)
        os.replace(tmp, path)  # DS702: rename with no fsync before it

    def bump(self):
        with self._lock:
            self.state["seq"] = self.state.get("seq", 0) + 1

    def persist_under_lock(self, path):
        # DS703 x3: snapshot AND write while holding the shared state lock
        # — disk latency serializes every other holder.
        with self._lock:
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(self.state, f)
                os.fsync(f.fileno())
            os.replace(tmp, path)
