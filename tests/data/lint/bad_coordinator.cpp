// Fixture: C++ event-vocabulary drift (DS103/DS104).
void log_event_locked(const char* type, int w, long task);

void transitions() {
  log_event_locked("fake_native_event", 1, -1);  // DS103: unregistered
  // "probe" IS in EVENT_TYPES but runtime/native.py's parser map does not
  // translate it — the drained line would be silently dropped:
  log_event_locked("probe", 1, -1);  // DS104
}
