"""Fixture: recovery-path exception-hygiene violations (DS401/DS402)."""


def resume(ckpt):
    try:
        return ckpt.load(0)
    except:  # noqa: E722  DS401: bare except
        pass


def swallow(ckpt):
    try:
        return ckpt.load(1)
    except Exception:  # DS402: swallowed, unreported
        pass
