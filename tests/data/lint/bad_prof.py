"""Fixture: introspection-plane discipline violations (DS201/DS202 + DS301).

Models the compile ledger's two riskiest shapes: a ledger class whose
entries/pending queues must stay lock-guarded with no blocking work under
the lock (an AOT compile takes SECONDS — holding the ledger lock across it
would serialize every concurrently-dispatching job), and an instrumented
stage that must never record from inside the traced function (the record
would run once, at compile time, and the "compile seconds" would be a
trace-time constant).
"""

import threading
import time

import jax


class Ledger:
    def __init__(self):
        self._lock = threading.Lock()
        self._entries = {}
        self._pending = []

    def record(self, ev):
        with self._lock:
            self._pending.append(ev)

    def record_racy(self, ev):
        self._pending.append(ev)  # DS201: guarded attribute, no lock held

    def build_under_lock(self, fn, x):
        with self._lock:
            time.sleep(0.01)  # DS202: the compile stand-in, lock held
            fn.wait()  # DS202: blocking on the build from under the lock


@jax.jit
def record_inside_trace(x, metrics):
    metrics.event("variant_compiled", variant="fused|8|int32")  # DS301
    t0 = time.perf_counter()  # DS301: the compile timer baked in at trace
    print("compiled at", t0)  # DS301
    return x + 1
