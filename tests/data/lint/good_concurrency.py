"""Fixture: near-miss twin of bad_concurrency — all discipline kept."""

import threading
import time

LOCK_A = threading.Lock()
LOCK_B = threading.Lock()
SHARED = {}


class Table:
    def __init__(self):
        self._lock = threading.Lock()
        self._state = [0]  # construction is single-threaded: not flagged
        self._scratch = []

    def guarded(self):
        with self._lock:
            self._state[0] = 1

    def unguarded_attr(self):
        self._scratch.append(1)  # never lock-guarded anywhere: not flagged

    def sleep_after_release(self):
        with self._lock:
            val = self._state[0]
        time.sleep(0.0)  # blocking AFTER the lock released: fine
        return val

    def cv_wait(self):
        cv = threading.Condition()
        with cv:
            cv.wait(timeout=0.01)  # condition pattern: wait on held object


def write_shared(key):
    with LOCK_A:
        SHARED[key] = 1


def same_order_twice():
    with LOCK_A:
        with LOCK_B:
            pass
    with LOCK_A:
        with LOCK_B:  # consistent A->B order everywhere: fine
            pass
