"""Fixture: near-miss twin of bad_registry — every shape here is clean."""


def run(metrics, journal, etype):
    metrics.bump("reassignments")  # registered counter
    metrics.event("job_done", n_keys=1)  # registered event
    journal.emit("worker_dead", worker=3)  # registered event
    metrics.event(etype, n_keys=1)  # dynamic name: runtime-guarded, not lint
    metrics.emitter("bogus_but_not_an_emit_method")  # different method name
