"""Fleet-plane tests (ISSUE 12, ARCHITECTURE §12): the framed-JSON wire
protocol, the pure (backend-free, serializable) control plane, locality/
size routing over live agents, draining and agent-loss re-routing, the
typed ``no_capacity`` verdict, the controller-restart drill (zero jobs
lost or re-dispatched, journal-asserted), the fleet observability
satellites (`dsort top` multi-URL, `dsort report` directory/glob), and
the `dsort bench --fleet-mixed` gate + BENCH_r12 artifact."""

import json
import os
import socket
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from dsort_tpu.analysis.spec import assert_conformant
from dsort_tpu.fleet import proto
from dsort_tpu.fleet.agent import FleetAgent
from dsort_tpu.fleet.controller import FleetController
from dsort_tpu.obs.merge import expand_path_args, group_rotated, merge_records
from dsort_tpu.serve.admission import ADMISSION_REASONS, AdmissionController
from dsort_tpu.serve.policy import ControlPolicy
from dsort_tpu.utils.events import COUNTERS, EVENT_TYPES, EventLog

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _sort_runner(data, metrics, job_id=None):
    metrics.event("job_done", n_keys=len(data), counters=dict(metrics.counters))
    return np.sort(data)


def _agents(*ids, runner=None, journals=None):
    out = []
    for i, aid in enumerate(ids):
        out.append(FleetAgent(
            runner=runner or _sort_runner, agent_id=aid,
            journal=journals[i] if journals else None,
        ))
    return out


def _close_all(ctl, agents):
    try:
        ctl.shutdown(drain=True, timeout=30)
    finally:
        for a in agents:
            a.close()


# -- wire protocol -----------------------------------------------------------


def test_proto_frame_round_trip():
    a, b = socket.socketpair()
    try:
        payload = np.arange(100, dtype=np.int32).tobytes()
        proto.send_frame(a, {"type": "submit", "job_id": "j1"}, payload)
        header, got = proto.recv_frame(b)
        assert header["type"] == "submit" and header["job_id"] == "j1"
        assert header["payload_len"] == len(payload) and got == payload
        proto.send_frame(b, {"type": "accepted", "job_id": "j1"})
        header, got = proto.recv_frame(a)
        assert header["type"] == "accepted" and got == b""
        a.close()
        assert proto.recv_frame(b) is None  # clean EOF at a boundary
    finally:
        for s in (a, b):
            try:
                s.close()
            except OSError:
                pass


def test_proto_rejects_bad_frames():
    with pytest.raises(proto.ProtocolError, match="unregistered"):
        proto.send_frame(None, {"type": "made_up"})
    a, b = socket.socketpair()
    try:
        # A torn frame (payload promised but the stream dies) must raise,
        # never return a short parse.
        head = json.dumps(
            {"type": "submit", "payload_len": 64}
        ).encode()
        import struct

        a.sendall(struct.pack(">I", len(head)) + head + b"short")
        a.close()
        with pytest.raises(proto.ProtocolError, match="mid-"):
            proto.recv_frame(b)
    finally:
        b.close()
    a, b = socket.socketpair()
    try:
        a.sendall(b"\x00\x00\x00\x04oops")
        with pytest.raises(proto.ProtocolError):
            proto.recv_frame(b)
    finally:
        a.close()
        b.close()


def test_encode_decode_array_round_trip():
    x = np.arange(33, dtype=np.int64)
    meta, payload = proto.encode_array(x)
    y = proto.decode_array(meta, payload)
    np.testing.assert_array_equal(x, y)
    with pytest.raises(proto.ProtocolError, match="bytes"):
        proto.decode_array(meta, payload[:-8])


def test_pure_ladder_twins_pinned():
    """The controller computes locality keys WITHOUT the backend: its pure
    twins must stay bit-equal to the jitted pipeline's originals."""
    from dsort_tpu.models.pipelines import FUSED_SMALL_JOB_MAX, pad_rung
    from dsort_tpu.obs.prof import variant_label
    from dsort_tpu.serve.variants import fused_variant_key

    assert proto.FLEET_SMALL_JOB_MAX == FUSED_SMALL_JOB_MAX
    rng = np.random.default_rng(0)
    ns = [1, 7, 8, 9, 100, 1 << 10, (1 << 16) + 3] + list(
        rng.integers(1, 1 << 22, 200)
    )
    for n in ns:
        n = int(n)
        assert proto.fused_rung(n) == pad_rung(n), n
        key = fused_variant_key(n, "int32", "auto")
        assert proto.variant_label_of_key(key) == variant_label(key), key
        assert variant_label(key).startswith(
            proto.fused_rung_prefix(n, "int32")
        )


def test_parse_agent_addrs():
    assert proto.parse_agent_addrs("a:1, b:2") == [("a", 1), ("b", 2)]
    assert proto.parse_agent_addrs([("h", 9)]) == [("h", 9)]
    with pytest.raises(ValueError, match="HOST:PORT"):
        proto.parse_agent_addrs("nocolon")
    with pytest.raises(ValueError, match="no agent"):
        proto.parse_agent_addrs("")


# -- the pure control plane --------------------------------------------------


def test_controller_imports_without_jax():
    """The §12 contract: the control plane (controller + policy + proto)
    imports and constructs in a process where importing jax RAISES."""
    code = (
        "import sys; sys.modules['jax'] = None\n"
        "from dsort_tpu.fleet.controller import FleetController\n"
        "from dsort_tpu.serve.policy import ControlPolicy\n"
        "p = ControlPolicy(); v = p.consider('t')\n"
        "assert v.admitted\n"
        "c = FleetController(['127.0.0.1:1'], heartbeat_s=60, start=False)\n"
        "assert c.stats()['agents'] == 0\n"
        "c.kill()\n"
        "print('pure-ok')\n"
    )
    r = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True,
        timeout=120, cwd=REPO,
    )
    assert r.returncode == 0, r.stderr
    assert "pure-ok" in r.stdout


def test_admission_no_capacity_ordering():
    assert "no_capacity" in ADMISSION_REASONS
    ctl = AdmissionController(max_queue_depth=1, max_tenant_inflight=1)
    v = ctl.consider("t", shutting_down=False, no_capacity=True)
    assert not v.admitted and v.reason == "no_capacity"
    # shutting_down outranks no_capacity; no_capacity outranks queue_full
    v = ctl.consider("t", shutting_down=True, no_capacity=True)
    assert v.reason == "shutting_down"
    ctl.consider("t", shutting_down=False)  # fill the queue
    v = ctl.consider("u", shutting_down=False, no_capacity=True)
    assert v.reason == "no_capacity"
    v = ctl.consider("u", shutting_down=False)
    assert v.reason == "queue_full"


def test_policy_state_round_trip_preserves_drr_order():
    """The restart contract's fairness half: a policy serialized mid-queue
    and restored pops the EXACT order the original would have."""
    def build():
        p = ControlPolicy(
            max_queue_depth=64, max_tenant_inflight=32,
            drr_quantum_keys=1000, tenant_weights={"heavy": 1.0, "vip": 2.0},
        )
        for i in range(6):
            t = ["heavy", "vip", "light"][i % 3]
            p.consider(t)
            p.push(t, 900 + i, f"j{i}")
        p.note_wait("heavy", 0.25)
        return p

    original = build()
    twin = build()
    state = json.loads(json.dumps(original.state_dict()))  # wire round trip
    restored = ControlPolicy(
        max_queue_depth=64, max_tenant_inflight=32,
        drr_quantum_keys=1000, tenant_weights={"heavy": 1.0, "vip": 2.0},
    )
    restored.load_state(state)
    assert restored.queue_depth == original.queue_depth
    assert restored.admission.tenant_inflight("vip") == 2
    seq_twin = [twin.pop() for _ in range(7)]
    seq_restored = [restored.pop() for _ in range(7)]
    assert seq_restored == seq_twin
    assert seq_restored[-1] is None


def test_policy_shed_window_survives_round_trip():
    p = ControlPolicy(slo_shed_ms=1.0)
    p.consider("t")
    p.push("t", 10, "j0")
    for _ in range(8):
        p.note_wait("t", 0.5)  # 500 ms >> 1 ms target
    assert p.should_shed("t")
    q = ControlPolicy(slo_shed_ms=1.0)
    q.load_state(json.loads(json.dumps(p.state_dict())))
    assert q.should_shed("t")


# -- routing over live agents ------------------------------------------------


def test_fleet_end_to_end_two_agents():
    journal = EventLog()
    agents = _agents("A", "B")
    ctl = FleetController(
        [a.addr for a in agents], heartbeat_s=0.2, journal=journal,
    )
    try:
        rng = np.random.default_rng(0)
        # Sequential submit->await keeps the affinity deterministic (no
        # busy-agent spill): every job of a size must land on ONE agent.
        for i in range(8):
            d = rng.integers(0, 10**6, 1000 if i % 2 else 2000, dtype=np.int32)
            v, t = ctl.submit(d, tenant=f"t{i % 2}")
            assert v.admitted
            np.testing.assert_array_equal(t.result(timeout=60), np.sort(d))
        types = [e.type for e in journal.events()]
        assert types.count("agent_register") == 2
        assert types.count("job_routed") == 8
        assert types.count("job_done") == 8
        # Locality stickiness: all jobs of one size land on ONE agent.
        by_size = {}
        for e in journal.events():
            if e.type == "job_routed":
                by_size.setdefault(e.fields["n_keys"], set()).add(
                    e.fields["agent"]
                )
        for size, used in by_size.items():
            assert len(used) == 1, f"size {size} scattered over {used}"
    finally:
        _close_all(ctl, agents)


def test_draining_agent_routes_around():
    """An agent advertising draining takes no new fleet work; jobs flow to
    the healthy agent (spill-over routing, not blocking)."""
    journal = EventLog()
    agents = _agents("A", "B")
    agents[0].drain()  # drains BEFORE the controller connects: the
    # welcome advertises it, so routing is deterministic with no sleeps
    ctl = FleetController(
        [a.addr for a in agents], heartbeat_s=0.2, journal=journal,
    )
    try:
        d = np.arange(500, dtype=np.int32)[::-1].copy()
        tickets = [ctl.submit(d, tenant="t")[1] for _ in range(3)]
        for t in tickets:
            np.testing.assert_array_equal(t.result(timeout=60), np.sort(d))
        routed = [
            e.fields["agent"] for e in journal.events()
            if e.type == "job_routed"
        ]
        assert routed and set(routed) == {"B"}
    finally:
        _close_all(ctl, agents)


def test_no_capacity_when_every_agent_drains():
    """ISSUE 12 satellite: the fleet's all-agents-draining rejection is the
    TYPED `no_capacity` verdict — journaled and counted per tenant in
    dsort_admissions_total — never a reused `queue_full`."""
    from dsort_tpu.obs import Telemetry

    journal = EventLog()
    tel = Telemetry()
    agents = _agents("A", "B")
    for a in agents:
        a.drain()
    ctl = FleetController(
        [a.addr for a in agents], heartbeat_s=0.2, journal=journal,
        telemetry=tel,
    )
    try:
        v, t = ctl.submit(np.arange(100, dtype=np.int32), tenant="acme")
        assert t is None and not v.admitted
        assert v.reason == "no_capacity"
        rej = [e for e in journal.events() if e.type == "job_rejected"]
        assert rej and rej[0].fields["reason"] == "no_capacity"
        assert tel.snapshot()["admissions"]["acme/no_capacity"] == 1
    finally:
        _close_all(ctl, agents)


def test_agent_loss_reroutes_inflight_job():
    """A dead agent's in-flight job re-enters the queue (`job_rerouted`,
    reason agent_lost) and completes on a survivor."""
    gate = threading.Event()

    def blocking_runner(data, metrics, job_id=None):
        gate.wait(60)
        return np.sort(data)

    journal = EventLog()
    a = FleetAgent(runner=blocking_runner, agent_id="A")
    b = FleetAgent(runner=_sort_runner, agent_id="B")
    ctl = FleetController(
        [a.addr, b.addr], heartbeat_s=0.2, journal=journal,
    )
    try:
        d = np.arange(777, dtype=np.int32)[::-1].copy()
        v, ticket = ctl.submit(d, tenant="t")
        assert v.admitted
        # Both agents idle -> least-loaded tie breaks on label: A wins.
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline:
            routed = [e for e in journal.events() if e.type == "job_routed"]
            if routed:
                break
            time.sleep(0.02)
        assert routed and routed[0].fields["agent"] == "A"
        threading.Thread(target=a.kill, daemon=True).start()
        np.testing.assert_array_equal(ticket.result(timeout=60), np.sort(d))
        types = [e.type for e in journal.events()]
        assert "job_rerouted" in types
        rr = next(e for e in journal.events() if e.type == "job_rerouted")
        assert rr.fields["reason"] == "agent_lost" and rr.fields["frm"] == "A"
        routed = [
            e.fields["agent"] for e in journal.events()
            if e.type == "job_routed"
        ]
        assert routed[-1] == "B"
    finally:
        gate.set()
        _close_all(ctl, [b])
        a.close(drain=False)


class _StalledAgent:
    """A stuck-but-connected agent: completes the hello/welcome handshake,
    answers pings, then swallows the first submit and never replies again.
    Accepts exactly ONE connection (reconnects fail) so the post-stall
    routing is deterministic."""

    def __init__(self, variants=()):
        self.variants = list(variants)
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind(("127.0.0.1", 0))
        self._listener.listen(1)
        self.addr = "127.0.0.1:%d" % self._listener.getsockname()[1]
        self.got_submit = threading.Event()
        self._conns = []
        threading.Thread(target=self._serve, daemon=True).start()

    def _serve(self):
        try:
            conn, _ = self._listener.accept()
        except OSError:
            return
        self._conns.append(conn)
        self._listener.close()
        try:
            while True:
                frame = proto.recv_frame(conn)
                if frame is None:
                    return
                header, _ = frame
                if header["type"] == "hello":
                    proto.send_frame(conn, {
                        "type": "welcome", "agent_id": "stall",
                        "capacity": 1, "big_jobs": False, "draining": False,
                        "variants": self.variants, "jobs": {},
                    })
                elif header["type"] == "ping" and not self.got_submit.is_set():
                    proto.send_frame(conn, {
                        "type": "heartbeat", "queued": 0, "in_flight": 0,
                        "draining": False, "variants": self.variants,
                        "capacity": 1,
                    })
                elif header["type"] == "submit":
                    self.got_submit.set()  # swallow; never reply again
        except (proto.ProtocolError, OSError):
            pass

    def close(self):
        for c in self._conns:
            try:
                c.close()
            except OSError:
                pass
        try:
            self._listener.close()
        except OSError:
            pass


def test_stalled_agent_does_not_stall_fleet_dispatch():
    """ISSUE 13 satellite (the ROADMAP-named stall): one stuck-but-
    connected agent must not stall fleet-wide dispatch.  Dispatch runs on
    per-agent lanes with a bounded per-agent send deadline
    (``dispatch_timeout_s``): the healthy agent's jobs flow immediately
    while the stalled lane waits out its deadline, and the swallowed job
    then fails over to the healthy agent."""
    journal = EventLog()
    d_stall = np.arange(1000, dtype=np.int32)[::-1].copy()
    d_ok = np.arange(2000, dtype=np.int32)[::-1].copy()
    # The stalled agent alone advertises the first job's rung: locality
    # routes that job onto it deterministically.
    stalled = _StalledAgent(
        variants=[proto.fused_rung_prefix(len(d_stall), "int32") + "lax"]
    )
    healthy = FleetAgent(runner=_sort_runner, agent_id="H")
    ctl = FleetController(
        [stalled.addr, healthy.addr],
        # A LIVE heartbeat: the health plane must not serialize behind the
        # stuck lane's request slot either (LaneBusy skip) — pings to the
        # healthy agent keep flowing throughout the stall.
        heartbeat_s=0.3,
        request_timeout_s=30,    # the OLD fleet-wide stall bound — never paid
        dispatch_timeout_s=4.0,  # the bounded per-agent send deadline
        journal=journal,
    )
    try:
        v, stuck = ctl.submit(d_stall, tenant="t")
        assert v.admitted
        assert stalled.got_submit.wait(10), "job never routed to the stall"
        # The healthy agent's jobs dispatch and complete WHILE the stalled
        # lane is still inside its send deadline — the old synchronous
        # dispatcher would have serialized them behind the stuck submit
        # for up to request_timeout_s.
        t0 = time.monotonic()
        tickets = [ctl.submit(d_ok, tenant="t")[1] for _ in range(3)]
        for t in tickets:
            np.testing.assert_array_equal(
                t.result(timeout=10), np.sort(d_ok)
            )
        healthy_took = time.monotonic() - t0
        assert healthy_took < 4.0, (
            f"healthy jobs took {healthy_took:.1f}s — dispatch stalled "
            "behind the stuck agent"
        )
        # At the deadline the stalled agent is failed over and the
        # swallowed job completes on the healthy agent.
        np.testing.assert_array_equal(
            stuck.result(timeout=30), np.sort(d_stall)
        )
        rr = [e for e in journal.events() if e.type == "job_rerouted"]
        assert rr and rr[0].fields["reason"] in (
            "dispatch_failed", "agent_lost"
        )
        # The trace is honest: the swallowed job was routed to the stall
        # first, re-routed at the deadline, and every completed dispatch
        # names the healthy agent.
        routed = [
            e.fields["agent"] for e in journal.events()
            if e.type == "job_routed"
        ]
        assert routed[0] == "stall" and routed.count("H") == 4
    finally:
        stalled.close()
        ctl.kill()
        healthy.close(drain=False)


# -- the controller-restart drill (ISSUE 12 acceptance) ----------------------


def test_controller_restart_drill(tmp_path):
    """Kill the controller with jobs queued AND in-flight on 2 agents;
    restart; assert via the MERGED journal that in-flight jobs complete
    without re-dispatch (exactly one agent-side job_start each) and the
    queued jobs drain in the persisted DRR order."""
    gate = threading.Event()

    def slow_runner(data, metrics, job_id=None):
        gate.wait(60)
        metrics.event(
            "job_done", n_keys=len(data), counters=dict(metrics.counters)
        )
        return np.sort(data)

    ja, jb = EventLog(), EventLog()
    agents = _agents("A", "B", runner=slow_runner, journals=[ja, jb])
    state_dir = str(tmp_path / "state")
    j1 = EventLog()
    ctl = FleetController(
        [a.addr for a in agents], state_dir=state_dir, heartbeat_s=0.3,
        journal=j1,
    )
    rng = np.random.default_rng(1)
    datas = []
    try:
        for i in range(6):
            d = rng.integers(0, 10**6, 400, dtype=np.int32)
            v, _ = ctl.submit(d, tenant=["acme", "blue", "coral"][i % 3])
            assert v.admitted
            datas.append(d)
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline:
            st = ctl.stats()
            if st["in_flight"] == 2 and st["queued"] == 4:
                break
            time.sleep(0.02)
        st = ctl.stats()
        assert st["in_flight"] == 2 and st["queued"] == 4, st
    finally:
        ctl.kill()  # ungraceful: no drain, no clean close

    # The persisted control plane names every job; replaying its policy
    # snapshot through a fresh ControlPolicy IS the expected DRR order.
    state = json.load(
        open(os.path.join(state_dir, "controller_state.json"))
    )
    assert {j["status"] for j in state["jobs"].values()} == {
        "inflight", "queued"
    }
    replay = ControlPolicy()
    replay.load_state(state["policy"])
    expected_order = []
    while True:
        nxt = replay.pop()
        if nxt is None:
            break
        expected_order.append(nxt[1])
    assert len(expected_order) == 4

    j2 = EventLog()
    ctl2 = FleetController(
        [a.addr for a in agents], state_dir=state_dir, heartbeat_s=0.3,
        journal=j2,
    )
    try:
        gate.set()  # release the in-flight (and then queued) jobs
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            st = ctl2.stats()
            if st["done"] + st["failed"] >= 6:
                break
            time.sleep(0.05)
        st = ctl2.stats()
        assert st["done"] == 6 and st["failed"] == 0, st
    finally:
        ctl2.shutdown(drain=True, timeout=30)
        for a in agents:
            a.close()

    merged = merge_records([
        [e.to_dict() for e in log.events()]
        for log in (j1, j2, ja, jb)
    ])
    # ZERO re-dispatch: each fleet job started exactly once on the agents.
    starts = {}
    for r in merged:
        if r["type"] == "job_start" and r["src"] in (2, 3):
            starts[r.get("job_id")] = starts.get(r.get("job_id"), 0) + 1
    assert len(starts) == 6
    assert all(v == 1 for v in starts.values()), starts
    # The restore-before-dispatch ordering is the declared
    # `controller_restore` contract (ISSUE 17): the restarted controller
    # announces itself BEFORE it dequeues or routes anything.
    report = assert_conformant(merged)
    assert report["contracts"]["controller_restore"]["checked"] == 1
    # The restart announced itself with the persisted counts.
    restore = next(r for r in merged if r["type"] == "controller_restore")
    assert restore["queued"] == 4 and restore["inflight"] == 2
    assert restore["src"] == 1
    # Nothing was re-routed (both agents survived and kept their jobs).
    assert not [r for r in merged if r["type"] == "job_rerouted"]
    # Queued jobs drained in the persisted DRR order.
    routed2 = [
        r["job_id"] for r in merged
        if r["type"] == "job_routed" and r["src"] == 1
    ]
    assert routed2 == expected_order


def test_restart_requeues_job_lost_with_its_agent(tmp_path):
    """An in-flight job whose agent never comes back is re-queued
    (`job_rerouted` reason=agent_lost) instead of waiting forever."""
    gate = threading.Event()

    def slow_runner(data, metrics, job_id=None):
        gate.wait(60)
        return np.sort(data)

    a = FleetAgent(runner=slow_runner, agent_id="A")
    b = FleetAgent(runner=_sort_runner, agent_id="B")
    state_dir = str(tmp_path / "state")
    j1 = EventLog()
    ctl = FleetController(
        [a.addr, b.addr], state_dir=state_dir, heartbeat_s=0.3, journal=j1,
    )
    d = np.arange(300, dtype=np.int32)[::-1].copy()
    try:
        v, _ = ctl.submit(d, tenant="t")
        assert v.admitted
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline:
            if ctl.stats()["in_flight"] == 1:
                break
            time.sleep(0.02)
        assert ctl.stats()["in_flight"] == 1
    finally:
        ctl.kill()
    a.kill()  # the agent dies WITH the controller
    gate.set()
    j2 = EventLog()
    ctl2 = FleetController(
        [a.addr, b.addr], state_dir=state_dir, heartbeat_s=0.3, journal=j2,
    )
    try:
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            st = ctl2.stats()
            if st["done"] + st["failed"] >= 1:
                break
            time.sleep(0.05)
        assert ctl2.stats()["done"] == 1
        types = [e.type for e in j2.events()]
        assert "controller_restore" in types and "job_rerouted" in types
        rr = next(e for e in j2.events() if e.type == "job_rerouted")
        assert rr.fields["reason"] == "agent_lost"
        assert_conformant(j2)  # restore announced before any dispatch
    finally:
        ctl2.shutdown(drain=True, timeout=30)
        b.close()


# -- observability satellites ------------------------------------------------


def _write_journal(path, types):
    log = EventLog()
    for t, fields in types:
        log.emit(t, **fields)
    log.write_jsonl(str(path))


def test_expand_path_args_directory_and_glob(tmp_path):
    d = tmp_path / "fleet"
    d.mkdir()
    _write_journal(d / "ctl.jsonl", [("job_start", {"mode": "fleet", "n_keys": 1})])
    _write_journal(d / "agent1.jsonl", [("probe", {"worker": 0, "ok": True})])
    (d / "ctl.jsonl.1").write_text(
        (d / "ctl.jsonl").read_text()
    )  # a rotation piece rides along
    got = expand_path_args([str(d)])
    assert [os.path.basename(p) for p in got] == [
        "agent1.jsonl", "ctl.jsonl", "ctl.jsonl.1"
    ]
    # Rotation pieces still collapse into their base journal downstream.
    groups = group_rotated(got)
    assert len(groups) == 2
    got = expand_path_args([str(d / "*.jsonl")])
    assert [os.path.basename(p) for p in got] == ["agent1.jsonl", "ctl.jsonl"]
    # Overlapping args never duplicate a journal into a phantom process.
    got = expand_path_args([str(d), str(d / "ctl.jsonl")])
    assert len(got) == len(set(got))
    empty = tmp_path / "empty"
    empty.mkdir()
    with pytest.raises(ValueError, match="no"):
        expand_path_args([str(empty)])
    with pytest.raises(ValueError, match="matched no"):
        expand_path_args([str(tmp_path / "nope*.jsonl")])


def test_cli_report_merges_directory(tmp_path, capsys):
    from dsort_tpu import cli

    d = tmp_path / "run"
    d.mkdir()
    _write_journal(d / "ctl.jsonl", [
        ("clock_sync", {"source": "ctl"}),
        ("job_routed", {"job_id": "f1", "agent": "A", "reason": "locality",
                        "n_keys": 10, "tenant": "t"}),
    ])
    _write_journal(d / "agent.jsonl", [
        ("clock_sync", {"source": "A"}),
        ("job_start", {"mode": "fleet", "n_keys": 10, "job_id": "f1"}),
        ("job_done", {"n_keys": 10}),
    ])
    rc = cli.main(["report", str(d)])
    out = capsys.readouterr().out
    assert rc == 0
    assert "job_routed" in out and "job_done" in out


def test_render_fleet_combines_sources():
    from dsort_tpu.obs import Telemetry
    from dsort_tpu.obs.telemetry import parse_prometheus_text
    from dsort_tpu.obs.top import render_fleet

    t1, t2 = Telemetry(), Telemetry()
    t1.admission_verdict("acme", "admitted")
    t1.admission_verdict("acme", "no_capacity")
    t2.admission_verdict("acme", "admitted")
    t1.set_gauge("variant_cache_hits", 8)
    t1.set_gauge("variant_cache_misses", 2)
    t2.set_gauge("variant_cache_hits", 2)
    t2.set_gauge("variant_cache_misses", 8)
    t1.set_gauge("queue_depth", 3)
    scrapes = [
        ("http://a/metrics", parse_prometheus_text(t1.render_prometheus())),
        ("http://b/metrics", parse_prometheus_text(t2.render_prometheus())),
    ]
    text = render_fleet(scrapes)
    assert "fleet:" in text and "http://a/metrics" in text
    # combined admissions: acme admitted 2, no_capacity 1
    assert "acme" in text and "no_capacity" in text
    # combined cache: 10 hits / 20 lookups = 50.0%
    assert "hit rate 50.0%" in text


def test_render_fleet_controller_admissions_not_double_counted():
    """With a controller among the sources (dsort_fleet_agents gauge),
    the fleet-wide admissions table sums controllers ONLY — an agent's
    local admission of a routed job mirrors the controller's and would
    double-count every fleet job."""
    from dsort_tpu.obs import Telemetry
    from dsort_tpu.obs.telemetry import parse_prometheus_text
    from dsort_tpu.obs.top import render_fleet

    ctl, agent = Telemetry(), Telemetry()
    ctl.set_gauge("fleet_agents", 1)
    ctl.admission_verdict("acme", "admitted")
    agent.admission_verdict("acme", "admitted")  # the routed job, again
    text = render_fleet([
        ("http://ctl/metrics", parse_prometheus_text(ctl.render_prometheus())),
        ("http://a1/metrics", parse_prometheus_text(agent.render_prometheus())),
    ])
    row = next(
        ln for ln in text.splitlines()
        if ln.strip().startswith("acme") and "admitted" in ln
    )
    assert row.split()[-1] == "1", row


def test_cli_top_multi_url_renders_fleet_view(capsys):
    from dsort_tpu import cli
    from dsort_tpu.obs import MetricsServer, Telemetry

    t1, t2 = Telemetry(), Telemetry()
    t1.admission_verdict("acme", "admitted")
    with MetricsServer(t1) as s1, MetricsServer(t2) as s2:
        rc = cli.main(["top", s1.url, s2.url])
    out = capsys.readouterr().out
    assert rc == 0
    assert "2/2 sources" in out and "fleet:" in out
    assert "admissions (fleet-wide):" in out
    # One dead agent must not abort the fleet view (that is exactly when
    # the operator looks): the reachable sources still render.
    with MetricsServer(t1) as s1:
        dead = f"http://127.0.0.1:1/metrics"
        rc = cli.main(["top", s1.url, dead])
    out = capsys.readouterr().out
    assert rc == 0
    assert "1/2 sources" in out and f"(unreachable: {dead})" in out


# -- registries + docs -------------------------------------------------------


def test_fleet_events_and_counters_registered():
    for etype in ("agent_register", "agent_heartbeat", "job_routed",
                  "job_rerouted", "controller_restore"):
        assert etype in EVENT_TYPES
    for counter in ("fleet_jobs_routed", "fleet_jobs_rerouted",
                    "fleet_heartbeats", "controller_restores"):
        assert counter in COUNTERS


def test_architecture_documents_fleet_plane():
    """§12's contract is test-enforced like §7-§11: frame vocabulary,
    event types, the no_capacity verdict, and the restart/re-attach
    contract all appear verbatim."""
    arch = open(os.path.join(REPO, "ARCHITECTURE.md"), encoding="utf-8").read()
    assert "## 12. Fleet plane" in arch
    for frame in proto.FRAME_TYPES:
        assert f"`{frame}`" in arch, f"frame type {frame} undocumented"
    for etype in ("agent_register", "agent_heartbeat", "job_routed",
                  "job_rerouted", "controller_restore"):
        assert f"`{etype}`" in arch, f"fleet event {etype} undocumented"
    assert "`no_capacity`" in arch
    for term in ("length-prefixed", "locality", "re-attach", "draining",
                 "ControlPolicy", "known_jobs", "state_dir"):
        assert term in arch, f"§12 must explain {term}"


def test_fleet_config_keys():
    from dsort_tpu.config import ConfigError, FleetConfig, SortConfig

    cfg = SortConfig.from_mapping({
        "FLEET_AGENTS": "h1:9200, h2:9200",
        "FLEET_STATE_DIR": "/tmp/fleet",
        "FLEET_ROUTING": "random",
        "FLEET_HEARTBEAT_S": "0.5",
        "FLEET_DISPATCH_TIMEOUT_S": "4.5",
        "FLEET_TELEMETRY": "0",
    })
    assert cfg.fleet.agents == ("h1:9200", "h2:9200")
    assert cfg.fleet.state_dir == "/tmp/fleet"
    assert cfg.fleet.routing == "random"
    assert cfg.fleet.heartbeat_s == 0.5
    assert cfg.fleet.dispatch_timeout_s == 4.5
    assert cfg.fleet.telemetry is False
    assert SortConfig.from_mapping({}).fleet.dispatch_timeout_s is None
    assert SortConfig.from_mapping({}).fleet.telemetry is True
    assert SortConfig.from_mapping({"FLEET_ROUTING": "health"}) \
        .fleet.routing == "health"
    with pytest.raises(ConfigError, match="routing"):
        FleetConfig(routing="mystery")
    with pytest.raises(ConfigError, match="heartbeat"):
        FleetConfig(heartbeat_s=0)
    with pytest.raises(ConfigError, match="dispatch_timeout"):
        FleetConfig(dispatch_timeout_s=0)
    with pytest.raises(ConfigError, match="HOST:PORT"):
        FleetConfig(agents=("nocolon",))


# -- CLI surface -------------------------------------------------------------


def test_cli_fleet_repl_two_agents(tmp_path, monkeypatch):
    """`dsort fleet --agents ...` drives the serve REPL over live agents:
    per-line tenants, sorted output files, a journaled fleet lifecycle."""
    from dsort_tpu import cli

    agents = _agents("A", "B")
    rng = np.random.default_rng(3)
    files, datas = [], []
    for i in range(3):
        d = rng.integers(0, 10**6, 700 + i * 100, dtype=np.int64)
        p = tmp_path / f"in{i}.txt"
        np.savetxt(p, d, fmt="%d")
        files.append(p)
        datas.append(d)
    journal = tmp_path / "fleet.jsonl"
    lines = iter(
        [f"tenant=acme {files[0]}", f"tenant=blue {files[1]}",
         f"tenant=acme {files[2]}", "exit"]
    )
    monkeypatch.setattr("builtins.input", lambda *_: next(lines))
    try:
        rc = cli.main([
            "fleet", "--agents", ",".join(a.addr for a in agents),
            "--state-dir", str(tmp_path / "state"),
            "-o", str(tmp_path / "out.txt"),
            "--journal", str(journal),
        ])
    finally:
        for a in agents:
            a.close()
    assert rc == 0
    records = EventLog.read_jsonl(str(journal))
    types = [r["type"] for r in records]
    assert types.count("job_routed") == 3
    assert types.count("job_done") == 3
    assert "agent_register" in types and types[-1] == "serve_stop"
    admitted = [r for r in records if r["type"] == "job_admitted"]
    assert {r["tenant"] for r in admitted} == {"acme", "blue"}
    out = np.loadtxt(tmp_path / "out.txt", dtype=np.int64)
    np.testing.assert_array_equal(out, np.sort(datas[-1]))


def test_cli_fleet_agent_flag_parse():
    """`dsort fleet` without agents fails loudly; bad routing is refused
    at the parser."""
    from dsort_tpu import cli

    with pytest.raises(SystemExit, match="--agents"):
        cli.main(["fleet"])
    with pytest.raises(SystemExit):
        cli.main(["fleet", "--agents", "h:1", "--routing", "mystery"])


def test_cli_fleet_agent_process_drains_on_sigterm(tmp_path):
    """The real `dsort fleet-agent` process: serves a routed job over TCP
    and SIGTERM-drains to exit 0 with a flushed journal."""
    import signal

    journal = tmp_path / "agent.jsonl"
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    p = subprocess.Popen(
        [sys.executable, "-m", "dsort_tpu.cli", "fleet-agent",
         "--mode", "local", "--port", "0", "--agent-id", "cliA",
         "--journal", str(journal)],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
        env=env, cwd=REPO,
    )
    try:
        line = p.stdout.readline()
        assert "listening on" in line, line
        addr = line.strip().rsplit(" ", 1)[-1]
        ctl = FleetController([addr], heartbeat_s=0.3)
        try:
            d = np.arange(1200, dtype=np.int32)[::-1].copy()
            v, ticket = ctl.submit(d, tenant="acme", job_id="cli-job")
            assert v.admitted
            np.testing.assert_array_equal(
                ticket.result(timeout=120), np.sort(d)
            )
        finally:
            ctl.shutdown(drain=True, timeout=30)
        p.send_signal(signal.SIGTERM)
        assert p.wait(timeout=60) == 0
    finally:
        if p.poll() is None:
            p.kill()
    records = EventLog.read_jsonl(str(journal))
    types = [r["type"] for r in records]
    assert "clock_sync" in types and "job_done" in types
    assert {r.get("tenant") for r in records if r["type"] == "job_admitted"} \
        == {"acme"}


# -- bench gate + artifact ---------------------------------------------------


def test_bench_fleet_mixed_gate(capsys):
    """Tier-1 gate for `make fleet-smoke`: 2 real agents over TCP behind
    the controller, locality beating random on the fleet-wide variant-
    cache hit rate, bit-identical outputs — and (ISSUE 14) the health
    arm's own row with live verdict counts plus the measured
    telemetry-vs-heartbeats-only overhead on the locality row."""
    from dsort_tpu import cli

    rc = cli.main(["bench", "--fleet-mixed", "--n", "20000", "--reps", "1"])
    out = capsys.readouterr().out
    rows = {
        r["metric"]: r for r in (
            json.loads(ln) for ln in out.splitlines() if ln.startswith("{")
        )
    }
    assert rc == 0
    row = rows["fleet_mixed_workload_2agents"]
    assert row["unit"] == "jobs/sec" and row["value"] > 0
    assert row["bit_identical"] is True
    assert row["agents"] == 2 and row["jobs"] >= 13
    assert row["cache_hit_rate"] > row["cache_hit_rate_random"]
    assert row["fairness_p95_ratio"] > 0
    assert row["rerouted"] == 0
    # Overhead is recorded at this scale, gated (<5%) on the real-scale
    # artifact (BENCH_r14.jsonl) where timing is not noise-dominated.
    assert isinstance(row["telemetry_overhead_frac"], float)
    health = rows["fleet_mixed_health_routing_2agents"]
    assert health["unit"] == "jobs/sec" and health["value"] > 0
    assert health["bit_identical"] is True
    assert health["health_verdicts"] > 0
    assert health["cache_hit_rate"] >= 0


def test_bench_r12_artifact_checks_and_compares():
    """BENCH_r12.jsonl: --check clean, the fleet row joins the trajectory
    as 'added' vs r11, and the recorded row carries the acceptance
    contract: locality > random hit rate, bit_identical, fairness inside
    the PR 7 3x bound."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "bench", os.path.join(REPO, "bench.py")
    )
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    r12 = os.path.join(REPO, "BENCH_r12.jsonl")
    assert bench.check_artifact(r12) == []
    rows = bench.compare_artifacts(os.path.join(REPO, "BENCH_r11.jsonl"), r12)
    added = {r["metric"] for r in rows if r["class"] == "added"}
    assert any(m.startswith("fleet_mixed_workload") for m in added)
    with open(r12) as f:
        lines = [json.loads(ln) for ln in f if ln.strip()]
    row = next(
        l for l in lines
        if l.get("metric", "").startswith("fleet_mixed_workload")
    )
    assert row["bit_identical"] is True
    assert row["cache_hit_rate"] > row["cache_hit_rate_random"]
    assert row["fairness_p95_ratio"] <= 3.0
    assert row["agents"] == 2
