"""Fault-tolerance tests — the verified reference behaviors from SURVEY.md §5.3
are the spec: detect-on-exchange, whole-shard retry on the first live worker,
result-slot pinning, clean failure when all workers die, per-job revival,
plus the heartbeat-timeout upgrade the reference lacks.
"""

import numpy as np
import pytest

from dsort_tpu.config import JobConfig
from dsort_tpu.data.ingest import gen_uniform
from dsort_tpu.scheduler import (
    DeviceExecutor,
    FaultInjector,
    JobFailedError,
    Scheduler,
    SpmdScheduler,
    WorkerTable,
)
from dsort_tpu.utils.metrics import Metrics

FAST = JobConfig(settle_delay_s=0.01, heartbeat_timeout_s=5.0)


def make_sched(injector=None):
    ex = DeviceExecutor(injector=injector)
    return Scheduler(ex, FAST)


def test_healthy_job():
    data = gen_uniform(10_000, seed=1)
    out = make_sched().run_job(data)
    np.testing.assert_array_equal(out, np.sort(data))


def test_one_worker_killed_before_dispatch():
    # The SURVEY.md §0 kill -9 experiment: kill worker 3 pre-dispatch; the job
    # must still complete correctly with >=1 reassignment logged.
    from dsort_tpu.utils.events import EventLog

    inj = FaultInjector()
    inj.kill(3)
    sched = make_sched(inj)
    data = gen_uniform(20_000, seed=2)
    journal = EventLog()
    m = Metrics(journal=journal)
    out = sched.run_job(data, metrics=m)
    np.testing.assert_array_equal(out, np.sort(data))
    assert m.counters["reassignments"] >= 1
    assert not sched.table.is_alive(3)
    # Fault timeline: kill-before-dispatch reads as
    # worker_dead -> reassign -> job_done, in that order.
    types = journal.types()
    assert types[0] == "job_start" and types[-1] == "job_done"
    assert types.index("worker_dead") < types.index("reassign") < types.index(
        "job_done"
    )
    dead = [e for e in journal.events() if e.type == "worker_dead"]
    assert any(e.fields["worker"] == 3 for e in dead)
    # the job_done record carries the final counters for `dsort report`
    done = journal.events()[-1]
    assert done.fields["counters"]["reassignments"] >= 1


def test_transient_failure_during_recv():
    # Reference detection actually fires at the recv stage (server.c:421-448).
    inj = FaultInjector()
    inj.fail_once(2, "recv")
    data = gen_uniform(5_000, seed=3)
    m = Metrics()
    out = make_sched(inj).run_job(data, metrics=m)
    np.testing.assert_array_equal(out, np.sort(data))
    assert m.counters["reassignments"] == 1


def test_multiple_workers_killed():
    inj = FaultInjector()
    for w in (1, 3, 5, 7):
        inj.kill(w)
    data = gen_uniform(30_000, seed=4)
    out = make_sched(inj).run_job(data)
    np.testing.assert_array_equal(out, np.sort(data))


def test_all_workers_dead_fails_cleanly_and_cluster_survives():
    inj = FaultInjector()
    ndev = DeviceExecutor().num_workers
    for w in range(ndev):
        inj.kill(w)
    sched = make_sched(inj)
    data = gen_uniform(1_000, seed=5)
    with pytest.raises(JobFailedError):
        sched.run_job(data)
    # Per-job optimistic revival (server.c:222,278): revive the processes and
    # the NEXT job on the same scheduler succeeds.
    for w in range(ndev):
        inj.revive(w)
    out = sched.run_job(data)
    np.testing.assert_array_equal(out, np.sort(data))


def test_hung_worker_detected_by_timeout():
    # The reference blocks forever on a hung worker (no heartbeat, SURVEY.md
    # §5.3); we must declare it dead and reassign.
    inj = FaultInjector()
    # Hang long enough to trip the 1 s timeout, short enough that device 0's
    # shared attempt lane drains before later tests land work on it.
    inj.hang_once(0, "sort", seconds=4.0)
    # compile_grace_s=0: CPU jits are instant, so the hang (on a cold shape,
    # where real TPU runs get a compile grace window) is detected at the
    # bare heartbeat timeout.
    job = JobConfig(settle_delay_s=0.01, heartbeat_timeout_s=1.0,
                    compile_grace_s=0.0)
    sched = Scheduler(DeviceExecutor(injector=inj), job)
    data = gen_uniform(4_000, seed=6)
    from dsort_tpu.utils.events import EventLog

    journal = EventLog()
    m = Metrics(journal=journal)
    out = sched.run_job(data, metrics=m)
    np.testing.assert_array_equal(out, np.sort(data))
    assert m.counters["heartbeat_timeouts"] >= 1
    assert not sched.table.is_alive(0)
    # Fault timeline: the hang is a heartbeat_lapse BEFORE the death record.
    types = journal.types()
    assert types.index("heartbeat_lapse") < types.index("worker_dead")
    assert types.index("worker_dead") < types.index("job_done")
    lapse = [e for e in journal.events() if e.type == "heartbeat_lapse"][0]
    assert lapse.fields["worker"] == 0


def test_cold_key_slow_compile_not_killed():
    """A first-contact stall on a (device, shape) whose budget included
    compile grace retries the SAME worker with grown windows instead of
    marking it dead — a slow Mosaic compile (observed r4: 488 s for a
    30-150 s shape) must not read as a hang.  The stall (2.5 s) outlives
    the 1.3 s cold budget but clears inside the doubled second window
    (1.3 + 2.6 = 3.9 s), so the queued retry completes from the warmed
    executable and the worker stays alive."""
    inj = FaultInjector()
    inj.hang_once(0, "sort", seconds=2.5)
    job = JobConfig(settle_delay_s=0.01, heartbeat_timeout_s=0.3,
                    compile_grace_s=1.0)
    sched = Scheduler(DeviceExecutor(injector=inj), job)
    data = gen_uniform(4_000, seed=61)
    m = Metrics()
    out = sched.run_job(data, metrics=m)
    np.testing.assert_array_equal(out, np.sort(data))
    assert m.counters["cold_wait_retries"] >= 1
    assert m.counters.get("reassignments", 0) == 0
    assert sched.table.is_alive(0)


def test_cold_key_genuine_hang_still_dies():
    """The cold-grace windows are bounded: a worker that hangs on first
    contact exhausts 1x+2x+4x the cold budget (~3.5 s here) and is then
    marked dead and reassigned like any hung worker.  The injected hang is
    6 s — past every grown window, but short enough that device 0's
    module-global attempt lane drains before later tests land work on it
    (same constraint as test_hung_worker_detected_by_timeout)."""
    inj = FaultInjector()
    inj.hang_once(0, "sort", seconds=6.0)
    job = JobConfig(settle_delay_s=0.01, heartbeat_timeout_s=0.2,
                    compile_grace_s=0.3)
    sched = Scheduler(DeviceExecutor(injector=inj), job)
    data = gen_uniform(4_000, seed=62)
    m = Metrics()
    out = sched.run_job(data, metrics=m)
    np.testing.assert_array_equal(out, np.sort(data))
    assert m.counters["cold_wait_retries"] == 2
    assert m.counters["heartbeat_timeouts"] >= 1
    assert not sched.table.is_alive(0)


def test_worker_table_first_live_linear_scan():
    t = WorkerTable(4)
    assert t.first_live() == 0
    t.mark_dead(0)
    t.mark_dead(1)
    assert t.first_live() == 2  # linear scan order, server.c:368-384
    assert t.first_live(exclude=2) == 3
    t.mark_dead(2)
    t.mark_dead(3)
    assert t.first_live() is None
    t.revive_all()
    assert t.live_workers() == [0, 1, 2, 3]


def test_spmd_scheduler_mesh_reform(mesh8):
    # SPMD path: device 2 dies -> mesh re-forms over 7 survivors -> correct.
    from dsort_tpu.utils.events import EventLog

    inj = FaultInjector()
    inj.fail_once(2, "spmd")
    sched = SpmdScheduler(job=FAST, injector=inj)
    data = gen_uniform(40_000, seed=7)
    journal = EventLog()
    m = Metrics(journal=journal)
    out = sched.sort(data, metrics=m)
    np.testing.assert_array_equal(out, np.sort(data))
    assert m.counters["mesh_reforms"] == 1
    assert len(sched.table.live_workers()) == 7
    # Fault timeline: worker_dead -> mesh_reform -> a second attempt_start
    # on the 7-device mesh -> job_done.
    types = journal.types()
    assert types[0] == "job_start" and types[-1] == "job_done"
    assert types.index("worker_dead") < types.index("mesh_reform")
    reform = [e for e in journal.events() if e.type == "mesh_reform"][0]
    assert reform.fields["survivors"] == 7
    attempts = [e for e in journal.events() if e.type == "attempt_start"]
    assert len(attempts) == 2
    assert attempts[1].fields["live"] == [i for i in range(8) if i != 2]


def test_spmd_cascading_device_loss(mesh8):
    """Two devices die in SUCCESSIVE attempts: the first loss re-forms the
    mesh over 7, the retry loses another participant and re-forms again
    over 6 — reassign-on-failure composes across re-formations (the
    reference survives repeated worker deaths the same way: every retry
    rescans liveness, ``server.c:367-401``)."""
    inj = FaultInjector()
    inj.fail_once(2, "spmd")
    inj.fail_once(5, "spmd")
    sched = SpmdScheduler(job=FAST, injector=inj)
    data = gen_uniform(50_000, seed=29)
    m = Metrics()
    out = sched.sort(data, metrics=m)
    np.testing.assert_array_equal(out, np.sort(data))
    assert m.counters["mesh_reforms"] == 2
    assert len(sched.table.live_workers()) == 6
    assert not sched.table.is_alive(2) and not sched.table.is_alive(5)


def test_spmd_scheduler_all_dead(mesh8):
    inj = FaultInjector()
    ndev = len(SpmdScheduler(job=FAST).devices)
    for i in range(ndev):
        inj.kill(i)
    sched = SpmdScheduler(job=FAST, injector=inj)
    with pytest.raises(JobFailedError):
        sched.sort(gen_uniform(100, seed=8))


def test_spmd_checkpointed_phase_recovery(mesh8, tmp_path):
    # Failure during the shuffle phase -> mesh re-forms; the local-sort
    # phase's checkpointed runs are restored instead of re-sorted
    # (SURVEY.md §7: re-run the phase from the last shard boundary).
    inj = FaultInjector()
    inj.fail_once(1, "spmd")
    job = JobConfig(
        settle_delay_s=0.01, checkpoint_dir=str(tmp_path), heartbeat_timeout_s=5.0
    )
    sched = SpmdScheduler(job=job, injector=inj)
    data = gen_uniform(30_000, seed=51)
    m = Metrics()
    out = sched.sort(data, metrics=m, job_id="spmdjob")
    np.testing.assert_array_equal(out, np.sort(data))
    assert m.counters["mesh_reforms"] == 1
    # The retry found all runs checkpointed and restored them.
    assert m.counters["spmd_phase_restores"] >= 1


def test_spmd_zipf_skew_with_injected_failure(mesh8):
    """BASELINE config #5: Zipf-skewed keys AND a device failure in one job —
    splitter quality under skew and reassign-on-failure compose."""
    from dsort_tpu.data.ingest import gen_zipf

    inj = FaultInjector()
    inj.fail_once(5, "spmd")
    sched = SpmdScheduler(job=FAST, injector=inj)
    data = gen_zipf(60_000, a=1.2, seed=13)
    m = Metrics()
    out = sched.sort(data, metrics=m)
    np.testing.assert_array_equal(out, np.sort(data))
    assert m.counters["mesh_reforms"] == 1


def test_taskpool_zipf_skew_with_kill():
    from dsort_tpu.data.ingest import gen_zipf

    inj = FaultInjector()
    inj.kill(2)
    sched = Scheduler(DeviceExecutor(injector=inj), FAST)
    data = gen_zipf(60_000, a=1.3, seed=14)
    m = Metrics()
    out = sched.run_job(data, metrics=m)
    np.testing.assert_array_equal(out, np.sort(data))
    assert m.counters.get("reassignments", 0) >= 1


# ---- real runtime errors (no injector) -> recovery (VERDICT r1 item 2) ----


def _xla_error(msg):
    from jax.errors import JaxRuntimeError

    try:
        return JaxRuntimeError(msg)
    except TypeError:  # some versions take no args; fall back to base type
        from jaxlib.xla_extension import XlaRuntimeError

        return XlaRuntimeError(msg)


def test_is_device_runtime_error_classifier():
    from dsort_tpu.scheduler.fault import is_device_runtime_error

    assert is_device_runtime_error(_xla_error("INTERNAL: device halted"))
    assert is_device_runtime_error(_xla_error("UNAVAILABLE: socket closed"))
    assert is_device_runtime_error(_xla_error("DATA_LOSS: HBM corruption"))
    # program bugs / OOM must NOT count as device death
    assert not is_device_runtime_error(_xla_error("INVALID_ARGUMENT: shape"))
    assert not is_device_runtime_error(_xla_error("RESOURCE_EXHAUSTED: OOM"))
    assert not is_device_runtime_error(ValueError("INTERNAL: not an XLA err"))


def test_taskpool_real_runtime_error_reassigns(monkeypatch):
    """A genuine XlaRuntimeError from a worker reassigns like an injected one."""
    sched = make_sched()
    real = sched.executor.sort_shard
    tripped = {}

    def flaky(worker, data):
        if worker == 1 and not tripped.get(1):
            tripped[1] = True
            raise _xla_error("INTERNAL: Failed to enqueue program")
        return real(worker, data)

    monkeypatch.setattr(sched.executor, "sort_shard", flaky)
    data = gen_uniform(10_000, seed=7)
    m = Metrics()
    out = sched.run_job(data, metrics=m)
    np.testing.assert_array_equal(out, np.sort(data))
    assert m.counters["reassignments"] == 1
    assert m.counters["device_runtime_errors"] == 1
    assert not sched.table.is_alive(1)


def test_taskpool_non_device_error_propagates(monkeypatch):
    """Program bugs must not be eaten by the fault-tolerance machinery."""
    sched = make_sched()

    def broken(worker, data):
        raise _xla_error("INVALID_ARGUMENT: bad shape in user program")

    monkeypatch.setattr(sched.executor, "sort_shard", broken)
    with pytest.raises(Exception, match="INVALID_ARGUMENT"):
        sched.run_job(gen_uniform(1_000, seed=8))


def test_spmd_real_runtime_error_device_death(monkeypatch, mesh8):
    """Runtime error + failing probe on one device -> mesh re-form, correct out."""
    from dsort_tpu.parallel.sample_sort import SampleSort

    sched = SpmdScheduler(job=JobConfig(settle_delay_s=0.01))
    real_sort = SampleSort.sort
    state = {"raised": False}

    def flaky_sort(self, data, metrics=None):
        if not state["raised"]:
            state["raised"] = True
            raise _xla_error("INTERNAL: Device 2 resets")
        return real_sort(self, data, metrics)

    monkeypatch.setattr(SampleSort, "sort", flaky_sort)
    real_probe = SpmdScheduler._probe_device
    monkeypatch.setattr(
        SpmdScheduler,
        "_probe_device",
        lambda self, idx: False if idx == 2 else real_probe(self, idx),
    )
    data = gen_uniform(50_000, seed=9)
    m = Metrics()
    out = sched.sort(data, metrics=m)
    np.testing.assert_array_equal(out, np.sort(data))
    assert m.counters["mesh_reforms"] == 1
    assert m.counters["device_runtime_errors"] == 1
    assert m.counters["device_deaths"] == 1
    assert not sched.table.is_alive(2)


def test_spmd_transient_runtime_error_retries(monkeypatch, mesh8):
    """Runtime error with every probe healthy -> bounded retry, no re-form."""
    from dsort_tpu.parallel.sample_sort import SampleSort

    sched = SpmdScheduler(job=JobConfig(settle_delay_s=0.01))
    real_sort = SampleSort.sort
    state = {"n": 0}

    def flaky_sort(self, data, metrics=None):
        state["n"] += 1
        if state["n"] == 1:
            raise _xla_error("UNAVAILABLE: relay hiccup")
        return real_sort(self, data, metrics)

    monkeypatch.setattr(SampleSort, "sort", flaky_sort)
    data = gen_uniform(50_000, seed=10)
    m = Metrics()
    out = sched.sort(data, metrics=m)
    np.testing.assert_array_equal(out, np.sort(data))
    assert m.counters["transient_retries"] == 1
    assert "mesh_reforms" not in m.counters
    assert len(sched.table.live_workers()) == 8


def test_spmd_transient_retries_exhausted(monkeypatch, mesh8):
    from dsort_tpu.parallel.sample_sort import SampleSort

    sched = SpmdScheduler(job=JobConfig(settle_delay_s=0.01, max_transient_retries=1))

    def always_fail(self, data, metrics=None):
        raise _xla_error("ABORTED: persistent but not a device death")

    monkeypatch.setattr(SampleSort, "sort", always_fail)
    with pytest.raises(Exception, match="ABORTED"):
        sched.sort(gen_uniform(10_000, seed=11))


# ---- shuffle-phase (range) checkpointing (VERDICT r1 item 6) ----


def test_spmd_shuffle_range_checkpoint_partial_loss(mesh8, tmp_path):
    """Failure AFTER the shuffle, while range 7 is read back: ranges 0..6 are
    restored from disk and only the lost key interval re-sorts."""
    inj = FaultInjector()
    job = JobConfig(
        settle_delay_s=0.01, checkpoint_dir=str(tmp_path), heartbeat_timeout_s=5.0
    )
    sched = SpmdScheduler(job=job, injector=inj)
    data = gen_uniform(40_000, seed=60)
    inj.fail_once(7, "assemble")
    from dsort_tpu.utils.events import EventLog

    journal = EventLog()
    m = Metrics(journal=journal)
    out = sched.sort(data, metrics=m, job_id="rangejob")
    np.testing.assert_array_equal(out, np.sort(data))
    assert m.counters["mesh_reforms"] == 1
    assert m.counters["shuffle_ranges_restored"] == 7  # N-1 restored
    # only the lost interval re-ran: far fewer keys than the whole job
    assert 0 < m.counters["shuffle_resort_keys"] < len(data) // 2
    # Fault timeline: persists (the 7 saved ranges) precede the death; the
    # retry's restore precedes completion.
    types = journal.types()
    assert "checkpoint_persist" in types
    assert types.index("checkpoint_persist") < types.index("worker_dead")
    restore = [e for e in journal.events() if e.type == "checkpoint_restore"]
    assert any(e.fields.get("kind") == "shuffle_ranges" for e in restore)
    assert types.index("checkpoint_restore") < types.index("job_done")


def test_spmd_shuffle_range_checkpoint_full_restore(mesh8, tmp_path):
    """A re-run of a completed job restores every range without sorting."""
    job = JobConfig(settle_delay_s=0.01, checkpoint_dir=str(tmp_path))
    sched = SpmdScheduler(job=job)
    data = gen_uniform(20_000, seed=61)
    out1 = sched.sort(data, job_id="fulljob")
    m = Metrics()
    out2 = sched.sort(data, metrics=m, job_id="fulljob")
    np.testing.assert_array_equal(out1, out2)
    assert m.counters["shuffle_phase_restores"] == 1
    assert "spmd_sort" not in m.phase_s  # no device program ran


def test_spmd_checkpoint_stale_job_id_cleared(mesh8, tmp_path):
    """Reusing a job_id with different same-length data must not serve the
    previous job's ranges (ADVICE r1: _sync_manifest-style guard)."""
    job = JobConfig(settle_delay_s=0.01, checkpoint_dir=str(tmp_path))
    sched = SpmdScheduler(job=job)
    a = gen_uniform(10_000, seed=62)
    b = gen_uniform(10_000, seed=63)
    out_a = sched.sort(a, job_id="reused")
    np.testing.assert_array_equal(out_a, np.sort(a))
    m = Metrics()
    out_b = sched.sort(b, metrics=m, job_id="reused")
    np.testing.assert_array_equal(out_b, np.sort(b))
    assert "shuffle_phase_restores" not in m.counters


def test_spmd_shuffle_resume_with_duplicate_boundary_keys(mesh8, tmp_path):
    """Boundary values duplicated across lost/kept ranges reconstruct by count."""
    rng = np.random.default_rng(64)
    data = rng.integers(0, 50, 40_000).astype(np.int32)  # heavy duplicates
    inj = FaultInjector()
    job = JobConfig(settle_delay_s=0.01, checkpoint_dir=str(tmp_path))
    sched = SpmdScheduler(job=job, injector=inj)
    inj.fail_once(4, "assemble")
    m = Metrics()
    out = sched.sort(data, metrics=m, job_id="dupjob")
    np.testing.assert_array_equal(out, np.sort(data))
    assert m.counters["shuffle_ranges_restored"] >= 1


def test_spmd_shuffle_resume_two_nonadjacent_gaps(mesh8, tmp_path):
    """Losing two non-adjacent ranges reconstructs both intervals by value."""
    from dsort_tpu.checkpoint import ShardCheckpoint

    job = JobConfig(settle_delay_s=0.01, checkpoint_dir=str(tmp_path))
    sched = SpmdScheduler(job=job)
    data = gen_uniform(40_000, seed=70)
    out1 = sched.sort(data, job_id="gapjob")
    # Simulate a partially-lost shuffle: delete ranges 2 and 5 from disk.
    ckpt = ShardCheckpoint(str(tmp_path), "gapjob")
    import os

    os.remove(ckpt._range_path(2))
    os.remove(ckpt._range_path(5))
    m = Metrics()
    out2 = sched.sort(data, metrics=m, job_id="gapjob")
    np.testing.assert_array_equal(out2, out1)
    assert m.counters["shuffle_ranges_restored"] == 6
    assert 0 < m.counters["shuffle_resort_keys"] < len(data)


# ---- ADVICE r2 fixes ----


def test_cancelled_classifies_transient():
    from dsort_tpu.scheduler.fault import (
        classify_runtime_error,
        is_device_runtime_error,
    )

    e = _xla_error("CANCELLED: sibling computation failed")
    assert classify_runtime_error(e) == "transient"
    assert not is_device_runtime_error(e)  # no longer unconditional death
    assert classify_runtime_error(_xla_error("INTERNAL: halt")) == "device"
    assert classify_runtime_error(ValueError("CANCELLED: not XLA")) is None


def test_taskpool_cancelled_retries_same_worker(monkeypatch):
    """CANCELLED retries on the same worker; it is NOT marked dead."""
    sched = make_sched()
    real = sched.executor.sort_shard
    tripped = {}

    def flaky(worker, data):
        if worker == 1 and not tripped.get(1):
            tripped[1] = True
            raise _xla_error("CANCELLED: work cancelled by sibling failure")
        return real(worker, data)

    monkeypatch.setattr(sched.executor, "sort_shard", flaky)
    data = gen_uniform(10_000, seed=21)
    m = Metrics()
    out = sched.run_job(data, metrics=m)
    np.testing.assert_array_equal(out, np.sort(data))
    assert m.counters["transient_retries"] == 1
    assert "reassignments" not in m.counters
    assert sched.table.is_alive(1)


def test_taskpool_cancelled_escalates_after_budget(monkeypatch):
    """Persistent CANCELLED on one worker escalates to reassignment."""
    sched = Scheduler(DeviceExecutor(), JobConfig(
        settle_delay_s=0.01, heartbeat_timeout_s=5.0, max_transient_retries=1
    ))
    real = sched.executor.sort_shard

    def always_cancelled(worker, data):
        if worker == 0:
            raise _xla_error("CANCELLED: persistently cancelled")
        return real(worker, data)

    monkeypatch.setattr(sched.executor, "sort_shard", always_cancelled)
    data = gen_uniform(10_000, seed=22)
    m = Metrics()
    out = sched.run_job(data, metrics=m)
    np.testing.assert_array_equal(out, np.sort(data))
    assert m.counters["transient_retries"] >= 1
    assert m.counters["reassignments"] >= 1
    assert not sched.table.is_alive(0)


def test_checkpoint_ignores_torn_tmp_files(tmp_path):
    """A crash between np.save and os.replace leaves '*.tmp.npy' files; they
    must neither crash listing nor be served as results (ADVICE r2).  Only
    STALE tmp files are swept: a fresh one may belong to a live concurrent
    writer sharing the job dir (ADVICE r3)."""
    import os
    import time

    from dsort_tpu.checkpoint import ShardCheckpoint

    ckpt = ShardCheckpoint(str(tmp_path), "torn")
    ckpt.save(0, np.arange(4, dtype=np.int32))
    ckpt.save_range(0, np.arange(4, dtype=np.int32))

    torn = ("shard_00001.npy.tmp.npy", "range_00001.npy.tmp.npy",
            "manifest.json.tmp")
    for name in torn + ("fresh_inflight.npy.tmp.npy",):
        with open(os.path.join(ckpt.dir, name), "wb") as f:
            f.write(b"torn")
    old = time.time() - ShardCheckpoint.TMP_SWEEP_AGE_S - 5
    for name in torn:  # crashed-writer leftovers are old by resume time
        os.utime(os.path.join(ckpt.dir, name), (old, old))
    assert ckpt.completed_shards() == [0]
    assert ckpt.completed_ranges() == [0]
    # a fresh handle (the next run) sweeps the stale leftovers only
    ckpt2 = ShardCheckpoint(str(tmp_path), "torn")
    left = [n for n in os.listdir(ckpt2.dir) if ".tmp" in n]
    assert left == ["fresh_inflight.npy.tmp.npy"]  # live writer untouched
    assert ckpt2.completed_shards() == [0]


def test_checkpoint_tmp_names_unique_per_writer(tmp_path):
    """Two instances sharing (root, job_id) never collide on tmp paths, so a
    concurrent writer's in-flight tmp cannot be replaced out from under it
    (ADVICE r3)."""
    from dsort_tpu.checkpoint import ShardCheckpoint

    a = ShardCheckpoint(str(tmp_path), "dup")
    b = ShardCheckpoint(str(tmp_path), "dup")
    assert a._token != b._token
    a.save(0, np.arange(8, dtype=np.int32))
    b.save(0, np.arange(8, dtype=np.int32)[::-1].copy())
    np.testing.assert_array_equal(a.load(0), np.arange(8, dtype=np.int32)[::-1])


def test_taskpool_stale_checkpoint_cleared(tmp_path):
    """Re-running `run_job` under the same job_id with DIFFERENT data must
    not serve the previous run's persisted shards (ADVICE r3: the taskpool
    path now carries the same fingerprint guard as SpmdScheduler.sort)."""
    job = JobConfig(settle_delay_s=0.01, checkpoint_dir=str(tmp_path))
    sched = Scheduler(DeviceExecutor(), job)
    a = gen_uniform(20_000, seed=81)
    out_a = sched.run_job(a, job_id="reused")
    np.testing.assert_array_equal(out_a, np.sort(a))
    # Same length, same dtype, different contents — only the fingerprint
    # distinguishes them, exactly the `dsort run FILE` re-run scenario.
    b = gen_uniform(20_000, seed=82)
    m = Metrics()
    out_b = sched.run_job(b, metrics=m, job_id="reused")
    np.testing.assert_array_equal(out_b, np.sort(b))
    assert "shards_restored" not in m.counters  # stale state was cleared


def test_taskpool_same_data_reuses_checkpoint(tmp_path):
    """The guard must not break legitimate resume: identical data under the
    same job_id still restores completed shards."""
    job = JobConfig(settle_delay_s=0.01, checkpoint_dir=str(tmp_path))
    sched = Scheduler(DeviceExecutor(), job)
    a = gen_uniform(20_000, seed=83)
    sched.run_job(a, job_id="samejob")
    m = Metrics()
    out = sched.run_job(a, metrics=m, job_id="samejob")
    np.testing.assert_array_equal(out, np.sort(a))
    assert m.counters["shards_restored"] == sched.executor.num_workers


# Bounded-wait budgets scaled for the CPU test mesh: cold attempts get a
# 2 s compile grace (shard_map compiles take ~1-2 s here), warm ones time
# out at 0.6 s.  Generous transient budget — retries queue behind the
# stalled attempt on its lane and drain once the stall clears.
HANG_FAST = JobConfig(
    settle_delay_s=0.01, heartbeat_timeout_s=0.3, compile_grace_s=2.0,
    exec_allowance_floor_s=0.3, exec_allowance_keys_per_s=1e9,
    max_transient_retries=5,
)


def test_spmd_inflight_hang_detected_and_mesh_reforms(monkeypatch, mesh8):
    """VERDICT r3 #1: a hang while the SPMD program is in flight (the
    reference's forever-block, server.c:358/421) is detected by the bounded
    wait; probes find the wedged device; the job completes on survivors."""
    import time as _time

    import dsort_tpu.parallel.sample_sort as ssmod

    orig_sort = ssmod.SampleSort.sort
    state = {"first": True}

    def hang_then_sort(self, data, metrics=None):
        if state["first"]:
            state["first"] = False
            _time.sleep(30.0)  # "forever"; runs on a daemon mesh lane
        return orig_sort(self, data, metrics)

    monkeypatch.setattr(ssmod.SampleSort, "sort", hang_then_sort)

    def fake_probe(self, idx):
        if idx == 3:
            return False  # the wedged chip fails its probe
        self.table.heartbeat(idx)
        return True

    monkeypatch.setattr(SpmdScheduler, "_probe_device", fake_probe)
    sched = SpmdScheduler(job=HANG_FAST)
    data = gen_uniform(30_000, seed=91)
    from dsort_tpu.utils.events import EventLog

    journal = EventLog()
    m = Metrics(journal=journal)
    t0 = _time.monotonic()
    out = sched.sort(data, metrics=m)
    np.testing.assert_array_equal(out, np.sort(data))
    assert _time.monotonic() - t0 < 15.0  # did NOT wait out the 30 s hang
    assert m.counters["spmd_wait_timeouts"] >= 1
    assert m.counters["mesh_reforms"] >= 1
    assert not sched.table.is_alive(3)
    # Fault timeline of the hang reap: the lapsed wait, then the probe
    # sweep pinpointing the wedged chip, then its death and the re-form.
    types = journal.types()
    assert (
        types.index("heartbeat_lapse")
        < types.index("probe")
        < types.index("worker_dead")
        < types.index("mesh_reform")
        < types.index("job_done")
    )
    probes = [e for e in journal.events() if e.type == "probe"]
    assert {p.fields["worker"] for p in probes} == set(range(8))
    assert [p.fields["ok"] for p in probes if p.fields["worker"] == 3] == [False]
    dead = [e for e in journal.events() if e.type == "worker_dead"]
    assert [e.fields["worker"] for e in dead] == [3]


def test_spmd_inflight_hang_healthy_devices_retries(mesh8):
    """A host-side stall (all probes pass) takes the bounded transient-retry
    path instead of killing healthy devices.  The retry queues behind the
    stalled attempt on the mesh lane, so it succeeds once the stall clears
    within the retry budget — hence the pre-warm (compile off the clock) and
    a stall shorter than retries x budget."""
    inj = FaultInjector()
    sched = SpmdScheduler(job=HANG_FAST, injector=inj)
    data = gen_uniform(30_000, seed=92)
    out0 = sched.sort(data)  # pre-warm: compile the SPMD program cleanly
    np.testing.assert_array_equal(out0, np.sort(data))
    inj.hang_once(0, "spmd", seconds=1.5)  # > the 0.6 s warm budget
    m = Metrics()
    out = sched.sort(data, metrics=m)
    np.testing.assert_array_equal(out, np.sort(data))
    assert m.counters["spmd_wait_timeouts"] >= 1
    assert m.counters["transient_retries"] >= 1
    assert sched.table.live_workers() == list(range(len(sched.devices)))


def test_spmd_healthy_timeout_budget_grows(mesh8):
    """Successive healthy-probe timeouts double the wait budget (boost =
    2**transient_retries): a stall longer than retries x the flat budget —
    a compile service running pathologically slow — delays the job instead
    of failing it.  With the flat budget this schedule exhausts at 3 x
    0.6 s (+ probe overhead) well before the 3.5 s stall clears; the
    geometric windows 0.6/1.2/2.4 reach ~4.2 s cumulative and the queued
    retry completes there.  The 3.5 s stall leaves ~0.85 s of slack on
    BOTH sides for probe/resubmit overhead on a loaded machine."""
    import dataclasses

    inj = FaultInjector()
    job = dataclasses.replace(HANG_FAST, max_transient_retries=2)
    sched = SpmdScheduler(job=job, injector=inj)
    data = gen_uniform(30_000, seed=93)
    out0 = sched.sort(data)  # pre-warm: compile off the clock
    np.testing.assert_array_equal(out0, np.sort(data))
    inj.hang_once(0, "spmd", seconds=3.5)
    m = Metrics()
    out = sched.sort(data, metrics=m)
    np.testing.assert_array_equal(out, np.sort(data))
    assert m.counters["transient_retries"] >= 2  # needed the grown windows
    assert sched.table.live_workers() == list(range(len(sched.devices)))


def test_probe_respects_injector(mesh8):
    """A wedged device can be modeled at the probe itself."""
    inj = FaultInjector()
    inj.fail_once(2, "probe")
    sched = SpmdScheduler(job=HANG_FAST, injector=inj)
    assert sched._probe_device(2) is False
    assert sched._probe_device(2) is True  # one-shot consumed


def test_fused_small_job_hang_falls_back_to_scheduler(monkeypatch, mesh8):
    """The fused small-job path ('dsort run' default for <2^20 keys) is
    bounded too: a hang there falls back to the SPMD scheduler."""
    import time as _time

    import dsort_tpu.models.pipelines as pmod
    from dsort_tpu import cli
    from dsort_tpu.config import SortConfig

    real = pmod.fused_sort_small
    state = {"first": True}

    def hang_once_fused(data, kernel="auto", metrics=None):
        if state["first"]:
            state["first"] = False
            _time.sleep(30.0)
        return real(data, kernel, metrics)

    monkeypatch.setattr(pmod, "fused_sort_small", hang_once_fused)
    cfg = SortConfig(job=HANG_FAST)
    sorter = cli._make_sorter(cfg, "spmd")
    data = gen_uniform(20_000, seed=93)
    m = Metrics()
    t0 = _time.monotonic()
    out = sorter(data, m)
    np.testing.assert_array_equal(out, np.sort(data))
    # fused_fallbacks (not fused_small_jobs) proves the TimeoutError path
    # fired: had the hang been waited out, the fused path would have
    # succeeded instead of falling back.
    assert _time.monotonic() - t0 < 15.0
    assert m.counters["fused_fallbacks"] == 1
    assert "fused_small_jobs" not in m.counters


def test_zombie_attempt_cannot_corrupt_checkpoint(monkeypatch, mesh8, tmp_path):
    """An abandoned attempt that wakes AFTER the re-formed mesh completed the
    job must be cancelled at its next checkpoint write, not interleave its
    stale (old mesh size) ranges/manifest with the live result."""
    import time as _time

    import dsort_tpu.parallel.sample_sort as ssmod
    from dsort_tpu.checkpoint import ShardCheckpoint

    orig = ssmod.SampleSort.sort_ranges
    state = {"first": True}

    def hang_then_ranges(self, data, metrics=None):
        if state["first"]:
            state["first"] = False
            _time.sleep(4.0)  # wakes AFTER the live attempt finished
        return orig(self, data, metrics)

    monkeypatch.setattr(ssmod.SampleSort, "sort_ranges", hang_then_ranges)

    def fake_probe(self, idx):
        if idx == 3:
            return False
        self.table.heartbeat(idx)
        return True

    monkeypatch.setattr(SpmdScheduler, "_probe_device", fake_probe)
    job = JobConfig(
        settle_delay_s=0.01, heartbeat_timeout_s=0.3, compile_grace_s=2.0,
        exec_allowance_floor_s=0.3, exec_allowance_keys_per_s=1e9,
        max_transient_retries=5, checkpoint_dir=str(tmp_path),
    )
    sched = SpmdScheduler(job=job)
    data = gen_uniform(30_000, seed=94)
    out = sched.sort(data, job_id="zombie")
    np.testing.assert_array_equal(out, np.sort(data))
    _time.sleep(4.5)  # let the zombie wake and hit its cancellation check
    ckpt = ShardCheckpoint(str(tmp_path), "zombie")
    man = ckpt.manifest()
    # 7 survivors -> 7 ranges; the zombie's 8-range layout must not exist.
    assert man["n_ranges"] == 7
    assert len(ckpt.completed_ranges()) == 7
    m2 = Metrics()
    out2 = sched.sort(data, metrics=m2, job_id="zombie")
    np.testing.assert_array_equal(out2, np.sort(data))
    assert m2.counters.get("shuffle_phase_restores") == 1  # clean full restore


def test_genuine_timeout_inside_attempt_propagates(monkeypatch, mesh8):
    """A TimeoutError raised INSIDE the program (e.g. checkpoint IO on a
    network mount) is not a lapsed bounded wait: no probes, no retries —
    it surfaces to the caller unchanged."""
    import dsort_tpu.parallel.sample_sort as ssmod

    def boom(self, data, metrics=None):
        raise TimeoutError("nfs io timed out")

    monkeypatch.setattr(ssmod.SampleSort, "sort", boom)
    sched = SpmdScheduler(job=HANG_FAST)
    m = Metrics()
    with pytest.raises(TimeoutError, match="nfs io"):
        sched.sort(gen_uniform(5_000, seed=95), metrics=m)
    assert "spmd_wait_timeouts" not in m.counters
    assert sched.table.live_workers() == list(range(len(sched.devices)))


def test_fused_path_latched_off_after_wedge(monkeypatch, mesh8):
    """A WARM fused-path wedge latches the path off (its lane thread is
    stuck forever) so later small jobs skip the fused attempt instead of
    paying a timeout each.  The path is warmed by one clean job first —
    a COLD lapse deliberately does not latch (see
    test_fused_cold_lapse_does_not_latch)."""
    import time as _time

    import dsort_tpu.models.pipelines as pmod
    from dsort_tpu import cli
    from dsort_tpu.config import SortConfig

    calls = {"n": 0}
    real = pmod.fused_sort_small

    def hang_after_first(data, kernel="auto", metrics=None):
        calls["n"] += 1
        if calls["n"] > 1:
            _time.sleep(30.0)
        return real(data, kernel, metrics)

    monkeypatch.setattr(pmod, "fused_sort_small", hang_after_first)
    cfg = SortConfig(job=HANG_FAST)
    sorter = cli._make_sorter(cfg, "spmd")
    data = gen_uniform(10_000, seed=96)
    m0 = Metrics()
    out0 = sorter(data, m0)  # clean: warms the fused (lane, size) bucket
    np.testing.assert_array_equal(out0, np.sort(data))
    assert m0.counters["fused_small_jobs"] == 1
    m1 = Metrics()
    out1 = sorter(data, m1)  # wedges on a WARM bucket -> falls back + latches
    np.testing.assert_array_equal(out1, np.sort(data))
    assert m1.counters["fused_fallbacks"] == 1
    t0 = _time.monotonic()
    m2 = Metrics()
    out2 = sorter(data, m2)  # latched: no third fused attempt, no wait
    np.testing.assert_array_equal(out2, np.sort(data))
    assert calls["n"] == 2
    assert "fused_fallbacks" not in m2.counters
    assert _time.monotonic() - t0 < 2.0  # went straight to the scheduler


def test_fused_cold_lapse_does_not_latch(monkeypatch, mesh8):
    """A COLD fused-path lapse — the first job paying a slow compile, not a
    wedged chip — falls back for that job but does NOT latch the path off:
    once the stall drains (the compile finishes and warms the executable),
    the next small job uses the fused path again."""
    import time as _time

    import dsort_tpu.models.pipelines as pmod
    from dsort_tpu import cli
    from dsort_tpu.config import SortConfig

    real = pmod.fused_sort_small
    state = {"n": 0}

    def stall_once(data, kernel="auto", metrics=None):
        state["n"] += 1
        if state["n"] == 1:
            _time.sleep(3.0)  # > the 2.6 s cold budget, drains quickly
        return real(data, kernel, metrics)

    monkeypatch.setattr(pmod, "fused_sort_small", stall_once)
    cfg = SortConfig(job=HANG_FAST)
    sorter = cli._make_sorter(cfg, "spmd")
    data = gen_uniform(10_000, seed=97)
    m1 = Metrics()
    out1 = sorter(data, m1)  # cold lapse -> fallback, NOT latched
    np.testing.assert_array_equal(out1, np.sort(data))
    assert m1.counters["fused_fallbacks"] == 1
    _time.sleep(1.0)  # let the stalled first attempt drain off the lane
    m2 = Metrics()
    out2 = sorter(data, m2)  # fused path alive again
    np.testing.assert_array_equal(out2, np.sort(data))
    assert m2.counters.get("fused_small_jobs") == 1


def test_fused_repeated_cold_lapses_latch(monkeypatch, mesh8):
    """A chip wedged on FIRST contact never warms the fused bucket, so every
    lapse stays cold and the single-lapse compile-grace exemption would
    retry forever (ADVICE r4).  The wedge discriminator is the fused LANE:
    one entry executing past the compile ceiling latches the path off —
    cold lapses alone (queued behind a possibly-still-compiling entry)
    never do, no matter how many."""
    import time as _time

    import dsort_tpu.models.pipelines as pmod
    from dsort_tpu import cli
    from dsort_tpu.config import SortConfig

    calls = {"n": 0}

    def wedge(data, kernel="auto", metrics=None):
        calls["n"] += 1
        _time.sleep(120.0)  # wedged from the very first contact

    monkeypatch.setattr(pmod, "fused_sort_small", wedge)
    cfg = SortConfig(job=HANG_FAST)
    sorter = cli._make_sorter(cfg, "spmd")
    data = gen_uniform(10_000, seed=98)
    # Leg 1 — lapses alone never latch: with the ceiling out of reach,
    # both jobs pay a cold lapse and fall back, and the path stays open
    # (this is the legitimately-slow-compile tolerance).
    monkeypatch.setattr(cli, "FUSED_COLD_WEDGE_CEILING_S", 1e9)
    for _ in range(2):
        m = Metrics()
        out = sorter(data, m)
        np.testing.assert_array_equal(out, np.sort(data))
        assert m.counters["fused_fallbacks"] == 1
    assert calls["n"] == 1  # the 2nd attempt queued behind the stuck lane
    # Leg 2 — the lane has now been inside ONE entry longer than this
    # ceiling: the next lapse reads that and latches.
    monkeypatch.setattr(cli, "FUSED_COLD_WEDGE_CEILING_S", 2.0)
    m3 = Metrics()
    out3 = sorter(data, m3)
    np.testing.assert_array_equal(out3, np.sort(data))
    assert m3.counters["fused_fallbacks"] == 1
    t0 = _time.monotonic()
    m4 = Metrics()
    out4 = sorter(data, m4)  # latched: no fused attempt, no wait
    np.testing.assert_array_equal(out4, np.sort(data))
    assert "fused_fallbacks" not in m4.counters
    assert _time.monotonic() - t0 < 2.0  # went straight to the scheduler
    # Leg 3 — the cold latch is evidence, not proof: it EXPIRES, and the
    # post-expiry retry either clears it (compile drained) or — as here,
    # lane still stuck — re-latches on that single lapse.
    monkeypatch.setattr(cli, "FUSED_COLD_RETRY_S", 0.3)
    _time.sleep(0.4)
    mr = Metrics()
    out_r = sorter(data, mr)  # retry attempt lapses cold -> re-latch
    np.testing.assert_array_equal(out_r, np.sort(data))
    assert mr.counters["fused_fallbacks"] == 1
    # Restore a long retry interval so the fresh re-latch cannot expire
    # between here and the final call.
    monkeypatch.setattr(cli, "FUSED_COLD_RETRY_S", 1800.0)
    t1 = _time.monotonic()
    mf = Metrics()
    out_f = sorter(data, mf)  # re-latched: closed again, no wait
    np.testing.assert_array_equal(out_f, np.sort(data))
    assert "fused_fallbacks" not in mf.counters
    assert _time.monotonic() - t1 < 2.0


def test_fused_fail_slow_backstop_latches(monkeypatch, mesh8):
    """A FAIL-SLOW device (each fused call errors after the wait budget but
    before the wedge ceiling) keeps the lane draining, so the lane-stuck
    discriminator never fires — the consecutive-cold-lapse backstop must
    latch the path off instead of letting every job pay a full budget."""
    import time as _time

    import dsort_tpu.models.pipelines as pmod
    from dsort_tpu import cli
    from dsort_tpu.config import SortConfig

    def fail_slow(data, kernel="auto", metrics=None):
        _time.sleep(4.0)  # outlasts the ~2.6 s cold budget, then drains

    monkeypatch.setattr(pmod, "fused_sort_small", fail_slow)
    monkeypatch.setattr(cli, "FUSED_COLD_LAPSE_BACKSTOP", 3)
    cfg = SortConfig(job=HANG_FAST)
    sorter = cli._make_sorter(cfg, "spmd")
    data = gen_uniform(10_000, seed=99)
    for _ in range(3):  # each lapses cold; the lane drains between jobs
        m = Metrics()
        out = sorter(data, m)
        np.testing.assert_array_equal(out, np.sort(data))
        assert m.counters["fused_fallbacks"] == 1
    t0 = _time.monotonic()
    mf = Metrics()
    out_f = sorter(data, mf)  # backstop latched: no attempt, no wait
    np.testing.assert_array_equal(out_f, np.sort(data))
    assert "fused_fallbacks" not in mf.counters
    assert _time.monotonic() - t0 < 2.0
    # The streak resets only on a fused SUCCESS, so the post-expiry retry
    # lapse re-latches immediately (streak still at the backstop) — one
    # budget per interval, not another full backstop run.
    monkeypatch.setattr(cli, "FUSED_COLD_RETRY_S", 0.3)
    _time.sleep(0.4)
    mr = Metrics()
    out_r = sorter(data, mr)
    np.testing.assert_array_equal(out_r, np.sort(data))
    assert mr.counters["fused_fallbacks"] == 1
    monkeypatch.setattr(cli, "FUSED_COLD_RETRY_S", 1800.0)
    t1 = _time.monotonic()
    m2 = Metrics()
    out2 = sorter(data, m2)  # re-latched on that single lapse
    np.testing.assert_array_equal(out2, np.sort(data))
    assert "fused_fallbacks" not in m2.counters
    assert _time.monotonic() - t1 < 2.0


def test_taskpool_genuine_timeout_inside_attempt_propagates(monkeypatch):
    """A TimeoutError raised INSIDE a shard attempt (e.g. IO on a network
    mount) is not a lapsed heartbeat wait: it surfaces instead of silently
    reassigning the shard (only WorkerWaitTimeout means 'worker hung')."""
    sched = make_sched()

    def boom(worker, data):
        raise TimeoutError("nfs io timed out")

    monkeypatch.setattr(sched.executor, "sort_shard", boom)
    m = Metrics()
    with pytest.raises(TimeoutError, match="nfs io"):
        sched.run_job(gen_uniform(4_000, seed=97), metrics=m)
    assert "heartbeat_timeouts" not in m.counters
    assert "reassignments" not in m.counters


def test_warm_shapes_keyed_per_device():
    """Compile grace is granted per (device, shape, dtype, kernel): warming a
    shape on worker 0 must not strip worker 1's first-attempt grace (ADVICE
    r3 — jit executables compile per device, so a worker revived for job 2
    or a shard reassigned to a fresh device still pays the full compile)."""
    job = JobConfig(settle_delay_s=0.01, heartbeat_timeout_s=1.0,
                    compile_grace_s=100.0)
    sched = Scheduler(DeviceExecutor(), job)
    shard = gen_uniform(1_000, seed=84)
    assert sched._attempt_timeout(0, shard) == pytest.approx(101.0)
    sched._attempt(0, shard)  # warms (device 0, shape, dtype, kernel)
    assert sched._attempt_timeout(0, shard) == pytest.approx(1.0)
    # same shape on a different device is still cold
    assert sched._attempt_timeout(1, shard) == pytest.approx(101.0)


def test_spmd_shuffle_resume_persists_recovery(mesh8, tmp_path):
    """After a subset re-sort, the recovered result is persisted: the NEXT
    run takes the full-restore path instead of repeating the re-sort."""
    from dsort_tpu.checkpoint import ShardCheckpoint

    job = JobConfig(settle_delay_s=0.01, checkpoint_dir=str(tmp_path))
    sched = SpmdScheduler(job=job)
    data = gen_uniform(40_000, seed=71)
    out1 = sched.sort(data, job_id="persistjob")
    ckpt = ShardCheckpoint(str(tmp_path), "persistjob")
    import os

    os.remove(ckpt._range_path(3))
    m2 = Metrics()
    out2 = sched.sort(data, metrics=m2, job_id="persistjob")
    np.testing.assert_array_equal(out2, out1)
    assert m2.counters["shuffle_resort_keys"] > 0
    m3 = Metrics()
    out3 = sched.sort(data, metrics=m3, job_id="persistjob")
    np.testing.assert_array_equal(out3, out1)
    assert m3.counters["shuffle_phase_restores"] == 1
    assert "shuffle_resort_keys" not in m3.counters


def test_attempt_threads_bounded_per_worker():
    """Hung attempts pin at most ONE thread per worker (VERDICT r2 weak #6):
    repeated hangs on the same worker serialize on its lane instead of
    spawning a new abandoned thread each time."""
    import threading

    inj = FaultInjector()
    job = JobConfig(settle_delay_s=0.01, heartbeat_timeout_s=0.5,
                    compile_grace_s=0.0)
    sched = Scheduler(DeviceExecutor(injector=inj), job)
    data = gen_uniform(4_000, seed=77)

    def lane_threads():
        return [t for t in threading.enumerate()
                if t.name.startswith("attempt-d")]

    inj.hang_once(7, "sort", seconds=3.0)
    sched.run_job(data)  # worker 7 hangs; shard reassigns; job completes
    inj.hang_once(7, "sort", seconds=3.0)
    sched.table.revive_all()
    sched.run_job(data)
    ts = lane_threads()
    # lanes are shared per DEVICE process-wide: no matter how many
    # schedulers or hangs this test session created, at most one attempt
    # thread exists per device — NOT one per hang or per scheduler
    import jax

    assert len(ts) <= len(jax.devices())
    assert all(t.daemon for t in ts)  # a hung lane never blocks process exit
    import time

    time.sleep(3.5)  # drain device 7's lane so later tests see it healthy


def test_abandoned_attempts_never_execute():
    """A queued attempt whose waiter timed out is SKIPPED when the lane
    unblocks — stale work must not re-run against later state."""
    import time

    inj = FaultInjector()
    job = JobConfig(settle_delay_s=0.01, heartbeat_timeout_s=0.4,
                    compile_grace_s=0.0)
    sched = Scheduler(DeviceExecutor(injector=inj), job)
    data = gen_uniform(4_000, seed=79)
    calls = []
    real = sched.executor.sort_shard

    def spy(worker, shard):
        calls.append(worker)
        return real(worker, shard)

    sched.executor.sort_shard = spy
    inj.hang_once(6, "sort", seconds=2.5)
    out1 = sched.run_job(data)  # worker 6's call hangs; shard reassigns
    np.testing.assert_array_equal(out1, np.sort(data))
    n_after_first = calls.count(6)
    sched.table.revive_all()
    out2 = sched.run_job(data)  # attempt queues behind the hang, abandons
    np.testing.assert_array_equal(out2, np.sort(data))
    time.sleep(3.0)  # hang clears; the abandoned entry must be skipped
    assert calls.count(6) == n_after_first  # never executed a zombie
