"""Fault-tolerance tests — the verified reference behaviors from SURVEY.md §5.3
are the spec: detect-on-exchange, whole-shard retry on the first live worker,
result-slot pinning, clean failure when all workers die, per-job revival,
plus the heartbeat-timeout upgrade the reference lacks.
"""

import numpy as np
import pytest

from dsort_tpu.config import JobConfig
from dsort_tpu.data.ingest import gen_uniform
from dsort_tpu.scheduler import (
    DeviceExecutor,
    FaultInjector,
    JobFailedError,
    Scheduler,
    SpmdScheduler,
    WorkerTable,
)
from dsort_tpu.utils.metrics import Metrics

FAST = JobConfig(settle_delay_s=0.01, heartbeat_timeout_s=5.0)


def make_sched(injector=None):
    ex = DeviceExecutor(injector=injector)
    return Scheduler(ex, FAST)


def test_healthy_job():
    data = gen_uniform(10_000, seed=1)
    out = make_sched().run_job(data)
    np.testing.assert_array_equal(out, np.sort(data))


def test_one_worker_killed_before_dispatch():
    # The SURVEY.md §0 kill -9 experiment: kill worker 3 pre-dispatch; the job
    # must still complete correctly with >=1 reassignment logged.
    inj = FaultInjector()
    inj.kill(3)
    sched = make_sched(inj)
    data = gen_uniform(20_000, seed=2)
    m = Metrics()
    out = sched.run_job(data, metrics=m)
    np.testing.assert_array_equal(out, np.sort(data))
    assert m.counters["reassignments"] >= 1
    assert not sched.table.is_alive(3)


def test_transient_failure_during_recv():
    # Reference detection actually fires at the recv stage (server.c:421-448).
    inj = FaultInjector()
    inj.fail_once(2, "recv")
    data = gen_uniform(5_000, seed=3)
    m = Metrics()
    out = make_sched(inj).run_job(data, metrics=m)
    np.testing.assert_array_equal(out, np.sort(data))
    assert m.counters["reassignments"] == 1


def test_multiple_workers_killed():
    inj = FaultInjector()
    for w in (1, 3, 5, 7):
        inj.kill(w)
    data = gen_uniform(30_000, seed=4)
    out = make_sched(inj).run_job(data)
    np.testing.assert_array_equal(out, np.sort(data))


def test_all_workers_dead_fails_cleanly_and_cluster_survives():
    inj = FaultInjector()
    ndev = DeviceExecutor().num_workers
    for w in range(ndev):
        inj.kill(w)
    sched = make_sched(inj)
    data = gen_uniform(1_000, seed=5)
    with pytest.raises(JobFailedError):
        sched.run_job(data)
    # Per-job optimistic revival (server.c:222,278): revive the processes and
    # the NEXT job on the same scheduler succeeds.
    for w in range(ndev):
        inj.revive(w)
    out = sched.run_job(data)
    np.testing.assert_array_equal(out, np.sort(data))


def test_hung_worker_detected_by_timeout():
    # The reference blocks forever on a hung worker (no heartbeat, SURVEY.md
    # §5.3); we must declare it dead and reassign.
    inj = FaultInjector()
    inj.hang_once(0, "sort", seconds=60.0)
    job = JobConfig(settle_delay_s=0.01, heartbeat_timeout_s=1.0)
    sched = Scheduler(DeviceExecutor(injector=inj), job)
    data = gen_uniform(4_000, seed=6)
    m = Metrics()
    out = sched.run_job(data, metrics=m)
    np.testing.assert_array_equal(out, np.sort(data))
    assert m.counters["heartbeat_timeouts"] >= 1
    assert not sched.table.is_alive(0)


def test_worker_table_first_live_linear_scan():
    t = WorkerTable(4)
    assert t.first_live() == 0
    t.mark_dead(0)
    t.mark_dead(1)
    assert t.first_live() == 2  # linear scan order, server.c:368-384
    assert t.first_live(exclude=2) == 3
    t.mark_dead(2)
    t.mark_dead(3)
    assert t.first_live() is None
    t.revive_all()
    assert t.live_workers() == [0, 1, 2, 3]


def test_spmd_scheduler_mesh_reform(mesh8):
    # SPMD path: device 2 dies -> mesh re-forms over 7 survivors -> correct.
    inj = FaultInjector()
    inj.fail_once(2, "spmd")
    sched = SpmdScheduler(job=FAST, injector=inj)
    data = gen_uniform(40_000, seed=7)
    m = Metrics()
    out = sched.sort(data, metrics=m)
    np.testing.assert_array_equal(out, np.sort(data))
    assert m.counters["mesh_reforms"] == 1
    assert len(sched.table.live_workers()) == 7


def test_spmd_scheduler_all_dead(mesh8):
    inj = FaultInjector()
    ndev = len(SpmdScheduler(job=FAST).devices)
    for i in range(ndev):
        inj.kill(i)
    sched = SpmdScheduler(job=FAST, injector=inj)
    with pytest.raises(JobFailedError):
        sched.sort(gen_uniform(100, seed=8))


def test_spmd_checkpointed_phase_recovery(mesh8, tmp_path):
    # Failure during the shuffle phase -> mesh re-forms; the local-sort
    # phase's checkpointed runs are restored instead of re-sorted
    # (SURVEY.md §7: re-run the phase from the last shard boundary).
    inj = FaultInjector()
    inj.fail_once(1, "spmd")
    job = JobConfig(
        settle_delay_s=0.01, checkpoint_dir=str(tmp_path), heartbeat_timeout_s=5.0
    )
    sched = SpmdScheduler(job=job, injector=inj)
    data = gen_uniform(30_000, seed=51)
    m = Metrics()
    out = sched.sort(data, metrics=m, job_id="spmdjob")
    np.testing.assert_array_equal(out, np.sort(data))
    assert m.counters["mesh_reforms"] == 1
    # The retry found all runs checkpointed and restored them.
    assert m.counters["spmd_phase_restores"] >= 1


def test_spmd_zipf_skew_with_injected_failure(mesh8):
    """BASELINE config #5: Zipf-skewed keys AND a device failure in one job —
    splitter quality under skew and reassign-on-failure compose."""
    from dsort_tpu.data.ingest import gen_zipf

    inj = FaultInjector()
    inj.fail_once(5, "spmd")
    sched = SpmdScheduler(job=FAST, injector=inj)
    data = gen_zipf(60_000, a=1.2, seed=13)
    m = Metrics()
    out = sched.sort(data, metrics=m)
    np.testing.assert_array_equal(out, np.sort(data))
    assert m.counters["mesh_reforms"] == 1


def test_taskpool_zipf_skew_with_kill():
    from dsort_tpu.data.ingest import gen_zipf

    inj = FaultInjector()
    inj.kill(2)
    sched = Scheduler(DeviceExecutor(injector=inj), FAST)
    data = gen_zipf(60_000, a=1.3, seed=14)
    m = Metrics()
    out = sched.run_job(data, metrics=m)
    np.testing.assert_array_equal(out, np.sort(data))
    assert m.counters.get("reassignments", 0) >= 1
