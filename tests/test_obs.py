"""Telemetry-plane tests (`dsort_tpu.obs`, PR 6 tentpole).

Covers the four pillars: journal aggregation (clock-aligned merge,
torn-line tolerance, multi-lane Chrome export), the live metrics endpoint
(Prometheus render + minimal-parser round trip + HTTP scrape), the
per-tenant SLO histograms (live tap == journal replay, exactly), and the
fault flight recorder (bundle schema + one drill per recovery path).  The
serve-smoke gate at the bottom is the acceptance path: `dsort serve
--metrics-port` scraped mid-session, quantiles asserted against the
journal-derived ground truth.
"""

import json
import urllib.request

import numpy as np
import pytest

from dsort_tpu.config import JobConfig
from dsort_tpu.obs import (
    BUNDLE_SCHEMA_KEYS,
    FlightRecorder,
    LatencyHistogram,
    MetricsServer,
    Telemetry,
    merge_journals,
    merge_records,
    parse_prometheus_text,
    read_journal,
    slo_from_journal,
)
from dsort_tpu.utils.events import EventLog, to_chrome_trace
from dsort_tpu.utils.metrics import Metrics

FAST = JobConfig(settle_delay_s=0.01)


# -- latency histogram -------------------------------------------------------


def test_histogram_quantile_is_upper_bound():
    h = LatencyHistogram()
    rng = np.random.default_rng(0)
    samples = rng.uniform(1e-3, 1.0, 500)
    for s in samples:
        h.observe(float(s))
    assert h.count == 500
    assert h.sum == pytest.approx(float(samples.sum()))
    for q in (0.5, 0.95, 0.99):
        exact = float(np.quantile(samples, q))
        got = h.quantile(q)
        # bucket-resolution contract: a hard upper bound, within one
        # 2^(1/4) bucket of the exact sample quantile
        assert exact <= got <= exact * 2 ** 0.5


def test_histogram_empty_and_determinism():
    assert LatencyHistogram().quantile(0.99) == 0.0
    a, b = LatencyHistogram(), LatencyHistogram()
    for v in (0.01, 0.02, 0.5, 0.5, 3.0):
        a.observe(v)
        b.observe(v)
    for q in (0.5, 0.95, 0.99):
        assert a.quantile(q) == b.quantile(q)


# -- journal merge -----------------------------------------------------------


def _journal_with(events, mono_base, wall_base):
    """Synthetic journal records: (type, dt, fields) at mono_base+dt."""
    out = []
    for seq, (etype, dt, fields) in enumerate(events):
        out.append({
            "seq": seq, "t": wall_base + dt, "mono": mono_base + dt,
            "type": etype, **fields,
        })
    return out


def test_merge_aligns_shifted_mono_bases():
    """Two journals over one wall timeline but wildly different monotonic
    bases must interleave at their true wall positions."""
    wall = 1_700_000_000.0
    a = _journal_with(
        [("job_start", 0.0, {"job": 1}), ("job_done", 0.4, {"job": 1})],
        mono_base=5.0, wall_base=wall,
    )
    b = _journal_with(
        [("clock_sync", 0.1, {"process": 1}),
         ("job_start", 0.2, {"job": 1}), ("job_done", 0.3, {"job": 1})],
        mono_base=9000.0, wall_base=wall,
    )
    merged = merge_records([a, b])
    types = [(r["src"], r["type"]) for r in merged]
    assert types == [
        (0, "job_start"), (1, "clock_sync"), (1, "job_start"),
        (1, "job_done"), (0, "job_done"),
    ]
    monos = [r["mono"] for r in merged]
    assert monos == sorted(monos)
    assert [r["seq"] for r in merged] == list(range(len(merged)))


def test_read_journal_skips_torn_lines(tmp_path):
    log = EventLog()
    log.emit("job_start", mode="spmd", n_keys=3)
    log.emit("job_done", n_keys=3)
    path = tmp_path / "j.jsonl"
    log.write_jsonl(str(path))
    with open(path, "a", encoding="utf-8") as f:
        f.write('{"seq": 99, "t": 1.0, "mono"')  # torn mid-write
        f.write("\nnot json at all\n")
        f.write('{"no_required_keys": true}\n')
        f.write('{"seq": 3, "t": "NaNish", "mono": "x", "type": "probe"}\n')
    records, skipped = read_journal(str(path))
    assert [r["type"] for r in records] == ["job_start", "job_done"]
    assert skipped == 4


def test_merge_journals_files(tmp_path):
    paths = []
    for i in range(2):
        log = EventLog()
        log.emit("job_start", mode="spmd", n_keys=1, process=i)
        log.emit("clock_sync", process=i)
        log.emit("job_done", n_keys=1)
        p = tmp_path / f"j{i}.jsonl"
        log.write_jsonl(str(p))
        paths.append(str(p))
    merged, skipped = merge_journals(paths)
    assert skipped == 0
    assert len(merged) == 6
    assert {r["src"] for r in merged} == {0, 1}
    monos = [r["mono"] for r in merged]
    assert monos == sorted(monos)


# -- chrome trace: one lane per job ------------------------------------------


def test_chrome_trace_distinct_tids_per_concurrent_job():
    """Two jobs interleaved on ONE journal get distinct tids and no
    overlapping phase spans on any one tid (satellite 4)."""
    from dsort_tpu.utils.metrics import PhaseTimer

    journal = EventLog()
    m1, m2 = Metrics(journal=journal), Metrics(journal=journal)
    t1, t2 = PhaseTimer(m1), PhaseTimer(m2)
    m1.event("job_start", mode="spmd", n_keys=10)
    with t1.phase("partition"):
        # job 2 starts and runs a phase INSIDE job 1's phase
        m2.event("job_start", mode="spmd", n_keys=20)
        with t2.phase("partition"):
            pass
        m2.event("job_done", n_keys=20)
    m1.event("job_done", n_keys=10)

    trace = to_chrome_trace([e.to_dict() for e in journal.events()])
    evs = [e for e in trace["traceEvents"] if e["ph"] in ("B", "E", "i")]
    tids = {e["tid"] for e in evs}
    assert len(tids) == 2  # one lane per job
    # per tid: spans nest properly and never interleave with the other job
    for tid in tids:
        depth = 0
        for e in evs:
            if e["tid"] != tid:
                continue
            if e["ph"] == "B":
                depth += 1
            elif e["ph"] == "E":
                depth -= 1
                assert depth >= 0
        assert depth == 0
    # thread_name metadata names each job lane
    meta = [e for e in trace["traceEvents"] if e["ph"] == "M"]
    assert len(meta) == 2


def test_chrome_trace_merged_sources_get_pids(tmp_path):
    logs = []
    for i in range(2):
        log = EventLog()
        m = Metrics(journal=log)
        m.event("job_start", mode="multihost", n_keys=1, process=i)
        m.event("job_done", n_keys=1)
        logs.append([e.to_dict() for e in log.events()])
    merged = merge_records(logs)
    trace = to_chrome_trace(merged)
    pids = {e["pid"] for e in trace["traceEvents"]}
    assert pids == {1, 2}


# -- telemetry registry + endpooint ------------------------------------------


def _run_jobs_with_telemetry(mesh8, tenant="acme", jobs=3):
    """Real SPMD jobs through one journal + one telemetry registry."""
    from dsort_tpu.scheduler import SpmdScheduler

    tel = Telemetry()
    journal = EventLog()
    sched = SpmdScheduler(
        job=JobConfig(settle_delay_s=0.01, tenant=tenant), telemetry=tel
    )
    rng = np.random.default_rng(1)
    for i in range(jobs):
        m = Metrics(journal=journal)
        out = sched.sort(rng.integers(0, 10**6, 20_000).astype(np.int32), m)
        assert (np.diff(out) >= 0).all()
        m.event("result_fetch", n_keys=len(out))
    return tel, journal


def test_telemetry_slo_matches_journal_ground_truth(mesh8):
    """The core SLO contract: the LIVE tap and a post-hoc journal replay
    report byte-identical per-tenant quantiles (same stamps, same
    histogram)."""
    tel, journal = _run_jobs_with_telemetry(mesh8)
    parsed = parse_prometheus_text(tel.render_prometheus())
    truth = slo_from_journal([e.to_dict() for e in journal.events()])
    assert truth, "journal must derive SLO histograms"
    for (tenant, stage), hist in truth.items():
        assert tenant == "acme"
        for q in (0.5, 0.95, 0.99):
            key = (
                "dsort_job_stage_seconds",
                tuple(sorted({
                    "tenant": tenant, "stage": stage, "quantile": str(q),
                }.items())),
            )
            assert parsed[key] == pytest.approx(hist.quantile(q), rel=1e-5), (
                f"scrape vs journal mismatch for {tenant}/{stage} p{q}"
            )
        count_key = (
            "dsort_job_stage_seconds_count",
            tuple(sorted({"tenant": tenant, "stage": stage}.items())),
        )
        assert parsed[count_key] == hist.count
    # all four stages observed (dispatch from attempt_start, fetch from
    # result_fetch)
    stages = {s for (_, s) in truth}
    assert stages == {
        "admit_to_dispatch", "dispatch_to_sorted", "sorted_to_fetched",
        "admit_to_sorted",
    }


def test_telemetry_counters_and_jobs(mesh8):
    tel, journal = _run_jobs_with_telemetry(mesh8, jobs=2)
    parsed = parse_prometheus_text(tel.render_prometheus())
    assert parsed[("dsort_jobs_total",
                   (("outcome", "done"), ("tenant", "acme")))] == 2
    assert parsed[("dsort_jobs_in_flight", ())] == 0
    assert parsed[("dsort_queue_depth", ())] == 0
    # every registered counter renders (zero-valued included)
    from dsort_tpu.utils.events import COUNTERS

    names = {
        dict(labels)["name"]
        for (name, labels) in parsed
        if name == "dsort_counter_total"
    }
    assert set(COUNTERS) <= names
    # phase wall time flowed through phase_end events
    assert any(name == "dsort_phase_seconds_total" for name, _ in parsed)


def test_telemetry_counter_deltas_not_double_counted():
    """job_done carries CUMULATIVE counters; two job_done events on one
    Metrics must absorb deltas, not re-add the running total."""
    tel = Telemetry()
    m = Metrics()
    tel.attach(m)
    tel.attach(m)  # idempotent
    assert len(m.taps) == 1
    m.bump("mesh_reforms")
    m.event("job_start", mode="spmd", n_keys=1)
    m.event("job_done", n_keys=1, counters=dict(m.counters))
    m.bump("mesh_reforms")
    m.event("job_start", mode="spmd", n_keys=1)
    m.event("job_done", n_keys=1, counters=dict(m.counters))
    snap = tel.snapshot()
    assert snap["counters"]["mesh_reforms"] == 2  # not 1 + 2 = 3


def test_metrics_server_scrape_roundtrip():
    tel = Telemetry()
    tel.observe_stage("default", "admit_to_sorted", 0.05)
    tel.set_gauge("queue_depth", 4)
    with MetricsServer(tel, port=0) as srv:
        body = urllib.request.urlopen(srv.url, timeout=10).read().decode()
        parsed = parse_prometheus_text(body)
        assert parsed[("dsort_queue_depth", ())] == 4
        js = json.loads(
            urllib.request.urlopen(
                srv.url.replace("/metrics", "/json"), timeout=10
            ).read().decode()
        )
        assert js["gauges"]["queue_depth"] == 4
        ok = urllib.request.urlopen(
            srv.url.replace("/metrics", "/healthz"), timeout=10
        )
        assert ok.read() == b"ok\n"
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(
                srv.url.replace("/metrics", "/nope"), timeout=10
            )


def test_parse_prometheus_rejects_garbage():
    with pytest.raises(ValueError):
        parse_prometheus_text("dsort_counter_total{name=unquoted} 1\n")
    with pytest.raises(ValueError):
        parse_prometheus_text("just words\n")


def test_dsort_top_renders_scrape(capsys):
    from dsort_tpu import cli

    tel = Telemetry()
    tel.observe_stage("acme", "admit_to_sorted", 0.02)
    tel.set_gauge("queue_depth", 1)
    with MetricsServer(tel, port=0) as srv:
        assert cli.main(["top", srv.url]) == 0
    out = capsys.readouterr().out
    assert "jobs in flight" in out and "queue depth: 1" in out
    assert "acme/admit_to_sorted" in out and "p95" in out


def test_dsort_top_unreachable_endpoint_fails_loudly():
    from dsort_tpu import cli

    assert cli.main(["top", "http://127.0.0.1:1/metrics"]) == 1


# -- flight recorder ---------------------------------------------------------


def test_flight_recorder_ring_and_bundle_schema(tmp_path):
    rec = FlightRecorder(
        str(tmp_path), ring_size=4, state_fn=lambda: {"mode": "unit"},
        config=FAST,
    )
    m = Metrics()
    rec.attach(m)
    rec.attach(m)  # idempotent
    assert m.taps.count(rec) == 1
    for i in range(10):
        m.event("probe", worker=i, ok=True)
    assert len(rec.events()) == 4  # bounded ring
    m.event("mesh_reform", survivors=7)
    bundles = FlightRecorder.read_bundles(str(tmp_path))
    assert len(bundles) == 1
    b = bundles[0]
    assert set(BUNDLE_SCHEMA_KEYS) <= set(b)
    assert b["recovery_path"] == "mesh_reform"
    assert b["detail"]["survivors"] == 7
    assert b["state"] == {"mode": "unit"}
    assert b["config"]["settle_delay_s"] == 0.01
    # the ring carries the recent past INCLUDING the trigger
    assert b["ring"][-1]["type"] == "mesh_reform"
    assert any(r["type"] == "probe" for r in b["ring"])
    # the dump itself is journaled + counted
    assert m.counters["flight_dumps"] == 1


def test_flight_bundle_schema_documented():
    """ARCHITECTURE documents the bundle format; the schema keys are the
    contract, so each must appear there verbatim (satellite: test-enforced
    bundle schema)."""
    import os

    arch = open(
        os.path.join(os.path.dirname(os.path.dirname(__file__)),
                     "ARCHITECTURE.md"),
        encoding="utf-8",
    ).read()
    for key in BUNDLE_SCHEMA_KEYS:
        assert f'"{key}"' in arch, (
            f"bundle key {key!r} missing from ARCHITECTURE.md §observability"
        )


# -- flight drills: one bundle per recovery path -----------------------------


def _bundles(d):
    return FlightRecorder.read_bundles(str(d))


def test_flight_drill_mesh_reform(mesh8, tmp_path):
    from dsort_tpu.scheduler import FaultInjector, SpmdScheduler

    inj = FaultInjector()
    inj.fail_once(2, "spmd")
    sched = SpmdScheduler(
        job=JobConfig(settle_delay_s=0.01, flight_recorder_dir=str(tmp_path)),
        injector=inj,
    )
    data = np.random.default_rng(2).integers(0, 10**6, 50_000).astype(np.int32)
    m = Metrics()
    out = sched.sort(data, m)
    np.testing.assert_array_equal(out, np.sort(data))
    paths = [b["recovery_path"] for b in _bundles(tmp_path)]
    assert "mesh_reform" in paths
    b = next(b for b in _bundles(tmp_path) if b["recovery_path"] == "mesh_reform")
    # names the cost: 7 survivors, and the counters snapshot carries the
    # re-form count at dump time
    assert b["detail"]["survivors"] == 7
    assert b["counters"].get("mesh_reforms", 0) >= 1
    assert any(
        r["type"] == "worker_dead" and r.get("worker") == 2 for r in b["ring"]
    )
    assert b["state"]["mode"] == "spmd"


def test_flight_drill_capacity_retry(mesh8, tmp_path):
    from dsort_tpu.scheduler import SpmdScheduler

    sched = SpmdScheduler(
        job=JobConfig(
            settle_delay_s=0.01, capacity_factor=1.0,
            flight_recorder_dir=str(tmp_path),
        ),
    )
    data = np.full(40_000, 7, np.int32)  # one bucket takes everything
    out = sched.sort(data, Metrics())
    np.testing.assert_array_equal(out, data)
    b = next(
        b for b in _bundles(tmp_path)
        if b["recovery_path"] == "capacity_retry"
    )
    assert b["detail"]["observed"] > 0 and b["detail"]["cap_pair"] > 0


def test_flight_drill_taskpool_reassign(tmp_path):
    from dsort_tpu.scheduler import DeviceExecutor, FaultInjector, Scheduler

    inj = FaultInjector()
    inj.fail_once(1, "sort")
    sched = Scheduler(
        DeviceExecutor(injector=inj),
        JobConfig(settle_delay_s=0.01, flight_recorder_dir=str(tmp_path)),
    )
    data = np.random.default_rng(3).integers(0, 10**6, 8_000).astype(np.int32)
    out = sched.run_job(data, Metrics())
    np.testing.assert_array_equal(out, np.sort(data))
    b = next(
        b for b in _bundles(tmp_path) if b["recovery_path"] == "reassign"
    )
    assert b["detail"]["frm"] == 1  # the dead worker the shard moved off
    assert b["state"]["mode"] == "taskpool"
    assert b["counters"].get("reassignments", 0) >= 1


def test_flight_drill_mid_ring_loss(mesh8, tmp_path):
    from dsort_tpu.scheduler import FaultInjector, SpmdScheduler

    inj = FaultInjector()
    inj.fail_once(3, "ring")
    sched = SpmdScheduler(
        job=JobConfig(
            settle_delay_s=0.01, exchange="ring",
            flight_recorder_dir=str(tmp_path),
        ),
        injector=inj,
    )
    data = np.random.default_rng(4).integers(0, 10**6, 50_000).astype(np.int32)
    out = sched.sort(data, Metrics())
    np.testing.assert_array_equal(out, np.sort(data))
    b = next(
        b for b in _bundles(tmp_path) if b["recovery_path"] == "mesh_reform"
    )
    # the ring names WHERE the loss happened: mid-ring, not dispatch
    assert any(
        r["type"] == "worker_dead" and r.get("stage") == "ring"
        for r in b["ring"]
    )


def test_flight_drill_handle_invalidation(mesh8, tmp_path):
    from dsort_tpu.scheduler import FaultInjector, SpmdScheduler

    inj = FaultInjector()
    sched = SpmdScheduler(
        job=JobConfig(settle_delay_s=0.01, flight_recorder_dir=str(tmp_path)),
        injector=inj,
    )
    data = np.random.default_rng(5).integers(0, 10**6, 50_000).astype(np.int32)
    m = Metrics()
    handle = sched.sort(data, m, keep_on_device=True)
    inj.fail_once(2, "spmd")
    sched.sort(data, m)  # second job loses a device -> re-form -> invalidate
    np.testing.assert_array_equal(handle.to_host(), np.sort(data))  # re-runs
    b = next(
        b for b in _bundles(tmp_path)
        if b["recovery_path"] == "device_handle_invalidated"
    )
    assert b["detail"]["reason"] == "mesh_reform"
    assert b["detail"]["n"] == 1


def test_flight_drill_checkpoint_restore(mesh8, tmp_path):
    from dsort_tpu.scheduler import SpmdScheduler

    job = JobConfig(
        settle_delay_s=0.01,
        checkpoint_dir=str(tmp_path / "ck"),
        flight_recorder_dir=str(tmp_path / "flight"),
    )
    data = np.random.default_rng(6).integers(0, 10**6, 30_000).astype(np.int32)
    SpmdScheduler(job=job).sort(data, Metrics(), job_id="j1")
    # a fresh scheduler resumes the persisted job: the restore IS the
    # recovery path the recorder must name
    out = SpmdScheduler(job=job).sort(data, Metrics(), job_id="j1")
    np.testing.assert_array_equal(out, np.sort(data))
    restores = [
        b for b in _bundles(tmp_path / "flight")
        if b["recovery_path"].startswith("checkpoint_restore")
    ]
    assert restores, "restore run must dump a bundle naming the resume path"
    assert any(
        b["recovery_path"] == "checkpoint_restore:shuffle_phase"
        for b in restores
    )


# -- the acceptance path: serve smoke + scrape vs journal --------------------


def _free_port() -> int:
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_serve_metrics_endpoint_smoke(tmp_path, monkeypatch):
    """Tier-1 gate (satellite 6 + acceptance): `dsort serve` under the
    in-suite smoke exposes a scrape-able endpoint whose Prometheus text
    round-trips the minimal parser and whose per-tenant p50/p95/p99 equal
    the journal-derived ground truth."""
    from dsort_tpu import cli

    rng = np.random.default_rng(7)
    files = []
    for i in range(3):
        p = tmp_path / f"in{i}.txt"
        p.write_text(
            "\n".join(str(x) for x in rng.integers(0, 10**6, 2000 + 500 * i))
        )
        files.append(str(p))
    journal = tmp_path / "serve.jsonl"
    port = _free_port()
    scraped = {}

    feed = iter(files)

    def fake_input(prompt=""):
        try:
            return next(feed)
        except StopIteration:
            # all jobs done, server still up: THE mid-session scrape
            url = f"http://127.0.0.1:{port}/metrics"
            scraped["text"] = urllib.request.urlopen(
                url, timeout=10
            ).read().decode()
            return "exit"

    monkeypatch.setattr("builtins.input", fake_input)
    rc = cli.main([
        "serve", "-o", str(tmp_path / "out.txt"), "--mode", "local",
        "--journal", str(journal), "--tenant", "acme",
        "--metrics-port", str(port),
    ])
    assert rc == 0
    assert scraped, "the scrape must have happened while serve was alive"
    parsed = parse_prometheus_text(scraped["text"])  # round-trips

    records, skipped = read_journal(str(journal))
    assert skipped == 0
    truth = slo_from_journal(records)
    tenants = {t for (t, _) in truth}
    assert tenants == {"acme"}
    for (tenant, stage), hist in truth.items():
        for q in (0.5, 0.95, 0.99):
            key = (
                "dsort_job_stage_seconds",
                tuple(sorted({
                    "tenant": tenant, "stage": stage, "quantile": str(q),
                }.items())),
            )
            assert parsed[key] == pytest.approx(hist.quantile(q), rel=1e-5)
    assert parsed[("dsort_jobs_total",
                   (("outcome", "done"), ("tenant", "acme")))] == 3
    # the serve session's phase wall time reached the endpoint too
    assert any(
        name == "dsort_phase_seconds_total" for (name, _) in parsed
    )


def test_failed_job_closes_on_telemetry(tmp_path):
    """A sorter that raises AFTER job_start must not leave the job open:
    `_run_one` closes it with job_failed, so jobs_in_flight returns to 0
    and the journal records the failure (code-review r6 fix)."""
    from dsort_tpu import cli

    inp = tmp_path / "in.txt"
    inp.write_text("3\n1\n2\n")
    journal = EventLog()
    tel = Telemetry()

    def exploding_sorter(data, metrics, job_id=None):
        metrics.event("job_start", mode="spmd", n_keys=len(data))
        raise OSError("disk full mid-checkpoint")

    with pytest.raises(OSError):
        cli._run_one(
            exploding_sorter, str(inp), str(tmp_path / "out.txt"),
            np.int32, journal=journal, telemetry=tel,
        )
    types = journal.types()
    assert types[0] == "job_start" and types[-1] == "job_failed"
    snap = tel.snapshot()
    assert snap["jobs_in_flight"] == 0
    assert snap["jobs"] == {"default/failed": 1}


def test_histogram_overflow_bucket_reports_observed_max():
    """Durations past the last bucket bound must not silently cap the
    quantile at the bound — the observed max is the only honest answer."""
    from dsort_tpu.obs.histogram import BUCKET_BOUNDS

    h = LatencyHistogram()
    h.observe(BUCKET_BOUNDS[-1] * 10)
    assert h.quantile(0.99) == BUCKET_BOUNDS[-1] * 10
    # a day-long job is within the bounded range (admission-control SLOs)
    assert BUCKET_BOUNDS[-1] > 24 * 3600


def test_read_bundles_orders_by_dump_time(tmp_path):
    """Bundles from several processes in one directory read back in
    wall-clock dump order, not pid-grouped filename order."""
    for name, t in (
        ("flight_900_0001_reassign.json", 3.0),
        ("flight_100_0001_mesh_reform.json", 2.0),
        ("flight_500_0001_capacity_retry.json", 1.0),
    ):
        (tmp_path / name).write_text(json.dumps({"t": t, "recovery_path": "x"}))
    got = [b["t"] for b in FlightRecorder.read_bundles(str(tmp_path))]
    assert got == [1.0, 2.0, 3.0]


def test_report_merge_cli(tmp_path, capsys):
    """`dsort report --merge a b` renders ONE aligned timeline and exports
    a multi-lane chrome trace; torn lines are skipped, not fatal."""
    from dsort_tpu import cli

    paths = []
    for i in range(2):
        log = EventLog()
        m = Metrics(journal=log)
        m.event("job_start", mode="multihost", n_keys=5, process=i)
        m.event("clock_sync", process=i)
        m.event("job_done", n_keys=5)
        p = tmp_path / f"p{i}.jsonl"
        log.write_jsonl(str(p))
        paths.append(str(p))
    with open(paths[1], "a", encoding="utf-8") as f:
        f.write('{"torn line\n')
    trace = tmp_path / "trace.json"
    rc = cli.main(
        ["report", "--merge", *paths, "--chrome-trace", str(trace)]
    )
    assert rc == 0
    out = capsys.readouterr().out
    assert "timeline:" in out
    assert out.count("job_start") >= 2  # both processes' jobs, one report
    loaded = json.loads(trace.read_text())
    assert {e["pid"] for e in loaded["traceEvents"]} == {1, 2}
