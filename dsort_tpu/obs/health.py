"""Live fleet health plane: streaming deltas -> rolling why-slow verdicts.

The PR 9 analyzer (`obs.analyze`) answers *why slow* only after the run,
by replaying a journal; the fleet controller routed jobs with no view of
which mesh was currently slow (ROADMAP item 1's named remainder).  This
module is the STREAMING counterpart:

- **`HealthDeltaCollector`** (agent side): a `Metrics` event tap — the
  same tap protocol as `obs.telemetry._TelemetryTap` — that accumulates
  the analyzer's inputs as BOUNDED deltas: per-phase wall seconds
  (``phase_end``), queue waits (``job_dequeued``), compile events
  (``variant_compiled``), the worst skew report and the high-water HBM
  watermark.  `drain()` returns one delta dict and resets; the fleet
  agent ships it as a ``telemetry`` frame on the heartbeat cadence (and
  with each result).  Exactness contract: the *running sums* (phase
  seconds, ``wait_s_sum``, ``compile_s_sum``) are scalars and survive any
  frame-budget eviction — only the auxiliary sample windows are lossy.

- **`HealthAnalyzer`** (controller side): folds deltas into rolling
  per-agent verdicts sharing `obs.analyze.VERDICT_KEYS` vocabulary
  (``dominant_phase``, ``straggler``, ``splits``, ``skew``, ``hbm`` are
  spelled — and computed — the same way), so a LIVE verdict and a replay
  of the same agent's journal through `obs.analyze.analyze_records` are
  comparable by construction (the live==replay drill in
  ``tests/test_health.py`` pins it).  Verdict per agent: straggler score
  vs fleet-mean busy time, dominant phase, queue/compile/execute split,
  SLO-breach risk (rolling p95 queue wait vs target), and the
  ``degraded`` bit the controller's ``routing="health"`` arm and the
  degraded->flight-bundle contract key on.
"""

from __future__ import annotations

import threading
from collections import deque

from dsort_tpu.obs.analyze import VERDICT_KEYS

#: Per-agent verdict keys (schema, test-enforced against ARCHITECTURE
#: §13).  The ones the replay analyzer also reports are spelled
#: identically (`SHARED_VERDICT_KEYS` must stay a subset of
#: `obs.analyze.VERDICT_KEYS` — test-pinned), so live and post-hoc
#: verdicts are comparable field by field.
HEALTH_VERDICT_KEYS = (
    "agent",
    "busy_s",
    "score",
    "straggler",
    "dominant_phase",
    "splits",
    "skew",
    "hbm",
    "slo_risk",
    "degraded",
    "seq",
)

#: The vocabulary shared with the replay analyzer, by construction.
SHARED_VERDICT_KEYS = tuple(
    k for k in HEALTH_VERDICT_KEYS if k in VERDICT_KEYS
)
assert SHARED_VERDICT_KEYS == (
    "straggler", "dominant_phase", "splits", "skew", "hbm",
)

#: Bounds on the collector's sample windows (NOT on the exact sums).
WAIT_WINDOW = 64
COMPILE_WINDOW = 32


class _HealthSums:
    """The EXACT running sums both ends of the stream accumulate — one
    copy of the merge rule (`merge_delta`), shared by the collector's
    failed-send `restore` and the analyzer's `_AgentHealth.fold`, so the
    two sides can never desynchronize field by field."""

    def __init__(self):
        self.phase_s: dict[str, float] = {}
        self.wait_sum = 0.0
        self.wait_count = 0
        self.compile_sum = 0.0
        self.compile_count = 0
        self.skew: dict | None = None
        self.hbm: dict | None = None
        self.jobs_done = 0
        self.jobs_failed = 0

    def merge_delta(self, delta: dict) -> None:
        """Fold one delta dict's sums in: phase seconds and wait/compile
        sums ADD (exactness), skew/HBM take the worst, job counts add."""
        for phase, sec in dict(delta.get("phases") or {}).items():
            if isinstance(sec, (int, float)):
                self.phase_s[str(phase)] = (
                    self.phase_s.get(str(phase), 0.0) + float(sec)
                )
        self.wait_sum += float(delta.get("wait_s_sum", 0.0) or 0.0)
        self.wait_count += int(delta.get("wait_count", 0) or 0)
        self.compile_sum += float(delta.get("compile_s_sum", 0.0) or 0.0)
        self.compile_count += int(delta.get("compile_count", 0) or 0)
        skew = delta.get("skew")
        if isinstance(skew, dict) and (
            self.skew is None
            or skew.get("max_mean_ratio", 0.0)
            > self.skew.get("max_mean_ratio", 0.0)
        ):
            self.skew = dict(skew)
        hbm = delta.get("hbm")
        if isinstance(hbm, dict) and (
            self.hbm is None
            or hbm.get("bytes_in_use", 0) > self.hbm.get("bytes_in_use", 0)
        ):
            self.hbm = dict(hbm)
        self.jobs_done += int(delta.get("jobs_done", 0) or 0)
        self.jobs_failed += int(delta.get("jobs_failed", 0) or 0)


class HealthDeltaCollector:
    """Agent-side `Metrics` tap accumulating bounded health deltas.

    Attach to every `Metrics` whose events land in the agent's journal
    (the service's metrics plus each admitted job's — the
    `SortService.job_taps` seam); `drain()` under the heartbeat cadence.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._seq = 0
        self._s = _HealthSums()
        self._waits: deque = deque(maxlen=WAIT_WINDOW)
        self._compiles: deque = deque(maxlen=COMPILE_WINDOW)

    # -- tap protocol ------------------------------------------------------

    def attach(self, metrics) -> None:
        """Tap a `Metrics` instance (idempotent)."""
        if self not in metrics.taps:
            metrics.taps.append(self)

    def observe(self, etype: str, fields: dict, mono: float, metrics) -> None:
        if etype == "phase_end":
            sec = fields.get("seconds")
            if isinstance(sec, (int, float)):
                phase = str(fields.get("phase", "?"))
                with self._lock:
                    self._s.phase_s[phase] = (
                        self._s.phase_s.get(phase, 0.0) + float(sec)
                    )
        elif etype == "job_dequeued":
            w = fields.get("wait_s")
            if isinstance(w, (int, float)):
                with self._lock:
                    self._s.wait_sum += float(w)
                    self._s.wait_count += 1
                    self._waits.append(float(w))
        elif etype == "variant_compiled":
            sec = fields.get("compile_s")
            if isinstance(sec, (int, float)):
                with self._lock:
                    self._s.compile_sum += float(sec)
                    self._s.compile_count += 1
                    self._compiles.append({
                        "variant": str(fields.get("variant", "?")),
                        "compile_s": float(sec),
                    })
        elif etype == "skew_report":
            ratio = fields.get("max_mean_ratio", 0.0)
            with self._lock:
                if (
                    self._s.skew is None
                    or ratio > self._s.skew.get("max_mean_ratio", 0.0)
                ):
                    self._s.skew = {
                        "max_mean_ratio": ratio,
                        "recv_argmax": fields.get("recv_argmax"),
                    }
        elif etype == "hbm_watermark":
            b = fields.get("bytes_in_use", 0)
            with self._lock:
                if (
                    self._s.hbm is None
                    or b > self._s.hbm.get("bytes_in_use", 0)
                ):
                    self._s.hbm = {
                        "bytes_in_use": b,
                        "phase": fields.get("phase", "?"),
                    }
        elif etype == "job_done":
            with self._lock:
                self._s.jobs_done += 1
        elif etype == "job_failed":
            with self._lock:
                self._s.jobs_failed += 1

    # -- the delta stream --------------------------------------------------

    def drain(self) -> dict:
        """One bounded delta dict; resets the accumulation.  Running sums
        are exact (never evicted downstream); the ``waits``/``compiles``
        windows are recent samples, oldest first."""
        with self._lock:
            self._seq += 1
            s = self._s
            delta = {
                "seq": self._seq,
                "phases": dict(s.phase_s),
                "wait_s_sum": s.wait_sum,
                "wait_count": s.wait_count,
                "waits": list(self._waits),
                "compile_s_sum": s.compile_sum,
                "compile_count": s.compile_count,
                "compiles": list(self._compiles),
                "skew": s.skew,
                "hbm": s.hbm,
                "jobs_done": s.jobs_done,
                "jobs_failed": s.jobs_failed,
            }
            self._s = _HealthSums()
            self._waits.clear()
            self._compiles.clear()
        return delta

    def restore(self, delta: dict) -> None:
        """Fold a drained-but-undelivered delta BACK (the agent's send
        failed — no controller attached / link dropped mid-frame).  The
        exact sums must survive a disconnect like results do, or a slow
        agent that completed work while detached under-reports its busy
        time forever and never scores as the straggler it is.  The sums
        merge through the SAME rule the analyzer folds with
        (`_HealthSums.merge_delta`); only the sample windows are handled
        here (restored samples are OLDER — they prepend)."""
        with self._lock:
            self._s.merge_delta(delta)
            old = [w for w in delta.get("waits") or ()
                   if isinstance(w, (int, float))]
            self._waits = deque(
                old + list(self._waits), maxlen=self._waits.maxlen
            )
            self._compiles = deque(
                [dict(c) for c in delta.get("compiles") or ()]
                + list(self._compiles),
                maxlen=self._compiles.maxlen,
            )


class _AgentHealth(_HealthSums):
    """Rolling accumulation of one agent's streamed deltas (the shared
    sums plus liveness, the delta sequence high-water mark, and the
    rolling wait window the SLO-risk p95 reads)."""

    def __init__(self):
        super().__init__()
        self.active = True
        self.seq = 0
        self.waits: deque = deque(maxlen=2 * WAIT_WINDOW)

    def fold(self, delta: dict) -> None:
        self.seq = max(self.seq, int(delta.get("seq", 0)))
        self.merge_delta(delta)
        for w in delta.get("waits") or ():
            if isinstance(w, (int, float)):
                self.waits.append(float(w))

    def busy_s(self) -> float:
        return sum(self.phase_s.values())


def _wait_p95(waits) -> float | None:
    if not waits:
        return None
    ordered = sorted(waits)
    return ordered[min(int(0.95 * len(ordered)), len(ordered) - 1)]


class HealthAnalyzer:
    """Controller-side incremental why-slow analyzer over streamed deltas.

    `ingest(agent, delta)` folds one agent's delta; `verdicts()` scores
    every known agent against the fleet-mean busy time exactly the way
    `obs.analyze.analyze_records` scores merged-journal sources, so the
    live straggler name, dominant phase and split match a replay of the
    same journals.  ``degraded`` flips when an agent is the fleet
    straggler at >= ``degraded_score`` times the mean (with at least
    ``min_busy_s`` of measured busy time — an idle fleet has no
    stragglers) or its rolling p95 queue wait breaches ``slo_ms``.
    """

    def __init__(
        self,
        degraded_score: float = 1.5,
        min_busy_s: float = 0.05,
        slo_ms: float | None = None,
    ):
        self.degraded_score = float(degraded_score)
        self.min_busy_s = float(min_busy_s)
        self.slo_ms = float(slo_ms) if slo_ms is not None else None
        self._lock = threading.Lock()
        self._agents: dict[str, _AgentHealth] = {}
        self._frames = 0

    def ingest(self, agent: str, delta: dict) -> None:
        with self._lock:
            st = self._agents.get(str(agent))
            if st is None:
                st = self._agents[str(agent)] = _AgentHealth()
            st.active = True  # a streaming agent is alive by definition
            st.fold(dict(delta or {}))
            self._frames += 1

    def set_active(self, agent: str, active: bool) -> None:
        """Mark one agent's liveness.  A DOWN agent keeps its rolling
        history (it may reconnect) but leaves the fleet-mean/straggler
        computation — a permanently-dead agent's frozen busy time must
        not make the one remaining healthy agent score as a straggler."""
        with self._lock:
            st = self._agents.get(str(agent))
            if st is not None:
                st.active = bool(active)

    def forget(self, agent: str) -> None:
        """Drop one agent's rolling state (it left the fleet for good)."""
        with self._lock:
            self._agents.pop(str(agent), None)

    @property
    def frames(self) -> int:
        with self._lock:
            return self._frames

    def agents(self) -> list[str]:
        with self._lock:
            return sorted(self._agents)

    def _verdict_locked(self, aid: str, mean_busy: float,
                        straggler_aid: str | None) -> dict:
        st = self._agents[aid]
        busy = st.busy_s()
        score = busy / mean_busy if mean_busy > 0 else 1.0
        dominant = (
            max(st.phase_s, key=st.phase_s.get) if st.phase_s else None
        )
        # The split mirrors obs.analyze.analyze_records verbatim (same
        # rounding, same subtraction) — the live==replay contract.
        compile_s = round(st.compile_sum, 6)
        total_phase_s = round(busy, 6)
        splits = {
            "queue_wait_s": round(st.wait_sum, 6),
            "compile_s": compile_s,
            "execute_s": round(max(total_phase_s - compile_s, 0.0), 6),
            "phase_wall_s": total_phase_s,
        }
        p95 = _wait_p95(st.waits)
        slo_risk = None
        if self.slo_ms is not None and p95 is not None:
            slo_risk = {
                "p95_wait_ms": round(p95 * 1e3, 3),
                "target_ms": self.slo_ms,
                "ratio": round(p95 * 1e3 / self.slo_ms, 3),
            }
        is_straggler = aid == straggler_aid
        degraded = st.active and bool(
            (
                is_straggler
                and score >= self.degraded_score
                and busy >= self.min_busy_s
            )
            or (slo_risk is not None and slo_risk["ratio"] >= 1.0)
        )
        return {
            "agent": aid,
            "busy_s": round(busy, 6),
            "score": round(score, 3),
            "straggler": is_straggler,
            "dominant_phase": dominant,
            "splits": splits,
            "skew": dict(st.skew) if st.skew else None,
            "hbm": dict(st.hbm) if st.hbm else None,
            "slo_risk": slo_risk,
            "degraded": degraded,
            "seq": st.seq,
        }

    def verdicts(self) -> dict[str, dict]:
        """``{agent_id: verdict}`` over every agent that ever streamed.

        The fleet mean and the straggler argmax are computed over ACTIVE
        agents only (`set_active`): a dead agent's frozen busy time must
        neither dilute the mean nor hold the straggler slot; its last
        verdict still renders (scored vs the live mean, never degraded).
        """
        with self._lock:
            if not self._agents:
                return {}
            busy = {
                a: st.busy_s() for a, st in self._agents.items() if st.active
            }
            if not busy:  # every agent down: score against all history
                busy = {a: st.busy_s() for a, st in self._agents.items()}
            mean_busy = sum(busy.values()) / len(busy)
            straggler_aid = None
            if len(busy) >= 2:
                # Same argmax the replay analyzer takes over merged
                # sources; sorted() makes ties deterministic.
                straggler_aid = max(sorted(busy), key=lambda a: busy[a])
            return {
                aid: self._verdict_locked(aid, mean_busy, straggler_aid)
                for aid in sorted(self._agents)
            }

    def verdict(self, agent: str) -> dict | None:
        return self.verdicts().get(str(agent))

    def scores(self) -> dict[str, tuple[bool, float]]:
        """``{agent_id: (degraded, score)}`` — the routing penalty input
        (`FleetController._route_locked`, ``routing="health"``)."""
        return {
            aid: (v["degraded"], v["score"])
            for aid, v in self.verdicts().items()
        }


def straggler_position(analyzer: HealthAnalyzer, agents) -> int | None:
    """Mesh POSITION of the degraded straggler among ``agents``, or None.

    The production binding for `SampleSort.straggler_fn` (ARCHITECTURE
    §18): ``agents`` is the attempt's agent ids in mesh-position order,
    and only a verdict that is BOTH the fleet straggler argmax AND
    degraded names a position — a merely-slowest-of-a-healthy-fleet
    agent never triggers the serve race, matching the routing penalty's
    own gate (`scores`).  Fault drills bind `FaultInjector.straggler`
    through the same seam instead, so tests exercise the identical
    race path a measured verdict would take.
    """
    verdicts = analyzer.verdicts()
    for pos, aid in enumerate(agents):
        v = verdicts.get(str(aid))
        if v is not None and v["straggler"] and v["degraded"]:
            return pos
    return None


def health_table(rows: dict[str, dict], indent: str = "") -> list[str]:
    """THE health-pane table — one copy of the columns, shared by the
    verdict-side renderer below and the scrape-side ``dsort top`` pane
    (`obs.top.render_health`).  ``rows``: per-agent cells with ``score``,
    ``degraded``, ``busy_ms``, ``dominant_phase``, ``straggler`` (marked
    ``*``)."""
    lines = [
        f"{indent}{'agent':<18}{'score':>8}{'degraded':>10}{'busy ms':>12}"
        f"{'dominant phase':>18}"
    ]
    for agent in sorted(rows):
        r = rows[agent]
        mark = "*" if r.get("straggler") else ""
        lines.append(
            f"{indent}{agent + mark:<18}{r.get('score', 0.0):>8.2f}"
            f"{'yes' if r.get('degraded') else 'no':>10}"
            f"{r.get('busy_ms', 0.0):>12.1f}"
            f"{str(r.get('dominant_phase') or '-'):>18}"
        )
    return lines


def format_health(verdicts: dict[str, dict]) -> str:
    """Human health pane over analyzer verdicts."""
    if not verdicts:
        return "(no health telemetry yet)\n"
    rows = {
        aid: {
            "score": v["score"],
            "degraded": v["degraded"],
            "busy_ms": v["busy_s"] * 1e3,
            "dominant_phase": v["dominant_phase"],
            "straggler": v["straggler"],
        }
        for aid, v in verdicts.items()
    }
    return "\n".join(health_table(rows)) + "\n"
