"""Streaming latency histogram: fixed log-spaced buckets, O(1) observe.

The SLO quantiles must be computable LIVE (the metrics endpoint snapshots
mid-session) and IDENTICALLY post-hoc from a journal — so both paths share
this one deterministic structure instead of keeping raw samples: fixed
bucket bounds mean a scrape and a journal replay that saw the same
durations report byte-identical quantiles, which is exactly what the
serve-smoke gate asserts.
"""

from __future__ import annotations

import bisect
import math
import threading

#: Bucket upper bounds in seconds: 100 us .. ~26 h, factor 2^(1/4) — ~19%
#: worst-case quantile resolution, 120 buckets, fixed for every histogram
#: so live and journal-derived instances always agree bucket-for-bucket.
BUCKET_BOUNDS: tuple[float, ...] = tuple(
    1e-4 * (2.0 ** (i / 4.0)) for i in range(120)
)


class LatencyHistogram:
    """Thread-safe log-bucketed duration histogram with quantile readout."""

    def __init__(self):
        self._lock = threading.Lock()
        # one overflow bucket past the last bound
        self._counts = [0] * (len(BUCKET_BOUNDS) + 1)
        self._total = 0
        self._sum = 0.0
        self._max = 0.0

    def observe(self, seconds: float) -> None:
        s = max(float(seconds), 0.0)
        i = bisect.bisect_left(BUCKET_BOUNDS, s)
        with self._lock:
            self._counts[i] += 1
            self._total += 1
            self._sum += s
            if s > self._max:
                self._max = s

    @property
    def count(self) -> int:
        with self._lock:
            return self._total

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum

    def quantile(self, q: float) -> float:
        """Upper bound of the bucket holding rank ``ceil(q * count)``.

        Deterministic (no interpolation): the reported figure is a hard
        "no worse than" bound, and two histograms over the same samples
        always report the same value.  0.0 on an empty histogram.
        """
        with self._lock:
            if self._total == 0:
                return 0.0
            rank = min(max(math.ceil(q * self._total), 1), self._total)
            seen = 0
            for i, c in enumerate(self._counts):
                seen += c
                if seen >= rank:
                    if i >= len(BUCKET_BOUNDS):
                        # Overflow bucket: the largest observed duration is
                        # the only honest "no worse than" bound left.
                        return self._max
                    return BUCKET_BOUNDS[i]
        return self._max  # pragma: no cover (loop always returns)

    def snapshot(self) -> dict:
        """JSON-able state (count, sum, nonzero buckets) for ``/json``."""
        with self._lock:
            return {
                "count": self._total,
                "sum": round(self._sum, 6),
                "buckets": {
                    str(i): c for i, c in enumerate(self._counts) if c
                },
            }
