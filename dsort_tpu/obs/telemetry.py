"""Live metrics registry + Prometheus text snapshot (pillar 2).

One `Telemetry` instance aggregates a session's observable state across
jobs — counter totals, phase wall time, job outcomes, jobs in flight, an
operator-settable gauge set (queue depth), and the per-tenant SLO
histograms (`obs.slo`).  It is fed by `Metrics` event taps: `attach` a
Telemetry to any `Metrics` and every event that job emits flows in live,
with the journal's own timestamps.

The snapshot renders in the Prometheus text exposition format (0.0.4) so
any scraper — or the in-tree minimal parser `parse_prometheus_text`, which
the tier-1 serve-smoke gate round-trips through — can consume it; the
stdlib HTTP endpoint lives in `obs.server`, the console view in
``dsort top``.
"""

from __future__ import annotations

import threading
from collections import defaultdict

from dsort_tpu.obs.histogram import LatencyHistogram
from dsort_tpu.obs.slo import SLO_QUANTILES, SloStateMachine
from dsort_tpu.utils.events import COUNTERS


class Telemetry:
    """Session-wide aggregate of counters, phases, gauges and SLO stages."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: dict[str, int] = defaultdict(int)
        self._phase_s: dict[str, float] = defaultdict(float)
        self._jobs: dict[tuple[str, str], int] = defaultdict(int)
        self._in_flight = 0
        self._gauges: dict[str, float] = {"queue_depth": 0.0}
        # Labeled gauge series keyed by (name, identity labels) — the
        # per-agent health gauges (ARCHITECTURE §13) ride here.
        self._series: dict[tuple[str, tuple], tuple[tuple, float]] = {}
        self._slo: dict[tuple[str, str], LatencyHistogram] = {}
        self._admissions: dict[tuple[str, str], int] = defaultdict(int)

    # -- ingestion ---------------------------------------------------------

    def attach(self, metrics) -> None:
        """Tap a `Metrics` instance so its events feed this registry.

        Idempotent per (metrics, telemetry) pair — schedulers and the CLI
        may both attach the same pair.
        """
        for tap in metrics.taps:
            if isinstance(tap, _TelemetryTap) and tap.telemetry is self:
                return
        metrics.taps.append(_TelemetryTap(self))

    def observe_stage(self, tenant: str, stage: str, seconds: float) -> None:
        key = (str(tenant), str(stage))
        with self._lock:
            h = self._slo.get(key)
            if h is None:
                h = self._slo[key] = LatencyHistogram()
        h.observe(seconds)

    def set_gauge(self, name: str, value: float) -> None:
        with self._lock:
            self._gauges[str(name)] = float(value)

    def set_series(
        self, name: str, labels: dict, value: float, key: dict | None = None
    ) -> None:
        """Set one LABELED gauge sample (``dsort_<name>{labels} value``).

        ``key`` (default: all of ``labels``) identifies the series for
        replacement — an info-style series whose non-key labels carry
        current state (the health pane's ``dominant_phase``) REPLACES its
        stale incarnation instead of accumulating one series per distinct
        label set.
        """
        def flat(d):
            return tuple(sorted((str(k), str(v)) for k, v in d.items()))

        with self._lock:
            self._series[(str(name), flat(key if key is not None else labels))] = (
                flat(labels), float(value)
            )

    def inc_counter(self, name: str, by: int = 1) -> None:
        """Directly bump a session counter (serving-layer events that have
        no per-job `Metrics` to ride a ``job_done`` absorption on)."""
        with self._lock:
            self._counters[str(name)] += int(by)

    def admission_verdict(self, tenant: str, reason: str) -> None:
        """Count one admission verdict — the per-tenant backpressure series
        (``dsort_admissions_total{tenant=,reason=}``) the serving layer
        publishes on every `SortService.submit`."""
        with self._lock:
            self._admissions[(str(tenant), str(reason))] += 1

    def _job_started(self) -> None:
        with self._lock:
            self._in_flight += 1

    def _job_finished(self, tenant: str, outcome: str) -> None:
        with self._lock:
            self._in_flight = max(self._in_flight - 1, 0)
            self._jobs[(str(tenant), str(outcome))] += 1

    def _absorb_counters(self, delta: dict) -> None:
        with self._lock:
            for k, v in delta.items():
                if isinstance(v, (int, float)) and v:
                    self._counters[str(k)] += int(v)

    def _absorb_phase(self, phase: str, seconds: float) -> None:
        with self._lock:
            self._phase_s[str(phase)] += float(seconds)

    # -- snapshots ---------------------------------------------------------

    def snapshot(self) -> dict:
        """JSON-able full state (the ``/json`` endpoint + ``dsort top``)."""
        from dsort_tpu.obs.prof import LEDGER

        ledger = LEDGER.snapshot()
        with self._lock:
            series_raw = sorted(self._series.items())
            snap = {
                "variant_ledger": ledger,
                "counters": dict(self._counters),
                "phase_seconds": {
                    k: round(v, 6) for k, v in self._phase_s.items()
                },
                "jobs": {
                    f"{t}/{o}": n for (t, o), n in self._jobs.items()
                },
                "jobs_in_flight": self._in_flight,
                "gauges": dict(self._gauges),
                "admissions": {
                    f"{t}/{r}": n for (t, r), n in self._admissions.items()
                },
                "slo": {
                    f"{t}/{s}": h.snapshot() for (t, s), h in self._slo.items()
                },
            }
        snap["series"] = {
            f"{name}{{{','.join(f'{k}={v}' for k, v in labels)}}}": v2
            for (name, _key), (labels, v2) in series_raw
        }
        return snap

    def render_prometheus(self) -> str:
        """The Prometheus text exposition snapshot (scrape body)."""
        from dsort_tpu.obs.prof import LEDGER

        ledger = LEDGER.snapshot()
        with self._lock:
            counters = dict(self._counters)
            phases = dict(self._phase_s)
            jobs = dict(self._jobs)
            in_flight = self._in_flight
            gauges = dict(self._gauges)
            series = dict(self._series)
            admissions = dict(self._admissions)
            slo = dict(self._slo)
        lines = [
            "# HELP dsort_counter_total Registered framework counters "
            "(utils.events.COUNTERS).",
            "# TYPE dsort_counter_total counter",
        ]
        # EVERY registered counter renders (0 when never bumped): scrape
        # series must not appear and vanish with job mix.
        for name in sorted(set(COUNTERS) | set(counters)):
            lines.append(
                f'dsort_counter_total{{name="{name}"}} '
                f"{counters.get(name, 0)}"
            )
        lines.append("# TYPE dsort_phase_seconds_total counter")
        for phase in sorted(phases):
            lines.append(
                f'dsort_phase_seconds_total{{phase="{phase}"}} '
                f"{phases[phase]:.6f}"
            )
        lines.append("# TYPE dsort_jobs_total counter")
        for (tenant, outcome) in sorted(jobs):
            lines.append(
                f'dsort_jobs_total{{tenant="{tenant}",outcome="{outcome}"}} '
                f"{jobs[(tenant, outcome)]}"
            )
        if admissions:
            lines.append(
                "# HELP dsort_admissions_total Serving-layer admission "
                "verdicts per tenant (serve.admission.ADMISSION_REASONS)."
            )
            lines.append("# TYPE dsort_admissions_total counter")
            for (tenant, reason) in sorted(admissions):
                lines.append(
                    f'dsort_admissions_total{{tenant="{tenant}",'
                    f'reason="{reason}"}} {admissions[(tenant, reason)]}'
                )
        if ledger:
            from dsort_tpu.obs.prof import LEDGER_GAUGES

            # The compile/cost/HBM ledger (obs.prof): one row per compiled
            # variant, same labels as the journal's variant_compiled
            # events — scrape == journal replay is the test contract.
            lines.append(
                "# HELP dsort_variant_compile_seconds Cumulative jit "
                "compile seconds per ladder-rung variant (obs.prof)."
            )
            for metric, field in LEDGER_GAUGES:
                lines.append(f"# TYPE {metric} gauge")
                for label in sorted(ledger):
                    lines.append(
                        f'{metric}{{variant="{label}"}} '
                        f"{ledger[label][field]:.6g}"
                    )
        lines.append("# TYPE dsort_jobs_in_flight gauge")
        lines.append(f"dsort_jobs_in_flight {in_flight}")
        for name in sorted(gauges):
            metric = f"dsort_{name}"
            lines.append(f"# TYPE {metric} gauge")
            lines.append(f"{metric} {gauges[name]:g}")
        # Labeled gauge series (per-agent health, ARCHITECTURE §13).
        typed: set[str] = set()
        for (name, _key), (labels, value) in sorted(series.items()):
            metric = f"dsort_{name}"
            if metric not in typed:
                typed.add(metric)
                lines.append(f"# TYPE {metric} gauge")
            body = ",".join(f'{k}="{v}"' for k, v in labels)
            lines.append(f"{metric}{{{body}}} {value:g}")
        lines.append(
            "# HELP dsort_job_stage_seconds Per-tenant SLO stage latency "
            "quantiles (obs.slo)."
        )
        lines.append("# TYPE dsort_job_stage_seconds summary")
        for (tenant, stage) in sorted(slo):
            h = slo[(tenant, stage)]
            labels = f'tenant="{tenant}",stage="{stage}"'
            for q in SLO_QUANTILES:
                lines.append(
                    f'dsort_job_stage_seconds{{{labels},quantile="{q}"}} '
                    f"{h.quantile(q):.6g}"
                )
            lines.append(
                f"dsort_job_stage_seconds_count{{{labels}}} {h.count}"
            )
            lines.append(
                f"dsort_job_stage_seconds_sum{{{labels}}} {h.sum:.6f}"
            )
        return "\n".join(lines) + "\n"


class _TelemetryTap:
    """Per-`Metrics` event tap feeding one `Telemetry`.

    Owns the per-job SLO state machine and the counter high-water mark for
    its Metrics instance (``job_done`` carries CUMULATIVE counters, so the
    registry must absorb deltas or a fused-fallback double ``job_done``
    would double-count).
    """

    def __init__(self, telemetry: Telemetry):
        self.telemetry = telemetry
        self._slo = SloStateMachine(telemetry.observe_stage)
        self._last_counters: dict = {}
        self._started: set = set()

    def observe(self, etype: str, fields: dict, mono: float, metrics) -> None:
        tel = self.telemetry
        job = fields.get("job")
        if etype == "job_start" and job not in self._started:
            self._started.add(job)
            tel._job_started()
        elif etype in ("job_done", "job_failed"):
            if job in self._started:
                self._started.discard(job)
                tenant = self._slo.tenant_of(job)
                tel._job_finished(
                    tenant, "done" if etype == "job_done" else "failed"
                )
            c = fields.get("counters")
            if isinstance(c, dict):
                tel._absorb_counters(
                    {
                        k: v - self._last_counters.get(k, 0)
                        for k, v in c.items()
                    }
                )
                self._last_counters = dict(c)
        elif etype == "phase_end":
            sec = fields.get("seconds")
            if isinstance(sec, (int, float)):
                tel._absorb_phase(fields.get("phase", "?"), sec)
        elif etype in ("plan_decision", "plan_override"):
            # The planner plane's gauges (ARCHITECTURE §15): per-policy
            # decision/override counts plus an info-style series carrying
            # the last chosen value — absorbed from the SAME journaled
            # events the plan verdict replays, wherever telemetry is
            # attached (serve, fleet, CLI), zero extra wiring.
            policy = str(fields.get("policy", "?"))
            which = (
                "plan_decisions" if etype == "plan_decision"
                else "plan_overrides"
            )
            with tel._lock:
                k = (which, (("policy", policy),))
                _, cur = tel._series.get(k, ((), 0.0))
                tel._series[k] = ((("policy", policy),), cur + 1.0)
            if etype == "plan_decision":
                chosen = fields.get("chosen")
                shown = (
                    f"[{len(chosen)} keys]"
                    if isinstance(chosen, (list, tuple)) else str(chosen)
                )
                tel.set_series(
                    "plan_info",
                    {"policy": policy, "chosen": shown},
                    1.0,
                    key={"policy": policy},
                )
        # The SLO machine consumes job_start BEFORE the outcome branches
        # above pop its state, and job_done after — step() order matters
        # only relative to its own reads, so one call at the end suffices.
        self._slo.step(etype, fields, mono)


def parse_prometheus_text(text: str) -> dict[tuple[str, tuple], float]:
    """Minimal Prometheus text parser: the tier-1 scrape round-trip.

    Returns ``{(metric_name, ((label, value), ...)): float}`` with labels
    sorted.  Covers exactly the subset `Telemetry.render_prometheus` emits
    (no escapes inside label values, no timestamps) and raises ValueError
    on anything that does not parse — a torn scrape must fail the gate, not
    vanish.
    """
    out: dict[tuple[str, tuple], float] = {}
    for raw in text.splitlines():
        line = raw.strip()
        if not line or line.startswith("#"):
            continue
        name_part, _, value_part = line.rpartition(" ")
        if not name_part:
            raise ValueError(f"unparseable metric line: {raw!r}")
        value = float(value_part)  # ValueError propagates
        labels: tuple = ()
        name = name_part.strip()
        if name.endswith("}"):
            name, _, label_body = name.partition("{")
            label_body = label_body[:-1]
            pairs = []
            for item in label_body.split(","):
                if not item:
                    continue
                k, eq, v = item.partition("=")
                if eq != "=" or not (v.startswith('"') and v.endswith('"')):
                    raise ValueError(f"unparseable labels: {raw!r}")
                pairs.append((k, v[1:-1]))
            labels = tuple(sorted(pairs))
        if not name or any(ch in name for ch in "{} "):
            raise ValueError(f"unparseable metric name: {raw!r}")
        out[(name, labels)] = value
    return out
