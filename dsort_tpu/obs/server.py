"""Stdlib HTTP endpoint serving a `Telemetry` snapshot (no new deps).

Three routes on a daemon-threaded ``ThreadingHTTPServer``:

- ``/metrics``  Prometheus text exposition (``Telemetry.render_prometheus``)
- ``/json``     the full JSON snapshot (``Telemetry.snapshot``)
- ``/healthz``  liveness probe (``ok``)

``port=0`` binds an ephemeral port (tests; `MetricsServer.port` reports the
bound one).  The handler reads one snapshot per request and never touches
scheduler state, so a slow scraper cannot stall a job.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from dsort_tpu.utils.logging import get_logger

log = get_logger("obs.server")

PROMETHEUS_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


class MetricsServer:
    """Background HTTP server exposing one `Telemetry` registry."""

    def __init__(self, telemetry, port: int = 0, host: str = "127.0.0.1"):
        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # scrapes are not job events
                pass

            def do_GET(self):
                try:
                    if self.path.split("?")[0] == "/metrics":
                        body = telemetry.render_prometheus().encode("utf-8")
                        ctype = PROMETHEUS_CONTENT_TYPE
                    elif self.path.split("?")[0] == "/json":
                        body = (
                            json.dumps(telemetry.snapshot()) + "\n"
                        ).encode("utf-8")
                        ctype = "application/json"
                    elif self.path.split("?")[0] == "/healthz":
                        body, ctype = b"ok\n", "text/plain"
                    else:
                        self.send_error(404)
                        return
                except Exception as e:  # a torn snapshot must not 500-loop
                    log.warning("metrics snapshot failed: %s", e)
                    self.send_error(500)
                    return
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self.telemetry = telemetry
        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._httpd.daemon_threads = True
        self.host = host
        self.port = int(self._httpd.server_address[1])
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name=f"dsort-metrics-{self.port}",
        )
        self._thread.start()

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}/metrics"

    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()

    def __enter__(self) -> "MetricsServer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
