"""Performance introspection: the compile/cost/HBM ledger + memwatch tap.

The telemetry plane (PR 6) answers *what happened*; this module is the
first half of *why slow* — every jitted program the tree builds records
what its compile actually cost:

- **`CompileLedger`**: a process-wide table keyed by the SAME ladder-rung
  variant keys the compiled-variant cache uses (`serve.variants`), holding
  compile seconds, XLA ``cost_analysis()`` flops / bytes-accessed, and
  ``memory_analysis()`` argument / output / temp HBM bytes per variant.
  Entries journal as ``variant_compiled`` events (drained into whichever
  job's `Metrics` is live when the compile lands) and render as gauges on
  ``/metrics`` (`obs.telemetry`) and as the ledger table in ``dsort top``.
- **`instrument_jit`**: wraps a ``jax.jit`` callable so its first call per
  specialization goes through the AOT path (``lower().compile()``) —
  the compile is TIMED and introspected instead of vanishing inside the
  first dispatch.  The compiled executable is cached per argument spec
  (shapes / dtypes / shardings), so repeat calls pay one dict lookup; any
  AOT failure falls back to the raw jit permanently for that spec (the
  instrument must never be able to fail a sort).
- **`MemWatch`**: an event tap (``--memwatch``) that snapshots device
  memory at every phase boundary into ``hbm_watermark`` events —
  ``memory_stats()`` where the backend provides it (TPU/GPU), the summed
  ``jax.live_arrays()`` footprint elsewhere (the CPU mesh) — so the
  analyzer (`obs.analyze`) can put an HBM waterline under the phase
  waterfall.

``peak_hbm_bytes`` is defined as ``argument + output + temp - alias``
(aliased/donated outputs share their argument's buffer) — the upper bound
of bytes live at once while the executable runs.
"""

from __future__ import annotations

import re
import threading
import time

from dsort_tpu.utils.logging import get_logger

log = get_logger("obs.prof")

#: Fields every ``variant_compiled`` event carries (schema, test-enforced
#: against ARCHITECTURE §9 like the flight-recorder bundle keys).
LEDGER_EVENT_FIELDS = (
    "variant",
    "compile_s",
    "flops",
    "bytes_accessed",
    "peak_hbm_bytes",
    "temp_hbm_bytes",
    "output_hbm_bytes",
    "argument_hbm_bytes",
)

#: (metric name, ledger field) of each ``/metrics`` gauge the ledger
#: exports — THE one copy `obs.telemetry.render_prometheus` and the
#: ``dsort top`` ledger table both render from.
LEDGER_GAUGES = (
    ("dsort_variant_compile_seconds", "compile_s"),
    ("dsort_variant_compiles", "compiles"),
    ("dsort_variant_flops", "flops"),
    ("dsort_variant_peak_hbm_bytes", "peak_hbm_bytes"),
)

def _new_entry(label: str) -> dict:
    return {
        "variant": label,
        "compiles": 0,
        "compile_s": 0.0,
        "flops": 0.0,
        "bytes_accessed": 0.0,
        "peak_hbm_bytes": 0,
        "temp_hbm_bytes": 0,
        "output_hbm_bytes": 0,
        "argument_hbm_bytes": 0,
    }


def _fold(entry: dict, event: dict) -> None:
    """Fold one ``variant_compiled`` event into an aggregate entry — the
    ONE aggregation rule `CompileLedger.record` and `ledger_from_journal`
    share (the scrape==journal parity contract rests on it).  Compile
    seconds accumulate (the total price paid for the variant); cost/HBM
    figures describe ONE executable, so re-compiles of the same variant
    (per-placement specializations) take the max.
    """
    entry["compiles"] += 1
    entry["compile_s"] = round(
        entry["compile_s"] + float(event.get("compile_s", 0.0)), 6
    )
    for f in ("flops", "bytes_accessed"):
        entry[f] = max(entry[f], float(event.get(f, 0.0)))
    for f in ("peak_hbm_bytes", "temp_hbm_bytes", "output_hbm_bytes",
              "argument_hbm_bytes"):
        entry[f] = max(entry[f], int(event.get(f, 0)))


def variant_label(key) -> str:
    """The ledger's string form of a variant key tuple (journal/metrics
    label): ``"fused|81920|int32|auto"``.

    Nested tuples (the ring's per-step caps) flatten with ``-`` and any
    character outside ``[A-Za-z0-9._|-]`` becomes ``_`` — the label rides
    inside Prometheus label values, and the in-tree minimal parser splits
    label bodies on commas, so the label must never contain one.
    """
    if isinstance(key, str):
        return key

    def part(p):
        if isinstance(p, (tuple, list)):
            return "-".join(part(q) for q in p)
        return _SAFE.sub("_", str(p))

    return "|".join(part(p) for p in key)


_SAFE = re.compile(r"[^A-Za-z0-9._|-]")


def _normalize_cost(cost) -> dict:
    """``Compiled.cost_analysis()`` returns a dict or a one-dict list
    depending on the jax version; normalize to flat floats."""
    if isinstance(cost, (list, tuple)):
        cost = cost[0] if cost else {}
    if not isinstance(cost, dict):
        return {}
    return {
        "flops": float(cost.get("flops", 0.0)),
        "bytes_accessed": float(cost.get("bytes accessed", 0.0)),
    }


def _normalize_memory(mem) -> dict:
    """``Compiled.memory_analysis()`` -> argument/output/temp/peak bytes
    (zeros when the backend provides nothing)."""
    arg = int(getattr(mem, "argument_size_in_bytes", 0) or 0)
    out = int(getattr(mem, "output_size_in_bytes", 0) or 0)
    tmp = int(getattr(mem, "temp_size_in_bytes", 0) or 0)
    alias = int(getattr(mem, "alias_size_in_bytes", 0) or 0)
    return {
        "argument_hbm_bytes": arg,
        "output_hbm_bytes": out,
        "temp_hbm_bytes": tmp,
        "peak_hbm_bytes": max(arg + out + tmp - alias, 0),
    }


class CompileLedger:
    """Process-wide ledger of jit compiles, keyed by variant label.

    `record` aggregates per variant (a prewarm compiles the same rung once
    per slice placement — compiles count up, compile seconds sum, HBM
    figures take the max) and queues one ``variant_compiled`` event per
    compile; `drain_to` journals the queued events through the first live
    `Metrics` that comes by, so the ledger needs no plumbing of its own.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._entries: dict[str, dict] = {}
        self._pending: list[dict] = []

    def record(
        self, key, compile_s: float, cost=None, mem=None
    ) -> dict:
        label = variant_label(key)
        c = _normalize_cost(cost)
        m = _normalize_memory(mem)
        event = {
            "variant": label,
            "compile_s": round(float(compile_s), 6),
            "flops": c.get("flops", 0.0),
            "bytes_accessed": c.get("bytes_accessed", 0.0),
            **m,
        }
        with self._lock:
            e = self._entries.get(label)
            if e is None:
                e = self._entries[label] = _new_entry(label)
            _fold(e, event)
            self._pending.append(event)
        return event

    def drain_to(self, metrics) -> int:
        """Journal queued compiles through ``metrics`` (no-op when the
        metrics has neither a journal nor taps — the events would vanish
        and must stay queued for a consumer that records)."""
        if metrics is None or (metrics.journal is None and not metrics.taps):
            return 0
        with self._lock:
            pending, self._pending = self._pending, []
        for ev in pending:
            metrics.bump("variant_compiles")
            metrics.event("variant_compiled", **ev)
        return len(pending)

    def snapshot(self) -> dict[str, dict]:
        """Aggregated per-variant rows (the ``/metrics`` gauge source)."""
        with self._lock:
            return {k: dict(v) for k, v in self._entries.items()}

    def pending(self) -> int:
        with self._lock:
            return len(self._pending)

    def reset(self) -> None:
        """Drop all state (tests; a process serves one trajectory)."""
        with self._lock:
            self._entries.clear()
            self._pending.clear()


#: THE process-wide ledger every instrumented build records into.
LEDGER = CompileLedger()


def ledger_from_journal(records: list[dict]) -> dict[str, dict]:
    """Replay ``variant_compiled`` events into the same aggregate shape as
    `CompileLedger.snapshot` — the scrape==journal ground-truth side."""
    out: dict[str, dict] = {}
    for r in records:
        if r.get("type") != "variant_compiled":
            continue
        label = str(r.get("variant", "?"))
        e = out.get(label)
        if e is None:
            e = out[label] = _new_entry(label)
        _fold(e, r)
    return out


# -- the instrumented jit boundary ------------------------------------------


def _arg_spec(a):
    """One argument's specialization signature: shape, dtype, placement.

    Placement matters — jit specializes per sharding/device (the serve
    prewarm compiles one executable per slice lead), so each placement is
    its own compiled entry in the wrapper's cache.
    """
    shape = getattr(a, "shape", None)
    if shape is None:
        return ("static", repr(a))
    sharding = getattr(a, "sharding", None)
    return (
        tuple(shape),
        str(getattr(a, "dtype", "?")),
        str(sharding) if sharding is not None else None,
    )


class LedgeredJit:
    """A jit callable whose compiles are timed and introspected.

    First call per argument spec: ``lower().compile()`` under a timer,
    ``cost_analysis``/``memory_analysis`` recorded into the ledger under
    ``key_fn(*args)``, the compiled executable cached.  Repeat calls are
    one dict lookup.  Any AOT-path failure logs once and pins that spec to
    the raw jit callable — instrumentation must never fail a sort.
    """

    def __init__(self, fn, key_fn, ledger: CompileLedger | None = None):
        self._fn = fn
        self._key_fn = key_fn
        self._ledger = ledger if ledger is not None else LEDGER
        self._lock = threading.Lock()
        self._compiled: dict[tuple, object] = {}

    def __call__(self, *args):
        spec = tuple(_arg_spec(a) for a in args)
        with self._lock:
            target = self._compiled.get(spec)
        if target is None:
            target = self._compile(spec, args)
        return target(*args)

    def _compile(self, spec: tuple, args):
        # Compile OUTSIDE the lock (seconds; two racing callers both
        # compile and both record — jax dedupes the executable underneath,
        # same doctrine as `serve.variants.VariantCache`).
        try:
            t0 = time.perf_counter()
            compiled = self._fn.lower(*args).compile()
            dt = time.perf_counter() - t0
            cost = mem = None
            try:
                cost = compiled.cost_analysis()
            except Exception:  # pragma: no cover - backend-dependent
                pass
            try:
                mem = compiled.memory_analysis()
            except Exception:  # pragma: no cover - backend-dependent
                pass
            self._ledger.record(self._key_fn(*args), dt, cost, mem)
        except Exception as e:
            log.warning(
                "compile instrumentation unavailable (%s); running the "
                "raw jit", (str(e).splitlines() or [repr(e)])[0][:120],
            )
            compiled = self._fn
        with self._lock:
            self._compiled.setdefault(spec, compiled)
        return compiled


def instrument_jit(fn, key_fn) -> LedgeredJit:
    """Wrap a jitted callable so its compiles land in the process ledger.

    ``key_fn(*call_args) -> tuple`` builds the variant key — static parts
    (worker count, rung, kernel) plus call-time parts (the dtype the jit
    would specialize on anyway).
    """
    return LedgeredJit(fn, key_fn, LEDGER)


# -- memwatch: HBM watermarks at phase boundaries ---------------------------


def device_memory_snapshot() -> dict:
    """Bytes resident on the accelerators right now.

    ``memory_stats()`` (bytes_in_use / peak_bytes_in_use) where the
    backend provides it; the summed ``jax.live_arrays()`` footprint
    otherwise (the CPU mesh — no peak there, but the waterline is real).
    """
    import jax

    per_dev: dict = {}
    peak = 0
    source = "memory_stats"
    for d in jax.local_devices():
        stats = None
        try:
            stats = d.memory_stats()
        except Exception:  # pragma: no cover - backend-dependent
            stats = None
        if not stats:
            source = "live_arrays"
            break
        per_dev[d.id] = int(stats.get("bytes_in_use", 0))
        peak = max(peak, int(stats.get("peak_bytes_in_use", 0)))
    if source == "live_arrays":
        per_dev = {}
        for a in jax.live_arrays():
            try:
                for shard in a.addressable_shards:
                    did = shard.data.devices().pop().id
                    per_dev[did] = per_dev.get(did, 0) + shard.data.nbytes
            except Exception:  # deleted/donated arrays mid-iteration
                continue
        peak = 0
    total = sum(per_dev.values())
    return {
        "bytes_in_use": int(total),
        "max_device_bytes": int(max(per_dev.values(), default=0)),
        "peak_bytes": int(peak),
        "devices": len(per_dev),
        "source": source,
    }


class MemWatch:
    """Event tap emitting ``hbm_watermark`` at every phase boundary.

    Attach to a job's `Metrics` (``--memwatch``); every ``phase_start``/
    ``phase_end`` triggers one snapshot.  The nested ``metrics.event``
    re-enters the tap list with an ``hbm_watermark`` type this tap
    ignores, so there is no recursion.
    """

    def __init__(self, snapshot_fn=None):
        self._snapshot = snapshot_fn or device_memory_snapshot

    def attach(self, metrics) -> None:
        if self not in metrics.taps:
            metrics.taps.append(self)

    def observe(self, etype: str, fields: dict, mono: float, metrics) -> None:
        if etype not in ("phase_start", "phase_end"):
            return
        try:
            snap = self._snapshot()
        except Exception as e:  # diagnostics must never fail the job
            log.warning("memwatch snapshot failed: %s", e)
            return
        metrics.bump("hbm_watermarks")
        metrics.event(
            "hbm_watermark",
            phase=fields.get("phase", "?"),
            edge="start" if etype == "phase_start" else "end",
            **snap,
        )
