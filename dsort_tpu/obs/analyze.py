"""Journal-native why-slow analysis: ``dsort report --analyze``.

The journal already records everything a performance verdict needs — phase
spans, job boundaries, queue waits, compile costs (`obs.prof`), skew
reports (`parallel.exchange`), HBM watermarks, wire-byte counters.  This
module replays any journal (single-process or a ``--merge``\\ d multi-host
trace, `obs.merge`) into one structured verdict:

- **phase waterfall + critical path**: per-(process, phase) wall seconds;
  the *critical process* is the one whose last event gates completion of
  the whole span, its largest phase is the *critical phase*, and the
  critical path lists that process's phases by wall share — "which host
  and which phase did the fleet wait on".
- **straggler attribution**: with >= 2 sources, each process's busy time
  (summed phase seconds) is scored against the fleet mean; the max score
  names the straggler, and ``phase_excess_s`` says which phases it lost
  the time in relative to its peers.
- **queue-wait vs execute vs compile split**: queue waits from
  ``job_dequeued`` (the serving layer's measured wait), compile seconds
  from ``variant_compiled``, execute = phase wall minus compile (compiles
  land inside the dispatching phase, so the subtraction attributes them).
- **wire**: bytes the exchange put on the wire (final ``job_done``
  counters) and — when the caller supplies a measured link bandwidth —
  the seconds those bytes *should* have cost.
- **skew**: the worst ``skew_report`` (max/mean bucket ratio + the
  predicted overloaded device).
- **hbm**: the high-water ``hbm_watermark`` and the phase it landed in.
- **recovery**: the failure-posture split (ARCHITECTURE §14) — what the
  session's recoveries COST: the coded-local side (``coded_recover``
  events: recoveries, keys reconstructed from replica slots, replica
  bytes consumed, recovery wall seconds) vs the re-run side (mesh
  re-forms, evictions, keys re-sorted by any resume/repair path), with
  ``path`` naming which posture the session actually took.
- **waves**: out-of-core wave jobs (`models.wave_sort`) — per-wave spans
  from ``wave_start``/``wave_done`` pairs, which wave GATED completion
  (latest ``wave_done``), the slowest wave, and the run-granular resume
  cost (``wave_resume`` missing-run totals).  The wave phases themselves
  (``wave_read``/``wave_sort``/``wave_exchange``/``wave_spill``/``merge``)
  land in the ordinary phase waterfall.
- **plan**: the planner audit (ARCHITECTURE §15) — every ``plan_decision``
  REPLAYED through its pure policy from the journaled inputs
  (`obs.plan.replay_decision`); ``mismatches`` counts decisions whose
  replay disagrees with what the live planner chose (pinned at 0: a
  decision that cannot be reproduced from its recorded inputs is an audit
  failure), plus the ``plan_override`` trail of explicit values that won.
- **conformance**: the trace-contract verdict (ARCHITECTURE §16) — the
  journal replayed against the declared `TRACE_CONTRACTS` grammars
  (`analysis.spec.contracts`): scoped traces checked, and every
  violation named by contract, scope, and the offending event sequence.
  The same engine serves ``dsort report --conform`` and the drill tests'
  ``assert_conformant``.

Every figure is derived from the records alone — the same replay
discipline as `obs.slo`: analyzing a journal twice, or a scrape and a
replay of the same session, must agree exactly.
"""

from __future__ import annotations

from dsort_tpu.obs.prof import ledger_from_journal

#: Top-level verdict keys (schema, test-enforced against ARCHITECTURE §9).
VERDICT_KEYS = (
    "span_s",
    "sources",
    "phases",
    "dominant_phase",
    "critical_src",
    "critical_phase",
    "critical_path",
    "straggler",
    "splits",
    "wire",
    "skew",
    "hbm",
    "jobs",
    "slowest_job",
    "compiles",
    "waves",
    "recovery",
    "plan",
    "conformance",
)


def _src_name(src: int) -> str:
    return f"p{int(src)}"


def analyze_records(
    records: list[dict], link_bytes_per_s: float | None = None
) -> dict:
    """One journal (raw or merged) -> the why-slow verdict dict.

    ``link_bytes_per_s`` (optional, e.g. from a transfer probe) prices the
    wire bytes into expected seconds; without it the wire section carries
    bytes only.
    """
    recs = sorted(
        (r for r in records if isinstance(r.get("mono"), (int, float))),
        key=lambda r: (r["mono"], r.get("seq", 0)),
    )
    if not recs:
        return {k: None for k in VERDICT_KEYS}
    t0 = recs[0]["mono"]
    t1 = recs[-1]["mono"]
    # Per-(src, phase) wall seconds; phase_end carries its own measured
    # ``seconds`` (PhaseTimer), so no start/end pairing is needed and a
    # torn journal missing a phase_start still attributes correctly.
    phase_s: dict[tuple[int, str], float] = {}
    src_end: dict[int, float] = {}
    src_events: dict[int, int] = {}
    waits: list[float] = []
    jobs: dict[tuple[int, object], dict] = {}
    counters_final: dict[tuple[int, object], dict] = {}
    skew_best: dict | None = None
    hbm_best: dict | None = None
    coded_recoveries = 0
    parity_recoveries = 0
    coded_keys = 0
    coded_replica_bytes = 0
    coded_wall_s = 0.0
    coded_budget_exceeded = 0
    straggler_serves = 0
    straggler_serve_keys = 0
    straggler_wall_s = 0.0
    mesh_reforms = 0
    evictions = 0
    wave_start: dict[tuple[int, object], float] = {}
    wave_span: dict[tuple[int, object], float] = {}
    wave_done_at: dict[tuple[int, object], float] = {}
    wave_resumed = 0
    plan_decisions: list[dict] = []
    plan_overrides: list[dict] = []
    for r in recs:
        src = int(r.get("src", 0))
        src_end[src] = r["mono"]
        src_events[src] = src_events.get(src, 0) + 1
        etype = r.get("type")
        if etype == "phase_end":
            sec = r.get("seconds")
            if isinstance(sec, (int, float)):
                key = (src, str(r.get("phase", "?")))
                phase_s[key] = phase_s.get(key, 0.0) + float(sec)
        elif etype == "job_dequeued":
            w = r.get("wait_s")
            if isinstance(w, (int, float)):
                waits.append(float(w))
        elif etype == "job_start":
            key = (src, r.get("job"))
            if key not in jobs:
                jobs[key] = {
                    "src": src,
                    "job": r.get("job"),
                    "tenant": r.get("tenant", "default"),
                    "n_keys": r.get("n_keys"),
                    "start": r["mono"],
                    "duration_s": None,
                }
        elif etype in ("job_done", "job_failed"):
            key = (src, r.get("job"))
            st = jobs.get(key)
            if st is not None and st["duration_s"] is None:
                st["duration_s"] = round(r["mono"] - st["start"], 6)
                st["outcome"] = "done" if etype == "job_done" else "failed"
            c = r.get("counters")
            if isinstance(c, dict):
                counters_final[key] = c
        elif etype == "skew_report":
            ratio = r.get("max_mean_ratio", 0.0)
            if skew_best is None or ratio > skew_best.get("max_mean_ratio", 0.0):
                skew_best = {
                    k: v for k, v in r.items()
                    if k not in ("seq", "t", "mono", "type")
                }
        elif etype in ("coded_recover", "parity_recover"):
            # Both are coded-local reconstructions (ARCHITECTURE §14/§18);
            # parity solves are tallied apart so the verdict can say WHICH
            # premium (full replicas vs XOR/P+Q slots) paid for recovery.
            if etype == "parity_recover":
                parity_recoveries += 1
            else:
                coded_recoveries += 1
            coded_keys += int(r.get("recovered_keys", 0) or 0)
            coded_replica_bytes += int(r.get("replica_bytes", 0) or 0)
            w = r.get("wall_s")
            coded_wall_s += float(w) if isinstance(w, (int, float)) else 0.0
        elif etype == "coded_budget_exceeded":
            coded_budget_exceeded += 1
        elif etype == "coded_straggler_serve":
            straggler_serves += 1
            straggler_serve_keys += int(r.get("recovered_keys", 0) or 0)
            w = r.get("wall_s")
            straggler_wall_s += (
                float(w) if isinstance(w, (int, float)) else 0.0
            )
        elif etype == "mesh_reform":
            mesh_reforms += 1
        elif etype == "job_evicted":
            evictions += 1
        elif etype == "wave_start":
            # Scoped by job ordinal: a session journal (the external-smoke
            # bench, a serve loop) holds MANY wave jobs, and wave ids
            # repeat per job — an unscoped key would pair one job's start
            # with another's done.
            wave_start.setdefault((src, r.get("job"), r.get("wave")), r["mono"])
        elif etype == "wave_done":
            key = (src, r.get("job"), r.get("wave"))
            t_start = wave_start.get(key)
            if t_start is not None:
                wave_span[key] = round(r["mono"] - t_start, 6)
            wave_done_at[key] = r["mono"]
        elif etype == "wave_resume":
            m = r.get("missing")
            wave_resumed += int(m) if isinstance(m, (int, float)) else 0
        elif etype == "plan_decision":
            plan_decisions.append(r)
        elif etype == "plan_override":
            plan_overrides.append(r)
        elif etype == "hbm_watermark":
            b = r.get("bytes_in_use", 0)
            if hbm_best is None or b > hbm_best.get("bytes_in_use", 0):
                hbm_best = {
                    "bytes_in_use": b,
                    "max_device_bytes": r.get("max_device_bytes", 0),
                    "phase": r.get("phase", "?"),
                    "edge": r.get("edge", "?"),
                    "src": src,
                }
    srcs = sorted(src_end)
    # -- phase waterfall + critical path ------------------------------------
    phase_totals: dict[str, float] = {}
    for (src, phase), sec in phase_s.items():
        phase_totals[phase] = phase_totals.get(phase, 0.0) + sec
    dominant_phase = (
        max(phase_totals, key=phase_totals.get) if phase_totals else None
    )
    critical_src = max(srcs, key=lambda s: src_end[s])
    crit_phases = {
        phase: sec for (src, phase), sec in phase_s.items()
        if src == critical_src
    }
    critical_phase = (
        max(crit_phases, key=crit_phases.get) if crit_phases else None
    )
    critical_path = [
        {"src": critical_src, "name": _src_name(critical_src),
         "phase": phase, "seconds": round(sec, 6)}
        for phase, sec in sorted(
            crit_phases.items(), key=lambda kv: -kv[1]
        )
    ]
    # -- straggler attribution ----------------------------------------------
    busy = {
        s: sum(sec for (src, _), sec in phase_s.items() if src == s)
        for s in srcs
    }
    straggler = None
    if len(srcs) >= 2:
        mean_busy = sum(busy.values()) / len(busy)
        scores = {
            s: (busy[s] / mean_busy if mean_busy > 0 else 1.0) for s in srcs
        }
        worst = max(scores, key=scores.get)
        others = [s for s in srcs if s != worst]
        excess = {}
        for (src, phase), sec in phase_s.items():
            if src != worst:
                continue
            peer = [phase_s.get((o, phase), 0.0) for o in others]
            peer_mean = sum(peer) / len(peer) if peer else 0.0
            if sec - peer_mean > 0:
                excess[phase] = round(sec - peer_mean, 6)
        straggler = {
            "src": worst,
            "name": _src_name(worst),
            "score": round(scores[worst], 3),
            "busy_s": round(busy[worst], 6),
            "phase_excess_s": dict(
                sorted(excess.items(), key=lambda kv: -kv[1])
            ),
        }
    # -- splits: queue wait vs execute vs compile ---------------------------
    ledger = ledger_from_journal(recs)
    compile_s = round(sum(e["compile_s"] for e in ledger.values()), 6)
    total_phase_s = round(sum(phase_totals.values()), 6)
    splits = {
        "queue_wait_s": round(sum(waits), 6),
        "compile_s": compile_s,
        "execute_s": round(max(total_phase_s - compile_s, 0.0), 6),
        "phase_wall_s": total_phase_s,
    }
    # -- wire ---------------------------------------------------------------
    bytes_on_wire = sum(
        int(c.get("exchange_bytes_on_wire", 0))
        for c in counters_final.values()
    )
    wire = {"bytes_on_wire": bytes_on_wire}
    if link_bytes_per_s and bytes_on_wire:
        wire["expected_transfer_s"] = round(
            bytes_on_wire / float(link_bytes_per_s), 6
        )
    # -- assemble -----------------------------------------------------------
    job_rows = [
        {k: v for k, v in j.items() if k != "start"}
        for j in jobs.values()
    ]
    finished = [j for j in job_rows if j.get("duration_s") is not None]
    slowest_job = (
        max(finished, key=lambda j: j["duration_s"]) if finished else None
    )
    # -- recovery: coded-local vs re-run posture (ARCHITECTURE §14) ---------
    resorted_keys = sum(
        int(c.get(k, 0))
        for c in counters_final.values()
        for k in (
            "shuffle_resort_keys", "wave_resort_keys",
            "multihost_resort_keys",
        )
    )
    recovery = None
    local_recoveries = coded_recoveries + parity_recoveries
    if (
        local_recoveries or coded_budget_exceeded or mesh_reforms
        or evictions or resorted_keys or straggler_serves
    ):
        # A coded recovery re-forms exactly once per loss, so reforms in
        # EXCESS of the coded recoveries — like resume-path re-sorts,
        # budget overruns, or evictions that never completed codedly —
        # mean a re-run recovery also happened this session.  Parity
        # solves count the same as replica merges here (both are
        # coded-local, §18); straggler serves inject NO failure and so
        # never imply a re-run on their own.
        rerun_like = (
            coded_budget_exceeded > 0
            or resorted_keys > 0
            or mesh_reforms > local_recoveries
            or (evictions > 0 and local_recoveries == 0)
        )
        if local_recoveries and rerun_like:
            path = "mixed"
        elif parity_recoveries and coded_recoveries:
            path = "mixed"
        elif parity_recoveries:
            path = "parity_reconstruct"
        elif coded_recoveries:
            path = "coded_reconstruct"
        elif straggler_serves and not (
            mesh_reforms or evictions or resorted_keys
            or coded_budget_exceeded
        ):
            path = "straggler_serve"
        else:
            path = "rerun"
        recovery = {
            "path": path,
            "coded": {
                "recoveries": coded_recoveries,
                "parity_recoveries": parity_recoveries,
                "recovered_keys": coded_keys,
                "replica_bytes": coded_replica_bytes,
                "wall_s": round(coded_wall_s, 6),
                "budget_exceeded": coded_budget_exceeded,
            },
            "straggler": {
                "serves": straggler_serves,
                "served_keys": straggler_serve_keys,
                "wall_s": round(straggler_wall_s, 6),
            },
            "rerun": {
                "mesh_reforms": mesh_reforms,
                "evictions": evictions,
                "resorted_keys": resorted_keys,
            },
        }
    # -- waves: the out-of-core wave pipeline's verdict ---------------------
    waves = None
    if wave_done_at or wave_start or wave_resumed:
        slowest_wave = None
        if wave_span:
            (s_src, s_job, s_wave), s_sec = max(
                wave_span.items(), key=lambda kv: kv[1]
            )
            slowest_wave = {
                "wave": s_wave, "seconds": s_sec, "src": s_src, "job": s_job,
            }
        gating = None
        if wave_done_at:
            (g_src, g_job, g_wave), _ = max(
                wave_done_at.items(), key=lambda kv: kv[1]
            )
            gating = {"wave": g_wave, "src": g_src, "job": g_job}
        waves = {
            "count": len(set(wave_start) | set(wave_done_at)),
            "resumed_runs": wave_resumed,
            "slowest": slowest_wave,
            "gating": gating,
        }
    # -- plan: replay every planner decision from its journaled inputs ------
    plan = None
    if plan_decisions or plan_overrides:
        from dsort_tpu.obs.plan import replay_decision

        replayed = []
        mismatches = 0
        by_policy: dict[str, int] = {}
        for d in plan_decisions:
            policy = str(d.get("policy"))
            inputs = d.get("inputs") or {}
            by_policy[policy] = by_policy.get(policy, 0) + 1
            try:
                rechosen, rejected = replay_decision(policy, inputs)
            except (ValueError, TypeError, KeyError):
                rechosen, rejected = None, []
            match = rechosen == d.get("chosen")
            if not match:
                mismatches += 1
            replayed.append({
                "policy": policy,
                "chosen": d.get("chosen"),
                "replayed": rechosen,
                "match": match,
                "inputs": inputs,
                "rejected": d.get("rejected") or rejected,
            })
        plan = {
            "decisions": len(plan_decisions),
            "overrides": len(plan_overrides),
            "mismatches": mismatches,
            "by_policy": by_policy,
            "replayed": replayed,
            "overridden": [
                {
                    "policy": o.get("policy"),
                    "explicit": o.get("explicit"),
                    "planned": o.get("planned"),
                    "inputs": o.get("inputs") or {},
                }
                for o in plan_overrides
            ],
        }
    # Trace-contract conformance rides every verdict: the analyzer sees
    # the whole record stream anyway, and a non-conformant journal makes
    # every OTHER figure suspect (a trace that lost its job_dequeued also
    # lost that job's queue wait).  Lazy import: the contract engine is
    # stdlib-only, but analyze is importable without the analysis package
    # on odd installs — a missing engine degrades to no verdict, loudly.
    try:
        from dsort_tpu.analysis.spec.contracts import conformance_report
    except ImportError:  # pragma: no cover - partial install
        conformance = None
    else:
        conformance = conformance_report(recs)
    return {
        "span_s": round(t1 - t0, 6),
        "sources": {
            _src_name(s): {
                "events": src_events[s],
                "busy_s": round(busy[s], 6),
                "end_s": round(src_end[s] - t0, 6),
            }
            for s in srcs
        },
        "phases": {
            _src_name(src): {
                phase: round(sec, 6)
                for (s2, phase), sec in sorted(phase_s.items())
                if s2 == src
            }
            for src in srcs
        },
        "dominant_phase": dominant_phase,
        "critical_src": _src_name(critical_src),
        "critical_phase": critical_phase,
        "critical_path": critical_path,
        "straggler": straggler,
        "splits": splits,
        "wire": wire,
        "skew": skew_best,
        "hbm": hbm_best,
        "jobs": job_rows,
        "slowest_job": slowest_job,
        "compiles": ledger,
        "waves": waves,
        "recovery": recovery,
        "plan": plan,
        "conformance": conformance,
    }


def format_analysis(verdict: dict) -> str:
    """The human table behind ``dsort report --analyze``."""
    if not verdict or verdict.get("span_s") is None:
        return "(empty journal: nothing to analyze)\n"
    lines = [f"why-slow verdict over a {verdict['span_s'] * 1e3:.1f} ms span:"]
    crit = verdict.get("critical_phase")
    lines.append(
        f"  critical path : {verdict['critical_src']}"
        + (f" / {crit}" if crit else "")
        + " gated completion"
    )
    if verdict.get("dominant_phase"):
        lines.append(
            f"  dominant phase: {verdict['dominant_phase']} "
            f"({verdict['splits']['phase_wall_s'] * 1e3:.1f} ms phase wall "
            "total)"
        )
    st = verdict.get("straggler")
    if st:
        worst = next(iter(st["phase_excess_s"]), None)
        lines.append(
            f"  straggler     : {st['name']} (busy {st['busy_s'] * 1e3:.1f} "
            f"ms, {st['score']:.2f}x fleet mean"
            + (f"; lost in {worst}" if worst else "")
            + ")"
        )
    sp = verdict["splits"]
    lines.append(
        f"  split         : queue wait {sp['queue_wait_s'] * 1e3:.1f} ms | "
        f"compile {sp['compile_s'] * 1e3:.1f} ms | "
        f"execute {sp['execute_s'] * 1e3:.1f} ms"
    )
    wire = verdict.get("wire") or {}
    if wire.get("bytes_on_wire"):
        exp = wire.get("expected_transfer_s")
        lines.append(
            f"  wire          : {wire['bytes_on_wire']:,} bytes"
            + (f" (~{exp * 1e3:.1f} ms at the probed link)" if exp else "")
        )
    skew = verdict.get("skew")
    if skew:
        lines.append(
            f"  skew          : max/mean bucket ratio "
            f"{skew.get('max_mean_ratio', 0):.2f}"
            + (
                f", heaviest receiver device {skew['recv_argmax']}"
                if "recv_argmax" in skew else ""
            )
        )
    hbm = verdict.get("hbm")
    if hbm:
        lines.append(
            f"  hbm watermark : {hbm['bytes_in_use']:,} bytes in phase "
            f"{hbm['phase']} ({hbm['edge']})"
        )
    rec = verdict.get("recovery")
    if rec:
        c, rr = rec["coded"], rec["rerun"]
        lines.append(
            f"  recovery      : {rec['path']} — coded {c['recoveries']} "
            f"recovery(ies), {c['recovered_keys']:,} keys from "
            f"{c['replica_bytes']:,} replica bytes in "
            f"{c['wall_s'] * 1e3:.1f} ms | re-run {rr['mesh_reforms']} "
            f"reform(s), {rr['resorted_keys']:,} keys re-sorted"
        )
    wv = verdict.get("waves")
    if wv:
        slow = wv.get("slowest") or {}
        gate = wv.get("gating") or {}
        bits = [f"{wv.get('count', 0)} waves"]
        if gate:
            bits.append(f"wave {gate.get('wave')} gated completion")
        if slow:
            bits.append(
                f"slowest wave {slow.get('wave')} "
                f"({(slow.get('seconds') or 0) * 1e3:.1f} ms)"
            )
        if wv.get("resumed_runs"):
            bits.append(f"{wv['resumed_runs']} runs re-sorted on resume")
        lines.append("  waves         : " + ", ".join(bits))
    pl = verdict.get("plan")
    if pl:
        lines.append(
            f"  plan          : {pl['decisions']} decision(s), "
            f"{pl['overrides']} override(s), "
            f"{pl['mismatches']} replay mismatch(es)"
        )
    conf = verdict.get("conformance")
    if conf:
        lines.append(
            f"  conformance   : {conf['checked']} trace(s) against "
            f"{len(conf['contracts'])} contract(s) — "
            + ("OK" if conf["ok"]
               else f"{len(conf['violations'])} VIOLATION(S) "
                    f"({', '.join(sorted({v['contract'] for v in conf['violations']}))})")
        )
    sj = verdict.get("slowest_job")
    if sj:
        lines.append(
            f"  slowest job   : job {sj.get('job')} "
            f"(tenant {sj.get('tenant')}, {sj.get('n_keys')} keys, "
            f"{(sj.get('duration_s') or 0) * 1e3:.1f} ms)"
        )
    lines.append("phase waterfall (per process):")
    for name, phases in sorted((verdict.get("phases") or {}).items()):
        for phase, sec in sorted(phases.items(), key=lambda kv: -kv[1]):
            lines.append(f"  {name:<6} {phase:<16} {sec * 1e3:>12.3f} ms")
    ledger = verdict.get("compiles") or {}
    if ledger:
        lines.append("compiled-variant ledger:")
        for label, e in sorted(ledger.items()):
            lines.append(
                f"  {label:<52} x{e['compiles']}  "
                f"{e['compile_s'] * 1e3:>10.1f} ms  "
                f"{e['flops']:>14.3g} flops  "
                f"{e['peak_hbm_bytes']:>12,} peak B"
            )
    pl = verdict.get("plan")
    if pl:
        # The audit trail: each decision replayed from its own inputs,
        # with the winning reason — why the planner chose what it chose.
        lines.append("planner decisions (replayed from journaled inputs):")
        for d in pl.get("replayed", []):
            chosen = d.get("chosen")
            shown = (
                f"[{len(chosen)} key(s)]"
                if isinstance(chosen, (list, tuple)) else chosen
            )
            inputs = d.get("inputs") or {}
            key_inputs = ", ".join(
                f"{k}={inputs[k]}" for k in sorted(inputs)
                if not isinstance(inputs[k], (list, dict))
            )
            ok = "ok" if d.get("match") else "MISMATCH"
            lines.append(
                f"  {d.get('policy'):<12} -> {shown}  [{ok}]  {key_inputs}"
            )
            for rej in (d.get("rejected") or [])[:2]:
                lines.append(
                    f"    rejected {rej.get('value')}: {rej.get('reason')}"
                )
        for o in pl.get("overridden", []):
            lines.append(
                f"  {o.get('policy'):<12} OVERRIDDEN: explicit "
                f"{o.get('explicit')} beat planned {o.get('planned')}"
            )
    return "\n".join(lines) + "\n"
