"""Planner plane: journaled signals in, journaled decisions out (§15).

PRs 9 and 14 made this tree measure everything it does — plan-phase
bucket histograms (``skew_report``), device-memory watermarks
(``hbm_watermark``), rolling per-agent health verdicts
(``health_verdict``), per-variant compile costs — yet the knobs those
signals inform (``exchange=``, ``wave_elems``, ``redundancy=``, the
prewarm set) stayed hand-set flags.  This module closes the loop: a
backend-free `Planner` that consumes the signals the tree already
journals and emits typed ``plan_decision`` events — policy name, chosen
value, the measured inputs it saw, the rejected alternatives — BEFORE
dispatch, so every automatic choice is a first-class, replayable,
auditable record.

The replay contract (the PR 9/14 doctrine, applied to decisions): every
policy is a PURE function of the ``inputs`` dict its event carries —
``replay_decision(policy, inputs)`` recomputes the identical choice from
the journal alone, and `obs.analyze`'s ``plan`` verdict re-runs every
journaled decision and counts mismatches (pinned at zero).  Planner
rolling state (the admission mix, the watermark peak, observed losses)
is likewise a fold over journal records: `Planner.replay(records)`
rebuilds the live object's `state_dict()` exactly.

Precedence is strict and journaled: explicit flag > conf file > planner.
The planner only fills knobs the user left genuinely unset
(`JobConfig.explicit` tri-state, threaded by the CLI/conf loaders); when
an explicit value wins while autotune is on, a ``plan_override`` event
records what the planner would have chosen and why it didn't apply.

Backend-free by contract (DS6xx layer map): no jax import, ever — the
fleet controller (itself a jax-free layer) runs the redundancy policy
in-process, and analyzing a journal of decisions must not initialize a
backend.
"""

from __future__ import annotations

import threading
from collections import Counter, deque

import numpy as np

#: The policy catalog — one entry per knob the planner may fill.
PLAN_POLICIES = (
    "exchange", "wave_elems", "redundancy", "redundancy_mode", "prewarm",
    "dispatch_timeout_s", "slice_devices",
)

#: Fields every ``plan_decision`` event carries (schema, test-enforced).
PLAN_DECISION_FIELDS = ("policy", "chosen", "inputs", "rejected")
#: Fields every ``plan_override`` event carries.
PLAN_OVERRIDE_FIELDS = ("policy", "explicit", "planned", "inputs")

#: SPMD-verifier contract (parsed, not imported — `dsort_tpu.analysis.spmd`).
#: The planner is host-plane (DS1202: no collectives), and its wave clamp
#: must stay a non-degenerate ordered 8-aligned window — the wave sizer
#: clamps into ``[WAVE_MIN_ELEMS, WAVE_MAX_ELEMS]``, so an inverted or
#: unaligned window would produce zero-size (or tile-misaligned) waves.
SPMD_CONTRACT = {
    "plane": "host",
    "consts": {
        "WAVE_MIN_ELEMS": (
            ("DS1303", "value >= 8"),
            ("DS1303", "value % 8 == 0"),
        ),
        "WAVE_MAX_ELEMS": (
            ("DS1303", "value % 8 == 0"),
            ("DS1303", "value >= WAVE_MIN_ELEMS"),
        ),
    },
}

# -- policy constants (the documented thresholds of ARCHITECTURE §15) --------

#: Plan-phase skew ratio (``max_mean_ratio``) at or above which the
#: measured-capacity ring schedule beats the padded all_to_all: the padded
#: collective sizes EVERY (src, dst) bucket at the max, so its wire bytes
#: and merge work scale with the ratio while the ring's stay ~flat.
SKEW_RING_THRESHOLD = 2.0
#: Keys sampled by the pre-dispatch skew probe (deterministic stride).
SKEW_PROBE_SAMPLE = 1 << 16
#: Fraction of device memory a wave may occupy (headroom for the exchange
#: buffers, the merge scratch, and the next wave's H2D overlap).
WAVE_HBM_BUDGET_FRAC = 0.6
#: Static working-set model: bytes touched per key per wave when no
#: ``hbm_watermark`` has been observed yet (sorted copy + exchange
#: capacity buffers + merge scratch).
WAVE_WORKING_SET_FACTOR = 8.0
WAVE_MIN_ELEMS = 1 << 18
WAVE_MAX_ELEMS = 1 << 26
#: Degraded-agent fraction at or above which the fleet buys a replica.
REDUNDANCY_DEGRADED_FRAC = 0.25
#: Post-exchange keys per device a small-job slice should stay under —
#: above it a wider slice spreads the merge; the slice_devices policy
#: picks the smallest power-of-two device count meeting it at the
#: admission mix's p90 rung.
SLICE_KEYS_PER_DEVICE = 1 << 20
#: Admissions remembered for the prewarm rung x dtype mix.
PREWARM_HISTORY = 64
#: Headroom multiplier over the observed p99 dispatch-accept latency: the
#: planned send deadline must absorb a tail excursion without failing over a
#: healthy-but-momentarily-slow agent (the failover re-route costs a full
#: re-dispatch plus a journaled job_rerouted).
DISPATCH_TIMEOUT_HEADROOM = 8.0
#: Floor for the planned send deadline — below this the socket round-trip
#: itself (connect + encode + accept) dominates the budget.
DISPATCH_TIMEOUT_MIN_S = 1.0
#: Dispatch-accept latencies remembered for the rolling p99.
DISPATCH_LATENCY_HISTORY = 256


def plan_rung(n: int) -> int:
    """The 8-aligned 1/8-power-of-two capacity-ladder rung for ``n`` keys.

    Same math as `models.pipelines.pad_rung` (test-pinned against it) —
    duplicated here because the planner must quantize admission sizes
    without importing the jax-backed pipelines module.
    """
    n = max(int(n), 1)
    step = max(8, 1 << max((n - 1).bit_length() - 3, 0))
    return -(-n // step) * step


def plan_ladder(hi: int, lo: int = 8) -> list[int]:
    """Ladder rungs in ``[lo, hi]`` — `parallel.exchange.ladder_rungs`'s
    enumeration, backend-free (test-pinned against it)."""
    lo = max(int(lo), 8)
    step = max(8, 1 << max((lo - 1).bit_length() - 3, 0))
    r = -(-lo // step) * step
    out: list[int] = []
    while r <= hi:
        out.append(r)
        r += max(8, 1 << max(r.bit_length() - 3, 0))
    return out


def variant_key_label(rung: int, dtype: str) -> str:
    """The journal-safe prewarm-set member: ``"<rung>:<dtype>"`` (tuples
    would come back from JSON as lists and break replay equality)."""
    return f"{int(rung)}:{dtype}"


# -- the pre-dispatch skew probe ---------------------------------------------

def probe_skew(data, num_workers: int, sample: int = SKEW_PROBE_SAMPLE) -> dict:
    """Sampled estimate of the plan-phase bucket histogram's skew.

    A deterministic stride-sample of ``data`` is sorted, split at the
    same equal-rank splitters the device plan targets, and reduced to the
    ``max_mean_ratio`` headline `parallel.exchange.skew_stats` computes —
    so the decision's measured input is directly comparable to the
    ``skew_report`` the chosen ring plan then journals from the exact
    histogram.  Host-side, numpy-only, O(sample log sample).
    """
    data = np.asarray(data)
    p = max(int(num_workers), 1)
    n = len(data)
    if n == 0 or p < 2:
        return {"max_mean_ratio": 1.0, "sample": 0, "num_workers": p,
                "n_keys": int(n)}
    stride = max(n // int(sample), 1)
    xs = np.sort(data[::stride][: int(sample)].astype(np.int64, copy=False))
    k = len(xs)
    # Equal-rank splitters over the sample, then bucket counts — the
    # sampled twin of `_choose_splitters` + the plan histogram.
    cut = [min((i + 1) * k // p, k - 1) for i in range(p - 1)]
    splitters = xs[cut]
    counts = np.diff(np.searchsorted(xs, splitters, side="right"),
                     prepend=0, append=k).astype(np.int64)
    mean = float(counts.mean())
    ratio = float(counts.max()) / mean if mean > 0 else 1.0
    return {
        "max_mean_ratio": round(ratio, 3),
        "sample": int(k),
        "num_workers": p,
        "n_keys": int(n),
    }


# -- the pure policies (decision == f(inputs), replayable) -------------------

def _decide_exchange(inputs: dict) -> tuple[str, list[dict]]:
    skew = float(inputs.get("max_mean_ratio", 1.0))
    p = int(inputs.get("num_workers", 1))
    fused_ok = bool(inputs.get("fused_ok", False))
    red = int(inputs.get("redundancy", 1))
    thr = SKEW_RING_THRESHOLD
    if p < 2:
        return "alltoall", [
            {"value": "ring", "reason": "single worker: no exchange steps"},
            {"value": "fused", "reason": "single worker: no exchange steps"},
        ]
    if red > 1:
        return "ring", [
            {"value": "alltoall",
             "reason": f"redundancy={red}: the padded collective has no "
                       "per-step seam for the replica plane"},
            {"value": "fused",
             "reason": f"redundancy={red}: the fused kernel carries no "
                       "replica slots"},
        ]
    hosts = int(inputs.get("hosts", 0))
    if hosts >= 2 and p // hosts >= 2:
        # A >=2-host grouping with >=2 devices per host: the two-level
        # schedule aggregates each host's contributions per destination
        # host and ships ONE merged transfer per (src-host, dst-host)
        # pair, so the DCN leg scales with the data crossing hosts, not
        # with P.  At 1 device/host there is nothing to aggregate (every
        # transfer is already cross-host) — fall through to the flat
        # skew decision.
        d = p // hosts
        return "hier", [
            {"value": "alltoall",
             "reason": f"{hosts}-host topology: the padded collective "
                       "ships every (src, dst) device bucket across hosts "
                       "individually; aggregation sends one merged "
                       "transfer per host pair on the DCN leg"},
            {"value": "ring",
             "reason": f"{hosts}-host topology ({d} devices/host): the "
                       "flat ring pushes full per-device buffers over the "
                       "host boundary on most steps; the two-level "
                       "schedule moves that traffic onto the intra-host "
                       "fabric"},
            {"value": "fused",
             "reason": "the fused kernel runs the FLAT ring schedule; it "
                       "has no host-aggregated DCN leg"},
        ]
    if skew >= thr:
        rejected = [
            {"value": "alltoall",
             "reason": f"measured skew {skew} >= {thr}: the padded "
                       "collective sizes every bucket at the max "
                       "(max_bucket x P wire bytes and merge work)"},
        ]
        if fused_ok:
            rejected.append(
                {"value": "ring",
                 "reason": "same measured schedule, but P-1 separate "
                           "dispatches vs one fused launch"})
            return "fused", rejected
        rejected.append(
            {"value": "fused",
             "reason": "Pallas ring kernel is TPU-gated on this backend"})
        return "ring", rejected
    return "alltoall", [
        {"value": "ring",
         "reason": f"measured skew {skew} < {thr}: per-step measured caps "
                   "save no wire bytes and P-1 dispatches cost more than "
                   "one collective"},
        {"value": "fused",
         "reason": f"measured skew {skew} < {thr}: nothing for the fused "
                   "measured schedule to win back"},
    ]


def _decide_wave_elems(inputs: dict) -> tuple[int, list[dict]]:
    cur = int(inputs.get("current", WAVE_MIN_ELEMS))
    itemsize = max(int(inputs.get("itemsize", 4)), 1)
    devbytes = int(inputs.get("max_device_bytes", 0) or 0)
    peak = int(inputs.get("peak_bytes", 0) or 0)
    if devbytes <= 0:
        return cur, [
            {"value": "resize",
             "reason": "no device memory stats (cpu backend or no "
                       "hbm_watermark observed): keeping wave_elems"},
        ]
    budget = int(devbytes * WAVE_HBM_BUDGET_FRAC)
    if peak > 0:
        per_elem = max(float(peak) / max(cur, 1), float(itemsize))
        basis = f"measured hbm_watermark peak {peak} B at {cur} elems/wave"
    else:
        per_elem = itemsize * WAVE_WORKING_SET_FACTOR
        basis = (f"static working-set model ({WAVE_WORKING_SET_FACTOR:g} x "
                 f"{itemsize} B/key)")
    target = max(int(budget / per_elem), 2)
    chosen = 1 << max(target.bit_length() - 1, 1)
    chosen = max(WAVE_MIN_ELEMS, min(WAVE_MAX_ELEMS, chosen))
    rejected = [
        {"value": chosen * 2,
         "reason": f"{basis}: predicted {int(chosen * 2 * per_elem)} B "
                   f"exceeds the {budget} B budget "
                   f"({WAVE_HBM_BUDGET_FRAC:g} x {devbytes} B device)"},
    ]
    if chosen != cur:
        rejected.append({"value": cur, "reason": f"{basis}: resized"})
    return chosen, rejected


def _decide_redundancy(inputs: dict) -> tuple[int, list[dict]]:
    agents = int(inputs.get("agents", 0))
    degraded = int(inputs.get("degraded", 0))
    losses = int(inputs.get("loss_events", 0))
    cur = int(inputs.get("current", 1))
    if agents <= 0 and losses == 0:
        return cur, [
            {"value": "resize",
             "reason": "no fleet health signal observed: keeping redundancy"},
        ]
    frac = degraded / agents if agents > 0 else 0.0
    if losses > 0 or frac >= REDUNDANCY_DEGRADED_FRAC:
        why = (f"{losses} loss event(s), {degraded}/{agents} agent(s) "
               f"degraded")
        return 2, [
            {"value": 1,
             "reason": f"{why}: a re-run posture re-sorts every lost key; "
                       "one replica recovers with a local merge"},
            {"value": 3,
             "reason": f"{why}: a second replica pays 3x exchange wire "
                       "bytes against a multi-loss rate nobody observed"},
        ]
    return 1, [
        {"value": 2,
         "reason": f"healthy fleet ({degraded}/{agents} degraded, "
                   f"{losses} losses): the replica wire-byte premium buys "
                   "no observed recovery"},
    ]


def _decide_redundancy_mode(inputs: dict) -> tuple[str, list[dict]]:
    """HOW a bought replica plane ships its premium (ARCHITECTURE §18).

    Deliberately a SEPARATE pure policy from `_decide_redundancy` (whose
    journaled decisions must keep replaying bit-identically): the r
    policy answers "buy availability at all?"; this one answers "full
    copies or parity slots?".  Observed LOSSES argue for full copies —
    replicate recovery needs no parity solve and tolerates a holder-set
    loss shape parity's budget might not — while a merely DEGRADED fleet
    (slow-but-alive agents, the straggler-serve case) gets parity's near
    1/P x wire premium at the same single-loss survivability.
    """
    agents = int(inputs.get("agents", 0))
    degraded = int(inputs.get("degraded", 0))
    losses = int(inputs.get("loss_events", 0))
    frac = degraded / agents if agents > 0 else 0.0
    if losses > 0:
        return "replicate", [
            {"value": "parity",
             "reason": f"{losses} observed loss event(s): full copies "
                       "recover any r-1 holder losses without a parity "
                       "solve or its erasure-budget shape limits"},
        ]
    if agents > 0 and frac >= REDUNDANCY_DEGRADED_FRAC:
        return "parity", [
            {"value": "replicate",
             "reason": f"{degraded}/{agents} agent(s) degraded but zero "
                       "losses: parity buys the same single-loss cover "
                       "(and the straggler-serve race) at ~1/P x the "
                       "(r-1)x replica wire premium"},
        ]
    return "replicate", [
        {"value": "parity",
         "reason": f"healthy fleet ({degraded}/{agents} degraded, "
                   f"{losses} losses): nothing to optimize; the default "
                   "mode keeps recovery solve-free"},
    ]


def _decide_slice_devices(inputs: dict) -> tuple[int, list[dict]]:
    """Devices per small-job serving slice, sized from the admission mix.

    The serving layer's slice width was a hand-set flag
    (``SERVE_SLICE_DEVICES``); this policy picks the smallest
    power-of-two divisor of the device count whose per-device share of
    the admission mix's p90 rung stays under `SLICE_KEYS_PER_DEVICE` —
    small jobs keep 1-device slices (maximum packing parallelism),
    a heavier mix widens the slice before the merge phase saturates a
    single chip.
    """
    ndev = int(inputs.get("num_devices", 1))
    cur = int(inputs.get("current", 1))
    rungs = [int(r) for r in inputs.get("rungs", ())]
    if ndev < 1:
        ndev = 1
    widths = [w for w in (1, 2, 4, 8, 16, 32, 64)
              if w <= ndev and ndev % w == 0]
    if not rungs:
        return cur, [
            {"value": "resize",
             "reason": "no admissions observed: keeping slice_devices"},
        ]
    p90 = int(np.percentile(rungs, 90))
    chosen = widths[-1]
    for w in widths:
        if p90 / w <= SLICE_KEYS_PER_DEVICE:
            chosen = w
            break
    rejected = []
    for w in widths:
        if w < chosen:
            rejected.append(
                {"value": w,
                 "reason": f"p90 admission rung {p90} keys / {w} device(s)"
                           f" = {p90 // w} > {SLICE_KEYS_PER_DEVICE} "
                           "keys/device: the merge phase saturates"})
        elif w > chosen:
            rejected.append(
                {"value": w,
                 "reason": f"p90 admission rung {p90} fits {chosen} "
                           "device(s); a wider slice halves the packing "
                           "parallelism for no merge relief"})
    if chosen != cur:
        rejected.append({"value": cur, "reason": "resized to the mix"})
    return chosen, rejected


def _decide_prewarm(inputs: dict) -> tuple[list, list[dict]]:
    history = [str(h) for h in inputs.get("history", ())]
    ladder = [int(r) for r in inputs.get("ladder", ())]
    dtype = str(inputs.get("dtype", "int32"))
    limit = int(inputs.get("limit", 0)) or len(ladder) or len(history)
    if not history:
        # Cold start: no admission mix to predict from — the exhaustive
        # ladder is the only honest warm set.
        return [variant_key_label(r, dtype) for r in ladder], []
    counts = Counter(history)
    ranked = sorted(counts, key=lambda lbl: (-counts[lbl], lbl))[:limit]
    chosen = sorted(ranked)
    keep = set(chosen)
    rejected = [
        {"value": variant_key_label(r, dtype),
         "reason": f"not admitted in the last {len(history)} job(s)"}
        for r in ladder if variant_key_label(r, dtype) not in keep
    ]
    return chosen, rejected


def _decide_dispatch_timeout_s(inputs: dict) -> tuple[float, list[dict]]:
    """The fleet's per-agent SEND deadline, sized from what dispatch
    actually costs: p99 of the observed accept latencies x headroom.  The
    hand-set default (request_timeout_s, 30 s) parks a job behind a stuck
    agent for the full request budget; the measured deadline fails over in
    seconds while the headroom keeps a healthy agent's tail excursion from
    tripping a spurious re-route."""
    cur = float(inputs.get("current", 0.0) or 0.0)
    p99 = float(inputs.get("p99_s", 0.0) or 0.0)
    samples = int(inputs.get("samples", 0))
    if samples <= 0 or p99 <= 0:
        return cur, [
            {"value": "resize",
             "reason": "no dispatch-accept latency observed yet: keeping "
                       "dispatch_timeout_s"},
        ]
    chosen = round(max(DISPATCH_TIMEOUT_MIN_S,
                       p99 * DISPATCH_TIMEOUT_HEADROOM), 3)
    rejected = [
        {"value": round(p99, 6),
         "reason": f"the bare p99 of {samples} accept(s) fails over a "
                   f"healthy agent on any tail excursion "
                   f"({DISPATCH_TIMEOUT_HEADROOM:g}x headroom applied)"},
    ]
    if chosen != cur:
        rejected.append(
            {"value": cur,
             "reason": f"measured p99 {p99} s x "
                       f"{DISPATCH_TIMEOUT_HEADROOM:g} headroom resized "
                       "the send deadline"})
    return chosen, rejected


_POLICY_FNS = {
    "exchange": _decide_exchange,
    "wave_elems": _decide_wave_elems,
    "redundancy": _decide_redundancy,
    "redundancy_mode": _decide_redundancy_mode,
    "prewarm": _decide_prewarm,
    "dispatch_timeout_s": _decide_dispatch_timeout_s,
    "slice_devices": _decide_slice_devices,
}


def replay_decision(policy: str, inputs: dict) -> tuple:
    """Recompute one decision from its journaled inputs — THE replay
    seam: ``plan_decision.chosen`` must equal
    ``replay_decision(policy, inputs)[0]`` for every journaled decision
    (`obs.analyze`'s ``plan`` verdict pins the mismatch count at 0)."""
    try:
        fn = _POLICY_FNS[policy]
    except KeyError:
        raise ValueError(
            f"unknown plan policy {policy!r}; registered: {PLAN_POLICIES}"
        ) from None
    return fn(dict(inputs or {}))


# -- the planner (rolling state = a fold over journal records) ---------------

class Planner:
    """Backend-free closed-loop tuner: observes journaled signals, decides.

    Attach it to a job's `Metrics` like the other live consumers
    (``planner.attach(metrics)`` — it is a standard event tap), feed it
    with `observe`, and ask it to fill knobs with `decide`.  Every
    decision emits ``plan_decision`` (and bumps ``plan_decisions``);
    every explicit-flag win emits ``plan_override``.  `state_dict` /
    `replay` pin the live-state == journal-replay contract.
    """

    def __init__(self, job=None, history: int = PREWARM_HISTORY):
        self.job = job
        self._lock = threading.Lock()
        self._admissions: deque = deque(maxlen=int(history))
        self._dispatch_lat: deque = deque(maxlen=DISPATCH_LATENCY_HISTORY)
        self._hbm_peak = 0
        self._max_device_bytes = 0
        self._loss_events = 0
        self._degraded: dict[str, bool] = {}
        self.decisions = Counter()
        self.overrides = Counter()
        self._last: dict[str, dict] = {}

    # -- signal ingestion (Metrics tap protocol) ----------------------------

    def attach(self, metrics) -> None:
        metrics.taps.append(self)

    def observe(self, etype: str, fields: dict, mono=None, metrics=None) -> None:
        """Fold one journal event into the rolling control inputs.

        The same signature as every other live tap; also the replay
        seam — `replay` calls this for each journal record, so anything
        folded here is by construction recomputable from the journal.
        """
        with self._lock:
            if etype == "job_admitted":
                n = fields.get("n_keys")
                if n:
                    self._admissions.append(variant_key_label(
                        plan_rung(int(n)), str(fields.get("dtype", "int32"))
                    ))
            elif etype == "hbm_watermark":
                self._hbm_peak = max(
                    self._hbm_peak, int(fields.get("bytes_in_use", 0) or 0)
                )
                self._max_device_bytes = max(
                    self._max_device_bytes,
                    int(fields.get("max_device_bytes", 0) or 0),
                )
            elif etype == "job_dispatched":
                # The accept round-trip the send deadline must cover — the
                # dispatch_timeout_s policy's measured input.
                lat = fields.get("accept_latency_s")
                if lat:
                    self._dispatch_lat.append(float(lat))
            elif etype == "worker_dead":
                self._loss_events += 1
            elif (etype == "job_rerouted"
                  and fields.get("reason") == "agent_lost"):
                # The fleet controller's loss signal: an agent died with
                # work on it (each re-route journals one of these).
                self._loss_events += 1
            elif etype == "health_verdict":
                aid = fields.get("agent")
                if aid is not None:
                    self._degraded[str(aid)] = bool(fields.get("degraded"))

    def state_dict(self) -> dict:
        """The rolling control inputs — exactly reproducible by `replay`
        over the journal (the live == replay pin)."""
        with self._lock:
            return {
                "admissions": list(self._admissions),
                "dispatch_latencies": [float(x) for x in self._dispatch_lat],
                "hbm_peak": self._hbm_peak,
                "max_device_bytes": self._max_device_bytes,
                "loss_events": self._loss_events,
                "degraded": dict(self._degraded),
            }

    @classmethod
    def replay(cls, records, job=None) -> "Planner":
        """Rebuild a planner's rolling state from journal records."""
        p = cls(job=job)
        for r in records:
            fields = {k: v for k, v in r.items()
                      if k not in ("type", "seq", "t", "mono")}
            p.observe(r.get("type", ""), fields)
        return p

    # -- precedence ---------------------------------------------------------

    def enabled(self) -> bool:
        return bool(self.job is not None and getattr(self.job, "autotune", False))

    def explicit_value(self, knob: str, call_value=None):
        """The winning explicit value for ``knob``, or None when the knob
        is genuinely unset (per-call override > CLI/conf explicit)."""
        if call_value is not None:
            return call_value
        if self.job is not None and knob in getattr(self.job, "explicit", ()):
            return getattr(self.job, knob, None)
        return None

    # -- decision emission --------------------------------------------------

    def decide(self, policy: str, inputs: dict, metrics=None):
        """Run one policy, journal the decision, return the chosen value."""
        chosen, rejected = replay_decision(policy, inputs)
        with self._lock:
            self.decisions[policy] += 1
            self._last[policy] = {"chosen": chosen, "inputs": dict(inputs)}
        if metrics is not None:
            metrics.bump("plan_decisions")
            metrics.event(
                "plan_decision", policy=policy, chosen=chosen,
                inputs=dict(inputs), rejected=rejected,
            )
        return chosen

    def note_override(self, policy: str, explicit, inputs: dict, metrics=None):
        """Journal an explicit-flag win: the planner yields, the journal
        records what it would have chosen.  Returns the explicit value."""
        planned, _ = replay_decision(policy, inputs)
        with self._lock:
            self.overrides[policy] += 1
        if metrics is not None:
            metrics.bump("plan_overrides")
            metrics.event(
                "plan_override", policy=policy, explicit=explicit,
                planned=planned, inputs=dict(inputs),
            )
        return explicit

    def resolve(self, policy: str, inputs: dict, metrics=None, call_value=None):
        """The one precedence seam: explicit flag > planner > caller default.

        Returns the value to use, or None when autotune is off and
        nothing was explicit (the caller's existing default applies).
        """
        explicit = self.explicit_value(policy, call_value)
        if not self.enabled():
            return explicit
        if explicit is not None:
            return self.note_override(policy, explicit, inputs, metrics)
        return self.decide(policy, inputs, metrics)

    # -- policy input builders (state -> inputs dicts) ----------------------

    def wave_inputs(self, current: int, itemsize: int,
                    max_device_bytes: int | None = None) -> dict:
        st = self.state_dict()
        return {
            "current": int(current),
            "itemsize": int(itemsize),
            "peak_bytes": st["hbm_peak"],
            "max_device_bytes": int(max_device_bytes or 0)
            or st["max_device_bytes"],
        }

    def prewarm_inputs(self, ladder, dtype: str, limit: int = 0) -> dict:
        st = self.state_dict()
        return {
            "history": st["admissions"],
            "ladder": [int(r) for r in ladder],
            "dtype": str(dtype),
            "limit": int(limit),
        }

    def dispatch_timeout_inputs(self, current: float | None = None) -> dict:
        st = self.state_dict()
        lats = st["dispatch_latencies"]
        p99 = float(np.percentile(lats, 99)) if lats else 0.0
        return {
            "current": float(current or 0.0),
            "p99_s": round(p99, 6),
            "samples": len(lats),
        }

    def redundancy_inputs(self, current: int = 1,
                          scores: dict | None = None) -> dict:
        st = self.state_dict()
        degraded = dict(st["degraded"])
        if scores is not None:
            # Controller path: the live HealthAnalyzer view supersedes the
            # folded events (same verdicts, fresher window).
            degraded = {str(a): bool(d) for a, (d, _) in scores.items()}
        return {
            "agents": len(degraded),
            "degraded": sum(1 for d in degraded.values() if d),
            "loss_events": st["loss_events"],
            "current": int(current),
        }

    def redundancy_mode_inputs(self, scores: dict | None = None) -> dict:
        """Same fleet-health signal as `redundancy_inputs`, minus the
        integer ``current`` (the mode axis has no resize semantics)."""
        inputs = self.redundancy_inputs(scores=scores)
        inputs.pop("current", None)
        return inputs

    def slice_inputs(self, current: int, num_devices: int) -> dict:
        st = self.state_dict()
        return {
            "current": int(current),
            "num_devices": int(num_devices),
            # Admission labels are "rung:dtype" (variant_key_label);
            # the slice policy sizes on the rung alone.
            "rungs": [int(lbl.split(":", 1)[0]) for lbl in st["admissions"]],
        }

    # -- snapshots ----------------------------------------------------------

    def snapshot(self) -> dict:
        """Per-policy decision/override counts + last choice (the gauge
        and ``dsort top`` pane source)."""
        with self._lock:
            return {
                policy: {
                    "decisions": self.decisions.get(policy, 0),
                    "overrides": self.overrides.get(policy, 0),
                    "last": self._last.get(policy, {}).get("chosen"),
                }
                for policy in PLAN_POLICIES
            }


# -- the sample_sort / wave_sort module seams (no shared state needed) -------

def planned_exchange(job, data, num_workers: int, metrics=None,
                     call_value=None, fused_ok: bool = False,
                     redundancy: int | None = None, hosts: int = 0):
    """The `SampleSort._dispatch_keys` autotune seam.

    Returns the exchange value to resolve (explicit > planner) or None
    (autotune off, nothing explicit: the config default applies
    unplanned, exactly the pre-planner behavior).  ``hosts`` is the
    MEASURED host topology (the caller's `resolve_hier_hosts` result —
    this module is backend-free and cannot probe the process count
    itself); >= 2 with >= 2 devices per host arms the two-level "hier"
    schedule.
    """
    if job is None or not getattr(job, "autotune", False):
        return call_value
    planner = Planner(job=job)
    explicit = planner.explicit_value("exchange", call_value)
    inputs = probe_skew(data, num_workers)
    inputs["fused_ok"] = bool(fused_ok)
    inputs["hosts"] = int(hosts)
    inputs["redundancy"] = int(
        redundancy if redundancy is not None
        else getattr(job, "redundancy", 1)
    )
    if explicit is not None:
        return planner.note_override("exchange", explicit, inputs, metrics)
    return planner.decide("exchange", inputs, metrics)


def planned_wave_elems(job, current: int, itemsize: int, records=(),
                       metrics=None, max_device_bytes: int | None = None) -> int:
    """The `ExternalWaveSort` autotune seam: size the wave from the
    journal's ``hbm_watermark`` ledger (``records``) instead of the
    hand-set default.  Returns the wave size to use."""
    if job is None or not getattr(job, "autotune", False):
        return int(current)
    planner = Planner.replay(records, job=job)
    inputs = planner.wave_inputs(current, itemsize, max_device_bytes)
    if "wave_elems" in getattr(job, "explicit", ()):
        return int(planner.note_override(
            "wave_elems", int(current), inputs, metrics
        ))
    return int(planner.decide("wave_elems", inputs, metrics))


def planned_slice_devices(job, serve, current: int, num_devices: int,
                          records=(), metrics=None) -> int:
    """The `serve.SortService` slice-width autotune seam (mirrors
    `planned_wave_elems`): size the small-job mesh sub-slice from the
    journaled admission mix instead of the hand-set
    ``SERVE_SLICE_DEVICES``.  Returns the slice width to use; the
    explicit flag/conf key wins with a journaled ``plan_override``.
    """
    if job is None or not getattr(job, "autotune", False):
        return int(current)
    planner = Planner.replay(records, job=job)
    inputs = planner.slice_inputs(current, num_devices)
    explicit = (
        "slice_devices" in getattr(job, "explicit", ())
        or (serve is not None and "slice_devices" in getattr(serve, "explicit", ()))
    )
    if explicit:
        return int(planner.note_override(
            "slice_devices", int(current), inputs, metrics
        ))
    return int(planner.decide("slice_devices", inputs, metrics))


# -- shared renderer (dsort top planner pane / report) -----------------------

def plan_table(rows, indent: str = "  ") -> str:
    """Render planner rows: ``(policy, decisions, overrides, last)``."""
    if not rows:
        return f"{indent}(no planner decisions)"
    head = f"{indent}{'policy':<12} {'decisions':>9} {'overrides':>9}  last chosen"
    lines = [head]
    for policy, dec, ovr, last in rows:
        if isinstance(last, (list, tuple)):
            shown = f"[{len(last)} key(s)]" if last else "[]"
        else:
            shown = "-" if last is None else str(last)
        lines.append(
            f"{indent}{policy:<12} {int(dec):>9} {int(ovr):>9}  {shown}"
        )
    return "\n".join(lines)
