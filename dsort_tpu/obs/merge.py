"""Journal aggregation: many per-process JSONL journals -> ONE global trace.

A multi-host run writes one journal per process, each stamped on its OWN
monotonic clock (arbitrary base per process) plus wall time.  Merging by
wall time alone jitters (NTP steps, coarse wall resolution mid-run);
merging by mono alone is meaningless across processes.  Every event already
carries BOTH stamps, so each journal's wall<->mono offset is recoverable:

    offset_j = median over events of (t - mono)

``clock_sync`` events (one per process at job start, one per native
coordinator drain) bless a dedicated pair for exactly this purpose and are
preferred when present.  The merger rebases every journal's ``mono`` onto
journal 0's monotonic base via these offsets, tags each record with its
source index (``src``), sorts, and reseqs — one coherent fleet timeline
that `format_report` and `to_chrome_trace` (one pid per source, one tid
per job) consume unchanged.

Torn lines (a crashed process mid-write), non-JSON garbage and records
missing their stamps are SKIPPED AND COUNTED, never raised: a journal is a
diagnostic artifact and a postmortem must render whatever survived.
"""

from __future__ import annotations

import json
import os
import re
import statistics

from dsort_tpu.utils.logging import get_logger

log = get_logger("obs.merge")

#: Keys a record must carry (with numeric stamps) to be mergeable.
_REQUIRED = ("type", "t", "mono")


def read_journal(path: str) -> tuple[list[dict], int]:
    """Tolerantly read one JSONL journal: ``(records, skipped_lines)``."""
    records: list[dict] = []
    skipped = 0
    with open(path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                obj = json.loads(line)
            except json.JSONDecodeError:
                skipped += 1
                continue
            if not isinstance(obj, dict) or not all(
                k in obj for k in _REQUIRED
            ) or not all(
                isinstance(obj[k], (int, float)) for k in ("t", "mono")
            ):
                skipped += 1
                continue
            records.append(obj)
    if skipped:
        log.warning("journal %s: skipped %d malformed line(s)", path, skipped)
    return records, skipped


def wall_mono_offset(records: list[dict]) -> float:
    """One journal's wall-minus-mono offset (``clock_sync`` pairs preferred,
    median over all events otherwise — robust to a few torn stamps)."""
    if not records:
        return 0.0
    pairs = [
        r["t"] - r["mono"] for r in records if r["type"] == "clock_sync"
    ] or [r["t"] - r["mono"] for r in records]
    return float(statistics.median(pairs))


def peer_shifts(journals: list[list[dict]], shifts: list[float]) -> list[float]:
    """Refine wall-derived shifts with PEER clock blessings (fleet runs).

    The fleet protocol carries ``(wall, mono)`` pairs on ``hello``/
    ``welcome``/``heartbeat`` frames; each side journals the peer's pair as
    a ``clock_sync`` event with ``peer``/``peer_mono`` fields next to its
    OWN stamps.  Where journal *k* blesses the peer that identifies
    journal *j* (its ``clock_sync`` carries ``source == peer``), journal
    *j*'s shift becomes purely MONOTONIC::

        shift_j = shift_k + (blessing record's mono - peer_mono)

    — the receipt instant in *k*'s frame minus the peer's mono at send, so
    a live fleet merges correctly even when an agent's WALL clock is
    skewed (no shared journal file, no NTP trust).  The blessings form a
    relation graph (symmetric blessings are one edge usable both ways);
    each connected component is ANCHORED at its lowest journal index
    (journal 0 when present — the reference frame; otherwise the
    component's wall-derived shift stands for its anchor) and resolved by
    BFS, each journal's shift overridden AT MOST ONCE — mutual
    controller<->agent blessings are a cycle whose redundant edge (one
    network round-trip of disagreement) is ignored, never accumulated.
    """
    sources: dict[str, int] = {}
    blessings: dict[str, tuple[int, float, float]] = {}
    for j, recs in enumerate(journals):
        for r in recs:
            if r.get("type") != "clock_sync":
                continue
            if r.get("source") is not None:
                sources.setdefault(str(r["source"]), j)
            if r.get("peer") is not None and isinstance(
                r.get("peer_mono"), (int, float)
            ):
                blessings.setdefault(
                    str(r["peer"]), (j, float(r["mono"]), float(r["peer_mono"]))
                )
    # Edges: shift_j = shift_k + d, traversable both directions.
    adj: dict[int, list[tuple[int, float]]] = {}
    for pid, (k, receipt_mono, peer_mono) in blessings.items():
        j = sources.get(pid)
        if j is None or j == k:
            continue
        d = receipt_mono - peer_mono
        adj.setdefault(k, []).append((j, d))
        adj.setdefault(j, []).append((k, -d))
    shifts = list(shifts)
    resolved: set[int] = set()
    for anchor in sorted(adj):
        if anchor in resolved:
            continue
        # The anchor keeps its incoming shift (journal 0's is exact by
        # definition; a component without journal 0 stays wall-anchored
        # through its lowest member) and mono alignment spreads outward.
        resolved.add(anchor)
        frontier = [anchor]
        while frontier:
            k = frontier.pop()
            for j, d in adj.get(k, ()):
                if j in resolved:
                    continue
                shifts[j] = shifts[k] + d
                resolved.add(j)
                frontier.append(j)
    return shifts


def merge_records(journals: list[list[dict]]) -> list[dict]:
    """Merge per-journal record lists into one aligned, re-sequenced trace.

    Journal 0's monotonic base is the reference frame; every other
    journal's ``mono`` is shifted by the difference of the wall<->mono
    offsets, so durations WITHIN a journal are exact (mono-derived) and
    placement ACROSS journals is wall-accurate.  Fleet journals carrying
    protocol-level peer blessings upgrade to purely monotonic alignment
    (`peer_shifts`).  Each record gains ``src`` (its journal index); the
    merged sequence is time-ordered and ``seq`` is rewritten to the
    global order.
    """
    base = wall_mono_offset(journals[0]) if journals else 0.0
    shifts = [wall_mono_offset(recs) - base for recs in journals]
    shifts = peer_shifts(journals, shifts)
    out: list[dict] = []
    for src, recs in enumerate(journals):
        if not recs:
            continue
        shift = shifts[src]
        for r in recs:
            r = dict(r)
            r["src"] = src
            r["mono"] = round(r["mono"] + shift, 6)
            out.append(r)
    out.sort(key=lambda r: (r["mono"], r.get("t", 0.0), r.get("seq", 0)))
    for i, r in enumerate(out):
        r["seq"] = i
    return out


def merge_journals(paths: list[str]) -> tuple[list[dict], int]:
    """Read + merge journal files: ``(merged_records, skipped_lines)``."""
    journals, skipped = [], 0
    for p in paths:
        recs, s = read_journal(str(p))
        journals.append(recs)
        skipped += s
    return merge_records(journals), skipped


# -- CLI path expansion (fleet runs produce N journals per run) --------------


def expand_path_args(paths: list[str]) -> list[str]:
    """``dsort report`` positional args -> concrete journal paths.

    Each arg may be a file, a DIRECTORY (expands to its ``*.jsonl`` files
    plus their rotation pieces, sorted), or a GLOB pattern (``fleet/
    *.jsonl`` — expanded with `glob.glob`, sorted).  A directory or
    pattern that matches nothing is a loud error: a typo'd fleet-trace
    merge must never silently render one journal as the whole fleet.
    Plain files pass through untouched (including not-yet-existing paths —
    the reader reports those).  Order: args in given order, matches sorted
    within each arg, so `group_rotated` downstream still collapses
    rotation sets.
    """
    import glob as _glob

    out: list[str] = []
    for p in paths:
        p = str(p)
        if os.path.isdir(p):
            matches = sorted(
                e for e in _glob.glob(os.path.join(p, "*.jsonl*"))
                if os.path.isfile(e)
            )
            if not matches:
                raise ValueError(f"directory {p!r} contains no *.jsonl journals")
            out.extend(matches)
        elif _glob.has_magic(p):
            matches = sorted(e for e in _glob.glob(p) if os.path.isfile(e))
            if not matches:
                raise ValueError(f"glob {p!r} matched no journal files")
            out.extend(matches)
        else:
            out.append(p)
    # One journal mentioned by two args (a glob overlapping a file arg)
    # must not merge with itself as a phantom second process.
    seen: set[str] = set()
    unique = []
    for p in out:
        if p not in seen:
            seen.add(p)
            unique.append(p)
    return unique


# -- rotated journal sets (--journal-rotate-mb) ------------------------------

_ROTATED = re.compile(r"^(?P<base>.+)\.(?P<n>\d+)$")


def rotation_base(path: str) -> str:
    """The un-rotated journal path a piece belongs to (identity for the
    base file itself)."""
    m = _ROTATED.match(str(path))
    return m.group("base") if m else str(path)


def rotated_set(path: str) -> list[str]:
    """One journal's rotated pieces in WRITE order: ``path.1`` (oldest),
    ``path.2``, ..., then ``path`` itself (newest) — exactly the order
    `EventLog.flush_jsonl` rotated them out, so concatenating the pieces
    reconstructs the original append order."""
    base = rotation_base(str(path))
    pieces = []
    d = os.path.dirname(base) or "."
    name = os.path.basename(base)
    try:
        entries = os.listdir(d)
    except OSError:
        entries = []
    for e in entries:
        m = _ROTATED.match(e)
        if m and m.group("base") == name:
            pieces.append((int(m.group("n")), os.path.join(d, e)))
    out = [p for _, p in sorted(pieces)]
    if os.path.exists(base) or not out:
        out.append(base)
    return out


def read_journal_set(paths: list[str]) -> tuple[list[dict], int]:
    """Read several files as ONE journal (a rotated set, concatenated in
    the given order): ``(records, skipped_lines)``."""
    records: list[dict] = []
    skipped = 0
    for p in paths:
        recs, s = read_journal(str(p))
        records.extend(recs)
        skipped += s
    return records, skipped


def group_rotated(paths: list[str]) -> list[list[str]]:
    """CLI args -> per-journal rotated sets, one group per logical journal.

    Each given path expands to its on-disk rotated set; paths naming
    pieces of the same journal (``a.jsonl.1 a.jsonl``) collapse into one
    group, so ``dsort report --merge`` never mistakes a rotation for a
    second process.  Group order follows first mention.

    A ``.N``-suffixed arg is treated as a rotation piece ONLY when its
    base journal is evident — also passed as an arg, or present on disk.
    Independent journals that merely end in digits (``trace.0 trace.1``,
    the per-rank naming some launchers use) each keep their own group, so
    the multi-process merge is never silently collapsed.
    """
    argset = {str(p) for p in paths}
    groups: dict[str, list[str]] = {}
    for p in paths:
        p = str(p)
        m = _ROTATED.match(p)
        if m and (m.group("base") in argset or os.path.isfile(m.group("base"))):
            base = m.group("base")
        else:
            base = p
        if base not in groups:
            # A .N-named independent journal is its own single-file group
            # (no sibling discovery — its trailing digits are not ours).
            groups[base] = [base] if _ROTATED.match(base) else rotated_set(base)
    return list(groups.values())
