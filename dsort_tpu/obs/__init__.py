"""Fleet telemetry plane (the PR 6 observability tentpole).

PR 1 made every execution mode journal typed events; this package turns
those journals — and the live event stream behind them — into an
operable telemetry surface, four pillars:

- `obs.merge`: join per-process/per-host JSONL journals into ONE global
  trace, aligning each journal's monotonic clock base via the (wall, mono)
  pairs every event already carries (``clock_sync`` events bless one pair
  per process explicitly).  Feeds ``dsort report --merge`` and the
  multi-lane Chrome-trace export.
- `obs.telemetry` + `obs.server`: a live metrics registry (counters, phase
  timings, queue depth, jobs in flight, per-tenant SLO histograms) fed by
  `Metrics` event taps, snapshotted in Prometheus text format over a
  stdlib HTTP endpoint (``dsort serve --metrics-port`` /
  ``MetricsServer``); ``dsort top`` renders a scrape as a console view.
- `obs.slo`: streaming per-job latency histograms
  (admit -> dispatch -> sorted -> fetched) keyed by the ``tenant=`` label
  `JobConfig` threads — ROADMAP item 1's admission-control signal.
- `obs.flight`: a bounded ring of recent events per scheduler that dumps a
  postmortem bundle (ring, config, mesh state, counters, the recovery
  path that fired) whenever any recovery path fires.

PR 9 adds the *why slow* plane (ARCHITECTURE §9):

- `obs.prof`: the compile/cost/HBM ledger — every jit build records
  compile seconds, XLA cost analysis and memory analysis under the same
  ladder-rung variant keys the serving cache uses (``variant_compiled``
  events, ``dsort_variant_*`` gauges) — plus the ``--memwatch`` tap
  snapshotting device memory at phase boundaries (``hbm_watermark``).
- `obs.analyze`: the journal-native why-slow verdict behind ``dsort
  report --analyze`` — phase waterfall with cross-process critical path,
  straggler attribution, queue/compile/execute split, wire bytes, skew.

PR 14 adds the LIVE half of why-slow (ARCHITECTURE §13):

- `obs.health`: the streaming counterpart of `obs.analyze` — fleet agents
  accumulate bounded telemetry deltas (`HealthDeltaCollector`, a Metrics
  tap) and ship them over the fleet protocol's ``telemetry`` frames on
  the heartbeat cadence; the controller's `HealthAnalyzer` folds them
  into rolling per-agent why-slow verdicts (straggler score, dominant
  phase, queue/compile/execute split, SLO-breach risk) that drive
  ``routing="health"``, the per-agent ``/metrics`` gauges, the ``dsort
  top`` health pane, and the degraded->flight-bundle contract.

PR 16 closes the loop (ARCHITECTURE §15):

- `obs.plan`: the planner plane — a backend-free `Planner` that folds the
  already-journaled signals (``skew_report``/probe skew, ``hbm_watermark``,
  ``job_admitted``, rolling health verdicts) into typed, replayable
  ``plan_decision`` events BEFORE dispatch: exchange selection, wave
  sizing, redundancy ``r``, and prewarm-set prediction.  Every decision
  carries its measured inputs + rejected alternatives; ``dsort report
  --analyze`` replays each one (the ``plan`` verdict key), ``/metrics``
  exports per-policy decision/override gauges, ``dsort top`` grows a
  planner pane, and explicit flags always win (journaled
  ``plan_override``; ``--no-autotune`` disables the plane entirely).
"""

from dsort_tpu.obs.analyze import (  # noqa: F401
    VERDICT_KEYS,
    analyze_records,
    format_analysis,
)
from dsort_tpu.obs.health import (  # noqa: F401
    HEALTH_VERDICT_KEYS,
    SHARED_VERDICT_KEYS,
    HealthAnalyzer,
    HealthDeltaCollector,
    format_health,
)
from dsort_tpu.obs.flight import (  # noqa: F401
    BUNDLE_SCHEMA_KEYS,
    RECOVERY_EVENTS,
    FlightRecorder,
)
from dsort_tpu.obs.histogram import LatencyHistogram  # noqa: F401
from dsort_tpu.obs.plan import (  # noqa: F401
    PLAN_DECISION_FIELDS,
    PLAN_OVERRIDE_FIELDS,
    PLAN_POLICIES,
    Planner,
    plan_table,
    probe_skew,
    replay_decision,
)
from dsort_tpu.obs.merge import (  # noqa: F401
    group_rotated,
    merge_journals,
    merge_records,
    read_journal,
    read_journal_set,
    rotated_set,
)
from dsort_tpu.obs.prof import (  # noqa: F401
    LEDGER,
    LEDGER_EVENT_FIELDS,
    CompileLedger,
    MemWatch,
    device_memory_snapshot,
    instrument_jit,
    ledger_from_journal,
    variant_label,
)
from dsort_tpu.obs.server import MetricsServer  # noqa: F401
from dsort_tpu.obs.slo import SLO_QUANTILES, SLO_STAGES, slo_from_journal  # noqa: F401
from dsort_tpu.obs.telemetry import Telemetry, parse_prometheus_text  # noqa: F401

__all__ = [
    "BUNDLE_SCHEMA_KEYS",
    "CompileLedger",
    "FlightRecorder",
    "HEALTH_VERDICT_KEYS",
    "HealthAnalyzer",
    "HealthDeltaCollector",
    "LEDGER",
    "LEDGER_EVENT_FIELDS",
    "LatencyHistogram",
    "MemWatch",
    "MetricsServer",
    "PLAN_DECISION_FIELDS",
    "PLAN_OVERRIDE_FIELDS",
    "PLAN_POLICIES",
    "Planner",
    "RECOVERY_EVENTS",
    "SHARED_VERDICT_KEYS",
    "SLO_QUANTILES",
    "SLO_STAGES",
    "Telemetry",
    "VERDICT_KEYS",
    "analyze_records",
    "device_memory_snapshot",
    "format_analysis",
    "format_health",
    "group_rotated",
    "instrument_jit",
    "ledger_from_journal",
    "merge_journals",
    "merge_records",
    "parse_prometheus_text",
    "plan_table",
    "probe_skew",
    "read_journal",
    "read_journal_set",
    "replay_decision",
    "rotated_set",
    "slo_from_journal",
    "variant_label",
]
