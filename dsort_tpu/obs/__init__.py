"""Fleet telemetry plane (the PR 6 observability tentpole).

PR 1 made every execution mode journal typed events; this package turns
those journals — and the live event stream behind them — into an
operable telemetry surface, four pillars:

- `obs.merge`: join per-process/per-host JSONL journals into ONE global
  trace, aligning each journal's monotonic clock base via the (wall, mono)
  pairs every event already carries (``clock_sync`` events bless one pair
  per process explicitly).  Feeds ``dsort report --merge`` and the
  multi-lane Chrome-trace export.
- `obs.telemetry` + `obs.server`: a live metrics registry (counters, phase
  timings, queue depth, jobs in flight, per-tenant SLO histograms) fed by
  `Metrics` event taps, snapshotted in Prometheus text format over a
  stdlib HTTP endpoint (``dsort serve --metrics-port`` /
  ``MetricsServer``); ``dsort top`` renders a scrape as a console view.
- `obs.slo`: streaming per-job latency histograms
  (admit -> dispatch -> sorted -> fetched) keyed by the ``tenant=`` label
  `JobConfig` threads — ROADMAP item 1's admission-control signal.
- `obs.flight`: a bounded ring of recent events per scheduler that dumps a
  postmortem bundle (ring, config, mesh state, counters, the recovery
  path that fired) whenever any recovery path fires.
"""

from dsort_tpu.obs.flight import (  # noqa: F401
    BUNDLE_SCHEMA_KEYS,
    RECOVERY_EVENTS,
    FlightRecorder,
)
from dsort_tpu.obs.histogram import LatencyHistogram  # noqa: F401
from dsort_tpu.obs.merge import merge_journals, merge_records, read_journal  # noqa: F401
from dsort_tpu.obs.server import MetricsServer  # noqa: F401
from dsort_tpu.obs.slo import SLO_QUANTILES, SLO_STAGES, slo_from_journal  # noqa: F401
from dsort_tpu.obs.telemetry import Telemetry, parse_prometheus_text  # noqa: F401

__all__ = [
    "BUNDLE_SCHEMA_KEYS",
    "FlightRecorder",
    "LatencyHistogram",
    "MetricsServer",
    "RECOVERY_EVENTS",
    "SLO_QUANTILES",
    "SLO_STAGES",
    "Telemetry",
    "merge_journals",
    "merge_records",
    "parse_prometheus_text",
    "read_journal",
    "slo_from_journal",
]
