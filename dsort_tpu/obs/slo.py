"""Per-tenant SLO stage latencies, derivable live AND from a journal.

The job lifecycle the journal already records maps onto four stage
boundaries — ``job_start`` (admit), the first ``attempt_start`` (dispatch),
``job_done`` (sorted), ``result_fetch`` (fetched) — so the SLO metrics are
pure derivation, no new instrumentation per execution mode.  One shared
derivation (`_JobState.durations`) backs both consumers:

- LIVE: `telemetry._TelemetryTap` feeds events into `_JobState` as they
  are emitted (with the journal's own monotonic stamps, `Metrics.event`)
  and pushes completed stage durations into the tenant-keyed
  `LatencyHistogram` set the metrics endpoint snapshots;
- POST-HOC: `slo_from_journal` replays a journal's records through the
  identical state machine, so a scrape and a journal replay of the same
  session report byte-identical quantiles — the property the serve-smoke
  gate asserts.

The ``tenant`` label rides the ``job_start`` event (threaded from
``JobConfig.tenant``); jobs in an interleaved journal are told apart by the
``job`` ordinal `Metrics.event` stamps on every record.
"""

from __future__ import annotations

from dsort_tpu.obs.histogram import LatencyHistogram

#: The stage vocabulary, in lifecycle order.  ``admit_to_sorted`` is the
#: end-to-end figure admission control (ROADMAP item 1) keys on.
SLO_STAGES: tuple[str, ...] = (
    "admit_to_dispatch",
    "dispatch_to_sorted",
    "sorted_to_fetched",
    "admit_to_sorted",
)

#: Quantiles the endpoint exposes per (tenant, stage).
SLO_QUANTILES: tuple[float, ...] = (0.5, 0.95, 0.99)

DEFAULT_TENANT = "default"


class _JobState:
    """Stage-boundary stamps of one in-flight job (keyed by ``job`` ordinal)."""

    __slots__ = ("tenant", "admit", "dispatch", "sorted")

    def __init__(self, tenant: str, admit: float):
        self.tenant = tenant
        self.admit = admit
        self.dispatch: float | None = None
        self.sorted: float | None = None

    def durations(self, done_mono: float) -> list[tuple[str, float]]:
        """Stage durations closable at ``job_done``/``job_failed`` time."""
        out = [("admit_to_sorted", done_mono - self.admit)]
        if self.dispatch is not None:
            out.append(("admit_to_dispatch", self.dispatch - self.admit))
            out.append(("dispatch_to_sorted", done_mono - self.dispatch))
        return out


class SloStateMachine:
    """The shared event -> stage-duration derivation.

    Call `step` with every event (in emission order per job); completed
    stage durations are reported through ``sink(tenant, stage, seconds)``.
    Uses only GIL-atomic dict/attr operations: concurrent emitters (the
    taskpool's shard threads) can at worst race two first-``attempt_start``
    stamps carrying near-identical monos — job_start/job_done, which gate
    the histograms, are single-threaded in every execution mode.
    """

    def __init__(self, sink):
        self._sink = sink
        self._jobs: dict = {}       # job ordinal -> _JobState
        self._done: dict = {}       # job ordinal -> (tenant, sorted mono)

    def step(self, etype: str, fields: dict, mono: float) -> None:
        job = fields.get("job")
        if etype == "job_start":
            # A repeated job_start on one ordinal is the fused path falling
            # back to the scheduler: admission already happened, keep it.
            if job not in self._jobs:
                self._jobs[job] = _JobState(
                    str(fields.get("tenant", DEFAULT_TENANT)), mono
                )
        elif etype == "attempt_start":
            st = self._jobs.get(job)
            if st is not None and st.dispatch is None:
                st.dispatch = mono
        elif etype in ("job_done", "job_failed"):
            st = self._jobs.pop(job, None)
            if st is not None:
                for stage, sec in st.durations(mono):
                    self._sink(st.tenant, stage, sec)
                if etype == "job_done":
                    self._done[job] = (st.tenant, mono)
                    # Bound retained terminal states: the fetch (if any)
                    # follows its job_done closely; a session never needs
                    # more than a handful pending.
                    while len(self._done) > 64:
                        self._done.pop(next(iter(self._done)))
        elif etype == "result_fetch":
            done = self._done.pop(job, None)
            if done is not None:
                tenant, sorted_mono = done
                self._sink(tenant, "sorted_to_fetched", mono - sorted_mono)

    @property
    def in_flight(self) -> int:
        return len(self._jobs)

    def tenant_of(self, job, default: str = DEFAULT_TENANT) -> str:
        """Tenant of an in-flight job ordinal (``default`` when unknown)."""
        st = self._jobs.get(job)
        return st.tenant if st is not None else default


def slo_from_journal(records: list[dict]) -> dict[tuple[str, str], LatencyHistogram]:
    """Replay a journal into ``{(tenant, stage): LatencyHistogram}``.

    Accepts raw or merged (`obs.merge`) records; jobs are keyed by
    ``(src, job)`` so a merged multi-host trace never conflates two hosts'
    ordinals.  Records predating the ``job`` stamp are skipped — no guess
    beats no data for an SLO.
    """
    hists: dict[tuple[str, str], LatencyHistogram] = {}

    def sink(tenant: str, stage: str, seconds: float) -> None:
        key = (tenant, stage)
        h = hists.get(key)
        if h is None:
            h = hists[key] = LatencyHistogram()
        h.observe(seconds)

    machines: dict = {}  # src -> SloStateMachine
    for r in sorted(records, key=lambda r: (r.get("mono", 0.0), r.get("seq", 0))):
        if "job" not in r or "mono" not in r:
            continue
        src = r.get("src", 0)
        m = machines.get(src)
        if m is None:
            m = machines[src] = SloStateMachine(sink)
        fields = {k: v for k, v in r.items() if k not in ("seq", "t", "mono", "type")}
        m.step(r["type"], fields, r["mono"])
    return hists
