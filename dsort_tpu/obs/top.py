"""``dsort top``: render one metrics scrape as a console snapshot.

Scrapes the `obs.server` endpoint (stdlib urllib), parses the Prometheus
text through the same minimal parser the tier-1 gate uses, and renders the
operator view: jobs in flight / queue depth, per-tenant job outcomes and
SLO stage quantiles, phase wall time, and the nonzero counters.  One-shot
by default; ``--interval`` refreshes until Ctrl-C.
"""

from __future__ import annotations

import urllib.request

from dsort_tpu.obs.slo import SLO_QUANTILES
from dsort_tpu.obs.telemetry import parse_prometheus_text


def fetch_metrics(url: str, timeout: float = 5.0) -> dict:
    """Scrape + parse one snapshot from a ``/metrics`` URL."""
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return parse_prometheus_text(resp.read().decode("utf-8"))


def _labeled(parsed: dict, metric: str) -> list[tuple[dict, float]]:
    return [
        (dict(labels), value)
        for (name, labels), value in sorted(parsed.items())
        if name == metric
    ]


def render_top(parsed: dict) -> str:
    """The console snapshot for one parsed scrape."""
    lines = []
    in_flight = parsed.get(("dsort_jobs_in_flight", ()), 0.0)
    queue = parsed.get(("dsort_queue_depth", ()), 0.0)
    lines.append(
        f"jobs in flight: {int(in_flight)}    queue depth: {int(queue)}"
    )
    # Compiled-variant cache (serving layer): entries/hits/misses/prewarmed
    # ride as gauges; the hit rate is the headline the operator watches.
    hits = parsed.get(("dsort_variant_cache_hits", ()), 0.0)
    misses = parsed.get(("dsort_variant_cache_misses", ()), 0.0)
    if hits or misses or ("dsort_variant_cache_entries", ()) in parsed:
        entries = int(parsed.get(("dsort_variant_cache_entries", ()), 0.0))
        prewarmed = int(parsed.get(("dsort_variant_cache_prewarmed", ()), 0.0))
        rate = hits / (hits + misses) if (hits + misses) else 0.0
        lines.append(
            f"variant cache: {entries} entries    hits {int(hits)}  "
            f"misses {int(misses)}  prewarmed {prewarmed}  "
            f"hit rate {rate * 100:.1f}%"
        )
    # Compile/cost/HBM ledger (obs.prof): one row per compiled variant.
    from dsort_tpu.obs.prof import LEDGER_GAUGES

    ledger: dict[str, dict] = {}
    for metric, field in LEDGER_GAUGES:
        for labels, value in _labeled(parsed, metric):
            ledger.setdefault(labels.get("variant", "?"), {})[field] = value
    if ledger:
        lines.append("variant ledger:")
        lines.append(
            f"  {'variant':<50}{'compiles':>9}"
            f"{'compile ms':>12}{'flops':>14}{'peak HBM':>14}"
        )
        for variant in sorted(ledger):
            row = ledger[variant]
            lines.append(
                f"  {variant:<50}{int(row.get('compiles', 0)):>9}"
                f"{row.get('compile_s', 0.0) * 1e3:>12.1f}"
                f"{row.get('flops', 0.0):>14.3g}"
                f"{int(row.get('peak_hbm_bytes', 0)):>14,}"
            )
    jobs = _labeled(parsed, "dsort_jobs_total")
    if jobs:
        lines.append("jobs:")
        for labels, value in jobs:
            lines.append(
                f"  {labels.get('tenant', '?'):<16} "
                f"{labels.get('outcome', '?'):<8} {int(value):>8}"
            )
    admissions = _labeled(parsed, "dsort_admissions_total")
    if admissions:
        lines.append("admissions:")
        for labels, value in admissions:
            lines.append(
                f"  {labels.get('tenant', '?'):<16} "
                f"{labels.get('reason', '?'):<14} {int(value):>8}"
            )
    # SLO table: one row per (tenant, stage) with its quantile columns.
    slo: dict[tuple[str, str], dict] = {}
    for labels, value in _labeled(parsed, "dsort_job_stage_seconds"):
        key = (labels.get("tenant", "?"), labels.get("stage", "?"))
        slo.setdefault(key, {})[labels.get("quantile", "?")] = value
    counts = {
        (labels.get("tenant", "?"), labels.get("stage", "?")): value
        for labels, value in _labeled(parsed, "dsort_job_stage_seconds_count")
    }
    if slo:
        qcols = "".join(f"{f'p{int(q * 100)}':>10}" for q in SLO_QUANTILES)
        lines.append(f"slo (ms): {'tenant/stage':<38}{qcols}{'count':>8}")
        for (tenant, stage) in sorted(slo):
            row = slo[(tenant, stage)]
            cells = "".join(
                f"{row.get(str(q), 0.0) * 1e3:>10.2f}" for q in SLO_QUANTILES
            )
            lines.append(
                f"  {tenant + '/' + stage:<44}{cells}"
                f"{int(counts.get((tenant, stage), 0)):>8}"
            )
    phases = _labeled(parsed, "dsort_phase_seconds_total")
    if phases:
        lines.append("phase wall time:")
        for labels, value in phases:
            lines.append(
                f"  {labels.get('phase', '?'):<20} {value * 1e3:>12.3f} ms"
            )
    counters = [
        (labels.get("name", "?"), value)
        for labels, value in _labeled(parsed, "dsort_counter_total")
        if value
    ]
    if counters:
        lines.append("counters (nonzero):")
        for name, value in counters:
            lines.append(f"  {name:<28} {int(value):>10}")
    return "\n".join(lines) + "\n"
