"""``dsort top``: render metrics scrape(s) as a console snapshot.

Scrapes one or more `obs.server` endpoints (stdlib urllib), parses the
Prometheus text through the same minimal parser the tier-1 gate uses, and
renders the operator view: jobs in flight / queue depth, per-tenant job
outcomes and SLO stage quantiles, phase wall time, and the nonzero
counters.  With SEVERAL URLs (a fleet run: the controller's endpoint plus
one per agent, ARCHITECTURE §12) `render_fleet` shows a per-mesh summary
row for each source plus COMBINED admissions and variant-cache tables
summed across the fleet.  One-shot by default; ``--interval`` refreshes
until Ctrl-C.
"""

from __future__ import annotations

import urllib.request

from dsort_tpu.obs.slo import SLO_QUANTILES
from dsort_tpu.obs.telemetry import parse_prometheus_text


def fetch_metrics(url: str, timeout: float = 5.0) -> dict:
    """Scrape + parse one snapshot from a ``/metrics`` URL."""
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return parse_prometheus_text(resp.read().decode("utf-8"))


def _labeled(parsed: dict, metric: str) -> list[tuple[dict, float]]:
    return [
        (dict(labels), value)
        for (name, labels), value in sorted(parsed.items())
        if name == metric
    ]


def _health_rows(parsed: dict) -> dict[str, dict]:
    """Per-agent health cells from the controller's labeled gauges
    (`obs.health` via `Telemetry.set_series`, ARCHITECTURE §13)."""
    rows: dict[str, dict] = {}
    for metric, field in (
        ("dsort_agent_health_score", "score"),
        ("dsort_agent_health_degraded", "degraded"),
        ("dsort_agent_health_busy_ms", "busy_ms"),
    ):
        for labels, value in _labeled(parsed, metric):
            rows.setdefault(labels.get("agent", "?"), {})[field] = value
    for labels, _value in _labeled(parsed, "dsort_agent_health_info"):
        row = rows.setdefault(labels.get("agent", "?"), {})
        row["dominant_phase"] = labels.get("dominant_phase", "-")
        row["straggler"] = labels.get("straggler") == "1"
    return rows


def render_health(parsed: dict) -> list[str]:
    """The health-pane lines (empty when the scrape has no health plane).
    One shared table formatter with the verdict-side renderer
    (`obs.health.health_table`) — the two panes cannot drift."""
    from dsort_tpu.obs.health import health_table

    rows = _health_rows(parsed)
    if not rows:
        return []
    return ["health:"] + health_table(rows, indent="  ")


def _plan_rows(parsed: dict) -> list[tuple]:
    """Per-policy planner cells from the scrape's labeled gauges
    (`obs.plan` via the telemetry tap, ARCHITECTURE §15)."""
    rows: dict[str, dict] = {}
    for metric, field in (
        ("dsort_plan_decisions", "decisions"),
        ("dsort_plan_overrides", "overrides"),
    ):
        for labels, value in _labeled(parsed, metric):
            rows.setdefault(labels.get("policy", "?"), {})[field] = value
    for labels, _value in _labeled(parsed, "dsort_plan_info"):
        row = rows.setdefault(labels.get("policy", "?"), {})
        row["last"] = labels.get("chosen", "-")
    return [
        (policy, row.get("decisions", 0), row.get("overrides", 0),
         row.get("last"))
        for policy, row in sorted(rows.items())
    ]


def render_plan(parsed: dict) -> list[str]:
    """The planner-pane lines (empty when the scrape has no planner
    plane).  One shared table formatter with the report-side renderer
    (`obs.plan.plan_table`) — the two panes cannot drift."""
    from dsort_tpu.obs.plan import plan_table

    rows = _plan_rows(parsed)
    if not rows:
        return []
    return ["planner:"] + plan_table(rows, indent="  ").splitlines()


def render_top(parsed: dict) -> str:
    """The console snapshot for one parsed scrape."""
    lines = []
    in_flight = parsed.get(("dsort_jobs_in_flight", ()), 0.0)
    queue = parsed.get(("dsort_queue_depth", ()), 0.0)
    lines.append(
        f"jobs in flight: {int(in_flight)}    queue depth: {int(queue)}"
    )
    lines.extend(render_health(parsed))
    lines.extend(render_plan(parsed))
    # Compiled-variant cache (serving layer): entries/hits/misses/prewarmed
    # ride as gauges; the hit rate is the headline the operator watches.
    hits = parsed.get(("dsort_variant_cache_hits", ()), 0.0)
    misses = parsed.get(("dsort_variant_cache_misses", ()), 0.0)
    if hits or misses or ("dsort_variant_cache_entries", ()) in parsed:
        entries = int(parsed.get(("dsort_variant_cache_entries", ()), 0.0))
        prewarmed = int(parsed.get(("dsort_variant_cache_prewarmed", ()), 0.0))
        rate = hits / (hits + misses) if (hits + misses) else 0.0
        lines.append(
            f"variant cache: {entries} entries    hits {int(hits)}  "
            f"misses {int(misses)}  prewarmed {prewarmed}  "
            f"hit rate {rate * 100:.1f}%"
        )
    # Compile/cost/HBM ledger (obs.prof): one row per compiled variant.
    from dsort_tpu.obs.prof import LEDGER_GAUGES

    ledger: dict[str, dict] = {}
    for metric, field in LEDGER_GAUGES:
        for labels, value in _labeled(parsed, metric):
            ledger.setdefault(labels.get("variant", "?"), {})[field] = value
    if ledger:
        lines.append("variant ledger:")
        lines.append(
            f"  {'variant':<50}{'compiles':>9}"
            f"{'compile ms':>12}{'flops':>14}{'peak HBM':>14}"
        )
        for variant in sorted(ledger):
            row = ledger[variant]
            lines.append(
                f"  {variant:<50}{int(row.get('compiles', 0)):>9}"
                f"{row.get('compile_s', 0.0) * 1e3:>12.1f}"
                f"{row.get('flops', 0.0):>14.3g}"
                f"{int(row.get('peak_hbm_bytes', 0)):>14,}"
            )
    jobs = _labeled(parsed, "dsort_jobs_total")
    if jobs:
        lines.append("jobs:")
        for labels, value in jobs:
            lines.append(
                f"  {labels.get('tenant', '?'):<16} "
                f"{labels.get('outcome', '?'):<8} {int(value):>8}"
            )
    admissions = _labeled(parsed, "dsort_admissions_total")
    if admissions:
        lines.append("admissions:")
        for labels, value in admissions:
            lines.append(
                f"  {labels.get('tenant', '?'):<16} "
                f"{labels.get('reason', '?'):<14} {int(value):>8}"
            )
    # SLO table: one row per (tenant, stage) with its quantile columns.
    slo: dict[tuple[str, str], dict] = {}
    for labels, value in _labeled(parsed, "dsort_job_stage_seconds"):
        key = (labels.get("tenant", "?"), labels.get("stage", "?"))
        slo.setdefault(key, {})[labels.get("quantile", "?")] = value
    counts = {
        (labels.get("tenant", "?"), labels.get("stage", "?")): value
        for labels, value in _labeled(parsed, "dsort_job_stage_seconds_count")
    }
    if slo:
        qcols = "".join(f"{f'p{int(q * 100)}':>10}" for q in SLO_QUANTILES)
        lines.append(f"slo (ms): {'tenant/stage':<38}{qcols}{'count':>8}")
        for (tenant, stage) in sorted(slo):
            row = slo[(tenant, stage)]
            cells = "".join(
                f"{row.get(str(q), 0.0) * 1e3:>10.2f}" for q in SLO_QUANTILES
            )
            lines.append(
                f"  {tenant + '/' + stage:<44}{cells}"
                f"{int(counts.get((tenant, stage), 0)):>8}"
            )
    phases = _labeled(parsed, "dsort_phase_seconds_total")
    if phases:
        lines.append("phase wall time:")
        for labels, value in phases:
            lines.append(
                f"  {labels.get('phase', '?'):<20} {value * 1e3:>12.3f} ms"
            )
    counters = [
        (labels.get("name", "?"), value)
        for labels, value in _labeled(parsed, "dsort_counter_total")
        if value
    ]
    if counters:
        lines.append("counters (nonzero):")
        for name, value in counters:
            lines.append(f"  {name:<28} {int(value):>10}")
    return "\n".join(lines) + "\n"


# -- fleet view (several endpoints at once, ARCHITECTURE §12) ----------------


def _cache_cells(parsed: dict) -> tuple[float, float, int, int]:
    hits = parsed.get(("dsort_variant_cache_hits", ()), 0.0)
    misses = parsed.get(("dsort_variant_cache_misses", ()), 0.0)
    entries = int(parsed.get(("dsort_variant_cache_entries", ()), 0.0))
    prewarmed = int(parsed.get(("dsort_variant_cache_prewarmed", ()), 0.0))
    return hits, misses, entries, prewarmed


def render_fleet(scrapes: list[tuple[str, dict]]) -> str:
    """The per-mesh fleet view for several parsed scrapes.

    One summary row per source (its URL, jobs in flight, queue depth,
    done/failed totals, cache hit rate) followed by the COMBINED
    admissions table and the combined variant-cache line (fleet hit rate
    = total hits / total lookups).  When a fleet CONTROLLER is among the
    sources (it exposes the ``dsort_fleet_agents`` gauge), the
    admissions table sums controllers only — every routed job is admitted
    a second time by its agent's local service, and summing both layers
    would double-count the fleet's real backpressure.
    """
    controller_urls = {
        url for url, parsed in scrapes
        if ("dsort_fleet_agents", ()) in parsed
    }
    lines = ["fleet:"]
    lines.append(
        f"  {'source':<40}{'in-flight':>10}{'queued':>8}{'done':>8}"
        f"{'failed':>8}{'hit rate':>10}"
    )
    tot_hits = tot_misses = tot_entries = tot_prewarmed = 0
    admissions: dict[tuple[str, str], int] = {}
    health_lines: list[str] = []
    for url, parsed in scrapes:
        in_flight = int(parsed.get(("dsort_jobs_in_flight", ()), 0.0))
        queued = int(parsed.get(("dsort_queue_depth", ()), 0.0))
        done = failed = 0
        for labels, value in _labeled(parsed, "dsort_jobs_total"):
            if labels.get("outcome") == "done":
                done += int(value)
            elif labels.get("outcome") == "failed":
                failed += int(value)
        hits, misses, entries, prewarmed = _cache_cells(parsed)
        tot_hits += hits
        tot_misses += misses
        tot_entries += entries
        tot_prewarmed += prewarmed
        rate = hits / (hits + misses) if (hits + misses) else 0.0
        agents = parsed.get(("dsort_fleet_agents", ()))
        tag = f" [{int(agents)} agents]" if agents is not None else ""
        lines.append(
            f"  {(url + tag)[:40]:<40}{in_flight:>10}{queued:>8}{done:>8}"
            f"{failed:>8}{rate * 100:>9.1f}%"
        )
        if controller_urls and url not in controller_urls:
            continue  # agent-local admissions mirror the controller's
        for labels, value in _labeled(parsed, "dsort_admissions_total"):
            key = (labels.get("tenant", "?"), labels.get("reason", "?"))
            admissions[key] = admissions.get(key, 0) + int(value)
        # The controller's per-agent health pane renders in the fleet view
        # too — it IS the fleet's why-slow summary (after the source rows).
        health_lines.extend(render_health(parsed))
    lines.extend(health_lines)
    if admissions:
        lines.append("admissions (fleet-wide):")
        for (tenant, reason) in sorted(admissions):
            lines.append(
                f"  {tenant:<16} {reason:<14} {admissions[(tenant, reason)]:>8}"
            )
    total = tot_hits + tot_misses
    lines.append(
        f"variant cache (combined): {tot_entries} entries    hits "
        f"{int(tot_hits)}  misses {int(tot_misses)}  prewarmed "
        f"{tot_prewarmed}  hit rate "
        f"{(tot_hits / total if total else 0.0) * 100:.1f}%"
    )
    return "\n".join(lines) + "\n"
