"""Fault flight recorder: a bounded event ring + postmortem bundles.

Today the cost of a recovery — which path fired, what it re-ran, what it
abandoned — lives in commit messages and bench rows; the mesh-availability
literature (arXiv:2011.03605) makes the case that surviving fabric loss in
production hinges on OBSERVING exactly that.  This recorder keeps the last
``ring_size`` events of its scheduler in memory and, whenever any recovery
path fires (`RECOVERY_EVENTS`), dumps a self-contained postmortem bundle to
``JobConfig.flight_recorder_dir``:

```json
{"schema": 1,
 "recovery_path": "mesh_reform",            // which path fired (+ kind)
 "detail":  {...},                          // the triggering event's fields
 "t": 1700000000.0, "mono": 12.5,           // when
 "counters": {"mesh_reforms": 1, ...},      // cumulative cost so far
 "config":  {...},                          // the job's JobConfig, JSON-able
 "state":   {"mode": "spmd", "live": [...]},// scheduler-provided mesh state
 "ring":    [{"mono": ..., "type": ..., ...}, ...]}  // the recent past
```

(`BUNDLE_SCHEMA_KEYS` is the schema contract; ARCHITECTURE §7 documents it
and a test keeps the two in lockstep.)  Wiring is one `attach` per job
`Metrics` — the recorder is an event tap, so every execution mode that
journals through metrics feeds it with zero extra plumbing.
"""

from __future__ import annotations

import dataclasses
import json
import os
import threading
import time
from collections import deque

from dsort_tpu.utils.logging import get_logger

log = get_logger("obs.flight")

#: Event types that ARE a recovery path firing: each dump's
#: ``recovery_path`` starts with one of these (``checkpoint_restore``
#: qualifies with its ``kind`` — e.g. ``checkpoint_restore:multihost_partial``
#: is the multi-host crash-retry).
RECOVERY_EVENTS = frozenset(
    {
        "mesh_reform",               # SPMD re-form over survivors
        "device_handle_invalidated", # device-resident handles re-run
        "capacity_retry",            # bucket overflow re-dispatch
        "reassign",                  # taskpool shard moved off a dead worker
        "checkpoint_restore",        # resume instead of re-sort (incl. multihost)
        "fused_fallback",            # fused path failed over to the scheduler
        "transient_retry",           # in-place retry on a healthy mesh
        "job_evicted",               # serving layer evicted a job off a slice
        "coded_recover",             # dead range rebuilt from replica slots
        "parity_recover",            # dead range solved from XOR/P+Q parity
    }
)

#: Top-level keys every bundle carries — the test-enforced schema.
BUNDLE_SCHEMA_KEYS = (
    "schema",
    "recovery_path",
    "detail",
    "t",
    "mono",
    "counters",
    "config",
    "state",
    "ring",
)

BUNDLE_SCHEMA_VERSION = 1


def _jsonable(value):
    try:
        json.dumps(value)
        return value
    except (TypeError, ValueError):
        return str(value)


def config_snapshot(job) -> dict:
    """A JobConfig (or any dataclass) as JSON-able key/values."""
    if dataclasses.is_dataclass(job):
        return {
            f.name: _jsonable(getattr(job, f.name))
            for f in dataclasses.fields(job)
        }
    return {"repr": repr(job)}


def recovery_path_name(etype: str, fields: dict) -> str:
    """The bundle's ``recovery_path`` label for one triggering event."""
    kind = fields.get("kind") or fields.get("stage")
    if etype == "checkpoint_restore" and fields.get("kind"):
        return f"{etype}:{fields['kind']}"
    if etype == "mesh_reform" and kind:
        return f"{etype}:{kind}"
    if etype == "coded_recover":
        # The coded plane's bundle name (ARCHITECTURE §14): the recovery
        # was a local reconstruction from replica slots, not a re-run.
        return "coded_reconstruct"
    if etype == "parity_recover":
        # The v2 parity plane (§18): same local posture, but the lost
        # range was SOLVED from XOR/P+Q slots rather than merged from a
        # full replica — named apart so postmortems show which premium
        # actually paid for the recovery.
        return "parity_reconstruct"
    return etype


class FlightRecorder:
    """Bounded ring of recent events + postmortem dumps on recovery paths.

    One per scheduler (`SpmdScheduler`/`Scheduler` build one when
    ``JobConfig.flight_recorder_dir`` is set; the multi-host driver builds
    one per call).  ``state_fn`` supplies the owner's live state (mesh
    membership, mode) at dump time; ``config`` is snapshotted once.
    """

    def __init__(
        self,
        directory: str,
        ring_size: int = 256,
        state_fn=None,
        config=None,
        events: frozenset | None = None,
    ):
        self.directory = str(directory)
        os.makedirs(self.directory, exist_ok=True)
        self._state_fn = state_fn
        self._config = config_snapshot(config) if config is not None else {}
        # Which event types trigger a dump.  The serving layer narrows this
        # to its own eviction events so a job carrying BOTH a scheduler
        # recorder and a service recorder never dumps one recovery twice.
        self._events = RECOVERY_EVENTS if events is None else frozenset(events)
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=max(int(ring_size), 1))
        self._seq = 0

    def attach(self, metrics) -> None:
        """Tap a job's `Metrics` (idempotent)."""
        if self not in metrics.taps:
            metrics.taps.append(self)

    def events(self) -> list[dict]:
        with self._lock:
            return list(self._ring)

    # -- tap protocol ------------------------------------------------------

    def observe(self, etype: str, fields: dict, mono: float, metrics) -> None:
        with self._lock:
            self._ring.append(
                {"mono": round(mono, 6), "type": etype, **fields}
            )
            if etype not in self._events:
                return
            self._seq += 1
            seq = self._seq
            ring = list(self._ring)
        # Dump OUTSIDE the lock: disk IO must never serialize against the
        # hot emit path of a concurrently-recovering scheduler.
        path = self._dump(seq, etype, fields, ring, mono, metrics)
        if path is not None:
            metrics.bump("flight_dumps")
            metrics.event(
                "flight_dump",
                path=os.path.basename(path),
                recovery_path=recovery_path_name(etype, fields),
            )

    # -- bundle IO ---------------------------------------------------------

    def _dump(
        self, seq: int, etype: str, fields: dict, ring: list, mono: float,
        metrics,
    ) -> str | None:
        bundle = {
            "schema": BUNDLE_SCHEMA_VERSION,
            "recovery_path": recovery_path_name(etype, fields),
            "detail": {k: _jsonable(v) for k, v in fields.items()},
            "t": round(time.time(), 6),
            "mono": round(mono, 6),
            "counters": dict(metrics.counters),
            "config": self._config,
            "state": self._state_fn() if self._state_fn is not None else {},
            "ring": ring,
        }
        name = f"flight_{os.getpid()}_{seq:04d}_{etype}.json"
        path = os.path.join(self.directory, name)
        tmp = path + ".tmp"
        try:
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(bundle, f, default=str)
                f.flush()
                # fsync before the atomic rename: a postmortem bundle
                # exists precisely because something is failing — it must
                # survive the host going down right after, and a reader
                # must never see a torn bundle.
                os.fsync(f.fileno())
            os.replace(tmp, path)
        except OSError as e:
            # The recorder is a diagnostic surface: a full disk must not
            # take the recovering job down with it.
            log.warning("flight recorder dump failed (%s): %s", name, e)
            return None
        log.warning(
            "flight recorder: postmortem bundle %s (%s)",
            name, bundle["recovery_path"],
        )
        return path

    @staticmethod
    def read_bundles(directory: str) -> list[dict]:
        """All bundles in ``directory``, wall-clock dump order.

        Ordered by each bundle's own ``t`` stamp (filename as tiebreak):
        a shared directory holds bundles from several processes, and the
        pid embedded in the names would otherwise group by process
        instead of by when each recovery actually fired.
        """
        out = []
        for name in sorted(os.listdir(directory)):
            if name.startswith("flight_") and name.endswith(".json"):
                with open(os.path.join(directory, name), encoding="utf-8") as f:
                    rec = json.load(f)
                rec["_file"] = name
                out.append(rec)
        out.sort(key=lambda r: (r.get("t", 0.0), r["_file"]))
        return out
