"""Profiling hooks (SURVEY.md §5.1 upgrade — the reference has none).

Wraps ``jax.profiler``: `profile_trace` captures a TensorBoard/Perfetto trace
of a region, `annotate` labels host-side phases so they show up alongside
device ops.  No-ops cleanly if profiling is unavailable.
"""

from __future__ import annotations

import contextlib


@contextlib.contextmanager
def profile_trace(logdir: str | None):
    """Capture a jax.profiler trace into ``logdir`` (None → no-op)."""
    if not logdir:
        yield
        return
    import jax

    with jax.profiler.trace(logdir):
        yield


@contextlib.contextmanager
def annotate(name: str):
    """Label a host-side region in profiler timelines (no-op off-profile).

    Only the annotation SETUP is guarded — exceptions raised by the body
    must propagate (a fault-tolerance path relies on JobFailedError crossing
    phase boundaries), so no try/except may wrap the ``yield``.

    If jax is not already imported, nothing can be profiling this process —
    so don't trigger the multi-second jax import from jax-free processes
    (e.g. a numpy-backend coordinator) just to build a no-op annotation.
    """
    import sys

    cm = contextlib.nullcontext()
    jax_mod = sys.modules.get("jax")
    if jax_mod is not None:
        try:
            cm = jax_mod.profiler.TraceAnnotation(name)
        except Exception:
            pass
    with cm:
        yield
