"""Profiling hooks (SURVEY.md §5.1 upgrade — the reference has none).

Wraps ``jax.profiler``: `profile_trace` captures a TensorBoard/Perfetto trace
of a region, `annotate` labels host-side phases so they show up alongside
device ops.  No-ops cleanly if profiling is unavailable.
"""

from __future__ import annotations

import contextlib


@contextlib.contextmanager
def profile_trace(logdir: str | None):
    """Capture a jax.profiler trace into ``logdir`` (None → no-op)."""
    if not logdir:
        yield
        return
    import jax

    with jax.profiler.trace(logdir):
        yield


@contextlib.contextmanager
def annotate(name: str):
    """Label a host-side region in profiler timelines (no-op off-profile).

    Only the annotation SETUP is guarded — exceptions raised by the body
    must propagate (a fault-tolerance path relies on JobFailedError crossing
    phase boundaries), so no try/except may wrap the ``yield``.
    """
    try:
        import jax

        cm = jax.profiler.TraceAnnotation(name)
    except Exception:
        cm = contextlib.nullcontext()
    with cm:
        yield
