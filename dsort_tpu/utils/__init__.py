"""Cross-cutting utilities: structured logging, metrics, tracing, events."""

from dsort_tpu.utils.events import EventLog  # noqa: F401
from dsort_tpu.utils.logging import get_logger  # noqa: F401
from dsort_tpu.utils.metrics import PhaseTimer, Metrics  # noqa: F401
