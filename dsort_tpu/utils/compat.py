"""JAX version compatibility shims.

One import site per drifted API, so version skew is absorbed here instead of
scattering ``hasattr`` checks through the drivers.
"""

from __future__ import annotations

import jax


def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = True):
    """`jax.shard_map` across the API move.

    Newer jax exposes ``jax.shard_map(..., check_vma=...)``; 0.4.x has only
    ``jax.experimental.shard_map.shard_map(..., check_rep=...)`` (the same
    replication check under its old name).
    """
    if hasattr(jax, "shard_map"):
        return jax.shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=check_vma,
        )
    from jax.experimental.shard_map import shard_map as _shard_map

    return _shard_map(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_rep=check_vma,
    )


def tpu_compiler_params(**kwargs):
    """Pallas-TPU compiler params across the `TPUCompilerParams` →
    `CompilerParams` rename."""
    from jax.experimental.pallas import tpu as pltpu

    cls = getattr(pltpu, "CompilerParams", None) or pltpu.TPUCompilerParams
    return cls(**kwargs)


def enable_x64(new_val: bool = True):
    """`jax.enable_x64` (context manager) across the API move from
    ``jax.experimental.enable_x64``."""
    if hasattr(jax, "enable_x64"):
        return jax.enable_x64(new_val)
    from jax.experimental import enable_x64 as _enable_x64

    return _enable_x64(new_val)


def set_x64(enable: bool = True) -> None:
    """Process-wide x64 switch — THE one allowed call site.

    Every entry point that needs 64-bit key dtypes (the CLI, the worker
    shim) routes through here instead of scattering
    ``jax.config.update("jax_enable_x64", ...)``; the analysis suite's
    DS501 checker enforces it, so when this API next moves there is exactly
    one line to change.
    """
    jax.config.update("jax_enable_x64", enable)
