"""Structured per-job event journal (the fault-timeline upgrade of §5.5).

The reference's only observability is unleveled printf of protocol steps;
the rebuild's recovery machinery — heartbeat lapses, device probes, mesh
re-forms, shard reassignment, capacity retries, checkpoint restores — went
through leveled logs only, which answer "what happened to job X" solely by
grepping stderr.  This module is the machine-readable trail: a thread-safe
`EventLog` of typed, monotonic-timestamped records emitted from every
execution mode (taskpool, SPMD, fused, multi-host, native coordinator), plus
its two consumers — a Chrome-trace (Perfetto ``trace_event``) exporter so
job timelines render next to ``jax.profiler`` captures, and the human
timeline behind ``dsort report``.

Wiring: an `EventLog` attaches to a `Metrics` instance
(``Metrics(journal=...)``); every site that already threads metrics can then
``metrics.event("worker_dead", worker=3)`` with zero cost when no journal is
attached.  `PhaseTimer` emits ``phase_start``/``phase_end`` pairs
automatically, so the phase breakdown and the fault timeline live in one
stream.
"""

from __future__ import annotations

import dataclasses
import json
import threading
import time

#: THE event-type registry.  `EventLog.emit` refuses unregistered types so
#: the journal schema stays documented here (and in README "Observability")
#: rather than drifting site by site.  Fields listed are conventions, not
#: schema — events carry whatever keyword fields their site provides.
EVENT_TYPES: dict[str, str] = {
    "job_start": "a sort job entered a scheduler (n_keys, mode)",
    "job_done": "the job completed (n_keys)",
    "job_failed": "the job failed cleanly (reason)",
    "attempt_start": "one execution attempt began (worker/live, shard)",
    "heartbeat_lapse": "a bounded wait lapsed — possible hang (worker/kind)",
    "probe": "a liveness probe ran on one device (worker, ok)",
    "worker_dead": "a worker/device was declared dead (worker, stage)",
    "reassign": "a shard moved to another worker (shard, frm, to)",
    "mesh_reform": "the SPMD mesh re-formed over survivors (survivors)",
    "capacity_retry": "an all_to_all bucket overflowed; retry resized "
                      "(observed, cap_pair)",
    "transient_retry": "a transient runtime error retried in place (worker)",
    "checkpoint_persist": "shard/range state persisted (kind, id, n)",
    "checkpoint_restore": "persisted state restored instead of re-sorting "
                          "(kind, n)",
    "checkpoint_clear": "stale/partial persisted state was cleared (reason)",
    "phase_start": "a timed phase opened (phase)",
    "phase_end": "a timed phase closed (phase, seconds)",
    "fused_fallback": "the fused small-job path failed over to the "
                      "scheduler (reason)",
    "worker_join": "a worker joined the native coordinator cluster (worker)",
    "task_done": "one shard's result landed (native coordinator; worker, "
                 "task)",
    "device_handle": "a device-resident result handle was issued "
                     "(n_keys, shards)",
    "device_handle_invalidated": "a mesh re-form invalidated outstanding "
                                 "device-resident handles (reason, n)",
    "device_validate": "on-device validation ran over a device-resident "
                       "result (ok, n)",
    "device_consume": "a jitted next stage consumed a device-resident "
                      "result (n_keys, donated)",
    "exchange_step": "one ring exchange step was planned with its measured "
                     "capacity (step, cap, bytes)",
    "exchange_resize": "a ring step's adaptive capacity exceeded the static "
                       "policy allocation — the per-step successor of the "
                       "whole-job capacity retry (step, cap, policy_cap)",
    "clock_sync": "a process published one (wall, mono) clock pair so the "
                  "journal merger (obs.merge) can align this journal's "
                  "monotonic base with its peers' (process/source)",
    "result_fetch": "a sorted result crossed device->host (n_keys) — the "
                    "'fetched' stage boundary of the SLO histograms",
    "flight_dump": "the fault flight recorder dumped a postmortem bundle "
                   "(path, recovery_path)",
    # Serving layer (dsort_tpu.serve, ARCHITECTURE §8):
    "job_admitted": "admission control accepted a job into the service "
                    "queue (tenant, queue_depth, n_keys)",
    "job_rejected": "admission control rejected a job (tenant, reason — "
                    "one of serve.admission.ADMISSION_REASONS)",
    "job_dequeued": "the fair scheduler dequeued a job for dispatch "
                    "(tenant, wait_s — the measured queue wait, big, "
                    "slices)",
    "job_evicted": "a fault evicted a queued/in-flight job from its mesh "
                   "slice (tenant, reason, slice, readmits) — dumps one "
                   "flight-recorder bundle per eviction",
    "job_readmitted": "an evicted job re-entered the service queue "
                      "(tenant, readmits)",
    "slice_retired": "a mesh sub-slice failed its liveness probe and left "
                     "the packing rotation (slice)",
    "variant_prewarm": "compiled-variant cache rungs were prewarmed at "
                       "startup (n, rungs)",
    "serve_drain": "the service began draining — no new admissions "
                   "(reason, drain, queued, in_flight)",
    "serve_stop": "the service wound down; the journal's close event "
                  "(jobs_done, jobs_failed, counters)",
    # Performance introspection plane (dsort_tpu.obs.prof/analyze, §9):
    "variant_compiled": "one jit compile landed in the variant ledger "
                        "(variant, compile_s, flops, bytes_accessed, "
                        "peak/temp/output/argument_hbm_bytes)",
    "skew_report": "the ring plan's measured bucket histogram, reduced "
                   "(max_mean_ratio, send/recv device loads, predicted "
                   "imbalance) — the skew signal the analyzer reads",
    "hbm_watermark": "a --memwatch device-memory snapshot at a phase "
                     "boundary (phase, edge, bytes_in_use, "
                     "max_device_bytes, source)",
    # Fused Pallas ring kernel (ops.ring_kernel, ARCHITECTURE §11):
    "fused_exchange_launch": "one fused ring kernel launch replaced the "
                             "P-1 per-step collective dispatches (steps, "
                             "dispatches, dispatches_replaced, total_cap)",
    "fused_exchange_step": "one planned in-kernel async-remote-copy step of "
                           "the fused ring (step, cap, bytes) — the fused "
                           "twin of exchange_step",
    # Fleet plane (dsort_tpu.fleet, ARCHITECTURE §12):
    "agent_register": "a fleet execution agent (re)registered with the "
                      "controller (agent, addr, capacity, big_jobs, "
                      "draining, variants, reattach)",
    "agent_heartbeat": "one controller->agent heartbeat round-trip (agent, "
                       "queued, in_flight, draining, variants)",
    "job_routed": "the fleet controller dispatched a job onto an agent "
                  "(job_id, tenant, agent, reason — locality/size/spill/"
                  "random/health, n_keys)",
    "job_rerouted": "a routed/in-flight job re-entered the fleet queue "
                    "after its agent drained, died, or forgot it (job_id, "
                    "tenant, frm, reason, readmits)",
    "job_dispatched": "an agent accepted a dispatched job — the submit "
                      "round-trip the send deadline must cover (job_id, "
                      "agent, accept_latency_s; the dispatch_timeout_s "
                      "policy's measured input)",
    "controller_restore": "a restarted fleet controller restored its "
                          "persisted queue + in-flight state (controller, "
                          "queued, inflight, agents)",
    # Health plane (obs.health over the fleet protocol, ARCHITECTURE §13):
    "health_verdict": "the controller's rolling why-slow verdict for one "
                      "agent, refreshed per ingested telemetry delta "
                      "(agent, score, straggler, dominant_phase, splits, "
                      "slo_risk, degraded, seq — obs.health."
                      "HEALTH_VERDICT_KEYS)",
    "agent_degraded": "an agent's health verdict flipped degraded — the "
                      "controller dumps a flight bundle and health routing "
                      "penalizes it for big jobs (agent, score, "
                      "dominant_phase)",
    # Coded redundancy plane (parallel.coded, ARCHITECTURE §14):
    "coded_replica_ship": "one coded exchange planned its redundancy plane "
                          "— full bucket copies to r-1 ring successors "
                          "(mode=replicate) or GF(256) parity slots "
                          "(mode=parity) (redundancy, mode, slots, bytes)",
    "coded_recover": "a dead device's range was reconstructed by a LOCAL "
                     "merge of a survivor's replica slots — zero keys "
                     "re-sorted, zero re-dispatch (dead, holders, "
                     "recovered_keys, replica_bytes, redundancy, wall_s)",
    "coded_budget_exceeded": "losses exceeded the replica budget (a dead "
                             "range's every holder dead too); recovery "
                             "degraded cleanly to the re-run path (dead, "
                             "redundancy)",
    # Coded exchange v2 (parity + straggler serving, ARCHITECTURE §18):
    "parity_recover": "a dead device's range was reconstructed through the "
                      "GF(256) parity plane — survivors' retained out-"
                      "buckets plus XOR/RAID-6 parity slots solved the "
                      "missing buckets (dead, holders, recovered_keys, "
                      "replica_bytes, redundancy, mode, wall_s)",
    "coded_straggler_serve": "a range owned by the measured straggler was "
                             "served from the replica/parity plane because "
                             "the reconstruction finished before the "
                             "owner's fetch — the exactly-once claim of "
                             "the straggler-first protocol (range, mode, "
                             "holders, recovered_keys, wall_s)",
    "coded_owner_fetch": "the straggler-first race's owner leg completed — "
                         "``won`` says whether the owner's own fetch beat "
                         "the reconstruction (the serve event is then "
                         "absent) or arrived late and was discarded "
                         "(range, won, wall_s)",
    # Planner plane (obs.plan, ARCHITECTURE §15):
    "plan_decision": "the closed-loop planner chose a knob value from "
                     "measured inputs, journaled BEFORE dispatch (policy — "
                     "one of obs.plan.PLAN_POLICIES, chosen, inputs — the "
                     "measured dict the pure policy replays from, rejected "
                     "— alternatives with reasons)",
    "plan_override": "an explicit flag/conf value won over the planner "
                     "while autotune was on (policy, explicit — the value "
                     "that won, planned — what the planner would have "
                     "chosen, inputs)",
    # Hierarchical exchange plane (parallel.exchange, ARCHITECTURE §17):
    "hier_exchange_plan": "one two-level exchange was sized from the (H,H) "
                          "host matrix (hosts, dev_per_host, legs, agg_cap, "
                          "scatter_cap, dcn_bytes, intra_bytes, "
                          "flat_ring_dcn_bytes)",
    "hier_exchange_leg": "one planned host-shift DCN leg of the two-level "
                         "exchange — H aggregated transfers, one per "
                         "(src-host, dst-host) pair (shift, cap, bytes)",
    "hier_reform": "the host grouping re-planned after a loss — a lost "
                   "device re-forms within its host; a lost host shrinks "
                   "the (H,H) legs to survivors or downgrades to the flat "
                   "ring (survivors, hosts_before, hosts_after, downgraded)",
    # Out-of-core wave pipeline (models.wave_sort, ARCHITECTURE §10):
    "wave_start": "one input wave entered the mesh pipeline "
                  "(wave, n_keys)",
    "wave_done": "a wave's runs all landed in the (wave, run) store "
                 "(wave, runs, n_keys)",
    "wave_resume": "an interrupted wave's missing runs were re-sorted at "
                   "run granularity — restart-resume or in-flight repair "
                   "(wave, missing, present, reason)",
}

#: THE counter registry: every `Metrics.bump` name in the package, with its
#: meaning.  The journal (``job_done`` carries the final counters), bench
#: artifact lines, and README's Observability section all share this one
#: vocabulary; ``tests/test_events.py`` greps the source tree to keep it
#: exhaustive.
COUNTERS: dict[str, str] = {
    "reassignments": "shards moved to another worker after a failure",
    "heartbeat_timeouts": "taskpool attempts abandoned on a lapsed wait",
    "cold_wait_retries": "cold-key waits extended (likely slow compile)",
    "transient_retries": "transient runtime errors retried in place",
    "device_runtime_errors": "real XLA runtime failures routed to recovery",
    "device_deaths": "devices marked dead after failed probes",
    "mesh_reforms": "SPMD mesh re-formed over surviving devices",
    "spmd_wait_timeouts": "bounded in-flight SPMD program waits lapsed",
    "capacity_retries": "all_to_all bucket overflows resized and re-run",
    "shards_restored": "taskpool shards served from checkpoint",
    "spmd_phase_restores": "SPMD local-sort phases restored from checkpoint",
    "shuffle_phase_restores": "SPMD shuffle phases fully restored",
    "shuffle_ranges_restored": "persisted shuffle ranges restored",
    "shuffle_resort_keys": "keys re-sorted by the shuffle resume path",
    "multihost_ranges_restored": "multi-host per-process ranges restored",
    "multihost_resort_keys": "keys re-sorted by the multi-host resume path",
    "batch_jobs_restored": "batched jobs served from checkpoint",
    "padded_elems": "elements allocated in batched padding layouts",
    "fused_small_jobs": "jobs served by the fused single-program path",
    "fused_fallbacks": "fused-path failures retried on the SPMD scheduler",
    "runs_resumed": "external-sort runs restored from a previous run",
    "runs_sorted": "external-sort runs sorted this run",
    "native_merges": "k-way merges executed in native code",
    "device_handles": "device-resident result handles issued",
    "device_handle_reruns": "invalidated device-resident handles re-run on "
                            "the current mesh",
    "device_validates": "on-device validations executed",
    "device_consumes": "device-resident results consumed by a jitted stage",
    "exchange_ring_steps": "ring exchange transfer steps executed",
    "exchange_bytes_on_wire": "bytes the bucket exchange put on the wire "
                              "(both schedules; whole mesh)",
    "exchange_bytes_saved": "wire bytes the ring schedule avoided vs the "
                            "policy-sized padded all_to_all",
    "flight_dumps": "postmortem bundles dumped by the fault flight recorder",
    "jobs_admitted": "jobs accepted by the serving layer's admission control",
    "jobs_rejected": "jobs rejected by admission control (typed verdict)",
    "jobs_readmitted": "evicted jobs re-admitted to the service queue",
    "slice_dispatches": "small jobs packed onto mesh sub-slices",
    "fullmesh_dispatches": "big jobs dispatched onto the full SPMD mesh",
    "variant_cache_hits": "compiled-variant cache hits (rung already cached)",
    "variant_cache_misses": "compiled-variant cache misses (rung compiled)",
    "variant_cache_evictions": "compiled variants dropped by the LRU bound",
    "variant_cache_prewarms": "compiled-variant rungs built by the startup "
                              "prewarm pass",
    "variant_compiles": "jit compiles recorded by the introspection ledger "
                        "(obs.prof; each carries cost/HBM analysis)",
    "hbm_watermarks": "device-memory snapshots taken at phase boundaries "
                      "(--memwatch)",
    "fused_exchange_launches": "fused ring kernel launches (each replaces "
                               "P-1 per-step exchange dispatches)",
    "fused_exchange_steps": "async-remote-copy steps executed inside fused "
                            "ring kernel launches",
    "fleet_jobs_routed": "jobs the fleet controller dispatched onto "
                         "execution agents",
    "fleet_jobs_rerouted": "routed jobs re-queued after an agent drained, "
                           "died, or forgot them",
    "fleet_heartbeats": "controller->agent heartbeat round-trips completed",
    "controller_restores": "fleet controller restarts that restored "
                           "persisted queue/in-flight state",
    "fleet_telemetry_frames": "health-plane telemetry deltas the controller "
                              "ingested from its agents",
    "health_verdicts": "rolling per-agent health verdicts the controller "
                       "journaled",
    "agent_degradations": "agent health verdicts that flipped degraded "
                          "(each dumps one flight bundle)",
    "coded_recoveries": "device losses recovered by a local replica-slot "
                        "merge instead of a re-run (parallel.coded)",
    "coded_replica_bytes": "wire bytes the coded replica plane shipped "
                           "(also charged to exchange_bytes_on_wire)",
    "coded_recovered_keys": "keys reconstructed from replica slots by "
                            "coded recoveries (merged, never re-sorted)",
    "coded_straggler_serves": "ranges served from the replica/parity plane "
                              "ahead of their measured-straggler owner "
                              "(no failure involved; parallel.coded)",
    "plan_decisions": "knob values the closed-loop planner chose from "
                      "measured inputs (obs.plan; each journals a "
                      "plan_decision)",
    "plan_overrides": "explicit flag/conf values that won over the planner "
                      "while autotune was on (each journals a "
                      "plan_override)",
    "hier_exchanges": "two-level (intra-host x DCN-leg) exchanges planned "
                      "and dispatched (parallel.exchange hier schedule)",
    "dcn_bytes_on_wire": "bytes the two-level exchange shipped over the "
                         "inter-host DCN legs (also charged to "
                         "exchange_bytes_on_wire)",
    "intra_host_bytes_on_wire": "bytes the two-level exchange kept on the "
                                "fast intra-host fabric (also charged to "
                                "exchange_bytes_on_wire)",
    "dcn_bytes_saved": "inter-host bytes the two-level schedule avoided vs "
                       "the flat ring's cross-host transfers for the same "
                       "measured histogram",
    "waves_sorted": "input waves run through the mesh exchange pipeline",
    "wave_runs_resorted": "(wave, run) store entries re-sorted by the "
                          "run-granular resume/repair path",
    "wave_resort_keys": "keys re-sorted by the wave resume/repair path",
}


@dataclasses.dataclass(frozen=True)
class Event:
    """One journal record.  ``t`` is wall-clock (cross-process mergeable);
    ``mono`` is ``time.monotonic()`` (in-process ordering and durations);
    ``seq`` is the per-log append index (total order even at equal clocks)."""

    seq: int
    t: float
    mono: float
    type: str
    fields: dict

    def to_dict(self) -> dict:
        return {
            "seq": self.seq,
            "t": round(self.t, 6),
            "mono": round(self.mono, 6),
            "type": self.type,
            **self.fields,
        }


class EventLog:
    """Thread-safe, append-only journal of typed events for one job/session.

    ``rotate_bytes`` (``--journal-rotate-mb``) bounds any one JSONL file: a
    `flush_jsonl` that leaves ``path`` at or over the threshold atomically
    renames it to ``path.N`` (N counting up — ``path.1`` is the oldest
    piece) and the next flush starts a fresh ``path``, so a million-user
    serve session can never grow one unbounded file.  ``dsort report``
    stitches a rotated set back into one journal (`obs.merge.rotated_set`).
    """

    def __init__(self, rotate_bytes: int | None = None):
        self._lock = threading.Lock()
        self._events: list[Event] = []
        self._flushed = 0  # events already written by flush_jsonl
        self._rotate_bytes = rotate_bytes
        self._rotations = 0

    def emit(self, etype: str, **fields) -> Event:
        if etype not in EVENT_TYPES:
            raise ValueError(
                f"unregistered event type {etype!r}; add it to "
                "dsort_tpu.utils.events.EVENT_TYPES"
            )
        t, mono = time.time(), time.monotonic()
        with self._lock:
            ev = Event(len(self._events), t, mono, etype, fields)
            self._events.append(ev)
        return ev

    def ingest(self, t: float, mono: float, etype: str, **fields) -> Event:
        """Append an event observed elsewhere (the native coordinator's
        drained lines) with ITS timestamps, under this log's sequence."""
        if etype not in EVENT_TYPES:
            raise ValueError(f"unregistered event type {etype!r}")
        with self._lock:
            ev = Event(len(self._events), t, mono, etype, fields)
            self._events.append(ev)
        return ev

    def events(self) -> list[Event]:
        with self._lock:
            return list(self._events)

    def types(self) -> list[str]:
        """Event types in append order — the sequence tests assert on."""
        return [e.type for e in self.events()]

    def __len__(self) -> int:
        with self._lock:
            return len(self._events)

    # -- persistence -------------------------------------------------------

    def write_jsonl(self, path: str) -> None:
        """One JSON object per line — the ``--journal`` artifact format."""
        with open(path, "w", encoding="utf-8") as f:
            for e in self.events():
                f.write(json.dumps(e.to_dict()) + "\n")

    def flush_jsonl(self, path: str) -> None:
        """Write only the events not yet flushed (truncating on the FIRST
        flush so a stale file never mixes sessions).  The per-job persist
        of long REPL sessions (`dsort serve/coordinator --journal`): IO per
        job stays O(new events), not O(session).  With ``rotate_bytes``
        set, a file left at/over the threshold rotates to ``path.N``
        afterwards (see the class docstring)."""
        with self._lock:
            events = list(self._events)
            start = self._flushed
            self._flushed = len(events)
        if start == 0:
            # The anti-mixing guard covers the WHOLE rotated set: a stale
            # session's path.N pieces would otherwise survive the base
            # truncation and stitch into this session's trace when
            # `dsort report` expands the set.
            self._clear_rotated(path)
        if start == 0 or events[start:]:
            with open(path, "w" if start == 0 else "a",
                      encoding="utf-8") as f:
                for e in events[start:]:
                    f.write(json.dumps(e.to_dict()) + "\n")
        self._maybe_rotate(path)

    def _clear_rotated(self, path: str) -> None:
        if not self._rotate_bytes:
            return
        import os
        import re

        d = os.path.dirname(path) or "."
        name = re.escape(os.path.basename(path))
        try:
            for entry in os.listdir(d):
                if re.fullmatch(rf"{name}\.\d+", entry):
                    os.remove(os.path.join(d, entry))
        except OSError:  # diagnostics: never fatal
            return

    def _maybe_rotate(self, path: str) -> None:
        if not self._rotate_bytes:
            return
        import os

        try:
            if os.path.getsize(path) < self._rotate_bytes:
                return
            with self._lock:
                self._rotations += 1
                n = self._rotations
            os.replace(path, f"{path}.{n}")
        except OSError:  # the journal is a diagnostic: never fatal
            return

    @staticmethod
    def read_jsonl(path: str) -> list[dict]:
        out = []
        with open(path, encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if line:
                    out.append(json.loads(line))
        return out


# -- consumer 1: Chrome-trace (Perfetto trace_event) export -----------------


def to_chrome_trace(records: list[dict]) -> dict:
    """Records (``Event.to_dict`` shape) -> a Chrome ``trace_event`` object.

    ``phase_start``/``phase_end`` pairs become B/E duration events;
    everything else becomes an instant event with its fields as ``args``.
    Timestamps are microseconds on the monotonic clock, rebased to the
    first record, so the timeline lines up with a ``jax.profiler`` capture
    of the same run when loaded into Perfetto side by side.

    Lane assignment: each source journal (the ``src`` field a merged
    multi-host trace carries, `obs.merge`) renders as its own ``pid``, and
    each job (the ``job`` ordinal `Metrics.event` stamps) as its own
    ``tid`` within it — so CONCURRENT jobs' phase spans land on distinct
    rows and can never pair B/E markers across jobs.  Records without a
    job ordinal (bare `EventLog.emit` callers) keep the legacy single
    lane.
    """
    if not records:
        return {"traceEvents": [], "displayTimeUnit": "ms"}
    # Chronological, not append, order: ingested native-coordinator records
    # carry their own (earlier) stamps but append at drain time.
    records = sorted(records, key=lambda r: (r["mono"], r.get("seq", 0)))
    t0 = records[0]["mono"]
    out = []
    tids: dict[tuple, int] = {}  # (pid, job ordinal) -> tid, first-seen order
    for r in records:
        us = (r["mono"] - t0) * 1e6
        args = {
            k: v
            for k, v in r.items()
            if k not in ("seq", "t", "mono", "type")
        }
        pid = int(r.get("src", 0)) + 1
        if "job" in r:
            # Job lanes start at tid 2: tid 1 is reserved for records with
            # no job ordinal (bare EventLog.emit callers, ingested native
            # lines), so un-attributed events never share — or pair B/E
            # markers with — a job's lane.
            key = (pid, r["job"])
            tid = tids.get(key)
            if tid is None:
                tid = tids[key] = sum(k[0] == pid for k in tids) + 2
                out.append(
                    {"name": "thread_name", "ph": "M", "pid": pid,
                     "tid": tid, "args": {"name": f"job {r['job']}"}}
                )
        else:
            tid = 1
        common = {"pid": pid, "tid": tid, "ts": round(us, 1)}
        if r["type"] == "phase_start":
            out.append(
                {"name": f"dsort:{args.get('phase', '?')}", "ph": "B",
                 **common}
            )
        elif r["type"] == "phase_end":
            out.append(
                {"name": f"dsort:{args.get('phase', '?')}", "ph": "E",
                 **common}
            )
        else:
            out.append(
                {"name": r["type"], "ph": "i", "s": "g", "args": args,
                 **common}
            )
    return {"traceEvents": out, "displayTimeUnit": "ms"}


# -- consumer 2: the human timeline behind `dsort report` -------------------


def format_report(records: list[dict]) -> str:
    """Human timeline + phase/counter tables for one journal.

    The timeline shows every non-phase event at its relative time; the phase
    table aggregates ``phase_end`` durations; the counter table shows the
    final counters carried by the last ``job_done``/``job_failed`` event
    (the schedulers attach them there).
    """
    if not records:
        return "(empty journal)\n"
    # Chronological order (see to_chrome_trace: ingested native records
    # append late but stamp early).
    records = sorted(records, key=lambda r: (r["mono"], r.get("seq", 0)))
    t0 = records[0]["mono"]
    lines = ["timeline:"]
    phase_s: dict[str, float] = {}
    counters: dict[str, int] = {}
    for r in records:
        rel_ms = (r["mono"] - t0) * 1e3
        fields = {
            k: v
            for k, v in r.items()
            if k not in ("seq", "t", "mono", "type")
        }
        if r["type"] == "phase_end":
            sec = fields.get("seconds")
            if isinstance(sec, (int, float)):
                phase_s[fields.get("phase", "?")] = (
                    phase_s.get(fields.get("phase", "?"), 0.0) + sec
                )
            continue
        if r["type"] == "phase_start":
            continue
        if r["type"] in ("job_done", "job_failed"):
            c = fields.pop("counters", None)
            if isinstance(c, dict):
                counters = c
        kv = " ".join(f"{k}={v}" for k, v in sorted(fields.items()))
        lines.append(f"  {rel_ms:10.1f} ms  {r['type']:<18} {kv}".rstrip())
    if phase_s:
        lines.append("phases:")
        for k, v in sorted(phase_s.items()):
            lines.append(f"  {k:<14} {v * 1e3:10.3f} ms")
    if counters:
        lines.append("counters:")
        for k, v in sorted(counters.items()):
            desc = COUNTERS.get(k, "")
            lines.append(f"  {k:<26} {v:>10}  {desc}".rstrip())
    return "\n".join(lines) + "\n"
