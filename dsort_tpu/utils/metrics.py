"""Per-phase timers and throughput metrics (SURVEY.md §5.5 upgrade).

The reference has no timers at all — not even elapsed time per job.  This
module provides the phase breakdown (ingest / partition / local sort /
shuffle / merge / egress) and the north-star keys/sec/chip metric from
BASELINE.json.
"""

from __future__ import annotations

import contextlib
import dataclasses
import itertools
import threading
import time
from collections import defaultdict

#: Process-wide job ordinals: one `Metrics` instance == one logical job, so
#: the first event a Metrics emits claims the next ordinal and every event
#: of that job carries it as the ``job`` field.  This is what lets the
#: Chrome-trace exporter give concurrent jobs distinct lanes and the SLO
#: tracker attribute stage boundaries to the right job in an interleaved
#: journal.  ``itertools.count`` is atomic under the GIL.
_JOB_ORDINALS = itertools.count(1)


@dataclasses.dataclass
class Metrics:
    """Accumulated per-phase wall times and counters for one job.

    Lock-protected: taskpool shard handlers and (rarely) an abandoned SPMD
    attempt overlapping its successor can bump the same instance from
    multiple threads, and dict read-modify-write is not atomic.
    """

    phase_s: dict[str, float] = dataclasses.field(
        default_factory=lambda: defaultdict(float)
    )
    counters: dict[str, int] = dataclasses.field(
        default_factory=lambda: defaultdict(int)
    )
    #: Optional `utils.events.EventLog`: when attached, every site that
    #: already threads a Metrics can journal typed events via `event` —
    #: the one hook that reaches all execution modes without new plumbing.
    journal: object | None = dataclasses.field(
        default=None, repr=False, compare=False
    )
    #: Live event taps (`dsort_tpu.obs`): objects with
    #: ``observe(etype, fields, mono, metrics)``, called synchronously on
    #: every `event` — the hook the telemetry registry and the fault flight
    #: recorder ride WITHOUT needing a journal attached.  Taps must never
    #: raise into the job (they are diagnostics).
    taps: list = dataclasses.field(
        default_factory=list, repr=False, compare=False
    )
    _lock: threading.Lock = dataclasses.field(
        default_factory=threading.Lock, repr=False, compare=False
    )
    _job_ord: int | None = dataclasses.field(
        default=None, repr=False, compare=False
    )

    def add(self, phase: str, seconds: float) -> None:
        with self._lock:
            self.phase_s[phase] += seconds

    def bump(self, counter: str, by: int = 1) -> None:
        with self._lock:
            self.counters[counter] += by

    def event(self, etype: str, **fields) -> None:
        """Emit a journal event and fan it out to the live taps.

        A no-op when neither a journal nor a tap is attached.  Every event
        is stamped with this instance's ``job`` ordinal (`_JOB_ORDINALS`);
        taps receive the journal's monotonic stamp so live consumers (the
        SLO histograms) and post-hoc journal analysis derive IDENTICAL
        durations.
        """
        if self.journal is None and not self.taps:
            return
        fields.setdefault("job", self._job_ordinal())
        mono = None
        if self.journal is not None:
            mono = self.journal.emit(etype, **fields).mono
        if self.taps:
            if mono is None:
                mono = time.monotonic()
            for tap in list(self.taps):
                tap.observe(etype, dict(fields), mono, self)

    def _job_ordinal(self) -> int:
        with self._lock:
            if self._job_ord is None:
                self._job_ord = next(_JOB_ORDINALS)
            return self._job_ord

    def total_s(self) -> float:
        return sum(self.phase_s.values())

    def keys_per_sec(self, n_keys: int) -> float:
        t = self.total_s()
        return n_keys / t if t > 0 else float("inf")

    def keys_per_sec_per_chip(self, n_keys: int, n_chips: int) -> float:
        return self.keys_per_sec(n_keys) / max(n_chips, 1)

    def summary(self) -> dict:
        return {
            "phases_ms": {k: round(v * 1e3, 3) for k, v in self.phase_s.items()},
            "counters": dict(self.counters),
            "total_ms": round(self.total_s() * 1e3, 3),
        }


class PhaseTimer:
    """Context-manager timer feeding a `Metrics` object.

    Each phase is also emitted as a ``jax.profiler`` trace annotation
    (`utils.tracing.annotate`), so when a profile capture is active
    (``dsort run --profile-dir`` / `tracing.profile_trace`) the host-side
    phases line up against device ops in the TensorBoard/Perfetto timeline.
    """

    def __init__(self, metrics: Metrics):
        self.metrics = metrics

    @contextlib.contextmanager
    def phase(self, name: str):
        from dsort_tpu.utils.tracing import annotate

        self.metrics.event("phase_start", phase=name)
        t0 = time.perf_counter()
        try:
            with annotate(f"dsort:{name}"):
                yield
        finally:
            dt = time.perf_counter() - t0
            self.metrics.add(name, dt)
            self.metrics.event("phase_end", phase=name, seconds=round(dt, 6))
