"""Leveled structured logging (SURVEY.md §5.1/§5.5 upgrade).

The reference's only observability is unconditional ``printf`` of protocol
steps *and full chunk contents* (``server.c:314-318,460-463``,
``client.c:106-109,120-123``) — measured in BASELINE.md to dominate wall time.
Here: standard ``logging`` with levels, a compact structured formatter, and no
O(N) data dumps anywhere on the hot path.
"""

from __future__ import annotations

import logging
import os
import sys

_FORMAT = "%(asctime)s.%(msecs)03d %(levelname).1s %(name)s: %(message)s"
_DATEFMT = "%H:%M:%S"
_configured = False


def get_logger(name: str) -> logging.Logger:
    global _configured
    if not _configured:
        handler = logging.StreamHandler(sys.stderr)
        handler.setFormatter(logging.Formatter(_FORMAT, _DATEFMT))
        root = logging.getLogger("dsort_tpu")
        root.addHandler(handler)
        root.setLevel(os.environ.get("DSORT_LOG_LEVEL", "INFO").upper())
        root.propagate = False
        _configured = True
    return logging.getLogger(f"dsort_tpu.{name}")
