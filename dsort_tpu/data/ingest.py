"""Ingest / egress + synthetic generators (part of L4, SURVEY.md §2.1).

The reference ingests by a two-pass ``fscanf`` loop over an ASCII file of one
int per line (``server.c:171-182``) and egresses one ``fprintf`` per int to a
hardcoded ``output.txt`` (``server.c:517-519``).  Equivalents here use numpy
bulk IO (single pass), plus the generator family for the BASELINE.json
benchmark configs: uniform random (config #2/#3), Zipf-skewed (config #5), and
TeraSort-style 100-byte records (config #4).
"""

from __future__ import annotations

import os

import numpy as np


def read_ints_file(path: str | os.PathLike, dtype=np.int32) -> np.ndarray:
    """Read an ASCII one-int-per-line file (reference input.txt format)."""
    return np.loadtxt(path, dtype=dtype, ndmin=1)


def write_ints_file(path: str | os.PathLike, data: np.ndarray) -> None:
    """Write one int per line (byte-compatible with reference output.txt)."""
    np.savetxt(path, np.asarray(data).reshape(-1), fmt="%d")


def gen_uniform(n: int, dtype=np.int32, seed: int = 0) -> np.ndarray:
    """Uniform random keys over the dtype's full range (BASELINE config #2/#3)."""
    rng = np.random.default_rng(seed)
    dtype = np.dtype(dtype)
    info = np.iinfo(dtype)
    return rng.integers(info.min, info.max, size=n, dtype=dtype, endpoint=False)


def gen_zipf(n: int, a: float = 1.3, dtype=np.int64, seed: int = 0) -> np.ndarray:
    """Zipf-skewed keys (BASELINE config #5) — stresses splitter balance."""
    rng = np.random.default_rng(seed)
    return rng.zipf(a, size=n).astype(dtype)


RECORD_BYTES = 100  # TeraSort record: 10-byte key + 90-byte value


def read_terasort_file(path: str | os.PathLike) -> tuple[np.ndarray, np.ndarray]:
    """Read a binary TeraSort file into ``(packed_keys, payload)``.

    Records are 100 bytes.  The first 8 key bytes pack big-endian into a
    uint64 sort key; the remaining 92 bytes (2 key bytes + 90 value bytes)
    ride as payload, so full records are preserved byte-exactly.
    """
    raw = np.fromfile(path, dtype=np.uint8)
    if len(raw) % RECORD_BYTES:
        raise ValueError(f"{path}: size {len(raw)} not a multiple of {RECORD_BYTES}")
    raw = raw.reshape(-1, RECORD_BYTES)
    keys = raw[:, :8].astype(np.uint64)
    packed = np.zeros(len(raw), dtype=np.uint64)
    for b in range(8):
        packed = (packed << np.uint64(8)) | keys[:, b]
    return packed, raw[:, 8:].copy()


def write_terasort_file(
    path: str | os.PathLike, keys: np.ndarray, payload: np.ndarray
) -> None:
    """Write ``(packed_keys, payload)`` back to 100-byte binary records."""
    n = len(keys)
    raw = np.empty((n, RECORD_BYTES), dtype=np.uint8)
    k = keys.astype(np.uint64)
    for b in range(8):
        raw[:, b] = (k >> np.uint64(8 * (7 - b))).astype(np.uint8)
    raw[:, 8:] = payload
    raw.tofile(path)


def gen_terasort_file(path: str | os.PathLike, n: int, seed: int = 0) -> None:
    """Generate a binary TeraSort input file of ``n`` 100-byte records."""
    keys, payload = gen_terasort(n, seed=seed)
    write_terasort_file(path, keys, payload)


def gen_terasort(
    n: int, key_bytes: int = 10, payload_bytes: int = 90, seed: int = 0
) -> tuple[np.ndarray, np.ndarray]:
    """TeraSort-style records (BASELINE config #4).

    Returns ``(keys, payloads)``: keys are the first 8 bytes of the 10-byte
    key interpreted big-endian as uint64 (sorting by this 8-byte prefix is
    byte-order-equivalent for random data; full 10-byte tie-breaking is done
    by carrying the remaining bytes in the payload), payloads are
    ``(n, key_bytes - 8 + payload_bytes)`` uint8.
    """
    rng = np.random.default_rng(seed)
    raw = rng.integers(0, 256, size=(n, key_bytes + payload_bytes), dtype=np.uint8)
    keys = raw[:, :8].astype(np.uint64)
    packed = np.zeros(n, dtype=np.uint64)
    for b in range(8):
        packed = (packed << np.uint64(8)) | keys[:, b]
    return packed, raw[:, 8:]
