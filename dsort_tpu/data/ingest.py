"""Ingest / egress + synthetic generators (part of L4, SURVEY.md §2.1).

The reference ingests by a two-pass ``fscanf`` loop over an ASCII file of one
int per line (``server.c:171-182``) and egresses one ``fprintf`` per int to a
hardcoded ``output.txt`` (``server.c:517-519``).  Equivalents here use numpy
bulk IO (single pass), plus the generator family for the BASELINE.json
benchmark configs: uniform random (config #2/#3), Zipf-skewed (config #5), and
TeraSort-style 100-byte records (config #4).
"""

from __future__ import annotations

import os

import numpy as np


def read_ints_file(path: str | os.PathLike, dtype=np.int32) -> np.ndarray:
    """Read an ASCII one-int-per-line file (reference input.txt format).

    Hot path is the native C++ parser (`runtime/native/textio.cpp` — the
    equivalent of the reference's C fscanf ingest, ``server.c:171-182``, at
    memory bandwidth); falls back to ``np.loadtxt`` when the native library
    is unavailable or the file needs its more lenient grammar ('#' comments,
    '+'-signed ints).
    """
    from dsort_tpu.runtime import native

    dtype = np.dtype(dtype)
    if native.available() and native.supports_text_dtype(dtype):
        with open(path, "rb") as f:
            raw = f.read()
        try:
            return native.parse_ints_text(raw, dtype)
        except ValueError:
            pass  # e.g. '#' comments or '+42' — loadtxt grammar handles them
        # OverflowError propagates: values outside `dtype` must fail loudly,
        # not fall back to np.loadtxt, which wraps them to INT_MIN silently.
    return np.loadtxt(path, dtype=dtype, ndmin=1)


def write_ints_file(path: str | os.PathLike, data: np.ndarray) -> None:
    """Write one int per line (byte-compatible with reference output.txt).

    Native C++ formatting (`textio.cpp`, std::to_chars) when available;
    ``np.savetxt`` fallback.
    """
    from dsort_tpu.runtime import native

    data = np.asarray(data).reshape(-1)
    if native.available() and native.supports_text_dtype(data.dtype):
        payload = native.format_ints_text(data)
        with open(path, "wb") as f:
            f.write(payload)
        return
    np.savetxt(path, data, fmt="%d")


def gen_uniform(n: int, dtype=np.int32, seed: int = 0) -> np.ndarray:
    """Uniform random keys over the dtype's full range (BASELINE config #2/#3)."""
    rng = np.random.default_rng(seed)
    dtype = np.dtype(dtype)
    info = np.iinfo(dtype)
    return rng.integers(info.min, info.max, size=n, dtype=dtype, endpoint=False)


def gen_uniform_bin_file(
    path: str | os.PathLike, n: int, dtype=np.int32, seed: int = 0,
    chunk: int = 1 << 24,
) -> None:
    """Stream ``n`` uniform keys to a raw binary file in bounded memory.

    The binary twin of `gen_uniform` for jobs too big to hold as text
    (10^9 int32 = 4 GB binary vs ~10.5 GB ASCII): `ExternalSort`'s input
    format, one little-endian key after another.
    """
    rng = np.random.default_rng(seed)
    dtype = np.dtype(dtype)
    info = np.iinfo(dtype)
    with open(path, "wb") as f:
        for lo in range(0, n, chunk):
            m = min(chunk, n - lo)
            f.write(
                rng.integers(info.min, info.max, size=m, dtype=dtype,
                             endpoint=False).tobytes()
            )


def gen_zipf(n: int, a: float = 1.3, dtype=np.int64, seed: int = 0) -> np.ndarray:
    """Zipf-skewed keys (BASELINE config #5) — stresses splitter balance.

    Values are clipped (not wrapped) into ``dtype``'s range: the heavy tail
    of a=1.3 exceeds int32 with probability ~1e-3 per draw, and a silent
    wraparound would turn skew-stress data into negative noise.
    """
    rng = np.random.default_rng(seed)
    vals = rng.zipf(a, size=n)
    return np.minimum(vals, np.iinfo(dtype).max).astype(dtype)


RECORD_BYTES = 100  # TeraSort record: 10-byte key + 90-byte value


def read_terasort_file(path: str | os.PathLike) -> tuple[np.ndarray, np.ndarray]:
    """Read a binary TeraSort file into ``(packed_keys, payload)``.

    Records are 100 bytes.  The first 8 key bytes pack big-endian into a
    uint64 sort key; the remaining 92 bytes (2 key bytes + 90 value bytes)
    ride as payload, so full records are preserved byte-exactly.  Key bytes
    8-9 sit in ``payload[:, :2]`` — `terasort_secondary` turns them into the
    tiebreak key that completes the full 10-byte ordering.
    """
    raw = np.fromfile(path, dtype=np.uint8)
    if len(raw) % RECORD_BYTES:
        raise ValueError(f"{path}: size {len(raw)} not a multiple of {RECORD_BYTES}")
    raw = raw.reshape(-1, RECORD_BYTES)
    packed = _pack_be64(raw[:, :8])
    return packed, raw[:, 8:].copy()


def _pack_be64(key_bytes: np.ndarray) -> np.ndarray:
    """(n, 8) uint8 big-endian rows -> native uint64 (one vectorized view)."""
    return (
        np.ascontiguousarray(key_bytes).view(">u8").reshape(-1).astype(np.uint64)
    )


def terasort_secondary(payload: np.ndarray) -> np.ndarray:
    """Tiebreak key from a TeraSort payload: key bytes 8-9, big-endian uint16.

    Sorting by ``(packed_keys, terasort_secondary(payload))`` orders records
    by the full 10-byte TeraSort key; the 8-byte prefix alone leaves records
    with colliding prefixes in arbitrary relative order.
    """
    return (payload[:, 0].astype(np.uint16) << np.uint16(8)) | payload[:, 1]


def write_terasort_file(
    path: str | os.PathLike, keys: np.ndarray, payload: np.ndarray
) -> None:
    """Write ``(packed_keys, payload)`` back to 100-byte binary records."""
    n = len(keys)
    raw = np.empty((n, RECORD_BYTES), dtype=np.uint8)
    k = keys.astype(np.uint64)
    for b in range(8):
        raw[:, b] = (k >> np.uint64(8 * (7 - b))).astype(np.uint8)
    raw[:, 8:] = payload
    raw.tofile(path)


def gen_terasort_file(path: str | os.PathLike, n: int, seed: int = 0) -> None:
    """Generate a binary TeraSort input file of ``n`` 100-byte records."""
    keys, payload = gen_terasort(n, seed=seed)
    write_terasort_file(path, keys, payload)


def gen_terasort(
    n: int, key_bytes: int = 10, payload_bytes: int = 90, seed: int = 0
) -> tuple[np.ndarray, np.ndarray]:
    """TeraSort-style records (BASELINE config #4).

    Returns ``(keys, payloads)``: keys are the first 8 bytes of the 10-byte
    key interpreted big-endian as uint64; payloads are
    ``(n, key_bytes - 8 + payload_bytes)`` uint8 whose first two columns are
    key bytes 8-9.  Pass ``terasort_secondary(payloads)`` as the sort's
    secondary key to order by the full 10-byte key.
    """
    rng = np.random.default_rng(seed)
    raw = rng.integers(0, 256, size=(n, key_bytes + payload_bytes), dtype=np.uint8)
    return _pack_be64(raw[:, :8]), raw[:, 8:]
