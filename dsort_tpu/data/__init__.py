"""Data plane: ingest/egress and synthetic workload generators."""

from dsort_tpu.data.ingest import read_ints_file, write_ints_file  # noqa: F401
from dsort_tpu.data.partition import equal_partition, pad_to_shards  # noqa: F401
