"""Partitioning: split a key array into per-worker shards (L4).

The reference partitions into ``MAX_WORKERS`` equal chunks with the remainder
spread one extra element each over the first ``total % MAX_WORKERS`` workers,
and aborts above 4,096 ints per chunk (``server.c:185-216``).
`equal_partition` keeps exactly those remainder semantics, uncapped;
`pad_to_shards` produces the static-shape ``(W, cap)`` layout + counts that the
SPMD phases require.
"""

from __future__ import annotations

import numpy as np

from dsort_tpu.ops.local_sort import sentinel_for


def equal_partition(total: int, num_workers: int) -> list[int]:
    """Chunk sizes per worker — reference remainder semantics (server.c:185-196)."""
    if num_workers < 1:
        raise ValueError("num_workers must be >= 1")
    base, rem = divmod(total, num_workers)
    return [base + (1 if i < rem else 0) for i in range(num_workers)]


def partition(data: np.ndarray, num_workers: int) -> list[np.ndarray]:
    """Split ``data`` into contiguous chunks per `equal_partition` sizes."""
    sizes = equal_partition(len(data), num_workers)
    out, off = [], 0
    for s in sizes:
        out.append(data[off : off + s])
        off += s
    return out


def pad_to_shards(
    data: np.ndarray, num_workers: int, multiple: int = 8, cap: int | None = None
) -> tuple[np.ndarray, np.ndarray]:
    """Lay ``data`` out as ``(num_workers, cap)`` + per-shard valid counts.

    ``cap`` is the max chunk size rounded up to ``multiple`` (TPU-friendly
    alignment); pads hold the dtype sentinel.  This is the static-shape
    successor of the reference's malloc'd variable chunks (``server.c:206-216``).
    An explicit ``cap`` overrides the computed one — multi-host drivers must
    agree on one global cap even when hosts hold unequal data amounts.
    """
    sizes = equal_partition(len(data), num_workers)
    if cap is None:
        cap = -(-max(sizes + [1]) // multiple) * multiple
    elif cap < max(sizes + [0]):
        raise ValueError(f"cap {cap} < largest shard {max(sizes)}")
    # np.empty + per-row TAIL fill, not np.full: only the pad gaps are
    # written twice, so the host cost is one pass over the data plus the
    # (usually tiny) padding — not two passes (VERDICT r4 next #1).
    out = np.empty((num_workers, cap), dtype=data.dtype)
    sent = sentinel_for(data.dtype)
    off = 0
    for i, s in enumerate(sizes):
        out[i, :s] = data[off : off + s]
        out[i, s:] = sent
        off += s
    return out, np.asarray(sizes, dtype=np.int32)


def pad_to_layout(
    data: np.ndarray, counts: np.ndarray, cap: int, fill=0
) -> np.ndarray:
    """Lay ``data`` out as ``(len(counts), cap)`` using precomputed shard sizes.

    Companion channels (e.g. a secondary sort key) reuse the sizes/cap a prior
    `pad_to_shards`/`pad_kv_to_shards` call computed, instead of re-partitioning.
    Pads hold ``fill``.
    """
    out = np.full((len(counts), cap) + data.shape[1:], fill, dtype=data.dtype)
    off = 0
    for i, s in enumerate(np.asarray(counts)):
        out[i, :s] = data[off : off + s]
        off += s
    return out


def pad_kv_to_shards(
    keys: np.ndarray,
    payload: np.ndarray,
    num_workers: int,
    multiple: int = 8,
    cap: int | None = None,
) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Key+payload variant of `pad_to_shards`; payload pads are zeros.

    Like `pad_to_shards`, an explicit ``cap`` lets multi-host drivers agree
    on one global layout across hosts with unequal record counts.
    """
    sizes = equal_partition(len(keys), num_workers)
    if cap is None:
        cap = -(-max(sizes + [1]) // multiple) * multiple
    elif cap < max(sizes + [0]):
        raise ValueError(f"cap {cap} < largest shard {max(sizes)}")
    out_k = np.full((num_workers, cap), sentinel_for(keys.dtype), dtype=keys.dtype)
    out_v = np.zeros((num_workers, cap) + payload.shape[1:], dtype=payload.dtype)
    off = 0
    for i, s in enumerate(sizes):
        out_k[i, :s] = keys[off : off + s]
        out_v[i, :s] = payload[off : off + s]
        off += s
    return out_k, out_v, np.asarray(sizes, dtype=np.int32)
