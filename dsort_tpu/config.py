"""Typed configuration for the framework (L5 of SURVEY.md's layer map).

The reference configures itself from two ``KEY=value`` text files parsed with
``strtok`` into header-defined globals (``server.c:61-90``, ``client.c:15-54``,
``server.conf``, ``client.conf``).  Here the same idea becomes one typed,
validated dataclass tree:

- the reference's node list / port (``SERVER_IP``/``SERVER_PORT``) is
  reinterpreted as a **device-mesh spec** (`MeshConfig`) — the cluster is a
  ``jax.sharding.Mesh``, not a TCP star;
- the reference's compile-time constants ``MAX_WORKERS=4``,
  ``MAX_SUPPORTED_CHUNK_SIZE=4096`` (``server.c:11,13``) become runtime,
  uncapped fields;
- ``KEY=value`` files still parse (`load_conf_file`) for parity, including the
  reference's exact keys, but unknown keys are reported instead of silently
  aborting the parse (``server.c:78-84`` quirk not replicated).
"""

from __future__ import annotations

import dataclasses
import os
from typing import Any, Mapping

import jax.numpy as jnp

# Reference parity: server.conf:1 / client.conf:1-2 key names.
_REFERENCE_KEYS = {"SERVER_IP", "SERVER_PORT"}


class ConfigError(ValueError):
    """Raised for invalid or inconsistent configuration."""


def load_conf_file(path: str | os.PathLike) -> dict[str, str]:
    """Parse a ``KEY=value`` conf file (reference ``read_conf_file`` parity).

    Unlike ``server.c:61-90`` this accepts any key set, ignores blank lines and
    ``#`` comments, strips whitespace, and raises a clear error for a missing
    file instead of calling ``fclose(NULL)`` (``server.c:87``).
    """
    out: dict[str, str] = {}
    try:
        with open(path, "r", encoding="utf-8") as f:
            for lineno, raw in enumerate(f, 1):
                line = raw.strip()
                if not line or line.startswith("#"):
                    continue
                if "=" not in line:
                    raise ConfigError(f"{path}:{lineno}: expected KEY=value, got {line!r}")
                key, _, value = line.partition("=")
                out[key.strip()] = value.strip()
    except FileNotFoundError as e:
        raise ConfigError(f"conf file not found: {path}") from e
    return out


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    """Device-mesh spec — the TPU-native successor of the reference's node list.

    The reference forms its "cluster" by blocking-accepting exactly 4 TCP
    connections, identified by accept order (``server.c:148-157``).  Here the
    cluster is a JAX device mesh: ``num_workers`` devices on the ``axis_name``
    axis (optionally times a ``dp`` batch axis for independent jobs).
    """

    num_workers: int | None = None  # None → all visible devices
    axis_name: str = "w"
    dp: int = 1                     # independent-job (batch) axis size
    dp_axis_name: str = "dp"

    def __post_init__(self) -> None:
        if self.num_workers is not None and self.num_workers < 1:
            raise ConfigError(f"num_workers must be >= 1, got {self.num_workers}")
        if self.dp < 1:
            raise ConfigError(f"dp must be >= 1, got {self.dp}")
        if self.axis_name == self.dp_axis_name:
            raise ConfigError("axis_name and dp_axis_name must differ")


@dataclasses.dataclass(frozen=True)
class JobConfig:
    """Per-job sort parameters.

    Supersedes the reference's compile-time caps: workers (``server.c:11``),
    chunk size (``server.c:13,193-196``), int32-only keys with ``-1`` reserved
    as the wire sentinel (``server.c:405-406``).  Key dtype is configurable;
    only the dtype's maximum value is reserved as padding sentinel, and only on
    the key+payload path (documented in ``ops.local_sort``).
    """

    key_dtype: Any = jnp.int32
    payload_bytes: int = 0          # 0 → key-only sort; >0 → TeraSort-style records
    local_kernel: str = "auto"      # per-chip sort: "auto" | "lax" | "block" | "bitonic" | "pallas" | "radix"
    # Post-shuffle combine: "auto" (block_merge wherever the block kernel
    # applies — measured 6x the flat re-sort on chip) | "sort" | "bitonic"
    # | "block_merge".
    merge_kernel: str = "auto"
    # Bucket exchange schedule: "alltoall" = one-shot padded collective;
    # "ring" = P-1 chunked ppermute steps with merge-as-you-receive and
    # per-step buffer capacities sized from the measured bucket histogram
    # (`parallel.exchange`) — bit-identical output, adaptive headroom;
    # "fused" = the same measured-capacity ring schedule run as ONE Pallas
    # kernel (`ops.ring_kernel`): per-step async remote DMAs with the merge
    # folded between them, one launch instead of P-1 dispatches;
    # "hier" = the two-level pod schedule (ARCHITECTURE §17): intra-host
    # aggregation ring, then ONE merged transfer per (src-host, dst-host)
    # pair over the DCN leg, then a local scatter — DCN bytes sized from
    # the (H, H) host matrix instead of scaling with P.
    exchange: str = "alltoall"
    # Host count the hier schedule groups the 1-D worker mesh into.
    # 0 = auto: `jax.process_count()` when genuinely multi-host, else 2
    # hosts simulated (`parallel.exchange.resolve_hier_hosts`); a value
    # that doesn't divide the worker count resolves to the nearest
    # divisor below it, and meshes under 4 workers downgrade to the flat
    # ring with a warning.
    hier_hosts: int = 0
    # Coded redundancy (ARCHITECTURE §14, arXiv:1702.04850): r-way bucket
    # replication across ring successors DURING the exchange, so up to r-1
    # device losses recover by a local merge of replica slots instead of a
    # re-run (zero keys re-sorted, zero re-dispatch).  1 = off (today's
    # re-run posture); r > 1 forces the keys-only lax ring schedule (the
    # replica plane rides its ppermute steps) and costs ~r x the exchange
    # wire bytes on the healthy path — the availability premium.
    redundancy: int = 1
    # HOW the redundancy plane ships its premium (ARCHITECTURE §18):
    # "replicate" = full bucket copies on ring successors ((r-1)x extra
    # wire bytes, survives any r-1 losses of a range's holder set);
    # "parity" = XOR (r=2) or RAID-6 P+Q GF(256) parity slots (r>=3) —
    # each device keeps its own out-plane locally for free and ships ONE
    # parity slot per parity index, so the wire premium falls from
    # (r-1)x toward 1/P x at the same single- (XOR) / double-loss (P+Q)
    # survivability; recovery is still a local merge (zero keys
    # re-sorted).  Ignored at redundancy=1.
    redundancy_mode: str = "replicate"
    # Sample-sort knobs (SURVEY.md §5.7 analogue of splitter selection):
    oversample: int = 32            # splitter candidates per device
    # Per-(src,dst) all_to_all bucket headroom over the ideal n/P split.
    # 1.3 suffices for oversample=32 splitters on uniform data; skewed data
    # overflows once and the retry resizes from the MEASURED max bucket
    # (sample_sort.cap_from_observed), so a blanket 2x tax — which doubled
    # both the exchange bytes and the merge-phase work — is gone (VERDICT r2).
    capacity_factor: float = 1.3
    max_capacity_retries: int = 3   # overflow → double capacity and retry
    # Fault tolerance (reference semantics, SURVEY.md §5.3, + heartbeat upgrade):
    max_reassign_attempts: int | None = None  # None → up to num_workers - 1
    settle_delay_s: float = 0.1     # reference's 100 ms usleep (server.c:304,391,446)
    heartbeat_timeout_s: float = 10.0  # fixes the reference's hang-blindness
    # Extra first-attempt budget while a (shape, dtype, kernel) combo is
    # cold: XLA/Mosaic compilation (30-150 s through a remote compiler) must
    # not read as a hung worker.  Applies once per combo per scheduler; a
    # genuinely hung worker on a cold shape is still detected, just slower.
    compile_grace_s: float = 240.0
    max_transient_retries: int = 2  # real runtime error, all devices healthy
    # In-flight SPMD/fused program hang detection (the reference's signature
    # blind spot, SURVEY.md §5.3: a worker that hangs without closing its
    # socket blocks server.c forever).  The whole-program wait is bounded by
    #   heartbeat_timeout_s + exec_allowance_floor_s
    #     + n_keys / exec_allowance_keys_per_s
    #     (+ compile_grace_s while this (mesh, size-bucket) is cold).
    # The 1 Mkeys/s allowance rate is ~1000x slower than the chip actually
    # sorts, so only a genuine hang trips the timeout; on lapse every device
    # is probed, the dead are excluded, and the job re-runs on the re-formed
    # mesh from the last checkpointed phase.
    exec_allowance_floor_s: float = 30.0
    exec_allowance_keys_per_s: float = 1e6
    checkpoint_dir: str | None = None  # persist sorted shards for partial recovery
    # Telemetry plane (dsort_tpu.obs):
    # Tenant label for the SLO histograms (per-tenant p50/p95/p99 of
    # admit->dispatch->sorted->fetched) — the admission-control signal the
    # multi-tenant serving layer (ROADMAP item 1) keys on.  Rides every
    # job_start event; constrained to Prometheus-label-safe characters.
    tenant: str = "default"
    # When set, the owning scheduler keeps a bounded ring of recent events
    # and dumps a postmortem bundle here whenever a recovery path fires
    # (obs.flight.FlightRecorder).
    flight_recorder_dir: str | None = None
    flight_ring_size: int = 256     # events retained in the recorder ring
    # Closed-loop planner plane (obs.plan, ARCHITECTURE §15).  When on, the
    # planner fills any knob the user left genuinely unset from measured
    # signals (journaled as plan_decision events); explicit flag/conf
    # values always win (journaled as plan_override).  Library default is
    # OFF (a bare JobConfig() behaves exactly as before); the CLI turns it
    # on unless --no-autotune / conf AUTOTUNE=0.
    autotune: bool = False
    # The tri-state's "explicit" bit: knob names the user actually set
    # (CLI flag given / conf key present), as opposed to riding the
    # dataclass default.  Filled by the conf/CLI loaders; the planner only
    # decides knobs NOT listed here.
    explicit: tuple = ()

    def is_explicit(self, knob: str) -> bool:
        """True when the user explicitly set ``knob`` (flag or conf key) —
        the planner must not override it."""
        return knob in self.explicit

    def __post_init__(self) -> None:
        import jax

        if jnp.dtype(self.key_dtype).itemsize == 8 and not jax.config.jax_enable_x64:
            raise ConfigError(
                f"key_dtype {self.key_dtype} needs 64-bit mode: call "
                "jax.config.update('jax_enable_x64', True) before building configs"
            )
        if self.payload_bytes < 0:
            raise ConfigError(f"payload_bytes must be >= 0, got {self.payload_bytes}")
        from dsort_tpu.ops.local_sort import LOCAL_KERNELS

        if self.local_kernel not in LOCAL_KERNELS:
            raise ConfigError(
                f"local_kernel must be one of {LOCAL_KERNELS}, got {self.local_kernel!r}"
            )
        if self.merge_kernel not in ("auto", "sort", "bitonic", "block_merge"):
            raise ConfigError(
                "merge_kernel must be 'auto', 'sort', 'bitonic' or "
                f"'block_merge', got {self.merge_kernel!r}"
            )
        if self.exchange not in ("alltoall", "ring", "fused", "hier"):
            raise ConfigError(
                "exchange must be 'alltoall', 'ring', 'fused' or 'hier', "
                f"got {self.exchange!r}"
            )
        if not isinstance(self.hier_hosts, int) or self.hier_hosts < 0:
            raise ConfigError(
                f"hier_hosts must be an integer >= 0, got {self.hier_hosts!r}"
            )
        if not isinstance(self.redundancy, int) or self.redundancy < 1:
            raise ConfigError(
                f"redundancy must be an integer >= 1, got {self.redundancy!r}"
            )
        if self.redundancy_mode not in ("replicate", "parity"):
            raise ConfigError(
                "redundancy_mode must be 'replicate' or 'parity', got "
                f"{self.redundancy_mode!r}"
            )
        if self.oversample < 1:
            raise ConfigError(f"oversample must be >= 1, got {self.oversample}")
        if self.capacity_factor < 1.0:
            raise ConfigError(f"capacity_factor must be >= 1.0, got {self.capacity_factor}")
        if self.max_transient_retries < 0:
            raise ConfigError(
                f"max_transient_retries must be >= 0, got {self.max_transient_retries}"
            )
        if self.exec_allowance_floor_s < 0:
            raise ConfigError(
                f"exec_allowance_floor_s must be >= 0, got {self.exec_allowance_floor_s}"
            )
        if self.exec_allowance_keys_per_s <= 0:
            raise ConfigError(
                "exec_allowance_keys_per_s must be > 0, got "
                f"{self.exec_allowance_keys_per_s}"
            )
        import re

        if not re.fullmatch(r"[A-Za-z0-9._-]+", self.tenant or ""):
            raise ConfigError(
                "tenant must match [A-Za-z0-9._-]+ (it becomes a metrics "
                f"label), got {self.tenant!r}"
            )
        if self.flight_ring_size < 1:
            raise ConfigError(
                f"flight_ring_size must be >= 1, got {self.flight_ring_size}"
            )
        if not isinstance(self.explicit, tuple):
            # Frozen dataclass: normalize lists/sets in place.
            object.__setattr__(self, "explicit", tuple(self.explicit))
        for knob in self.explicit:
            if not isinstance(knob, str) or not knob:
                raise ConfigError(
                    f"explicit must name knobs as strings, got {knob!r}"
                )


@dataclasses.dataclass(frozen=True)
class ServeConfig:
    """Multi-tenant serving-layer knobs (`dsort_tpu.serve.SortService`).

    Bounds and policies of the async admission queue, the weighted
    deficit-round-robin fair scheduler, mesh-slice packing, and the
    compiled-variant cache (ARCHITECTURE §8).  The prewarm range is
    expressed in key counts and expands to the same 8-aligned 1/8-power-
    of-two capacity ladder (`parallel.exchange.ladder_rungs` /
    `models.pipelines.pad_rung`) the compiled variants are keyed on.
    """

    max_queue_depth: int = 64       # jobs queued service-wide (admission bound)
    max_tenant_inflight: int = 16   # one tenant's queued + running jobs
    slice_devices: int = 1          # devices per small-job mesh sub-slice
    small_job_max: int | None = None  # None -> models.pipelines.FUSED_SMALL_JOB_MAX
    # Fair-scheduler deficit granted per visit, in keys.  Deliberately
    # SMALL relative to typical jobs: a tenant dispatches at most
    # ~quantum/job_cost jobs per rotation, so tenants interleave at fine
    # grain; a job costlier than the quantum simply accumulates deficit
    # over several (cheap, host-side) rotations while others are served.
    drr_quantum_keys: int = 1 << 14
    tenant_weights: Mapping[str, float] = dataclasses.field(
        default_factory=dict
    )
    variant_cache_entries: int = 64  # LRU bound on cached compiled variants
    prewarm: bool = False            # compile warm rungs at startup
    # Which rungs the startup prewarm compiles: "auto" = the planner's
    # predicted set from the admission stream's recent rung x dtype mix
    # (obs.plan's prewarm policy; falls back to the full ladder on a cold
    # start with no history), "all" = the old exhaustive ladder
    # (--prewarm all / conf SERVE_PREWARM=all).
    prewarm_policy: str = "auto"
    prewarm_min_keys: int = 1 << 14
    prewarm_max_keys: int = 1 << 16
    # SLO-driven admission shedding (--slo-shed-ms): reject with the typed
    # verdict `slo_shed` when a tenant's live p95 queue wait (a sliding
    # window of measured job_dequeued waits) exceeds this target while work
    # is still queued; an empty queue always admits, so shedding recovers
    # by itself once the backlog drains.  None = disabled.
    slo_shed_ms: float | None = None

    def __post_init__(self) -> None:
        if self.max_queue_depth < 1:
            raise ConfigError(
                f"max_queue_depth must be >= 1, got {self.max_queue_depth}"
            )
        if self.max_tenant_inflight < 1:
            raise ConfigError(
                f"max_tenant_inflight must be >= 1, got {self.max_tenant_inflight}"
            )
        if self.slice_devices < 1:
            raise ConfigError(
                f"slice_devices must be >= 1, got {self.slice_devices}"
            )
        if self.small_job_max is not None and self.small_job_max < 1:
            raise ConfigError(
                f"small_job_max must be >= 1, got {self.small_job_max}"
            )
        if self.drr_quantum_keys < 1:
            raise ConfigError(
                f"drr_quantum_keys must be >= 1, got {self.drr_quantum_keys}"
            )
        if self.variant_cache_entries < 1:
            raise ConfigError(
                "variant_cache_entries must be >= 1, got "
                f"{self.variant_cache_entries}"
            )
        for t, w in dict(self.tenant_weights).items():
            if not (isinstance(w, (int, float)) and w > 0):
                raise ConfigError(
                    f"tenant weight for {t!r} must be > 0, got {w!r}"
                )
        if self.prewarm_policy not in ("auto", "all"):
            raise ConfigError(
                f"prewarm_policy must be 'auto' or 'all', got "
                f"{self.prewarm_policy!r}"
            )
        if not (0 < self.prewarm_min_keys <= self.prewarm_max_keys):
            raise ConfigError(
                "prewarm range must satisfy 0 < min <= max, got "
                f"[{self.prewarm_min_keys}, {self.prewarm_max_keys}]"
            )
        if self.slo_shed_ms is not None and self.slo_shed_ms <= 0:
            raise ConfigError(
                f"slo_shed_ms must be > 0, got {self.slo_shed_ms}"
            )


@dataclasses.dataclass(frozen=True)
class ExternalConfig:
    """Out-of-core sort knobs (`dsort external` / `dsort terasort
    --external`; ARCHITECTURE §10).

    ``run_elems`` sizes the single-device spill runs
    (`models.external_sort`); ``wave_elems`` sizes the per-wave device
    budget of the mesh wave pipeline (`models.wave_sort`); ``mesh`` is the
    wave pipeline's worker count (None = single-device external sort).
    Conf-file keys ``EXTERNAL_RUN_ELEMS`` / ``EXTERNAL_WAVE_ELEMS`` /
    ``EXTERNAL_MESH`` follow the same conf/flag precedence as ``SERVE_*``.
    """

    run_elems: int = 1 << 22
    wave_elems: int = 1 << 22
    mesh: int | None = None

    def __post_init__(self) -> None:
        if self.run_elems < 2:
            raise ConfigError(f"run_elems must be >= 2, got {self.run_elems}")
        if self.wave_elems < 2:
            raise ConfigError(
                f"wave_elems must be >= 2, got {self.wave_elems}"
            )
        if self.mesh is not None and self.mesh < 1:
            raise ConfigError(f"mesh must be >= 1, got {self.mesh}")


@dataclasses.dataclass(frozen=True)
class FleetConfig:
    """Fleet-plane knobs (`dsort fleet` / `dsort fleet-agent`;
    ARCHITECTURE §12).

    ``agents`` is the controller's agent endpoint list (flag ``--agents
    host:port,...`` / conf ``FLEET_AGENTS``); ``state_dir`` is where the
    controller persists its restart-safe queue + job table
    (``FLEET_STATE_DIR`` — without it a restart loses queued jobs);
    ``routing`` picks variant-cache-locality routing, the random A/B
    baseline, or ``health`` — locality for small jobs plus live
    straggler-penalized big-job placement (``FLEET_ROUTING``);
    ``heartbeat_s`` paces the controller's agent pings
    (``FLEET_HEARTBEAT_S``); ``dispatch_timeout_s`` is the per-agent SEND
    deadline — how long one agent may sit on a submit before its lane
    fails it over (``FLEET_DISPATCH_TIMEOUT_S``; None = the controller's
    request timeout); ``telemetry`` opts agents into the health plane's
    bounded delta stream on the heartbeat cadence (``FLEET_TELEMETRY``;
    on by default — off = heartbeats-only, the bench's overhead
    baseline).
    """

    agents: tuple[str, ...] = ()
    state_dir: str | None = None
    routing: str = "locality"
    heartbeat_s: float = 2.0
    dispatch_timeout_s: float | None = None
    telemetry: bool = True

    def __post_init__(self) -> None:
        from dsort_tpu.fleet.proto import ROUTING_POLICIES

        if self.routing not in ROUTING_POLICIES:
            raise ConfigError(
                f"routing must be one of {ROUTING_POLICIES}, got "
                f"{self.routing!r}"
            )
        if self.heartbeat_s <= 0:
            raise ConfigError(
                f"heartbeat_s must be > 0, got {self.heartbeat_s}"
            )
        if self.dispatch_timeout_s is not None and self.dispatch_timeout_s <= 0:
            raise ConfigError(
                f"dispatch_timeout_s must be > 0, got "
                f"{self.dispatch_timeout_s}"
            )
        for a in self.agents:
            if ":" not in str(a):
                raise ConfigError(
                    f"agent address {a!r} must be HOST:PORT"
                )


@dataclasses.dataclass(frozen=True)
class SortConfig:
    """Top-level framework config: mesh + job + control-plane endpoints."""

    mesh: MeshConfig = dataclasses.field(default_factory=MeshConfig)
    job: JobConfig = dataclasses.field(default_factory=JobConfig)
    serve: ServeConfig = dataclasses.field(default_factory=ServeConfig)
    external: ExternalConfig = dataclasses.field(default_factory=ExternalConfig)
    fleet: FleetConfig = dataclasses.field(default_factory=FleetConfig)
    # Control-plane endpoint (native coordinator; reference server.conf parity).
    server_ip: str = "127.0.0.1"
    server_port: int = 9008        # reference default, server.conf:1
    output_path: str = "output.txt"  # reference hardcodes this (server.c:517)

    @classmethod
    def from_mapping(cls, m: Mapping[str, str]) -> "SortConfig":
        """Build from a flat KEY=value mapping (conf file or CLI overrides).

        Accepts the reference's exact keys (``SERVER_IP``, ``SERVER_PORT``)
        plus framework keys (``NUM_WORKERS``, ``KEY_DTYPE``, ``OVERSAMPLE``,
        ``CAPACITY_FACTOR``, ``PAYLOAD_BYTES``, ``HEARTBEAT_TIMEOUT_S``,
        ``OUTPUT_PATH``, ``DP``, ``CHECKPOINT_DIR``, ``EXCHANGE``,
        ``REDUNDANCY``, ``REDUNDANCY_MODE``, ``TENANT``, ``FLIGHT_DIR``,
        ``AUTOTUNE`` — the
        closed-loop planner switch; a knob key PRESENT in the mapping is
        explicit and never planner-overridden) and serving-layer keys
        (``SERVE_QUEUE_DEPTH``, ``SERVE_TENANT_INFLIGHT``,
        ``SERVE_SLICE_DEVICES``, ``SERVE_SMALL_JOB_MAX``,
        ``SERVE_WEIGHTS`` — ``tenant=weight,...`` — ``SERVE_PREWARM``,
        and ``SERVE_SLO_SHED_MS``) and out-of-core keys
        (``EXTERNAL_RUN_ELEMS``, ``EXTERNAL_WAVE_ELEMS``,
        ``EXTERNAL_MESH``) and fleet-plane keys (``FLEET_AGENTS`` —
        ``host:port,host:port`` — ``FLEET_STATE_DIR``, ``FLEET_ROUTING``,
        ``FLEET_HEARTBEAT_S``, ``FLEET_TELEMETRY``).
        """
        def geti(key: str, default: int | None) -> int | None:
            return int(m[key]) if key in m else default

        mesh = MeshConfig(
            num_workers=geti("NUM_WORKERS", None),
            dp=geti("DP", 1),
        )
        # The tri-state's conf half: a key PRESENT in the mapping is an
        # explicit user choice the planner must not override (obs.plan);
        # a key absent rides the dataclass default and stays plannable.
        _EXPLICIT_KEYS = {
            "EXCHANGE": "exchange",
            "REDUNDANCY": "redundancy",
            "REDUNDANCY_MODE": "redundancy_mode",
            "EXTERNAL_WAVE_ELEMS": "wave_elems",
            "SERVE_PREWARM": "prewarm",
            "SERVE_SLICE_DEVICES": "slice_devices",
            "FLEET_DISPATCH_TIMEOUT_S": "dispatch_timeout_s",
        }
        explicit = tuple(
            sorted(knob for key, knob in _EXPLICIT_KEYS.items() if key in m)
        )
        # Numeric fallbacks reference the dataclass defaults so a tuning
        # there can never silently diverge from the conf-file path.
        job = JobConfig(
            key_dtype=jnp.dtype(m.get("KEY_DTYPE", "int32")),
            payload_bytes=geti("PAYLOAD_BYTES", 0),
            local_kernel=m.get("LOCAL_KERNEL", JobConfig.local_kernel),
            merge_kernel=m.get("MERGE_KERNEL", JobConfig.merge_kernel),
            exchange=m.get("EXCHANGE", JobConfig.exchange),
            hier_hosts=geti("HIER_HOSTS", JobConfig.hier_hosts),
            redundancy=geti("REDUNDANCY", JobConfig.redundancy),
            redundancy_mode=m.get(
                "REDUNDANCY_MODE", JobConfig.redundancy_mode
            ),
            oversample=geti("OVERSAMPLE", JobConfig.oversample),
            capacity_factor=float(
                m.get("CAPACITY_FACTOR", JobConfig.capacity_factor)
            ),
            heartbeat_timeout_s=float(
                m.get("HEARTBEAT_TIMEOUT_S", JobConfig.heartbeat_timeout_s)
            ),
            checkpoint_dir=m.get("CHECKPOINT_DIR") or None,
            tenant=m.get("TENANT", JobConfig.tenant),
            flight_recorder_dir=m.get("FLIGHT_DIR") or None,
            autotune=m.get("AUTOTUNE", "0").strip().lower()
            in ("1", "true", "yes"),
            explicit=explicit,
        )
        from dsort_tpu.serve.fair import parse_weights

        serve = ServeConfig(
            max_queue_depth=geti("SERVE_QUEUE_DEPTH", ServeConfig.max_queue_depth),
            max_tenant_inflight=geti(
                "SERVE_TENANT_INFLIGHT", ServeConfig.max_tenant_inflight
            ),
            slice_devices=geti("SERVE_SLICE_DEVICES", ServeConfig.slice_devices),
            small_job_max=geti("SERVE_SMALL_JOB_MAX", None),
            tenant_weights=parse_weights(m.get("SERVE_WEIGHTS")),
            prewarm=m.get("SERVE_PREWARM", "0").strip().lower()
            in ("1", "true", "yes", "all"),
            prewarm_policy=(
                "all"
                if m.get("SERVE_PREWARM", "").strip().lower() == "all"
                else "auto"
            ),
            slo_shed_ms=(
                float(m["SERVE_SLO_SHED_MS"])
                if "SERVE_SLO_SHED_MS" in m else None
            ),
        )
        external = ExternalConfig(
            run_elems=geti("EXTERNAL_RUN_ELEMS", ExternalConfig.run_elems),
            wave_elems=geti("EXTERNAL_WAVE_ELEMS", ExternalConfig.wave_elems),
            mesh=geti("EXTERNAL_MESH", None),
        )
        fleet = FleetConfig(
            agents=tuple(
                a.strip() for a in m.get("FLEET_AGENTS", "").split(",")
                if a.strip()
            ),
            state_dir=m.get("FLEET_STATE_DIR") or None,
            routing=m.get("FLEET_ROUTING", FleetConfig.routing),
            heartbeat_s=float(
                m.get("FLEET_HEARTBEAT_S", FleetConfig.heartbeat_s)
            ),
            dispatch_timeout_s=(
                float(m["FLEET_DISPATCH_TIMEOUT_S"])
                if m.get("FLEET_DISPATCH_TIMEOUT_S") else None
            ),
            telemetry=m.get("FLEET_TELEMETRY", "1").strip().lower()
            not in ("0", "false", "no"),
        )
        return cls(
            mesh=mesh,
            job=job,
            serve=serve,
            external=external,
            fleet=fleet,
            server_ip=m.get("SERVER_IP", "127.0.0.1"),
            server_port=int(m.get("SERVER_PORT", 9008)),
            output_path=m.get("OUTPUT_PATH", "output.txt"),
        )

    @classmethod
    def from_conf_file(cls, path: str | os.PathLike) -> "SortConfig":
        return cls.from_mapping(load_conf_file(path))
